"""Benchmark: TPE candidate-suggestion throughput on the 20-dim mixed space.

The BASELINE.json headline (north star >= 10k suggestions/s on TPU):
time the jitted batched TPE suggest step (B trials per device program,
n_EI_candidates per dim per trial) against the in-repo numpy reference
TPE (the reference's execution model: interpreted, per-trial, 24
candidates) on the same 500-observation history.

Prints ONE JSON line: {"metric", "value", "unit", "vs_baseline", ...}.
"""

import json
import os
import sys
import time

import numpy as np

# graftmesh: the mesh-sharded serve/PBT rows (and the mesh-sharded
# program contracts bench_ir re-traces) run over virtual CPU devices
# when no real multi-chip mesh is attached -- the flag must be armed
# before jax initializes its backends, i.e. before any bench work
from hyperopt_tpu.parallel.mesh import force_host_cpu_devices

force_host_cpu_devices(8)


def build_history(n_obs, space, seed=0):
    """A Trials store with n_obs completed synthetic trials."""
    from hyperopt_tpu import Domain, Trials, rand
    from hyperopt_tpu.base import JOB_STATE_DONE
    from hyperopt_tpu.models.synthetic import mixed_space_fn

    domain = Domain(mixed_space_fn, space)
    trials = Trials()
    rng = np.random.default_rng(seed)
    ids = trials.new_trial_ids(n_obs)
    docs = rand.suggest(ids, domain, trials, seed=seed)
    for doc in docs:
        doc["state"] = JOB_STATE_DONE
        doc["result"] = {"status": "ok", "loss": float(rng.uniform(0, 10))}
    trials.insert_trial_docs(docs)
    trials.refresh()
    return domain, trials


def bench_lint():
    """graftlint totals over the package against the committed baseline
    -- stamped so the baseline trend is tracked alongside perf (a
    growing baseline is a regression the same way a slowing ask is).

    Returns (unbaselined_findings_total, baseline_size); the first must
    be 0 on a healthy tree (the tier-1 lint test enforces it)."""
    from hyperopt_tpu.analysis import lint_paths, load_baseline

    repo = os.path.dirname(os.path.abspath(__file__))
    baseline_path = os.path.join(repo, "lint_baseline.json")
    baseline = (
        load_baseline(baseline_path)
        if os.path.exists(baseline_path) else None
    )
    result = lint_paths([os.path.join(repo, "hyperopt_tpu")],
                        baseline=baseline, root=repo)
    return len(result.findings), result.baseline_size


def bench_trace():
    """graftrace (hyperopt-tpu-lint --trace) over the package, plus a
    LIVE lockdep probe: the GL5xx concurrency totals are stamped so a
    new unguarded access or lock-order cycle is visible in the round
    JSON even when nobody ran the fast tier, and the probe proves the
    runtime sanitizer is armed and detecting (it wraps two locks,
    establishes an order, then deliberately inverts it under try/
    except -- exactly one caught inversion is the healthy stamp).

    Returns (trace_findings_total, trace_rules_checked,
    lockdep_inversions_observed); zero lint work executes any code
    under test -- pure AST."""
    import threading

    from hyperopt_tpu.analysis import lint_paths, load_baseline
    from hyperopt_tpu.analysis.lockdep import LockDep, LockOrderError
    from hyperopt_tpu.analysis.rules import RULES

    repo = os.path.dirname(os.path.abspath(__file__))
    baseline_path = os.path.join(repo, "lint_baseline.json")
    baseline = (
        load_baseline(baseline_path)
        if os.path.exists(baseline_path) else None
    )
    result = lint_paths([os.path.join(repo, "hyperopt_tpu")],
                        baseline=baseline, root=repo, pack="trace")
    rules_checked = sum(1 for r in RULES if r.startswith("GL5"))

    dep = LockDep()
    a = dep.wrap(threading.Lock(), "bench.probe.a")
    b = dep.wrap(threading.Lock(), "bench.probe.b")
    with a:
        with b:
            pass
    try:
        with b:
            with a:  # the deliberate inversion the sanitizer must catch
                pass
    except LockOrderError:
        pass
    return len(result.findings), rules_checked, dep.inversions


def bench_ir():
    """graftir (hyperopt-tpu-lint --ir) over the program registry: the
    count of dispatch-critical families whose jaxpr/lowering checked
    out, and how many drifted from the committed shape/cost manifest --
    stamped so a program whose contract moved (shape, donation, FLOPs)
    is visible in the round JSON even when nobody ran the fast tier.

    Traces and lowers on CPU only -- no device execution, so the rows
    are identical on- and off-accelerator."""
    from hyperopt_tpu.analysis.ir import check_programs

    repo = os.path.dirname(os.path.abspath(__file__))
    result = check_programs(
        contracts_path=os.path.join(repo, "program_contracts.json")
    )
    return result.programs_checked, result.contract_drift


def bench_wire():
    """graftwire (hyperopt-tpu-lint --wire) over the protocol seams:
    how many wire ops checked out across both fronts, how many drifted
    from the committed wire_contracts.json, and the fraction of
    registered crash points some test actually arms -- stamped so a
    dead fault window or a silent reply-shape change is visible in the
    round JSON even when nobody ran the fast tier.

    Returns (wire_ops_checked, wire_contract_drift,
    crash_points_armed_frac); the fraction must be 1.0 on a healthy
    tree (the GL604 satellite) and the smoke test pins it.  Pure AST
    -- no server starts, no socket opens."""
    from hyperopt_tpu.analysis.wire import check_wire

    repo = os.path.dirname(os.path.abspath(__file__))
    result = check_wire(root=repo)
    frac = (
        result.crash_points_armed / result.crash_points_total
        if result.crash_points_total else 0.0
    )
    return result.ops_checked, result.contract_drift, round(frac, 4)


def bench_rtt(n_calls=20):
    """Dispatch round-trip of a trivial device program, in ms.

    Wall-clock rows (seconds_to_best_at_1k, sync suggest rates) are
    RTT-dominated on a remote-attached chip (~100 ms/call over the axon
    tunnel vs low-single-digit ms on PCIe/ICI); emitting the measured
    RTT with every bench run makes that variance attributable instead of
    looking like program regressions (VERDICT r2 weak #1).  Completion
    is forced by a scalar fetch: ``block_until_ready`` is a no-op on the
    tunnel platform.
    """
    import jax
    import jax.numpy as jnp

    f = jax.jit(lambda x: x + 1.0)
    float(f(jnp.float32(0.0)))  # compile
    t0 = time.perf_counter()
    for i in range(n_calls):
        float(f(jnp.float32(i)))  # fetch forces the full round-trip
    return (time.perf_counter() - t0) / n_calls * 1000.0


def bench_host_tpe(domain, trials, n_calls=15, native=False):
    """Host path: per-trial interpreted TPE suggest.

    ``native=False`` pins the pure-numpy oracle (the reference's execution
    model -- the honest baseline); ``native=True`` lets the C++ host-math
    library serve the hot functions (this framework's accelerated host
    path).
    """
    import contextlib
    import unittest.mock

    from hyperopt_tpu import tpe

    if native:
        from hyperopt_tpu import native as native_mod

        ctx = (
            contextlib.nullcontext()
            if native_mod.available()
            else None
        )
        if ctx is None:
            return None
    else:
        ctx = unittest.mock.patch.object(tpe, "_native", lambda: None)

    with ctx:
        # warmup (builds the vectorize helper cache)
        tpe.suggest([10_000], domain, trials, seed=0)
        t0 = time.perf_counter()
        for i in range(n_calls):
            tpe.suggest([10_001 + i], domain, trials, seed=i)
        dt = time.perf_counter() - t0
    return n_calls / dt


def bench_jax_tpe(domain, trials, batch=64, n_cand=128, n_calls=30,
                  above_cap=None):
    """TPU path: one compiled program suggests the whole batch.

    ``above_cap`` is :func:`tpe_jax.build_suggest_fn`'s above-model
    compaction knob (None = framework default, 0 = full-width scoring);
    the obs-scaling sweep measures both settings at each history size.
    """
    import jax

    from hyperopt_tpu import tpe_jax
    from hyperopt_tpu.jax_trials import obs_buffer_for, packed_space_for

    ps = packed_space_for(domain)
    buf = obs_buffer_for(domain, trials)
    fn = tpe_jax.build_suggest_fn(ps, n_cand, 0.25, 25.0, 1.0,
                                  above_cap=above_cap)
    arrays = buf.device_arrays(
        pow2_cap=tpe_jax._resolve_above_cap(above_cap)
    )
    key = jax.random.key(0)

    out = fn(key, *arrays, batch=batch)  # compile
    jax.block_until_ready(out)
    # pre-derive per-call keys: a fold_in inside the timed loop would add
    # one extra (tunnel-latency) device dispatch per iteration
    keys = list(jax.random.split(key, n_calls))
    jax.block_until_ready(keys)
    t0 = time.perf_counter()
    for i in range(n_calls):
        out = fn(keys[i], *arrays, batch=batch)
    jax.block_until_ready(out)
    dt = time.perf_counter() - t0
    return batch * n_calls / dt, out


def bench_obs_scaling(space, batch, n_cand, sizes):
    """Suggestion-throughput sweep over history sizes (VERDICT r5 item
    2): the high-observation cliff, tracked round over round.  At each
    observation count the jitted batched suggest is timed twice --
    compacted (the default above-model cap) and full-width
    (``above_cap=0``, the pre-round-6 behavior) -- so the JSON carries
    both the absolute curve and the compaction speedup.

    Returns a list of {n_obs, suggestions_per_sec,
    full_width_suggestions_per_sec, compaction_speedup_x} rows.
    """
    rows = []
    for n_obs in sizes:
        domain, trials = build_history(n_obs, space, seed=n_obs)
        # fewer timed calls at the big sizes: the full-width run is the
        # pre-fix cliff being measured, no need to soak in it
        n_calls = 8 if n_obs <= 2500 else 4
        rate, _ = bench_jax_tpe(
            domain, trials, batch=batch, n_cand=n_cand, n_calls=n_calls
        )
        full_rate, _ = bench_jax_tpe(
            domain, trials, batch=batch, n_cand=n_cand, n_calls=n_calls,
            above_cap=0,
        )
        rows.append({
            "n_obs": n_obs,
            "suggestions_per_sec": round(rate, 1),
            "full_width_suggestions_per_sec": round(full_rate, 1),
            "compaction_speedup_x": (
                round(rate / full_rate, 2) if full_rate else None
            ),
        })
    return rows


def bench_jax_latency(domain, trials, n_cand=128, n_calls=30):
    """Single-suggest (B=1) latency path.

    Returns the PIPELINED rate: every call enqueued, one block at the
    end (device-compute bound; the SAME semantics as round 1's
    single_suggest_per_sec, kept for round-over-round comparison).
    The old companion ``single_suggest_sync_per_sec`` -- blocking on
    every call, what the RETIRED solo sync driver paid per ask -- is
    gone with its regime (round 20): a sequential ``fmin`` now rides
    the serve engine (``fmin_client_asks_per_sec``), so the 8.7/s
    two-round-trips-per-trial floor is no longer a path any driver
    takes.

    The device view is bucketed with the round-6 compaction default
    (``pow2_cap``), exactly the path ``suggest()`` runs -- an uncapped
    view would time a wider history slice than any real ask uploads.
    """
    import jax

    from hyperopt_tpu import tpe_jax
    from hyperopt_tpu.jax_trials import obs_buffer_for, packed_space_for

    ps = packed_space_for(domain)
    buf = obs_buffer_for(domain, trials)
    fn = tpe_jax.build_suggest_fn(ps, n_cand, 0.25, 25.0, 1.0)
    arrays = buf.device_arrays(
        pow2_cap=tpe_jax._resolve_above_cap(None)
    )
    key = jax.random.key(1)
    jax.block_until_ready(fn(key, *arrays, batch=1))
    keys = list(jax.random.split(key, n_calls))
    jax.block_until_ready(keys)
    t0 = time.perf_counter()
    for i in range(n_calls):
        out = fn(keys[i], *arrays, batch=1)
    jax.block_until_ready(out)
    return n_calls / (time.perf_counter() - t0)


def bench_spec_latency(domain, trials, n_cand=128, k=32, n_calls=64):
    """Per-ask rate of the speculative path through the REAL suggest API
    (cache pops + one k-wide dispatch per k asks on a fixed history)."""
    from functools import partial

    from hyperopt_tpu import tpe_jax

    algo = partial(tpe_jax.suggest, n_EI_candidates=n_cand, speculative=k)
    algo(trials.new_trial_ids(1), domain, trials, seed=0)  # warm/compile
    t0 = time.perf_counter()
    for i in range(n_calls):
        algo(trials.new_trial_ids(1), domain, trials, seed=1 + i)
    return n_calls / (time.perf_counter() - t0)


def _tell_from_col(ps, buf, i, loss):
    """Stage one synthetic completed trial into ``buf`` (values recycled
    from an existing observation column -- speed benches only care about
    the tell/ask mechanics, not the posterior trajectory)."""
    col = i % max(buf.count, 1)
    vals = {
        ps.labels[d]: float(buf.values[d, col])
        for d in range(ps.n_dims)
        if buf.active[d, col]
    }
    buf.add(vals, float(loss))


def bench_fused_latency(domain, trials, n_cand=128, n_calls=30):
    """Fused tell+ask sync rate: the one-dispatch sequential regime.

    Each timed iteration is one full sequential step -- stage an O(D)
    delta tell, then apply it AND draw the next suggestion in a single
    blocking dispatch (``build_suggest_fn(state_io=True)`` over a
    resident history).  Reported alongside
    ``single_suggest_sync_per_sec``, whose two blocking round trips per
    trial (history upload + suggest dispatch) this path halves.  Runs
    on a private resident mirror so the shared buffer's cache is
    untouched.
    """
    import jax

    from hyperopt_tpu import tpe_jax
    from hyperopt_tpu.jax_trials import ObsBuffer, packed_space_for

    ps = packed_space_for(domain)
    buf = ObsBuffer(ps, resident=True)
    buf.sync(trials)
    a_cap = tpe_jax._resolve_above_cap(None)
    fused = tpe_jax.build_suggest_fn(ps, n_cand, 0.25, 25.0, 1.0,
                                     state_io=True)
    plain = tpe_jax.build_suggest_fn(ps, n_cand, 0.25, 25.0, 1.0)
    buf.device_arrays(pow2_cap=a_cap)  # materialize the mirror
    keys = list(jax.random.split(jax.random.key(2), n_calls + 1))
    jax.block_until_ready(keys)

    def step(i, key):
        _tell_from_col(ps, buf, i, loss=float(i % 7))
        fusable = buf.take_fusable_delta(a_cap)
        if fusable is None:  # bucket crossed mid-bench: settle + plain ask
            out = plain(key, *buf.device_arrays(pow2_cap=a_cap), batch=1)
            return jax.device_get(out)
        state, delta = fusable
        out = fused(key, *state, *delta, batch=1)
        buf.commit_resident(out[:4])
        return jax.device_get((out[4], out[5]))

    step(0, keys[-1])  # compile
    t0 = time.perf_counter()
    for i in range(n_calls):
        step(1 + i, keys[i])
    return n_calls / (time.perf_counter() - t0)


def bench_transfer_per_ask(space, sizes, n_asks=8):
    """Host->device traffic of one sequential tell+ask, COUNTED (not
    timed) from the ObsBuffer byte accounting, at each history size:
    resident O(D) delta vs generation-bump full re-upload.  The
    resident row must stay flat in n_obs (the acceptance contract);
    the re-upload row grows with the bucketed history width.
    """
    from hyperopt_tpu import tpe_jax
    from hyperopt_tpu.jax_trials import ObsBuffer, packed_space_for

    a_cap = tpe_jax._resolve_above_cap(None)
    rows = []
    for n_obs in sizes:
        domain, trials = build_history(n_obs, space, seed=n_obs)
        ps = packed_space_for(domain)
        per_ask = {}
        for resident in (True, False):
            buf = ObsBuffer(ps, resident=resident)
            buf.sync(trials)
            buf.device_arrays(pow2_cap=a_cap)  # steady state: mirror warm
            b0 = buf.transfer_bytes_total
            for i in range(n_asks):
                _tell_from_col(ps, buf, i, loss=float(i % 5))
                buf.device_arrays(pow2_cap=a_cap)  # what one ask uploads
            per_ask[resident] = (buf.transfer_bytes_total - b0) / n_asks
        rows.append({
            "n_obs": n_obs,
            "resident_bytes_per_ask": round(per_ask[True], 1),
            "full_reupload_bytes_per_ask": round(per_ask[False], 1),
        })
    return rows


def bench_fused_dispatches(n_trials=120, seed=11):
    """Deterministic dispatch accounting for the fused sequential driver:
    a real ``fmin`` run (``algo=tpe_jax.suggest(fused=True)`` over
    ``JaxTrials(resident=True)``) whose ObsBuffer dispatch counter is
    read back -- one device dispatch per trial is the contract (the
    counter-based form of "tell+ask fused", immune to timing noise).
    ``n_trials`` stays below the first bucket-growth boundary so the
    expected count is exactly ``n_trials`` + 1 trailing ask-ahead
    pre-dispatch after the final result.
    """
    from functools import partial

    import numpy as np

    from hyperopt_tpu import fmin, tpe_jax
    from hyperopt_tpu.jax_trials import JaxTrials
    from hyperopt_tpu.models.synthetic import mixed_space, mixed_space_fn

    trials = JaxTrials(resident=True)
    fmin(
        mixed_space_fn,
        mixed_space(),
        algo=partial(tpe_jax.suggest, fused=True),
        max_evals=n_trials,
        trials=trials,
        rstate=np.random.default_rng(seed),
        show_progressbar=False,
        return_argmin=False,
    )
    buf = next(iter(trials._buffers.values()))
    # the trailing pre-dispatch (enqueued after the last result, never
    # consumed) is driver wind-down, not per-trial cost
    return (buf.dispatch_count - 1) / n_trials


def bench_resume_overhead(n_trials=60, seed=11):
    """Per-trial cost of crash recoverability (ISSUE 6 acceptance row):
    a real fused ``fmin`` run with ``DriverRecovery`` active, reading
    back the coordinator's own wall-clock accumulator (WAL appends +
    bundle publishes) -- a direct measurement, immune to the compile-
    time noise a with/without A-B comparison would drown in.

    Returns (seconds_per_trial, wal_tells) -- the second is the
    zero-lost/zero-duplicate counter, asserted == n_trials.
    """
    import tempfile
    from functools import partial

    import numpy as np

    from hyperopt_tpu import fmin, tpe_jax
    from hyperopt_tpu.jax_trials import JaxTrials
    from hyperopt_tpu.models.synthetic import mixed_space, mixed_space_fn
    from hyperopt_tpu.utils.checkpoint import DriverRecovery

    with tempfile.TemporaryDirectory() as d:
        rec = DriverRecovery(os.path.join(d, "bench.ckpt"), cadence=25)
        trials = JaxTrials(resident=True)
        fmin(
            mixed_space_fn,
            mixed_space(),
            algo=partial(tpe_jax.suggest, fused=True),
            max_evals=n_trials,
            trials=trials,
            resume_from=rec,
            rstate=np.random.default_rng(seed),
            show_progressbar=False,
            return_argmin=False,
        )
        return rec.seconds_spent / n_trials, rec.wal.total_tells


def bench_serve(space, n_studies=64, rounds=6, n_cand=128,
                n_startup_jobs=3):
    """The multi-tenant suggestion service (round 12): ``n_studies``
    concurrent studies, one slotted batch, ``rounds`` full ask+tell
    rounds -- so each timed round is ONE study-batched fused tell+ask
    dispatch serving every study.  The solo baseline is the same
    engine at one study (the sequential fused ask a lone tenant pays),
    so the speedup column isolates what continuous batching buys.

    Returns a dict of the stamped keys: ``serve_studies_per_sec``
    (asks served per second across studies), ``serve_ask_p50_ms`` /
    ``serve_ask_p99_ms`` (submit-to-ack latency percentiles),
    ``serve_batch_occupancy`` (mean filled-slot fraction of the timed
    rounds), ``serve_vs_solo_speedup_x``, and the config stamps.
    """
    from hyperopt_tpu.serve import SuggestService

    def run(n, n_rounds, warmup_rounds=1):
        svc = SuggestService(
            space, max_batch=max(n, 4), background=False,
            n_startup_jobs=n_startup_jobs, n_cand=n_cand,
        )
        handles = [
            svc.create_study(f"bench{i:03d}", seed=i) for i in range(n)
        ]

        def loss(vals):
            return sum(
                float(v) for v in vals.values()
                if isinstance(v, (int, float))
            )

        def round_once():
            futs = [h.ask_async() for h in handles]
            svc.pump()
            for h, f in zip(handles, futs):
                tid, vals = f.result(timeout=120)
                h.tell(tid, loss(vals))

        for _ in range(warmup_rounds):
            round_once()  # compile + first materialization
        lat0 = len(svc.scheduler.ask_latencies)
        t0 = time.perf_counter()
        for _ in range(n_rounds):
            round_once()
        dt = time.perf_counter() - t0
        # the metrics are bounded deques: snapshot to lists to slice
        lats = list(svc.scheduler.ask_latencies)[lat0:]
        occ = list(svc.scheduler.occupancy)[-n_rounds:]
        svc.shutdown()
        return n * n_rounds / dt, lats, occ

    rate, lats, occ = run(n_studies, rounds)
    # solo baseline: same engine, one tenant, same ask count budget
    solo_rate, _, _ = run(1, min(max(rounds * 4, 8), 32))
    lats_ms = sorted(1000.0 * x for x in lats)

    def pct(p):
        return lats_ms[min(len(lats_ms) - 1, int(p * len(lats_ms)))]

    return {
        "serve_studies_per_sec": round(rate, 1),
        "serve_ask_p50_ms": round(pct(0.50), 3),
        "serve_ask_p99_ms": round(pct(0.99), 3),
        "serve_batch_occupancy": round(float(np.mean(occ)), 4),
        "serve_vs_solo_speedup_x": (
            round(rate / solo_rate, 2) if solo_rate else None
        ),
        "serve_solo_asks_per_sec": round(solo_rate, 1),
        "serve_batch": n_studies,
    }


def bench_serve_mesh(space, mesh_devices=(1, 2, 4), n_studies=64,
                     rounds=6, n_cand=128, n_startup_jobs=3):
    """graftmesh serve rows (round 17): the study-batched fused
    tell+ask with its slot axis sharded over a ``study`` mesh, per
    mesh shape.  Keys are ``"study=N"``; values are asks served per
    second across studies (same protocol as :func:`bench_serve`'s
    timed window).  On virtual CPU devices the absolute numbers share
    the host's cores -- the per-shape trajectory is the comparable
    signal, and real multi-chip hardware fills in the scaling claim
    via the MULTICHIP dryrun's serve stage.

    Returns ``(rates, efficiency)`` -- ``efficiency["study=N"]`` is
    ``rate_N / (N * rate_1)``, the near-linear-scaling diagnostic.
    """
    import jax

    from hyperopt_tpu.parallel.mesh import study_mesh
    from hyperopt_tpu.serve import SuggestService

    avail = len(jax.devices())

    def loss(vals):
        return sum(
            float(v) for v in vals.values() if isinstance(v, (int, float))
        )

    rates = {}
    for n_dev in mesh_devices:
        if n_dev > avail:
            continue
        svc = SuggestService(
            space, max_batch=max(n_studies, 4), background=False,
            n_startup_jobs=n_startup_jobs, n_cand=n_cand,
            mesh=study_mesh(n_dev),
        )
        handles = [
            svc.create_study(f"mesh{n_dev}_{i:03d}", seed=i)
            for i in range(n_studies)
        ]

        def round_once():
            futs = [h.ask_async() for h in handles]
            svc.pump()
            for h, f in zip(handles, futs):
                tid, vals = f.result(timeout=120)
                h.tell(tid, loss(vals))

        round_once()  # compile + first materialization
        t0 = time.perf_counter()
        for _ in range(rounds):
            round_once()
        dt = time.perf_counter() - t0
        svc.shutdown()
        rates[f"study={n_dev}"] = round(n_studies * rounds / dt, 1)

    base = rates.get("study=1")
    efficiency = {
        k: round(v / (int(k.split("=")[1]) * base), 4)
        for k, v in rates.items()
        if base and k != "study=1"
    }
    return rates, efficiency


def bench_pbt_mesh(mesh_devices=(1, 2, 4), pop=64, exploit_every=5,
                   n_rounds=8):
    """graftmesh PBT rows (round 17): the shard_map population
    schedule (member blocks training collective-free, all-gathers only
    at exploit boundaries) at each ``trial`` mesh shape, on the
    synthetic quadratic member (CPU-sized; the transformer family
    rides the same ``compile_pbt`` seam on accelerators).  Keys are
    ``"trial=N"``; values member-steps/s.  Returns
    ``(rates, efficiency)`` like :func:`bench_serve_mesh`."""
    import jax
    import jax.numpy as jnp

    from hyperopt_tpu.parallel.mesh import mesh_from_spec
    from hyperopt_tpu.pbt import compile_pbt

    avail = len(jax.devices())

    def train_fn(state, hypers, key):
        theta = state["theta"] - hypers["lr"] * 2.0 * (
            state["theta"] - 0.7
        )
        return {"theta": theta}, (theta - 0.7) ** 2

    init = {"theta": jnp.zeros((pop,), jnp.float32)}
    rates = {}
    for n_dev in mesh_devices:
        if n_dev > avail or pop % n_dev:
            continue
        if n_dev == 1:
            runner = compile_pbt(
                train_fn, init, {"lr": (1e-3, 1.0)}, pop_size=pop,
                exploit_every=exploit_every, n_rounds=n_rounds,
            )
        else:
            runner = compile_pbt(
                train_fn, init, {"lr": (1e-3, 1.0)}, pop_size=pop,
                exploit_every=exploit_every, n_rounds=n_rounds,
                mesh=mesh_from_spec((n_dev,), ("trial",)),
                trial_axis="trial", shard_mode="shard_map",
            )
        runner(seed=99)  # compile
        t0 = time.perf_counter()
        runner(seed=0)
        dt = time.perf_counter() - t0
        rates[f"trial={n_dev}"] = round(
            pop * exploit_every * n_rounds / dt, 1
        )

    base = rates.get("trial=1")
    efficiency = {
        k: round(v / (int(k.split("=")[1]) * base), 4)
        for k, v in rates.items()
        if base and k != "trial=1"
    }
    return rates, efficiency


def bench_guard(space, n_cand=128):
    """graftguard rows (round 13): the runtime-protection layer's
    three behaviors, measured on small deterministic scenarios.

    ``serve_shed_rate``: fraction of a 4x-overcommitted submit storm
    refused with typed ``Overloaded`` (deterministic: counted, the
    queue bound decides it).  ``serve_quarantine_count``: finite-check
    trips a NaN-telling tenant accrues before K-trip eviction
    (deterministic: equals the eviction threshold).
    ``serve_watchdog_recovery_ms``: wall-clock from an injected
    dispatch hang's watchdog timeout to the retried round serving
    (measured; the one timing row).
    """
    from hyperopt_tpu.distributed.faults import DeviceFaultPlan, FaultPlan
    from hyperopt_tpu.exceptions import Overloaded, ServeError
    from hyperopt_tpu.serve import SuggestService

    def loss(vals):
        return sum(
            float(v) for v in vals.values() if isinstance(v, (int, float))
        )

    # -- shed rate under a 4x submit storm --------------------------------
    svc = SuggestService(
        space, max_batch=8, background=False, n_startup_jobs=3,
        n_cand=n_cand, max_queue=8, study_queue_cap=2,
    )
    handles = [svc.create_study(f"ov{i}", seed=i) for i in range(8)]
    futs = []
    for _ in range(4):
        for h in handles:
            try:
                futs.append(h.ask_async())
            except Overloaded:
                pass
    while any(not f.done() for f in futs):
        svc.pump()
    sched = svc.scheduler
    shed_rate = sched.shed_count / (sched.shed_count + sched.admitted_count)
    svc.shutdown()

    # -- quarantine trips to eviction for a NaN tenant --------------------
    svc = SuggestService(
        space, max_batch=4, background=False, n_startup_jobs=3,
        n_cand=n_cand,
    )
    bad = svc.create_study("bad", seed=1)
    first = dict(svc.create_study("probe", seed=2).ask(timeout=60)[1])
    bad.tell(0, float("nan"), vals=first)
    for _ in range(4):
        if svc.scheduler.study("bad").quarantined:
            break
        try:
            f = bad.ask_async()
            svc.pump()
            f.exception(timeout=60)
        except ServeError:
            break
    quarantine_count = svc.scheduler.quarantine_count
    assert svc.scheduler.evictions == 1
    svc.shutdown()

    # -- watchdog recovery from a hung dispatch ---------------------------
    plan = FaultPlan(seed=0, device=DeviceFaultPlan(hang_at=2, hang_s=0.5))
    svc = SuggestService(
        space, max_batch=4, background=False, n_startup_jobs=3,
        n_cand=n_cand, fs=plan.fs(),
    )
    h = svc.create_study("w", seed=3)
    for rnd in range(2):
        tid, vals = h.ask(timeout=60)
        h.tell(tid, loss(vals))
        if rnd == 0:  # arm after the compile round
            svc.scheduler.dispatch_timeout = 0.1
    assert svc.scheduler.watchdog_recoveries == 1
    recovery_ms = float(svc.scheduler.watchdog_recovery_ms[0])
    svc.shutdown()

    return {
        "serve_shed_rate": round(float(shed_rate), 4),
        "serve_quarantine_count": int(quarantine_count),
        "serve_watchdog_recovery_ms": round(recovery_ms, 3),
    }


def bench_fleet(space, n_replicas=3, n_studies=12, rounds=3, n_cand=128):
    """graftfleet rows (round 18): the horizontal serve fleet -- N
    replica services behind the consistent-hash router, studies rooted
    in one shared WAL/snapshot directory with claim/epoch fencing.

    ``fleet_studies_per_sec``: asks served per second aggregated
    across the fleet (per-replica coalesced dispatch rounds via the
    router's batch path).  ``fleet_ask_p99_ms_failover``: p99 per-ask
    latency over a window in which one replica is KILLED -- the first
    ask that finds it dead pays the failover (WAL+bundle
    re-materialization on survivors) inline, so the tail IS the
    recovery story.  ``fleet_recovery_ms``: wall-clock of that
    failover re-materialization (measured).  The 10^4-study churn soak
    lives in ``tests/test_fleet_chaos.py`` (slow tier); this is its
    small, every-round twin.
    """
    import shutil
    import tempfile

    from hyperopt_tpu.serve import Fleet, FleetRouter

    def loss(vals):
        return sum(
            float(v) for v in vals.values() if isinstance(v, (int, float))
        )

    root = tempfile.mkdtemp(prefix="bench-fleet-")
    try:
        fleet = Fleet(
            space, root, n_replicas=n_replicas, max_batch=16,
            n_startup_jobs=3, n_cand=n_cand, snapshot_cadence=64,
        )
        router = FleetRouter(fleet)
        names = [f"f{i:03d}" for i in range(n_studies)]
        for i, n in enumerate(names):
            router.create_study(n, seed=i)

        def round_once():
            got = router.ask_batch(names, timeout=120)
            for n, (tid, vals) in got.items():
                router.tell(n, tid, loss(vals), vals=vals)

        # boot pre-warm (the LLM-serving pattern): push every replica
        # to its full pow2 slot cap once, so the one cap-16 trace is
        # compiled up front and neither churn nor failover adoption
        # ever recompiles mid-traffic (pow2 caps never shrink, so the
        # shape sticks) -- the failover window below then measures
        # failover, not XLA compiles
        for rid in sorted(fleet.replicas):
            rep = fleet.replicas[rid]
            n_pads = max(0, 9 - len(rep.service.studies()))
            pads = [
                rep.open_study(f"warm-{rid}-{i:02d}", seed=1000 + i)
                for i in range(n_pads)
            ]
            futs = [h.ask_async() for h in pads]
            if futs:
                rep.pump_until(futs, timeout=120)
            for h in pads:
                h.close()

        round_once()  # compile + first materialization
        t0 = time.perf_counter()
        for _ in range(rounds):
            round_once()
        dt = time.perf_counter() - t0
        rate = n_studies * rounds / dt

        # the failover window: kill one replica, then drive per-ask so
        # the latency distribution includes the inline recovery
        victim = fleet.route(names[0])
        fleet.kill_replica(victim)
        lats = []
        for _ in range(2):
            for n in names:
                t1 = time.perf_counter()
                tid, vals = router.ask(n, timeout=120)
                lats.append(time.perf_counter() - t1)
                router.tell(n, tid, loss(vals), vals=vals)
        recovery_ms = fleet.recovery_ms
        fleet.shutdown()
    finally:
        shutil.rmtree(root, ignore_errors=True)
    lats_ms = sorted(1000.0 * x for x in lats)
    p99 = lats_ms[min(len(lats_ms) - 1, int(0.99 * len(lats_ms)))]
    return {
        "fleet_studies_per_sec": round(rate, 1),
        "fleet_ask_p99_ms_failover": round(p99, 3),
        "fleet_recovery_ms": round(float(recovery_ms), 3),
        "fleet_replicas": n_replicas,
    }


def bench_pilot(space, n_studies=12, rounds=3, n_cand=128):
    """graftpilot rows (round 21): the self-driving fleet.

    ``pilot_scale_out_ms`` / ``pilot_scale_in_ms``: wall-clock of one
    pilot-driven membership actuation -- ``add_replica`` with live
    study migration on the way out, drain + retire on the way in --
    as timed by the controller's own gauges.
    ``fleet_studies_per_sec_autoscaled``: asks served per second
    while the fleet runs UNDER the control loop -- each wave is
    submitted async so the pilot's scrape (the same
    ``fleet.metrics_rows`` a /metrics poller reads; no side channel)
    sees the real queue before the wave is pumped.  The 10^4-study
    autoscaled soak in ``tests/test_fleet_chaos.py`` is this at full
    scale.  ``replay_fidelity``: the flight log recorded during that
    traffic, replayed through the graftreplay harness against a fresh
    solo service, reproduces every suggestion stream bitwise (1.0 on
    hash match -- the record-once-replay-bitwise contract).
    """
    import shutil
    import tempfile

    from hyperopt_tpu.obs.flightrec import FlightRecorder
    from hyperopt_tpu.serve import (
        Fleet,
        FleetPilot,
        FleetRouter,
        PilotConfig,
        SuggestService,
    )
    from hyperopt_tpu.serve.replay import (
        ServiceTarget,
        load_workload,
        replay_fidelity,
        replay_workload,
    )

    def loss(vals):
        return sum(
            float(v) for v in vals.values() if isinstance(v, (int, float))
        )

    root = tempfile.mkdtemp(prefix="bench-pilot-")
    log = os.path.join(root, "flight.jsonl")
    try:
        recorder = FlightRecorder(path=log)
        fleet = Fleet(
            space, root, replica_ids=["r0", "r1"], max_batch=16,
            n_startup_jobs=3, n_cand=n_cand, snapshot_cadence=64,
            recorder=recorder,
        )
        router = FleetRouter(fleet)
        pilot = FleetPilot(fleet, config=PilotConfig(
            min_replicas=1, max_replicas=3, shed_high=0,
            queue_high=max(2.0, n_studies / 2), breach_ticks=1,
            clear_ticks=1, cooldown_ticks=0,
        ))
        names = [f"a{i:03d}" for i in range(n_studies)]
        recorded = {n: [] for n in names}
        for i, n in enumerate(names):
            router.create_study(n, seed=i)
        served = 0
        t0 = time.perf_counter()
        for _ in range(rounds):
            by_rep = {}
            for n in names:
                by_rep.setdefault(fleet.route(n), []).append(n)
            futs = {}
            for rid, group in by_rep.items():
                rep = fleet.replicas[rid]
                for n in group:
                    futs[n] = (rid, rep.ask_async(n))
            pilot.tick()  # the scrape sees the queued wave
            got, shed = {}, []
            for rid in {r for r, _ in futs.values()}:
                group = [
                    (n, f) for n, (r2, f) in futs.items() if r2 == rid
                ]
                fleet.replicas[rid].pump_until(
                    [f for _, f in group], timeout=120
                )
                for n, f in group:
                    try:
                        got[n] = f.result(timeout=0)
                    except ValueError:
                        # shed by the pilot's mid-wave migration: the
                        # WAL-logged seed re-serves identically
                        shed.append(n)
            for n in shed:
                got[n] = router.ask(n, timeout=120, recover=True)
            for n in names:
                tid, vals = got[n]
                router.tell(n, tid, loss(vals), vals=vals)
                recorded[n].append((int(tid), dict(vals)))
                served += 1
        dt = time.perf_counter() - t0
        # the quiet tail: the pilot shrinks the fleet back down
        for _ in range(4):
            pilot.tick()
        prow = {
            r["name"]: r for r in pilot.metrics_rows()
            if not r.get("labels")
        }
        out_ms = prow["pilot_scale_out_ms"]["value"]
        in_ms = prow["pilot_scale_in_ms"]["value"]
        fleet.shutdown()
        recorder.flush()
        target = ServiceTarget(SuggestService(
            space, background=False, max_batch=16, n_startup_jobs=3,
            n_cand=n_cand,
        ))
        replayed = replay_workload(load_workload(log), target, timeout=120)
        target.service.shutdown()
        fidelity = replay_fidelity(recorded, replayed)
    finally:
        shutil.rmtree(root, ignore_errors=True)
    return {
        "pilot_scale_out_ms": round(float(out_ms), 3),
        "pilot_scale_in_ms": round(float(in_ms), 3),
        "fleet_studies_per_sec_autoscaled": round(served / dt, 1),
        "replay_fidelity": fidelity,
    }


def bench_obs(space, n_cand=128, n_startup_jobs=3, n_studies=8,
              rounds=12):
    """graftscope rows (round 19): what observability costs, measured.

    * ``obs_overhead_frac_serve`` -- the study-batched serve loop with
      a flight recorder at FULL cadence + the device-metrics twin at
      cadence 1, as a fractional slowdown vs the untracked loop
      (acceptance budget: <= 0.05 at default cadence; the default --
      everything off -- costs exactly zero extra dispatches, pinned
      deterministically in tests/test_obs.py);
    * ``obs_overhead_frac_fused`` -- the same comparison in the
      one-tenant fused regime (batch 1, the solo driver's dispatch
      shape);
    * ``obs_events_per_sec`` -- spans recorded per second during the
      armed serve window;
    * ``metrics_scrape_ms_fleet`` -- wall-clock of ONE fleet-wide
      ``metrics`` scrape through a live TCP router over two replicas
      (median of 5 round-trips).
    """
    import json as _json
    import socket as _socket
    import threading as _threading

    from hyperopt_tpu.obs import FlightRecorder
    from hyperopt_tpu.serve import SuggestService

    def loss(vals):
        return sum(
            float(v) for v in vals.values()
            if isinstance(v, (int, float))
        )

    def run(n, n_rounds, recorder=None, every=0):
        svc = SuggestService(
            space, max_batch=max(n, 4), background=False,
            n_startup_jobs=n_startup_jobs, n_cand=n_cand,
            recorder=recorder, device_metrics_every=every,
        )
        handles = [
            svc.create_study(f"obs{i:03d}", seed=i) for i in range(n)
        ]

        def round_once():
            futs = [h.ask_async() for h in handles]
            svc.pump()
            for h, f in zip(handles, futs):
                tid, vals = f.result(timeout=120)
                h.tell(tid, loss(vals))

        round_once()  # compile + first materialization
        t0 = time.perf_counter()
        for _ in range(n_rounds):
            round_once()
        dt = time.perf_counter() - t0
        svc.shutdown()
        return dt

    def overhead(n, n_rounds):
        # armed at the DEFAULT cadence: the flight recorder records
        # every span (cadence 1), the device twin stays at its default
        # (off -- its zero-extra-dispatch half is pinned in test_obs)
        plain = run(n, n_rounds)
        rec = FlightRecorder(capacity=65536)
        t0 = rec.recorded_total
        armed = run(n, n_rounds, recorder=rec)
        frac = max(0.0, armed / plain - 1.0)
        events = (rec.recorded_total - t0) / armed
        return frac, events

    serve_frac, events_per_sec = overhead(n_studies, rounds)
    fused_frac, _ = overhead(1, max(rounds * 4, 16))

    # the fleet-wide scrape: two TCP replicas behind a live router
    from hyperopt_tpu.serve.router import RouterServer, _Backend
    from hyperopt_tpu.serve.service import serve_forever

    svcs, servers, backends = [], [], []
    for rid in ("b0", "b1"):
        svc = SuggestService(
            space, background=True, max_wait_ms=1.0,
            n_startup_jobs=n_startup_jobs, n_cand=n_cand, owner=rid,
        )
        server = serve_forever(svc, port=0)
        _threading.Thread(target=server.serve_forever, daemon=True).start()
        svcs.append(svc)
        servers.append(server)
        backends.append(
            _Backend(rid, "127.0.0.1", server.server_address[1])
        )
    router = RouterServer(backends)
    rserver = router.serve_forever(port=0)
    _threading.Thread(target=rserver.serve_forever, daemon=True).start()
    try:
        with _socket.create_connection(
            ("127.0.0.1", rserver.server_address[1]), timeout=30
        ) as sock:
            f = sock.makefile("rw")
            samples = []
            for _ in range(5):
                t0 = time.perf_counter()
                f.write(_json.dumps({"op": "metrics"}) + "\n")
                f.flush()
                reply = _json.loads(f.readline())
                samples.append(1000.0 * (time.perf_counter() - t0))
                assert reply.get("ok") and len(reply["replicas"]) == 2
        scrape_ms = sorted(samples)[len(samples) // 2]
    finally:
        rserver.shutdown()
        rserver.server_close()
        for server in servers:
            server.shutdown()
            server.server_close()
        for svc in svcs:
            svc.shutdown()

    return {
        "obs_overhead_frac_serve": round(serve_frac, 4),
        "obs_overhead_frac_fused": round(fused_frac, 4),
        "obs_events_per_sec": round(events_per_sec, 1),
        "metrics_scrape_ms_fleet": round(scrape_ms, 3),
    }


def bench_device_loop(n_evals=8192, batch=128):
    """Secondary metric: a FULL experiment (suggest + evaluate + history)
    as one on-device program -- trials/sec end-to-end on a 2-dim
    quadratic (device_loop.compile_fmin).  Runs on EVERY backend --
    CPU rounds get a CPU-sized config from main() and the JSON stamps
    the config keyed by backend, so the trajectory is stamped every
    round and rounds stay comparable within a backend."""
    import time

    try:
        import jax.numpy as jnp

        from hyperopt_tpu import hp
        from hyperopt_tpu.device_loop import compile_fmin

        space = {
            "x": hp.uniform("x", -5.0, 5.0),
            "y": hp.loguniform("y", -7.0, 2.3),
        }

        def obj(cfg):
            return (cfg["x"] - 1.0) ** 2 + (jnp.log(cfg["y"]) + 2.3) ** 2

        runner = compile_fmin(obj, space, max_evals=n_evals, batch_size=batch)
        runner(seed=0)  # compile
        t0 = time.perf_counter()
        runner(seed=1)
        return n_evals / (time.perf_counter() - t0)
    except Exception:  # secondary metric must never sink the headline
        import traceback

        print("bench_device_loop failed:", file=sys.stderr)
        traceback.print_exc()
        return None


def bench_asha_device(max_jobs=40, workers=4, max_budget=27, eta=3):
    """ASHA driving COMPILED DEVICE training programs (round 5): each
    evaluation is one jitted TinyLM train run of ``budget`` SGD steps;
    the async workers overlap host scheduling + result fetches with the
    device queue.  Returns (asha_seconds, sync_seconds, asha_best,
    sync_best) at EQUAL jobs -- the sync ladder evaluates the same
    number of programs serially, paying one dispatch+fetch round-trip
    per evaluation with an idle device in between.
    """
    try:
        from hyperopt_tpu.hyperband import asha, successive_halving
        from hyperopt_tpu.models import transformer

        fn = transformer.budget_objective()
        space = transformer.hpo_space()
        # warm every rung budget once: compiles out of the timing
        for b in (1, 3, 9, 27):
            if b <= max_budget:
                fn({"lr": 0.1, "wd": 1e-4}, b)

        t0 = time.perf_counter()
        out_a = asha(
            fn, space, max_budget=max_budget, eta=eta, max_jobs=max_jobs,
            workers=workers, rstate=np.random.default_rng(0),
        )
        asha_s = time.perf_counter() - t0

        # the sync ladder at the same total evaluation count: one
        # n_configs=27, eta=3 bracket is 27+9+3+1 = 40 evals = max_jobs
        t0 = time.perf_counter()
        out_s = successive_halving(
            fn, space, max_budget=max_budget, min_budget=1, eta=eta,
            n_configs=27, rstate=np.random.default_rng(0),
        )
        sync_s = time.perf_counter() - t0
        return asha_s, sync_s, out_a["best_loss"], out_s["best_loss"]
    except Exception:  # secondary metric must never sink the headline
        import traceback

        print("bench_asha_device failed:", file=sys.stderr)
        traceback.print_exc()
        return None, None, None, None


# THE BASELINE.md PBT study config (32 members x 200 steps, exploit/
# explore every 10): the single source for both the executed run and the
# JSON comparability stamp, so the stamp can never drift from what ran
PBT_STUDY_CONFIG = {"pop": 32, "exploit_every": 10, "n_rounds": 20}


def bench_pbt(pop=None, exploit_every=None, n_rounds=None):
    """Secondary metric: Population-Based Training member-steps/s on the
    transformer family (the during-training scheduler the reference's
    independent-trial model cannot express -- BASELINE.md round 3).

    Defaults ARE ``PBT_STUDY_CONFIG`` (the BASELINE.md study config), so
    the JSON quality field is directly comparable to the study's
    0.103-0.115 population-median envelope.
    Returns (member_steps_per_sec, final_population_median_loss)."""
    pop = PBT_STUDY_CONFIG["pop"] if pop is None else pop
    exploit_every = (
        PBT_STUDY_CONFIG["exploit_every"]
        if exploit_every is None else exploit_every
    )
    n_rounds = PBT_STUDY_CONFIG["n_rounds"] if n_rounds is None else n_rounds
    try:
        import jax
        import jax.numpy as jnp

        from hyperopt_tpu.models import transformer
        from hyperopt_tpu.pbt import compile_pbt

        model = transformer.TinyLM(vocab=32, d_model=32, n_heads=2,
                                   n_layers=2, max_len=32)
        params = transformer.init_population(
            model, pop, jax.random.key(0), seq_len=32
        )
        momentum = jax.tree.map(jnp.zeros_like, params)
        train_fn = transformer.make_pbt_train_fn(
            model, batch_size=32, seq_len=32, vocab=32
        )
        runner = compile_pbt(
            train_fn, (params, momentum),
            {"lr": (1e-4, 1.0), "wd": (1e-7, 1e-2)},
            pop_size=pop, exploit_every=exploit_every, n_rounds=n_rounds,
        )
        runner(seed=99)  # compile
        t0 = time.perf_counter()
        out = runner(seed=0)
        dt = time.perf_counter() - t0
        rate = pop * exploit_every * n_rounds / dt
        # nanmedian: a member perturbed into divergence in the last
        # window must not turn the JSON field into bare NaN
        return rate, float(np.nanmedian(out["loss_history"][-1]))
    except Exception:  # secondary metric must never sink the headline
        import traceback

        print("bench_pbt failed:", file=sys.stderr)
        traceback.print_exc()
        return None, None


def bench_best_at_1k(n_trials=1000, seed=7, speculative=0):
    """BASELINE.json's second headline metric: wall-clock to best-loss @
    1k trials on the 20-dim mixed space -- a realistic suggest->evaluate
    fmin loop (``algo=tpe_jax.suggest``, per-trial sequential asks, the
    path a migrating hyperopt user runs first).  ``speculative=k``
    measures the same loop with k-ahead speculative dispatch.

    Returns (seconds, best_loss, n_trials).
    """
    from functools import partial

    import numpy as np

    from hyperopt_tpu import fmin
    from hyperopt_tpu import tpe_jax
    from hyperopt_tpu.jax_trials import JaxTrials
    from hyperopt_tpu.models.synthetic import mixed_space, mixed_space_fn

    algo = (
        partial(tpe_jax.suggest, speculative=speculative)
        if speculative
        else tpe_jax.suggest
    )
    trials = JaxTrials()
    t0 = time.perf_counter()
    fmin(
        mixed_space_fn,
        mixed_space(),
        algo=algo,
        max_evals=n_trials,
        trials=trials,
        rstate=np.random.default_rng(seed),
        show_progressbar=False,
        return_argmin=False,
    )
    dt = time.perf_counter() - t0
    return dt, float(min(trials.losses())), n_trials


def bench_fmin_client(n_trials=1000, seed=7, ask_ahead=4):
    """The round-20 sequential headline: the SAME 1k-trial experiment
    as ``bench_best_at_1k``, with ``fmin`` routed through the serve
    engine (``fmin(ask_ahead=k)`` -- graftclient).  The suggestion
    stream is BITWISE the solo driver's at any depth (submit-time
    seeds + the fresh_window gate), so ``best_loss_at_1k_client``
    equals ``best_loss_at_1k`` by construction; the wall-clock is the
    number that moves -- the engine's resident stacked state replaces
    the per-ask history re-upload, the depth-k window keeps the
    pipeline primed, and the client loop sheds the algo-seam's
    per-trial full-store scans.

    Returns (seconds, best_loss, asks_per_sec).
    """
    import numpy as np

    from hyperopt_tpu import fmin
    from hyperopt_tpu.jax_trials import JaxTrials
    from hyperopt_tpu.models.synthetic import mixed_space, mixed_space_fn

    trials = JaxTrials()
    t0 = time.perf_counter()
    fmin(
        mixed_space_fn,
        mixed_space(),
        max_evals=n_trials,
        trials=trials,
        rstate=np.random.default_rng(seed),
        show_progressbar=False,
        return_argmin=False,
        ask_ahead=ask_ahead,
    )
    dt = time.perf_counter() - t0
    return dt, float(min(trials.losses())), n_trials / dt


def bench_burst(space, n_clients=64, n_studies=4, asks_per_client=8,
                n_cand=128, pool_width=32):
    """The round-22 graftburst concurrency headline: N concurrent
    clients speak the negotiated binary frame protocol to ONE served
    engine over TCP, each pipelining a window of asks and telling the
    results back.  Three rows come out of the single timed scenario:

    ``fleet_asks_per_sec_concurrent``
        aggregate served asks/sec across all clients -- the CI-sized
        twin of the 10^3-client soak (BENCH_BURST_CLIENTS sizes it up
        on an accelerator host);
    ``wal_fsyncs_per_tell``
        durability amortization under load: group commit issues one
        barrier per WAL per round instead of one fsync per tell, so
        the ratio collapses toward studies/asks-per-round
        (acceptance < 0.2) while the durability point is unchanged;
    ``client_cobatch_occupancy``
        mean filled-slot fraction of the engine's vmapped rounds while
        the clients co-ride -- the co-batching payoff made visible.
    """
    import concurrent.futures
    import shutil
    import socket
    import tempfile
    import threading

    from hyperopt_tpu.serve import SuggestService
    from hyperopt_tpu.serve.frames import FrameConn
    from hyperopt_tpu.serve.service import serve_forever

    root = tempfile.mkdtemp(prefix="bench_burst_")
    svc = SuggestService(
        space, root=root, background=True, max_batch=64,
        n_startup_jobs=3, n_cand=n_cand, snapshot_cadence=1000,
        max_queue=4096, study_queue_cap=64,
    )
    srv = serve_forever(svc, port=0)
    threading.Thread(target=srv.serve_forever, daemon=True).start()
    addr = srv.server_address[:2]
    names = [f"b{i}" for i in range(n_studies)]
    for i, name in enumerate(names):
        svc.create_study(name, seed=i)

    def one_client(i):
        name = names[i % n_studies]
        sock = socket.create_connection(addr, timeout=60)
        served = 0
        try:
            conn = FrameConn(sock.makefile("rwb"))
            futs = [
                conn.submit({"op": "ask", "study": name, "timeout": 45})
                for _ in range(asks_per_client)
            ]
            replies = [conn.drain(f) for f in futs]
            tells = [
                conn.submit({
                    "op": "tell", "study": name, "tid": r["tid"],
                    "loss": 0.1 + (r["tid"] % 97) / 100.0,
                })
                for r in replies if r.get("ok")
            ]
            for f in tells:
                if conn.drain(f).get("ok"):
                    served += 1
            conn.close()
        finally:
            sock.close()
        return served

    t0 = time.perf_counter()
    with concurrent.futures.ThreadPoolExecutor(pool_width) as pool:
        served = sum(pool.map(one_client, range(n_clients)))
    dt = time.perf_counter() - t0
    c = svc.counters
    occ = [float(x) for x in svc.scheduler.occupancy]
    srv.shutdown()
    srv.server_close()
    svc.shutdown()
    shutil.rmtree(root, ignore_errors=True)
    tells = max(c.get("wal_tells", 0), 1)
    return {
        "fleet_asks_per_sec_concurrent": round(served / dt, 1),
        "wal_fsyncs_per_tell": round(c.get("wal_fsyncs", 0) / tells, 4),
        "client_cobatch_occupancy": (
            round(float(np.mean(occ)), 4) if occ else None
        ),
        "burst_config": {
            "n_clients": n_clients, "n_studies": n_studies,
            "asks_per_client": asks_per_client,
            "pool_width": pool_width,
        },
    }


def bench_storm(space, n_replicas=3, n_studies=4, rounds=6, n_cand=128):
    """The round-23 graftstorm rows: the fleet under a HOSTILE network
    -- a seeded 10%-reset + latency + truncate storm on the client
    wire and a mid-run black-hole partition of one backend -- measured
    over real sockets through the TCP router.  Three rows:

    ``fleet_asks_per_sec_under_storm``
        aggregate ask+tell throughput with the storm armed -- resets,
        torn frames, a failover, and a heal all inside the timed
        window;
    ``net_fault_recovery_ms``
        mean wall-clock of the ops that needed at least one transport
        retry (reconnect + resubmission + any failover adoption) --
        the price of a fault, not the price of the round;
    ``net_typed_error_rate``
        injected transport faults absorbed per client op.  Every one
        of them surfaced typed and was retried; a raw exception
        anywhere fails the bench.
    """
    import shutil
    import tempfile
    import threading

    from hyperopt_tpu.client import RemoteStudy
    from hyperopt_tpu.distributed.faults import NetFaultPlan
    from hyperopt_tpu.serve import SuggestService
    from hyperopt_tpu.serve.router import RouterServer, _Backend
    from hyperopt_tpu.serve.service import serve_forever

    root = tempfile.mkdtemp(prefix="bench_storm_")
    services, servers, backends = [], [], []
    for i in range(n_replicas):
        svc = SuggestService(
            space, root=root, owner=f"r{i}", background=True,
            max_batch=16, n_startup_jobs=3, n_cand=n_cand,
            snapshot_cadence=1000,
        )
        srv = serve_forever(svc, port=0)
        threading.Thread(target=srv.serve_forever, daemon=True).start()
        host, port = srv.server_address[:2]
        services.append(svc)
        servers.append(srv)
        backends.append(_Backend(f"r{i}", host, port))
    # the rate storm lives on the client wire; the router-side plan
    # carries only the black-hole partition (a rate storm on backend
    # dials would read as backend death to the failover detector)
    router_plan = NetFaultPlan(seed=230)
    router = RouterServer(
        backends, salt="bench-storm", read_timeout=5.0,
        net_plan=router_plan,
    )
    rsrv = router.serve_forever(port=0)
    threading.Thread(target=rsrv.serve_forever, daemon=True).start()
    rhost, rport = rsrv.server_address[:2]

    plan = NetFaultPlan(
        seed=23, reset_rate=0.10, latency=0.001, truncate_rate=0.05,
        burst=2,
    )
    names = [f"n{i}" for i in range(n_studies)]
    clients = {
        n: RemoteStudy(
            rhost, rport, n, seed=i, net_plan=plan,
            key=f"client/{n}", read_timeout=5.0,
        )
        for i, n in enumerate(names)
    }
    victim = router.ring.owner(names[0])
    pairs = 0
    recovery = []
    t0 = time.perf_counter()
    for r in range(rounds):
        if r == rounds // 2:
            router_plan.partition(victim)  # black-hole one backend
        for n in names:
            c = clients[n]
            before = c.stats.get("transport_errors", 0)
            t_op = time.perf_counter()
            tid, vals = c.ask(timeout=45)
            c.tell(tid, 0.1 + (tid % 97) / 100.0, vals)
            if c.stats.get("transport_errors", 0) > before:
                recovery.append(time.perf_counter() - t_op)
            pairs += 1
        if r == rounds // 2:
            router_plan.heal(victim)  # partition lifts; probe rejoins
            router.probe_backends()
    dt = time.perf_counter() - t0
    faults = sum(
        c.stats.get("transport_errors", 0) for c in clients.values()
    )
    for c in clients.values():
        c.close()
    rsrv.shutdown()
    rsrv.server_close()
    for srv in servers:
        srv.shutdown()
        srv.server_close()
    for svc in services:
        svc.shutdown()
    shutil.rmtree(root, ignore_errors=True)
    ops = pairs * 2  # one ask + one tell per pair
    return {
        "fleet_asks_per_sec_under_storm": round(pairs / dt, 1),
        "net_fault_recovery_ms": (
            round(1000.0 * sum(recovery) / len(recovery), 2)
            if recovery else 0.0
        ),
        "net_typed_error_rate": round(faults / ops, 4),
        "storm_config": {
            "n_replicas": n_replicas, "n_studies": n_studies,
            "rounds": rounds, "reset_rate": 0.10,
            "truncate_rate": 0.05, "faulted_ops": len(recovery),
        },
    }


def bench_best_at_1k_device_loop(n_trials=1000, n_cand=128, seed=7,
                                 batch_size=32):
    """The same 1k-trial experiment as ONE on-device program
    (``device_loop.compile_fmin``): suggest + evaluate + history append
    fused under a ``lax.scan``.  Compile time excluded (the program is
    reusable across seeds); returns (seconds, best_loss, n_actually_run --
    compile_fmin rounds max_evals up to a batch multiple).

    ``batch_size=1`` is the SEQUENTIAL on-device mode (round-3 study,
    BASELINE.md): one posterior update per trial, matching the host-
    driven loop's quality (~0.22-0.23 median best on the 20-dim space)
    at on-device wall-clock (~1.5 s vs ~240 s host-driven over the
    tunnel).  Population mode (batch_size>1) trades posterior updates
    for throughput.  Candidate counts match the host path's per-family
    defaults (cont ``n_cand`` / cat 24)."""
    try:
        from hyperopt_tpu.device_loop import compile_fmin
        from hyperopt_tpu.models.synthetic import mixed_space, mixed_space_fn_jax

        runner = compile_fmin(
            mixed_space_fn_jax, mixed_space(), max_evals=n_trials,
            batch_size=batch_size, n_EI_candidates=n_cand,
            n_EI_candidates_cat=24,
        )
        runner(seed=seed + 1)  # compile
        t0 = time.perf_counter()
        out = runner(seed=seed)
        dt = time.perf_counter() - t0
        return dt, float(out["best_loss"]), int(out["n_evals"])
    except Exception:  # secondary metric must never sink the headline
        import traceback

        print("bench_best_at_1k_device_loop failed:", file=sys.stderr)
        traceback.print_exc()
        return None, None, 0


def bench_compiled_at_1k(n_trials=1000, n_cand=128, seed=7):
    """The RTT-floor headline: the SAME 1k-trial experiment as
    ``bench_best_at_1k`` routed through ``fmin(compiled=True)`` -- the
    whole ask-evaluate-tell loop as one device program, returning a
    standard Trials store.  Sequential on-device mode (batch_size=1,
    one posterior update per trial -- host-path quality).  The program
    is compiled once and reused (a warm fmin call pays zero compile,
    like a seed sweep); the timed call includes the Trials rebuild, so
    the number is the full fmin-contract wall-clock.

    Returns (seconds, best_loss)."""
    try:
        import numpy as np

        from hyperopt_tpu import Trials, fmin
        from hyperopt_tpu.device_loop import compile_fmin
        from hyperopt_tpu.models.synthetic import (
            mixed_space,
            mixed_space_fn_jax,
        )

        runner = compile_fmin(
            mixed_space_fn_jax, mixed_space(), max_evals=n_trials,
            batch_size=1, n_EI_candidates=n_cand, n_EI_candidates_cat=24,
        )
        runner(seed=seed)  # compile (reused by every fmin call below)
        trials = Trials()
        t0 = time.perf_counter()
        fmin(
            mixed_space_fn_jax, mixed_space(), compiled=True,
            max_evals=n_trials, trials=trials, return_argmin=False,
            rstate=np.random.default_rng(seed),
            compiled_options={"runner": runner, "seed": seed},
        )
        dt = time.perf_counter() - t0
        return dt, float(min(trials.losses()))
    except Exception:  # secondary metric must never sink the headline
        import traceback

        print("bench_compiled_at_1k failed:", file=sys.stderr)
        traceback.print_exc()
        return None, None


def bench_mlp_tune(n_evals=512, batch=32, n_epochs=8):
    """End-to-end HPO *over actual training*: each trial initializes
    and trains its own MLP (SGD+momentum, per-trial params/opt-state
    carried through an inner fori_loop) INSIDE the experiment scan --
    the ``TrainableObjective`` seam, a real vmapped training loop, not
    a closed-form objective.  Returns trials/sec end-to-end."""
    try:
        from hyperopt_tpu.device_loop import compile_fmin
        from hyperopt_tpu.models.synthetic import (
            mlp_tune_objective,
            mlp_tune_space,
        )

        runner = compile_fmin(
            mlp_tune_objective(n_epochs=n_epochs),
            mlp_tune_space(), max_evals=n_evals, batch_size=batch,
        )
        runner(seed=0)  # compile
        t0 = time.perf_counter()
        out = runner(seed=1)
        dt = time.perf_counter() - t0
        return out["n_evals"] / dt
    except Exception:  # secondary metric must never sink the headline
        import traceback

        print("bench_mlp_tune failed:", file=sys.stderr)
        traceback.print_exc()
        return None


def bench_compiled_asha(n_evals_flat=128, n_evals_asha=256, batch=16,
                        eta=2, rung_epochs=1, n_rungs=3):
    """graftrung time-to-quality: the fused-ASHA compiled sweep vs the
    flat compiled sweep on mlp-tune, same backend.  Flat trains every
    config to full fidelity (the asha ladder's survivor budget of
    ``rung_epochs * (eta**n_rungs - 1) / (eta - 1)`` epochs); asha
    spends the lane-epochs early stopping saves on ~2x more configs and
    is timed to the moment it reaches the flat sweep's final best loss
    (progress rows give per-bracket host timestamps).  Returns a dict
    of stamped rows, or None on failure."""
    try:
        from hyperopt_tpu.device_loop import compile_fmin
        from hyperopt_tpu.models.synthetic import (
            mlp_tune_objective,
            mlp_tune_space,
        )

        total_ep = rung_epochs * (eta ** n_rungs - 1) // (eta - 1)
        chunk = batch  # one bracket per chunk: progress-row resolution

        def build(n_evals, rows, **kw):
            return compile_fmin(
                mlp_tune_objective(n_epochs=total_ep),
                mlp_tune_space(), max_evals=n_evals, batch_size=batch,
                chunk_size=chunk, progress_every=1,
                progress_callback=lambda row: rows.append(
                    (time.perf_counter(), row["best_loss"])
                ),
                **kw,
            )

        rows_flat, rows_asha = [], []
        flat = build(n_evals_flat, rows_flat)
        asha = build(
            n_evals_asha, rows_asha,
            asha={"eta": eta, "rung_epochs": rung_epochs,
                  "n_rungs": n_rungs},
        )
        flat(seed=0)
        asha(seed=0)  # compile both before timing
        rows_flat.clear()
        t0f = time.perf_counter()
        out_f = flat(seed=1)
        t_flat_total = time.perf_counter() - t0f
        rows_asha.clear()
        t0a = time.perf_counter()
        out_a = asha(seed=1)
        t_asha_total = time.perf_counter() - t0a

        # the quality target is the flat sweep's final best -- unless
        # asha's full-fidelity best never reached it, in which case the
        # easier of the two finals keeps both times defined and the
        # ratio honest (and the reached_flat_best row says which)
        q = max(out_f["best_loss"], out_a["best_loss"])

        def first_at(rows, t0):
            for t, b in rows:
                if b <= q:
                    return t - t0
            return None

        t_f = first_at(rows_flat, t0f)
        t_a = first_at(rows_asha, t0a)
        return {
            "speedup_x": (t_f / t_a) if t_f and t_a else None,
            "flat_seconds_to_quality": t_f,
            "asha_seconds_to_quality": t_a,
            "flat_seconds_total": t_flat_total,
            "asha_seconds_total": t_asha_total,
            "flat_best_loss": out_f["best_loss"],
            "asha_best_loss": out_a["best_loss"],
            "quality_target": q,
            "asha_reached_flat_best": bool(
                out_a["best_loss"] <= out_f["best_loss"]
            ),
        }
    except Exception:  # secondary metric must never sink the headline
        import traceback

        print("bench_compiled_asha failed:", file=sys.stderr)
        traceback.print_exc()
        return None


def bench_callback_overhead(n_evals=512, batch=32, n_chunks=8):
    """What the io_callback observability seam costs: the chunked
    device loop timed with the progress callback streaming a row EVERY
    chunk vs the identical chunked program with no callback.  Stamped
    as a fraction of the no-callback wall-clock (>= 0; the result
    streams are bitwise identical either way, so this is pure
    observability overhead)."""
    try:
        import jax.numpy as jnp

        from hyperopt_tpu import hp
        from hyperopt_tpu.device_loop import compile_fmin

        space = {
            "x": hp.uniform("x", -5.0, 5.0),
            "y": hp.loguniform("y", -7.0, 2.3),
        }

        def obj(cfg):
            return (cfg["x"] - 1.0) ** 2 + (jnp.log(cfg["y"]) + 2.3) ** 2

        chunk = max(batch, n_evals // n_chunks)
        rows = []
        plain = compile_fmin(
            obj, space, max_evals=n_evals, batch_size=batch,
            chunk_size=chunk,
        )
        with_cb = compile_fmin(
            obj, space, max_evals=n_evals, batch_size=batch,
            chunk_size=chunk, progress_callback=rows.append,
            progress_every=1,
        )
        plain(seed=0)  # compile
        with_cb(seed=0)  # compile
        t0 = time.perf_counter()
        plain(seed=1)
        t_plain = time.perf_counter() - t0
        rows.clear()
        t0 = time.perf_counter()
        with_cb(seed=1)
        t_cb = time.perf_counter() - t0
        assert rows, "progress callback never fired"
        return max(0.0, (t_cb - t_plain) / t_plain)
    except Exception:  # secondary metric must never sink the headline
        import traceback

        print("bench_callback_overhead failed:", file=sys.stderr)
        traceback.print_exc()
        return None


def main():
    from hyperopt_tpu.models.synthetic import mixed_space

    import jax

    # persistent XLA compilation cache (VERDICT r4 weak #4: shipped but
    # wired nowhere): on by default -- compiles dominate cold wall-clock
    # for every program family here -- opt out with
    # BENCH_COMPILATION_CACHE=0; the JSON stamps what ran
    cache_dir = None
    if os.environ.get("BENCH_COMPILATION_CACHE", "1") != "0":
        from hyperopt_tpu.utils import enable_compilation_cache

        cache_dir = enable_compilation_cache()

    # headline batch on an accelerator; CPU-only runs get a size that
    # finishes in minutes (the program is deliberately TPU-sized)
    on_accel = jax.devices()[0].platform != "cpu"
    default_batch = "4096" if on_accel else "64"
    batch = int(os.environ.get("BENCH_BATCH", default_batch))
    n_cand = int(os.environ.get("BENCH_N_CAND", "128"))
    n_obs = int(os.environ.get("BENCH_N_OBS", "500"))
    n_trials_1k = int(
        os.environ.get("BENCH_N_TRIALS", "1000" if on_accel else "60")
    )

    space = mixed_space()  # 20-dim mixed continuous/categorical
    domain, trials = build_history(n_obs, space)

    numpy_rate = bench_host_tpe(domain, trials, native=False)
    native_rate = bench_host_tpe(domain, trials, native=True)

    platform = jax.devices()[0].platform
    jax_rate, _ = bench_jax_tpe(domain, trials, batch=batch, n_cand=n_cand)
    # obs-scaling sweep (VERDICT r5 item 2): 500 / 2.5k / 10k obs,
    # compacted vs full-width, env-overridable for CI smoke sizing
    obs_sweep_sizes = [
        int(s) for s in os.environ.get(
            "BENCH_OBS_SWEEP", "500,2500,10000"
        ).split(",") if s.strip()
    ]
    obs_scaling = bench_obs_scaling(space, batch, n_cand, obs_sweep_sizes)
    from hyperopt_tpu.ops.kernels import DEFAULT_ABOVE_CAP as above_cap_default
    latency_rate = bench_jax_latency(
        domain, trials, n_cand=n_cand
    )
    fused_sync_rate = bench_fused_latency(domain, trials, n_cand=n_cand)
    spec_rate = bench_spec_latency(domain, trials, n_cand=n_cand)
    # round-7 traffic/dispatch contract rows: counted deterministically,
    # so they are comparable across platforms and rounds (no timing)
    transfer_rows = bench_transfer_per_ask(space, obs_sweep_sizes)
    dispatches_per_trial = bench_fused_dispatches(
        n_trials=min(120, n_trials_1k)
    )
    resume_overhead, resume_wal_tells = bench_resume_overhead(
        n_trials=min(60, n_trials_1k)
    )
    assert resume_wal_tells == min(60, n_trials_1k)
    # round-12 multi-tenant service rows: studies/sec served out of one
    # slotted batch, ask-latency percentiles, occupancy, and the
    # continuous-batching speedup over the one-tenant sequential rate
    serve_rows = bench_serve(
        space,
        n_studies=int(os.environ.get("BENCH_SERVE_STUDIES", "64")),
        rounds=int(os.environ.get("BENCH_SERVE_ROUNDS", "6")),
        n_cand=n_cand,
    )
    # round-13 graftguard rows: overload shedding, poisoned-tenant
    # quarantine, and watchdog recovery on deterministic scenarios
    guard_rows = bench_guard(space, n_cand=n_cand)
    # round-19 graftscope rows: the cost of observability, measured --
    # armed-at-full-cadence overhead on the serve and fused regimes,
    # span throughput, and one fleet-wide scrape through a live router
    obs_rows = bench_obs(space, n_cand=n_cand)
    # round-18 graftfleet rows: the horizontal fleet -- aggregate
    # throughput through the router, p99 ask latency across a
    # replica-kill window, and failover recovery time
    fleet_rows = bench_fleet(
        space,
        n_replicas=int(os.environ.get("BENCH_FLEET_REPLICAS", "3")),
        n_cand=n_cand,
    )
    # round-21 graftpilot rows: the self-driving fleet -- actuation
    # latencies of pilot-driven scale-out/scale-in, throughput under
    # the control loop, and the record-once-replay-bitwise fidelity
    pilot_rows = bench_pilot(
        space,
        n_studies=int(os.environ.get("BENCH_PILOT_STUDIES", "12")),
        n_cand=n_cand,
    )
    # round-22 graftburst rows: N concurrent binary-frame clients on
    # one served engine -- aggregate asks/sec, the group-commit fsync
    # amortization ratio, and co-batched round occupancy
    burst_rows = bench_burst(
        space,
        # 10^4 concurrent on accelerators (ROADMAP item 1's sustained-
        # fleet scale; CPU rounds keep a size that finishes in minutes)
        n_clients=int(os.environ.get(
            "BENCH_BURST_CLIENTS", "10000" if on_accel else "64"
        )),
        n_studies=int(os.environ.get("BENCH_BURST_STUDIES", "4")),
        asks_per_client=int(os.environ.get("BENCH_BURST_ASKS", "8")),
        n_cand=n_cand,
    )
    # round-23 graftstorm rows: the routed fleet under a seeded
    # reset+truncate+latency storm with a mid-run partition+heal --
    # throughput with faults armed, the wall-clock price of a faulted
    # op, and the injected-fault absorption rate
    storm_rows = bench_storm(
        space,
        n_replicas=int(os.environ.get("BENCH_STORM_REPLICAS", "3")),
        n_studies=int(os.environ.get("BENCH_STORM_STUDIES", "4")),
        rounds=int(os.environ.get("BENCH_STORM_ROUNDS", "6")),
        n_cand=n_cand,
    )
    # round-17 graftmesh rows: the study-sharded serve engine and the
    # shard_map PBT schedule per mesh shape (virtual CPU devices here;
    # the MULTICHIP dryrun runs the same programs on real meshes)
    mesh_devices = tuple(
        int(s) for s in os.environ.get(
            "BENCH_MESH_DEVICES", "1,2,4"
        ).split(",") if s.strip()
    )
    serve_mesh_rates, serve_mesh_eff = bench_serve_mesh(
        space, mesh_devices=mesh_devices,
        n_studies=int(os.environ.get("BENCH_SERVE_STUDIES", "64")),
        rounds=int(os.environ.get("BENCH_SERVE_ROUNDS", "6")),
        n_cand=n_cand,
    )
    pbt_mesh_rates, pbt_mesh_eff = bench_pbt_mesh(
        mesh_devices=mesh_devices
    )
    # round-14: the device-loop family is stamped on EVERY backend --
    # CPU rounds get CPU-sized configs, keyed by backend in the JSON so
    # the per-backend trajectory stays comparable (the old CPU skip
    # left device_loop_* unstamped on every CPU round)
    dl_evals, dl_batch = (8192, 128) if on_accel else (1024, 32)
    device_loop_config = {
        "backend": platform, "n_evals": dl_evals, "batch": dl_batch,
    }
    loop_rate = bench_device_loop(n_evals=dl_evals, batch=dl_batch)

    sec_1k, best_1k, _ = bench_best_at_1k(n_trials=n_trials_1k)
    spec_sec_1k, spec_best_1k, _ = bench_best_at_1k(
        n_trials=n_trials_1k, speculative=8
    )
    # round-20 graftclient rows: the same experiment with fmin routed
    # through the serve engine (bitwise stream, so the quality row is
    # an invariant check and the wall-clock row is the story)
    ask_ahead_depth = int(os.environ.get("BENCH_ASK_AHEAD", "4"))
    client_sec_1k, client_best_1k, client_asks_per_sec = (
        bench_fmin_client(n_trials=n_trials_1k, ask_ahead=ask_ahead_depth)
    )
    dl_sec_1k, dl_best_1k, dl_n = bench_best_at_1k_device_loop(
        n_trials=n_trials_1k, n_cand=n_cand
    )
    # sequential on-device mode: one posterior update per trial --
    # host-path quality at on-device wall-clock (round-3 study)
    dls_sec_1k, dls_best_1k, dls_n = bench_best_at_1k_device_loop(
        n_trials=n_trials_1k, n_cand=n_cand, batch_size=1
    )
    # round-14 compiled-objective rows: the RTT-floor close-out --
    # fmin(compiled=True) wall-clock on the SAME experiment as the host
    # sequential headline, HPO over a real vmapped training loop, and
    # the cost of the io_callback observability seam
    comp_sec_1k, comp_best_1k = bench_compiled_at_1k(
        n_trials=n_trials_1k, n_cand=n_cand
    )
    mlp_evals, mlp_batch = (2048, 64) if on_accel else (128, 16)
    mlp_rate = bench_mlp_tune(n_evals=mlp_evals, batch=mlp_batch)
    # round-24 graftrung rows: fused-ASHA time-to-quality vs the flat
    # compiled sweep (same backend, same objective family)
    ca_flat, ca_asha, ca_batch = (
        (2048, 4096, 64) if on_accel else (128, 256, 16)
    )
    ca_flat = int(os.environ.get("BENCH_ASHA_FLAT", ca_flat))
    ca_asha = int(os.environ.get("BENCH_ASHA_EVALS", ca_asha))
    ca_batch = int(os.environ.get("BENCH_ASHA_BATCH", ca_batch))
    compiled_asha = bench_compiled_asha(
        n_evals_flat=ca_flat, n_evals_asha=ca_asha, batch=ca_batch
    )
    cb_evals, cb_batch = (4096, 128) if on_accel else (256, 16)
    cb_frac = bench_callback_overhead(n_evals=cb_evals, batch=cb_batch)
    if platform != "cpu":
        pbt_rate, pbt_median = bench_pbt()
        asha_s, sha_sync_s, asha_best, sha_sync_best = bench_asha_device()
    else:
        pbt_rate, pbt_median = None, None
        asha_s, sha_sync_s, asha_best, sha_sync_best = (None,) * 4
    # comparability contract: the stamped config IS the dict bench_pbt
    # defaulted from, so the JSON cannot misreport what ran
    pbt_config = dict(
        PBT_STUDY_CONFIG,
        total_steps=(
            PBT_STUDY_CONFIG["exploit_every"] * PBT_STUDY_CONFIG["n_rounds"]
        ),
    )
    rtt_ms = bench_rtt()
    lint_findings_total, lint_baseline_size = bench_lint()
    ir_programs_checked, ir_contract_drift = bench_ir()
    (trace_findings_total, trace_rules_checked,
     lockdep_inversions_observed) = bench_trace()
    (wire_ops_checked, wire_contract_drift,
     crash_points_armed_frac) = bench_wire()

    print(
        json.dumps(
            {
                "metric": "tpe_suggestions_per_sec_20dim_mixed",
                "value": round(jax_rate, 1),
                "unit": "suggestions/s",
                "vs_baseline": round(jax_rate / numpy_rate, 2),
                "baseline_numpy_tpe_per_sec": round(numpy_rate, 1),
                "host_native_tpe_per_sec": (
                    round(native_rate, 1) if native_rate else None
                ),
                "single_suggest_per_sec": round(latency_rate, 1),
                # single_suggest_sync_per_sec RETIRED (round 20): the
                # solo sync dispatch regime it measured no longer
                # exists -- fmin rides the serve engine; see
                # fmin_client_asks_per_sec
                "single_suggest_fused_sync_per_sec": round(
                    fused_sync_rate, 1
                ),
                "speculative_suggest_per_sec": round(spec_rate, 1),
                "host_to_device_bytes_per_ask": transfer_rows,
                "dispatches_per_trial": round(dispatches_per_trial, 3),
                # round-10 crash-recovery contract rows: durability cost
                # per trial (WAL append + amortized bundle publish), and
                # the same as a fraction of the fused per-trial dispatch
                # time (acceptance bound: < 0.10)
                "resume_overhead_per_trial": round(resume_overhead, 6),
                "resume_overhead_frac_of_fused": round(
                    resume_overhead * fused_sync_rate, 4
                ),
                # round-12 serve rows (bench_serve): study-batched
                # fused tell+ask with continuous batching
                **serve_rows,
                # round-13 graftguard rows (bench_guard): runtime
                # protection -- shed rate, quarantine trips, watchdog
                # recovery latency
                **guard_rows,
                # round-18 graftfleet rows (bench_fleet): sharded
                # replicas behind the consistent-hash router --
                # aggregate studies/sec, failover-window p99, recovery
                **fleet_rows,
                **pilot_rows,
                # round-22 graftburst rows (bench_burst): concurrent
                # binary-frame clients on one engine -- aggregate
                # throughput, wal_fsyncs_per_tell (< 0.2 acceptance),
                # co-batch occupancy
                **burst_rows,
                # round-23 graftstorm rows (bench_storm): the fleet
                # under a hostile network -- throughput with the storm
                # armed, mean recovery wall-clock of faulted ops, and
                # typed transport faults absorbed per op
                **storm_rows,
                # round-19 graftscope rows (bench_obs): tracing-armed
                # overhead fractions, span throughput, and the
                # fleet-wide /metrics scrape latency
                **obs_rows,
                # round-17 graftmesh rows: per-mesh-shape throughput
                # of the study-sharded serve engine and the shard_map
                # PBT schedule, plus the near-linear-scaling
                # diagnostic rate_N / (N * rate_1) per family
                "serve_studies_per_sec_mesh": serve_mesh_rates,
                "pbt_member_steps_per_sec_mesh": pbt_mesh_rates,
                "mesh_scaling_efficiency": {
                    "serve": serve_mesh_eff,
                    "pbt": pbt_mesh_eff,
                },
                "device_loop_trials_per_sec": (
                    round(loop_rate, 1) if loop_rate else None
                ),
                # round-14: the device-loop family is stamped every
                # round; this keys the numbers by backend + config so
                # CPU and accelerator trajectories never get compared
                # against each other
                "device_loop_config": device_loop_config,
                # round-14 compiled-objective rows (fmin(compiled=True)
                # / TrainableObjective / io_callback cadence)
                "seconds_to_best_at_1k_compiled": (
                    round(comp_sec_1k, 3) if comp_sec_1k is not None
                    else None
                ),
                "best_loss_at_1k_compiled": (
                    round(comp_best_1k, 5) if comp_best_1k is not None
                    else None
                ),
                "compiled_vs_host_speedup_x": (
                    round(sec_1k / comp_sec_1k, 1)
                    if comp_sec_1k else None
                ),
                "mlp_tune_trials_per_sec": (
                    round(mlp_rate, 1) if mlp_rate else None
                ),
                "mlp_tune_config": {
                    "backend": platform, "n_evals": mlp_evals,
                    "batch": mlp_batch,
                },
                "device_loop_callback_overhead_frac": (
                    round(cb_frac, 4) if cb_frac is not None else None
                ),
                # round-24 graftrung rows (compile_fmin(asha=)): fused
                # rung-based early stopping vs the flat compiled sweep,
                # keyed by backend+config like every device-loop row
                "compiled_asha_vs_flat_speedup_x": (
                    round(compiled_asha["speedup_x"], 2)
                    if compiled_asha and compiled_asha["speedup_x"]
                    else None
                ),
                "compiled_asha_seconds_to_quality": (
                    round(compiled_asha["asha_seconds_to_quality"], 3)
                    if compiled_asha
                    and compiled_asha["asha_seconds_to_quality"]
                    is not None else None
                ),
                "compiled_flat_seconds_to_quality": (
                    round(compiled_asha["flat_seconds_to_quality"], 3)
                    if compiled_asha
                    and compiled_asha["flat_seconds_to_quality"]
                    is not None else None
                ),
                "compiled_asha_best_loss": (
                    round(compiled_asha["asha_best_loss"], 5)
                    if compiled_asha else None
                ),
                "compiled_asha_reached_flat_best": (
                    compiled_asha["asha_reached_flat_best"]
                    if compiled_asha else None
                ),
                "compiled_asha_config": {
                    "backend": platform, "n_evals_flat": ca_flat,
                    "n_evals_asha": ca_asha, "batch": ca_batch,
                    "eta": 2, "rung_epochs": 1, "n_rungs": 3,
                },
                "seconds_to_best_at_1k": round(sec_1k, 2),
                "best_loss_at_1k": round(best_1k, 5),
                "seconds_to_best_at_1k_spec8": round(spec_sec_1k, 2),
                "best_loss_at_1k_spec8": round(spec_best_1k, 5),
                # round-20 graftclient rows (bench_fmin_client): fmin
                # as a serve client with the depth-k ask-ahead window;
                # the stream is bitwise the solo driver's, so
                # best_loss_at_1k_client == best_loss_at_1k is an
                # invariant, not a coincidence
                "seconds_to_best_at_1k_client": round(client_sec_1k, 2),
                "best_loss_at_1k_client": round(client_best_1k, 5),
                "fmin_client_asks_per_sec": round(client_asks_per_sec, 1),
                "fmin_ask_ahead_depth": ask_ahead_depth,
                "n_trials_1k": n_trials_1k,
                "device_loop_seconds_at_1k": (
                    round(dl_sec_1k, 3) if dl_sec_1k is not None else None
                ),
                "device_loop_best_at_1k": (
                    round(dl_best_1k, 5) if dl_best_1k is not None else None
                ),
                "device_loop_n_trials": dl_n,
                "device_loop_seq_seconds_at_1k": (
                    round(dls_sec_1k, 3) if dls_sec_1k is not None else None
                ),
                "device_loop_seq_best_at_1k": (
                    round(dls_best_1k, 5) if dls_best_1k is not None else None
                ),
                "device_loop_seq_n_trials": dls_n,
                "pbt_member_steps_per_sec": (
                    round(pbt_rate, 1) if pbt_rate else None
                ),
                "pbt_final_median_loss": (
                    round(pbt_median, 4) if pbt_median is not None else None
                ),
                "pbt_config": pbt_config if pbt_rate else None,
                "asha_device_seconds": (
                    round(asha_s, 2) if asha_s is not None else None
                ),
                "sha_sync_device_seconds": (
                    round(sha_sync_s, 2) if sha_sync_s is not None else None
                ),
                "asha_device_speedup_x": (
                    round(sha_sync_s / asha_s, 2)
                    if asha_s and sha_sync_s else None
                ),
                "asha_device_best": (
                    round(asha_best, 4) if asha_best is not None else None
                ),
                "sha_sync_device_best": (
                    round(sha_sync_best, 4)
                    if sha_sync_best is not None else None
                ),
                "obs_scaling": obs_scaling,
                "above_cap": above_cap_default,
                # round-9 static-analysis trend rows: unbaselined
                # findings must be 0 (tier-1 enforces), baseline size
                # tracks the grandfathered-debt burn-down
                "lint_findings_total": lint_findings_total,
                "lint_baseline_size": lint_baseline_size,
                # round-11 graftir contract rows: registered program
                # families checked at the IR level, and how many
                # drifted from program_contracts.json (0 on a healthy
                # tree -- drift is accepted only via --update-contracts)
                "ir_programs_checked": ir_programs_checked,
                "ir_contract_drift": ir_contract_drift,
                # round-16 graftrace rows: GL5xx concurrency findings
                # over the package (0 on a healthy tree), how many
                # rules checked, and the lockdep probe (exactly 1 =
                # the runtime sanitizer is armed and detecting)
                "trace_findings_total": trace_findings_total,
                "trace_rules_checked": trace_rules_checked,
                "lockdep_inversions_observed": lockdep_inversions_observed,
                # round-20 graftwire rows: wire ops checked across both
                # fronts, reply-contract drift vs wire_contracts.json
                # (0 on a healthy tree), and the armed fraction of the
                # crash-point registries (1.0 = no dead fault windows)
                "wire_ops_checked": wire_ops_checked,
                "wire_contract_drift": wire_contract_drift,
                "crash_points_armed_frac": crash_points_armed_frac,
                "rtt_ms": round(rtt_ms, 2),
                "compilation_cache": cache_dir is not None,
                "batch": batch,
                "n_EI_candidates": n_cand,
                "n_obs": n_obs,
                "platform": platform,
            }
        )
    )


if __name__ == "__main__":
    sys.exit(main())
