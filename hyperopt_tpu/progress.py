"""Progress-reporting callbacks for fmin.

Capability parity with the reference's ``hyperopt/progress.py`` +
``std_out_err_redirect_tqdm.py`` (SURVEY.md SS2): a tqdm context showing
trials completed and best loss so far; stdout redirected through tqdm so
objective prints do not shred the bar.
"""

from __future__ import annotations

import contextlib
import sys

__all__ = ["tqdm_progress_callback", "no_progress_callback", "default_callback"]


class ProgressContext:
    """Handle given to FMinIter: ``update(n, best_loss=...)``."""

    def __init__(self, pbar=None):
        self._pbar = pbar

    def update(self, n=1, best_loss=None):
        if self._pbar is None:
            return
        if best_loss is not None:
            self._pbar.set_postfix_str(f"best loss: {best_loss:.6g}", refresh=False)
        self._pbar.update(n)


class _TqdmWriteProxy:
    """File-like stdout proxy writing through ``tqdm.write``."""

    def __init__(self, stream, tqdm_cls):
        self._stream = stream
        self._tqdm = tqdm_cls

    def write(self, text):
        text = text.rstrip("\n")
        if text:
            self._tqdm.write(text, file=self._stream)

    def flush(self):
        self._stream.flush()

    def __getattr__(self, name):
        return getattr(self._stream, name)


@contextlib.contextmanager
def tqdm_progress_callback(initial, total):
    from tqdm import tqdm

    pbar = tqdm(
        total=total,
        initial=initial,
        ascii=False,
        dynamic_ncols=True,
        unit="trial",
        leave=True,
        file=sys.stderr,
    )
    old_stdout = sys.stdout
    try:
        sys.stdout = _TqdmWriteProxy(old_stdout, tqdm)
        yield ProgressContext(pbar)
    finally:
        sys.stdout = old_stdout
        pbar.close()


@contextlib.contextmanager
def no_progress_callback(initial, total):
    yield ProgressContext(None)


default_callback = tqdm_progress_callback
