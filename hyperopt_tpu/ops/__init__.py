"""JAX/TPU compute kernels.

``kernels`` -- shape-static vmapped TPE math: adaptive-Parzen GMM fitting
over masked observation buffers, rejection-free truncated-normal sampling
(inverse CDF), mixture log-densities, categorical posteriors, EI scoring.
``compile`` -- the space compiler: lowers an ``hp.*`` pyll graph to a
``PackedSpace`` + one jitted stochastic sampler emitting dense values and
active-masks (replacing the reference's interpreted ``rec_eval`` sampling;
SURVEY.md SS7 design stance #1-#2).
"""

from . import compile, kernels
from .compile import PackedSpace, compile_space

__all__ = ["compile", "kernels", "PackedSpace", "compile_space"]
