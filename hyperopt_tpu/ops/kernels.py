"""Shape-static JAX kernels for TPE.

The math of :mod:`hyperopt_tpu.tpe` (reference ``hyperopt/tpe.py``,
SURVEY.md SS3.2) re-derived for the TPU execution model:

* observations live in fixed-capacity buffers with validity masks (ragged
  idxs/vals -> dense + mask, SURVEY.md SS7 'hard parts');
* truncated sampling is inverse-CDF (``ndtri``), never rejection loops;
* per-hyperparameter fits/draws/scores are ``vmap``-ed over dimensions and
  candidates; everything lowers to elementwise + small sorts/matmuls that
  XLA fuses.

All kernels are pure functions of arrays -- no Python branching on traced
values -- so a single ``jit`` covers the whole suggest step.
"""

from __future__ import annotations

import jax
import jax.numpy as jnp
from jax.scipy.special import ndtr, ndtri

__all__ = [
    "forgetting_weights",
    "parzen_fit",
    "trunc_gmm_sample",
    "trunc_gmm_logpdf",
    "categorical_fit",
    "split_below_above",
    "ei_argmax",
    "ei_best_cont",
    "ei_best_cat",
    "fit_all_dims",
]


def _below_pad(lf):
    """Static buffer width for the compacted below set: n_below <= lf, so
    lf slots (rounded up to a multiple of 8 sublanes) always suffice."""
    return max(8, (int(lf) + 7) // 8 * 8)


def compact_below(obs_row, below_row, lf_pad):
    """Gather the (few) below-set slots of one dim into a small buffer.

    The below model has at most ``n_below <= LF`` components, but the
    observation buffer is capacity-sized; compacting before the Parzen fit
    shrinks the candidate-scoring inner dimension ~cap/LF-fold.  A stable
    argsort on ~mask keeps slot (time) order, so forgetting weights are
    unchanged.
    """
    order = jnp.argsort(~below_row, stable=True)
    idx = order[:lf_pad]
    return obs_row[idx], below_row[idx]


def fit_all_dims(ps_consts, values, active, losses, valid, gamma, lf, prior_weight):
    """Shared front half of a TPE suggest step: good/bad split + vmapped
    Parzen/categorical fits for every dimension.

    Args mirror the ObsBuffer arrays; ``ps_consts`` is PackedSpace._consts.
    Returns a dict with continuous fits (below compacted to [Dc, lf_pad+1],
    above full [Dc, cap+1]) and categorical posteriors (pb/pa: [Dk, k_max]);
    entries are None for absent families.
    """
    below, above, _ = split_below_above(losses, valid, gamma, lf)
    out = {"cont": None, "cat": None}
    lf_pad = _below_pad(lf)

    cont_idx = ps_consts["cont_idx"]
    if cont_idx.shape[0]:
        obs_c = values[cont_idx]
        lat = jnp.where(
            ps_consts["logspace"][:, None],
            _safe_log(obs_c),
            obs_c,
        )
        act_c = active[cont_idx]
        dc = cont_idx.shape[0]
        pw_v = jnp.full((dc,), prior_weight, dtype=jnp.float32)
        lf_v = jnp.full((dc,), lf, dtype=jnp.float32)
        fit = jax.vmap(parzen_fit)
        lat_b, mask_b = jax.vmap(compact_below, in_axes=(0, 0, None))(
            lat, act_c & below[None, :], lf_pad
        )
        wb, mb, sb = fit(
            lat_b, mask_b,
            ps_consts["prior_mu"], ps_consts["prior_sigma"], pw_v, lf_v,
        )
        wa, ma, sa = fit(
            lat, act_c & above[None, :],
            ps_consts["prior_mu"], ps_consts["prior_sigma"], pw_v, lf_v,
        )
        out["cont"] = (wb, mb, sb, wa, ma, sa)

    cat_idx = ps_consts["cat_idx"]
    if cat_idx.shape[0]:
        obs_k = values[cat_idx] - ps_consts["int_low"][:, None]
        act_k = active[cat_idx]
        dk = cat_idx.shape[0]
        pw_v = jnp.full((dk,), prior_weight, dtype=jnp.float32)
        lf_v = jnp.full((dk,), lf, dtype=jnp.float32)
        cfit = jax.vmap(categorical_fit)
        obs_kb, mask_kb = jax.vmap(compact_below, in_axes=(0, 0, None))(
            obs_k, act_k & below[None, :], lf_pad
        )
        pb = cfit(obs_kb, mask_kb, ps_consts["prior_p"], pw_v, lf_v)
        pa = cfit(obs_k, act_k & above[None, :], ps_consts["prior_p"], pw_v, lf_v)
        out["cat"] = (pb, pa)

    return out

TINY = 1e-12
F32_TINY = 1e-30


def forgetting_weights(mask, lf):
    """Linear-forgetting weights over a masked, slot-time-ordered buffer.

    Newest ``lf`` valid observations weigh 1; older ones ramp linearly from
    1/n.  Matches :func:`hyperopt_tpu.tpe.linear_forgetting_weights` on the
    valid slots; zeros elsewhere.
    """
    mask_f = mask.astype(jnp.float32)
    n = jnp.sum(mask_f)
    rank = jnp.cumsum(mask_f) - 1.0  # time rank of each valid slot
    n_ramp = jnp.maximum(n - lf, 0.0)
    inv_n = 1.0 / jnp.maximum(n, 1.0)
    ramp = inv_n + rank * (1.0 - inv_n) / jnp.maximum(n_ramp - 1.0, 1.0)
    w = jnp.where(rank >= n_ramp, 1.0, ramp)
    return w * mask_f


def parzen_fit(obs, mask, prior_mu, prior_sigma, prior_weight, lf):
    """Adaptive-Parzen GMM fit over a masked observation buffer.

    Args:
      obs: [N] observed values (latent space; garbage where ``mask`` false).
      mask: [N] bool validity.
      prior_mu, prior_sigma, prior_weight: scalars.
      lf: linear-forgetting horizon (scalar).

    Returns:
      (weights, mus, sigmas): each [N + 1] -- one component per buffer slot
      plus the prior component, sorted by mu; invalid slots carry weight 0.
      Same math as :func:`hyperopt_tpu.tpe.adaptive_parzen_normal`:
      neighbor-gap sigmas computed on the sorted array *with the prior
      inserted*, clipped to [prior_sigma/min(100, 1+n), prior_sigma], prior
      sigma pinned, forgetting weights + prior_weight, normalized.
    """
    n = jnp.sum(mask.astype(jnp.float32))
    tw = forgetting_weights(mask, lf)

    big = jnp.asarray(jnp.inf, dtype=obs.dtype)
    vals = jnp.concatenate([jnp.where(mask, obs, big), prior_mu[None]])
    wts = jnp.concatenate([tw, prior_weight[None]])
    valid = jnp.concatenate([mask, jnp.ones((1,), dtype=bool)])
    is_prior = jnp.concatenate(
        [jnp.zeros_like(mask), jnp.ones((1,), dtype=bool)]
    )

    order = jnp.argsort(vals, stable=True)
    sv = vals[order]
    sw = wts[order]
    sprior = is_prior[order]
    svalid = valid[order]

    m = sv.shape[0]
    neg = -jnp.inf
    left_gap = jnp.concatenate([jnp.full((1,), neg, sv.dtype), sv[1:] - sv[:-1]])
    right_gap = jnp.concatenate([sv[1:] - sv[:-1], jnp.full((1,), neg, sv.dtype)])
    left_avail = jnp.concatenate([jnp.zeros((1,), bool), svalid[:-1]])
    right_avail = jnp.concatenate([svalid[1:], jnp.zeros((1,), bool)])
    raw = jnp.maximum(
        jnp.where(left_avail, left_gap, neg), jnp.where(right_avail, right_gap, neg)
    )
    raw = jnp.where(jnp.isfinite(raw), raw, prior_sigma)

    minsigma = prior_sigma / jnp.minimum(100.0, 1.0 + n)
    sigma = jnp.clip(raw, minsigma, prior_sigma)
    sigma = jnp.where(sprior, prior_sigma, sigma)
    sigma = jnp.where(svalid, sigma, 1.0)

    sw = jnp.where(svalid, sw, 0.0)
    sw = sw / jnp.maximum(jnp.sum(sw), F32_TINY)
    sv = jnp.where(svalid, sv, 0.0)  # keep padded mus finite for downstream
    return sw, sv, sigma


def _safe_log(x):
    return jnp.log(jnp.maximum(x, F32_TINY))


def trunc_gmm_sample(key, weights, mus, sigmas, low, high, logspace, q, n_samples):
    """Draw ``n_samples`` from a truncated (latent-space) GMM.

    ``low``/``high`` are latent-space bounds (+-inf when unbounded);
    ``logspace`` exponentiates draws into natural space; ``q > 0``
    quantizes in natural space.  Inverse-CDF truncation -- no rejection.
    """
    k_comp, k_u = jax.random.split(key)
    logits = jnp.where(weights > 0, _safe_log(weights), -jnp.inf)
    comp = jax.random.categorical(k_comp, logits, shape=(n_samples,))
    m = mus[comp]
    s = jnp.maximum(sigmas[comp], TINY)

    a = ndtr((low - m) / s)
    b = ndtr((high - m) / s)
    u = jax.random.uniform(k_u, (n_samples,), dtype=mus.dtype)
    p = jnp.clip(a + u * (b - a), TINY, 1.0 - 1e-7)
    x = m + s * ndtri(p)
    x = jnp.clip(x, low, high)

    nat = jnp.where(logspace, jnp.exp(x), x)
    qq = jnp.maximum(q, TINY)
    nat_low = jnp.where(logspace, jnp.exp(low), low)
    nat_high = jnp.where(logspace, jnp.exp(high), high)
    rounded = jnp.round(nat / qq) * qq
    rounded = jnp.clip(
        rounded,
        jnp.where(jnp.isfinite(nat_low), jnp.round(nat_low / qq) * qq, nat_low),
        jnp.where(jnp.isfinite(nat_high), jnp.round(nat_high / qq) * qq, nat_high),
    )
    return jnp.where(q > 0, rounded, nat)


def trunc_gmm_logpdf(x, weights, mus, sigmas, low, high, logspace, q):
    """log-density of natural-space samples ``x`` [S] under the truncated
    (optionally quantized / log-space) GMM with components [K]."""
    sigmas = jnp.maximum(sigmas, TINY)
    logw = jnp.where(weights > 0, _safe_log(weights), -jnp.inf)

    a = ndtr((low - mus) / sigmas)
    b = ndtr((high - mus) / sigmas)
    log_mass = _safe_log(b - a)  # [K]

    lat = jnp.where(logspace, _safe_log(x), x)[:, None]  # [S,1]

    # continuous density
    z = (lat - mus) / sigmas
    log_pdf = -0.5 * z * z - jnp.log(sigmas) - 0.5 * jnp.log(2.0 * jnp.pi)
    jac = jnp.where(logspace, jnp.squeeze(lat, -1), 0.0)  # d(log x)/dx
    ll_cont = (
        jax.scipy.special.logsumexp(logw + log_pdf - log_mass, axis=1) - jac
    )

    # quantized bin mass
    qq = jnp.maximum(q, TINY)
    ub_nat = x + qq / 2.0
    lb_nat = x - qq / 2.0
    ub_lat = jnp.where(logspace, _safe_log(ub_nat), ub_nat)[:, None]
    lb_lat = jnp.where(logspace, _safe_log(lb_nat), lb_nat)[:, None]
    ub_lat = jnp.minimum(ub_lat, high)
    lb_lat = jnp.maximum(lb_lat, low)
    bin_mass = ndtr((ub_lat - mus) / sigmas) - ndtr((lb_lat - mus) / sigmas)
    ll_q = jax.scipy.special.logsumexp(
        logw + _safe_log(bin_mass) - log_mass, axis=1
    )

    return jnp.where(q > 0, ll_q, ll_cont)


def categorical_fit(obs, mask, prior_p, prior_weight, lf):
    """Categorical posterior from weighted counts + prior pseudocounts.

    Args:
      obs: [N] observed category indices (as floats; garbage where masked).
      mask: [N] bool.
      prior_p: [K] prior pmf (zero-padded beyond the true cardinality).

    Returns [K] posterior pmf (zero on padded options).  Matches
    :func:`hyperopt_tpu.tpe.categorical_posterior`.
    """
    tw = forgetting_weights(mask, lf)
    k = prior_p.shape[0]
    onehot = (obs[:, None] == jnp.arange(k, dtype=obs.dtype)[None, :]).astype(
        tw.dtype
    )
    counts = jnp.sum(onehot * tw[:, None], axis=0)
    n_options = jnp.sum(prior_p > 0).astype(counts.dtype)
    pseudo = counts * (prior_p > 0) + prior_weight * prior_p * n_options
    return pseudo / jnp.maximum(jnp.sum(pseudo), F32_TINY)


def split_below_above(losses, valid, gamma, lf):
    """Good/bad split over the masked loss buffer.

    ``n_below = min(ceil(gamma * sqrt(n_ok)), lf)`` (SURVEY.md SS3.2);
    ties broken by slot order (reference breaks by tid -- slots are
    tid-ordered).  Returns (below_mask, above_mask, n_below).
    """
    valid = valid & jnp.isfinite(losses)
    n_ok = jnp.sum(valid.astype(jnp.float32))
    n_below = jnp.minimum(jnp.ceil(gamma * jnp.sqrt(n_ok)), lf)

    keyed = jnp.where(valid, losses, jnp.inf)
    order = jnp.argsort(keyed, stable=True)  # stable: slot order breaks ties
    rank = jnp.argsort(order, stable=True)
    below = valid & (rank < n_below)
    above = valid & ~below
    return below, above, n_below


def ei_argmax(samples, ll_below, ll_above):
    """Factorized EI: the candidate maximizing log l(x) - log g(x)."""
    score = ll_below - ll_above
    return samples[jnp.argmax(score)], jnp.max(score)


def ei_best_cont(key, wb, mb, sb, wa, ma, sa, low, high, logspace, q, n_cand):
    """One continuous dim: draw n_cand from the below-model, score the EI
    log-likelihood ratio, return (best value, best score)."""
    samples = trunc_gmm_sample(key, wb, mb, sb, low, high, logspace, q, n_cand)
    ll_b = trunc_gmm_logpdf(samples, wb, mb, sb, low, high, logspace, q)
    ll_a = trunc_gmm_logpdf(samples, wa, ma, sa, low, high, logspace, q)
    return ei_argmax(samples, ll_b, ll_a)


def ei_best_cat(key, p_below, p_above, n_cand):
    """One categorical dim: draw candidate categories from the below
    posterior, score log p_b - log p_a, return (best index, best score)."""
    logits = jnp.where(p_below > 0, _safe_log(p_below), -jnp.inf)
    cands = jax.random.categorical(key, logits, shape=(n_cand,))
    llr = _safe_log(p_below[cands]) - _safe_log(p_above[cands])
    best = jnp.argmax(llr)
    return cands[best].astype(jnp.float32), llr[best]
