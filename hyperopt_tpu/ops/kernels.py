"""Shape-static JAX kernels for TPE.

The math of :mod:`hyperopt_tpu.tpe` (reference ``hyperopt/tpe.py``,
SURVEY.md SS3.2) re-derived for the TPU execution model:

* observations live in fixed-capacity buffers with validity masks (ragged
  idxs/vals -> dense + mask, SURVEY.md SS7 'hard parts');
* truncated sampling is inverse-CDF (``ndtri``), never rejection loops;
* per-hyperparameter fits/draws/scores are ``vmap``-ed over dimensions and
  candidates; everything lowers to elementwise + small sorts/matmuls that
  XLA fuses.

All kernels are pure functions of arrays -- no Python branching on traced
values -- so a single ``jit`` covers the whole suggest step.
"""

from __future__ import annotations

from typing import NamedTuple

import jax
import jax.numpy as jnp
import numpy as np
from jax.scipy.special import ndtr, ndtri

__all__ = [
    "DEFAULT_ABOVE_CAP",
    "HistoryState",
    "apply_delta",
    "apply_delta_masked",
    "history_summary",
    "check_prior_weight",
    "compact_gmm",
    "forgetting_weights",
    "parzen_fit",
    "quantize_nat",
    "trunc_gmm_sample",
    "trunc_gmm_sample_pre",
    "trunc_gmm_logpdf",
    "gmm_precompute",
    "gmm_logpdf_cont_pre",
    "gmm_logpdf_quant_pre",
    "categorical_fit",
    "split_below_above",
    "ei_best_cont",
    "ei_best_cat",
    "ei_scores_cont",
    "ei_scores_cat",
    "ei_sweep_cont",
    "ei_sweep_cat",
    "ei_sweep_cont_scores",
    "ei_sweep_cat_scores",
    "fit_all_dims",
]


class HistoryState(NamedTuple):
    """The four dense observation arrays every suggest path threads.

    One container for the three places the history lives as a unit: the
    resident :class:`hyperopt_tpu.jax_trials.ObsBuffer` device mirror,
    the fused tell+ask programs (state in, state out, donated), and the
    :mod:`hyperopt_tpu.device_loop` scan carry.  A NamedTuple is a
    pytree, so it crosses jit/scan boundaries as-is and unpacks with
    ``*state`` wherever the four positional arrays are expected.
    """

    values: jax.Array  # [D, cap] natural-space draws
    active: jax.Array  # [D, cap] per-dim activity mask
    losses: jax.Array  # [cap]
    valid: jax.Array  # [cap] slot occupancy


def history_summary(state):
    """(best finite loss, occupied slots) of a :class:`HistoryState`.

    The chunk-boundary progress row of the chunked device loop
    (:func:`hyperopt_tpu.device_loop.compile_fmin` with ``chunk_size``):
    computed inside the chunk program so the ``io_callback`` row costs
    two reductions, not a history fetch.  ``best`` is ``inf`` while no
    finite loss exists (all-failed startup chunks).
    """
    ok = state.valid & jnp.isfinite(state.losses)
    best = jnp.min(jnp.where(ok, state.losses, jnp.inf))
    done = jnp.sum(state.valid.astype(jnp.int32))
    return best, done


def apply_delta(values, active, losses, valid, vcol, acol, loss, idx):
    """Stage one completed trial into the history: an O(D) delta tell.

    The incremental alternative to re-uploading the whole bucketed
    history on every generation bump (the O(n_obs*D) term that made the
    sequential driver dispatch-bound): one value/active column, one
    loss scalar, one slot index -- ~5*D+8 bytes of host->device traffic
    -- applied by ``dynamic_update_slice`` so a single compiled program
    covers every slot of a bucket (``idx`` is traced; no per-slot
    retrace).  The write is pure data movement, so the updated state is
    bitwise identical to a fresh upload of the same host arrays -- the
    parity contract the resident ObsBuffer and the fused tell+ask
    programs both rely on.  Only in-order appends come through here
    (``valid`` is a prefix mask, so the new slot is simply marked
    occupied); a late out-of-order completion shifts the tail on the
    host and re-materializes.
    """
    idx = jnp.asarray(idx, jnp.int32)
    zero = jnp.int32(0)
    values = jax.lax.dynamic_update_slice(
        values, jnp.asarray(vcol, values.dtype)[:, None], (zero, idx)
    )
    active = jax.lax.dynamic_update_slice(
        active, jnp.asarray(acol, active.dtype)[:, None], (zero, idx)
    )
    losses = jax.lax.dynamic_update_slice(
        losses, jnp.asarray(loss, losses.dtype)[None], (idx,)
    )
    valid = jax.lax.dynamic_update_slice(
        valid, jnp.ones((1,), valid.dtype), (idx,)
    )
    return HistoryState(values, active, losses, valid)


def apply_delta_masked(values, active, losses, valid, vcol, acol, loss,
                       idx, apply):
    """:func:`apply_delta` gated by a traced ``apply`` flag.

    The per-slot form the study-batched service engine
    (:mod:`hyperopt_tpu.serve.batched`) vmaps over a leading study
    axis: slots WITH a staged tell apply their O(D) delta, slots
    without pass their state through untouched -- one program shape
    covers every tell/no-tell mix, so join/leave churn never retraces.
    ``jnp.where(True, new, old)`` selects ``new`` elementwise, so an
    applying slot's state is bitwise :func:`apply_delta`'s output and
    a skipping slot's is bitwise its input -- the per-study parity
    contract of the batched engine reduces to PR 4's.
    """
    new = apply_delta(values, active, losses, valid, vcol, acol, loss, idx)
    old = (values, active, losses, valid)
    return HistoryState(
        *(jnp.where(apply, n, o) for n, o in zip(new, old))
    )


def check_prior_weight(prior_weight):
    """Host-level builder guard (call at build time, never under trace):
    ``_inverse_cdf_onehot`` has no all-zero-weight fallback, so a
    zero-weight prior with an empty below set would sample from zeroed
    (mu=sigma=0) component params and silently return constants."""
    if prior_weight <= 0:
        raise ValueError(
            "prior_weight must be > 0: a zero-weight prior degenerates "
            "the below-model mixture for dims with no observations"
        )


def _below_pad(lf, cap=None, gamma=None):
    """Static buffer width for the compacted below set.

    The device computes ``n_below = min(ceil(gamma * sqrt(n_ok)), lf)`` in
    float32 (:func:`split_below_above`) with ``n_ok <= cap``, so the host
    bound is ``min(lf, ceil_f64(gamma * sqrt(cap)) + 1)`` -- the +1 absorbs
    float32-vs-float64 ceil disagreement at exact integer boundaries (the
    device value can land one above the float64 ceil, and when that ceil is
    already a multiple of 8 the sublane round-up adds no slack).  For
    typical capacities this is far below ``lf`` (cap=512, gamma=.25 -> 7),
    which shrinks every [S, K_below] sampling/scoring loop.  Rounded up to
    a multiple of 8 sublanes."""
    bound = int(lf)
    if cap is not None and gamma is not None and gamma > 0:
        import math

        bound = min(bound, int(math.ceil(gamma * math.sqrt(float(cap)))) + 1)
    return max(8, (bound + 7) // 8 * 8)


# Default component cap for the ABOVE Parzen model (the single knob the
# suggest builders resolve their ``above_cap=None`` against).  The above
# model's component count tracks the observation count, so full-width
# scoring is the linear term that collapsed suggest throughput 109k/s ->
# 3.8k/s between 500 and 10k obs (BASELINE.md 10k-soak row); 512 keeps
# the <= 500-obs headline configs bitwise untouched (their live component
# count never reaches the cap, so compaction is the identity) while
# pinning the scoring width flat past it.
DEFAULT_ABOVE_CAP = 512


def _above_pad(above_cap):
    """Static padded width of the compacted above model: the cap rounded
    up to a multiple of 8 sublanes (floored at one sublane row)."""
    return max(8, (int(above_cap) + 7) // 8 * 8)


def compact_gmm(weights, mus, sigmas, out_width):
    """Merge a sorted Parzen mixture into a fixed-width component buffer.

    Input is one :func:`parzen_fit` output row: components sorted by mu
    with the live ones (weight > 0, prior included) a PREFIX and padded
    slots (weight 0) behind them.  The ``out_width`` output groups the
    live prefix into ``out_width`` contiguous runs of near-equal size --
    adjacent in mu, so every merge is of near-duplicate neighbors -- and
    moment-matches each run: group weight is the weight sum (total
    mixture mass is preserved), group mu the weighted mean, group sigma
    the mixture standard deviation ``sqrt(E[s^2 + mu^2] - mu_g^2)``
    computed as within-variance + spread so float cancellation can only
    shrink the (non-negative) spread term, never the variance itself.
    The linear-forgetting weights thus decide what survives: a heavy
    (recent) component dominates its group's moments, near-zero-weight
    (oldest) components fold into their neighbors' mass.

    PARITY CONTRACT: a group holding exactly ONE live component passes
    its (w, mu, sigma) through UNTOUCHED -- and when the live count is
    <= ``out_width`` the grouping is the identity, so the compacted
    mixture equals the full one slot-for-slot and downstream scoring is
    bitwise identical (padded tails only append exact-zero terms to the
    score reductions).  Above the cap, scoring cost drops from O(n_obs)
    to O(out_width) per candidate.

    Group sums come from exclusive-prefix cumsums differenced at the
    group boundaries -- O(K) elementwise + one [out_width]-row gather --
    instead of a [K, out_width] one-hot contraction, which would cost
    more than the scoring it saves at B=1 (the sequential device-loop /
    latency path must stay cheap at every width).
    """
    k = weights.shape[0]
    live = weights > 0
    n_live = jnp.sum(live.astype(jnp.int32))
    # group(i) = floor(i * W / scale): identity while n_live <= out_width
    scale = jnp.maximum(n_live, out_width)
    g = jnp.arange(out_width + 1, dtype=jnp.int32)
    bounds = jnp.clip((g * scale + out_width - 1) // out_width, 0, k)

    sig2 = sigmas * sigmas
    cols = jnp.stack(
        [
            weights,
            weights * mus,
            weights * mus * mus,
            weights * sig2,
            live.astype(weights.dtype),
        ],
        axis=-1,
    )  # [K, 5]
    p = jnp.concatenate(
        [jnp.zeros((1, 5), weights.dtype), jnp.cumsum(cols, axis=0)], axis=0
    )
    seg = p[bounds[1:]] - p[bounds[:-1]]  # [out_width, 5]

    w_g = seg[:, 0]
    cnt = seg[:, 4]
    live_g = cnt > 0
    single = cnt == 1.0  # float cumsums of 0/1 are exact below 2^24
    w_safe = jnp.maximum(w_g, F32_TINY)
    mu_g = seg[:, 1] / w_safe
    spread = jnp.maximum(seg[:, 2] / w_safe - mu_g * mu_g, 0.0)
    sigma_g = jnp.sqrt(seg[:, 3] / w_safe + spread)

    # singleton groups gather the ORIGINAL component (bitwise parity);
    # prefix-sum differencing would round its last bits
    orig = jnp.stack([weights, mus, sigmas], axis=-1)[
        jnp.clip(bounds[:-1], 0, k - 1)
    ]  # [out_width, 3]
    w_out = jnp.where(single, orig[:, 0], jnp.where(live_g, w_g, 0.0))
    mu_out = jnp.where(single, orig[:, 1], jnp.where(live_g, mu_g, 0.0))
    s_out = jnp.where(single, orig[:, 2], jnp.where(live_g, sigma_g, 1.0))
    return w_out, mu_out, s_out


def compact_below(obs_row, below_row, lf_pad):
    """Gather the (few) below-set slots of one dim into a small buffer.

    The below model has at most ``n_below <= LF`` components, but the
    observation buffer is capacity-sized; compacting before the Parzen fit
    shrinks the candidate-scoring inner dimension ~cap/LF-fold.  Selection
    is ``top_k`` over descending slot keys -- the first ``lf_pad`` set
    slots in slot (time) order, so forgetting weights are unchanged --
    instead of a full stable argsort over the capacity (measured 1.4x
    on the B=1 device-loop fit, bench_artifacts/ROOFLINE.md round 5).
    Slots past the set count gather garbage values under a False mask
    (ignored by every consumer, exactly as the argsort form's inf-pad).
    """
    n = below_row.shape[0]
    slot_key = jnp.where(
        below_row, jnp.arange(n, 0, -1, dtype=jnp.int32), 0
    )
    _, idx = jax.lax.top_k(slot_key, lf_pad)
    return obs_row[idx], below_row[idx]


def fit_all_dims(ps_consts, values, active, losses, valid, gamma, lf,
                 prior_weight, pad_gamma=None, above_cap=None):
    """Shared front half of a TPE suggest step: good/bad split + vmapped
    Parzen/categorical fits for every dimension.

    Args mirror the ObsBuffer arrays; ``ps_consts`` is PackedSpace._consts.
    Returns a dict with continuous fits (below compacted to [Dc, lf_pad+1],
    above full [Dc, cap+1] or compacted to [Dc, above_pad]) and
    categorical posteriors (pb/pa: [Dk, k_max]); entries are None for
    absent families.

    ``gamma`` may be a TRACED scalar (the adaptive on-device path tunes
    it per step); the static below-buffer width then needs a host-level
    upper bound -- pass ``pad_gamma`` = the largest gamma the trace can
    produce (None = ``gamma`` itself is static).

    ``above_cap`` (host int, None = full width) caps the ABOVE Parzen
    model at a fixed component width via :func:`compact_gmm` whenever
    the buffer would exceed it -- the below model is already compacted
    (``compact_below``) and the categorical posteriors are [k_max] by
    construction, so the above model is the only fit whose width (and
    therefore every [S, K] scoring loop) grows with the observation
    count.  Identity (bitwise) while the live above components fit
    under the cap; see :func:`compact_gmm` for the merge contract.
    """
    below, above, _ = split_below_above(losses, valid, gamma, lf)
    out = {"cont": None, "cat": None}
    lf_pad = _below_pad(
        lf, cap=losses.shape[0],
        gamma=gamma if pad_gamma is None else pad_gamma,
    )

    cont_idx = ps_consts["cont_idx"]
    if cont_idx.shape[0]:
        obs_c = values[cont_idx]
        lat = jnp.where(
            ps_consts["logspace"][:, None],
            _safe_log(obs_c),
            obs_c,
        )
        act_c = active[cont_idx]
        dc = cont_idx.shape[0]
        pw_v = jnp.full((dc,), prior_weight, dtype=jnp.float32)
        lf_v = jnp.full((dc,), lf, dtype=jnp.float32)
        fit = jax.vmap(parzen_fit)
        lat_b, mask_b = jax.vmap(compact_below, in_axes=(0, 0, None))(
            lat, act_c & below[None, :], lf_pad
        )
        wb, mb, sb = fit(
            lat_b, mask_b,
            ps_consts["prior_mu"], ps_consts["prior_sigma"], pw_v, lf_v,
        )
        wa, ma, sa = fit(
            lat, act_c & above[None, :],
            ps_consts["prior_mu"], ps_consts["prior_sigma"], pw_v, lf_v,
        )
        if above_cap is not None:
            a_pad = _above_pad(above_cap)
            if wa.shape[1] > a_pad:
                wa, ma, sa = jax.vmap(
                    compact_gmm, in_axes=(0, 0, 0, None)
                )(wa, ma, sa, a_pad)
        out["cont"] = (wb, mb, sb, wa, ma, sa)

    cat_idx = ps_consts["cat_idx"]
    if cat_idx.shape[0]:
        obs_k = values[cat_idx] - ps_consts["int_low"][:, None]
        act_k = active[cat_idx]
        dk = cat_idx.shape[0]
        pw_v = jnp.full((dk,), prior_weight, dtype=jnp.float32)
        lf_v = jnp.full((dk,), lf, dtype=jnp.float32)
        cfit = jax.vmap(categorical_fit)
        obs_kb, mask_kb = jax.vmap(compact_below, in_axes=(0, 0, None))(
            obs_k, act_k & below[None, :], lf_pad
        )
        pb = cfit(obs_kb, mask_kb, ps_consts["prior_p"], pw_v, lf_v)
        pa = cfit(obs_k, act_k & above[None, :], ps_consts["prior_p"], pw_v, lf_v)
        out["cat"] = (pb, pa)

    return out

TINY = 1e-12
F32_TINY = 1e-30


def forgetting_weights(mask, lf):
    """Linear-forgetting weights over a masked, slot-time-ordered buffer.

    Newest ``lf`` valid observations weigh 1; older ones ramp linearly from
    1/n.  Matches :func:`hyperopt_tpu.tpe.linear_forgetting_weights` on the
    valid slots; zeros elsewhere.
    """
    mask_f = mask.astype(jnp.float32)
    n = jnp.sum(mask_f)
    rank = jnp.cumsum(mask_f) - 1.0  # time rank of each valid slot
    n_ramp = jnp.maximum(n - lf, 0.0)
    inv_n = 1.0 / jnp.maximum(n, 1.0)
    ramp = inv_n + rank * (1.0 - inv_n) / jnp.maximum(n_ramp - 1.0, 1.0)
    w = jnp.where(rank >= n_ramp, 1.0, ramp)
    return w * mask_f


def parzen_fit(obs, mask, prior_mu, prior_sigma, prior_weight, lf):
    """Adaptive-Parzen GMM fit over a masked observation buffer.

    Args:
      obs: [N] observed values (latent space; garbage where ``mask`` false).
      mask: [N] bool validity.
      prior_mu, prior_sigma, prior_weight: scalars.
      lf: linear-forgetting horizon (scalar).

    Returns:
      (weights, mus, sigmas): each [N + 1] -- one component per buffer slot
      plus the prior component, sorted by mu; invalid slots carry weight 0.
      Same math as :func:`hyperopt_tpu.tpe.adaptive_parzen_normal`:
      neighbor-gap sigmas computed on the sorted array *with the prior
      inserted*, clipped to [prior_sigma/min(100, 1+n), prior_sigma], prior
      sigma pinned, forgetting weights + prior_weight, normalized.
    """
    n = jnp.sum(mask.astype(jnp.float32))
    tw = forgetting_weights(mask, lf)

    big = jnp.asarray(jnp.inf, dtype=obs.dtype)
    vals = jnp.concatenate([jnp.where(mask, obs, big), prior_mu[None]])
    wts = jnp.concatenate([tw, prior_weight[None]])
    valid = jnp.concatenate([mask, jnp.ones((1,), dtype=bool)])
    is_prior = jnp.concatenate(
        [jnp.zeros_like(mask), jnp.ones((1,), dtype=bool)]
    )

    # ONE variadic stable sort carrying every payload: bitwise-identical
    # to argsort + four gathers, but TPU gathers serialize -- the fused
    # sort is 4x faster at capacity width on the B=1 device loop
    # (bench_artifacts/ROOFLINE.md round 5; the fit was 40% of a step)
    sv, sw, sprior, svalid = jax.lax.sort(
        (vals, wts, is_prior.astype(jnp.int8), valid.astype(jnp.int8)),
        num_keys=1, is_stable=True,
    )
    sprior = sprior.astype(bool)
    svalid = svalid.astype(bool)

    m = sv.shape[0]
    neg = -jnp.inf
    left_gap = jnp.concatenate([jnp.full((1,), neg, sv.dtype), sv[1:] - sv[:-1]])
    right_gap = jnp.concatenate([sv[1:] - sv[:-1], jnp.full((1,), neg, sv.dtype)])
    left_avail = jnp.concatenate([jnp.zeros((1,), bool), svalid[:-1]])
    right_avail = jnp.concatenate([svalid[1:], jnp.zeros((1,), bool)])
    raw = jnp.maximum(
        jnp.where(left_avail, left_gap, neg), jnp.where(right_avail, right_gap, neg)
    )
    raw = jnp.where(jnp.isfinite(raw), raw, prior_sigma)

    minsigma = prior_sigma / jnp.minimum(100.0, 1.0 + n)
    sigma = jnp.clip(raw, minsigma, prior_sigma)
    sigma = jnp.where(sprior, prior_sigma, sigma)
    sigma = jnp.where(svalid, sigma, 1.0)

    sw = jnp.where(svalid, sw, 0.0)
    sw = sw / jnp.maximum(jnp.sum(sw), F32_TINY)
    sv = jnp.where(svalid, sv, 0.0)  # keep padded mus finite for downstream
    return sw, sv, sigma


def _safe_log(x):
    return jnp.log(jnp.maximum(x, F32_TINY))


def quantize_nat(nat, q, low, high, logspace):
    """Natural-space quantization shared by every sampling path (prior,
    TPE below-model draws, annealing neighborhoods): round to the q-grid
    and clip to the rounded finite bounds; ``low``/``high`` are latent
    (log-space dims exponentiate).  ``q <= 0`` passes through."""
    qq = jnp.maximum(q, TINY)
    nat_low = jnp.where(logspace, jnp.exp(low), low)
    nat_high = jnp.where(logspace, jnp.exp(high), high)
    rounded = jnp.round(nat / qq) * qq
    rounded = jnp.clip(
        rounded,
        jnp.where(jnp.isfinite(nat_low), jnp.round(nat_low / qq) * qq, nat_low),
        jnp.where(jnp.isfinite(nat_high), jnp.round(nat_high / qq) * qq, nat_high),
    )
    return jnp.where(q > 0, rounded, nat)


def gmm_precompute(weights, mus, sigmas, low, high):
    """Per-component constants shared by sampling and scoring.

    Everything here is [K]-sized, so under the batch ``vmap`` (which maps
    fits with ``in_axes=None``) it is computed once per dimension, not per
    trial or candidate -- the [S, K] inner loops below touch only
    precomputed reciprocals and log-constants.
    """
    sig = jnp.maximum(sigmas, TINY)
    inv_s = 1.0 / sig
    a = ndtr((low - mus) * inv_s)
    b = ndtr((high - mus) * inv_s)
    log_mass = _safe_log(b - a)
    logw = jnp.where(weights > 0, _safe_log(weights), -jnp.inf)
    # c1 folds every per-component additive term of the truncated-normal
    # log-density, so a scored term is just c1 - 0.5 * z^2.
    c1 = jnp.where(
        weights > 0,
        logw - log_mass - jnp.log(sig) - 0.5 * jnp.log(2.0 * jnp.pi),
        -jnp.inf,
    )
    c1max = jnp.max(c1)
    c1max = jnp.where(jnp.isfinite(c1max), c1max, 0.0)
    cdf = jnp.cumsum(jnp.maximum(weights, 0.0))
    cdf_lo = jnp.concatenate([jnp.zeros((1,), cdf.dtype), cdf[:-1]])
    return {
        "mus": mus,
        "inv_s": inv_s,
        "mu_inv_s": mus * inv_s,
        # w / truncated-mass, 0 on padded slots: the quantized bin-mass
        # scorer sums wmass * bin_mass directly (single log at the end).
        "wmass": jnp.where(weights > 0, weights / jnp.maximum(b - a, TINY), 0.0),
        "c1": c1,
        # exact upper bound on any scored term (z^2 >= 0): single-pass
        # logsumexp stabilization without the per-sample max sweep.
        "c1max": c1max,
        "cdf": cdf,
        "cdf_lo": cdf_lo,
        # [K, 4] stacked per-component params (mu, sigma, cdf-low,
        # cdf-high): the sampler's one-hot pick contracts against this
        # once instead of running four masked reductions.
        "params4": jnp.stack([mus, sig, a, b], axis=-1),
    }


def _inverse_cdf_onehot(u, cdf, cdf_lo=None):
    """[S, K] one-hot component pick per sample via inverse-CDF on the
    weight cumsum.

    One uniform per sample + [S, K] interval tests -- far cheaper on the
    VPU than ``jax.random.categorical``'s K Gumbel draws per sample.
    Component k is picked iff ``cdf[k-1] <= scaled < cdf[k]``.  ``scaled``
    is clamped strictly below ``cdf[-1]`` so float rounding at ``u *
    cdf[-1] == cdf[-1]`` cannot fall outside every interval; zero-weight
    (padded) slots have ``cdf[k] == cdf[k-1]`` -- an empty interval --
    and are never selected.
    """
    if cdf_lo is None:
        cdf_lo = jnp.concatenate([jnp.zeros((1,), cdf.dtype), cdf[:-1]])
    scaled = jnp.minimum(u * cdf[-1], cdf[-1] * (1.0 - 1e-6))[:, None]
    return ((scaled >= cdf_lo) & (scaled < cdf)).astype(u.dtype)


def trunc_gmm_sample_pre(key, pre, low, high, logspace, q, n_samples):
    """Draw ``n_samples`` from a truncated (latent-space) GMM given its
    :func:`gmm_precompute` dict.  Inverse-CDF truncation -- no rejection.

    Per-sample component parameters come from a fused one-hot
    multiply-sum over K (XLA fuses all four reductions into one [S, K]
    loop) -- TPU gathers serialize and were the measured bottleneck.
    """
    k_comp, k_u = jax.random.split(key)
    u_comp = jax.random.uniform(k_comp, (n_samples,), dtype=pre["mus"].dtype)
    onehot = _inverse_cdf_onehot(u_comp, pre["cdf"], pre["cdf_lo"])
    # HIGHEST precision: the default TPU matmul rounds operands to
    # bfloat16, which would deterministically bias every drawn candidate
    # (mus/sigmas/truncation CDFs to 8 mantissa bits).  At [S, K] x [K, 4]
    # the exact contraction is still far cheaper than masked reductions.
    picked = jnp.matmul(
        onehot, pre["params4"], precision=jax.lax.Precision.HIGHEST
    )  # [S, 4]
    m, s, a, b = (picked[:, i] for i in range(4))

    u = jax.random.uniform(k_u, (n_samples,), dtype=pre["mus"].dtype)
    p = jnp.clip(a + u * (b - a), TINY, 1.0 - 1e-7)
    x = m + s * ndtri(p)
    x = jnp.clip(x, low, high)

    nat = jnp.where(logspace, jnp.exp(x), x)
    return quantize_nat(nat, q, low, high, logspace)


def trunc_gmm_sample(key, weights, mus, sigmas, low, high, logspace, q, n_samples):
    """Draw ``n_samples`` from a truncated (latent-space) GMM.

    ``low``/``high`` are latent-space bounds (+-inf when unbounded);
    ``logspace`` exponentiates draws into natural space; ``q > 0``
    quantizes in natural space.
    """
    pre = gmm_precompute(weights, mus, sigmas, low, high)
    return trunc_gmm_sample_pre(key, pre, low, high, logspace, q, n_samples)


def gmm_logpdf_cont_pre(x, pre, logspace):
    """Continuous (unquantized) truncated-GMM log-density at natural-space
    ``x`` [S]: one fused multiply + exp per [S, K] term.  Truncation
    bounds are already folded into ``pre['c1']`` via the log-mass.

    Stabilized by the *static* shift ``c1max`` (an exact upper bound on
    every term, since z^2 >= 0) instead of a per-sample max -- a single
    pass over K rather than logsumexp's two.  Terms more than ~88 nats
    below the bound underflow harmlessly.  If the whole sum underflows
    (a sample in the far tail of every component) the result falls back
    to the largest shifted term -- the one-term logsumexp answer, exact
    where one component dominates -- so far-tail candidates keep their
    true ordering; the max reduction has no data dependence on the sum,
    so XLA fuses both into the same pass over the terms."""
    lat = jnp.where(logspace, _safe_log(x), x)
    z = lat[:, None] * pre["inv_s"] - pre["mu_inv_s"]
    terms = (pre["c1"] - pre["c1max"]) - 0.5 * z * z
    sm = jnp.sum(jnp.exp(terms), axis=1)
    mx = jnp.max(terms, axis=1)
    jac = jnp.where(logspace, lat, 0.0)
    ll = jnp.where(sm > 1e-38, jnp.log(jnp.maximum(sm, 1e-38)), mx)
    return pre["c1max"] + ll - jac


def gmm_logpdf_quant_pre(x, pre, low, high, logspace, q):
    """Quantized bin-mass log-density at natural-space ``x`` [S].

    Bin masses are non-negative, so the mixture mass is a direct weighted
    sum (``wmass = w / truncation-mass``) with ONE log at the end -- no
    per-term log, no logsumexp max pass.  A bin with zero mass under every
    component scores ~log(1e-38) instead of -inf (never wins the argmax).

    Known drift vs the log-domain reference math: candidates whose total
    bin mass underflows float32 (< ~1e-38) all collapse to the same floor
    score, losing relative ordering in the far tail.  Acceptable for the
    suggest path because candidates are drawn from the *below* model, so
    their below-mass is never in the underflow tail and the above-mass
    floor only saturates the llr in the candidate's favor uniformly; the
    traced-``q`` parity path (:func:`trunc_gmm_logpdf`) shares this
    behavior by construction.
    """
    qq = jnp.maximum(q, TINY)
    ub_nat = x + qq / 2.0
    lb_nat = x - qq / 2.0
    ub_lat = jnp.where(logspace, _safe_log(ub_nat), ub_nat)[:, None]
    lb_lat = jnp.where(logspace, _safe_log(lb_nat), lb_nat)[:, None]
    ub_lat = jnp.minimum(ub_lat, high)
    lb_lat = jnp.maximum(lb_lat, low)
    inv_s = pre["inv_s"]
    mu_inv_s = pre["mu_inv_s"]
    bin_mass = ndtr(ub_lat * inv_s - mu_inv_s) - ndtr(lb_lat * inv_s - mu_inv_s)
    p = jnp.sum(pre["wmass"] * bin_mass, axis=1)
    return jnp.log(jnp.maximum(p, 1e-38))


def trunc_gmm_logpdf(x, weights, mus, sigmas, low, high, logspace, q):
    """log-density of natural-space samples ``x`` [S] under the truncated
    (optionally quantized / log-space) GMM with components [K].

    General (traced-``q``) form computing both families; the suggest path
    partitions dims by static ``q > 0`` at build time and calls the
    ``*_pre`` halves directly so each dim pays only its own family.
    """
    pre = gmm_precompute(weights, mus, sigmas, low, high)
    ll_cont = gmm_logpdf_cont_pre(x, pre, logspace)
    ll_q = gmm_logpdf_quant_pre(x, pre, low, high, logspace, q)
    return jnp.where(q > 0, ll_q, ll_cont)


def categorical_fit(obs, mask, prior_p, prior_weight, lf):
    """Categorical posterior from weighted counts + prior pseudocounts.

    Args:
      obs: [N] observed category indices (as floats; garbage where masked).
      mask: [N] bool.
      prior_p: [K] prior pmf (zero-padded beyond the true cardinality).

    Returns [K] posterior pmf (zero on padded options).  Matches
    :func:`hyperopt_tpu.tpe.categorical_posterior`.
    """
    tw = forgetting_weights(mask, lf)
    k = prior_p.shape[0]
    onehot = (obs[:, None] == jnp.arange(k, dtype=obs.dtype)[None, :]).astype(
        tw.dtype
    )
    counts = jnp.sum(onehot * tw[:, None], axis=0)
    n_options = jnp.sum(prior_p > 0).astype(counts.dtype)
    pseudo = counts * (prior_p > 0) + prior_weight * prior_p * n_options
    return pseudo / jnp.maximum(jnp.sum(pseudo), F32_TINY)


def split_below_above(losses, valid, gamma, lf):
    """Good/bad split over the masked loss buffer.

    ``n_below = min(ceil(gamma * sqrt(n_ok)), lf)`` (SURVEY.md SS3.2);
    ties broken by slot order (reference breaks by tid -- slots are
    tid-ordered).  Returns (below_mask, above_mask, n_below).
    """
    valid = valid & jnp.isfinite(losses)
    n_ok = jnp.sum(valid.astype(jnp.float32))
    n_below = jnp.minimum(jnp.ceil(gamma * jnp.sqrt(n_ok)), lf)

    keyed = jnp.where(valid, losses, jnp.inf)
    order = jnp.argsort(keyed, stable=True)  # stable: slot order breaks ties
    # inverse permutation by scatter -- cheaper than a second sort
    rank = jnp.zeros_like(order).at[order].set(
        jnp.arange(order.shape[0], dtype=order.dtype)
    )
    below = valid & (rank < n_below)
    above = valid & ~below
    return below, above, n_below


def ei_scores_cont(key, wb, mb, sb, wa, ma, sa, low, high, logspace, q,
                   n_cand, has_q=None):
    """One continuous dim: draw n_cand from the below-model and score the
    EI log-likelihood ratio for EVERY candidate.  Returns (samples [S],
    llr [S]).

    ``has_q`` is a *static* (trace-time) flag: True = quantized bin-mass
    scoring only, False = continuous density only, None = traced ``q``
    dispatch (computes both families; parity/compat path).
    """
    pre_b = gmm_precompute(wb, mb, sb, low, high)
    pre_a = gmm_precompute(wa, ma, sa, low, high)
    samples = trunc_gmm_sample_pre(key, pre_b, low, high, logspace, q, n_cand)
    if has_q is True:
        ll_b = gmm_logpdf_quant_pre(samples, pre_b, low, high, logspace, q)
        ll_a = gmm_logpdf_quant_pre(samples, pre_a, low, high, logspace, q)
    elif has_q is False:
        ll_b = gmm_logpdf_cont_pre(samples, pre_b, logspace)
        ll_a = gmm_logpdf_cont_pre(samples, pre_a, logspace)
    else:
        ll_b = jnp.where(
            q > 0,
            gmm_logpdf_quant_pre(samples, pre_b, low, high, logspace, q),
            gmm_logpdf_cont_pre(samples, pre_b, logspace),
        )
        ll_a = jnp.where(
            q > 0,
            gmm_logpdf_quant_pre(samples, pre_a, low, high, logspace, q),
            gmm_logpdf_cont_pre(samples, pre_a, logspace),
        )
    return samples, ll_b - ll_a


def ei_best_cont(key, wb, mb, sb, wa, ma, sa, low, high, logspace, q, n_cand,
                 has_q=None):
    """One continuous dim: draw n_cand from the below-model, score the EI
    log-likelihood ratio, return (best value, best score)."""
    samples, llr = ei_scores_cont(
        key, wb, mb, sb, wa, ma, sa, low, high, logspace, q, n_cand,
        has_q=has_q,
    )
    return samples[jnp.argmax(llr)], jnp.max(llr)


def _ei_sweep_grouped(q_np, consts, cont_keys, fit_arrays, n_cand, kernel):
    """Shared scaffolding of the batched continuous EI sweeps: partition
    dims by *static* ``q > 0`` (``q_np`` is the host numpy q vector) so
    only quantized dims pay the ndtr-heavy bin-mass scoring, run
    ``kernel(key, *fits, *consts, n_cand=, has_q=)`` double-vmapped over
    (trial, dim) per group, and scatter-merge the per-group outputs.
    Every dim lands in exactly one group, so the zero inits never leak.

    At B=1 (the sequential device loop / single-ask latency path) the
    [S, K] grids are tiny and per-kernel overhead dominates, so BOTH
    families run as ONE fused group with traced-``q`` dispatch instead
    -- each dim's selected family computes the same formulas on the
    same per-dim key, so outputs are bitwise identical to the
    partitioned form, at ~0.08 ms/step less (measured, B=1 device loop,
    bench_artifacts/ROOFLINE.md round 5).  Batched calls keep the
    partition: there the grids are large and the saved ndtr FLOPs win.
    """
    B, Dc = cont_keys.shape
    outs = None
    q_np = np.asarray(q_np)
    groups = (
        (False, np.flatnonzero(q_np <= 0)),
        (True, np.flatnonzero(q_np > 0)),
    )
    if B == 1 and all(p.size for _, p in groups):
        groups = ((None, np.arange(len(q_np))),)
    for has_q, pos in groups:
        if pos.size == 0:
            continue
        if pos.size == len(q_np):
            # identity group (the fused B=1 path): indexing runtime
            # arrays with arange emits per-dim gathers, which serialize
            # on TPU and cost more than the fused sweep itself
            grp_fits = tuple(fit_arrays)
            grp_consts = tuple(
                consts[k] for k in ("low", "high", "logspace", "q")
            )
        else:
            grp_fits = tuple(t[pos] for t in fit_arrays)
            grp_consts = tuple(
                consts[k][pos] for k in ("low", "high", "logspace", "q")
            )
        per_dim = jax.vmap(
            lambda k, *a: kernel(k, *a, n_cand=n_cand, has_q=has_q),
            in_axes=(0,) * 11,
        )
        per_batch = jax.vmap(per_dim, in_axes=(0,) + (None,) * 10)
        if B == 1 and pos.size == len(q_np):
            # identity group at B=1 ONLY: single-dim vmap with the batch
            # axis re-attached by broadcast -- the size-1 outer vmap and
            # the arange scatter-merge both lower to serializing ops.
            # At B > 1 this branch would broadcast row-0's keys to every
            # column (regression caught by the atpe lock test).
            res = per_dim(cont_keys[0], *grp_fits, *grp_consts)
            return tuple(r[None] for r in res)
        keys_grp = cont_keys if pos.size == len(q_np) else cont_keys[:, pos]
        res = per_batch(keys_grp, *grp_fits, *grp_consts)
        if pos.size == len(q_np):
            return res
        if outs is None:
            outs = tuple(
                jnp.zeros((B, Dc) + r.shape[2:], r.dtype) for r in res
            )
        outs = tuple(o.at[:, pos].set(r) for o, r in zip(outs, res))
    return outs


def ei_sweep_cont(q_np, consts, cont_keys, fit_arrays, n_cand):
    """Batched continuous EI sweep over all trials x continuous dims.

    The single shared implementation of the candidate sweep used by both
    the single-device (:mod:`hyperopt_tpu.tpe_jax`) and mesh-sharded
    (:mod:`hyperopt_tpu.parallel.sharded`) suggest builders.

    Args:
      q_np: host [Dc] numpy array of quantizations (static).
      consts: PackedSpace._consts dict (needs low/high/logspace/q).
      cont_keys: [B, Dc] PRNG keys.
      fit_arrays: (wb, mb, sb, wa, ma, sa), leading dim Dc.
      n_cand: candidates per (trial, dim) (static).

    Returns (vals, scores): each [B, Dc], in cont-dim order.
    """
    return _ei_sweep_grouped(
        q_np, consts, cont_keys, fit_arrays, n_cand, ei_best_cont
    )


def ei_sweep_cat(cat_keys, pb, pa, n_cand):
    """Batched categorical EI sweep: [B, Dk] keys x [Dk, K] posteriors ->
    (vals, scores) each [B, Dk] (values are category indices as floats,
    before int_low offset)."""
    per_cat = jax.vmap(
        lambda k, b, a: ei_best_cat(k, b, a, n_cand=n_cand),
        in_axes=(0, 0, 0),
    )
    per_batch = jax.vmap(per_cat, in_axes=(0, None, None))
    return per_batch(cat_keys, pb, pa)


def ei_sweep_cont_scores(q_np, consts, cont_keys, fit_arrays, n_cand):
    """Per-candidate form of :func:`ei_sweep_cont` for the joint-EI path:
    returns (vals, llrs) each [B, Dc, S] -- every candidate's value and
    EI log-likelihood ratio, no per-dim argmax."""
    return _ei_sweep_grouped(
        q_np, consts, cont_keys, fit_arrays, n_cand, ei_scores_cont
    )


def ei_sweep_cat_scores(cat_keys, pb, pa, n_cand):
    """Per-candidate form of :func:`ei_sweep_cat` for the joint-EI path:
    (vals, llrs) each [B, Dk, S]."""
    per_cat = jax.vmap(
        lambda k, b, a: ei_scores_cat(k, b, a, n_cand=n_cand),
        in_axes=(0, 0, 0),
    )
    per_batch = jax.vmap(per_cat, in_axes=(0, None, None))
    return per_batch(cat_keys, pb, pa)


def ei_best_cat(key, p_below, p_above, n_cand):
    """One categorical dim: draw candidate categories from the below
    posterior, score log p_b - log p_a, return (best index, best score).

    Equivalent to scoring each drawn candidate and taking the argmax:
    the winner is the category with the highest llr among those *hit* by
    any draw, so only the [S, K] hit mask is needed -- no per-sample
    gathers.
    """
    u = jax.random.uniform(key, (n_cand,), dtype=p_below.dtype)
    onehot = _inverse_cdf_onehot(u, jnp.cumsum(jnp.maximum(p_below, 0.0)))
    # hit counts via an [1, S] x [S, K] contraction -- measured faster
    # than the elementwise any-reduction under the (trial, dim) vmap
    hit = jnp.matmul(
        jnp.ones((1, n_cand), onehot.dtype), onehot
    )[0] > 0  # [K]
    # padded options (p_below == 0) must never win the argmax
    llr = jnp.where(
        p_below > 0, _safe_log(p_below) - _safe_log(p_above), -jnp.inf
    )
    best = jnp.argmax(jnp.where(hit, llr, -jnp.inf))
    return best.astype(jnp.float32), llr[best]


def ei_scores_cat(key, p_below, p_above, n_cand):
    """One categorical dim, per-candidate form for the joint-EI path:
    draw n_cand categories from the below posterior and return
    (category indices [S] as floats, llr [S]).  Index and llr come out of
    one exact [S, K] x [K, 2] contraction against the one-hot pick."""
    u = jax.random.uniform(key, (n_cand,), dtype=p_below.dtype)
    onehot = _inverse_cdf_onehot(u, jnp.cumsum(jnp.maximum(p_below, 0.0)))
    llr_k = jnp.where(
        p_below > 0, _safe_log(p_below) - _safe_log(p_above), 0.0
    )  # zero-weight options are never drawn; 0 keeps the matmul finite
    k = p_below.shape[0]
    table = jnp.stack(
        [jnp.arange(k, dtype=p_below.dtype), llr_k], axis=-1
    )  # [K, 2]
    picked = jnp.matmul(onehot, table, precision=jax.lax.Precision.HIGHEST)
    return picked[:, 0], picked[:, 1]
