"""Pallas TPU kernel for the TPE hot op: batched GMM log-density scoring.

The suggest step's FLOPs live in scoring S candidates against K mixture
components for every (dimension) row -- an [R, S, K] logsumexp with
per-row components.  The XLA path recomputes the [S, K] terms for its
max and sum passes; this kernel streams the component axis through VMEM
in 128-lane chunks with an online (flash-style) logsumexp, one pass over
the terms, while the (row-block, sample-tile) grid pipelines HBM->VMEM
copies against VPU work (pallas_guide.md: grids+BlockSpec, fori_loop,
online reductions).

TPU tiling: rows are processed 8 at a time (sublane width) and samples
in 512-wide tiles (lane-aligned), so every block shape is (8, *) with a
last dimension divisible by 128 -- the layout the Mosaic lowering
requires.  ``pad_rows`` / ``pad_components`` provide the padding.

Exposed as ``ei_scores(...)`` = log l(x) - log g(x) for the continuous
(unquantized) family; quantized/categorical dims stay on the XLA path.
``interpret=True`` runs the same kernel on CPU for tests.

Measured on a TPU v5e chip (round 1): this kernel scores 16 x 524k x 640
terms in ~52-70 ms, while the XLA scorer in :mod:`.kernels`
(static-shift single pass, compiler-fused) does the same work in ~29 ms
-- XLA's fusion wins for this elementwise+reduction shape, so the
production suggest path stays on XLA and this kernel is kept as the
verified VMEM-streaming alternative (useful as a template for ops XLA
fuses poorly).

VERDICT (round 2, measured -- the claim is retired): no op in this
workload has a profile Pallas can win.  Stage decomposition of the
B=4096 suggest program on chip: Parzen fits 5 ms, categorical sweep
6 ms, continuous sweep 40 ms, of which the above-model scoring --
the single hottest op, [4096 x 14 x 128 x 513] fused
mul/sub/exp/sum/max terms -- runs at ~212 Gterm/s (~1.6+ TFLOP/s
effective at ~8 VPU ops + exp per term), i.e. VPU-COMPUTE-bound.
Its HBM traffic is negligible (inputs are [Dc, K] mixture constants
and [B, S] latents; the term tensor never materializes thanks to XLA
fusion), so Pallas's levers -- explicit VMEM streaming, layout
control, HBM pipelining -- have nothing to buy: round 1's kernel
lost 2x by re-deriving what the fusion already does.  The algorithmic
alternative (grid-tabulated above-model log-density shared across the
batch) was also built and measured 2x slower -- per-candidate table
lookups are gathers, which serialize on TPU (DESIGN.md SS3 has both
tables).  This module stays as the working Pallas template +
regression test for a future op with the right profile (gather-heavy
or fusion-hostile), none of which this framework currently contains.
"""

from __future__ import annotations

import functools

import numpy as np

__all__ = ["gmm_logpdf_rows", "ei_scores", "pad_components", "pad_rows"]

_LOG_SQRT_2PI = 0.9189385332046727  # log(sqrt(2*pi))
LANE = 128
SUBLANE = 8
S_TILE = 512


def pad_components(w, mu, sigma, log_mass, lane=LANE):
    """Zero-weight-pad the component axis to a multiple of ``lane``."""
    import jax.numpy as jnp

    k = w.shape[-1]
    pad = (-k) % lane
    if pad == 0:
        return w, mu, sigma, log_mass
    pw = [(0, 0)] * (w.ndim - 1) + [(0, pad)]
    return (
        jnp.pad(w, pw),                      # weight 0 -> masked out
        jnp.pad(mu, pw),
        jnp.pad(sigma, pw, constant_values=1.0),
        jnp.pad(log_mass, pw),
    )


def pad_rows(x, sublane=SUBLANE, constant_values=0.0):
    """Pad the row axis to a multiple of the sublane width.

    Pass ``constant_values=1.0`` for sigma-like arrays the kernel takes a
    log of -- zero-padded rows would produce NaNs in-kernel."""
    import jax.numpy as jnp

    r = x.shape[0]
    pad = (-r) % sublane
    if pad == 0:
        return x
    return jnp.pad(
        x, [(0, pad)] + [(0, 0)] * (x.ndim - 1),
        constant_values=constant_values,
    )


def _gmm_rows_kernel(x_ref, w_ref, mu_ref, sig_ref, lm_ref, out_ref):
    """One grid cell: out[8, T] = logsumexp_k(log w_k + logN(x | mu_k,
    sig_k) - log_mass_k) for an 8-row block and a T-sample tile.

    Streams K in 128-lane chunks with an online max/accumulator pair;
    the [8, T, 128] term tensor lives only for one chunk.
    """
    import jax
    import jax.numpy as jnp
    from jax.experimental import pallas as pl

    T = x_ref.shape[1]
    K = w_ref.shape[1]
    x = x_ref[...]  # [8, T]

    def chunk(i, carry):
        m, acc = carry  # running max / running sum, each [8, T]
        sl = pl.ds(i * LANE, LANE)
        w = w_ref[:, sl]      # [8, 128]
        mu = mu_ref[:, sl]
        sig = sig_ref[:, sl]
        lm = lm_ref[:, sl]
        logw = jnp.where(w > 0, jnp.log(jnp.maximum(w, 1e-30)), -jnp.inf)
        c1 = logw - jnp.log(sig) - lm - _LOG_SQRT_2PI  # [8, 128]
        z = (x[:, :, None] - mu[:, None, :]) / sig[:, None, :]  # [8, T, 128]
        t = c1[:, None, :] - 0.5 * z * z
        tmax = jnp.max(t, axis=2)  # [8, T]
        m_new = jnp.maximum(m, tmax)
        safe = jnp.isfinite(m_new)
        scale = jnp.where(
            jnp.isfinite(m), jnp.exp(jnp.minimum(m - m_new, 0.0)), 0.0
        )
        add = jnp.where(
            safe,
            jnp.sum(
                jnp.exp(t - jnp.where(safe, m_new, 0.0)[:, :, None]), axis=2
            ),
            0.0,
        )
        return m_new, acc * scale + add

    m0 = jnp.full(x.shape, -jnp.inf, dtype=jnp.float32)
    a0 = jnp.zeros(x.shape, dtype=jnp.float32)
    m, acc = jax.lax.fori_loop(0, K // LANE, chunk, (m0, a0))
    out_ref[...] = m + jnp.log(jnp.maximum(acc, 1e-30))


@functools.lru_cache(maxsize=32)
def _build_rows_call(R, S, K, s_tile, interpret):
    import jax
    from jax.experimental import pallas as pl
    from jax.experimental.pallas import tpu as pltpu

    xs_map = lambda r, s: (r, s)
    comp_map = lambda r, s: (r, 0)
    call = pl.pallas_call(
        _gmm_rows_kernel,
        grid=(R // SUBLANE, S // s_tile),
        in_specs=[
            pl.BlockSpec((SUBLANE, s_tile), xs_map, memory_space=pltpu.VMEM),
            pl.BlockSpec((SUBLANE, K), comp_map, memory_space=pltpu.VMEM),
            pl.BlockSpec((SUBLANE, K), comp_map, memory_space=pltpu.VMEM),
            pl.BlockSpec((SUBLANE, K), comp_map, memory_space=pltpu.VMEM),
            pl.BlockSpec((SUBLANE, K), comp_map, memory_space=pltpu.VMEM),
        ],
        out_specs=pl.BlockSpec((SUBLANE, s_tile), xs_map,
                               memory_space=pltpu.VMEM),
        out_shape=jax.ShapeDtypeStruct((R, S), jax.numpy.float32),
        interpret=bool(interpret),
    )
    return call


def gmm_logpdf_rows(x, w, mu, sigma, log_mass, interpret=False):
    """Batched truncated-GMM log-density (latent space, unquantized).

    Args:
      x: [R, S] latent-space sample rows (one row per dimension; a batch
        of trials flattens its candidates into the row).
      w/mu/sigma/log_mass: [R, K] per-row mixture components.
    Rows are padded to a multiple of 8, K to a multiple of 128, and S
    must divide by a 128-multiple tile (padded here if needed).
    Returns [R, S] log-densities (without the log-space jacobian, which
    the caller applies -- it does not depend on the mixture).
    """
    import jax.numpy as jnp

    R, S = x.shape
    w, mu, sigma, log_mass = pad_components(w, mu, sigma, log_mass)
    x = pad_rows(x)
    w, mu, log_mass = pad_rows(w), pad_rows(mu), pad_rows(log_mass)
    sigma = pad_rows(sigma, constant_values=1.0)  # log(sig) in-kernel
    s_tile = S_TILE if S % S_TILE == 0 else LANE
    s_pad = (-S) % s_tile
    if s_pad:
        x = jnp.pad(x, [(0, 0), (0, s_pad)])
    call = _build_rows_call(
        x.shape[0], x.shape[1], w.shape[1], s_tile, interpret
    )
    out = call(
        x.astype(jnp.float32),
        w.astype(jnp.float32),
        mu.astype(jnp.float32),
        sigma.astype(jnp.float32),
        log_mass.astype(jnp.float32),
    )
    return out[:R, :S]


def ei_scores(x_lat, below, above, interpret=False):
    """EI log-likelihood-ratio scores for candidate rows.

    ``below``/``above`` are (w, mu, sigma, log_mass) tuples of [R, K]
    arrays; returns [R, S] of ``log l(x) - log g(x)`` (the jacobian terms
    cancel between numerator and denominator).
    """
    ll_b = gmm_logpdf_rows(x_lat, *below, interpret=interpret)
    ll_a = gmm_logpdf_rows(x_lat, *above, interpret=interpret)
    return ll_b - ll_a


# ---------------------------------------------------------------------------
# graftir registration (hyperopt-tpu-lint --ir)
# ---------------------------------------------------------------------------

from .compile import ProgramCapture, register_program  # noqa: E402


@register_program(
    "pallas.ei_scores",
    families=(
        "hyperopt_tpu.ops.pallas_kernels:ei_scores",
        "hyperopt_tpu.ops.pallas_kernels:gmm_logpdf_rows",
    ),
)
def _registry_pallas_ei_scores(p):
    """The Pallas GMM-scoring kernel pair, traced in interpret mode so
    the pallas_call lowers on CPU; the jaxpr (and the VMEM-streaming
    structure it wraps) is the same object Mosaic lowers on TPU."""
    import jax
    import jax.numpy as jnp

    R, S, K = 8, 128, 128
    comp = tuple(
        jax.ShapeDtypeStruct((R, K), jnp.float32) for _ in range(4)
    )
    fn = jax.jit(functools.partial(ei_scores, interpret=True))
    return ProgramCapture(
        fn=fn,
        args=(jax.ShapeDtypeStruct((R, S), jnp.float32), comp, comp),
    )
