"""Pallas TPU kernel for the TPE hot op: batched GMM log-density scoring.

The suggest step's FLOPs live in scoring S candidates against K mixture
components for every (trial x dimension) row -- an [R, S, K] logsumexp.
The XLA path materializes [S, K] score matrices per row; this kernel
streams the component axis through VMEM in 128-wide chunks with an online
(flash-style) logsumexp, so VMEM pressure is O(S + 128) per row instead
of O(S*K), and the row grid pipelines HBM->VMEM copies against VPU work
(pallas_guide.md: grids+BlockSpec, fori_loop, online reductions).

Exposed as ``ei_scores(...)`` = log l(x) - log g(x) for the continuous
(unquantized) family; quantized/categorical dims stay on the XLA path.
``interpret=True`` runs the same kernel on CPU for tests.
"""

from __future__ import annotations

import functools

import numpy as np

__all__ = ["gmm_logpdf_rows", "ei_scores", "pad_components"]

_LOG_SQRT_2PI = 0.9189385332046727  # log(sqrt(2*pi))
LANE = 128


def pad_components(w, mu, sigma, log_mass, lane=LANE):
    """Zero-weight-pad the component axis to a multiple of ``lane``."""
    import jax.numpy as jnp

    k = w.shape[-1]
    pad = (-k) % lane
    if pad == 0:
        return w, mu, sigma, log_mass
    pw = [(0, 0)] * (w.ndim - 1) + [(0, pad)]
    return (
        jnp.pad(w, pw),                      # weight 0 -> masked out
        jnp.pad(mu, pw),
        jnp.pad(sigma, pw, constant_values=1.0),
        jnp.pad(log_mass, pw),
    )


def _gmm_row_kernel(x_ref, w_ref, mu_ref, sig_ref, lm_ref, out_ref):
    """One grid row: out[1, S] = logsumexp_k(log w_k + N(x | mu_k, sig_k)).

    Streams K in 128-lane chunks with an online max/accumulator pair.
    """
    import jax
    import jax.numpy as jnp
    from jax.experimental import pallas as pl

    S = x_ref.shape[1]
    K = w_ref.shape[1]
    x = x_ref[0, :]  # [S]

    def chunk(i, carry):
        m, acc = carry  # running max [S], running sum [S]
        sl = pl.ds(i * LANE, LANE)
        w = w_ref[0, sl]
        mu = mu_ref[0, sl]
        sig = sig_ref[0, sl]
        lm = lm_ref[0, sl]
        logw = jnp.where(w > 0, jnp.log(jnp.maximum(w, 1e-30)), -jnp.inf)
        z = (x[:, None] - mu[None, :]) / sig[None, :]  # [S, 128]
        t = (
            (logw - jnp.log(sig) - lm)[None, :]
            - 0.5 * z * z
            - _LOG_SQRT_2PI
        )
        tmax = jnp.max(t, axis=1)
        m_new = jnp.maximum(m, tmax)
        safe = jnp.isfinite(m_new)
        scale = jnp.where(
            jnp.isfinite(m), jnp.exp(jnp.minimum(m - m_new, 0.0)), 0.0
        )
        add = jnp.where(
            safe,
            jnp.sum(jnp.exp(t - jnp.where(safe, m_new, 0.0)[:, None]), axis=1),
            0.0,
        )
        return m_new, acc * scale + add

    m0 = jnp.full((S,), -jnp.inf, dtype=jnp.float32)
    a0 = jnp.zeros((S,), dtype=jnp.float32)
    m, acc = jax.lax.fori_loop(0, K // LANE, chunk, (m0, a0))
    out_ref[0, :] = m + jnp.log(jnp.maximum(acc, 1e-30))


@functools.lru_cache(maxsize=32)
def _build_rows_call(R, S, K, interpret):
    import jax
    from jax.experimental import pallas as pl
    from jax.experimental.pallas import tpu as pltpu

    row = lambda r: (r, 0)
    call = pl.pallas_call(
        _gmm_row_kernel,
        grid=(R,),
        in_specs=[
            pl.BlockSpec((1, S), row, memory_space=pltpu.VMEM),
            pl.BlockSpec((1, K), row, memory_space=pltpu.VMEM),
            pl.BlockSpec((1, K), row, memory_space=pltpu.VMEM),
            pl.BlockSpec((1, K), row, memory_space=pltpu.VMEM),
            pl.BlockSpec((1, K), row, memory_space=pltpu.VMEM),
        ],
        out_specs=pl.BlockSpec((1, S), row, memory_space=pltpu.VMEM),
        out_shape=jax.ShapeDtypeStruct((R, S), jax.numpy.float32),
        interpret=bool(interpret),
    )
    return call


def gmm_logpdf_rows(x, w, mu, sigma, log_mass, interpret=False):
    """Batched truncated-GMM log-density (latent space, unquantized).

    Args:
      x: [R, S] latent-space sample rows.
      w/mu/sigma/log_mass: [R, K] per-row mixture components (K padded to
        a multiple of 128; ``pad_components`` does this).
    Returns [R, S] log-densities (without the log-space jacobian, which
    the caller applies -- it does not depend on the mixture).
    """
    import jax.numpy as jnp

    w, mu, sigma, log_mass = pad_components(w, mu, sigma, log_mass)
    R, S = x.shape
    K = w.shape[1]
    call = _build_rows_call(R, S, K, interpret)
    return call(
        x.astype(jnp.float32),
        w.astype(jnp.float32),
        mu.astype(jnp.float32),
        sigma.astype(jnp.float32),
        log_mass.astype(jnp.float32),
    )


def ei_scores(x_lat, below, above, interpret=False):
    """EI log-likelihood-ratio scores for candidate rows.

    ``below``/``above`` are (w, mu, sigma, log_mass) tuples of [R, K]
    arrays; returns [R, S] of ``log l(x) - log g(x)`` (the jacobian terms
    cancel between numerator and denominator).
    """
    ll_b = gmm_logpdf_rows(x_lat, *below, interpret=interpret)
    ll_a = gmm_logpdf_rows(x_lat, *above, interpret=interpret)
    return ll_b - ll_a
