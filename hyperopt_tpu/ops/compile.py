"""The space compiler: ``hp.*`` pyll graph -> one jitted stochastic program.

Replaces the reference's interpreted per-trial ``pyll.rec_eval`` sampling
(SURVEY.md SS3.3) with a TPU-first design (SS7 stance #1): the space is
*compiled once* into a ``PackedSpace`` -- flat per-dimension parameter
arrays -- and sampling a batch of n trials is a single XLA program emitting
dense ``[D, n]`` values plus an active-mask.  Ragged idxs/vals encoding is
reconstructed only at the API boundary (``vectorize.dense_to_idxs_vals``).

Conditional (``hp.choice``) structure compiles to padded condition tables:
``active[d] = OR_a AND_c (values[cond_dim[d,a,c]] == cond_val[d,a,c])`` --
pure elementwise work, no control flow, so nested choice spaces
(NAS-Bench-style) jit cleanly.
"""

from __future__ import annotations

import functools

import numpy as np

from ..exceptions import CompileError
from ..pyll.base import as_apply
from ..pyll_utils import expr_to_config

__all__ = ["PackedSpace", "compile_space"]

_CONT_DISTS = {
    "uniform": (False, False),  # (logspace, quantized)
    "quniform": (False, True),
    "loguniform": (True, False),
    "qloguniform": (True, True),
    "normal": (False, False),
    "qnormal": (False, True),
    "lognormal": (True, False),
    "qlognormal": (True, True),
}
_CAT_DISTS = {"randint", "categorical", "randint_via_categorical"}


class PackedSpace:
    """Flat array encoding of a search space (host numpy; device-ready).

    Continuous dims are parameterized in *latent* space (log-space dists
    fit/sample on log values): ``low/high`` latent bounds (+-inf if
    unbounded), ``prior_mu/prior_sigma`` the TPE prior component, ``q``
    natural-space quantization (0 = none).  Categorical dims carry a
    zero-padded prior pmf and an integer offset (for ``hp.randint(low,
    high)``).  Condition tables encode hp.choice activation (see module
    docstring).
    """

    def __init__(self, labels, hps):
        self.labels = labels
        self.hps = hps
        D = len(labels)
        self.n_dims = D
        idx = {label: d for d, label in enumerate(labels)}

        kind = np.zeros(D, dtype=np.int32)
        cont, cat = [], []
        for d, label in enumerate(labels):
            dist = hps[label].dist
            if dist in _CONT_DISTS:
                cont.append(d)
            elif dist in _CAT_DISTS:
                cat.append(d)
            else:
                raise CompileError(f"cannot compile distribution {dist!r}")
        kind[cat] = 1
        self.kind = kind
        self.cont_idx = np.asarray(cont, dtype=np.int32)
        self.cat_idx = np.asarray(cat, dtype=np.int32)

        # -- continuous dim params (latent space) -------------------------
        Dc = len(cont)
        self.low = np.full(Dc, -np.inf, dtype=np.float32)
        self.high = np.full(Dc, np.inf, dtype=np.float32)
        self.prior_mu = np.zeros(Dc, dtype=np.float32)
        self.prior_sigma = np.ones(Dc, dtype=np.float32)
        self.logspace = np.zeros(Dc, dtype=bool)
        self.q = np.zeros(Dc, dtype=np.float32)
        for i, d in enumerate(cont):
            info = hps[labels[d]]
            p = info.params
            logspace, quantized = _CONT_DISTS[info.dist]
            self.logspace[i] = logspace
            if quantized:
                qv = p.get("q")
                if not isinstance(qv, (int, float)):
                    raise CompileError(
                        f"{info.label}: q must be a literal number, got {qv!r}"
                    )
                self.q[i] = float(qv)
            if info.dist in ("uniform", "quniform", "loguniform", "qloguniform"):
                lo, hi = p["low"], p["high"]
                if not isinstance(lo, (int, float)) or not isinstance(hi, (int, float)):
                    raise CompileError(
                        f"{info.label}: bounds must be literal numbers"
                    )
                self.low[i], self.high[i] = float(lo), float(hi)
                self.prior_mu[i] = 0.5 * (float(lo) + float(hi))
                self.prior_sigma[i] = float(hi) - float(lo)
            else:
                mu, sg = p["mu"], p["sigma"]
                if not isinstance(mu, (int, float)) or not isinstance(sg, (int, float)):
                    raise CompileError(
                        f"{info.label}: mu/sigma must be literal numbers"
                    )
                self.prior_mu[i], self.prior_sigma[i] = float(mu), float(sg)

        # -- categorical dim params ---------------------------------------
        Dk = len(cat)
        n_opts = []
        int_low = []
        priors = []
        for d in cat:
            info = hps[labels[d]]
            p = info.params
            if info.dist == "randint":
                lo = int(p["low"])
                hi = int(p["high"])
                n_opts.append(hi - lo)
                int_low.append(lo)
                priors.append(np.full(hi - lo, 1.0 / (hi - lo)))
            else:
                pm = np.asarray(p["p"], dtype=np.float64)
                n_opts.append(len(pm))
                int_low.append(0)
                priors.append(pm / pm.sum())
        self.k_max = max(n_opts, default=1)
        self.n_options = np.asarray(n_opts, dtype=np.int32)
        self.int_low = np.asarray(int_low, dtype=np.int32)
        self.prior_p = np.zeros((Dk, self.k_max), dtype=np.float32)
        for i, pm in enumerate(priors):
            self.prior_p[i, : len(pm)] = pm

        # -- condition tables ---------------------------------------------
        a_max = max((len(hps[l].conditions) for l in labels), default=1) or 1
        c_max = max(
            (len(conj) for l in labels for conj in hps[l].conditions), default=1
        ) or 1
        self.a_max, self.c_max = a_max, c_max
        self.alt_mask = np.zeros((D, a_max), dtype=bool)
        self.term_mask = np.zeros((D, a_max, c_max), dtype=bool)
        self.cond_dim = np.zeros((D, a_max, c_max), dtype=np.int32)
        self.cond_val = np.zeros((D, a_max, c_max), dtype=np.float32)
        for d, label in enumerate(labels):
            conds = sorted(hps[label].conditions) or [()]
            for a, conj in enumerate(conds):
                self.alt_mask[d, a] = True
                for c, term in enumerate(conj):
                    if term.name not in idx:
                        raise CompileError(
                            f"condition on unknown label {term.name!r}"
                        )
                    self.term_mask[d, a, c] = True
                    self.cond_dim[d, a, c] = idx[term.name]
                    self.cond_val[d, a, c] = float(term.val)

        self.unconditional = bool(
            all(hps[l].unconditional for l in labels)
        )

    # -- device-side programs ---------------------------------------------
    @functools.cached_property
    def _consts(self):
        """Device-resident constants (built lazily, after conftest env).

        Materialized OUTSIDE any jit trace (callers touch this property
        eagerly before tracing) -- a cached_property filled during a trace
        would cache tracers and leak them into later programs.
        """
        import jax
        import jax.numpy as jnp

        with jax.ensure_compile_time_eval():
            return {
                k: jnp.asarray(getattr(self, k))
                for k in (
                    "low", "high", "prior_mu", "prior_sigma", "logspace", "q",
                    "prior_p", "int_low", "n_options",
                    "alt_mask", "term_mask", "cond_dim", "cond_val",
                    "cont_idx", "cat_idx",
                )
            }

    def active_fn(self, values):
        """[D, n] dense values -> [D, n] active mask (pure jnp; jittable)."""
        import jax.numpy as jnp

        c = self._consts
        if self.unconditional:
            return jnp.ones(values.shape, dtype=bool)
        gathered = values[c["cond_dim"]]  # [D, A, C, n]
        eq = jnp.abs(gathered - c["cond_val"][..., None]) < 0.5
        term_ok = eq | ~c["term_mask"][..., None]
        conj = jnp.all(term_ok, axis=2) & c["alt_mask"][..., None]
        return jnp.any(conj, axis=1)

    def sample_prior_fn(self, key, n):
        """Jit-traceable: draw n prior configs -> (values [D,n], active [D,n]).

        Continuous dims: bounded dims draw uniform in latent space, normal
        dims draw mu + sigma*z; log-space dims exponentiate; quantized dims
        round in natural space.  Categorical dims: Gumbel/categorical over
        the padded prior pmf.
        """
        import jax
        import jax.numpy as jnp

        c = self._consts
        D = self.n_dims
        Dc = len(self.cont_idx)
        Dk = len(self.cat_idx)
        ku, kz, kc = jax.random.split(key, 3)
        values = jnp.zeros((D, n), dtype=jnp.float32)

        if Dc:
            low, high = c["low"][:, None], c["high"][:, None]
            bounded = jnp.isfinite(low)
            u = jax.random.uniform(ku, (Dc, n), dtype=jnp.float32)
            z = jax.random.normal(kz, (Dc, n), dtype=jnp.float32)
            lat = jnp.where(
                bounded,
                low + u * (high - low),
                c["prior_mu"][:, None] + c["prior_sigma"][:, None] * z,
            )
            from .kernels import quantize_nat

            nat = jnp.where(c["logspace"][:, None], jnp.exp(lat), lat)
            nat = quantize_nat(
                nat, c["q"][:, None], low, high, c["logspace"][:, None]
            )
            values = values.at[c["cont_idx"]].set(nat)

        if Dk:
            logits = jnp.where(
                c["prior_p"] > 0, jnp.log(jnp.maximum(c["prior_p"], 1e-30)), -jnp.inf
            )
            draws = jax.random.categorical(
                kc, logits[:, None, :], axis=-1, shape=(Dk, n)
            )
            values = values.at[c["cat_idx"]].set(
                draws.astype(jnp.float32) + c["int_low"][:, None]
            )

        return values, self.active_fn(values)

    @functools.cached_property
    def sample_prior(self):
        """Jitted ``(key, n) -> (values, active)`` with static n."""
        import jax

        _ = self._consts  # materialize constants outside the trace
        return jax.jit(self.sample_prior_fn, static_argnums=(1,))

    def __repr__(self):
        return (
            f"PackedSpace(D={self.n_dims}, cont={len(self.cont_idx)}, "
            f"cat={len(self.cat_idx)}, k_max={self.k_max}, "
            f"conditional={not self.unconditional})"
        )


def compile_space(space):
    """Compile an hp-annotated space (pyll graph or pytree of graphs) into
    a :class:`PackedSpace`."""
    expr = as_apply(space)
    hps = expr_to_config(expr)
    labels = sorted(hps)
    if not labels:
        raise CompileError("space has no hyperparameters")
    return PackedSpace(labels, hps)
