"""The space compiler: ``hp.*`` pyll graph -> one jitted stochastic program.

Replaces the reference's interpreted per-trial ``pyll.rec_eval`` sampling
(SURVEY.md SS3.3) with a TPU-first design (SS7 stance #1): the space is
*compiled once* into a ``PackedSpace`` -- flat per-dimension parameter
arrays -- and sampling a batch of n trials is a single XLA program emitting
dense ``[D, n]`` values plus an active-mask.  Ragged idxs/vals encoding is
reconstructed only at the API boundary (``vectorize.dense_to_idxs_vals``).

Conditional (``hp.choice``) structure compiles to padded condition tables:
``active[d] = OR_a AND_c (values[cond_dim[d,a,c]] == cond_val[d,a,c])`` --
pure elementwise work, no control flow, so nested choice spaces
(NAS-Bench-style) jit cleanly.
"""

from __future__ import annotations

import dataclasses
import functools
import importlib
import os

import numpy as np

from ..exceptions import CompileError
from ..pyll.base import as_apply
from ..pyll_utils import expr_to_config

__all__ = [
    "PackedSpace",
    "ProgramCapture",
    "ProgramParams",
    "ProgramSpec",
    "compile_space",
    "program_family",
    "reference_space",
    "register_program",
    "registered_programs",
]

_CONT_DISTS = {
    "uniform": (False, False),  # (logspace, quantized)
    "quniform": (False, True),
    "loguniform": (True, False),
    "qloguniform": (True, True),
    "normal": (False, False),
    "qnormal": (False, True),
    "lognormal": (True, False),
    "qlognormal": (True, True),
}
_CAT_DISTS = {"randint", "categorical", "randint_via_categorical"}


class PackedSpace:
    """Flat array encoding of a search space (host numpy; device-ready).

    Continuous dims are parameterized in *latent* space (log-space dists
    fit/sample on log values): ``low/high`` latent bounds (+-inf if
    unbounded), ``prior_mu/prior_sigma`` the TPE prior component, ``q``
    natural-space quantization (0 = none).  Categorical dims carry a
    zero-padded prior pmf and an integer offset (for ``hp.randint(low,
    high)``).  Condition tables encode hp.choice activation (see module
    docstring).
    """

    def __init__(self, labels, hps):
        self.labels = labels
        self.hps = hps
        D = len(labels)
        self.n_dims = D
        idx = {label: d for d, label in enumerate(labels)}

        kind = np.zeros(D, dtype=np.int32)
        cont, cat = [], []
        for d, label in enumerate(labels):
            dist = hps[label].dist
            if dist in _CONT_DISTS:
                cont.append(d)
            elif dist in _CAT_DISTS:
                cat.append(d)
            else:
                raise CompileError(f"cannot compile distribution {dist!r}")
        kind[cat] = 1
        self.kind = kind
        self.cont_idx = np.asarray(cont, dtype=np.int32)
        self.cat_idx = np.asarray(cat, dtype=np.int32)

        # -- continuous dim params (latent space) -------------------------
        Dc = len(cont)
        self.low = np.full(Dc, -np.inf, dtype=np.float32)
        self.high = np.full(Dc, np.inf, dtype=np.float32)
        self.prior_mu = np.zeros(Dc, dtype=np.float32)
        self.prior_sigma = np.ones(Dc, dtype=np.float32)
        self.logspace = np.zeros(Dc, dtype=bool)
        self.q = np.zeros(Dc, dtype=np.float32)
        for i, d in enumerate(cont):
            info = hps[labels[d]]
            p = info.params
            logspace, quantized = _CONT_DISTS[info.dist]
            self.logspace[i] = logspace
            if quantized:
                qv = p.get("q")
                if not isinstance(qv, (int, float)):
                    raise CompileError(
                        f"{info.label}: q must be a literal number, got {qv!r}"
                    )
                self.q[i] = float(qv)
            if info.dist in ("uniform", "quniform", "loguniform", "qloguniform"):
                lo, hi = p["low"], p["high"]
                if not isinstance(lo, (int, float)) or not isinstance(hi, (int, float)):
                    raise CompileError(
                        f"{info.label}: bounds must be literal numbers"
                    )
                self.low[i], self.high[i] = float(lo), float(hi)
                self.prior_mu[i] = 0.5 * (float(lo) + float(hi))
                self.prior_sigma[i] = float(hi) - float(lo)
            else:
                mu, sg = p["mu"], p["sigma"]
                if not isinstance(mu, (int, float)) or not isinstance(sg, (int, float)):
                    raise CompileError(
                        f"{info.label}: mu/sigma must be literal numbers"
                    )
                self.prior_mu[i], self.prior_sigma[i] = float(mu), float(sg)

        # -- categorical dim params ---------------------------------------
        Dk = len(cat)
        n_opts = []
        int_low = []
        priors = []
        for d in cat:
            info = hps[labels[d]]
            p = info.params
            if info.dist == "randint":
                lo = int(p["low"])
                hi = int(p["high"])
                n_opts.append(hi - lo)
                int_low.append(lo)
                priors.append(np.full(hi - lo, 1.0 / (hi - lo)))
            else:
                pm = np.asarray(p["p"], dtype=np.float64)
                n_opts.append(len(pm))
                int_low.append(0)
                priors.append(pm / pm.sum())
        self.k_max = max(n_opts, default=1)
        self.n_options = np.asarray(n_opts, dtype=np.int32)
        self.int_low = np.asarray(int_low, dtype=np.int32)
        self.prior_p = np.zeros((Dk, self.k_max), dtype=np.float32)
        for i, pm in enumerate(priors):
            self.prior_p[i, : len(pm)] = pm

        # -- condition tables ---------------------------------------------
        a_max = max((len(hps[l].conditions) for l in labels), default=1) or 1
        c_max = max(
            (len(conj) for l in labels for conj in hps[l].conditions), default=1
        ) or 1
        self.a_max, self.c_max = a_max, c_max
        self.alt_mask = np.zeros((D, a_max), dtype=bool)
        self.term_mask = np.zeros((D, a_max, c_max), dtype=bool)
        self.cond_dim = np.zeros((D, a_max, c_max), dtype=np.int32)
        self.cond_val = np.zeros((D, a_max, c_max), dtype=np.float32)
        for d, label in enumerate(labels):
            conds = sorted(hps[label].conditions) or [()]
            for a, conj in enumerate(conds):
                self.alt_mask[d, a] = True
                for c, term in enumerate(conj):
                    if term.name not in idx:
                        raise CompileError(
                            f"condition on unknown label {term.name!r}"
                        )
                    self.term_mask[d, a, c] = True
                    self.cond_dim[d, a, c] = idx[term.name]
                    self.cond_val[d, a, c] = float(term.val)

        self.unconditional = bool(
            all(hps[l].unconditional for l in labels)
        )

    # -- device-side programs ---------------------------------------------
    @functools.cached_property
    def _consts(self):
        """Device-resident constants (built lazily, after conftest env).

        Materialized OUTSIDE any jit trace (callers touch this property
        eagerly before tracing) -- a cached_property filled during a trace
        would cache tracers and leak them into later programs.
        """
        import jax
        import jax.numpy as jnp

        with jax.ensure_compile_time_eval():
            return {
                k: jnp.asarray(getattr(self, k))
                for k in (
                    "low", "high", "prior_mu", "prior_sigma", "logspace", "q",
                    "prior_p", "int_low", "n_options",
                    "alt_mask", "term_mask", "cond_dim", "cond_val",
                    "cont_idx", "cat_idx",
                )
            }

    def active_fn(self, values):
        """[D, n] dense values -> [D, n] active mask (pure jnp; jittable)."""
        import jax.numpy as jnp

        c = self._consts
        if self.unconditional:
            return jnp.ones(values.shape, dtype=bool)
        gathered = values[c["cond_dim"]]  # [D, A, C, n]
        eq = jnp.abs(gathered - c["cond_val"][..., None]) < 0.5
        term_ok = eq | ~c["term_mask"][..., None]
        conj = jnp.all(term_ok, axis=2) & c["alt_mask"][..., None]
        return jnp.any(conj, axis=1)

    def sample_prior_fn(self, key, n):
        """Jit-traceable: draw n prior configs -> (values [D,n], active [D,n]).

        Continuous dims: bounded dims draw uniform in latent space, normal
        dims draw mu + sigma*z; log-space dims exponentiate; quantized dims
        round in natural space.  Categorical dims: Gumbel/categorical over
        the padded prior pmf.
        """
        import jax
        import jax.numpy as jnp

        c = self._consts
        D = self.n_dims
        Dc = len(self.cont_idx)
        Dk = len(self.cat_idx)
        ku, kz, kc = jax.random.split(key, 3)
        values = jnp.zeros((D, n), dtype=jnp.float32)

        if Dc:
            low, high = c["low"][:, None], c["high"][:, None]
            bounded = jnp.isfinite(low)
            u = jax.random.uniform(ku, (Dc, n), dtype=jnp.float32)
            z = jax.random.normal(kz, (Dc, n), dtype=jnp.float32)
            lat = jnp.where(
                bounded,
                low + u * (high - low),
                c["prior_mu"][:, None] + c["prior_sigma"][:, None] * z,
            )
            from .kernels import quantize_nat

            nat = jnp.where(c["logspace"][:, None], jnp.exp(lat), lat)
            nat = quantize_nat(
                nat, c["q"][:, None], low, high, c["logspace"][:, None]
            )
            values = values.at[c["cont_idx"]].set(nat)

        if Dk:
            logits = jnp.where(
                c["prior_p"] > 0, jnp.log(jnp.maximum(c["prior_p"], 1e-30)), -jnp.inf
            )
            draws = jax.random.categorical(
                kc, logits[:, None, :], axis=-1, shape=(Dk, n)
            )
            values = values.at[c["cat_idx"]].set(
                draws.astype(jnp.float32) + c["int_low"][:, None]
            )

        return values, self.active_fn(values)

    @functools.cached_property
    def sample_prior(self):
        """Jitted ``(key, n) -> (values, active)`` with static n."""
        import jax

        _ = self._consts  # materialize constants outside the trace
        return jax.jit(self.sample_prior_fn, static_argnums=(1,))

    def __repr__(self):
        return (
            f"PackedSpace(D={self.n_dims}, cont={len(self.cont_idx)}, "
            f"cat={len(self.cat_idx)}, k_max={self.k_max}, "
            f"conditional={not self.unconditional})"
        )


def compile_space(space):
    """Compile an hp-annotated space (pyll graph or pytree of graphs) into
    a :class:`PackedSpace`."""
    expr = as_apply(space)
    hps = expr_to_config(expr)
    labels = sorted(hps)
    if not labels:
        raise CompileError("space has no hyperparameters")
    return PackedSpace(labels, hps)


# ---------------------------------------------------------------------------
# graftir program registry: the dispatch-critical program families
# ---------------------------------------------------------------------------
#
# graftlint (analysis/rules.py) sees source AST only; nothing there can
# know what actually ends up INSIDE a compiled program -- a host callback
# smuggled in via a helper, a silent f64 promotion, a donation XLA never
# saw, a 10 MB constant baked into the jaxpr.  The registry is the other
# half: every dispatch-critical program family registers a builder that
# reconstructs the program over ABSTRACT inputs (jax.ShapeDtypeStruct),
# so the IR checker (analysis/ir.py) can trace and lower each one on CPU
# with zero device execution and audit the jaxpr the AST rules cannot
# see.  Shape/cost contracts are pinned in the committed
# ``program_contracts.json`` (see ``hyperopt-tpu-lint --ir``).


def program_family(fn):
    """The program-FAMILY identity of a callable handed to a trace
    wrapper: ``module:qualname`` with any ``<locals>`` suffix stripped,
    so every closure a builder constructs maps back to the builder that
    owns the family (``build_suggest_fn.<locals>.fused`` ->
    ``hyperopt_tpu.tpe_jax:build_suggest_fn``).  ``functools.partial``
    wrappers resolve to the wrapped callable.  The registry-completeness
    test records these at ``jax.jit`` construction time and asserts
    every family reachable from the dispatch-critical entry points is
    claimed by a registered program."""
    while isinstance(fn, functools.partial):
        fn = fn.func
    fn = getattr(fn, "__wrapped__", fn)
    mod = getattr(fn, "__module__", None) or "<unknown>"
    qn = getattr(fn, "__qualname__", None) or getattr(
        fn, "__name__", "<anonymous>"
    )
    return f"{mod}:{qn.split('.<locals>')[0]}"


@dataclasses.dataclass
class ProgramParams:
    """The knobs every registered builder is parameterized by: the
    compiled reference space plus history width / suggestion batch /
    speculative draw width.  Helpers build the abstract input specs all
    history-shaped programs share (zero device execution: even the PRNG
    key spec comes from ``jax.eval_shape``)."""

    space: PackedSpace
    n_obs: int = 128
    batch: int = 4
    k_spec: int = 8
    #: study-axis width the serve-batched program contracts are pinned
    #: at (a small pow2 slot capacity; the family retraces per capacity
    #: exactly like history buckets, so one representative width pins
    #: the whole family's IR behavior)
    n_studies: int = 4

    def key_spec(self):
        import jax

        return jax.eval_shape(lambda: jax.random.key(0))

    def keys_spec(self, n=None):
        """[S] stacked PRNG keys (one per study slot)."""
        import jax

        s = self.n_studies if n is None else int(n)
        return jax.eval_shape(lambda: jax.random.split(jax.random.key(0), s))

    def study_history_specs(self, n=None):
        """The four history arrays with a leading study axis -- the
        :class:`hyperopt_tpu.serve.batched.StudyBatchState` layout."""
        import jax
        import jax.numpy as jnp

        s = self.n_studies if n is None else int(n)
        D, N = self.space.n_dims, self.n_obs
        return (
            jax.ShapeDtypeStruct((s, D, N), jnp.float32),
            jax.ShapeDtypeStruct((s, D, N), jnp.bool_),
            jax.ShapeDtypeStruct((s, N), jnp.float32),
            jax.ShapeDtypeStruct((s, N), jnp.bool_),
        )

    def study_delta_specs(self, n=None):
        """Per-slot O(D) tell deltas + the apply mask:
        (vcol, acol, loss, slot, apply)."""
        import jax
        import jax.numpy as jnp

        s = self.n_studies if n is None else int(n)
        D = self.space.n_dims
        return (
            jax.ShapeDtypeStruct((s, D), jnp.float32),
            jax.ShapeDtypeStruct((s, D), jnp.bool_),
            jax.ShapeDtypeStruct((s,), jnp.float32),
            jax.ShapeDtypeStruct((s,), jnp.int32),
            jax.ShapeDtypeStruct((s,), jnp.bool_),
        )

    def study_mask_spec(self, n=None):
        """A [S] bool per-slot mask (warm/active flags)."""
        import jax
        import jax.numpy as jnp

        s = self.n_studies if n is None else int(n)
        return jax.ShapeDtypeStruct((s,), jnp.bool_)

    def history_specs(self):
        """(values, active, losses, valid) at the registry bucket."""
        import jax
        import jax.numpy as jnp

        D, N = self.space.n_dims, self.n_obs
        return (
            jax.ShapeDtypeStruct((D, N), jnp.float32),
            jax.ShapeDtypeStruct((D, N), jnp.bool_),
            jax.ShapeDtypeStruct((N,), jnp.float32),
            jax.ShapeDtypeStruct((N,), jnp.bool_),
        )

    def delta_specs(self):
        """The O(D) tell delta: (vcol, acol, loss, slot)."""
        import jax
        import jax.numpy as jnp

        D = self.space.n_dims
        return (
            jax.ShapeDtypeStruct((D,), jnp.float32),
            jax.ShapeDtypeStruct((D,), jnp.bool_),
            jax.ShapeDtypeStruct((), jnp.float32),
            jax.ShapeDtypeStruct((), jnp.int32),
        )


@dataclasses.dataclass
class ProgramCapture:
    """What a registered builder hands the IR checker: a jitted callable
    (anything supporting ``.trace(*args, **kwargs)``), the abstract
    arguments to trace it over, and the DECLARED donation contract --
    the argnums the program family promises to donate (checked against
    the lowered program's input-output aliasing, GL403)."""

    fn: object
    args: tuple
    kwargs: dict = dataclasses.field(default_factory=dict)
    donate_argnums: tuple = ()
    #: GL401's explicit escape hatch: the host-callback primitives this
    #: program DECLARES it contains (e.g. ``("io_callback",)`` for the
    #: chunked device loop's progress row).  An undeclared callback in
    #: the jaxpr is still a finding, and so is a stale declaration the
    #: traced program no longer contains -- the allowlist is a contract,
    #: not a mute button.  The callback set is also pinned in the
    #: committed manifest (GL406 ``callbacks`` field).
    allowed_callbacks: tuple = ()
    #: run the enable_x64 re-trace (GL402)?  A program that shares its
    #: closure with another registered program (same build, different
    #: static batch) may skip the duplicate re-trace -- the family's
    #: promotion behavior is already pinned by the sibling.
    x64_check: bool = True


@dataclasses.dataclass(frozen=True)
class ProgramSpec:
    name: str
    build: object          # build(params: ProgramParams) -> ProgramCapture
    families: tuple        # program_family() keys this spec covers
    path: str              # repo-relative source file of the registration
    line: int


PROGRAM_REGISTRY = {}

#: modules that own dispatch-critical program families; imported (once)
#: by :func:`registered_programs` so their registrations run.  A new
#: program family starts by adding its module here and a
#: ``@register_program`` builder there.
_PROGRAM_MODULES = (
    "hyperopt_tpu.ops.compile",
    "hyperopt_tpu.jax_trials",
    "hyperopt_tpu.tpe_jax",
    "hyperopt_tpu.anneal_jax",
    "hyperopt_tpu.atpe_jax",
    "hyperopt_tpu.device_loop",
    "hyperopt_tpu.parallel.sharded",
    "hyperopt_tpu.ops.pallas_kernels",
    "hyperopt_tpu.serve.batched",
    "hyperopt_tpu.pbt",
    "hyperopt_tpu.hyperband",
    "hyperopt_tpu.obs.device",
)


def _rel_source_path(filename):
    """Repo-relative posix path of a registration site (cwd-independent:
    anchored at the package parent, never the process cwd)."""
    pkg_root = os.path.dirname(os.path.dirname(os.path.dirname(
        os.path.abspath(__file__)
    )))
    try:
        rel = os.path.relpath(os.path.abspath(filename), start=pkg_root)
    except ValueError:  # different drive (windows)
        rel = filename
    return rel.replace(os.sep, "/")


def register_program(name, families=()):
    """Decorator registering a dispatch-critical program family.

    The decorated ``build(params)`` must return a :class:`ProgramCapture`
    over ABSTRACT inputs -- it may build jitted closures (cheap) but must
    not execute device programs.  ``families`` lists the
    :func:`program_family` keys of every callable this program wraps,
    the completeness contract the registry test enforces."""

    def deco(build):
        code = getattr(build, "__code__", None)
        spec = ProgramSpec(
            name=name,
            build=build,
            families=tuple(families),
            path=_rel_source_path(
                code.co_filename if code else __file__
            ),
            line=code.co_firstlineno if code else 1,
        )
        if name in PROGRAM_REGISTRY:
            raise ValueError(f"program {name!r} registered twice")
        PROGRAM_REGISTRY[name] = spec
        return build

    return deco


def registered_programs():
    """Import every program-owning module and return the registry
    (name -> :class:`ProgramSpec`, insertion-ordered)."""
    for mod in _PROGRAM_MODULES:
        importlib.import_module(mod)
    return dict(PROGRAM_REGISTRY)


def reference_space():
    """The registry's canonical mixed space: two continuous families
    (bounded + log), one quantized, one categorical -- enough structure
    that every kernel family (uniform/log/quantize/categorical paths)
    appears in the traced programs without bloating trace time."""
    from .. import hp

    return {
        "x": hp.uniform("x", -5.0, 5.0),
        "lr": hp.loguniform("lr", -6.0, 0.0),
        "width": hp.quniform("width", 16.0, 256.0, 16.0),
        "unit": hp.choice("unit", [0, 1, 2]),
    }


def default_program_params(n_obs=128, batch=4, k_spec=8):
    """The parameterization the committed contracts are pinned at."""
    ps = compile_space(reference_space())
    return ProgramParams(space=ps, n_obs=n_obs, batch=batch, k_spec=k_spec)


@register_program(
    "compile.sample_prior",
    families=("hyperopt_tpu.ops.compile:PackedSpace.sample_prior_fn",),
)
def _registry_sample_prior(p):
    """The startup-regime ask: every suggest path below ``n_startup_jobs``
    serves prior draws through this program."""
    import jax

    _ = p.space._consts
    fn = jax.jit(p.space.sample_prior_fn, static_argnums=(1,))
    return ProgramCapture(fn=fn, args=(p.key_spec(), p.batch))
