"""The optimization driver: ``fmin`` / ``FMinIter`` / ``space_eval``.

Capability parity with the reference's ``hyperopt/fmin.py`` (SURVEY.md SS2,
SS3.1): ask the algo for new trial docs at the plugin seam, enqueue,
evaluate synchronously (``serial_evaluate``) or wait for async backends
(``block_until_done``), apply stopping rules (max_evals / timeout /
loss_threshold / early_stop_fn), checkpoint trials to
``trials_save_file`` each round, and return the argmin config.
"""

from __future__ import annotations

import functools
import logging
import os
import pickle
import time
import timeit
import traceback

import numpy as np

from . import base, progress as progress_mod
from .base import (
    Ctrl,
    Domain,
    JOB_STATE_DONE,
    JOB_STATE_ERROR,
    JOB_STATE_NEW,
    JOB_STATE_RUNNING,
    STATUS_FAIL,
    STATUS_OK,
    Trials,
    spec_from_misc,
    trials_from_docs,
)
from .exceptions import (
    AllTrialsFailed,
    CheckpointError,
    InvalidAnnotatedParameter,
    TrialTimeout,
)
from .pyll.base import as_apply, rec_eval
from .pyll_utils import expr_to_config
from .utils import coarse_utcnow

logger = logging.getLogger(__name__)

__all__ = [
    "fmin",
    "FMinIter",
    "space_eval",
    "generate_trials_to_calculate",
    "fmin_pass_expr_memo_ctrl",
    "partial",
    "StopExperiment",
]


class StopExperiment:
    """Sentinel an algo may return instead of new trials to halt fmin."""


def fmin_pass_expr_memo_ctrl(f):
    """Decorator: objective wants raw (expr, memo, ctrl) instead of a
    materialized config (parity: reference ``fmin_pass_expr_memo_ctrl``)."""
    f.fmin_pass_expr_memo_ctrl = True
    return f


def partial(fn, **kwargs):
    """functools.partial that preserves algo attributes (convenience)."""
    import functools

    rval = functools.partial(fn, **kwargs)
    functools.update_wrapper(rval, fn, updated=[])
    return rval


def space_eval(space, hp_assignment):
    """Substitute {label: value} into a space -> the concrete config object
    the objective would receive (choices resolve to their chosen option)."""
    space = as_apply(space)
    hps = expr_to_config(space)
    memo = {}
    for label, info in hps.items():
        if label in hp_assignment:
            memo[info.node] = hp_assignment[label]
    return rec_eval(space, memo=memo)


def generate_trials_to_calculate(points):
    """Seed a Trials object with explicit configs to evaluate first.

    ``points`` is a list of dicts {label: value} (choice values are
    indices).  Parity: reference ``fmin.generate_trials_to_calculate``.
    """
    trials = Trials()
    new_ids = trials.new_trial_ids(len(points))
    miscs = [
        {
            "tid": tid,
            "cmd": None,
            "workdir": None,
            "idxs": {key: [tid] for key in point},
            "vals": {key: [point[key]] for key in point},
        }
        for tid, point in zip(new_ids, points)
    ]
    results = [{"status": base.STATUS_NEW} for _ in points]
    docs = trials.new_trial_docs(new_ids, [None] * len(points), results, miscs)
    trials.insert_trial_docs(docs)
    trials.refresh()
    return trials


class FMinIter:
    """Object-based fmin: step the ask/evaluate loop explicitly."""

    catch_eval_exceptions = False
    pickle_protocol = pickle.HIGHEST_PROTOCOL

    def __init__(
        self,
        algo,
        domain,
        trials,
        rstate,
        asynchronous=None,
        max_queue_len=1,
        poll_interval_secs=0.1,
        max_evals=float("inf"),
        timeout=None,
        loss_threshold=None,
        verbose=False,
        show_progressbar=True,
        early_stop_fn=None,
        trials_save_file="",
        recovery=None,
        trial_timeout=None,
        catch=(),
        recorder=None,
        client=None,
    ):
        # graftscope: the driver's trace spans (driver.trial /
        # tell.wal_append / tell.applied) -- observation only, never
        # touching the rstate stream (the invisibility invariant)
        from .obs.flightrec import NULL_RECORDER

        self.recorder = recorder if recorder is not None else NULL_RECORDER
        self.algo = algo
        self.domain = domain
        self.trials = trials
        self.rstate = rstate
        self.asynchronous = (
            trials.asynchronous if asynchronous is None else asynchronous
        )
        self.max_queue_len = max_queue_len
        self.poll_interval_secs = poll_interval_secs
        self.max_evals = max_evals
        self.timeout = timeout
        self.loss_threshold = loss_threshold
        self.start_time = timeit.default_timer()
        self.verbose = verbose
        self.show_progressbar = show_progressbar
        self.early_stop_fn = early_stop_fn
        self.early_stop_args = []
        self.trials_save_file = trials_save_file
        # crash recovery (utils.checkpoint.DriverRecovery): write-ahead
        # tell log + durable bundles.  Sequential driver only -- async
        # backends have their own durability story (the queue itself).
        self._recovery = None if self.asynchronous else recovery
        # per-trial failure containment: a deadline in seconds, and a
        # tuple of exception classes recorded as STATUS_FAIL trials
        # (with traceback) instead of aborting the study
        self.trial_timeout = trial_timeout
        if catch and not isinstance(catch, tuple):
            catch = (catch,)
        self.catch = catch or ()
        # ask-ahead seam (sequential driver): seed pre-drawn for the NEXT
        # ask so an algo's result hook can pre-dispatch it -- see
        # _notify_result
        self._ask_ahead_seed = None
        # graftclient: with a client, the driver stops being its own
        # dispatch regime -- asks/tells route through the in-process
        # serve engine (client.py), durability through the study WAL,
        # and every driver.trial span carries the client-path study id
        self._client = client
        self._span_study = "driver" if client is None else client.study_name

        if self.asynchronous:
            # async workers fetch the Domain by attachment (SURVEY.md SS3.4)
            if "FMinIter_Domain" not in trials.attachments:
                try:
                    trials.attachments["FMinIter_Domain"] = pickle.dumps(
                        domain, protocol=self.pickle_protocol
                    )
                except Exception:
                    logger.warning("domain not picklable for async backend")

    def _draw_seed(self):
        # works for both np.random.Generator and legacy RandomState
        if hasattr(self.rstate, "integers"):
            return int(self.rstate.integers(2**31 - 1))
        return int(self.rstate.randint(2**31 - 1))

    def _take_seed(self):
        """The next ask's seed: the one pre-drawn for the ask-ahead hook
        if a result notification already drew it, else a fresh draw.
        Exactly one seed is consumed per ask either way, so the rstate
        stream -- and therefore the suggestion stream -- is identical
        with and without an ask-ahead hook installed."""
        seed = self._ask_ahead_seed
        if seed is not None:
            self._ask_ahead_seed = None
            return seed
        return self._draw_seed()

    def _notify_result(self):
        """Ask-ahead seam of the sequential driver: right after a result
        is recorded, give the algo's registered hook
        (``domain._ask_ahead_hook``, installed e.g. by
        ``tpe_jax.suggest(fused=True)``) the chance to pre-dispatch the
        next suggestion -- the fused tell+ask device program is then in
        flight while the driver does its host-side bookkeeping, and the
        next ask only blocks on the fetch.  The seed is pre-drawn from
        the same rstate stream the ask would use (``_take_seed`` hands
        it back), so pre-dispatched and plain asks see identical seeds.
        A hook failure disables the hook and falls back to plain asks:
        ask-ahead is an optimization, never a correctness dependency."""
        hook = getattr(self.domain, "_ask_ahead_hook", None)
        if hook is None:
            return
        if self._ask_ahead_seed is None:
            self._ask_ahead_seed = self._draw_seed()
        try:
            hook(self.trials, self._ask_ahead_seed)
        except Exception:
            logger.exception(
                "ask-ahead hook failed; continuing with plain asks"
            )
            self.domain._ask_ahead_hook = None

    # -- stopping rules ----------------------------------------------------
    def _timed_out(self):
        return (
            self.timeout is not None
            and timeit.default_timer() - self.start_time >= self.timeout
        )

    def _loss_reached(self):
        if self.loss_threshold is None:
            return False
        try:
            best = self.trials.best_trial["result"]["loss"]
        except AllTrialsFailed:
            return False
        return best <= self.loss_threshold

    def _early_stopped(self):
        if self.early_stop_fn is None:
            return False
        if len(self.trials.trials) == 0:
            return False
        stop, kwargs = self.early_stop_fn(self.trials, *self.early_stop_args)
        self.early_stop_args = kwargs
        return bool(stop)

    def should_stop(self):
        return self._timed_out() or self._loss_reached() or self._early_stopped()

    # -- crash recovery seams ----------------------------------------------
    def _crashpoint(self, name):
        if self._recovery is not None:
            self._recovery.fs.crashpoint(name)

    def _log_ask(self, docs):
        """Write-ahead the new trial docs (plus the rstate cursor after
        their seed draw) BEFORE they are inserted: an ask that reached
        the log is never re-drawn on resume; one that did not is
        re-issued from the recorded cursor and draws the same seed."""
        if self._recovery is not None:
            self._recovery.log_ask(base.SONify(docs), self.rstate)

    def _log_tell(self, trial, result=None):
        """Write-ahead one evaluation outcome BEFORE it is applied --
        the exactly-once half of the recovery contract: a logged tell
        is never re-evaluated and never double-applied on resume."""
        if self._recovery is None:
            return
        rec = self.recorder
        t0 = timeit.default_timer() if rec.enabled else 0.0
        if result is not None:
            self._recovery.log_tell(
                trial["tid"], JOB_STATE_DONE, result=result
            )
        else:
            self._recovery.log_tell(
                trial["tid"], JOB_STATE_ERROR,
                error=list(trial["misc"].get("error", ())),
                tb=trial["misc"].get("traceback"),
            )
        if rec.enabled:
            rec.record(
                "tell.wal_append", t0, timeit.default_timer(),
                study="driver", tid=int(trial["tid"]),
            )

    # -- evaluation --------------------------------------------------------
    def _evaluate_one(self, spec, ctrl):
        """One objective call, under the per-trial deadline when
        ``trial_timeout`` is set.  The deadline runs the objective on a
        daemon thread: on expiry the trial is recorded as failed and
        the driver moves on -- the runaway evaluation cannot be killed,
        only abandoned (documented in FAILURES.md)."""
        if not self.trial_timeout:
            return self.domain.evaluate(spec, ctrl)
        import threading

        box = {}

        def _run():
            try:
                box["result"] = self.domain.evaluate(spec, ctrl)
            except BaseException as e:
                box["error"] = e

        worker = threading.Thread(target=_run, daemon=True)
        worker.start()
        worker.join(self.trial_timeout)
        if worker.is_alive():
            raise TrialTimeout(
                f"objective exceeded trial_timeout="
                f"{self.trial_timeout}s; recording STATUS_FAIL and "
                "continuing (the runaway thread is abandoned)"
            )
        if "error" in box:
            raise box["error"]
        return box["result"]

    def _record_tell(self, trial, result=None):
        """The write-ahead seam shared by both dispatch regimes: the
        legacy solo driver logs to its ``DriverRecovery`` WAL, the
        engine client tells/fails through the study's serve WAL -- in
        both, the outcome is durable BEFORE the doc finalizes, so a
        resumed run never re-runs or double-applies a trial."""
        if self._client is not None:
            self._client.record_tell(trial, result)
        else:
            self._log_tell(trial, result=result)

    def _evaluate_trial(self, trial):
        """Evaluate ONE queued trial doc in place -- containment
        (``catch=`` / ``trial_timeout=``), durability write-ahead,
        recorder spans, and the ask-ahead notification -- shared by
        :meth:`serial_evaluate` and the engine-client loop so both
        regimes contain failures and record outcomes identically."""
        trial["state"] = JOB_STATE_RUNNING
        trial["book_time"] = coarse_utcnow()
        trial["owner"] = "serial"
        spec = spec_from_misc(trial["misc"])
        ctrl = Ctrl(self.trials, current_trial=trial)
        result = failure = None
        t_eval = (
            timeit.default_timer() if self.recorder.enabled else 0.0
        )
        try:
            result = self._evaluate_one(spec, ctrl)
        except TrialTimeout as e:
            failure = ("TrialTimeout", str(e), None)
        except self.catch as e:
            failure = (type(e).__name__, str(e), traceback.format_exc())
        except Exception as e:
            logger.error("job exception: %s", e)
            trial["state"] = JOB_STATE_ERROR
            trial["misc"]["error"] = (str(type(e)), str(e))
            trial["misc"]["traceback"] = traceback.format_exc()
            trial["refresh_time"] = coarse_utcnow()
            # the failure is durable before any (re)raise: a
            # resumed driver must not re-run a crashing objective
            self._record_tell(trial)
            if not self.catch_eval_exceptions:
                self.trials.refresh()
                raise
        if result is not None or failure is not None:
            if failure is not None:
                kind, msg, tb = failure
                logger.warning(
                    "trial %s recorded as failed (%s): %s",
                    trial["tid"], kind, msg,
                )
                result = {
                    "status": STATUS_FAIL,
                    "loss": None,
                    "failure": f"{kind}: {msg}",
                }
                if tb is not None:
                    result["traceback"] = tb
            result = base.SONify(result)
            # write-ahead: the tell is on disk before it is applied
            self._record_tell(trial, result=result)
            trial["state"] = JOB_STATE_DONE
            trial["result"] = result
            trial["refresh_time"] = coarse_utcnow()
            if self.recorder.enabled:
                self.recorder.record(
                    "driver.trial", t_eval, timeit.default_timer(),
                    study=self._span_study, tid=int(trial["tid"]),
                    status=result.get("status"),
                )
            self._crashpoint("after_tell_before_ask_ahead")
            self._notify_result()

    def serial_evaluate(self, N=-1):
        for trial in self.trials._dynamic_trials:
            if trial["state"] != JOB_STATE_NEW:
                continue
            self._evaluate_trial(trial)
            N -= 1
            if N == 0:
                break
        self.trials.refresh()

    def block_until_done(self):
        unfinished_states = [JOB_STATE_NEW, JOB_STATE_RUNNING]

        def get_queue_len():
            return self.trials.count_by_state_unsynced(unfinished_states)

        qlen = get_queue_len()
        while qlen > 0:
            if self._timed_out():
                logger.warning("timeout while waiting on %d jobs", qlen)
                break
            time.sleep(self.poll_interval_secs)
            self.trials.refresh()
            qlen = get_queue_len()

    # -- checkpoint --------------------------------------------------------
    def _save_trials(self):
        # tmp + fsync + rename (was a bare pickle.dump: the latent
        # GL301/GL305 -- a crash mid-dump left a truncated pickle under
        # the real name, unloadable on resume)
        if self.trials_save_file:
            from .utils.checkpoint import save_trials

            save_trials(self.trials, self.trials_save_file)

    def _checkpoint_round(self, force=False):
        """Round-boundary durability: the recovery bundle at its tell
        cadence (WAL covers the gaps), or -- without a recovery
        coordinator (async backends, legacy callers) -- the plain
        durable trials pickle every round."""
        if self._recovery is not None:
            self._recovery.maybe_checkpoint(
                self.trials, self.rstate,
                ask_ahead_seed=self._ask_ahead_seed, force=force,
            )
        else:
            self._save_trials()

    # -- main loop ---------------------------------------------------------
    def _run_client(self, N):
        """The engine-client loop (graftclient): evaluate any already-
        queued docs first (``points_to_evaluate``, restored NEW docs),
        then drive up to N trials through the study's depth-k ask/tell
        window.  One trial = await the window head (its dispatch has
        been submitted -- and on a background engine, in flight --
        since before the previous trial's bookkeeping), insert the doc,
        evaluate under the shared containment machinery, tell.  The
        stopping rules, progress protocol, and per-trial containment
        are exactly the solo loop's."""
        trials = self.trials
        client = self._client
        n_new = 0
        initial_n_done = trials.count_by_state_unsynced(JOB_STATE_DONE)
        with self._progress_ctx(initial=0, total=N) as progress:
            if trials.count_by_state_unsynced(JOB_STATE_NEW):
                self.serial_evaluate()
                client.maybe_snapshot()
            while n_new < N:
                trials.refresh()
                if self.should_stop() or not client.budget_left():
                    break
                tid, vals = client.next_suggestion()
                doc = client.insert_new_doc(tid, vals)
                n_new += 1
                self._evaluate_trial(doc)
                client.maybe_snapshot()
                n_done = trials.count_by_state_unsynced(JOB_STATE_DONE)
                n_new_done = n_done - initial_n_done
                if n_new_done > 0:
                    try:
                        best_loss = trials.best_trial["result"]["loss"]
                    except AllTrialsFailed:
                        best_loss = None
                    progress.update(
                        n_done - (initial_n_done + progress_done(progress)),
                        best_loss=best_loss,
                    )
                    set_progress_done(progress, n_new_done)
        trials.refresh()

    def run(self, N, block_until_done=True):
        """Enqueue and evaluate up to N new trials."""
        if self._client is not None:
            return self._run_client(N)
        trials = self.trials
        algo = self.algo
        n_queued = 0

        def get_queue_len():
            return trials.count_by_state_unsynced(JOB_STATE_NEW)

        def get_n_done():
            return trials.count_by_state_unsynced(JOB_STATE_DONE)

        stopped = False
        initial_n_done = get_n_done()
        with self._progress_ctx(initial=0, total=N) as progress:
            while n_queued < N:
                qlen = get_queue_len()
                while (
                    qlen < self.max_queue_len and n_queued < N and not stopped
                ):
                    n_to_enqueue = min(self.max_queue_len - qlen, N - n_queued)
                    if self.should_stop():
                        stopped = True
                        break
                    new_ids = trials.new_trial_ids(n_to_enqueue)
                    self.trials.refresh()
                    new_trials = algo(new_ids, self.domain, trials, self._take_seed())
                    if new_trials is StopExperiment:
                        stopped = True
                        break
                    if new_trials is None or len(new_trials) == 0:
                        stopped = True
                        break
                    assert len(new_ids) >= len(new_trials)
                    self._log_ask(new_trials)
                    trials.insert_trial_docs(new_trials)
                    trials.refresh()
                    n_queued += len(new_trials)
                    qlen = get_queue_len()

                if self.asynchronous:
                    if block_until_done:
                        self.block_until_done()
                    else:
                        time.sleep(self.poll_interval_secs)
                    trials.refresh()
                else:
                    self.serial_evaluate()

                n_done = get_n_done()
                n_new_done = n_done - initial_n_done
                if n_new_done > 0:
                    try:
                        best_loss = trials.best_trial["result"]["loss"]
                    except AllTrialsFailed:
                        best_loss = None
                    progress.update(
                        n_done - (initial_n_done + progress_done(progress)),
                        best_loss=best_loss,
                    )
                    set_progress_done(progress, n_done - initial_n_done)

                self._checkpoint_round()
                if stopped:
                    break
        self._checkpoint_round(force=True)

    def _progress_ctx(self, initial, total):
        if callable(self.show_progressbar) and not isinstance(
            self.show_progressbar, bool
        ):
            return self.show_progressbar(initial=initial, total=total)
        if self.show_progressbar:
            return progress_mod.tqdm_progress_callback(initial=initial, total=total)
        return progress_mod.no_progress_callback(initial=initial, total=total)

    def exhaust(self):
        n_done = len(self.trials)
        self.run(self.max_evals - n_done, block_until_done=self.asynchronous)
        self.trials.refresh()
        return self

    def __iter__(self):
        return self

    def __next__(self):
        self.run(1, block_until_done=self.asynchronous)
        if len(self.trials) >= self.max_evals:
            raise StopIteration()
        return self.trials


def progress_done(progress):
    return getattr(progress, "_n_done", 0)


def set_progress_done(progress, n):
    progress._n_done = n


def _driver_guard(algo, fn, space):
    """The study fingerprint stamped into every recovery artifact
    (reusing the PR-3/4 checkpoint-guard identities): resuming under a
    different algo, objective, or space silently changes the experiment
    and must be refused instead."""
    from .hyperband import _algo_identity, _space_fingerprint

    return [
        "fmin-driver", 1,
        _algo_identity(algo),
        _algo_identity(fn),
        _space_fingerprint(as_apply(space)),
    ]


def _compiled_algo_name(algo):
    """Map the plugin-seam ``algo`` onto a device-loop algo name for
    ``fmin(compiled=True)``: strings pass through, the repo's suggest
    callables (tpe/anneal/rand/atpe, host or _jax, partial-wrapped)
    resolve by module."""
    if algo is None:
        return "tpe"
    if isinstance(algo, str):
        if algo not in ("tpe", "anneal", "rand", "atpe"):
            raise ValueError(
                f"unknown compiled algo {algo!r}: expected "
                "tpe|anneal|rand|atpe"
            )
        return algo
    a = algo
    while isinstance(a, functools.partial):
        a = a.func
    mod = getattr(a, "__module__", "") or ""
    short = mod.rsplit(".", 1)[-1]
    base = short[:-4] if short.endswith("_jax") else short
    if base in ("tpe", "anneal", "rand", "atpe"):
        return base
    raise ValueError(
        f"compiled=True cannot map algo {algo!r} onto a device-loop "
        "algo; pass algo='tpe'|'anneal'|'rand'|'atpe'"
    )


def _run_compiled(fn, space, algo, max_evals, loss_threshold, trials,
                  rstate, return_argmin, options):
    """The ``fmin(compiled=True)`` body: route the experiment through
    ``device_loop.compile_fmin`` -- suggest, evaluate (plain fn or
    :class:`~hyperopt_tpu.device_loop.TrainableObjective` training
    loop), history append all inside the compiled scan -- and rebuild a
    standard ``Trials`` store from the device history."""
    from .device_loop import _to_trials, compile_fmin

    opts = dict(options or {})
    runner = opts.pop("runner", None)
    seed = opts.pop("seed", None)
    if seed is None:
        # one draw from the caller's stream: deterministic under a
        # seeded rstate, like every host-driver seed
        if hasattr(rstate, "integers"):
            seed = int(rstate.integers(2**31 - 1))
        else:
            seed = int(rstate.randint(2**31 - 1))
    if trials is not None and len(trials):
        raise ValueError(
            "compiled=True starts a fresh experiment; warm-start via "
            "device_loop.history_from_trials + compile_fmin("
            "warm_capacity=...) instead"
        )
    if runner is None:
        if not isinstance(max_evals, (int, np.integer)):
            raise ValueError(
                "compiled=True requires an integer max_evals (the scan "
                "length is part of the compiled program)"
            )
        runner = compile_fmin(
            fn, space, int(max_evals),
            algo=_compiled_algo_name(algo),
            loss_threshold=loss_threshold, **opts,
        )
    elif opts:
        raise ValueError(
            "compiled_options: pass either a prebuilt runner= (from "
            "compile_fmin, for compile reuse across calls) or builder "
            "options, not both"
        )
    out = runner(seed=seed)
    if trials is None:
        trials = Trials()
    _to_trials(
        runner._packed_space, out["values"], out["active"],
        out["losses"], trials=trials,
    )
    if return_argmin:
        return trials.argmin
    try:
        return trials.best_trial["result"]["loss"]
    except AllTrialsFailed:
        return None


def _fmin_result(trials, return_argmin):
    """The shared fmin return contract (argmin or best loss)."""
    if return_argmin:
        if len(trials.trials) == 0:
            raise InvalidAnnotatedParameter(
                "There are no evaluation tasks, cannot return argmin of task losses."
            )
        return trials.argmin
    if len(trials) > 0:
        try:
            return trials.best_trial["result"]["loss"]
        except AllTrialsFailed:
            return None
    return None


def _run_engine_client(fn, space, algo, max_evals, timeout,
                       loss_threshold, trials, rstate,
                       pass_expr_memo_ctrl, catch_eval_exceptions,
                       verbose, return_argmin, points_to_evaluate,
                       max_queue_len, show_progressbar, early_stop_fn,
                       trials_save_file, resume_from, trial_timeout,
                       catch, recorder, engine, ask_ahead):
    """The ``fmin(engine=...)`` body (graftclient): open a study on an
    in-process serve engine and drive the sequential loop through
    ``StudyHandle.ask``/``tell`` with a depth-k ask-ahead window --
    the solo fused path's job, done by the one engine (ISSUE 15).

    Since graftburst, ``engine=True`` goes through the client module's
    shared-service registry: concurrent ``fmin`` calls of the same
    study family (root, space, algo + knobs, objective) co-batch into
    ONE scheduler's vmapped rounds, each stream bitwise its solo run;
    the last client out shuts the shared service down."""
    from .client import connect

    if max_queue_len != 1:
        raise ValueError(
            "engine routing drives one ask at a time -- use "
            "ask_ahead=k for pipelining (max_queue_len applies to the "
            "solo/async drivers)"
        )
    if trials is not None and (
        type(trials).fmin is not Trials.fmin
        or getattr(trials, "asynchronous", False)
    ):
        raise ValueError(
            "engine routing supports sequential Trials stores; async "
            "backends (ThreadTrials / FileTrials / SparkTrials...) "
            "dispatch their own fmin"
        )
    domain = Domain(fn, space, pass_expr_memo_ctrl=pass_expr_memo_ctrl)

    root = None
    require_existing = False
    eng = True if engine is None or isinstance(engine, bool) else engine
    if eng is True:
        if resume_from is not None:
            root = str(resume_from)
            require_existing = True
        elif trials_save_file:
            root = str(trials_save_file)
        if root is not None and os.path.isfile(root):
            raise CheckpointError(
                f"{root!r} is a FILE -- a legacy solo-driver "
                "checkpoint; engine-client durability uses a "
                "study-root DIRECTORY (<root>/fmin.wal + fmin.snap, "
                "audited by hyperopt-tpu-fsck --serve).  Resume legacy "
                "checkpoints with engine=False, or start a fresh "
                "recoverable run against a directory (MIGRATION.md)"
            )
    elif trials_save_file or resume_from is not None:
        raise ValueError(
            "with a provided engine service, durability rides its "
            "root=; drop trials_save_file/resume_from (restore is "
            "implicit when the root holds study artifacts)"
        )

    if trials is None and points_to_evaluate is not None:
        assert isinstance(points_to_evaluate, list)
        trials = generate_trials_to_calculate(points_to_evaluate)
    elif (
        trials is not None
        and points_to_evaluate is not None
        and len(trials) == 0
    ):
        assert isinstance(points_to_evaluate, list)
        seeded = generate_trials_to_calculate(points_to_evaluate)
        trials._ids.update(t["tid"] for t in seeded._dynamic_trials)
        trials._insert_trial_docs(seeded._dynamic_trials)
        trials.refresh()

    client, trials, rstate, restored = connect(
        eng, algo, domain, trials, rstate, fn=fn,
        ask_ahead=1 if ask_ahead is None else int(ask_ahead),
        root=root, require_existing=require_existing,
        max_submits=max_evals, recorder=recorder,
    )
    rval = FMinIter(
        algo,
        domain,
        trials,
        max_evals=max_evals,
        timeout=timeout,
        loss_threshold=loss_threshold,
        rstate=rstate,
        verbose=verbose,
        max_queue_len=1,
        show_progressbar=show_progressbar,
        early_stop_fn=early_stop_fn,
        trial_timeout=trial_timeout,
        catch=catch,
        recorder=recorder,
        client=client,
    )
    rval.catch_eval_exceptions = catch_eval_exceptions
    try:
        rval.exhaust()
    except BaseException:
        # a crash (SimulatedCrash, uncaught objective error) must
        # leave the WAL as the truth, un-compacted -- but the
        # co-batching registry hold is dropped so a same-process
        # retry restores from disk, not from the dead run's service
        client.abandon()
        raise
    # orderly completion only
    client.finalize()
    return _fmin_result(trials, return_argmin)


def fmin(
    fn,
    space,
    algo=None,
    max_evals=None,
    timeout=None,
    loss_threshold=None,
    trials=None,
    rstate=None,
    allow_trials_fmin=True,
    pass_expr_memo_ctrl=None,
    catch_eval_exceptions=False,
    verbose=False,
    return_argmin=True,
    points_to_evaluate=None,
    max_queue_len=1,
    show_progressbar=True,
    early_stop_fn=None,
    trials_save_file="",
    resume_from=None,
    trial_timeout=None,
    catch=(),
    compiled=False,
    compiled_options=None,
    recorder=None,
    engine=None,
    ask_ahead=None,
):
    """Minimize ``fn`` over ``space`` using ``algo``.

    Engine routing (graftclient): ``engine=True`` (or any
    ``ask_ahead=``) routes the sequential driver through an in-process
    :class:`~hyperopt_tpu.serve.SuggestService` -- ``fmin`` becomes a
    client of the same study-batched engine that serves multi-tenant
    traffic, so admission control, quarantine, the dispatch watchdog,
    WAL durability, mesh sharding, and graftscope all apply to a solo
    run.  ``ask_ahead=k`` keeps k asks submitted ahead (seeds drawn at
    submit time, dispatch gated on posterior freshness), so the stream
    is bitwise the solo fused driver's AT ANY DEPTH; ``k=1`` is the
    exact one-dispatch-per-trial degenerate.  ``engine`` may also be a
    caller-built ``SuggestService`` (chaos harnesses arm crash points
    on its ``fs`` seam).  In this mode ``trials_save_file`` /
    ``resume_from`` name a study-root DIRECTORY (``<root>/fmin.wal`` /
    ``.snap`` -- audit with ``hyperopt-tpu-fsck --serve``), one
    durability story shared with the serve tier.  ``algo`` must map
    onto an engine body (``tpe_jax`` / ``anneal_jax`` /
    ``atpe_jax`` ``.suggest``, partials included).

    Observability (graftscope): ``recorder`` (a
    :class:`~hyperopt_tpu.obs.FlightRecorder`) arms driver trace spans
    (``driver.trial`` / ``tell.wal_append``); arming it changes no
    suggestion stream (the invisibility invariant).  Compiled runs
    stream per-chunk device metrics instead: pass
    ``compiled_options={"chunk_size": ..., "metrics_registry": reg}``.

    Drop-in parity with the reference ``hyperopt.fmin`` (SURVEY.md SS2 L4);
    pass ``algo=hyperopt_tpu.tpe.suggest`` for the host parity path or
    ``algo=hyperopt_tpu.tpe_jax.suggest`` for the jitted TPU path.

    Crash recovery (sequential driver): ``trials_save_file`` routes
    through :class:`~hyperopt_tpu.utils.checkpoint.DriverRecovery` -- a
    write-ahead tell log plus durable checkpoint bundles -- so a driver
    killed at any point resumes with zero lost / zero duplicated tells
    and a suggestion stream bitwise identical to the uninterrupted run
    (the restored numpy bit-generator supersedes a passed ``rstate``).
    ``resume_from`` is the explicit form: the checkpoint must already
    exist (a :class:`~hyperopt_tpu.exceptions.CheckpointError` refuses
    a missing or foreign-study one); it may also be a ``DriverRecovery``
    instance for injection (chaos tests arm crash points on its ``fs``).

    Per-trial containment: ``trial_timeout`` (seconds) records an
    overrunning objective as a STATUS_FAIL trial and moves on;
    ``catch`` (an exception class or tuple) does the same for raising
    objectives, with the traceback attached to the result -- both are
    WAL-logged, so a resumed run never re-runs a known-bad trial.

    Compiled objectives: ``compiled=True`` routes a JAX-traceable ``fn``
    (a jnp function over ``[batch]`` value dicts, or a
    :class:`~hyperopt_tpu.device_loop.TrainableObjective` training
    loop) through ``device_loop.compile_fmin`` -- the whole
    ask-evaluate-tell loop as ONE device program, no per-trial RTT --
    and returns the standard ``Trials``/argmin contract.  ``algo`` may
    be a device-loop name ('tpe'|'anneal'|'rand'|'atpe') or one of this
    repo's suggest callables (mapped by module); ``compiled_options``
    passes builder knobs through (``batch_size``, ``chunk_size``,
    ``progress_callback``, ``checkpoint_path``/``resume`` for
    kill-and-resume, ``seed`` to pin the device seed, or a prebuilt
    ``runner=`` for compile reuse across calls).  A
    ``TrainableObjective`` may add ``compiled_options={"asha": {...}}``
    (graftrung): rung-based successive-halving early stopping fused
    inside the compiled scan -- per-bracket promotions on-device, no
    host round trip between rungs; see ``compile_fmin``'s ``asha=``.
    """
    if algo is None:
        if bool(engine) or ask_ahead is not None:
            from . import tpe_jax

            algo = tpe_jax.suggest
            logger.warning(
                "fmin: algo not specified, defaulting to "
                "tpe_jax.suggest (the engine routing's native body)"
            )
        else:
            from . import tpe

            algo = tpe.suggest
            logger.warning(
                "fmin: algo not specified, defaulting to tpe.suggest"
            )

    if max_evals is None:
        max_evals = float("inf")

    if rstate is None:
        env_rseed = os.environ.get("HYPEROPT_FMIN_SEED", "")
        if env_rseed:
            rstate = np.random.default_rng(int(env_rseed))
        else:
            rstate = np.random.default_rng()
    elif isinstance(rstate, (int, np.integer)):
        rstate = np.random.default_rng(int(rstate))

    validate_timeout(timeout)
    validate_loss_threshold(loss_threshold)
    validate_timeout(trial_timeout)

    use_engine = bool(engine) or ask_ahead is not None
    if use_engine and compiled:
        raise ValueError(
            "engine=/ask_ahead= route the sequential driver through "
            "the serve engine; compiled=True is the on-device regime "
            "-- pick one"
        )
    if use_engine:
        return _run_engine_client(
            fn, space, algo, max_evals, timeout, loss_threshold,
            trials, rstate, pass_expr_memo_ctrl, catch_eval_exceptions,
            verbose, return_argmin, points_to_evaluate, max_queue_len,
            show_progressbar, early_stop_fn, trials_save_file,
            resume_from, trial_timeout, catch, recorder, engine,
            ask_ahead,
        )

    if compiled:
        # the RTT-floor bypass: the WHOLE ask-evaluate-tell loop runs
        # on device (device_loop.compile_fmin) and comes back as a
        # standard Trials store.  Host-driver-only features are
        # rejected loudly rather than silently ignored.
        if trials_save_file or resume_from is not None:
            raise ValueError(
                "compiled=True durability rides compiled_options "
                "(chunk_size/checkpoint_path/resume -- the chunked "
                "device loop), not trials_save_file/resume_from"
            )
        unsupported = [
            name for name, v in (
                ("timeout", timeout),
                ("early_stop_fn", early_stop_fn),
                ("points_to_evaluate", points_to_evaluate),
                ("trial_timeout", trial_timeout),
                ("catch", catch or None),
            ) if v is not None
        ]
        if unsupported:
            raise ValueError(
                f"compiled=True runs the experiment as one device "
                f"program; host-driver feature(s) {unsupported} do not "
                "apply (loss_threshold compiles to the on-device "
                "stopping rule; use compiled_options for chunked "
                "progress/checkpointing)"
            )
        return _run_compiled(
            fn, space, algo, max_evals, loss_threshold, trials, rstate,
            return_argmin, compiled_options,
        )

    from .utils.checkpoint import DriverRecovery

    recovery = None
    ask_ahead_seed = None
    if resume_from is not None or trials_save_file:
        if isinstance(resume_from, DriverRecovery):
            # injected coordinator (the chaos suite arms crash points
            # on its fs seam): load-if-exists, start fresh otherwise
            recovery = resume_from
        else:
            recovery = DriverRecovery(resume_from or trials_save_file)
            if resume_from is not None and not recovery.exists():
                raise CheckpointError(
                    f"resume_from checkpoint {recovery.path!r} does "
                    "not exist; pass trials_save_file= to start a "
                    "fresh recoverable run instead"
                )
        recovery.set_guard(_driver_guard(algo, fn, space))
        if recovery.exists():
            restored = recovery.load()
            trials = restored.trials
            ask_ahead_seed = restored.ask_ahead_seed
            if restored.rstate is not None:
                rstate = restored.rstate
                logger.info(
                    "resumed %d trials from %r (replayed %d tell(s) "
                    "from the WAL); bit-generator state restored -- "
                    "the suggestion stream continues exactly where the "
                    "previous run stopped",
                    len(trials), recovery.path,
                    restored.n_replayed_tells,
                )

    if trials is None:
        if points_to_evaluate is None:
            trials = Trials()
        else:
            assert isinstance(points_to_evaluate, list)
            trials = generate_trials_to_calculate(points_to_evaluate)
    elif points_to_evaluate is not None and len(trials) == 0:
        assert isinstance(points_to_evaluate, list)
        seeded = generate_trials_to_calculate(points_to_evaluate)
        trials._ids.update(t["tid"] for t in seeded._dynamic_trials)
        trials._insert_trial_docs(seeded._dynamic_trials)
        trials.refresh()

    # Backends (ThreadTrials / FileTrials / SparkTrials...) implement their
    # own fmin dispatch; plain Trials.fmin recurses here with
    # allow_trials_fmin=False (reference seam, SURVEY.md SS3.5).
    if allow_trials_fmin and type(trials).fmin is not Trials.fmin:
        return trials.fmin(
            fn,
            space,
            algo=algo,
            max_evals=max_evals,
            timeout=timeout,
            loss_threshold=loss_threshold,
            max_queue_len=max_queue_len,
            rstate=rstate,
            pass_expr_memo_ctrl=pass_expr_memo_ctrl,
            verbose=verbose,
            catch_eval_exceptions=catch_eval_exceptions,
            return_argmin=return_argmin,
            show_progressbar=show_progressbar,
            early_stop_fn=early_stop_fn,
            trials_save_file=trials_save_file,
        )

    domain = Domain(fn, space, pass_expr_memo_ctrl=pass_expr_memo_ctrl)

    rval = FMinIter(
        algo,
        domain,
        trials,
        max_evals=max_evals,
        timeout=timeout,
        loss_threshold=loss_threshold,
        rstate=rstate,
        verbose=verbose,
        max_queue_len=max_queue_len,
        show_progressbar=show_progressbar,
        early_stop_fn=early_stop_fn,
        trials_save_file=trials_save_file,
        recovery=recovery,
        trial_timeout=trial_timeout,
        catch=catch,
        recorder=recorder,
    )
    rval.catch_eval_exceptions = catch_eval_exceptions
    if ask_ahead_seed is not None:
        # the bundle-recorded ask-ahead seam position: the seed the
        # crashed run had pre-drawn for its next ask (same stream, so
        # the resumed ask sees the identical seed either way)
        rval._ask_ahead_seed = int(ask_ahead_seed)
    if rval._recovery is not None and not recovery.exists():
        # anchor checkpoint before the first ask: WAL replay needs a
        # bundle to be relative to, and points_to_evaluate seeds must
        # survive a crash before the first cadence boundary
        recovery.checkpoint(trials, rstate)
    rval.exhaust()

    return _fmin_result(trials, return_argmin)


def validate_timeout(timeout):
    if timeout is not None and (
        not isinstance(timeout, (int, float)) or timeout <= 0
    ):
        raise Exception(
            f"The timeout argument should be None or a positive value. Given value: {timeout}"
        )


def validate_loss_threshold(loss_threshold):
    if loss_threshold is not None and not isinstance(loss_threshold, (int, float)):
        raise Exception(
            f"The loss_threshold argument should be None or a numeric value. Given value: {loss_threshold}"
        )
