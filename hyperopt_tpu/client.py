"""graftclient: ``fmin`` as a serve-engine client (ISSUE 15).

The sequential host driver was the last code path with its own dispatch
regime: one ``state_io`` fused program per trial, its own write-ahead
log (``utils.checkpoint.DriverRecovery``), its own ask-ahead seam.  The
study-batched serve engine built in PRs 8-14 runs the SAME per-study
math (the solo fused closure, vmapped) behind admission control,
quarantine, a watchdog, WAL durability, mesh sharding, and
observability -- so this module deletes the solo regime instead of
continuing to shave it: ``fmin(engine=True)`` opens a study on an
in-process :class:`~hyperopt_tpu.serve.SuggestService` (no TCP, no
background thread by default) and drives every trial through
``StudyHandle.ask`` / ``tell``.  This is the Vizier-service posture --
every client, including a single-user ``fmin``, speaks to the one
engine -- and it means every engine improvement (graftmesh, graftguard,
graftscope, graftfleet) accrues to single-user ``fmin`` for free.

Correctness story (the reason the collapse is safe):

* **Submit-time seeds.**  The scheduler draws each ask's seed from the
  study's own rstate stream at SUBMIT time -- and the client wires the
  study's rstate to ``fmin``'s own ``rstate``, so the seed sequence is
  exactly what the solo driver's ``_take_seed`` would have drawn.
* **Depth-k ask-ahead window** (``fmin(ask_ahead=k)``): the client
  keeps up to ``k`` asks submitted ahead; the study's ``fresh_window``
  gate holds a queued ask back until every previously served
  suggestion has its tell, so every dispatch sees the full posterior.
  Together the two make the suggestion stream *bitwise identical at
  any depth* -- k=1 degenerates to one fused dispatch per trial, the
  old solo regime, and k>1 keeps the pipeline primed (the dispatch for
  trial i+1 is queued, seeded, and -- on a background-mode service --
  already in flight while the driver finishes trial i's host-side
  bookkeeping) without ever trading staleness for it.
* **One durability story.**  ``trials_save_file`` / ``resume_from``
  become a serve study root: the per-study ``TellWAL`` + snapshot
  bundle (PR 8) absorb the driver WAL's job -- ask records carry the
  post-draw rstate cursor, tell records carry the full SONified result
  dict, ``fail`` records make failed/errored trials durable before
  their docs finalize (a resumed run never re-runs a known-bad trial),
  and the snapshot bundle carries the client's Trials docs.  Audit and
  repair with ``hyperopt-tpu-fsck --serve ROOT`` (the ``--driver`` role
  now covers only legacy solo-driver checkpoint files).
* **Backpressure is a pace signal.**  A typed
  :class:`~hyperopt_tpu.exceptions.Overloaded` refusal becomes bounded
  retry-with-backoff under the client deadline
  (:meth:`EngineClient._submit_one`), escalating to
  :class:`~hyperopt_tpu.exceptions.DeadlineExpired` -- never a stuck
  full-timeout hang, never a lost trial.

Algorithm routing: ``tpe_jax.suggest`` and ``anneal_jax.suggest``
(partials included) map onto the engine's vmapped program bodies;
``atpe_jax.suggest`` keeps its host decision layer as a per-study
``host_algo`` dispatch hook served inside the same rounds (adaptive
settings cannot vmap across studies).  Anything else -- host-parity
algos, ``joint_ei``, ``speculative=k`` -- raises with a pointer at the
solo compatibility path.
"""

from __future__ import annotations

import collections
import functools
import json
import logging
import threading
import time

import numpy as np

from .base import (
    JOB_STATE_DONE,
    JOB_STATE_ERROR,
    JOB_STATE_NEW,
    STATUS_OK,
    SONify,
    Trials,
)
from .exceptions import (
    CheckpointError,
    DeadlineExpired,
    DispatchTimeout,
    NetworkTimeout,
    Overloaded,
    OwnershipLost,
    PeerUnreachable,
    ReplicaDead,
    StudyPoisoned,
    StudyQuarantined,
)
from .rand import docs_from_idxs_vals

logger = logging.getLogger(__name__)

__all__ = [
    "CLIENT_STUDY",
    "EngineClient",
    "EngineSpec",
    "RemoteStudy",
    "connect",
    "resolve_engine_algo",
]

#: the study name a solo ``fmin`` client opens on its service: the
#: durable root then holds ``fmin.wal`` / ``fmin.snap`` -- one study
#: family per root, exactly one tenant
CLIENT_STUDY = "fmin"

#: snapshot cadence of a client study (tells per bundle publish) --
#: the DriverRecovery default, so the durability granularity of the
#: unified layout matches the driver WAL it replaces
CLIENT_SNAPSHOT_CADENCE = 25

#: round width of a SHARED client service: concurrent ``fmin`` clients
#: of one (root, space, algo, objective) family ride the same vmapped
#: rounds up to this many asks per dispatch (graftburst co-batching)
SHARED_MAX_BATCH = 64

#: per-study submit-ahead cap of a shared service; a client's
#: ``ask_ahead`` window is clamped to it (depth is stream-invisible,
#: so the clamp is bitwise-safe -- and without it a deep window would
#: spin forever against the cap's Overloaded backpressure)
SHARED_QUEUE_CAP = 8

# -- the co-batching registry (graftburst tentpole 2) -----------------------
#
# ``fmin(engine=True)`` used to build a PRIVATE max_batch=1 service per
# call -- N concurrent clients meant N schedulers, N dispatch rounds,
# zero batching.  The registry below keys LIVE client-owned services by
# their full study-family identity (root, algo, algo knobs, space
# fingerprint, objective identity); concurrent ``connect()`` calls with
# the same key share one wide service and each open their own study on
# it, so their asks co-batch into the same vmapped rounds.  Each stream
# stays bitwise its solo run: seeds are drawn from each study's OWN
# rstate at submit time (the PR-8 construction) and the per-slot math
# is vmapped identically whatever the round width.
#
# Refcounted, live-only: release at zero shuts the service down and
# drops the entry, so a SEQUENTIAL restore still finds the root closed
# and quiescent -- sharing only ever spans temporally-overlapping
# clients.  Chaos harnesses (fs=) and recorder runs stay private: an
# armed fault plan or span recorder belongs to ONE call.
_SHARED_SERVICES = {}  # key -> [service, refcount]
_SHARED_LOCK = threading.Lock()


def _registry_key(spec, domain, fn, root):
    """The full study-family identity of one client-owned service."""
    from .hyperband import _algo_identity, _space_fingerprint

    return json.dumps(
        [
            str(root),
            spec.name,
            sorted(spec.algo_kw.items()),
            spec.n_startup_jobs,
            sorted((spec.hook_kw or {}).items()),
            bool(spec.resident),
            _space_fingerprint(domain.expr),
            _algo_identity(fn) if fn is not None else None,
        ],
        sort_keys=True,
        default=str,
    )


def _alloc_study_name(service):
    """The next free client study name on ``service``: ``fmin`` when
    free (the solo layout -- restore keys on it), else ``fmin-2``,
    ``fmin-3``, ...  Callers hold :data:`_SHARED_LOCK`."""
    existing = set(service.studies())
    if CLIENT_STUDY not in existing:
        return CLIENT_STUDY
    i = 2
    while f"{CLIENT_STUDY}-{i}" in existing:
        i += 1
    return f"{CLIENT_STUDY}-{i}"


def _release_shared(key, service, study_name):
    """Drop one client's hold on a shared service; the last one out
    shuts it down (snapshots inside) and retires the registry entry."""
    with _SHARED_LOCK:
        entry = _SHARED_SERVICES.get(key)
        if entry is None or entry[0] is not service:
            # registry moved on (shouldn't happen); close just our study
            service.close_study(study_name)
            return
        entry[1] -= 1
        if entry[1] > 0:
            service.close_study(study_name)
            return
        del _SHARED_SERVICES[key]
        # shutdown INSIDE the lock: a racing connect() on the same key
        # must not build a second service over a root still closing
        service.shutdown()


class EngineSpec:
    """How one plugin-seam ``algo`` maps onto the serve engine."""

    __slots__ = ("name", "algo_kw", "n_startup_jobs", "hook_kw", "resident")

    def __init__(self, name, algo_kw, n_startup_jobs, hook_kw=None,
                 resident=None):
        self.name = name
        self.algo_kw = dict(algo_kw)
        self.n_startup_jobs = int(n_startup_jobs)
        self.hook_kw = hook_kw
        self.resident = resident


def _unwrap_algo(algo):
    """Peel partial layers; outermost keywords win (call semantics)."""
    kw = {}
    a = algo
    while isinstance(a, functools.partial):
        merged = dict(a.keywords or {})
        merged.update(kw)
        kw = merged
        a = a.func
    return a, kw


def resolve_engine_algo(algo):
    """Map the plugin-seam ``algo`` onto an :class:`EngineSpec`.

    Raises ``ValueError`` (naming the offender and the fallback) for
    anything the engine cannot serve bitwise: host-parity algos,
    ``joint_ei``, ``speculative=k`` (the solo driver's staleness-based
    amortization -- the engine's fresh ask-ahead window replaces it),
    or unknown keywords.
    """
    a, kw = _unwrap_algo(algo)
    mod = getattr(a, "__module__", "") or ""
    short = mod.rsplit(".", 1)[-1]
    if short not in ("tpe_jax", "anneal_jax", "atpe_jax") or getattr(
        a, "__name__", ""
    ) != "suggest":
        raise ValueError(
            f"fmin(engine=...) cannot route algo {algo!r} through the "
            "serve engine: supported are tpe_jax.suggest, "
            "anneal_jax.suggest and atpe_jax.suggest (partials "
            "included); pass engine=False for the solo compatibility "
            "path"
        )
    if kw.pop("speculative", 0):
        raise ValueError(
            "algo speculative=k is the solo driver's staleness-based "
            "dispatch amortization; the engine client's ask_ahead=k "
            "window replaces it without trading posterior freshness -- "
            "drop speculative= (or pass engine=False)"
        )
    kw.pop("max_stale", None)  # only meaningful with speculative
    # solo dispatch-shape knobs: the engine's stacked state is
    # inherently resident (fused=True's whole point), so these are
    # satisfied by construction rather than contradicted
    kw.pop("fused", None)
    kw.pop("ask_ahead", None)
    resident = kw.pop("resident", None)
    if short == "tpe_jax":
        from . import tpe_jax as m

        if kw.pop("joint_ei", False):
            raise ValueError(
                "joint_ei=True has no batched engine body (measured "
                "quality-neutral, kept for its structural property "
                "only); pass engine=False to use it"
            )
        algo_kw = dict(
            n_cand=int(kw.pop("n_EI_candidates",
                              m._default_n_EI_candidates)),
            gamma=float(kw.pop("gamma", m._default_gamma)),
            lf=float(kw.pop("linear_forgetting",
                            m._default_linear_forgetting)),
            prior_weight=float(kw.pop("prior_weight",
                                      m._default_prior_weight)),
            n_cand_cat=kw.pop("n_EI_candidates_cat",
                              m._default_n_EI_candidates_cat),
            above_cap=kw.pop("above_cap", None),
        )
        n_startup = int(kw.pop("n_startup_jobs",
                               m._default_n_startup_jobs))
        spec = EngineSpec("tpe", algo_kw, n_startup, resident=resident)
    elif short == "anneal_jax":
        from . import anneal_jax as m

        algo_kw = dict(
            avg_best_idx=float(kw.pop("avg_best_idx",
                                      m._default_avg_best_idx)),
            shrink_coef=float(kw.pop("shrink_coef",
                                     m._default_shrink_coef)),
        )
        # anneal warms at the first observation regardless (the
        # scheduler's algo-aware warm mask); n_startup_jobs is unused
        spec = EngineSpec("anneal", algo_kw, 1, resident=resident)
    else:
        if kw.pop("mesh", None) is not None:
            raise ValueError(
                "atpe mesh= shards the candidate sweep of the SOLO "
                "dispatch; unsupported on the client path (pass "
                "engine=False)"
            )
        hook_kw = dict(
            n_startup_jobs=int(kw.pop("n_startup_jobs", 20)),
            linear_forgetting=int(kw.pop("linear_forgetting", 25)),
            lock_fraction=float(kw.pop("lock_fraction", 0.5)),
            elite_count=int(kw.pop("elite_count", 8)),
        )
        spec = EngineSpec(
            "atpe", {}, hook_kw["n_startup_jobs"], hook_kw=hook_kw,
            resident=resident,
        )
    if kw:
        raise ValueError(
            f"fmin(engine=...) cannot map algo keyword(s) {sorted(kw)} "
            "onto the serve engine; pass engine=False for the solo "
            "compatibility path"
        )
    return spec


def _make_host_hook(spec, domain, trials):
    """The atpe ``host_algo`` hook: the solo host-adaptive dispatch
    verbatim -- host decision layer (``ATPEOptimizer`` settings + lock
    rolls) over the client's live Trials, device sweep through the
    shared ``suggest_dense`` engine -- minus the doc building the
    client now owns.  Bitwise the solo ``atpe_jax.suggest`` stream."""
    from . import atpe_jax
    from .pyll.stochastic import ensure_rng

    hk = spec.hook_kw
    if spec.resident is not None:
        from .jax_trials import obs_buffer_for

        obs_buffer_for(domain, trials, resident=bool(spec.resident))

    def hook(seed):
        opt = atpe_jax._optimizer_for(
            domain, hk["lock_fraction"], hk["elite_count"]
        )
        rng = ensure_rng(int(seed))
        return atpe_jax._dense_draw(
            domain, trials, opt, rng, 1, hk["n_startup_jobs"],
            hk["linear_forgetting"],
        )

    return hook


def _misc_vals(trial):
    """{label: value} of one doc -- the ``ObsBuffer._add_doc``
    extraction, so what the client tells is bitwise what the solo
    buffer would have ingested from the same doc."""
    return {
        k: v[0] for k, v in trial["misc"]["vals"].items() if len(v) == 1
    }


def _client_guard(base_guard, fn):
    """The study guard of a client-owned service: the serve guard
    (algo + space fingerprint) extended with the OBJECTIVE identity --
    resuming a root under a different objective silently changes the
    experiment and must be refused (the PR-6 driver-guard posture)."""
    from .hyperband import _algo_identity

    return list(base_guard) + ["fmin-client", _algo_identity(fn)]


class EngineClient:
    """``FMinIter``'s view of the engine: one study, one window.

    Built by :func:`connect`; driven by ``FMinIter`` (which owns the
    evaluation machinery -- ``catch=`` / ``trial_timeout=`` / recorder
    spans).  The client owns the serve-side half: the depth-k submit
    window with Overloaded backoff, doc building from served vals,
    tells/fails with their durable payloads, and restore."""

    def __init__(self, service, handle, spec, domain, trials, rstate,
                 ask_ahead=1, owns_service=True, max_submits=None,
                 restored=False, shared_key=None):
        self.service = service
        self.handle = handle
        self.study = handle._study
        self.spec = spec
        self.domain = domain
        self.trials = trials
        self.rstate = rstate
        self.ps = service.ps
        # clamp the window to the service's per-study submit cap: depth
        # is stream-invisible (fresh_window holds dispatch order), and
        # an unclamped window on a shared service would spin the
        # Overloaded backoff loop against study_queue_cap forever
        self.ask_ahead = max(
            1, min(int(ask_ahead), service.scheduler.study_queue_cap)
        )
        self._shared_key = shared_key
        self.owns_service = owns_service
        #: total ask budget (max_evals); submits stop at it so the
        #: rstate cursor ends exactly where the solo driver's would
        self.max_submits = (
            float("inf") if max_submits is None else max_submits
        )
        self.restored = restored
        self._queue = collections.deque()  # submitted-ahead requests
        self._recovering = bool(
            self.study.pending_asks or self.study.outstanding
        )
        self.durable = self.study.persist is not None
        self.closed = False

    @property
    def study_name(self):
        return self.study.name

    # -- the ask window ----------------------------------------------------
    def budget_left(self):
        return self.study.next_tid < self.max_submits or bool(
            self._queue
        ) or self._recovering

    def _submit_one(self, deadline):
        """Submit one ask, turning :class:`Overloaded` into bounded
        retry-with-backoff under ``deadline`` (the satellite-3
        contract: backpressure paces the client, it never strands it
        in a full-timeout hang -- the typed escalation is
        :class:`DeadlineExpired`)."""
        while True:
            remaining = deadline - time.perf_counter()
            if remaining <= 0:
                raise DeadlineExpired(
                    f"client study {self.study_name!r}: ask window "
                    "submit deadline exhausted"
                )
            try:
                req = self.service._submit(
                    self.study, timeout=remaining
                )
            except Overloaded as e:
                from .serve.service import RETRY_AFTER_CAP

                # honor the server's (jittered, PR-16) hint, capped: a
                # wild hint must never eat the whole client deadline
                wait = min(
                    e.retry_after if e.retry_after else 0.05,
                    RETRY_AFTER_CAP,
                )
                if time.perf_counter() + wait >= deadline:
                    raise DeadlineExpired(
                        f"client study {self.study_name!r}: the engine "
                        f"stayed overloaded ({e.reason}) past the "
                        "client deadline; last retry_after hint was "
                        f"{wait}s"
                    ) from e
                time.sleep(wait)  # graftlint: disable=GL303 the backoff IS the server's typed retry_after hint, bounded by the client deadline above -- not an unbounded retry loop
                continue
            self._queue.append(req)
            return

    def next_suggestion(self, timeout=60.0):
        """The next (tid, vals) of the stream: re-delivered exactly
        once for asks a crashed run left undelivered, else from the
        depth-k submit-ahead window."""
        if self._recovering:
            if self.study.pending_asks or self.study.outstanding:
                return self.handle.ask(
                    timeout=timeout, recover=True, backoff=True
                )
            self._recovering = False
        deadline = time.perf_counter() + float(timeout)
        while (
            len(self._queue) < self.ask_ahead
            and self.study.next_tid < self.max_submits
        ):
            self._submit_one(deadline)
        if not self._queue:
            raise RuntimeError(
                f"client study {self.study_name!r}: ask budget "
                f"({self.max_submits}) exhausted"
            )
        req = self._queue.popleft()
        return self.service._await(
            req, max(deadline - time.perf_counter(), 0.001)
        )

    # -- docs --------------------------------------------------------------
    def insert_new_doc(self, tid, vals):
        """One NEW trial doc from served vals -- byte-for-byte what the
        solo algo seam would have inserted (same ``docs_from_idxs_vals``
        path over the same label set)."""
        tid = int(tid)
        idxs = {
            label: ([tid] if label in vals else [])
            for label in self.ps.labels
        }
        vv = {
            label: ([vals[label]] if label in vals else [])
            for label in self.ps.labels
        }
        docs = docs_from_idxs_vals(
            [tid], self.domain, self.trials, idxs, vv
        )
        self.trials.insert_trial_docs(docs)
        self.trials.refresh()
        for doc in reversed(self.trials._dynamic_trials):
            if doc["tid"] == tid:
                return doc
        raise RuntimeError(f"inserted doc for tid {tid} not found")

    # -- tells -------------------------------------------------------------
    def record_tell(self, trial, result=None):
        """Report one evaluation outcome to the engine, write-ahead of
        the doc finalizing (the PR-6 ordering, now through the ONE
        serve WAL): a posterior-ok result tells (vals + loss + the full
        SONified result dict for doc rebuild); anything dead -- failed
        status, non-finite/missing loss, or an ERROR doc -- fails the
        tid durably so resume never re-runs or re-serves it."""
        tid = int(trial["tid"])
        ok = False
        loss = None
        if result is not None and result.get("status") == STATUS_OK:
            loss = result.get("loss")
            ok = loss is not None and np.isfinite(float(loss))
        if ok:
            self.handle.tell(
                tid, float(loss), vals=_misc_vals(trial),
                result=SONify(result) if self.durable else None,
            )
            return
        doc = None
        if self.durable:
            doc = SONify({
                "state": (
                    JOB_STATE_ERROR if result is None else JOB_STATE_DONE
                ),
                "misc": trial["misc"],
                "result": result,
            })
        self.handle.fail(tid, doc=doc)

    # -- restore -----------------------------------------------------------
    def rebuild_trials(self, store=None):
        """The client half of restore: Trials docs from the snapshot
        bundle's client blob plus the WAL-suffix replay (tell records
        finalize exactly once, fail records rebuild their durable doc
        payloads, served-but-untold asks are NOT materialized -- the
        recover path re-delivers them and the normal loop rebuilds
        their docs).  Rebuilds INTO ``store`` when it is an empty
        sequential store (the caller keeps their handle), else into a
        fresh one of the same class."""
        st = self.study
        blob = st.client_blob or {}
        docs_by_tid = {}
        for d in blob.get("docs", ()):
            docs_by_tid[int(d["tid"])] = d
        for rec in st.restore_records or ():
            kind = rec.get("kind")
            if kind == "tell":
                tid = int(rec["tid"])
                have = docs_by_tid.get(tid)
                if have is not None and have["state"] == JOB_STATE_DONE:
                    continue  # bundle already carries the final doc
                result = rec.get("result") or {
                    "status": STATUS_OK, "loss": float(rec["loss"]),
                }
                docs_by_tid[tid] = self._rebuild_doc(
                    tid, dict(rec["vals"]), result, JOB_STATE_DONE
                )
            elif kind == "fail":
                tid = int(rec["tid"])
                have = docs_by_tid.get(tid)
                if have is not None and have["state"] in (
                    JOB_STATE_DONE, JOB_STATE_ERROR
                ):
                    continue
                payload = rec.get("doc") or {}
                docs_by_tid[tid] = self._rebuild_fail_doc(tid, payload)
        st.client_blob = None
        st.restore_records = None
        if store is not None and not store._dynamic_trials:
            trials = store
        else:
            trials = (type(store) if store is not None else Trials)()
        docs = [docs_by_tid[t] for t in sorted(docs_by_tid)]
        if docs:
            trials.insert_trial_docs(docs)
            trials.refresh()
        self.trials = trials
        return trials

    def _rebuild_doc(self, tid, vals, result, state):
        doc = self.insert_doc_shape(tid, vals, result)
        doc["state"] = state
        return doc

    def insert_doc_shape(self, tid, vals, result):
        """A doc dict (NOT inserted) from (tid, vals) -- deterministic,
        so WAL replay and the live loop produce identical misc."""
        labels = self.ps.labels
        misc = {
            "tid": tid,
            "cmd": self.domain.cmd,
            "workdir": self.domain.workdir,
            "idxs": {
                label: ([tid] if label in vals else [])
                for label in sorted(labels)
            },
            "vals": {
                label: ([vals[label]] if label in vals else [])
                for label in sorted(labels)
            },
        }
        store = self.trials if self.trials is not None else Trials()
        return store.new_trial_docs([tid], [None], [result], [misc])[0]

    def _rebuild_fail_doc(self, tid, payload):
        misc = payload.get("misc")
        state = payload.get("state", JOB_STATE_ERROR)
        result = payload.get("result")
        if misc is not None:
            store = self.trials if self.trials is not None else Trials()
            doc = store.new_trial_docs(
                [tid], [None],
                [result if result is not None else {"status": "new"}],
                [dict(misc)],
            )[0]
        else:  # a bare fail record (non-durable client wrote none)
            doc = self.insert_doc_shape(
                tid, {}, result if result is not None else {"status": "new"}
            )
        doc["state"] = state
        return doc

    # -- durability seams --------------------------------------------------
    def maybe_snapshot(self):
        """Trial-boundary snapshot cadence: the service defers client
        studies' snapshots to here, so the bundled doc blob can never
        capture a trial mid-finalize (tell WAL-durable, doc not yet
        DONE -- compacting that window away would strand the doc)."""
        if self.durable:
            self.study.persist.maybe_snapshot(self.study)

    def arm_durability(self):
        """Wire the client blob into the study's snapshot bundle and
        publish the anchor snapshot (fresh durable studies only):
        points_to_evaluate docs must survive a crash before the first
        cadence boundary, and WAL replay needs a bundle to be relative
        to -- exactly the PR-6 anchor-checkpoint rule."""
        if not self.durable:
            return
        st = self.study
        st.client_state_fn = lambda: {
            "format": 1,
            "docs": SONify(list(self.trials._dynamic_trials)),
        }
        if not self.restored:
            from .distributed import _common

            _common.with_retries(
                lambda: st.persist.snapshot(st), label="client anchor"
            )

    def finalize(self):
        """Orderly end of the run: drop the still-queued window tail,
        publish the final snapshot, close the study (and the service,
        when this client owns it).  Crashes never come here -- the WAL
        stays the truth."""
        if self.closed:
            return
        self.closed = True
        while self._queue:
            self.service.scheduler.drop_request(self._queue.popleft())
        if self.owns_service:
            if self._shared_key is not None:
                _release_shared(
                    self._shared_key, self.service, self.study_name
                )
            else:
                self.service.shutdown()  # close_study snapshots inside
        else:
            self.service.close_study(self.study_name)

    def abandon(self):
        """Crash-path release: drop the co-batching registry hold
        WITHOUT finalizing -- no final snapshot, no study close, no
        shutdown; the WAL stays the truth for restore (the solo crash
        posture).  A later ``connect()`` on the same family then builds
        a fresh service and restores from disk instead of silently
        riding the dead run's live one."""
        if self.closed:
            return
        self.closed = True
        if self._shared_key is not None:
            with _SHARED_LOCK:
                entry = _SHARED_SERVICES.get(self._shared_key)
                if entry is not None and entry[0] is self.service:
                    entry[1] -= 1
                    if entry[1] <= 0:
                        del _SHARED_SERVICES[self._shared_key]


def connect(engine, algo, domain, trials, rstate, fn=None, ask_ahead=1,
            root=None, require_existing=False, max_submits=None,
            recorder=None, fs=None):
    """Build the :class:`EngineClient` for one ``fmin`` call.

    ``engine`` is ``True`` (own an in-process service) or a caller's
    :class:`~hyperopt_tpu.serve.SuggestService` (chaos harnesses pass
    one with crash points armed on its ``fs`` seam).  ``root`` enables
    the unified durability layout; ``require_existing`` is the
    ``resume_from=`` posture (a missing root is refused).  Returns
    ``(client, trials, rstate, restored)`` -- on restore, the rebuilt
    Trials store and the study's restored rstate supersede the passed
    ones, exactly the PR-6 driver semantics.

    **Co-batching** (graftburst): ``engine=True`` connects through the
    shared-service registry -- concurrent ``fmin`` calls whose study
    family matches (same root, space, algo + knobs, objective) ride ONE
    wide scheduler, each as its own study (``fmin``, ``fmin-2``, ...),
    their asks vmapped together per round.  Every stream is bitwise its
    solo run; the last client out shuts the service down, so sequential
    runs (and restores) see exactly the solo layout.  ``fs=`` and
    ``recorder=`` opt out into a private service.
    """
    from .serve import SuggestService

    spec = resolve_engine_algo(algo)
    owns = not isinstance(engine, SuggestService)
    shared_key = None
    if owns:
        if fs is None and recorder is None:
            shared_key = _registry_key(spec, domain, fn, root)
            with _SHARED_LOCK:
                entry = _SHARED_SERVICES.get(shared_key)
                if entry is None:
                    service = SuggestService(
                        domain.expr, algo=spec.name, root=root,
                        max_batch=SHARED_MAX_BATCH, background=False,
                        n_startup_jobs=spec.n_startup_jobs,
                        snapshot_cadence=CLIENT_SNAPSHOT_CADENCE,
                        finite_check=False,
                        study_queue_cap=SHARED_QUEUE_CAP,
                        max_queue=8 * SHARED_MAX_BATCH,
                        **spec.algo_kw,
                    )
                    if fn is not None:
                        # objective identity joins the study guard:
                        # resuming this root under a different
                        # objective is refused
                        service._guard = _client_guard(
                            service._guard, fn
                        )
                    entry = _SHARED_SERVICES[shared_key] = [service, 0]
                entry[1] += 1
                service = entry[0]
        else:
            # an armed fault plan or a span recorder belongs to ONE
            # call: private service, the pre-graftburst shape
            service = SuggestService(
                domain.expr, algo=spec.name, root=root,
                max_batch=1, background=False,
                n_startup_jobs=spec.n_startup_jobs,
                snapshot_cadence=CLIENT_SNAPSHOT_CADENCE,
                finite_check=False,
                study_queue_cap=max(2, int(ask_ahead)),
                max_queue=max(8, 2 * int(ask_ahead)),
                recorder=recorder,
                **(dict(spec.algo_kw, fs=fs) if fs is not None
                   else spec.algo_kw),
            )
            if fn is not None:
                service._guard = _client_guard(service._guard, fn)
    else:
        service = engine
        if service.scheduler.algo != spec.name:
            raise ValueError(
                f"the provided engine serves algo "
                f"{service.scheduler.algo!r} but fmin's algo maps to "
                f"{spec.name!r}"
            )
        if root is not None and service.root != str(root):
            raise ValueError(
                "pass durability through the provided engine's root= "
                f"(engine root {service.root!r} != {root!r})"
            )
    try:
        if require_existing:
            from .serve.service import StudyPersistence

            probe = StudyPersistence(
                service.root, CLIENT_STUDY, None, fs=service.fs
            )
            if not probe.exists():
                probe.close()
                raise CheckpointError(
                    f"resume_from root {service.root!r} holds no "
                    f"{CLIENT_STUDY!r} study artifacts; pass "
                    "trials_save_file= to start a fresh recoverable "
                    "run instead"
                )
            probe.close()

        host_algo = None
        if spec.name == "atpe":
            # the hook closes over the LIVE trials store; on restore it
            # is rebound below once the rebuilt store exists
            host_algo = _make_host_hook(spec, domain, trials)
        # allocate-and-create under the registry lock: two co-batched
        # clients racing to open their studies must not both pick the
        # same free name
        with _SHARED_LOCK:
            study_name = _alloc_study_name(service)
            handle = service.create_study(study_name, seed=0,
                                          host_algo=host_algo)
        study = handle._study
        restored = bool(
            study.n_tells or study.pending_asks or study.outstanding
            or study.client_blob or study.n_asks
        )
        client = EngineClient(
            service, handle, spec, domain, trials, rstate,
            ask_ahead=ask_ahead, owns_service=owns,
            max_submits=max_submits, restored=restored,
            shared_key=shared_key,
        )
        if restored:
            trials = client.rebuild_trials(trials)
            rstate = study.rstate  # the post-draw cursor of the last ask
            client.rstate = rstate
            if spec.name == "atpe":
                study.host_algo = _make_host_hook(spec, domain, trials)
            logger.info(
                "resumed %d trial doc(s) from %r (study %r); rstate "
                "cursor restored -- the suggestion stream continues "
                "exactly where the previous run stopped",
                len(trials), service.root, study_name,
            )
        else:
            if trials is None:
                trials = Trials()
            client.trials = trials
            # the study's stream IS fmin's stream: submit-time seeds
            # come off the driver's own rstate
            study.rstate = rstate
        # depth-k window, posterior-fresh by construction
        study.fresh_window = 1
        client.arm_durability()
        return client, trials, rstate, restored
    except BaseException:
        # a failed connect must not strand its registry hold
        if shared_key is not None:
            with _SHARED_LOCK:
                entry = _SHARED_SERVICES.get(shared_key)
                if entry is not None and entry[0] is service:
                    entry[1] -= 1
                    if entry[1] <= 0:
                        del _SHARED_SERVICES[shared_key]
                        service.shutdown()
        raise


# ---------------------------------------------------------------------------
# the TCP study client (graftstorm)
# ---------------------------------------------------------------------------

#: the reply ``error_type`` -> typed exception map: a server-side
#: failure crosses the wire as a name and is re-raised as the matching
#: class, so the ONLY errors a RemoteStudy caller ever sees are the
#: typed hierarchy (the storm acceptance contract)
_REPLY_ERRORS = {
    "DeadlineExpired": DeadlineExpired,
    "DispatchTimeout": DispatchTimeout,
    "NetworkTimeout": NetworkTimeout,
    "OwnershipLost": OwnershipLost,
    "PeerUnreachable": PeerUnreachable,
    "ReplicaDead": ReplicaDead,
    "StudyPoisoned": StudyPoisoned,
    "StudyQuarantined": StudyQuarantined,
}


class RemoteStudy:
    """Exactly-once client for ONE study behind a TCP front (a serve
    process or the fleet router).

    The transport discipline the storm chaos suite pins:

    * every socket carries connect AND read deadlines
      (:func:`~.serve.frames.dial`): a silent peer surfaces typed
      :class:`NetworkTimeout`, never a hung client thread;
    * a transport failure (reset, torn frame, missed deadline, refused
      connect) drops the connection and retries the op on a fresh one
      with bounded backoff -- asks resubmit with ``recover=True`` (the
      service re-serves the oldest undelivered suggestion BITWISE
      instead of burning a fresh seed), tells resubmit with their
      explicit ``vals`` payload (the WAL tid-dedup absorbs the
      duplicate) -- so a lost ack never loses or duplicates a trial;
    * server-side errors come back typed (``error_type``) and are
      re-raised as the matching exceptions class;
    * typed ``Overloaded`` (queue caps, draining, the connection-cap
      refusal) is retried under the server's ``retry_after`` hint,
      capped -- backpressure paces the client, it never strands it.

    Retries are bounded: ``max_retries`` failed attempts on one op
    escalate to :class:`PeerUnreachable` (transport) or re-raise the
    last typed refusal (backpressure).  NOT thread-safe -- one
    RemoteStudy per driving thread, like :class:`~.serve.frames.
    FrameConn` underneath it.
    """

    def __init__(self, host, port, name, seed=0, connect_timeout=None,
                 read_timeout=None, net_plan=None, key=None,
                 max_retries=8, create=True, takeover=False):
        from .serve.frames import (
            DEFAULT_CONNECT_TIMEOUT, DEFAULT_READ_TIMEOUT,
        )

        self.host = host
        self.port = int(port)
        self.name = str(name)
        self.connect_timeout = (
            DEFAULT_CONNECT_TIMEOUT if connect_timeout is None
            else float(connect_timeout)
        )
        self.read_timeout = (
            DEFAULT_READ_TIMEOUT if read_timeout is None
            else float(read_timeout)
        )
        self.net_plan = net_plan
        self.key = key if key is not None else f"client/{name}"
        self.max_retries = int(max_retries)
        self.stats = collections.Counter()
        self._conn = None
        if create:
            self.call({
                "op": "create_study", "name": self.name,
                "seed": int(seed), "takeover": bool(takeover),
            })

    # -- transport ---------------------------------------------------------
    def _connect(self):
        from .serve.frames import FrameConn, dial

        if self._conn is None:
            _sock, f = dial(
                self.host, self.port,
                connect_timeout=self.connect_timeout,
                read_timeout=self.read_timeout,
                net_plan=self.net_plan, key=self.key,
            )
            self._conn = FrameConn(f)
        return self._conn

    def _drop(self):
        c, self._conn = self._conn, None
        if c is not None:
            c.close()

    def close(self):
        self._drop()

    def call(self, req, mutate=None):
        """One op, exactly-once under a hostile network: bounded
        transport retries on fresh connections (``mutate`` rewrites
        the request for resubmission -- the ask path's
        ``recover=True``), bounded ``Overloaded`` backoff under the
        server's own hint, typed re-raise for everything else."""
        from .distributed.faults import SimulatedCrash
        from .serve.frames import FrameError
        from .serve.service import RETRY_AFTER_CAP

        transport = (
            NetworkTimeout, PeerUnreachable, ConnectionError,
            FrameError, OSError,
        )
        last = None
        for attempt in range(self.max_retries + 1):
            if attempt:
                self.stats["retries"] += 1
                if mutate is not None:
                    req = mutate(req)
            try:
                reply = self._connect().call(req)
            except Overloaded as e:
                # the front's connection-cap refusal rides the hello
                # line: typed backpressure, retried under its hint
                self._drop()
                last = e
                self.stats["typed:Overloaded"] += 1
                time.sleep(min(  # graftlint: disable=GL303 the backoff IS the server's typed retry_after hint, capped and bounded by the attempt budget
                    e.retry_after or 0.05, RETRY_AFTER_CAP
                ))
                continue
            except SimulatedCrash:
                # the armed NET crash point: this client "died" in the
                # send/ack window.  Drop the conn so a harness that
                # restarts the client on this object resumes clean,
                # then die for real (BaseException propagates)
                self._drop()
                raise
            except transport as e:
                self._drop()
                last = e
                self.stats["transport_errors"] += 1
                self.stats[f"transport:{type(e).__name__}"] += 1
                time.sleep(min(0.01 * (attempt + 1), 0.05))  # graftlint: disable=GL303 bounded linear backoff under the max_retries attempt budget -- not an unbounded retry loop
                continue
            if reply.get("ok"):
                return reply
            etype = reply.get("error_type")
            if etype == "Overloaded":
                last = Overloaded(
                    reply.get("error") or "overloaded",
                    retry_after=reply.get("retry_after"),
                    reason=reply.get("reason") or "queue_full",
                )
                self.stats["typed:Overloaded"] += 1
                time.sleep(min(  # graftlint: disable=GL303 the backoff IS the server's typed retry_after hint, capped and bounded by the attempt budget
                    last.retry_after or 0.05, RETRY_AFTER_CAP
                ))
                continue
            self.stats[f"typed:{etype}"] += 1
            exc = _REPLY_ERRORS.get(etype)
            if exc is not None:
                raise exc(reply.get("error") or etype)
            if etype == "FrameError":
                # the server closed past a framing error; the conn is
                # dead -- retry on a fresh one
                self._drop()
                last = FrameError(reply.get("error") or "framing error")
                self.stats["transport_errors"] += 1
                continue
            raise RuntimeError(
                f"study {self.name!r}: server error "
                f"{etype or '?'}: {reply.get('error')}"
            )
        if isinstance(last, Overloaded):
            raise last
        raise PeerUnreachable(
            f"study {self.name!r}: {self.max_retries + 1} attempts "
            f"exhausted against {self.host}:{self.port} (last: "
            f"{type(last).__name__ if last else '?'}: {last})"
        ) from (last if isinstance(last, Exception) else None)

    # -- the study API -----------------------------------------------------
    def ask(self, timeout=60.0):
        """The next (tid, vals): resubmitted with ``recover=True``
        after any transport failure, so a suggestion the service
        already logged is re-delivered bitwise, never re-drawn."""
        reply = self.call(
            {"op": "ask", "study": self.name, "timeout": float(timeout)},
            mutate=lambda r: dict(r, recover=True),
        )
        return reply["tid"], reply["vals"]

    def tell(self, tid, loss, vals):
        """Report one result.  ``vals`` is REQUIRED: a re-tell after a
        lost ack must carry the full payload (the service refuses a
        payload-less tell for a tid it no longer has outstanding), and
        the WAL tid-dedup absorbs the duplicate exactly-once."""
        self.call({
            "op": "tell", "study": self.name, "tid": int(tid),
            "loss": float(loss), "vals": vals,
        })

    def best(self):
        return self.call({"op": "best", "study": self.name})["best"]

    def close_study(self):
        self.call({"op": "close_study", "study": self.name})
        self._drop()
