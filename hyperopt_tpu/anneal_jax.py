"""Annealing as one jitted XLA program -- the TPU-native anneal path.

Same plugin boundary and semantics as :mod:`hyperopt_tpu.anneal`
(capability parity with the reference's ``hyperopt/anneal.py``, SURVEY.md
SS2), re-designed for the TPU execution model like
:mod:`hyperopt_tpu.tpe_jax`: the whole suggest step -- anchor pick
(geometric over loss rank), per-dimension shrinking neighborhoods,
prior fallbacks for inactive/conditional dims, conditional activity --
is a single compiled program over the dense masked observation buffers,
vmapped over the requested batch of trials.  No per-trial or
per-hyperparameter Python loop.
"""

from __future__ import annotations

from .jax_trials import cached_suggest_fn, host_key, obs_buffer_for, packed_space_for
from .rand import docs_from_idxs_vals
from .vectorize import dense_to_idxs_vals

__all__ = ["suggest", "suggest_batch", "build_anneal_fn"]

_default_avg_best_idx = 2.0
_default_shrink_coef = 0.1


def build_anneal_fn(ps, avg_best_idx, shrink_coef, state_io=False,
                    raw=False):
    """Compile the full annealing suggest step for a PackedSpace.

    Returns jitted ``fn(key, values, active, losses, valid, batch) ->
    (new_values [D, B], new_active [D, B])`` with ``batch`` static.
    ``state_io=True`` returns the fused tell+ask variant instead (same
    contract as :func:`hyperopt_tpu.tpe_jax.build_suggest_fn`'s: a
    staged O(D) observation delta is applied to the donated state
    buffers and the suggestion drawn from the updated history, one
    dispatch total).  ``raw=True`` returns the unjitted closure (the
    :mod:`hyperopt_tpu.serve.batched` vmap seam -- same contract as
    :func:`tpe_jax.build_suggest_fn`'s).  Matches
    :class:`hyperopt_tpu.anneal.AnnealingAlgo` semantics:

    * anchor trial per suggestion: rank ``geometric(1/avg_best_idx) - 1``
      into the loss-sorted ok history (clamped);
    * continuous dims: bounded dims draw uniform on the anchor-centred
      interval of latent width ``(high-low) * frac``, clipped to the
      bounds; unbounded dims draw ``normal(anchor, sigma * frac)``;
      ``frac = 1 / (1 + n_obs_d * shrink_coef)`` with per-dim obs counts;
    * categorical dims: redraw from the prior with probability ``frac``,
      else keep the anchor's category;
    * any dim inactive on the anchor trial (conditional branch not taken)
      or an empty history falls back to a prior draw.
    """
    import jax
    import jax.numpy as jnp

    c = ps._consts
    D = ps.n_dims
    Dc = len(ps.cont_idx)
    Dk = len(ps.cat_idx)
    abi = float(avg_best_idx)
    sc = float(shrink_coef)

    def fn(key, values, active, losses, valid, batch):
        kr, ku, kz, kcoin, kp = jax.random.split(key, 5)

        ok = valid & jnp.isfinite(losses)
        n_ok = jnp.sum(ok.astype(jnp.int32))
        order = jnp.argsort(jnp.where(ok, losses, jnp.inf), stable=True)

        # geometric(p)-1 ranks via inverse transform; p = 1/avg_best_idx
        # (explicit f32: an un-dtyped uniform widens to f64 under x64,
        # the promotion class the GL402 IR check pins at trace time)
        p = 1.0 / max(abi, 1.0 + 1e-9)
        u = jax.random.uniform(
            kr, (batch,), dtype=jnp.float32, minval=1e-12, maxval=1.0
        )
        rank = jnp.floor(jnp.log(u) / jnp.log1p(-p)).astype(jnp.int32)
        rank = jnp.clip(rank, 0, jnp.maximum(n_ok - 1, 0))
        cols = order[rank]  # [B] anchor slots

        anchor_vals = values[:, cols]  # [D, B]
        anchor_act = active[:, cols] & (n_ok > 0)  # [D, B]

        # per-dim observation counts -> neighborhood shrink fraction
        n_obs = jnp.sum((active & ok[None, :]).astype(jnp.float32), axis=1)
        frac = 1.0 / (1.0 + n_obs * sc)  # [D]

        prior_vals, _ = ps.sample_prior_fn(kp, batch)  # [D, B]
        new_values = jnp.zeros((D, batch), dtype=jnp.float32)

        if Dc:
            ci = c["cont_idx"]
            a_nat = anchor_vals[ci]
            lat_a = jnp.where(
                c["logspace"][:, None],
                jnp.log(jnp.maximum(a_nat, 1e-30)),
                a_nat,
            )
            low, high = c["low"][:, None], c["high"][:, None]
            fr = frac[ci][:, None]
            bounded = jnp.isfinite(low)

            uu = jax.random.uniform(ku, (Dc, batch), dtype=jnp.float32)
            zz = jax.random.normal(kz, (Dc, batch), dtype=jnp.float32)

            width = (high - low) * fr
            lo2 = jnp.maximum(low, lat_a - width / 2.0)
            hi2 = jnp.minimum(high, lat_a + width / 2.0)
            lat_b = lo2 + uu * jnp.maximum(hi2 - lo2, 0.0)
            lat_u = lat_a + c["prior_sigma"][:, None] * fr * zz
            lat = jnp.where(bounded, lat_b, lat_u)

            from .ops.kernels import quantize_nat

            nat = jnp.where(c["logspace"][:, None], jnp.exp(lat), lat)
            nat = quantize_nat(
                nat, c["q"][:, None], low, high, c["logspace"][:, None]
            )
            nat = jnp.where(anchor_act[ci], nat, prior_vals[ci])
            new_values = new_values.at[ci].set(nat)

        if Dk:
            ki = c["cat_idx"]
            coin = jax.random.uniform(kcoin, (Dk, batch), dtype=jnp.float32)
            redraw = coin < frac[ki][:, None]
            cat = jnp.where(
                redraw | ~anchor_act[ki], prior_vals[ki], anchor_vals[ki]
            )
            new_values = new_values.at[ki].set(cat)

        return new_values, ps.active_fn(new_values)

    if not state_io:
        if raw:
            return fn
        return jax.jit(fn, static_argnames=("batch",))

    from .ops import kernels as K

    def fused(key, values, active, losses, valid, vcol, acol, loss, idx,
              batch):
        state = K.apply_delta(
            values, active, losses, valid, vcol, acol, loss, idx
        )
        new_values, new_active = fn(key, *state, batch)
        return tuple(state) + (new_values, new_active)

    if raw:
        return fused
    return jax.jit(
        fused, static_argnames=("batch",), donate_argnums=(1, 2, 3, 4)
    )


def _anneal_builder(ps_, abi, sc, sio):
    return build_anneal_fn(ps_, abi, sc, state_io=sio)


def _dense_draw(domain, trials, seed, batch, avg_best_idx, shrink_coef):
    import jax

    from .tpe_jax import _state_dispatch

    ps = packed_space_for(domain)
    buf = obs_buffer_for(domain, trials)
    key = host_key(int(seed) % (2**31 - 1))

    if buf.count == 0:
        buf.dispatch_count += 1
        values, active = ps.sample_prior(key, batch)
    else:
        params = (float(avg_best_idx), float(shrink_coef))
        fn = cached_suggest_fn(
            domain, "_anneal_jax_cache", params + (False,), _anneal_builder,
        )
        fused = (
            cached_suggest_fn(
                domain, "_anneal_jax_cache", params + (True,),
                _anneal_builder,
            )
            if buf.resident
            else None
        )
        values, active = _state_dispatch(buf, key, batch, None, fn, fused)
    return jax.device_get((values, active))


def suggest_batch(
    new_ids,
    domain,
    trials,
    seed,
    avg_best_idx=_default_avg_best_idx,
    shrink_coef=_default_shrink_coef,
):
    """Sparse (idxs, vals) for a batch of ids -- one device program."""
    from .tpe_jax import _cast_vals

    ps = packed_space_for(domain)
    values, active = _dense_draw(
        domain, trials, seed, len(new_ids), avg_best_idx, shrink_coef
    )
    idxs, vals = dense_to_idxs_vals(new_ids, ps.labels, values, active)
    return _cast_vals(ps, idxs, vals)


def suggest(
    new_ids,
    domain,
    trials,
    seed,
    avg_best_idx=_default_avg_best_idx,
    shrink_coef=_default_shrink_coef,
    speculative=0,
    max_stale=None,
    resident=None,
):
    """The TPU plugin-boundary entry point: ``algo=anneal_jax.suggest``.

    ``speculative=k`` serves k sequential asks from one k-wide draw
    (same cache/staleness semantics as :func:`tpe_jax.suggest`: the
    anchor distribution refreshes on every redraw, and the cache
    invalidates once the history moves past ``max_stale``).

    ``resident=True`` keeps the observation mirror device-resident:
    sequential tells become O(D) deltas and, with exactly one tell
    pending, the delta is fused into the ask dispatch via the
    ``state_io`` program variant -- same one-dispatch semantics and
    bitwise-identical suggestions as :func:`tpe_jax.suggest`'s resident
    path (shared :func:`tpe_jax._state_dispatch` engine).

    COMPATIBILITY STATUS (round 20, graftclient): the solo resident /
    speculative modes are the parity reference; a sequential ``fmin``
    routes this same anneal body through the serve engine
    (``fmin(engine=True)`` / ``ask_ahead=k`` -- bitwise this stream at
    any depth, with the serve tier's durability and protection).
    """
    ps = packed_space_for(domain)
    if resident is not None:
        obs_buffer_for(domain, trials, resident=bool(resident))
    if speculative and len(new_ids) == 1:
        from .tpe_jax import _cast_vals, _speculative_cols

        params = (
            "anneal", float(avg_best_idx), float(shrink_coef),
            id(trials), int(speculative),
            int(speculative) - 1 if max_stale is None else int(max_stale),
        )
        values, active = _speculative_cols(
            domain, trials, seed, int(speculative), max_stale, params,
            1,  # 'warm' flips once any history exists (prior -> anneal)
            lambda s, k: _dense_draw(
                domain, trials, s, k, avg_best_idx, shrink_coef
            ),
        )
        idxs, vals = dense_to_idxs_vals(new_ids, ps.labels, values, active)
        idxs, vals = _cast_vals(ps, idxs, vals)
    else:
        idxs, vals = suggest_batch(
            new_ids, domain, trials, seed,
            avg_best_idx=avg_best_idx, shrink_coef=shrink_coef,
        )
    return docs_from_idxs_vals(new_ids, domain, trials, idxs, vals)


# ---------------------------------------------------------------------------
# graftir registrations (hyperopt-tpu-lint --ir)
# ---------------------------------------------------------------------------

from .ops.compile import ProgramCapture, register_program  # noqa: E402

_ANNEAL_FAMILIES = ("hyperopt_tpu.anneal_jax:build_anneal_fn",)


@register_program("anneal_jax.suggest", families=_ANNEAL_FAMILIES)
def _registry_anneal_suggest(p):
    _ = p.space._consts
    fn = build_anneal_fn(p.space, _default_avg_best_idx,
                         _default_shrink_coef)
    return ProgramCapture(
        fn=fn, args=(p.key_spec(),) + p.history_specs(),
        kwargs={"batch": p.batch},
    )


@register_program("anneal_jax.fused_tell_ask", families=_ANNEAL_FAMILIES)
def _registry_anneal_fused(p):
    """The annealing twin of ``tpe_jax.fused_tell_ask`` (same donated
    ``state_io`` contract, shared ``_state_dispatch`` driver)."""
    _ = p.space._consts
    fn = build_anneal_fn(p.space, _default_avg_best_idx,
                         _default_shrink_coef, state_io=True)
    return ProgramCapture(
        fn=fn,
        args=(p.key_spec(),) + p.history_specs() + p.delta_specs(),
        kwargs={"batch": 1},
        donate_argnums=(1, 2, 3, 4),
    )
