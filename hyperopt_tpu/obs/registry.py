"""graftscope's typed metrics registry: bounded by construction.

Three metric types over one shared lock:

* :class:`Counter` -- a monotone total (``inc``); the migration target
  for every ad-hoc ``self.x += 1`` counter attribute the serve stack
  accumulated (GL307 now flags the old pattern);
* :class:`Gauge` -- a point-in-time value (``set``/``inc``/``dec``),
  ``None`` until first set so "never happened" reads unambiguously
  (``Fleet.recovery_ms`` before the first failover);
* :class:`Histogram` -- FIXED buckets plus a bounded ring of raw
  observations (``maxlen`` -- the PR-8 ``METRICS_WINDOW`` idiom), so
  percentile reads (bench) keep working while exposition gets real
  bucket counts.  Nothing in a histogram grows per event (GL306-clean
  by construction).

Cardinality is capped at registration: a labeled metric declares its
label NAMES up front and its label-value sets are bounded at
``label_cap`` children -- the child for any further label value is the
shared ``_overflow`` series, so a misbehaving caller can degrade
resolution but never memory.

Reads are snapshot-consistent: :meth:`MetricsRegistry.collect` takes
the registry lock once and returns plain dicts, so a scrape racing a
dispatch round never sees a half-updated histogram.

Back-compat descriptors (:class:`CounterAttr` / :class:`GaugeAttr` /
:class:`HistogramAttr`) expose registry metrics AS the plain attribute
names the codebase already reads (``scheduler.dispatch_count``,
``buf.transfer_bytes_total``, ``scheduler.ask_latencies``), so every
pre-graftscope read path -- bench, tests, counters dicts -- keeps
working unchanged while the storage moves onto the registry.

Timing helpers (``Gauge.set_duration_ms`` / ``Histogram.
observe_since``) compute the delta INSIDE the registry, so library
code never needs an inline ``time.perf_counter() - t0`` expression --
the exact ad-hoc pattern GL307 retires.
"""

from __future__ import annotations

import collections
import threading
import time

__all__ = [
    "Counter",
    "Gauge",
    "Histogram",
    "MetricsRegistry",
    "CounterAttr",
    "GaugeAttr",
    "HistogramAttr",
    "DEFAULT_WINDOW",
    "DEFAULT_LABEL_CAP",
    "LATENCY_BUCKETS_S",
    "LATENCY_BUCKETS_MS",
    "RATIO_BUCKETS",
]

#: ring-buffer length for histogram raw-value windows (the PR-8
#: METRICS_WINDOW: plenty for any bench window, bounded for a
#: long-running service)
DEFAULT_WINDOW = 65536

#: label-value children a labeled metric may materialize before new
#: values collapse into the shared overflow series
DEFAULT_LABEL_CAP = 64

#: the overflow label value unbounded-cardinality callers collapse into
OVERFLOW_LABEL = "_overflow"

LATENCY_BUCKETS_S = (
    0.0005, 0.001, 0.0025, 0.005, 0.01, 0.025, 0.05, 0.1, 0.25, 0.5,
    1.0, 2.5, 5.0, 10.0, float("inf"),
)
LATENCY_BUCKETS_MS = (
    0.5, 1.0, 2.5, 5.0, 10.0, 25.0, 50.0, 100.0, 250.0, 500.0, 1000.0,
    2500.0, 5000.0, float("inf"),
)
RATIO_BUCKETS = (
    0.1, 0.25, 0.5, 0.75, 0.9, 1.0, float("inf"),
)


class Counter:
    """A monotone total.  ``set_total`` exists only for the back-compat
    descriptors (``self.x += 1`` round-trips through get+set)."""

    kind = "counter"

    def __init__(self, name, help="", lock=None, labels=None):
        self.name = name
        self.help = help
        self.labels = dict(labels or {})
        self._lock = lock if lock is not None else threading.RLock()
        self._value = 0

    def inc(self, n=1):
        with self._lock:
            self._value += n

    def set_total(self, value):
        with self._lock:
            self._value = value

    @property
    def value(self):
        with self._lock:
            return self._value

    def collect(self):
        with self._lock:
            return {
                "name": self.name, "type": self.kind, "help": self.help,
                "labels": dict(self.labels), "value": self._value,
            }

    def __getstate__(self):
        d = self.__dict__.copy()
        d.pop("_lock", None)
        return d

    def __setstate__(self, d):
        self.__dict__.update(d)
        self._lock = threading.RLock()


class Gauge:
    """A point-in-time value; ``None`` until first set."""

    kind = "gauge"

    def __init__(self, name, help="", lock=None, labels=None):
        self.name = name
        self.help = help
        self.labels = dict(labels or {})
        self._lock = lock if lock is not None else threading.RLock()
        self._value = None

    def set(self, value):
        with self._lock:
            self._value = value

    def inc(self, n=1):
        with self._lock:
            self._value = (self._value or 0) + n

    def dec(self, n=1):
        self.inc(-n)

    def set_duration_ms(self, t0):
        """Set to the milliseconds elapsed since ``t0`` (a
        ``time.perf_counter()`` instant) -- the registry-side timing
        helper that retires inline ad-hoc deltas (GL307)."""
        self.set(1000.0 * (time.perf_counter() - t0))

    @property
    def value(self):
        with self._lock:
            return self._value

    def collect(self):
        with self._lock:
            return {
                "name": self.name, "type": self.kind, "help": self.help,
                "labels": dict(self.labels), "value": self._value,
            }

    def __getstate__(self):
        d = self.__dict__.copy()
        d.pop("_lock", None)
        return d

    def __setstate__(self, d):
        self.__dict__.update(d)
        self._lock = threading.RLock()


class _Ring(collections.deque):
    """The histogram's bounded raw-value window: still a deque (the
    pre-graftscope read paths slice/sort/len it), but ``append`` also
    feeds the fixed buckets so direct appends -- the back-compat write
    path -- never desynchronize the exposition."""

    def __init__(self, hist, iterable=(), maxlen=DEFAULT_WINDOW):
        super().__init__(iterable, maxlen)
        self._hist = hist

    def append(self, v):
        collections.deque.append(self, v)
        self._hist._bucket_add(v)

    def __reduce__(self):  # pickled via the owning Histogram only
        return (list, (list(self),))


class Histogram:
    """Fixed-bucket counts + a bounded ring of raw observations.

    ``buckets`` are upper bounds (the last is ``+inf``); ``observe``
    (or a direct ``ring.append`` from a back-compat attribute) bumps
    exactly one cumulative-count cell, the running sum, and the ring.
    Bounded by construction: ``len(buckets)`` cells + ``window`` ring
    slots, forever.
    """

    kind = "histogram"

    def __init__(self, name, help="", buckets=LATENCY_BUCKETS_S,
                 window=DEFAULT_WINDOW, lock=None, labels=None):
        self.name = name
        self.help = help
        self.labels = dict(labels or {})
        buckets = tuple(float(b) for b in buckets)
        if not buckets or buckets[-1] != float("inf"):
            buckets = buckets + (float("inf"),)
        self.buckets = buckets
        self.window = int(window)
        self._lock = lock if lock is not None else threading.RLock()
        self._counts = [0] * len(buckets)
        self._sum = 0.0
        self._count = 0
        self.ring = _Ring(self, maxlen=self.window)

    def _bucket_add(self, v):
        with self._lock:
            v = float(v)
            for i, b in enumerate(self.buckets):
                if v <= b:
                    self._counts[i] += 1
                    break
            self._sum += v
            self._count += 1

    def observe(self, v):
        self.ring.append(v)

    def observe_since(self, t0):
        """Observe the seconds elapsed since ``t0`` (see
        :meth:`Gauge.set_duration_ms`)."""
        self.observe(time.perf_counter() - t0)

    @property
    def count(self):
        with self._lock:
            return self._count

    def collect(self):
        with self._lock:
            return {
                "name": self.name, "type": self.kind, "help": self.help,
                "labels": dict(self.labels),
                "buckets": [
                    {"le": b, "count": c}
                    for b, c in zip(self.buckets, self._counts)
                ],
                "sum": self._sum,
                "count": self._count,
            }

    def __getstate__(self):
        d = self.__dict__.copy()
        d["ring"] = list(self.ring)
        d.pop("_lock", None)
        return d

    def __setstate__(self, d):
        ring = d.pop("ring", [])
        self.__dict__.update(d)
        self._lock = threading.RLock()
        self.ring = _Ring(self, ring, maxlen=self.window)


class _LabeledMetric:
    """Cardinality-bounded family of one metric type: children keyed by
    label-value tuples, capped at ``label_cap`` -- past the cap every
    new combination shares the ``_overflow`` child."""

    def __init__(self, factory, name, help, label_names, label_cap,
                 lock, **kw):
        self.name = name
        self.kind = factory.kind
        self.help = help
        self.label_names = tuple(label_names)
        self.label_cap = int(label_cap)
        self._factory = factory
        self._kw = kw
        self._lock = lock
        self._children = {}

    def labels(self, **values):
        key = tuple(str(values.get(n, "")) for n in self.label_names)
        with self._lock:
            child = self._children.get(key)
            if child is None:
                if len(self._children) >= self.label_cap:
                    key = (OVERFLOW_LABEL,) * len(self.label_names)
                    child = self._children.get(key)
                if child is None:
                    child = self._factory(
                        self.name, help=self.help, lock=self._lock,
                        labels=dict(zip(self.label_names, key)),
                        **self._kw,
                    )
                    self._children[key] = child
            return child

    def collect(self):
        with self._lock:
            children = list(self._children.values())
        return [c.collect() for c in children]

    def __getstate__(self):
        d = self.__dict__.copy()
        d.pop("_lock", None)
        return d

    def __setstate__(self, d):  # graftlint: disable=GL501 unpickle-time: the object is not yet visible to any other thread, and the lock it re-shares is created on this line
        self.__dict__.update(d)
        self._lock = threading.RLock()
        for c in self._children.values():
            c._lock = self._lock


class MetricsRegistry:
    """One component's metrics, under one lock.

    ``const_labels`` stamp every collected series (the fleet sets
    ``replica=<owner>`` so a router-side merge can tell replicas
    apart).  Metrics are get-or-create by name with a type check --
    two callers registering ``serve_dispatch_total`` as different
    types is a bug, not a silent shadow.
    """

    def __init__(self, namespace="", const_labels=None,
                 label_cap=DEFAULT_LABEL_CAP):
        self.namespace = str(namespace)
        self.const_labels = dict(const_labels or {})
        self.label_cap = int(label_cap)
        self._lock = threading.RLock()
        self._metrics = {}

    def _get_or_create(self, factory, name, help, labels, **kw):
        with self._lock:
            m = self._metrics.get(name)
            if m is None:
                if labels:
                    m = _LabeledMetric(
                        factory, name, help, labels, self.label_cap,
                        self._lock, **kw,
                    )
                else:
                    m = factory(name, help=help, lock=self._lock, **kw)
                self._metrics[name] = m
            elif m.kind != factory.kind:
                raise TypeError(
                    f"metric {name!r} already registered as {m.kind}, "
                    f"not {factory.kind}"
                )
            return m

    def counter(self, name, help="", labels=()):
        return self._get_or_create(Counter, name, help, tuple(labels))

    def gauge(self, name, help="", labels=()):
        return self._get_or_create(Gauge, name, help, tuple(labels))

    def histogram(self, name, help="", buckets=LATENCY_BUCKETS_S,
                  window=DEFAULT_WINDOW, labels=()):
        return self._get_or_create(
            Histogram, name, help, tuple(labels),
            buckets=buckets, window=window,
        )

    def collect(self):
        """Snapshot-consistent read: one lock acquisition, plain
        dicts out (``const_labels`` merged into every series)."""
        with self._lock:
            metrics = list(self._metrics.values())
            const = dict(self.const_labels)
        out = []
        for m in metrics:
            got = m.collect()
            for row in got if isinstance(got, list) else [got]:
                row["labels"] = {**const, **row["labels"]}
                out.append(row)
        return out

    # registries ride along inside pickled ObsBuffers (checkpoint
    # bundles, attachments): locks are not picklable, values are
    def __getstate__(self):
        d = self.__dict__.copy()
        d.pop("_lock", None)
        return d

    def __setstate__(self, d):  # graftlint: disable=GL501 unpickle-time: the registry is not yet visible to any other thread, and the lock it re-shares is created on this line
        self.__dict__.update(d)
        self._lock = threading.RLock()
        for m in self._metrics.values():
            if isinstance(m, _LabeledMetric):
                m._lock = self._lock
                for c in m._children.values():
                    c._lock = self._lock
            else:
                m._lock = self._lock


def _instance_registry(obj, attr):
    reg = getattr(obj, attr, None)
    if reg is None:
        # lazily heal objects unpickled from pre-graftscope artifacts
        reg = MetricsRegistry()
        setattr(obj, attr, reg)
    return reg


class CounterAttr:
    """Descriptor exposing a registry :class:`Counter` behind a plain
    numeric attribute name: ``self.dispatch_count += 1`` keeps working
    (get + set round-trip) while the storage, exposition, and bounds
    live on the instance's :class:`MetricsRegistry` (found at
    ``registry_attr``, created lazily for unpickled old objects)."""

    def __init__(self, name, help="", registry_attr="metrics"):
        self.name = name
        self.help = help
        self.registry_attr = registry_attr

    def _metric(self, obj):
        return _instance_registry(obj, self.registry_attr).counter(
            self.name, help=self.help
        )

    def __get__(self, obj, objtype=None):
        if obj is None:
            return self
        return self._metric(obj).value

    def __set__(self, obj, value):
        self._metric(obj).set_total(value)


class GaugeAttr:
    """:class:`CounterAttr`'s gauge twin (``None`` until first set)."""

    def __init__(self, name, help="", registry_attr="metrics"):
        self.name = name
        self.help = help
        self.registry_attr = registry_attr

    def _metric(self, obj):
        return _instance_registry(obj, self.registry_attr).gauge(
            self.name, help=self.help
        )

    def __get__(self, obj, objtype=None):
        if obj is None:
            return self
        return self._metric(obj).value

    def __set__(self, obj, value):
        self._metric(obj).set(value)


class HistogramAttr:
    """Descriptor exposing a registry :class:`Histogram`'s bounded
    ring behind the deque attribute name the code already appends to
    and the bench already slices (``scheduler.ask_latencies``)."""

    def __init__(self, name, help="", buckets=LATENCY_BUCKETS_S,
                 window=DEFAULT_WINDOW, registry_attr="metrics"):
        self.name = name
        self.help = help
        self.buckets = buckets
        self.window = window
        self.registry_attr = registry_attr

    def histogram(self, obj):
        return _instance_registry(obj, self.registry_attr).histogram(
            self.name, help=self.help, buckets=self.buckets,
            window=self.window,
        )

    def __get__(self, obj, objtype=None):
        if obj is None:
            return self
        return self.histogram(obj).ring
