"""Device-side event streaming: graftscope's declared io_callback twin.

Two seams feed device-side signals into the registry without touching
the hot programs:

* :func:`build_device_metrics_fn` -- a SEPARATE tiny compiled program
  over the serve stack's stacked history arrays (losses/valid with the
  leading study axis) that reduces per-round occupancy / trials-done /
  best-loss on device and ships ONE ordered ``io_callback`` row to the
  host sink.  The scheduler dispatches it only on its
  ``device_metrics_every`` cadence -- cadence off means the twin is
  never even built, so disabled tracing costs exactly zero extra
  dispatches (the pin in ``tests/test_obs.py``).  Registered in
  graftir as ``obs.device_metrics`` with the callback DECLARED in
  ``allowed_callbacks`` (GL401's contract: an undeclared callback is a
  finding, and so is a stale declaration).
* :func:`progress_to_registry` -- the adapter that turns the chunked
  device loop's existing declared progress rows (PR 10's
  ``progress_callback`` seam) into registry gauges/counters, so
  ``compile_fmin(metrics_registry=...)`` streams per-chunk
  trials/sec + best-loss without a second callback program.
"""

from __future__ import annotations

import time

from ..ops.compile import ProgramCapture, register_program

__all__ = ["build_device_metrics_fn", "progress_to_registry"]


def build_device_metrics_fn(sink):
    """Compile the metrics twin: ``(losses [S,N], valid [S,N], active
    [S]) -> n_active`` with one ordered ``io_callback`` shipping
    ``{"active_slots", "trials_done", "best_loss"}`` to ``sink``.

    Read-only by contract: no donation, no state outputs -- the round's
    streams cannot be perturbed by dispatching it (the invisibility
    invariant), only by its wall-clock cost, which the cadence bounds.
    """
    import jax
    import jax.numpy as jnp
    from jax.experimental import io_callback

    def _emit(n_active, done, best):
        sink({
            "active_slots": int(n_active),
            "trials_done": int(done),
            "best_loss": float(best),
        })

    def metrics_fn(losses, valid, active):
        ok = valid & jnp.isfinite(losses) & active[:, None]
        best = jnp.min(jnp.where(ok, losses, jnp.inf))
        done = jnp.sum(ok)
        n_active = jnp.sum(active)
        # the ONLY sanctioned host hop in this family: declared in the
        # graftir registration's allowed_callbacks (GL401 contract)
        io_callback(_emit, None, n_active, done, best, ordered=True)
        return n_active

    return jax.jit(metrics_fn)


def progress_to_registry(registry, recorder=None, t0=None):
    """A ``progress_callback`` for :func:`hyperopt_tpu.device_loop.
    compile_fmin` that lands each declared per-chunk row on
    ``registry``: ``device_loop_best_loss`` / ``device_loop_trials_done``
    gauges, ``device_loop_trials_per_sec`` (since ``t0``, default the
    adapter's construction), and the ``obs_device_events_total``
    counter; ``recorder`` (optional) gets a ``device.chunk`` span per
    row."""
    start = time.perf_counter() if t0 is None else t0
    best = registry.gauge(
        "device_loop_best_loss", "best finite loss so far (per chunk)"
    )
    done_g = registry.gauge(
        "device_loop_trials_done", "trials completed so far"
    )
    rate = registry.gauge(
        "device_loop_trials_per_sec", "trials/sec since the run started"
    )
    events = registry.counter(
        "obs_device_events_total",
        "device->host metric rows received via declared io_callback",
    )

    def callback(row):
        best.set(row["best_loss"])
        done_g.set(row["trials_done"])
        dt = time.perf_counter() - start  # graftlint: disable=GL307 elapsed-run denominator for the trials/sec gauge (the gauge IS the registry sink)
        if dt > 0:
            rate.set(row["trials_done"] / dt)
        events.inc()
        if recorder is not None:
            recorder.event("device.chunk", **row)

    return callback


# ---------------------------------------------------------------------------
# graftir registration (hyperopt-tpu-lint --ir)
# ---------------------------------------------------------------------------


@register_program(
    "obs.device_metrics",
    families=("hyperopt_tpu.obs.device:build_device_metrics_fn",),
)
def _registry_device_metrics(p):
    """The serve metrics twin over the stacked study axis: read-only
    reduction + one DECLARED ordered io_callback, no donation."""
    import jax
    import jax.numpy as jnp

    fn = build_device_metrics_fn(lambda row: None)
    s, n = p.n_studies, p.n_obs
    return ProgramCapture(
        fn=fn,
        args=(
            jax.ShapeDtypeStruct((s, n), jnp.float32),
            jax.ShapeDtypeStruct((s, n), jnp.bool_),
            jax.ShapeDtypeStruct((s,), jnp.bool_),
        ),
        allowed_callbacks=("io_callback",),
    )
