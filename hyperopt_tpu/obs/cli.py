"""``hyperopt-tpu-scope``: scrape metrics / tail spans from a live
replica, the whole fleet through the router, or a flight-log file.

Examples::

    # one replica's metrics, Prometheus text
    hyperopt-tpu-scope metrics --port 7077

    # the WHOLE fleet in one call (point at the router)
    hyperopt-tpu-scope metrics --port 7076 --json

    # the last 20 spans of a live replica's flight recorder
    hyperopt-tpu-scope trace --port 7077 --tail 20

    # a flight-recorder file, offline (post-mortem)
    hyperopt-tpu-scope flight /var/run/study-root/flight.wal --tail 50
"""

from __future__ import annotations

import argparse
import json


def _rpc(host, port, req, timeout=30.0):
    # graftstorm: dial() carries both connect and read deadlines, so a
    # hung replica surfaces typed NetworkTimeout instead of stranding
    # the console
    from ..serve.frames import dial

    sock, f = dial(
        host, int(port), connect_timeout=timeout, read_timeout=timeout,
    )
    try:
        f.write((json.dumps(req) + "\n").encode("utf-8"))
        f.flush()
        line = f.readline()
    finally:
        f.close()
        sock.close()
    if not line:
        raise ConnectionError(f"{host}:{port} closed the connection")
    return json.loads(line)


def _span_line(span):
    fixed = {"name", "ts", "dur_ms", "seq"}
    ids = " ".join(
        f"{k}={span[k]}" for k in sorted(span) if k not in fixed
    )
    dur = f" {span['dur_ms']:.3f}ms" if "dur_ms" in span else ""
    return f"{span.get('ts', 0):.6f} {span['name']}{dur} {ids}".rstrip()


def main(argv=None):
    parser = argparse.ArgumentParser(
        prog="hyperopt-tpu-scope",
        description="graftscope console: scrape Prometheus-style "
        "metrics from a serve replica (or the whole fleet via the "
        "router), tail trace spans, or read a flight-log file.",
    )
    sub = parser.add_subparsers(dest="cmd", required=True)

    for name, doc in (
        ("metrics", "scrape /metrics-style exposition over the "
         "JSON-line protocol (a router target aggregates every live "
         "replica in one call)"),
        ("trace", "tail the flight recorder of a live target"),
    ):
        p = sub.add_parser(name, help=doc)
        p.add_argument("--host", default="127.0.0.1")
        p.add_argument("--port", type=int, required=True)
        p.add_argument("--timeout", type=float, default=30.0)
        p.add_argument("--json", action="store_true",
                       help="print raw JSON instead of text")
        if name == "trace":
            p.add_argument("--tail", type=int, default=50)

    p = sub.add_parser(
        "flight", help="read a flight-recorder file offline"
    )
    p.add_argument("path")
    p.add_argument("--tail", type=int, default=None)
    p.add_argument("--json", action="store_true")

    args = parser.parse_args(argv)

    if args.cmd == "flight":
        from .flightrec import read_flight_log

        spans = read_flight_log(args.path, tail=args.tail)
        if args.json:
            print(json.dumps(spans))
        else:
            for s in spans:
                print(_span_line(s))
        return 0

    if args.cmd == "metrics":
        reply = _rpc(
            args.host, args.port, {"op": "metrics"}, timeout=args.timeout
        )
        if not reply.get("ok"):
            print(json.dumps(reply))
            return 1
        if args.json:
            print(json.dumps(reply.get("metrics", [])))
        else:
            print(reply.get("text", ""), end="")
        return 0

    # trace
    reply = _rpc(
        args.host, args.port,
        {"op": "trace", "tail": args.tail}, timeout=args.timeout,
    )
    if not reply.get("ok"):
        print(json.dumps(reply))
        return 1
    spans = reply.get("spans", [])
    if args.json:
        print(json.dumps(spans))
    else:
        for s in spans:
            print(_span_line(s))
    return 0


if __name__ == "__main__":
    raise SystemExit(main())
