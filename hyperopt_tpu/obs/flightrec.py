"""graftscope's trace-span flight recorder.

Spans are flat dicts -- ``{"name", "ts", "dur_ms", **ids}`` -- recorded
into a bounded in-memory ring (the last ``capacity`` spans are always
inspectable over the ``trace`` op / ``hyperopt-tpu-scope trace``) and,
when a ``path`` is configured, appended to a WAL-style durable export:
one checksummed line per span in exactly the :mod:`~hyperopt_tpu.utils.
wal` record format, written through the PR-3 ``fs=`` seam so the chaos
suites can crash it (``obs_flight_export_mid_append`` leaves a torn
line) and ``hyperopt-tpu-fsck --obs`` can truncate the torn tail the
same way driver/serve WAL recovery does.

The span taxonomy (DESIGN.md SS3f) covers the full ask/tell lifecycle,
carrying study/tid/slot/shard/replica ids end-to-end::

    ask.submit      admitted into the scheduler queue (event)
    ask.queued      submit -> picked into a dispatch round
    serve.dispatch  one batched device dispatch (n picked, slots, shards)
    ask.delivered   submit -> ack (the client-visible latency)
    tell.wal_append the durability barrier of one tell
    tell.applied    host-buffer + staged-delta application
    tell            the whole tell critical section

The invisibility invariant: recording is OBSERVATION ONLY -- no span
ever touches an rstate stream, a seed draw, or device state, so every
parity/chaos suite passes bitwise with a recorder armed at full
cadence (``tests/test_obs.py`` pins it).  ``NULL_RECORDER`` is the
default everywhere: disarmed call sites pay one no-op method call.

Exports are flush-only (kernel-visible, surviving process death; only
a machine crash tears the tail, which recovery absorbs) -- a span is
telemetry, not a tell: it never earns an fsync barrier on the hot
path.  :meth:`FlightRecorder.flush` adds an explicit barrier for
orderly shutdown.
"""

from __future__ import annotations

import collections
import os
import threading
import time

from ..distributed.faults import REAL_FS
from ..utils.wal import _decode_line, _encode_record

__all__ = [
    "FlightRecorder",
    "NullRecorder",
    "NULL_RECORDER",
    "read_flight_log",
    "audit_flight_log",
    "repair_flight_log",
]

DEFAULT_CAPACITY = 4096

FLIGHT_MAGIC = "hyperopt-tpu-flight-1"


class NullRecorder:
    """The disarmed recorder: every call is a no-op.  Call sites keep
    one unconditional ``recorder.record(...)`` instead of branching."""

    enabled = False

    def record(self, name, t0=None, t1=None, **ids):
        pass

    def event(self, name, **ids):
        pass

    def tail(self, n=None):
        return []

    def flush(self):
        pass

    def close(self):
        pass


NULL_RECORDER = NullRecorder()


class FlightRecorder:
    """Bounded span ring + optional WAL-style durable export.

    ``capacity`` bounds the in-memory ring; ``cadence`` samples spans
    (1 = full cadence, k keeps every k-th; admission is per-span and
    deterministic in the record sequence, never in time); ``path``
    arms the durable export through ``fs``.
    """

    enabled = True

    def __init__(self, capacity=DEFAULT_CAPACITY, path=None, fs=REAL_FS,
                 cadence=1):
        self.capacity = int(capacity)
        self.path = None if path is None else str(path)
        self.fs = fs
        self.cadence = max(1, int(cadence))
        self._lock = threading.RLock()
        self._ring = collections.deque(maxlen=self.capacity)
        self._f = None
        self._seq = 0
        self.recorded_total = 0
        self.sampled_out = 0
        self.exported_total = 0

    # -- recording ---------------------------------------------------------
    def record(self, name, t0=None, t1=None, **ids):
        """Record one span.  ``t0``/``t1`` are ``time.perf_counter()``
        instants (both None = a point event); ``ids`` are the
        study/tid/slot/shard/replica correlation fields."""
        with self._lock:
            self._seq += 1
            if self.cadence > 1 and (self._seq - 1) % self.cadence:
                self.sampled_out += 1
                return None
            span = {"name": str(name), "ts": time.time()}
            if t0 is not None and t1 is not None:
                span["dur_ms"] = 1000.0 * (t1 - t0)
            span.update(ids)
            self._ring.append(span)
            self.recorded_total += 1
            if self.path is not None:
                self._export(span)
            return span

    def event(self, name, **ids):
        return self.record(name, **ids)

    def tail(self, n=None):
        """The most recent ``n`` spans (all, when None) -- plain dict
        copies, safe to mutate/serialize."""
        with self._lock:
            spans = list(self._ring)
        if n is not None:
            spans = spans[-int(n):]
        return [dict(s) for s in spans]

    # -- durable export ----------------------------------------------------
    def _ensure_open(self):  # graftlint: disable=GL503 one-time header publish (or torn-tail truncation) when the log is first opened; every later append through here is flush-only
        if self._f is None:
            if not self.fs.exists(self.path):
                tmp = f"{self.path}.tmp.{os.getpid()}"
                with self.fs.open(tmp, "w") as f:
                    f.write(_encode_record(
                        {"seq": -1, "magic": FLIGHT_MAGIC}
                    ))
                    self.fs.fsync(f)
                self.fs.rename(tmp, self.path)
            else:
                # the torn-tail rule at reopen: a restarted recorder
                # must append onto a valid prefix, never bury a crash's
                # torn line mid-file
                repair_flight_log(self.path, fs=self.fs)
            self._f = self.fs.open(self.path, "a")

    def _export(self, span):
        """Append one checksummed line (flush-only; lock held).  The
        crash point fires mid-record, leaving a torn line exactly like
        a machine crash would -- the recovery the fsck path pins."""
        try:
            self._ensure_open()
            line = _encode_record(dict(span, seq=self._seq))
            half = max(1, len(line) // 2)
            self._f.write(line[:half])
            self.fs.crashpoint("obs_flight_export_mid_append")
            self._f.write(line[half:])
            self._f.flush()
            self.exported_total += 1
        except OSError:
            # telemetry must never take the serving path down: drop
            # the handle (a torn partial record is the torn-tail rule's
            # job) and keep recording in memory
            self._drop_handle()
        except BaseException:
            # simulated process death mid-append: release the handle
            # over the torn line (reopen truncates it) and keep dying
            self._drop_handle()
            raise

    def _drop_handle(self):
        if self._f is not None:
            try:
                self._f.close()
            except OSError:
                pass
            self._f = None

    def flush(self):
        """Explicit durability barrier (shutdown/cadence points)."""
        with self._lock:
            f = self._f
        if f is not None:
            f.flush()
            self.fs.fsync(f)

    def close(self):
        with self._lock:
            self._drop_handle()


# ---------------------------------------------------------------------------
# reading + fsck (the --obs family)
# ---------------------------------------------------------------------------


def _scan_flight_log(path, fs=REAL_FS):
    """(header, spans, good_bytes, torn_bytes, bad_lines) -- the WAL
    scan rule applied to a flight log, except mid-file corruption is
    REPORTED (a span log is telemetry: fsck quarantines nothing, it
    just counts what it had to skip)."""
    with fs.open(path, "rb") as f:
        raw = f.read()
    lines = raw.splitlines(keepends=True)
    header, spans, good, bad = None, [], 0, 0
    for i, bline in enumerate(lines):
        try:
            line = bline.decode("utf-8")
        except UnicodeDecodeError:
            line = ""
        body = _decode_line(line)
        if body is None:
            if i == len(lines) - 1:
                break  # torn tail
            bad += 1  # mid-file garbage: skipped, counted
            good += len(bline)
            continue
        if body.get("seq") == -1:
            if header is None:
                header = body
        else:
            spans.append(body)
        good += len(bline)
    return header, spans, good, len(raw) - good, bad


def read_flight_log(path, fs=REAL_FS, tail=None):
    """Valid spans of a flight log (torn tail ignored)."""
    _h, spans, _g, _t, _b = _scan_flight_log(path, fs=fs)
    return spans[-int(tail):] if tail is not None else spans


def audit_flight_log(path, fs=REAL_FS):
    """fsck audit: ``[(kind, path, detail), ...]`` issue rows."""
    issues = []
    if not fs.exists(path):
        issues.append(("obs_missing", path, "no flight log at path"))
        return issues
    header, spans, _good, torn, bad = _scan_flight_log(path, fs=fs)
    if header is None or header.get("magic") != FLIGHT_MAGIC:
        issues.append((
            "obs_bad_header", path,
            f"missing/foreign header {header!r}",
        ))
    if torn:
        issues.append((
            "obs_torn_tail", path,
            f"{torn} torn tail byte(s) after {len(spans)} valid span(s)",
        ))
    if bad:
        issues.append((
            "obs_corrupt_records", path,
            f"{bad} corrupt mid-file record(s) skipped",
        ))
    return issues


def repair_flight_log(path, fs=REAL_FS):
    """Truncate a torn tail atomically (tmp + fsync + rename); returns
    the bytes dropped.  Mid-file corruption stays in place -- the
    scanner already skips it, and telemetry is not worth quarantining."""
    _h, _spans, good, torn, _bad = _scan_flight_log(path, fs=fs)
    if not torn:
        return 0
    with fs.open(path, "rb") as f:
        raw = f.read()
    tmp = f"{path}.tmp.{os.getpid()}"
    with fs.open(tmp, "wb") as f:
        f.write(raw[:good])
        fs.fsync(f)
    fs.rename(tmp, path)
    return torn
