"""Prometheus-style text + JSON exposition for graftscope registries.

The wire unit is the COLLECTED series row (see
:meth:`~hyperopt_tpu.obs.registry.MetricsRegistry.collect`): a plain
dict carrying name/type/help/labels and either a scalar ``value`` or a
histogram's buckets/sum/count.  Rows are what the serve ``metrics`` op
ships as JSON, what the router merges across replicas (tagging each
row with its ``replica`` label), and what :func:`render_prometheus`
renders -- so a fleet-wide scrape is one router call that concatenates
rows, not N separate text documents glued together.
"""

from __future__ import annotations

import math

__all__ = ["render_prometheus", "tag_rows", "merge_rows"]


def _fmt_value(v):
    if v is None:
        return "NaN"
    v = float(v)
    if math.isinf(v):
        return "+Inf" if v > 0 else "-Inf"
    if v == int(v) and abs(v) < 1e15:
        return str(int(v))
    return repr(v)


def _escape(s):
    return (
        str(s).replace("\\", "\\\\").replace('"', '\\"').replace("\n", "\\n")
    )


def _label_str(labels, extra=None):
    items = dict(labels or {})
    if extra:
        items.update(extra)
    if not items:
        return ""
    body = ",".join(
        f'{k}="{_escape(v)}"' for k, v in sorted(items.items())
    )
    return "{" + body + "}"


def tag_rows(rows, **labels):
    """Stamp extra labels onto collected rows (the router tags each
    replica's rows with ``replica=<rid>`` before merging); rows that
    already carry a label keep their own value."""
    out = []
    for row in rows:
        row = dict(row)
        row["labels"] = {**labels, **(row.get("labels") or {})}
        out.append(row)
    return out


def merge_rows(*row_lists):
    """Concatenate collected-row lists (label sets keep the series
    distinct; exposition groups HELP/TYPE by name)."""
    out = []
    for rows in row_lists:
        out.extend(rows)
    return out


def render_prometheus(rows):
    """Collected rows -> Prometheus text exposition.  HELP/TYPE are
    emitted once per metric name (first row's help wins); histogram
    rows expand into cumulative ``_bucket``/``_sum``/``_count``."""
    seen = set()
    lines = []
    for row in rows:
        name = row["name"]
        if name not in seen:
            seen.add(name)
            if row.get("help"):
                lines.append(f"# HELP {name} {row['help']}")
            lines.append(f"# TYPE {name} {row.get('type', 'untyped')}")
        labels = row.get("labels") or {}
        if row.get("type") == "histogram":
            acc = 0
            for b in row["buckets"]:
                acc += b["count"]
                le = "+Inf" if math.isinf(b["le"]) else _fmt_value(b["le"])
                lines.append(
                    f"{name}_bucket{_label_str(labels, {'le': le})} {acc}"
                )
            lines.append(
                f"{name}_sum{_label_str(labels)} {_fmt_value(row['sum'])}"
            )
            lines.append(
                f"{name}_count{_label_str(labels)} {row['count']}"
            )
        else:
            lines.append(
                f"{name}{_label_str(labels)} {_fmt_value(row.get('value'))}"
            )
    return "\n".join(lines) + ("\n" if lines else "")
