"""graftscope: the unified observability subsystem.

One schema for every operational signal the stack emits:

* :mod:`.registry` -- the typed, bounded metrics registry
  (Counter/Gauge/Histogram, label cardinality capped at registration,
  snapshot-consistent reads) plus the back-compat descriptors that
  keep every pre-graftscope attribute read path working;
* :mod:`.flightrec` -- trace spans for the ask/tell lifecycle in a
  bounded flight recorder with a WAL-style durable export
  (``hyperopt-tpu-fsck --obs`` recovers a torn tail);
* :mod:`.device` -- device-side event streaming: the declared
  ``io_callback`` metrics twin (graftir ``obs.device_metrics``) and
  the device-loop progress adapter;
* :mod:`.expo` -- Prometheus-style text + JSON exposition, merged
  fleet-wide by the router;
* :mod:`.cli` -- the ``hyperopt-tpu-scope`` console script (scrape a
  replica or the whole fleet through the router; tail spans live or
  from a flight-log file).

The governing invariant (tested, not aspirational): observability is
**bitwise-invisible** -- arming a recorder at full cadence changes no
suggestion stream, no WAL byte, no recovery outcome; and disabled
device-metrics cadence dispatches exactly zero extra programs.
"""

from .expo import merge_rows, render_prometheus, tag_rows
from .flightrec import (
    NULL_RECORDER,
    FlightRecorder,
    NullRecorder,
    audit_flight_log,
    read_flight_log,
    repair_flight_log,
)
from .registry import (
    Counter,
    CounterAttr,
    Gauge,
    GaugeAttr,
    Histogram,
    HistogramAttr,
    MetricsRegistry,
)

__all__ = [
    "Counter",
    "CounterAttr",
    "FlightRecorder",
    "Gauge",
    "GaugeAttr",
    "Histogram",
    "HistogramAttr",
    "MetricsRegistry",
    "NULL_RECORDER",
    "NullRecorder",
    "audit_flight_log",
    "merge_rows",
    "read_flight_log",
    "render_prometheus",
    "repair_flight_log",
    "tag_rows",
]
