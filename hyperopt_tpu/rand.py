"""Random-search suggest algorithm (host/numpy parity path).

Capability parity with the reference's ``hyperopt/rand.py`` (SURVEY.md SS2):
draw each new trial's config from the prior by evaluating the space with a
seeded RNG.  The jitted TPU equivalent is :mod:`hyperopt_tpu.rand_jax`.
"""

from __future__ import annotations

from .pyll.stochastic import ensure_rng
from .vectorize import VectorizeHelper

__all__ = ["suggest", "suggest_batch"]


def _domain_helper(domain):
    helper = getattr(domain, "_vectorize_helper", None)
    if helper is None:
        helper = VectorizeHelper(domain.expr)
        domain._vectorize_helper = helper
    return helper


def docs_from_idxs_vals(new_ids, domain, trials, idxs, vals):
    """Build NEW trial documents from a sparse batch encoding."""
    labels = sorted(idxs)
    rval_specs = []
    rval_results = []
    rval_miscs = []
    for tid in new_ids:
        misc = {
            "tid": tid,
            "cmd": domain.cmd,
            "workdir": domain.workdir,
            "idxs": {label: [] for label in labels},
            "vals": {label: [] for label in labels},
        }
        rval_specs.append(None)
        rval_results.append(domain.new_result())
        rval_miscs.append(misc)
    by_tid = {m["tid"]: m for m in rval_miscs}
    for label in labels:
        for tid, val in zip(idxs[label], vals[label]):
            by_tid[tid]["idxs"][label] = [tid]
            by_tid[tid]["vals"][label] = [val]
    return trials.new_trial_docs(new_ids, rval_specs, rval_results, rval_miscs)


def suggest_batch(new_ids, domain, trials, seed):
    """Sparse (idxs, vals) for a batch of new trial ids."""
    rng = ensure_rng(seed)
    helper = _domain_helper(domain)
    return helper.sample_batch(new_ids, rng)


def suggest(new_ids, domain, trials, seed):
    """The algo plugin-boundary entry point (SURVEY.md SS2 L3)."""
    idxs, vals = suggest_batch(new_ids, domain, trials, seed)
    return docs_from_idxs_vals(new_ids, domain, trials, idxs, vals)


# Validation flag checked by fmin: random search explores the full prior,
# so fmin's duplicate-coverage warning does not apply.
suggest.is_exhaustive = False
