"""ctypes loader/builder for the native host-math library.

Compiles ``tpe_math.cpp`` on first use (g++, cached next to the source),
binds via ctypes (no pybind11 dependency), and exposes numpy-friendly
wrappers with the exact :mod:`hyperopt_tpu.tpe` semantics.  Everything
degrades gracefully: ``available()`` is False when no compiler or the
build fails, and callers fall back to numpy.

Opt out with ``HYPEROPT_TPU_NATIVE=0``; force with ``=1`` (raises if the
build fails).
"""

from __future__ import annotations

import ctypes
import logging
import os
import subprocess
import sysconfig
import threading

import numpy as np

logger = logging.getLogger(__name__)

__all__ = ["available", "gmm_lpdf", "adaptive_parzen", "lib_path", "build"]

_HERE = os.path.dirname(os.path.abspath(__file__))
_SRC = os.path.join(_HERE, "tpe_math.cpp")
_LOCK = threading.Lock()
_STATE = {"lib": None, "tried": False}


def lib_path():
    tag = sysconfig.get_platform().replace("-", "_")
    return os.path.join(_HERE, f"libtpe_math_{tag}.so")


def build(force=False):
    """Compile the shared library; returns its path or raises."""
    out = lib_path()
    if os.path.exists(out) and not force:
        if os.path.getmtime(out) >= os.path.getmtime(_SRC):
            return out
    cmd = [
        "g++", "-O3", "-std=c++17", "-shared", "-fPIC",
        _SRC, "-o", out + ".tmp",
    ]
    subprocess.run(cmd, check=True, capture_output=True, timeout=120)
    os.replace(out + ".tmp", out)
    logger.info("built native tpe_math: %s", out)
    return out


def _load():
    with _LOCK:
        if _STATE["tried"]:
            return _STATE["lib"]
        _STATE["tried"] = True
        mode = os.environ.get("HYPEROPT_TPU_NATIVE", "auto")
        if mode == "0":
            return None
        try:
            lib = ctypes.CDLL(build())
        except Exception as e:
            if mode == "1":
                raise
            logger.debug("native tpe_math unavailable: %s", e)
            return None

        c_double_p = ctypes.POINTER(ctypes.c_double)
        lib.ht_gmm_lpdf.argtypes = [
            c_double_p, ctypes.c_int64, c_double_p, c_double_p, c_double_p,
            ctypes.c_int64, ctypes.c_double, ctypes.c_double, ctypes.c_double,
            ctypes.c_int32, c_double_p,
        ]
        lib.ht_gmm_lpdf.restype = None
        lib.ht_adaptive_parzen.argtypes = [
            c_double_p, ctypes.c_int64, ctypes.c_double, ctypes.c_double,
            ctypes.c_double, ctypes.c_int64, c_double_p, c_double_p, c_double_p,
        ]
        lib.ht_adaptive_parzen.restype = ctypes.c_int64
        _STATE["lib"] = lib
        return lib


def available():
    return _load() is not None


def _as_c(a):
    arr = np.ascontiguousarray(a, dtype=np.float64)
    return arr, arr.ctypes.data_as(ctypes.POINTER(ctypes.c_double))


def gmm_lpdf(x, w, mu, sigma, low=None, high=None, q=None, logspace=False):
    """Native truncated/quantized (log)GMM log-density; None if no lib."""
    lib = _load()
    if lib is None:
        return None
    x_arr, x_p = _as_c(np.atleast_1d(x))
    w_arr, w_p = _as_c(w)
    mu_arr, mu_p = _as_c(mu)
    sig_arr, sig_p = _as_c(sigma)
    out = np.empty(x_arr.shape[0], dtype=np.float64)
    lib.ht_gmm_lpdf(
        x_p, x_arr.shape[0], w_p, mu_p, sig_p, w_arr.shape[0],
        float(-np.inf if low is None else low),
        float(np.inf if high is None else high),
        float(0.0 if q is None else q),
        int(bool(logspace)),
        out.ctypes.data_as(ctypes.POINTER(ctypes.c_double)),
    )
    return out


def adaptive_parzen(mus, prior_weight, prior_mu, prior_sigma, lf):
    """Native adaptive-Parzen fit; None if no lib."""
    lib = _load()
    if lib is None:
        return None
    mus_arr, mus_p = _as_c(np.atleast_1d(np.asarray(mus, dtype=np.float64)))
    n = mus_arr.shape[0] if np.asarray(mus).size else 0
    m = n + 1
    w = np.empty(m)
    mu = np.empty(m)
    sig = np.empty(m)
    lib.ht_adaptive_parzen(
        mus_p, n, float(prior_weight), float(prior_mu), float(prior_sigma),
        int(lf or 0),
        w.ctypes.data_as(ctypes.POINTER(ctypes.c_double)),
        mu.ctypes.data_as(ctypes.POINTER(ctypes.c_double)),
        sig.ctypes.data_as(ctypes.POINTER(ctypes.c_double)),
    )
    return w, mu, sig
