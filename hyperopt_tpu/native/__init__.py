"""ctypes loader/builder for the native host-math library.

Compiles ``tpe_math.cpp`` on first use (g++, cached next to the source),
binds via ctypes (no pybind11 dependency), and exposes numpy-friendly
wrappers with the exact :mod:`hyperopt_tpu.tpe` semantics.  Everything
degrades gracefully: ``available()`` is False when no compiler or the
build fails, and callers fall back to numpy.

Opt out with ``HYPEROPT_TPU_NATIVE=0``; force with ``=1`` (raises if the
build fails).
"""

from __future__ import annotations

import ctypes
import logging
import os
import subprocess
import sysconfig
import threading

import numpy as np

logger = logging.getLogger(__name__)

__all__ = ["available", "gmm_lpdf", "adaptive_parzen", "lib_path", "build"]

_HERE = os.path.dirname(os.path.abspath(__file__))
_SRC = os.path.join(_HERE, "tpe_math.cpp")
_LOCK = threading.Lock()
_STATE = {"lib": None, "tried": False, "strict_error": None}


def _cpu_tag():
    """Capability token folded into the cache filename: -march=native
    code from one CPU must never be loaded on a different one (SIGILL,
    not a graceful fallback).  Hash of the cpuinfo flags line on Linux;
    'generic' elsewhere (those builds skip the cache-poisoning risk by
    being keyed per machine class only)."""
    try:
        with open("/proc/cpuinfo") as f:
            for line in f:
                if line.startswith("flags"):
                    import hashlib

                    return hashlib.md5(line.encode()).hexdigest()[:8]
    except OSError:
        pass
    return "generic"


def lib_path():
    tag = sysconfig.get_platform().replace("-", "_")
    return os.path.join(_HERE, f"libtpe_math_{tag}_{_cpu_tag()}.so")


def build(force=False):
    """Compile the shared library; returns its path or raises."""
    out = lib_path()
    if os.path.exists(out) and not force:
        if os.path.getmtime(out) >= os.path.getmtime(_SRC):
            return out
    cmd = [
        "g++", "-O3", "-std=c++17", "-shared", "-fPIC",
        # built on the machine that runs it (first-use build), so
        # -march=native is safe; -fno-math-errno lets gcc vectorize the
        # exp/erf loops via libmvec where available
        "-march=native", "-fno-math-errno", "-funroll-loops",
        _SRC, "-o", out + ".tmp",
    ]
    subprocess.run(cmd, check=True, capture_output=True, timeout=120)
    os.replace(out + ".tmp", out)
    logger.info("built native tpe_math: %s", out)
    return out


def _raise_strict():
    """Strict mode (=1) must fail EVERY caller, not just the first --
    silently returning None would degrade later calls to the numpy
    fallback strict mode exists to forbid.  A FRESH wrapper is raised per
    call (re-raising one shared exception object would grow its
    __traceback__ forever), and the env var is re-read so flipping to
    =0/auto after a strict failure restores the graceful fallback."""
    if os.environ.get("HYPEROPT_TPU_NATIVE", "auto") != "1":
        return None
    err = _STATE["strict_error"]
    raise RuntimeError(
        f"native tpe_math build failed under HYPEROPT_TPU_NATIVE=1: {err}"
    ) from err


def _load():
    # lock-free fast path: after the first resolution this runs on every
    # hot-path call (28x per host suggest), and a mutex acquisition per
    # call measurably hurt the native-vs-numpy comparison.  "tried" is
    # published ONLY after the final lib/None outcome is in _STATE, so a
    # concurrent caller during the (seconds-long) first build blocks on
    # the lock instead of observing a half-initialized None.
    if _STATE["tried"]:
        if _STATE["strict_error"] is not None:
            return _raise_strict()
        return _STATE["lib"]
    with _LOCK:
        if _STATE["tried"]:
            if _STATE["strict_error"] is not None:
                return _raise_strict()
            return _STATE["lib"]
        mode = os.environ.get("HYPEROPT_TPU_NATIVE", "auto")
        if mode == "0":
            _STATE["tried"] = True
            return None
        try:
            lib = ctypes.CDLL(build())
        except Exception as e:
            if mode == "1":
                _STATE["strict_error"] = e  # cached: re-raised per call
            _STATE["tried"] = True  # don't rebuild-loop on a broken env
            if mode == "1":
                raise
            logger.debug("native tpe_math unavailable: %s", e)
            return None

        # pointers bind as c_void_p so callers can pass the raw
        # ``arr.ctypes.data`` integer -- building a typed POINTER view
        # per argument per call was the dominant wrapper cost
        p = ctypes.c_void_p
        lib.ht_gmm_lpdf.argtypes = [
            p, ctypes.c_int64, p, p, p,
            ctypes.c_int64, ctypes.c_double, ctypes.c_double, ctypes.c_double,
            ctypes.c_int32, p,
        ]
        lib.ht_gmm_lpdf.restype = None
        lib.ht_adaptive_parzen.argtypes = [
            p, ctypes.c_int64, ctypes.c_double, ctypes.c_double,
            ctypes.c_double, ctypes.c_int64, p, p, p,
        ]
        lib.ht_adaptive_parzen.restype = ctypes.c_int64
        _STATE["lib"] = lib
        _STATE["tried"] = True
        return lib


def available():
    return _load() is not None


def _as_c(a):
    """C-contiguous float64 view (no copy when already compliant) and its
    raw data address.  ``arr.ctypes.data`` (an int) is much cheaper per
    call than building a typed POINTER view with ``data_as``."""
    if (
        type(a) is np.ndarray
        and a.dtype == _F64
        and a.flags.c_contiguous
    ):
        return a, a.ctypes.data
    arr = np.ascontiguousarray(a, dtype=np.float64)
    return arr, arr.ctypes.data


_F64 = np.dtype(np.float64)


def gmm_lpdf(x, w, mu, sigma, low=None, high=None, q=None, logspace=False):
    """Native truncated/quantized (log)GMM log-density; None if no lib."""
    lib = _load()
    if lib is None:
        return None
    x_arr, x_p = _as_c(np.atleast_1d(x))
    w_arr, w_p = _as_c(w)
    _mu_arr, mu_p = _as_c(mu)
    _sig_arr, sig_p = _as_c(sigma)
    out = np.empty(x_arr.shape[0], dtype=np.float64)
    lib.ht_gmm_lpdf(
        x_p, x_arr.shape[0], w_p, mu_p, sig_p, w_arr.shape[0],
        -np.inf if low is None else float(low),
        np.inf if high is None else float(high),
        0.0 if q is None else float(q),
        1 if logspace else 0,
        out.ctypes.data,
    )
    return out


def adaptive_parzen(mus, prior_weight, prior_mu, prior_sigma, lf):
    """Native adaptive-Parzen fit; None if no lib."""
    lib = _load()
    if lib is None:
        return None
    mus_arr, mus_p = _as_c(np.atleast_1d(np.asarray(mus, dtype=np.float64)))
    n = mus_arr.shape[0]
    m = n + 1
    w = np.empty(m)
    mu = np.empty(m)
    sig = np.empty(m)
    lib.ht_adaptive_parzen(
        mus_p, n, float(prior_weight), float(prior_mu), float(prior_sigma),
        int(lf or 0),
        w.ctypes.data, mu.ctypes.data, sig.ctypes.data,
    )
    return w, mu, sig
