// Native host-side TPE math: truncated/quantized (log)GMM log-densities
// and the adaptive-Parzen fit.
//
// Role: the reference framework is numpy-bound pure Python (SURVEY.md SS2
// "native-code checklist"); here the *device* hot path is XLA/Pallas, and
// this library is the native runtime for the HOST path -- the numpy-parity
// TPE (oracle, CPU-only deployments, ATPE inner loops), where per-suggest
// latency is dominated by exactly these loops.  Deterministic functions
// only (sampling stays in numpy so seeded reproducibility is preserved);
// semantics bit-match hyperopt_tpu/tpe.py within float tolerance, enforced
// by tests/test_native.py.
//
// Build: g++ -O3 -march=native -shared -fPIC tpe_math.cpp -o libtpe_math.so
// (driven by hyperopt_tpu/native/__init__.py; ctypes binding, no pybind11).

#include <algorithm>
#include <cmath>
#include <cstdint>
#include <vector>

namespace {

constexpr double kEps = 1e-12;
constexpr double kTiny = 1e-300;
constexpr double kLogSqrt2Pi = 0.918938533204672741780329736406;
const double kSqrt2 = std::sqrt(2.0);

inline double normal_cdf(double x, double mu, double sigma) {
  double s = std::max(sigma, kEps);
  return 0.5 * (1.0 + std::erf((x - mu) / (s * kSqrt2)));
}

inline double log_sum_exp_pair(double acc, double term) {
  // acc, term in log space
  if (term == -INFINITY) return acc;
  if (acc == -INFINITY) return term;
  double m = std::max(acc, term);
  return m + std::log(std::exp(acc - m) + std::exp(term - m));
}

}  // namespace

extern "C" {

// out[s] = log p(x_s) under the truncated / quantized / (log-space) GMM.
// low/high are latent-space bounds (+-inf accepted); q <= 0 means
// unquantized; logspace != 0 means lognormal mixture (x in natural space).
void ht_gmm_lpdf(const double* x, int64_t S, const double* w,
                 const double* mu, const double* sigma, int64_t K,
                 double low, double high, double q, int32_t logspace,
                 double* out) {
  // Per-component constants hoisted out of the S*K loops; the former
  // running pairwise log-sum-exp paid 2 exp + 1 log PER TERM, which is
  // why numpy's vectorized single-max pass overtook this path at large
  // K (measured: 0.83x at 2,500 obs).  Continuous: c1 folds every
  // additive term, inner loop is one fused z^2 (pass 1) + one exp
  // (pass 2).  Quantized: weights/mass accumulate in LINEAR space
  // (masses are non-negative), one log per sample.
  std::vector<double> logw(K), log_mass(K), inv_sig(K), c1(K), wmass(K);
  double wsum = 0.0;
  for (int64_t k = 0; k < K; ++k) wsum += w[k];
  if (wsum <= 0.0) wsum = 1.0;
  for (int64_t k = 0; k < K; ++k) {
    double wk = w[k] / wsum;
    logw[k] = std::log(std::max(wk, kTiny));
    double a = std::isinf(low) ? 0.0 : normal_cdf(low, mu[k], sigma[k]);
    double b = std::isinf(high) ? 1.0 : normal_cdf(high, mu[k], sigma[k]);
    double mass_k = std::max(b - a, kEps);
    log_mass[k] = std::log(mass_k);
    inv_sig[k] = 1.0 / std::max(sigma[k], kEps);
    c1[k] = logw[k] + std::log(inv_sig[k]) - kLogSqrt2Pi - log_mass[k];
    wmass[k] = wk / mass_k;
  }

  std::vector<double> t(K);
  for (int64_t s = 0; s < S; ++s) {
    if (q <= 0.0) {
      double lat = logspace ? std::log(std::max(x[s], kTiny)) : x[s];
      double jac = logspace ? lat : 0.0;
      double m = -INFINITY;
      for (int64_t k = 0; k < K; ++k) {  // pass 1: terms + max (no exp)
        double z = (lat - mu[k]) * inv_sig[k];
        double tk = c1[k] - 0.5 * z * z;
        t[k] = tk;
        if (tk > m) m = tk;
      }
      if (m == -INFINITY) {
        out[s] = -INFINITY;
        continue;
      }
      double sum = 0.0;
      for (int64_t k = 0; k < K; ++k) sum += std::exp(t[k] - m);
      out[s] = m + std::log(sum) - jac;
    } else {
      double ub = x[s] + q / 2.0, lb = x[s] - q / 2.0;
      double ub_lat = logspace ? std::log(std::max(ub, kEps)) : ub;
      double lb_lat = logspace ? std::log(std::max(lb, kEps)) : lb;
      if (!std::isinf(high)) ub_lat = std::min(ub_lat, high);
      if (!std::isinf(low)) lb_lat = std::max(lb_lat, low);
      double p = 0.0;
      for (int64_t k = 0; k < K; ++k) {
        double mass = normal_cdf(ub_lat, mu[k], sigma[k]) -
                      normal_cdf(lb_lat, mu[k], sigma[k]);
        // per-component kEps floor: exact numpy-oracle parity
        // (GMM1_lpdf_numpy clamps each bin mass at EPS before the log)
        p += wmass[k] * std::max(mass, kEps);
      }
      out[s] = std::log(std::max(p, kTiny));
    }
  }
}

// Adaptive-Parzen fit (hyperopt_tpu.tpe.adaptive_parzen_normal semantics).
// mus: n time-ordered observations.  Outputs have n+1 entries (sorted,
// prior inserted).  Returns the prior's position.
int64_t ht_adaptive_parzen(const double* mus, int64_t n, double prior_weight,
                           double prior_mu, double prior_sigma, int64_t lf,
                           double* w_out, double* mu_out, double* sig_out) {
  int64_t m = n + 1;
  if (n == 0) {
    w_out[0] = 1.0;
    mu_out[0] = prior_mu;
    sig_out[0] = prior_sigma;
    return 0;
  }

  // forgetting weights in time order
  std::vector<double> tw(n, 1.0);
  if (lf > 0 && lf < n) {
    int64_t ramp_len = n - lf;
    double lo = 1.0 / static_cast<double>(n);
    for (int64_t i = 0; i < ramp_len; ++i) {
      tw[i] = ramp_len > 1
                  ? lo + static_cast<double>(i) * (1.0 - lo) /
                             static_cast<double>(ramp_len - 1)
                  : lo;
    }
  }

  // argsort of the observations
  std::vector<int64_t> order(n);
  for (int64_t i = 0; i < n; ++i) order[i] = i;
  std::stable_sort(order.begin(), order.end(), [&](int64_t a, int64_t b) {
    return mus[a] < mus[b];
  });

  // prior insertion position (searchsorted-left on sorted mus)
  int64_t prior_pos = 0;
  while (prior_pos < n && mus[order[prior_pos]] < prior_mu) ++prior_pos;

  for (int64_t i = 0; i < m; ++i) {
    if (i < prior_pos) {
      mu_out[i] = mus[order[i]];
      w_out[i] = tw[order[i]];
    } else if (i == prior_pos) {
      mu_out[i] = prior_mu;
      w_out[i] = prior_weight;
    } else {
      mu_out[i] = mus[order[i - 1]];
      w_out[i] = tw[order[i - 1]];
    }
  }

  // neighbor-gap sigmas on the prior-inserted sorted array
  if (m == 1) {
    sig_out[0] = prior_sigma;
  } else if (m == 2) {
    double gap = std::max(std::abs(mu_out[1] - mu_out[0]), kEps);
    sig_out[0] = gap;
    sig_out[1] = gap;
  } else {
    for (int64_t i = 1; i + 1 < m; ++i) {
      sig_out[i] =
          std::max(mu_out[i] - mu_out[i - 1], mu_out[i + 1] - mu_out[i]);
    }
    sig_out[0] = mu_out[1] - mu_out[0];
    sig_out[m - 1] = mu_out[m - 1] - mu_out[m - 2];
  }
  double maxsigma = prior_sigma;
  double minsigma =
      prior_sigma / std::min(100.0, 1.0 + static_cast<double>(n));
  for (int64_t i = 0; i < m; ++i) {
    sig_out[i] = std::clamp(sig_out[i], minsigma, maxsigma);
  }
  sig_out[prior_pos] = prior_sigma;

  double wsum = 0.0;
  for (int64_t i = 0; i < m; ++i) wsum += w_out[i];
  for (int64_t i = 0; i < m; ++i) w_out[i] /= wsum;
  return prior_pos;
}

}  // extern "C"
