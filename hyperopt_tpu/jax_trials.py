"""Dense observation history for the JAX algorithms.

The north-star ``JaxTrials`` backend (BASELINE.json / SURVEY.md SS7 stance
#3): observation history lives in preallocated dense buffers (values +
active-masks per hyperparameter, losses + validity), grown by doubling so
jitted suggest steps see a small set of static shapes (GROWTH_FACTOR-
bucketed capacity -> bounded recompiles, SURVEY.md SS7 'shape
polymorphism'; a recompile costs seconds, padded-slot compute costs
microseconds, so buckets are coarse: 4x per growth).

``ObsBuffer`` is the packing engine: it incrementally mirrors any
``Trials`` store (only completed, status-ok, finite-loss trials enter the
posterior -- failed/NaN trials are masked out, SURVEY.md SS5).
``JaxTrials`` is a drop-in ``Trials`` subclass that owns buffers keyed by
compiled space, so repeated suggest calls do zero re-packing.
"""

from __future__ import annotations

import numpy as np

from .base import Trials, posterior_state
from .ops.compile import PackedSpace

__all__ = ["ObsBuffer", "JaxTrials", "MIN_CAPACITY", "GROWTH_FACTOR"]

MIN_CAPACITY = 128
GROWTH_FACTOR = 4


class ObsBuffer:
    """Dense, capacity-bucketed mirror of completed trials for one space.

    Arrays (host numpy; handed to jit as-is and transferred once per call):
      values: [D, cap] natural-space draws (garbage where inactive)
      active: [D, cap] per-dim activity mask
      losses: [cap]
      valid:  [cap] slot occupancy
    Slots are tid-ordered (time order for forgetting weights).
    """

    def __init__(self, space: PackedSpace, capacity=MIN_CAPACITY):
        self.space = space
        self.capacity = int(capacity)
        D = space.n_dims
        self.values = np.zeros((D, self.capacity), dtype=np.float32)
        self.active = np.zeros((D, self.capacity), dtype=bool)
        self.losses = np.zeros(self.capacity, dtype=np.float32)
        self.valid = np.zeros(self.capacity, dtype=bool)
        self.tids = np.zeros(self.capacity, dtype=np.int64)
        self.count = 0
        self._n_scanned = 0  # trials-list prefix already scanned
        self._pending = []  # scanned-but-still-pending doc indices
        self._legacy_tids = False  # loaded from a checkpoint without tids
        self._generation = 0  # bumped on every mutation
        self._device_cache = None  # ((generation, bucket), arrays-on-device)

    def _grow(self):
        new_cap = self.capacity * GROWTH_FACTOR
        for name in ("values", "active"):
            old = getattr(self, name)
            new = np.zeros((old.shape[0], new_cap), dtype=old.dtype)
            new[:, : self.capacity] = old
            setattr(self, name, new)
        for name in ("losses", "valid", "tids"):
            old = getattr(self, name)
            new = np.zeros(new_cap, dtype=old.dtype)
            new[: self.capacity] = old
            setattr(self, name, new)
        self.capacity = new_cap

    def add(self, vals_dict, loss, tid=None):
        """Ingest one completed trial: {label: value} + loss.

        Slots stay TID-ORDERED (forgetting weights are positional --
        host-path parity): an in-order tid appends; a late completion
        (async backends) inserts at its tid position with one vectorized
        shift of the tail, keeping the sync path free of full rebuilds.
        """
        if self.count == self.capacity:
            self._grow()
        n = self.count
        if tid is None:
            tid = self.tids[n - 1] + 1 if n else 0
        i = int(np.searchsorted(self.tids[:n], tid))
        if i < n:  # late completion: shift the newer tail right by one
            self.values[:, i + 1: n + 1] = self.values[:, i:n]
            self.active[:, i + 1: n + 1] = self.active[:, i:n]
            self.losses[i + 1: n + 1] = self.losses[i:n]
            self.tids[i + 1: n + 1] = self.tids[i:n]
        label_pos = self._label_pos
        self.values[:, i] = 0.0
        self.active[:, i] = False
        for label, v in vals_dict.items():
            d = label_pos.get(label)
            if d is None:
                continue
            self.values[d, i] = v
            self.active[d, i] = True
        self.losses[i] = loss
        self.tids[i] = tid
        self.valid[n] = True  # occupancy is a prefix mask
        self.count = n + 1
        self._generation += 1

    @property
    def _label_pos(self):
        pos = getattr(self, "_label_pos_cache", None)
        if pos is None:
            pos = {label: d for d, label in enumerate(self.space.labels)}
            self._label_pos_cache = pos
        return pos

    def _add_doc(self, t):
        vals = {
            k: v[0] for k, v in t["misc"]["vals"].items() if len(v) == 1
        }
        self.add(vals, float(t["result"]["loss"]), tid=int(t["tid"]))

    def sync(self, trials: Trials):
        """Ingest trials completed since the last sync.

        The scan is incremental (a cursor over the trials list) BUT docs
        scanned while still pending are remembered and revisited: under
        an async backend a trial is routinely observed in flight and
        completes later -- dropping it would silently starve the
        posterior (a real round-2 bug).  Late completions insert at
        their tid position (``add``), so slot order keeps matching the
        host path's tid-sorted observation lists without full rebuilds.
        Classification is the shared :func:`hyperopt_tpu.base.
        posterior_state` predicate (which also keeps a doc pending
        through an async worker's state-then-result write window).
        Returns the number of newly ingested observations; a shrunk
        list (delete_all) rebuilds from scratch.
        """
        docs = trials.trials
        if len(docs) < self._n_scanned or getattr(
            self, "_legacy_tids", False
        ):
            # shrunk list (delete_all) OR a legacy checkpoint whose tids
            # were synthesized as arange (only valid for contiguous-tid
            # runs): rebuild from the doc list, the source of truth
            self.__init__(self.space, MIN_CAPACITY)

        before = self.count
        still_pending = []
        for i in self._pending:
            t = docs[i]
            ps = posterior_state(t)
            if ps == "ok":
                self._add_doc(t)  # completed after an earlier scan
            elif ps == "pending":
                still_pending.append(i)
        self._pending = still_pending

        for i in range(self._n_scanned, len(docs)):
            t = docs[i]
            ps = posterior_state(t)
            if ps == "ok":
                self._add_doc(t)
            elif ps == "pending":
                self._pending.append(i)
        self._n_scanned = len(docs)
        return self.count - before

    def arrays(self):
        """The four dense arrays at current (bucketed) capacity."""
        return self.values, self.active, self.losses, self.valid

    def _device_bucket(self, pow2_cap=None):
        """Static width handed to jit: the smallest power-of-2 >= count
        (floored at MIN_CAPACITY, capped at capacity).

        The suggest program's above-model scoring is proportional to the
        buffer width it sees; with 4x capacity growth alone, a buffer
        grown to 8192 for 2,500 observations pays >3x padded compute on
        EVERY suggest (measured in the round-2 soak: trials/s dropped
        ~40% after the 2048->8192 growth).  Slicing uploads to a pow2
        bucket of the live count bounds padding at 2x while keeping
        retraces logarithmic.

        ``pow2_cap`` (the caller's above-model compaction cap, round 6):
        with compaction active the scoring width is STATIC past the cap
        -- only the cheap O(n log n) fit still sees the buffer width --
        so the 2x-padding argument above stops applying there and the
        bucket stops re-bucketing at every pow2 crossing: past the cap
        it grows by GROWTH_FACTOR steps, aligned with the host capacity
        schedule, halving the retrace count at large histories."""
        coarse = GROWTH_FACTOR.bit_length() - 1
        b = MIN_CAPACITY
        while b < self.count:
            b <<= 1 if (pow2_cap is None or b < pow2_cap) else coarse
        return min(b, self.capacity)

    def device_arrays(self, pow2_cap=None):
        """The four arrays on the default device -- sliced to the pow2
        bucket of the live count (see :meth:`_device_bucket`) and cached
        by (generation, bucket): repeated suggest calls against
        unchanged history transfer nothing (the 'on-device history'
        contract of the north star).  ``pow2_cap`` coarsens the bucket
        schedule past a compaction cap (see :meth:`_device_bucket`)."""
        b = self._device_bucket(pow2_cap)
        key = (self._generation, b)
        if self._device_cache is None or self._device_cache[0] != key:
            import jax

            self._device_cache = (
                key,
                tuple(jax.device_put(a[..., :b]) for a in self.arrays()),
            )
        return self._device_cache[1]


class JaxTrials(Trials):
    """``Trials`` whose completed history is mirrored into dense device-ready
    buffers -- the on-device experiment store of the TPU path.

    Use exactly like ``Trials``; the JAX algorithms
    (:mod:`hyperopt_tpu.tpe_jax`, :mod:`hyperopt_tpu.rand_jax`) detect it
    and reuse its buffers instead of maintaining their own.
    """

    def __init__(self, exp_key=None, refresh=True):
        self._buffers = {}  # id(PackedSpace) -> ObsBuffer
        super().__init__(exp_key=exp_key, refresh=refresh)

    def obs_buffer(self, space: PackedSpace) -> ObsBuffer:
        buf = self._buffers.get(id(space))
        if buf is None:
            buf = ObsBuffer(space)
            self._buffers[id(space)] = buf
        buf.sync(self)
        return buf

    def __getstate__(self):
        # buffers are derived state; rebuilt on demand after unpickling
        state = self.__dict__.copy()
        state["_buffers"] = {}
        return state


def obs_buffer_for(domain, trials) -> ObsBuffer:
    """The shared entry point used by the JAX algos: prefer the JaxTrials
    resident buffer, else a buffer cached on the domain.

    The domain-side cache keys on the trials-store identity (weakref): a
    Domain reused across two stores must never serve one store's
    observations for the other."""
    import weakref

    space = packed_space_for(domain)
    if isinstance(trials, JaxTrials):
        return trials.obs_buffer(space)
    cached = getattr(domain, "_obs_buffer", None)
    buf = None
    if cached is not None:
        ref, buf_cached = cached
        if ref() is trials and buf_cached.space is space:
            buf = buf_cached
    if buf is None:
        buf = ObsBuffer(space)
        domain._obs_buffer = (weakref.ref(trials), buf)
    buf.sync(trials)
    return buf


def packed_space_for(domain) -> PackedSpace:
    """Compile (once) and cache the domain's space."""
    ps = getattr(domain, "_packed_space", None)
    if ps is None:
        from .ops.compile import compile_space

        ps = compile_space(domain.expr)
        domain._packed_space = ps
    return ps


def host_key(seed):
    """A PRNG key built on the CPU backend.

    ``jax.random.key`` dispatches a (tiny) program to the default device;
    on a remote-attached TPU that is a full round-trip (~90 ms measured
    over the tunnel) per suggest call.  Keys are 8 bytes of bit-twiddling
    -- make them on the host CPU and let the consuming program upload.
    """
    import jax

    try:
        cpu = jax.local_devices(backend="cpu")[0]
    except RuntimeError:
        return jax.random.key(seed)
    with jax.default_device(cpu):
        return jax.random.key(seed)


def cached_suggest_fn(domain, cache_attr, params, builder):
    """Per-domain cache of compiled suggest programs, shared by every JAX
    algo path (tpe_jax / anneal_jax / parallel.sharded).

    ``params`` is the hashable hyperparameter tuple; the cache key adds
    the compiled-space identity so a domain whose space object is swapped
    recompiles.  ``builder(packed_space, *params)`` builds the jitted fn.
    """
    ps = packed_space_for(domain)
    key = (id(ps),) + tuple(params)
    cache = getattr(domain, cache_attr, None)
    if cache is None:
        cache = {}
        setattr(domain, cache_attr, cache)
    fn = cache.get(key)
    if fn is None:
        fn = builder(ps, *params)
        cache[key] = fn
    return fn
