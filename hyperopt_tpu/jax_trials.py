"""Dense observation history for the JAX algorithms.

The north-star ``JaxTrials`` backend (BASELINE.json / SURVEY.md SS7 stance
#3): observation history lives in preallocated dense buffers (values +
active-masks per hyperparameter, losses + validity), grown by doubling so
jitted suggest steps see a small set of static shapes (GROWTH_FACTOR-
bucketed capacity -> bounded recompiles, SURVEY.md SS7 'shape
polymorphism'; a recompile costs seconds, padded-slot compute costs
microseconds, so buckets are coarse: 4x per growth).

``ObsBuffer`` is the packing engine: it incrementally mirrors any
``Trials`` store (only completed, status-ok, finite-loss trials enter the
posterior -- failed/NaN trials are masked out, SURVEY.md SS5).
``JaxTrials`` is a drop-in ``Trials`` subclass that owns buffers keyed by
compiled space, so repeated suggest calls do zero re-packing.
"""

from __future__ import annotations

import numpy as np

from .base import Trials, posterior_state
from .obs.registry import CounterAttr, MetricsRegistry
from .ops.compile import PackedSpace

__all__ = ["ObsBuffer", "JaxTrials", "MIN_CAPACITY", "GROWTH_FACTOR"]

MIN_CAPACITY = 128
GROWTH_FACTOR = 4

# Resident mode: past this many staged-but-unapplied delta tells, one full
# re-materialization is cheaper (and simpler) than a chain of delta
# dispatches -- only reachable when many tells land between asks (long
# startup phases, batched completions), never in the 1-tell-per-ask
# sequential driver the delta path exists for.
MAX_PENDING_DELTAS = 32

_APPLY_DELTA = None  # lazily-built jitted delta program (donated state)


def _apply_delta_fn():
    global _APPLY_DELTA
    if _APPLY_DELTA is None:
        import jax

        from .ops.kernels import apply_delta

        # donate_argnums: the old state buffers are dead the moment the
        # delta applies -- donation lets XLA update in place instead of
        # holding two copies of the bucketed history in device memory
        _APPLY_DELTA = jax.jit(apply_delta, donate_argnums=(0, 1, 2, 3))
    return _APPLY_DELTA


class ObsBuffer:
    """Dense, capacity-bucketed mirror of completed trials for one space.

    Arrays (host numpy; handed to jit as-is and transferred once per call):
      values: [D, cap] natural-space draws (garbage where inactive)
      active: [D, cap] per-dim activity mask
      losses: [cap]
      valid:  [cap] slot occupancy
    Slots are tid-ordered (time order for forgetting weights).

    ``resident=True`` keeps a device-side mirror of the four arrays that
    is updated INCREMENTALLY: each in-order ``add`` stages an O(D) delta
    (one value/active column + one loss scalar) applied by a jitted
    ``dynamic_update_slice`` program with donated state buffers, instead
    of re-uploading the whole bucketed history on every generation bump
    (the O(n_obs*D)-bytes-per-ask term that left the sequential driver
    dispatch-bound -- BENCH_r05).  Bucket growth, out-of-order tid
    inserts, and rebuilds re-materialize the mirror exactly as the
    non-resident log schedule does; the host arrays stay the source of
    truth either way, so the resident view is bitwise identical to a
    fresh upload at every step.  Deterministic counters
    (``transfer_bytes_total`` / ``delta_tells`` / ``full_uploads`` /
    ``dispatch_count``) expose the traffic and dispatch behavior for
    benchmarks and regression pins.
    """

    # graftscope: the deterministic traffic/dispatch counters live on
    # a per-buffer MetricsRegistry, exposed behind their historic
    # attribute names (reads, `+=` writes, and pickles all unchanged)
    transfer_bytes_total = CounterAttr(
        "obs_transfer_bytes_total", "host->device history bytes moved")
    delta_tells = CounterAttr(
        "obs_delta_tells_total", "O(D) incremental delta tells applied")
    full_uploads = CounterAttr(
        "obs_full_uploads_total", "full history re-materializations")
    dispatch_count = CounterAttr(
        "obs_dispatch_total", "device programs dispatched by this buffer")

    def __init__(self, space: PackedSpace, capacity=MIN_CAPACITY,
                 resident=False):
        self.metrics = MetricsRegistry("obs_buffer")
        self.space = space
        self.capacity = int(capacity)
        D = space.n_dims
        self.values = np.zeros((D, self.capacity), dtype=np.float32)
        self.active = np.zeros((D, self.capacity), dtype=bool)
        self.losses = np.zeros(self.capacity, dtype=np.float32)
        self.valid = np.zeros(self.capacity, dtype=bool)
        self.tids = np.zeros(self.capacity, dtype=np.int64)
        self.count = 0
        self._n_scanned = 0  # trials-list prefix already scanned
        self._pending = []  # scanned-but-still-pending doc indices
        self._legacy_tids = False  # loaded from a checkpoint without tids
        self._generation = 0  # bumped on every mutation
        self._device_cache = None  # ((generation, bucket), arrays-on-device)
        self.resident = bool(resident)
        self._resident = None  # {"bucket": int, "arrays": HistoryState}
        self._resident_full = True  # mirror needs a full materialization
        self._pending_deltas = []  # [(slot, values-col, active-col, loss)]

    def _grow(self):
        new_cap = self.capacity * GROWTH_FACTOR
        for name in ("values", "active"):
            old = getattr(self, name)
            new = np.zeros((old.shape[0], new_cap), dtype=old.dtype)
            new[:, : self.capacity] = old
            setattr(self, name, new)
        for name in ("losses", "valid", "tids"):
            old = getattr(self, name)
            new = np.zeros(new_cap, dtype=old.dtype)
            new[: self.capacity] = old
            setattr(self, name, new)
        self.capacity = new_cap

    def add(self, vals_dict, loss, tid=None):
        """Ingest one completed trial: {label: value} + loss.

        Slots stay TID-ORDERED (forgetting weights are positional --
        host-path parity): an in-order tid appends; a late completion
        (async backends) inserts at its tid position with one vectorized
        shift of the tail, keeping the sync path free of full rebuilds.
        """
        if self.count == self.capacity:
            self._grow()
        n = self.count
        if tid is None:
            tid = self.tids[n - 1] + 1 if n else 0
        i = int(np.searchsorted(self.tids[:n], tid))
        if i < n:  # late completion: shift the newer tail right by one
            self.values[:, i + 1: n + 1] = self.values[:, i:n]
            self.active[:, i + 1: n + 1] = self.active[:, i:n]
            self.losses[i + 1: n + 1] = self.losses[i:n]
            self.tids[i + 1: n + 1] = self.tids[i:n]
        label_pos = self._label_pos
        self.values[:, i] = 0.0
        self.active[:, i] = False
        for label, v in vals_dict.items():
            d = label_pos.get(label)
            if d is None:
                continue
            self.values[d, i] = v
            self.active[d, i] = True
        self.losses[i] = loss
        self.tids[i] = tid
        self.valid[n] = True  # occupancy is a prefix mask
        self.count = n + 1
        self._generation += 1
        if self.resident:
            if i == n and len(self._pending_deltas) < MAX_PENDING_DELTAS:
                # in-order append: stage the O(D) delta for the mirror
                self._pending_deltas.append((
                    n, self.values[:, n].copy(), self.active[:, n].copy(),
                    float(loss),
                ))
            else:
                # late insert shifted the tail (or the delta backlog is
                # past the crossover): re-materialize on next use
                self._resident_full = True
                self._pending_deltas.clear()

    @property
    def _label_pos(self):
        pos = getattr(self, "_label_pos_cache", None)
        if pos is None:
            pos = {label: d for d, label in enumerate(self.space.labels)}
            self._label_pos_cache = pos
        return pos

    def _add_doc(self, t):
        vals = {
            k: v[0] for k, v in t["misc"]["vals"].items() if len(v) == 1
        }
        self.add(vals, float(t["result"]["loss"]), tid=int(t["tid"]))

    def sync(self, trials: Trials):
        """Ingest trials completed since the last sync.

        The scan is incremental (a cursor over the trials list) BUT docs
        scanned while still pending are remembered and revisited: under
        an async backend a trial is routinely observed in flight and
        completes later -- dropping it would silently starve the
        posterior (a real round-2 bug).  Late completions insert at
        their tid position (``add``), so slot order keeps matching the
        host path's tid-sorted observation lists without full rebuilds.
        Classification is the shared :func:`hyperopt_tpu.base.
        posterior_state` predicate (which also keeps a doc pending
        through an async worker's state-then-result write window).
        Returns the number of newly ingested observations; a shrunk
        list (delete_all) rebuilds from scratch.
        """
        docs = trials.trials
        if len(docs) < self._n_scanned or getattr(
            self, "_legacy_tids", False
        ):
            # shrunk list (delete_all) OR a legacy checkpoint whose tids
            # were synthesized as arange (only valid for contiguous-tid
            # runs): rebuild from the doc list, the source of truth
            self.__init__(self.space, MIN_CAPACITY, resident=self.resident)

        before = self.count
        still_pending = []
        for i in self._pending:
            t = docs[i]
            ps = posterior_state(t)
            if ps == "ok":
                self._add_doc(t)  # completed after an earlier scan
            elif ps == "pending":
                still_pending.append(i)
        self._pending = still_pending

        for i in range(self._n_scanned, len(docs)):
            t = docs[i]
            ps = posterior_state(t)
            if ps == "ok":
                self._add_doc(t)
            elif ps == "pending":
                self._pending.append(i)
        self._n_scanned = len(docs)
        return self.count - before

    def arrays(self):
        """The four dense arrays at current (bucketed) capacity."""
        return self.values, self.active, self.losses, self.valid

    def _device_bucket(self, pow2_cap=None):
        """Static width handed to jit: the smallest power-of-2 >= count
        (floored at MIN_CAPACITY, capped at capacity).

        The suggest program's above-model scoring is proportional to the
        buffer width it sees; with 4x capacity growth alone, a buffer
        grown to 8192 for 2,500 observations pays >3x padded compute on
        EVERY suggest (measured in the round-2 soak: trials/s dropped
        ~40% after the 2048->8192 growth).  Slicing uploads to a pow2
        bucket of the live count bounds padding at 2x while keeping
        retraces logarithmic.

        ``pow2_cap`` (the caller's above-model compaction cap, round 6):
        with compaction active the scoring width is STATIC past the cap
        -- only the cheap O(n log n) fit still sees the buffer width --
        so the 2x-padding argument above stops applying there and the
        bucket stops re-bucketing at every pow2 crossing: past the cap
        it grows by GROWTH_FACTOR steps, aligned with the host capacity
        schedule, halving the retrace count at large histories."""
        coarse = GROWTH_FACTOR.bit_length() - 1
        b = MIN_CAPACITY
        while b < self.count:
            b <<= 1 if (pow2_cap is None or b < pow2_cap) else coarse
        return min(b, self.capacity)

    def device_arrays(self, pow2_cap=None):
        """The four arrays on the default device -- sliced to the pow2
        bucket of the live count (see :meth:`_device_bucket`) and cached
        by (generation, bucket): repeated suggest calls against
        unchanged history transfer nothing (the 'on-device history'
        contract of the north star).  ``pow2_cap`` coarsens the bucket
        schedule past a compaction cap (see :meth:`_device_bucket`).

        In resident mode the return value is the incrementally-updated
        device mirror: staged delta tells are applied by the jitted
        O(D) delta program (one dispatch each) and a full upload happens
        only on the first use, at bucket growth, and after out-of-order
        inserts -- the log schedule, not once per observation."""
        if self.resident:
            return self._resident_sync(pow2_cap)
        b = self._device_bucket(pow2_cap)
        key = (self._generation, b)
        if self._device_cache is None or self._device_cache[0] != key:
            import jax

            arrays = tuple(a[..., :b] for a in self.arrays())
            self.transfer_bytes_total += sum(a.nbytes for a in arrays)
            self.full_uploads += 1
            self._device_cache = (
                key,
                tuple(jax.device_put(a) for a in arrays),
            )
        return self._device_cache[1]

    def set_resident(self, flag):
        """Flip the device mirror between resident (incremental-delta)
        and re-upload mode.  The host arrays are authoritative either
        way, so flipping is always safe; the next :meth:`device_arrays`
        call (re)materializes whichever view is now active."""
        flag = bool(flag)
        if flag == self.resident:
            return
        self.resident = flag
        self._resident = None
        self._resident_full = True
        self._pending_deltas = []
        self._device_cache = None

    _DELTA_BYTES_FIXED = 8  # loss float32 + slot index int32

    def _delta_nbytes(self, vcol, acol):
        return vcol.nbytes + acol.nbytes + self._DELTA_BYTES_FIXED

    def _materialize_resident(self, b):
        import jax

        from .ops.kernels import HistoryState

        arrays = tuple(a[..., :b] for a in self.arrays())
        self.transfer_bytes_total += sum(a.nbytes for a in arrays)
        self.full_uploads += 1
        self._resident = {
            "bucket": b,
            "arrays": HistoryState(*(jax.device_put(a) for a in arrays)),
        }
        self._pending_deltas.clear()
        self._resident_full = False

    def _resident_sync(self, pow2_cap=None):
        """Bring the device mirror up to date and return it."""
        b = self._device_bucket(pow2_cap)
        st = self._resident
        if st is None or st["bucket"] != b or self._resident_full:
            self._materialize_resident(b)
        elif self._pending_deltas:
            apply_delta = _apply_delta_fn()
            arrays = st["arrays"]
            for slot, vcol, acol, loss in self._pending_deltas:
                arrays = apply_delta(
                    *arrays, vcol, acol, np.float32(loss), np.int32(slot)
                )
                self.transfer_bytes_total += self._delta_nbytes(vcol, acol)
                self.delta_tells += 1
                self.dispatch_count += 1
            self._pending_deltas.clear()
            st["arrays"] = arrays
        return self._resident["arrays"]

    def take_fusable_delta(self, pow2_cap=None):
        """Pop the single pending delta for a fused tell+ask dispatch.

        Returns ``(state, (vcol, acol, loss, slot))`` -- the current
        resident :class:`~hyperopt_tpu.ops.kernels.HistoryState` plus
        the staged O(D) delta -- when the one-dispatch fused path can
        run: the mirror exists at the CURRENT bucket and exactly one
        in-order tell is pending.  The caller owns the handoff: it must
        feed both to a ``state_io`` suggest program and commit the
        returned state via :meth:`commit_resident` (the old buffers are
        donated).  Returns ``None`` when the fused path cannot run
        (cold mirror, bucket growth, zero or multiple pending tells) --
        callers fall back to :meth:`device_arrays` + a plain ask.
        """
        if not self.resident or self._resident_full or self._resident is None:
            return None
        if len(self._pending_deltas) != 1:
            return None
        if self._resident["bucket"] != self._device_bucket(pow2_cap):
            return None
        slot, vcol, acol, loss = self._pending_deltas.pop()
        self.transfer_bytes_total += self._delta_nbytes(vcol, acol)
        self.delta_tells += 1
        return self._resident["arrays"], (
            vcol, acol, np.float32(loss), np.int32(slot),
        )

    def commit_resident(self, arrays):
        """Install a fused program's state outputs as the mirror (the
        counterpart of :meth:`take_fusable_delta`)."""
        from .ops.kernels import HistoryState

        self._resident["arrays"] = HistoryState(*arrays)

    def __getstate__(self):
        # device-side state never pickles (checkpoints/attachments carry
        # the host arrays; mirrors rebuild on first use after load)
        state = self.__dict__.copy()
        state["_device_cache"] = None
        state["_resident"] = None
        state["_resident_full"] = True
        state["_pending_deltas"] = []
        return state


class JaxTrials(Trials):
    """``Trials`` whose completed history is mirrored into dense device-ready
    buffers -- the on-device experiment store of the TPU path.

    Use exactly like ``Trials``; the JAX algorithms
    (:mod:`hyperopt_tpu.tpe_jax`, :mod:`hyperopt_tpu.rand_jax`) detect it
    and reuse its buffers instead of maintaining their own.

    ``resident=True`` makes every owned buffer device-resident: tells
    stage O(D) deltas instead of invalidating the device cache (see
    :class:`ObsBuffer`), which is what the fused sequential driver
    (``tpe_jax.suggest(fused=True)``) wants under it.
    """

    def __init__(self, exp_key=None, refresh=True, resident=False):
        self._buffers = {}  # id(PackedSpace) -> ObsBuffer
        self._resident_default = bool(resident)
        super().__init__(exp_key=exp_key, refresh=refresh)

    def obs_buffer(self, space: PackedSpace, resident=None) -> ObsBuffer:
        buf = self._buffers.get(id(space))
        if buf is None:
            buf = self._restore_stashed(space)
            if buf is None:
                buf = ObsBuffer(
                    space,
                    resident=getattr(self, "_resident_default", False),
                )
            self._buffers[id(space)] = buf
        if resident is not None:
            buf.set_resident(resident)
        buf.sync(self)
        return buf

    def _restore_stashed(self, space: PackedSpace):
        """Rebuild a buffer from a checkpoint-bundle npz blob
        (``DriverRecovery.load`` stashes them on the unpickled store):
        the resumed resident mirror starts from the saved dense arrays
        and ``sync`` only ingests the WAL-replayed suffix, instead of
        re-scanning the whole doc list.  A blob whose labels do not
        match ``space`` is simply not this space's buffer."""
        blobs = getattr(self, "_stashed_obs_npz", None)
        if not blobs:
            return None
        from .utils.checkpoint import load_obs_buffer_bytes

        for i, blob in enumerate(blobs):
            try:
                buf = load_obs_buffer_bytes(space, blob)
            except ValueError:
                continue
            blobs.pop(i)
            buf.set_resident(getattr(self, "_resident_default", False))
            return buf
        return None

    def __getstate__(self):
        # buffers are derived state; rebuilt on demand after unpickling
        state = self.__dict__.copy()
        state["_buffers"] = {}
        state.pop("_stashed_obs_npz", None)  # bundle-restore residue
        return state


def obs_buffer_for(domain, trials, resident=None) -> ObsBuffer:
    """The shared entry point used by the JAX algos: prefer the JaxTrials
    store-owned buffer, else a buffer cached on the domain.

    The domain-side cache keys on the trials-store identity (weakref): a
    Domain reused across two stores must never serve one store's
    observations for the other.  ``resident`` (None = leave as-is)
    flips the buffer's device-mirror mode (:meth:`ObsBuffer.
    set_resident`) -- the knob the resident/fused suggest paths use."""
    import weakref

    space = packed_space_for(domain)
    if isinstance(trials, JaxTrials):
        return trials.obs_buffer(space, resident=resident)
    cached = getattr(domain, "_obs_buffer", None)
    buf = None
    if cached is not None:
        ref, buf_cached = cached
        if ref() is trials and buf_cached.space is space:
            buf = buf_cached
    if buf is None:
        buf = ObsBuffer(space)
        domain._obs_buffer = (weakref.ref(trials), buf)
    if resident is not None:
        buf.set_resident(resident)
    buf.sync(trials)
    return buf


def packed_space_for(domain) -> PackedSpace:
    """Compile (once) and cache the domain's space."""
    ps = getattr(domain, "_packed_space", None)
    if ps is None:
        from .ops.compile import compile_space

        ps = compile_space(domain.expr)
        domain._packed_space = ps
    return ps


def host_key(seed):
    """A PRNG key built on the CPU backend.

    ``jax.random.key`` dispatches a (tiny) program to the default device;
    on a remote-attached TPU that is a full round-trip (~90 ms measured
    over the tunnel) per suggest call.  Keys are 8 bytes of bit-twiddling
    -- make them on the host CPU and let the consuming program upload.
    """
    import jax

    try:
        cpu = jax.local_devices(backend="cpu")[0]
    except RuntimeError:
        return jax.random.key(seed)
    with jax.default_device(cpu):
        return jax.random.key(seed)


# ---------------------------------------------------------------------------
# graftir registration (hyperopt-tpu-lint --ir)
# ---------------------------------------------------------------------------

from .ops.compile import ProgramCapture, register_program  # noqa: E402


@register_program(
    "jax_trials.apply_delta",
    families=("hyperopt_tpu.ops.kernels:apply_delta",),
)
def _registry_apply_delta(p):
    """The standalone O(D) delta-tell program the resident mirror
    dispatches per staged observation (``_apply_delta_fn``) -- donated
    state, exactly as :meth:`ObsBuffer._resident_sync` builds it."""
    import jax

    from .ops.kernels import apply_delta

    fn = jax.jit(apply_delta, donate_argnums=(0, 1, 2, 3))
    return ProgramCapture(
        fn=fn, args=p.history_specs() + p.delta_specs(),
        donate_argnums=(0, 1, 2, 3),
    )


def cached_suggest_fn(domain, cache_attr, params, builder):
    """Per-domain cache of compiled suggest programs, shared by every JAX
    algo path (tpe_jax / anneal_jax / parallel.sharded).

    ``params`` is the hashable hyperparameter tuple; the cache key adds
    the compiled-space identity so a domain whose space object is swapped
    recompiles.  ``builder(packed_space, *params)`` builds the jitted fn.
    """
    ps = packed_space_for(domain)
    key = (id(ps),) + tuple(params)
    cache = getattr(domain, cache_attr, None)
    if cache is None:
        cache = {}
        setattr(domain, cache_attr, cache)
    fn = cache.get(key)
    if fn is None:
        fn = builder(ps, *params)
        cache[key] = fn
    return fn
