"""Render pyll space graphs to Graphviz dot text.

Capability parity with the reference's ``hyperopt/graphviz.py``
(SURVEY.md SS2): emit a dot description of a search-space expression --
hyperparameter nodes highlighted, switch edges labeled by branch index.
Pure text emission; no graphviz binary dependency.
"""

from __future__ import annotations

from .pyll.base import Literal, as_apply, dfs

__all__ = ["dot_hyperparameters"]


def _node_label(node):
    if isinstance(node, Literal):
        text = repr(node.obj)
        if len(text) > 20:
            text = text[:17] + "..."
        return text.replace('"', "'")
    return node.name


def dot_hyperparameters(expr):
    """Return a dot-format string for the graph rooted at ``expr``."""
    expr = as_apply(expr)
    nodes = dfs(expr)
    ids = {id(n): f"n{i}" for i, n in enumerate(nodes)}
    lines = [
        "digraph space {",
        "  rankdir=TB;",
        '  node [fontsize=10, shape=box, style=rounded];',
    ]
    for n in nodes:
        nid = ids[id(n)]
        label = _node_label(n)
        attrs = f'label="{label}"'
        if n.name == "hyperopt_param":
            param_label = n.pos_args[0].obj if n.pos_args else "?"
            attrs = (
                f'label="{param_label}", shape=ellipse, style=filled, '
                'fillcolor=lightblue'
            )
        elif n.name == "switch":
            attrs = f'label="switch", shape=diamond'
        elif isinstance(n, Literal):
            attrs = f'label="{label}", shape=plaintext'
        lines.append(f"  {nid} [{attrs}];")
    for n in nodes:
        nid = ids[id(n)]
        for i, child in enumerate(n.pos_args):
            edge = ""
            if n.name == "switch" and i > 0:
                edge = f' [label="{i - 1}"]'
            lines.append(f"  {ids[id(child)]} -> {nid}{edge};")
        for key, child in n.named_args:
            lines.append(f'  {ids[id(child)]} -> {nid} [label="{key}"];')
    lines.append("}")
    return "\n".join(lines)
