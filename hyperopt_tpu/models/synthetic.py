"""Synthetic benchmark domains (the domain battery).

Mirrors the reference's shared test fixtures (``tests/test_domains.py``:
quadratic1, q1_lognormal, q1_choice, n_arms, branin, gauss_wave2,
many_dists -- SURVEY.md SS4): every suggest algorithm is validated by
running fmin end-to-end on this battery against best-loss thresholds,
not by mocking.

Also provides the parametric ``mixed_space`` used by throughput benchmarks
(BASELINE.json: 20-dim mixed continuous/categorical space).
"""

from __future__ import annotations

import math

import numpy as np

from .. import hp

__all__ = ["SyntheticDomain", "DOMAINS", "battery", "mixed_space", "branin_fn",
           "hartmann6_fn", "mlp_tune_objective", "mlp_tune_space",
           "cond_tune_objective", "cond_tune_space"]


class SyntheticDomain:
    """One benchmark objective: fn over a space, plus test thresholds.

    ``fn`` takes the materialized config (scalar or dict, matching what the
    space evaluates to).  ``loss_target(n)`` gives the loss a competent
    optimizer should reach within n evaluations (used as loose test
    thresholds, reference-style: SURVEY.md SS4 'domain battery' row).
    """

    def __init__(self, name, fn, space, global_min, targets):
        self.name = name
        self.fn = fn
        self.space = space
        self.global_min = global_min
        self.targets = targets  # {n_evals: loss threshold}

    def make_space(self):
        return self.space()

    def __repr__(self):
        return f"SyntheticDomain({self.name})"


# -- simple 1-D -------------------------------------------------------------


def _quadratic1_fn(x):
    return (x - 3.0) ** 2


def _q1_lognormal_fn(x):
    return max(0.0, min((x - 3.0) ** 2 / 2.0, 10.0))


def _q1_choice_fn(cfg):
    if cfg["case"] == 1:
        return (cfg["x"] - 1.0) ** 2
    return 0.5 * (cfg["x"] + 2.5) ** 2 + 0.25


def _n_arms_fn(arm):
    return [0.0, 0.25, 0.5, 0.75, 1.0][arm]


# -- classic BBO ------------------------------------------------------------


def branin_fn(cfg):
    """Branin-Hoo; global min 0.397887 at (-pi, 12.275), (pi, 2.275),
    (9.42478, 2.475)."""
    x1, x2 = cfg["x1"], cfg["x2"]
    a = 1.0
    b = 5.1 / (4 * math.pi**2)
    c = 5.0 / math.pi
    r = 6.0
    s = 10.0
    t = 1.0 / (8 * math.pi)
    return (
        a * (x2 - b * x1**2 + c * x1 - r) ** 2 + s * (1 - t) * math.cos(x1) + s
    )


_H6_ALPHA = np.array([1.0, 1.2, 3.0, 3.2])
_H6_A = np.array(
    [
        [10, 3, 17, 3.5, 1.7, 8],
        [0.05, 10, 17, 0.1, 8, 14],
        [3, 3.5, 1.7, 10, 17, 8],
        [17, 8, 0.05, 10, 0.1, 14],
    ]
)
_H6_P = 1e-4 * np.array(
    [
        [1312, 1696, 5569, 124, 8283, 5886],
        [2329, 4135, 8307, 3736, 1004, 9991],
        [2348, 1451, 3522, 2883, 3047, 6650],
        [4047, 8828, 8732, 5743, 1091, 381],
    ]
)


def hartmann6_fn(cfg):
    """Hartmann-6; global min -3.32237."""
    x = np.array([cfg[f"x{i}"] for i in range(6)])
    inner = np.sum(_H6_A * (x - _H6_P) ** 2, axis=1)
    return float(-np.sum(_H6_ALPHA * np.exp(-inner)))


def _rosenbrock2_fn(cfg):
    x, y = cfg["x"], cfg["y"]
    return (1 - x) ** 2 + 100.0 * (y - x**2) ** 2


# -- conditional / gnarly ---------------------------------------------------


def _gauss_wave2_fn(cfg):
    """Conditional objective: branch 1 can beat branch 0 only if its
    amplitude is tuned -- exercises choice + nested continuous."""
    x = cfg["x"]
    base = math.exp(-((x / 10.0) ** 2))
    if cfg["kind"] == "raw":
        return -base
    return -base * cfg["amp"]


def _many_dists_fn(cfg):
    """Smoke objective over every distribution family."""
    t = 0.0
    t += (cfg["a_u"] - 1.0) ** 2 / 25.0
    t += (cfg["b_qu"] - 2.0) ** 2 / 25.0
    t += (math.log(max(cfg["c_lu"], 1e-12)) + 1.0) ** 2 / 9.0
    t += (cfg["d_n"] / 2.0) ** 2
    t += (cfg["e_qn"] / 4.0) ** 2
    t += (math.log(max(cfg["f_ln"], 1e-12)) / 2.0) ** 2
    t += abs(cfg["g_ri"] - 3) / 10.0
    branch = cfg["branch"]
    if branch["which"] == 0:
        t += (branch["inner_u"] - 0.5) ** 2
    elif branch["which"] == 1:
        t += 0.1 + (math.log(max(branch["inner_lu"], 1e-12)) - 0.0) ** 2 / 9.0
    else:
        t += 0.05 + abs(branch["inner_c"] - 1) * 0.2
    return t


def _space_quadratic1():
    return hp.uniform("x", -5, 5)


def _space_q1_lognormal():
    return hp.lognormal("x", 0.0, 1.0)


def _space_q1_choice():
    return hp.choice(
        "p",
        [
            {"case": 1, "x": hp.uniform("x1", -5, 5)},
            {"case": 2, "x": hp.uniform("x2", -5, 5)},
        ],
    )


def _space_n_arms():
    return hp.choice("arm", [0, 1, 2, 3, 4])


def _space_branin():
    return {"x1": hp.uniform("x1", -5, 10), "x2": hp.uniform("x2", 0, 15)}


def _space_hartmann6():
    return {f"x{i}": hp.uniform(f"x{i}", 0, 1) for i in range(6)}


def _space_rosenbrock2():
    return {"x": hp.uniform("x", -2, 2), "y": hp.uniform("y", -1, 3)}


def _space_gauss_wave2():
    return hp.choice(
        "curve",
        [
            {"kind": "raw", "x": hp.uniform("x_raw", -20, 20)},
            {
                "kind": "amp",
                "x": hp.uniform("x_amp", -20, 20),
                "amp": hp.uniform("amp", 0.5, 1.5),
            },
        ],
    )


def _trap15_fn(cfg):
    """Deceptive multi-basin trap (round-3 ATPE stall battery).

    Each of 15 dims has a BROAD gentle basin at x=-2 (floor 0.18) and a
    NARROW basin reaching 0 at x=+3 (catchment ~1.7% of the range):
    posterior exploitation converges into the broad basin; leaving it
    requires continued wide-exploration draws.  Built to exercise the
    stalled-experiment adaptation levers; the measured verdict
    (BASELINE.md round 3) is that plain TPE's adaptive-Parzen PRIOR
    COMPONENT -- weight ~1/(n_below+1) in every below-model -- already
    supplies that exploration, so explicit stall levers add little.
    """
    xs = np.array([cfg[f"t{i}"] for i in range(15)])
    broad = 0.18 + (xs + 2.0) ** 2 / 30.0
    narrow = 25.0 * (xs - 3.0) ** 2
    return float(np.mean(np.minimum(broad, narrow)))


def _space_trap15():
    return {f"t{i}": hp.uniform(f"t{i}", -5.0, 5.0) for i in range(15)}


def _space_many_dists():
    return {
        "a_u": hp.uniform("a_u", -5, 5),
        "b_qu": hp.quniform("b_qu", -5, 5, 0.5),
        "c_lu": hp.loguniform("c_lu", -4, 2),
        "d_n": hp.normal("d_n", 0, 2),
        "e_qn": hp.qnormal("e_qn", 0, 4, 1),
        "f_ln": hp.lognormal("f_ln", 0, 1),
        "g_ri": hp.randint("g_ri", 10),
        "branch": hp.choice(
            "branch",
            [
                {"which": 0, "inner_u": hp.uniform("inner_u", 0, 1)},
                {"which": 1, "inner_lu": hp.loguniform("inner_lu", -3, 3)},
                {"which": 2, "inner_c": hp.pchoice(
                    "inner_c", [(0.2, 0), (0.5, 1), (0.3, 2)]
                )},
            ],
        ),
    }


DOMAINS = {
    d.name: d
    for d in [
        SyntheticDomain(
            "quadratic1", _quadratic1_fn, _space_quadratic1, 0.0,
            {80: 0.3},
        ),
        SyntheticDomain(
            "q1_lognormal", _q1_lognormal_fn, _space_q1_lognormal, 0.0,
            {80: 0.5},
        ),
        SyntheticDomain(
            "q1_choice", _q1_choice_fn, _space_q1_choice, 0.0,
            {80: 0.35},
        ),
        SyntheticDomain(
            "n_arms", _n_arms_fn, _space_n_arms, 0.0,
            {30: 0.0},
        ),
        SyntheticDomain(
            "branin", branin_fn, _space_branin, 0.397887,
            {100: 1.2},
        ),
        SyntheticDomain(
            "hartmann6", hartmann6_fn, _space_hartmann6, -3.32237,
            {150: -1.2},
        ),
        SyntheticDomain(
            "rosenbrock2", _rosenbrock2_fn, _space_rosenbrock2, 0.0,
            {120: 6.0},
        ),
        SyntheticDomain(
            "gauss_wave2", _gauss_wave2_fn, _space_gauss_wave2, -1.5,
            {100: -1.0},
        ),
        SyntheticDomain(
            "many_dists", _many_dists_fn, _space_many_dists, 0.0,
            {100: 1.5},
        ),
        SyntheticDomain(
            "trap15", _trap15_fn, _space_trap15, 0.0,
            {200: 0.30},
        ),
    ]
}


def battery(names=None):
    """The canonical domain list (CasePerDomain-style reuse, SURVEY.md SS4)."""
    if names is None:
        return list(DOMAINS.values())
    return [DOMAINS[n] for n in names]


# -- throughput benchmark space --------------------------------------------


def mixed_space(n_uniform=8, n_loguniform=4, n_quniform=2, n_randint=3, n_choice=3):
    """A D-dim mixed continuous/categorical flat space (defaults: 20-dim,
    the BASELINE.json throughput config)."""
    space = {}
    for i in range(n_uniform):
        space[f"u{i}"] = hp.uniform(f"u{i}", -5, 5)
    for i in range(n_loguniform):
        space[f"lu{i}"] = hp.loguniform(f"lu{i}", -5, 2)
    for i in range(n_quniform):
        space[f"qu{i}"] = hp.quniform(f"qu{i}", 0, 20, 1)
    for i in range(n_randint):
        space[f"ri{i}"] = hp.randint(f"ri{i}", 8)
    for i in range(n_choice):
        space[f"ch{i}"] = hp.choice(f"ch{i}", list(range(5)))
    return space


def mixed_space_fn(cfg):
    """Cheap separable loss over ``mixed_space`` (throughput benchmarking:
    objective cost ~0 so suggest dominates)."""
    t = 0.0
    for k, v in cfg.items():
        if k.startswith("lu"):
            t += (math.log(max(v, 1e-12))) ** 2 / 50.0
        elif k.startswith("u"):
            t += (v - 1.0) ** 2 / 50.0
        elif k.startswith("qu"):
            t += abs(v - 10.0) / 100.0
        elif k.startswith("ri") or k.startswith("ch"):
            t += 0.02 * (v % 3)
    return t


def budgeted_quadratic_fn(cfg, budget):
    """Multi-fidelity battery member for the scheduler family
    (SHA/Hyperband/ASHA drivers and their distributed twins): a
    quadratic whose observation noise shrinks with evaluation budget,
    so promotion must pick genuinely good configs through rung-0 noise.
    Deterministic per ``(config, budget)`` and module-level picklable --
    the Domain-shipping backends (filequeue/Mongo) can send it to
    worker processes."""
    rng = np.random.default_rng(int(1e6 * (cfg["x"] % 1)) % 2**31)
    return (cfg["x"] - 3.0) ** 2 + float(rng.normal(0.0, 1.0 / budget))


def budgeted_quadratic_space():
    return {"x": hp.uniform("x", -10.0, 10.0)}


def mixed_space_fn_jax(cfg):
    """``mixed_space_fn`` as jnp math over ``[batch]`` value arrays -- the
    device-loop twin (``device_loop.compile_fmin`` needs a JAX-traceable
    objective).  Categorical/randint dims arrive as float indices."""
    import jax.numpy as jnp

    t = 0.0
    for k, v in cfg.items():
        if k.startswith("lu"):
            t = t + jnp.log(jnp.maximum(v, 1e-12)) ** 2 / 50.0
        elif k.startswith("u"):
            t = t + (v - 1.0) ** 2 / 50.0
        elif k.startswith("qu"):
            t = t + jnp.abs(v - 10.0) / 100.0
        elif k.startswith("ri") or k.startswith("ch"):
            t = t + 0.02 * (jnp.round(v).astype(jnp.int32) % 3)
    return t


def mlp_tune_space():
    """The MLP-tuning search space: optimizer hyperparameters of a
    fixed-architecture regressor (shapes are static; the knobs are the
    training dynamics -- lr, momentum, weight decay, init scale)."""
    return {
        "lr": hp.loguniform("lr", math.log(1e-3), math.log(1.0)),
        "momentum": hp.uniform("momentum", 0.0, 0.99),
        "wd": hp.loguniform("wd", math.log(1e-6), math.log(1e-2)),
        "init_scale": hp.loguniform(
            "init_scale", math.log(1e-2), math.log(1.0)
        ),
    }


def mlp_tune_objective(n_epochs=8, n_train=256, in_dim=8, hidden=32,
                       seed=0):
    """End-to-end MLP tuning as a :class:`hyperopt_tpu.device_loop.
    TrainableObjective`: each trial initializes its own 2-layer MLP
    (tanh head) at its drawn ``init_scale``, trains ``n_epochs``
    full-batch SGD+momentum epochs on a fixed synthetic regression set
    (device-resident after the first dispatch), and reports final MSE.
    A REAL vmapped training loop -- params and momentum are per-trial
    carried state inside the experiment scan, not a closed-form
    objective.  Pair with :func:`mlp_tune_space`."""
    import jax
    import jax.numpy as jnp

    from ..device_loop import TrainableObjective

    key = jax.random.key(seed)
    kx, kw, kn = jax.random.split(key, 3)
    X = jax.random.normal(kx, (n_train, in_dim), jnp.float32)
    w_true = jax.random.normal(kw, (in_dim,), jnp.float32)
    y = jnp.tanh(X @ w_true) + 0.1 * jax.random.normal(
        kn, (n_train,), jnp.float32
    )

    def _mse(params):
        h = jnp.tanh(X @ params["w1"] + params["b1"])
        pred = h @ params["w2"] + params["b2"]
        return jnp.mean((pred - y) ** 2)

    def init_fn(k, cfg):
        k1, k2 = jax.random.split(k)
        scale = cfg["init_scale"]
        params = {
            "w1": scale * jax.random.normal(
                k1, (in_dim, hidden), jnp.float32
            ),
            "b1": jnp.zeros((hidden,), jnp.float32),
            "w2": scale * jax.random.normal(
                k2, (hidden,), jnp.float32
            ),
            "b2": jnp.zeros((), jnp.float32),
        }
        momentum = jax.tree.map(jnp.zeros_like, params)
        return params, momentum

    def step_fn(state, cfg, epoch):
        del epoch  # constant-lr schedule
        params, momentum = state
        grads = jax.grad(_mse)(params)
        momentum = jax.tree.map(
            lambda m, g, p: cfg["momentum"] * m - cfg["lr"] * (
                g + cfg["wd"] * p
            ),
            momentum, grads, params,
        )
        params = jax.tree.map(lambda p, m: p + m, params, momentum)
        return params, momentum

    def loss_fn(state, cfg):
        params, _ = state
        return _mse(params)

    return TrainableObjective(init_fn, step_fn, loss_fn, n_epochs=n_epochs)


def cond_tune_space():
    """A CONDITIONAL training search space (nested ``hp.choice``):
    regularizer family on the outer choice, a Nesterov-style boost
    behind a second choice nested inside the momentum branch.  The
    device loop's active-mask contract is what makes this trainable
    on-device: off-branch dims arrive as 0.0 (the host driver simply
    omits them), so :func:`cond_tune_objective` reads every label
    unconditionally without gating on the choice index itself."""
    return {
        "ct_lr": hp.loguniform("ct_lr", math.log(1e-3), math.log(1.0)),
        "reg": hp.choice("ct_reg", [
            {"kind": "none"},
            {
                "kind": "l2",
                "wd": hp.loguniform(
                    "ct_wd", math.log(1e-6), math.log(1e-1)
                ),
            },
            {
                "kind": "momentum",
                "mu": hp.uniform("ct_mu", 0.0, 0.99),
                "nest": hp.choice("ct_nest", [
                    {"boost": "off"},
                    {
                        "boost": "on",
                        "extra": hp.uniform("ct_extra", 0.0, 1.0),
                    },
                ]),
            },
        ]),
    }


def cond_tune_objective(n_epochs=4, n_train=64, in_dim=4, hidden=8,
                        seed=0):
    """The conditional-space twin of :func:`mlp_tune_objective` (pair
    with :func:`cond_tune_space`).  Deliberately reads the off-branch
    dims (``ct_wd``/``ct_mu``/``ct_extra``) UNGATED -- correct if and
    only if the compiled scan masks inactive-branch columns to 0.0 at
    init, exactly the host driver's omit-inactive-labels semantics
    (the PR-10 residue the graftrung PR closes).  ``init_fn`` takes the
    ``active=`` mask keyword: the l2 branch starts from a smaller-norm
    head (branch-aware init sizing through the declared seam)."""
    import jax
    import jax.numpy as jnp

    from ..device_loop import TrainableObjective

    key = jax.random.key(seed)
    kx, kw, kn = jax.random.split(key, 3)
    X = jax.random.normal(kx, (n_train, in_dim), jnp.float32)
    w_true = jax.random.normal(kw, (in_dim,), jnp.float32)
    y = jnp.tanh(X @ w_true) + 0.1 * jax.random.normal(
        kn, (n_train,), jnp.float32
    )

    def _mse(params):
        h = jnp.tanh(X @ params["w1"] + params["b1"])
        pred = h @ params["w2"] + params["b2"]
        return jnp.mean((pred - y) ** 2)

    def init_fn(k, cfg, active):
        k1, k2 = jax.random.split(k)
        scale = jnp.where(active["ct_wd"], 0.25, 0.5)
        params = {
            "w1": scale * jax.random.normal(
                k1, (in_dim, hidden), jnp.float32
            ),
            "b1": jnp.zeros((hidden,), jnp.float32),
            "w2": scale * jax.random.normal(
                k2, (hidden,), jnp.float32
            ),
            "b2": jnp.zeros((), jnp.float32),
        }
        momentum = jax.tree.map(jnp.zeros_like, params)
        return params, momentum

    def step_fn(state, cfg, epoch):
        del epoch
        params, momentum = state
        # every conditional knob read bare: 0.0 off-branch by contract
        lr = cfg["ct_lr"] * (1.0 + cfg["ct_extra"])
        grads = jax.grad(_mse)(params)
        momentum = jax.tree.map(
            lambda m, g, p: cfg["ct_mu"] * m - lr * (
                g + cfg["ct_wd"] * p
            ),
            momentum, grads, params,
        )
        params = jax.tree.map(lambda p, m: p + m, params, momentum)
        return params, momentum

    def loss_fn(state, cfg):
        params, _ = state
        return _mse(params)

    return TrainableObjective(init_fn, step_fn, loss_fn, n_epochs=n_epochs)
