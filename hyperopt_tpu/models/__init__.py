"""Benchmark objectives and model families.

``synthetic`` -- the domain battery (quadratic1, branin, hartmann6,
gauss_wave2, many_dists, ...) mirroring the reference's
``tests/test_domains.py`` fixtures (SURVEY.md SS4).
``surrogate`` -- HPOBench-style XGBoost surrogate (8-dim mixed space).
``nasbench`` -- NAS-Bench-201-style choice-heavy architecture search.
``resnet`` -- flax ResNet-20 with a vmapped population train step (the
TPU flagship objective, BASELINE.json config #4).
``transformer`` -- decoder-only LM on an in-context next-token task,
same population-training shape (the MXU-native family).
"""

from . import synthetic

__all__ = ["synthetic"]


def __getattr__(name):
    if name in ("surrogate", "nasbench", "resnet", "transformer"):
        import importlib

        mod = importlib.import_module(f".{name}", __name__)
        globals()[name] = mod
        return mod
    raise AttributeError(name)
