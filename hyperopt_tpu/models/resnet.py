"""ResNet-20 / CIFAR-10 with a vmapped population train step.

BASELINE.json config #4: the lr+weight-decay sweep evaluates a whole
*population* of ResNet-20s at once -- hyperparameters become a batched
leading axis via ``vmap`` (population training), the population shards
over the ``trial`` mesh axis and each member's data batch over ``cand``
(reusing the suggest mesh).  This is the TPU-native replacement for
farming one model per worker process: the MXU sees one big fused program
instead of P small ones.

Synthetic CIFAR-shaped data keeps the objective hermetic (zero-egress
image); swap ``synthetic_cifar_batch`` for a real loader in production.
"""

from __future__ import annotations

import functools

import numpy as np

__all__ = [
    "ResNet",
    "resnet20",
    "synthetic_cifar_batch",
    "make_population_train_step",
    "population_objective",
    "hpo_space",
]


def _flax():
    import flax.linen as nn

    return nn


def resnet20(num_classes=10, width=16):
    """Standard CIFAR ResNet-20: 3 stages x 3 basic blocks, 16/32/64 ch."""
    return ResNet(stage_sizes=(3, 3, 3), num_classes=num_classes, width=width)


def ResNet(stage_sizes=(3, 3, 3), num_classes=10, width=16):
    nn = _flax()
    import jax.numpy as jnp

    class BasicBlock(nn.Module):
        filters: int
        strides: int = 1

        @nn.compact
        def __call__(self, x, train=True):
            residual = x
            y = nn.Conv(self.filters, (3, 3), strides=(self.strides,) * 2,
                        padding="SAME", use_bias=False)(x)
            y = nn.GroupNorm(num_groups=8)(y)
            y = nn.relu(y)
            y = nn.Conv(self.filters, (3, 3), padding="SAME", use_bias=False)(y)
            y = nn.GroupNorm(num_groups=8)(y)
            if residual.shape != y.shape:
                residual = nn.Conv(self.filters, (1, 1),
                                   strides=(self.strides,) * 2,
                                   use_bias=False)(residual)
                residual = nn.GroupNorm(num_groups=8)(residual)
            return nn.relu(y + residual)

    class _ResNet(nn.Module):
        @nn.compact
        def __call__(self, x, train=True):
            y = nn.Conv(width, (3, 3), padding="SAME", use_bias=False)(x)
            y = nn.GroupNorm(num_groups=8)(y)
            y = nn.relu(y)
            for stage, n_blocks in enumerate(stage_sizes):
                filters = width * (2**stage)
                for block in range(n_blocks):
                    strides = 2 if stage > 0 and block == 0 else 1
                    y = BasicBlock(filters, strides)(y, train=train)
            y = jnp.mean(y, axis=(1, 2))
            return nn.Dense(num_classes)(y)

    # GroupNorm (not BatchNorm): batch-stat-free so population vmap and
    # mesh sharding need no cross-replica stat sync.
    return _ResNet()


def synthetic_cifar_batch(key, batch_size=128, image_size=32, num_classes=10):
    """Deterministic CIFAR-shaped synthetic batch (class-conditional means
    so the task is learnable, not pure noise)."""
    import jax
    import jax.numpy as jnp

    k_lbl, k_img = jax.random.split(key)
    labels = jax.random.randint(k_lbl, (batch_size,), 0, num_classes)
    means = jnp.linspace(-1.0, 1.0, num_classes)[labels]
    images = means[:, None, None, None] * 0.5 + 0.5 * jax.random.normal(
        k_img, (batch_size, image_size, image_size, 3)
    )
    return images, labels


def make_population_train_step(model, mesh=None, trial_axis="trial",
                               data_axis="cand"):
    """Build ``train_step(pop_params, pop_opt, lr, wd, images, labels)``.

    vmaps a single-model SGD(+momentum, +weight-decay) step over the
    population leading axis; with ``mesh`` given, population shards over
    ``trial_axis`` and the data batch over ``data_axis`` via sharding
    constraints (GSPMD inserts the collectives -- SURVEY.md SS5 TPU
    equivalent of trial-level farming).
    """
    import jax
    import jax.numpy as jnp
    import optax

    def loss_fn(params, images, labels):
        logits = model.apply({"params": params}, images)
        loss = optax.softmax_cross_entropy_with_integer_labels(
            logits, labels
        ).mean()
        return loss, logits

    def one_member_step(params, momentum, lr, wd, images, labels):
        (loss, _), grads = jax.value_and_grad(loss_fn, has_aux=True)(
            params, images, labels
        )
        new_momentum = jax.tree.map(
            lambda m, g: 0.9 * m + g, momentum, grads
        )
        new_params = jax.tree.map(
            lambda p, m: p - lr * (m + wd * p), params, new_momentum
        )
        return new_params, new_momentum, loss

    pop_step = jax.vmap(one_member_step, in_axes=(0, 0, 0, 0, None, None))

    if mesh is None:
        return jax.jit(pop_step)

    from jax.sharding import NamedSharding, PartitionSpec as P

    pop_spec = P(trial_axis)
    data_spec = P(data_axis)

    def sharded_step(pop_params, pop_momentum, lr, wd, images, labels):
        constrain = functools.partial(jax.lax.with_sharding_constraint)
        pop_params = jax.tree.map(
            lambda x: constrain(x, NamedSharding(mesh, pop_spec)), pop_params
        )
        images = constrain(images, NamedSharding(mesh, data_spec))
        labels = constrain(labels, NamedSharding(mesh, data_spec))
        return pop_step(pop_params, pop_momentum, lr, wd, images, labels)

    return jax.jit(sharded_step)


def init_population(model, pop_size, key, image_size=32):
    """Per-member init (different seeds) stacked on a leading axis."""
    import jax
    import jax.numpy as jnp

    def init_one(k):
        dummy = jnp.zeros((1, image_size, image_size, 3))
        return model.init(k, dummy)["params"]

    keys = jax.random.split(key, pop_size)
    return jax.vmap(init_one)(keys)


def hpo_space():
    """The lr+wd sweep space (config #4)."""
    from .. import hp

    return {
        "lr": hp.loguniform("lr", np.log(1e-4), np.log(1.0)),
        "wd": hp.loguniform("wd", np.log(1e-6), np.log(1e-2)),
    }


def population_objective(pop_size=4, n_steps=3, batch_size=32, image_size=8,
                         width=8, seed=0, mesh=None):
    """Factory: an fmin-compatible objective that trains a (tiny by
    default) ResNet population member with the suggested lr/wd and returns
    final train loss.  Uses Ctrl-free sync evaluation; for population
    batching pass configs through ``suggest_batch`` + ThreadTrials."""
    import jax
    import jax.numpy as jnp

    model = ResNet(stage_sizes=(1, 1, 1), width=width) if width <= 8 else resnet20()
    step = make_population_train_step(model, mesh=mesh)
    key = jax.random.key(seed)
    init_key, data_key = jax.random.split(key)
    images, labels = synthetic_cifar_batch(data_key, batch_size, image_size)

    def objective(cfg):
        params = init_population(model, 1, init_key, image_size)
        momentum = jax.tree.map(jnp.zeros_like, params)
        lr = jnp.asarray([cfg["lr"]], jnp.float32)
        wd = jnp.asarray([cfg["wd"]], jnp.float32)
        loss = None
        for _ in range(n_steps):
            params, momentum, loss = step(params, momentum, lr, wd, images, labels)
        return float(loss[0])

    return objective
