"""Tiny decoder-only transformer LM with a vmapped population train step.

Extends the benchmark model families (SURVEY.md SS6 configs; resnet.py is
config #4) with the workload TPUs are actually built for: causal-
attention language modeling, where the MXU sees the attention and MLP
matmuls of a whole *population* of models at once.  Same TPU-native
population-training shape as :mod:`hyperopt_tpu.models.resnet` --
hyperparameters become a batched leading axis via ``vmap``, the
population shards over the ``trial`` mesh axis and each member's token
batch over ``cand`` (reusing the suggest mesh), GSPMD inserts the
collectives.

The synthetic task is *in-context* next-token prediction: every sequence
follows ``x[t+1] = (x[t] + delta) % vocab`` with a per-sequence delta, so
the model must attend to earlier transitions to infer delta before it can
predict -- learnable only through attention, hermetic in a zero-egress
image (swap ``synthetic_token_batch`` for a real corpus in production).
"""

from __future__ import annotations

import numpy as np

__all__ = [
    "TinyLM",
    "synthetic_token_batch",
    "make_population_train_step",
    "make_pbt_train_fn",
    "init_population",
    "population_objective",
    "device_objective",
    "hpo_space",
]


def TinyLM(vocab=64, d_model=32, n_heads=2, n_layers=2, max_len=64):
    """Decoder-only pre-LN transformer LM (flax)."""
    import flax.linen as nn
    import jax.numpy as jnp

    class Block(nn.Module):
        @nn.compact
        def __call__(self, x):
            h = nn.LayerNorm()(x)
            h = nn.SelfAttention(
                num_heads=n_heads, qkv_features=d_model,
                deterministic=True,
            )(h, mask=nn.make_causal_mask(jnp.zeros(x.shape[:-1])))
            x = x + h
            h = nn.LayerNorm()(x)
            h = nn.Dense(4 * d_model)(h)
            h = nn.gelu(h)
            h = nn.Dense(d_model)(h)
            return x + h

    class _LM(nn.Module):
        @nn.compact
        def __call__(self, tokens):
            # tokens [B, T] int32 -> logits [B, T, vocab]
            pos = jnp.arange(tokens.shape[-1])
            x = nn.Embed(vocab, d_model)(tokens)
            x = x + nn.Embed(max_len, d_model)(pos)
            for _ in range(n_layers):
                x = Block()(x)
            x = nn.LayerNorm()(x)
            return nn.Dense(vocab)(x)

    return _LM()


def synthetic_token_batch(key, batch_size=64, seq_len=32, vocab=64,
                          n_deltas=8):
    """In-context modular-progression sequences.

    Each sequence picks ``delta`` from ``n_deltas`` options and a random
    start; tokens follow ``x[t+1] = (x[t] + delta) % vocab``.  Predicting
    position t requires inferring delta from earlier transitions --
    an attention-dependent task with loss floor ~log(n_deltas) at t=1
    and ~0 later.
    """
    import jax
    import jax.numpy as jnp

    k_delta, k_start = jax.random.split(key)
    deltas = jax.random.randint(k_delta, (batch_size, 1), 1, n_deltas + 1)
    starts = jax.random.randint(k_start, (batch_size, 1), 0, vocab)
    t = jnp.arange(seq_len)[None, :]
    return (starts + deltas * t) % vocab


def _next_token_loss_fn(model):
    """Shared next-token loss: ONE definition for both execution modes
    (host-driven population step and the fused device objective) so the
    BASELINE comparisons between them stay apples-to-apples."""
    import optax

    def loss_fn(params, tokens):
        logits = model.apply({"params": params}, tokens[:, :-1])
        return optax.softmax_cross_entropy_with_integer_labels(
            logits, tokens[:, 1:]
        ).mean()

    return loss_fn


def _sgd_update(params, momentum, grads, lr, wd):
    """Shared SGD(momentum=0.9, coupled weight-decay) member update."""
    import jax

    new_momentum = jax.tree.map(lambda m, g: 0.9 * m + g, momentum, grads)
    new_params = jax.tree.map(
        lambda p, m: p - lr * (m + wd * p), params, new_momentum
    )
    return new_params, new_momentum


def _member_train_step(loss_fn, params, momentum, lr, wd, tokens):
    """ONE member's gradient step -- the single definition shared by the
    population step and the PBT/SHA train fn (loss reported pre-update)."""
    import jax

    loss, grads = jax.value_and_grad(loss_fn)(params, tokens)
    params, momentum = _sgd_update(params, momentum, grads, lr, wd)
    return params, momentum, loss


def make_population_train_step(model, mesh=None, trial_axis="trial",
                               data_axis="cand"):
    """Build ``train_step(pop_params, pop_opt, lr, wd, tokens)``.

    vmaps a single-model SGD(+momentum, +weight-decay) next-token step
    over the population leading axis; with ``mesh`` given, population
    shards over ``trial_axis`` and the token batch over ``data_axis``
    (sharding constraints; GSPMD inserts the collectives).
    """
    import functools

    import jax

    loss_fn = _next_token_loss_fn(model)
    pop_step = jax.vmap(
        functools.partial(_member_train_step, loss_fn),
        in_axes=(0, 0, 0, 0, None),
    )

    if mesh is None:
        return jax.jit(pop_step)

    from jax.sharding import NamedSharding, PartitionSpec as P

    def sharded_step(pop_params, pop_momentum, lr, wd, tokens):
        constrain = jax.lax.with_sharding_constraint
        pop_params = jax.tree.map(
            lambda x: constrain(x, NamedSharding(mesh, P(trial_axis))),
            pop_params,
        )
        tokens = constrain(tokens, NamedSharding(mesh, P(data_axis)))
        return pop_step(pop_params, pop_momentum, lr, wd, tokens)

    return jax.jit(sharded_step)


def make_pbt_train_fn(model, batch_size=16, seq_len=16, vocab=16):
    """Adapter to :func:`hyperopt_tpu.pbt.compile_pbt`'s contract:
    ``train_fn(state, hypers, key) -> (state, losses[P])`` with
    ``state = (params, momentum)`` population pytrees and hypers
    ``{"lr": [P], "wd": [P]}``.  A fresh token batch is drawn from
    ``key`` every step (all members see the same data; hyperparameters
    are the only member difference, as in population training)."""
    import jax

    loss_fn = _next_token_loss_fn(model)

    def train_fn(state, hypers, key):
        params, momentum = state
        tokens = synthetic_token_batch(
            key, batch_size, seq_len, vocab, n_deltas=min(8, vocab - 1)
        )
        params, momentum, losses = jax.vmap(
            lambda p, m, lr, wd: _member_train_step(
                loss_fn, p, m, lr, wd, tokens
            )
        )(params, momentum, hypers["lr"], hypers["wd"])
        return (params, momentum), losses

    return train_fn


def init_population(model, pop_size, key, seq_len=32):
    """Per-member init (different seeds) stacked on a leading axis."""
    import jax
    import jax.numpy as jnp

    def init_one(k):
        dummy = jnp.zeros((1, seq_len - 1), jnp.int32)
        return model.init(k, dummy)["params"]

    return jax.vmap(init_one)(jax.random.split(key, pop_size))


def device_objective(n_steps=4, batch_size=16, seq_len=16, vocab=16,
                     d_model=16, n_heads=2, n_layers=1, seed=0):
    """A ``device_loop``-compatible objective: the whole HPO experiment --
    suggest, *train a TinyLM per trial*, observe -- compiles to ONE XLA
    program.

    Returns a jittable ``objective(cfg) -> [B] losses`` over a dict of
    ``[B]`` value arrays: each batch member initializes its own model
    (shared key -- the hyperparameters are the only difference), trains
    ``n_steps`` of SGD+momentum under ``lax.fori_loop``, and reports
    final next-token loss.  Feed to
    ``device_loop.compile_fmin(device_objective(...), hpo_space(), ...)``
    for zero-host-round-trip HPO over actual model training.
    """
    import jax
    import jax.numpy as jnp

    model = TinyLM(vocab=vocab, d_model=d_model, n_heads=n_heads,
                   n_layers=n_layers, max_len=seq_len)
    key = jax.random.key(seed)
    init_key, data_key = jax.random.split(key)
    tokens = synthetic_token_batch(
        data_key, batch_size, seq_len, vocab, n_deltas=min(8, vocab - 1)
    )
    # init ONCE at factory time (hyperparameters are the only per-member
    # difference); the vmapped trainer closes over the shared params
    params0 = model.init(
        init_key, jnp.zeros((1, seq_len - 1), jnp.int32)
    )["params"]
    base_loss_fn = _next_token_loss_fn(model)

    def loss_fn(params):
        return base_loss_fn(params, tokens)

    def train_one(lr, wd):
        momentum = jax.tree.map(jnp.zeros_like, params0)

        def body(_, carry):
            params, momentum = carry
            grads = jax.grad(loss_fn)(params)
            return _sgd_update(params, momentum, grads, lr, wd)

        params, _ = jax.lax.fori_loop(0, n_steps, body, (params0, momentum))
        return loss_fn(params)

    def objective(cfg):
        return jax.vmap(train_one)(
            jnp.asarray(cfg["lr"], jnp.float32),
            jnp.asarray(cfg["wd"], jnp.float32),
        )

    return objective


def hpo_space():
    """lr + weight-decay sweep (the transformer twin of resnet config #4)."""
    from .. import hp

    return {
        "lr": hp.loguniform("lr", np.log(1e-4), np.log(1.0)),
        "wd": hp.loguniform("wd", np.log(1e-6), np.log(1e-2)),
    }


def budget_objective(batch_size=16, seq_len=16, vocab=16, d_model=16,
                     n_heads=2, n_layers=1, seed=0):
    """Budget-aware DEVICE objective for the async schedulers
    (:func:`hyperopt_tpu.hyperband.asha` / ``successive_halving`` /
    ``hyperband``): ``fn(cfg, budget) -> float`` trains a TinyLM for
    ``budget`` SGD steps as one jitted device program and fetches the
    final next-token loss (VERDICT r4 weak #6: the scheduler that
    exists to exploit async hardware had never touched hardware).

    One compiled program per DISTINCT budget (rung budgets form a small
    ladder, so compiles are bounded and cached).  Thread-safe by
    construction: the jitted programs hold no Python state, JAX
    dispatch is thread-safe, and a racy double-compile of the same
    budget is harmless -- ASHA's workers overlap their host-side
    scheduling and result fetches with each other's device queue time,
    which is exactly the overlap the async scheduler exists to buy.
    """
    import jax
    import jax.numpy as jnp

    model = TinyLM(vocab=vocab, d_model=d_model, n_heads=n_heads,
                   n_layers=n_layers, max_len=seq_len)
    key = jax.random.key(seed)
    init_key, data_key = jax.random.split(key)
    tokens = synthetic_token_batch(
        data_key, batch_size, seq_len, vocab, n_deltas=min(8, vocab - 1)
    )
    params0 = model.init(
        init_key, jnp.zeros((1, seq_len - 1), jnp.int32)
    )["params"]
    base_loss_fn = _next_token_loss_fn(model)

    def loss_fn(params):
        return base_loss_fn(params, tokens)

    progs = {}

    def make_prog(n_steps):
        def train(lr, wd):
            momentum = jax.tree.map(jnp.zeros_like, params0)

            def body(_, carry):
                params, momentum = carry
                grads = jax.grad(loss_fn)(params)
                return _sgd_update(params, momentum, grads, lr, wd)

            params, _ = jax.lax.fori_loop(
                0, n_steps, body, (params0, momentum)
            )
            return loss_fn(params)

        return jax.jit(train)

    def fn(cfg, budget):
        n = int(budget)
        prog = progs.get(n)
        if prog is None:
            prog = progs.setdefault(n, make_prog(n))
        return float(prog(jnp.float32(cfg["lr"]), jnp.float32(cfg["wd"])))

    return fn


def population_objective(n_steps=4, batch_size=16, seq_len=16, vocab=16,
                         d_model=16, n_heads=2, n_layers=1, seed=0,
                         mesh=None):
    """Factory: an fmin-compatible objective -- train a TinyLM with the
    suggested lr/wd for ``n_steps`` and return final next-token loss."""
    import jax
    import jax.numpy as jnp

    model = TinyLM(vocab=vocab, d_model=d_model, n_heads=n_heads,
                   n_layers=n_layers, max_len=seq_len)
    step = make_population_train_step(model, mesh=mesh)
    key = jax.random.key(seed)
    init_key, data_key = jax.random.split(key)
    tokens = synthetic_token_batch(
        data_key, batch_size, seq_len, vocab, n_deltas=min(8, vocab - 1)
    )

    def objective(cfg):
        params = init_population(model, 1, init_key, seq_len)
        momentum = jax.tree.map(jnp.zeros_like, params)
        lr = jnp.asarray([cfg["lr"]], jnp.float32)
        wd = jnp.asarray([cfg["wd"]], jnp.float32)
        loss = None
        for _ in range(n_steps):
            params, momentum, loss = step(params, momentum, lr, wd, tokens)
        return float(loss[0])

    return objective
