"""NAS-Bench-201-style architecture search benchmark (choice-heavy space).

BASELINE.json config #5: the NAS-Bench-201 cell is a DAG on 4 nodes with
6 edges, each edge labeled by one of 5 operations -- as a search space,
6 stacked ``hp.choice`` dims (5^6 = 15625 architectures).  The real
benchmark is a lookup table of trained accuracies; this hermetic stand-in
synthesizes a table with the same statistical character: strong per-edge
op marginals, pairwise edge interactions, and a deterministic per-arch
residual.  ``tabular=True`` precomputes the full 15625-entry table (so
the judge can verify against exhaustive argmin); the default computes
entries on demand.
"""

from __future__ import annotations

import itertools

import numpy as np

from .. import hp

__all__ = [
    "OPS",
    "N_EDGES",
    "space",
    "objective",
    "arch_from_config",
    "full_table",
    "optimal_loss",
]

OPS = ("none", "skip_connect", "nor_conv_1x1", "nor_conv_3x3", "avg_pool_3x3")
N_EDGES = 6  # 4-node cell: edges (0,1),(0,2),(0,3),(1,2),(1,3),(2,3)

# deterministic structured table parameters (fixed seed; part of the
# benchmark definition, like a checked-in lookup table)
_rng = np.random.default_rng(201)
# marginal utility of op o on edge e
_MARGINAL = _rng.normal(0.0, 1.0, size=(N_EDGES, len(OPS)))
# conv ops are better on average; 'none' prunes capacity
_MARGINAL[:, OPS.index("nor_conv_3x3")] += 1.2
_MARGINAL[:, OPS.index("nor_conv_1x1")] += 0.8
_MARGINAL[:, OPS.index("none")] -= 1.0
# pairwise interactions between edge ops
_PAIRS = _rng.normal(0.0, 0.25, size=(N_EDGES, N_EDGES, len(OPS), len(OPS)))


def space():
    """6 x hp.choice over the 5 ops (flat choice-heavy space)."""
    return {f"edge{e}": hp.choice(f"edge{e}", list(range(len(OPS))))
            for e in range(N_EDGES)}


def arch_from_config(cfg):
    return tuple(int(cfg[f"edge{e}"]) for e in range(N_EDGES))


def _raw_score(arch):
    s = sum(_MARGINAL[e, op] for e, op in enumerate(arch))
    for e1 in range(N_EDGES):
        for e2 in range(e1 + 1, N_EDGES):
            s += _PAIRS[e1, e2, arch[e1], arch[e2]]
    # deterministic residual (per-arch 'training noise'); Python ints with
    # an explicit 64-bit mask give the same wraparound as uint64 without
    # numpy's overflow RuntimeWarning
    h = 0
    for op in arch:
        h = (h * 1000003 + op + 1) & 0xFFFFFFFFFFFFFFFF
    resid = (float(h % 10_000) / 10_000.0 - 0.5) * 0.3
    return s + resid


def objective(cfg):
    """Loss = 100 - synthetic accuracy (%), in roughly [5, 45]."""
    arch = arch_from_config(cfg)
    score = _raw_score(arch)
    acc = 55.0 + 40.0 / (1.0 + np.exp(-0.35 * score))  # 55..95%
    return float(100.0 - acc)


_table_cache = None


def full_table():
    """All 15625 (arch, loss) pairs (cached)."""
    global _table_cache
    if _table_cache is None:
        archs = list(itertools.product(range(len(OPS)), repeat=N_EDGES))
        losses = np.array(
            [objective({f"edge{e}": a[e] for e in range(N_EDGES)}) for a in archs]
        )
        _table_cache = (archs, losses)
    return _table_cache


def optimal_loss():
    _, losses = full_table()
    return float(losses.min())
