"""HPOBench-style XGBoost surrogate benchmark (8-dim mixed space).

BASELINE.json config #3: a mixed continuous/int/categorical space shaped
like XGBoost's hyperparameters with a deterministic, structured response
surface standing in for the real HPOBench lookup tables (which cannot be
downloaded in a zero-egress image).  The surface has the properties that
make HPOBench discriminative for optimizers: a log-scale optimum basin
for eta/regularization, integer plateaus for depth, interaction terms,
categorical offsets, and a rugged low-amplitude residual.
"""

from __future__ import annotations

import math

import numpy as np

from .. import hp

__all__ = ["space", "objective", "best_known"]


def space():
    """8-dim mixed: 4 cont (log/linear) + 2 int + 2 categorical."""
    return {
        "eta": hp.loguniform("eta", math.log(1e-3), math.log(1.0)),
        "reg_lambda": hp.loguniform("reg_lambda", math.log(1e-5), math.log(10.0)),
        "subsample": hp.uniform("subsample", 0.3, 1.0),
        "colsample": hp.uniform("colsample", 0.3, 1.0),
        "max_depth": hp.uniformint("max_depth", 2, 12),
        "min_child_weight": hp.quniform("min_child_weight", 1, 20, 1),
        "booster": hp.choice("booster", ["gbtree", "dart"]),
        "grow_policy": hp.pchoice(
            "grow_policy", [(0.7, "depthwise"), (0.3, "lossguide")]
        ),
    }


def _rugged(x, scale=0.015):
    """Deterministic low-amplitude residual (makes the surface non-convex
    without hiding the basin)."""
    return scale * math.sin(37.0 * x) * math.cos(17.0 * x * x)


def objective(cfg):
    """Validation-error-like loss in [0, ~1.2]; optimum ~0.031."""
    log_eta = math.log(cfg["eta"])
    log_lam = math.log(cfg["reg_lambda"])

    # basin: eta near 5e-2, lambda near 1e-2 (log-space quadratics)
    loss = 0.03
    loss += 0.018 * (log_eta - math.log(5e-2)) ** 2
    loss += 0.004 * (log_lam - math.log(1e-2)) ** 2
    # depth plateau: 6..8 optimal, integer steps matter
    depth = int(cfg["max_depth"])
    loss += 0.012 * max(0, 6 - depth) + 0.008 * max(0, depth - 8)
    # subsample/colsample ridge with interaction
    loss += 0.05 * (cfg["subsample"] - 0.85) ** 2
    loss += 0.05 * (cfg["colsample"] - 0.8) ** 2
    loss += 0.04 * abs(cfg["subsample"] - cfg["colsample"]) * (
        1.0 if depth > 8 else 0.3
    )
    # min_child_weight: mild preference for small values, interacting
    # with eta (big eta + small mcw overfits)
    mcw = float(cfg["min_child_weight"])
    loss += 0.002 * mcw
    loss += 0.02 * max(0.0, log_eta - math.log(0.2)) * max(0.0, 5.0 - mcw)
    # categorical offsets
    if cfg["booster"] == "dart":
        loss += 0.006
    if cfg["grow_policy"] == "lossguide":
        loss += 0.004 if depth <= 8 else -0.003
    # rugged residual keyed on the continuous dims
    loss += abs(_rugged(log_eta) + _rugged(cfg["subsample"], 0.01))
    return float(loss)


def best_known():
    """Approximate optimal loss (for test thresholds)."""
    return 0.032
