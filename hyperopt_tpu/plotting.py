"""matplotlib diagnostics over a Trials history.

Capability parity with the reference's ``hyperopt/plotting.py``
(SURVEY.md SS2): loss-vs-time scatter (``main_plot_history``), loss
histogram (``main_plot_histogram``), and per-hyperparameter scatters
colored by loss (``main_plot_vars``).  matplotlib is imported lazily so
the core package has no hard dependency.
"""

from __future__ import annotations

import logging

import numpy as np

from .base import JOB_STATE_DONE, STATUS_OK

logger = logging.getLogger(__name__)

__all__ = ["main_plot_history", "main_plot_histogram", "main_plot_vars"]

default_status_colors = {
    "new": "k",
    "running": "g",
    "ok": "b",
    "fail": "r",
}


def _plt():
    import matplotlib.pyplot as plt

    return plt


def _ok_losses(trials, bandit=None):
    losses, statuses = [], []
    for t in trials.trials:
        r = t["result"]
        statuses.append(r.get("status", "new"))
        losses.append(r.get("loss"))
    return losses, statuses


def main_plot_history(trials, do_show=True, status_colors=None, title=None):
    """Scatter of loss against trial order, colored by status; the running
    best is overlaid."""
    plt = _plt()
    if status_colors is None:
        status_colors = default_status_colors
    losses, statuses = _ok_losses(trials)

    for status in sorted(set(statuses)):
        xs = [i for i, s in enumerate(statuses) if s == status and losses[i] is not None]
        ys = [losses[i] for i in xs]
        plt.scatter(
            xs, ys, c=status_colors.get(status, "m"), label=status, s=12
        )
    ok = [
        (i, l)
        for i, (l, s) in enumerate(zip(losses, statuses))
        if s == STATUS_OK and l is not None and np.isfinite(l)
    ]
    if ok:
        best = np.minimum.accumulate([l for _, l in ok])
        plt.plot([i for i, _ in ok], best, "k--", lw=1, label="best so far")
    plt.xlabel("trial")
    plt.ylabel("loss")
    plt.title(title or "loss history")
    plt.legend(loc="best", fontsize=8)
    if do_show:
        plt.show()
    return plt.gcf()


def main_plot_histogram(trials, do_show=True, title=None):
    """Histogram of completed ok losses."""
    plt = _plt()
    losses = [
        t["result"]["loss"]
        for t in trials.trials
        if t["state"] == JOB_STATE_DONE
        and t["result"].get("status") == STATUS_OK
        and t["result"].get("loss") is not None
    ]
    if not losses:
        logger.warning("main_plot_histogram: no completed ok trials")
        return None
    plt.hist(np.asarray(losses, dtype=float), bins=min(30, max(5, len(losses) // 3)))
    plt.xlabel("loss")
    plt.ylabel("count")
    plt.title(title or f"loss histogram ({len(losses)} trials)")
    if do_show:
        plt.show()
    return plt.gcf()


def main_plot_vars(trials, do_show=True, colorize_best=10, columns=3):
    """Per-hyperparameter scatter of value vs loss; the best trials are
    highlighted."""
    plt = _plt()
    samples = []  # (label, value, loss)
    for t in trials.trials:
        if t["state"] != JOB_STATE_DONE:
            continue
        loss = t["result"].get("loss")
        if loss is None or not np.isfinite(float(loss)):
            continue
        for label, vals in t["misc"]["vals"].items():
            if len(vals) == 1:
                samples.append((label, vals[0], float(loss)))
    if not samples:
        logger.warning("main_plot_vars: nothing to plot")
        return None
    labels = sorted({s[0] for s in samples})
    all_losses = sorted(s[2] for s in samples)
    best_cut = (
        all_losses[min(colorize_best, len(all_losses) - 1)]
        if colorize_best
        else None
    )
    rows = int(np.ceil(len(labels) / columns))
    fig, axes = plt.subplots(
        rows, columns, figsize=(4 * columns, 3 * rows), squeeze=False
    )
    for i, label in enumerate(labels):
        ax = axes[i // columns][i % columns]
        pts = [(v, l) for (lab, v, l) in samples if lab == label]
        xs = [p[0] for p in pts]
        ys = [p[1] for p in pts]
        colors = (
            ["r" if l <= best_cut else "b" for _, l in pts]
            if best_cut is not None
            else "b"
        )
        ax.scatter(xs, ys, c=colors, s=10)
        ax.set_title(label, fontsize=9)
        ax.set_ylabel("loss", fontsize=8)
    for j in range(len(labels), rows * columns):
        axes[j // columns][j % columns].axis("off")
    fig.tight_layout()
    if do_show:
        plt.show()
    return fig
