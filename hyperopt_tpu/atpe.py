"""Adaptive TPE: self-tuning TPE hyperparameters + parameter locking.

Capability parity with the reference's ``hyperopt/atpe.py`` (SURVEY.md
SS2): the reference ships pretrained LightGBM/scikit-learn meta-models
(JSON/txt blobs) that pick TPE's own hyperparameters and lock converged
parameters per space.  Pretrained blobs cannot ship here (zero-egress
image, no lightgbm), so this implementation derives the same *decisions*
from online statistics instead of offline meta-models:

* **TPE hyperparameter adaptation** -- gamma / n_EI_candidates /
  prior_weight scale with space width, categorical fraction, history
  length and recent improvement rate;
* **parameter locking** -- hyperparameters whose values have converged
  across the elite set (low spread relative to prior width) are frozen to
  their elite modal value for a fraction of suggestions, concentrating
  search on the unconverged subspace.

If ``lightgbm`` IS importable, ``ATPEOptimizer(meta_model=...)`` accepts a
user-trained model with the same decision interface (import-gated, like
the reference's optional dependency).
"""

from __future__ import annotations

import logging

import numpy as np

from . import tpe
from .base import posterior_state
from .jax_trials import packed_space_for
from .pyll.stochastic import ensure_rng
from .rand import _domain_helper, docs_from_idxs_vals

logger = logging.getLogger(__name__)

__all__ = ["suggest", "ATPEOptimizer"]


def _ok_trials(trials):
    return [t for t in trials.trials if posterior_state(t) == "ok"]


# the categorical dim family as named by the domain helper's dist field
CAT_DISTS = ("randint", "categorical", "randint_via_categorical")


def _tpe_jax_cat_default():
    from . import tpe_jax

    return tpe_jax._default_n_EI_candidates_cat


def _pure_categorical(domain):
    """True when every dim is categorical-family -- the regime where
    ATPE's heuristics measured neutral-to-harmful (BASELINE.md).  Single
    shared predicate (packed-space classification) so settings and
    locking can never disagree about the regime."""
    ps = packed_space_for(domain)
    return ps.n_dims > 0 and len(ps.cat_idx) == ps.n_dims


class ATPEOptimizer:
    """Derives per-step TPE settings and a lock set from the history.

    ``base_n_ei`` anchors the adaptive candidate count at the caller's
    default (24 on the host parity path, 128 on the jitted TPU path) --
    adaptation may only RAISE it.  Round-2 battery measurement: anchoring
    at 24 on the TPU path silently weakened the sweep vs plain
    ``tpe_jax`` (93 < 128 candidates on NAS-Bench) and cost ~1.1 loss
    median there.
    """

    def __init__(self, lock_fraction=0.5, elite_count=8, meta_model=None,
                 base_n_ei=24):
        self.lock_fraction = lock_fraction
        self.elite_count = elite_count
        self.meta_model = meta_model  # optional lightgbm-style scorer
        self.base_n_ei = int(base_n_ei)

    # -- TPE hyperparameter adaptation ------------------------------------
    def tpe_settings(self, domain, trials):
        ps = packed_space_for(domain)
        n_dims = ps.n_dims
        frac_cat = len(ps.cat_idx) / max(n_dims, 1)
        ok = _ok_trials(trials)
        n = len(ok)

        explore_fraction = 0.0
        if _pure_categorical(domain):
            # Pure-categorical spaces: every heuristic lever measured
            # neutral-to-harmful there (BASELINE.md ATPE table -- the
            # saturated categorical argmax means extra candidates are
            # pure exploitation, a boosted prior flattens the posterior
            # that IS the exploitation mechanism, and locking emits
            # duplicates), so the heuristics emit plain TPE settings and
            # let the posterior work.  A user meta_model still gets the
            # final say below, as on every other space.
            gamma, n_ei, prior_weight = 0.25, self.base_n_ei, 1.0
        else:
            # wider spaces need a bigger elite fraction.  Candidate
            # counts adapt per FAMILY: more candidates sharpen
            # continuous dims (the llr landscape is continuous) but
            # saturate categorical dims into pure argmax exploitation
            # once draws cover every option (measured -- BASELINE.md NAS
            # table), so categorical dims pin the reference's 24 and
            # only the continuous count scales.
            gamma = float(np.clip(0.20 + 0.01 * n_dims, 0.15, 0.35))
            n_ei = int(np.clip(
                self.base_n_ei * (1 + n_dims / 20),
                self.base_n_ei, max(256, 2 * self.base_n_ei),
            ))
            prior_weight = 1.0

            # improvement trend: stalled experiments re-explore,
            # improving ones sharpen.  Stall = the best loss gained
            # less than 2% of its total improvement over the last
            # ~15 trials -- measured round 3 (BASELINE.md trap
            # battery): the previous detector (gain <= 1e-6 relative)
            # never fired on smooth objectives, where TPE inches
            # forward forever, so the lever was dead in exactly the
            # deceptive-basin regime it targets.  The response is
            # two-sided: a stronger prior widens the Parzen models AND
            # ``explore_fraction`` routes a quarter of suggestions to
            # pure prior draws (restarts) -- on deceptive multi-basin
            # spaces the posterior's own argmax cannot leave the basin
            # it converged into, only off-posterior draws can.
            if n >= 20:
                losses = [float(t["result"]["loss"]) for t in ok]
                best_first = np.minimum.accumulate(losses)
                w = min(15, max(2, n // 2))
                recent_gain = best_first[-w] - best_first[-1]
                total_gain = best_first[0] - best_first[-1]
                if recent_gain <= 0.02 * (total_gain + 1e-12):
                    prior_weight = 1.5
                    explore_fraction = 0.25
                else:
                    gamma = max(0.15, gamma - 0.05)

        if self.meta_model is not None:
            try:  # optional learned override (reference-style meta-model)
                gamma, n_ei, prior_weight = self.meta_model(
                    n_dims, frac_cat, n, gamma, n_ei, prior_weight
                )
            except Exception as e:  # pragma: no cover
                logger.warning("meta_model failed, using heuristics: %s", e)

        return {
            "gamma": gamma,
            "n_EI_candidates": n_ei,
            "prior_weight": prior_weight,
            # consumed by the jax engine's per-family sweep; the host
            # parity path reads the other fields explicitly and ignores
            # this key (its single n_EI applies to every dim, anchored
            # at the reference's 24).  Shared constant: the speculation
            # saturation guard judges against this same value.
            "n_EI_candidates_cat": _tpe_jax_cat_default(),
            # probability a suggestion is a pure prior draw (stall-
            # triggered restart; consumed by both suggest paths, never
            # forwarded to the TPE engines)
            "explore_fraction": explore_fraction,
        }

    # -- parameter locking --------------------------------------------------
    def locked_values(self, domain, trials, rng):
        """{label: value} of converged hyperparameters to freeze this step."""
        if rng.uniform() > self.lock_fraction:
            return {}
        return self.lock_candidates(domain, trials)

    def lock_candidates(self, domain, trials):
        """The gate-free half of :meth:`locked_values`: which labels have
        converged across the elite set, and to what value.  Invariant for
        a fixed history, so batched suggests compute it once and roll
        only the per-suggestion gate.

        The lock set is CAPPED at half the space's labels, keeping the
        most-converged: locking may concentrate search, never collapse it.
        Round-2 battery measurement: uncapped locking on the small
        all-categorical NAS-Bench space could freeze every arch edge to
        the elite mode, emitting duplicate architectures and losing to
        plain TPE; with the cap at least half the dims keep exploring.
        """
        ok = _ok_trials(trials)
        if len(ok) < 20:
            return {}
        ok.sort(key=lambda t: float(t["result"]["loss"]))
        elite = ok[: self.elite_count]

        if _pure_categorical(domain):
            # locking there can only re-emit elite values the
            # below-posterior already concentrates on, and a mostly-
            # locked draw is a duplicate configuration burning an
            # evaluation (measured on NAS-Bench -- BASELINE.md).  The
            # TPE posterior is the right exploitation mechanism.
            return {}
        helper = _domain_helper(domain)
        locked = {}  # label -> (convergence score in (0, 1], value)
        for label, info in helper.hps.items():
            vals = [
                t["misc"]["vals"][label][0]
                for t in elite
                if len(t["misc"]["vals"].get(label, [])) == 1
            ]
            if len(vals) < max(3, len(elite) // 2):
                continue
            if info.dist in CAT_DISTS:
                uniq, counts = np.unique(np.asarray(vals, dtype=int),
                                         return_counts=True)
                share = counts.max() / counts.sum()
                if share >= 0.8:
                    score = (share - 0.8) / 0.2
                    locked[label] = (score, int(uniq[np.argmax(counts)]))
            else:
                arr = np.asarray(vals, dtype=float)
                p = info.params
                if info.dist in ("loguniform", "qloguniform", "lognormal",
                                 "qlognormal"):
                    arr = np.log(np.maximum(arr, 1e-300))
                if "low" in p and isinstance(p.get("low"), (int, float)):
                    width = float(p["high"]) - float(p["low"])
                else:
                    width = 2.0 * float(p.get("sigma", 1.0))
                if width > 0 and arr.std() < 0.05 * width:
                    v = float(np.median(arr))
                    if info.dist.startswith("q") and isinstance(
                        p.get("q"), (int, float)
                    ):
                        q = float(p["q"])
                        v = float(np.round(v / q) * q)
                    if info.dist in ("loguniform", "qloguniform", "lognormal",
                                     "qlognormal"):
                        v = float(np.exp(v))
                    score = 1.0 - float(arr.std()) / (0.05 * width)
                    locked[label] = (score, v)
        # at least half the dims must keep exploring (locking may
        # concentrate, never collapse) -- a 1-dim space gets no locking
        max_lock = len(helper.hps) // 2
        if max_lock == 0:
            return {}
        if len(locked) > max_lock:
            keep = sorted(locked, key=lambda k: -locked[k][0])[:max_lock]
            locked = {k: locked[k] for k in keep}
        locked = {k: v for k, (_, v) in locked.items()}
        if locked:
            logger.debug("atpe locking %s", sorted(locked))
        return locked

    # -- one suggestion -----------------------------------------------------
    def suggest_config(self, domain, trials, rng, n_startup_jobs=20):
        helper = _domain_helper(domain)
        ok = _ok_trials(trials)
        if len(ok) < n_startup_jobs:
            return helper.sample_one(rng)

        settings = self.tpe_settings(domain, trials)
        if rng.uniform() < settings.get("explore_fraction", 0.0):
            # stall-triggered restart: an off-posterior prior draw (the
            # posterior's own argmax cannot leave the basin it converged
            # into); locking is skipped too -- a restart that keeps the
            # converged values is not a restart
            return helper.sample_one(rng)
        locked = self.locked_values(domain, trials, rng)

        draws = tpe._posterior_draws(
            domain, trials, rng,
            prior_weight=settings["prior_weight"],
            n_EI_candidates=settings["n_EI_candidates"],
            gamma=settings["gamma"],
            LF=tpe._default_linear_forgetting,
        )
        # freeze converged labels BEFORE routing so a locked choice also
        # re-routes its subtree consistently
        draws.update(locked)
        return tpe._route_draws(domain, draws)


def suggest(new_ids, domain, trials, seed, n_startup_jobs=20,
            lock_fraction=0.5, elite_count=8):
    """The algo plugin-boundary entry point: ``algo=atpe.suggest``."""
    rng = ensure_rng(seed)
    opt = getattr(domain, "_atpe_optimizer", None)
    if (opt is None or opt.lock_fraction != lock_fraction
            or opt.elite_count != elite_count):
        opt = ATPEOptimizer(lock_fraction=lock_fraction, elite_count=elite_count)
        domain._atpe_optimizer = opt
    helper = _domain_helper(domain)
    labels = sorted(helper.hps)
    idxs = {label: [] for label in labels}
    vals = {label: [] for label in labels}
    for tid in new_ids:
        config = opt.suggest_config(
            domain, trials, rng, n_startup_jobs=n_startup_jobs
        )
        for label, value in config.items():
            idxs[label].append(tid)
            vals[label].append(value)
    return docs_from_idxs_vals(new_ids, domain, trials, idxs, vals)
