"""Adaptive TPE: self-tuning TPE hyperparameters + parameter locking.

Capability parity with the reference's ``hyperopt/atpe.py`` (SURVEY.md
SS2): the reference ships pretrained LightGBM/scikit-learn meta-models
(JSON/txt blobs) that pick TPE's own hyperparameters and lock converged
parameters per space.  Pretrained blobs cannot ship here (zero-egress
image, no lightgbm), so this implementation derives the same *decisions*
from online statistics instead of offline meta-models:

* **TPE hyperparameter adaptation** -- gamma / n_EI_candidates /
  prior_weight scale with space width, categorical fraction, history
  length and recent improvement rate;
* **parameter locking** -- hyperparameters whose values have converged
  across the elite set (low spread relative to prior width) are frozen to
  their elite modal value for a fraction of suggestions, concentrating
  search on the unconverged subspace.

If ``lightgbm`` IS importable, ``ATPEOptimizer(meta_model=...)`` accepts a
user-trained model with the same decision interface (import-gated, like
the reference's optional dependency).
"""

from __future__ import annotations

import logging

import numpy as np

from . import tpe
from .base import JOB_STATE_DONE, STATUS_OK
from .jax_trials import packed_space_for
from .pyll.stochastic import ensure_rng
from .rand import _domain_helper, docs_from_idxs_vals

logger = logging.getLogger(__name__)

__all__ = ["suggest", "ATPEOptimizer"]


def _ok_trials(trials):
    return [
        t
        for t in trials.trials
        if t["state"] == JOB_STATE_DONE
        and t["result"].get("status") == STATUS_OK
        and t["result"].get("loss") is not None
        and np.isfinite(float(t["result"]["loss"]))
    ]


class ATPEOptimizer:
    """Derives per-step TPE settings and a lock set from the history."""

    def __init__(self, lock_fraction=0.5, elite_count=8, meta_model=None):
        self.lock_fraction = lock_fraction
        self.elite_count = elite_count
        self.meta_model = meta_model  # optional lightgbm-style scorer

    # -- TPE hyperparameter adaptation ------------------------------------
    def tpe_settings(self, domain, trials):
        ps = packed_space_for(domain)
        n_dims = ps.n_dims
        frac_cat = len(ps.cat_idx) / max(n_dims, 1)
        ok = _ok_trials(trials)
        n = len(ok)

        # wider spaces need a bigger elite fraction; categorical-heavy
        # spaces need more candidates to cover the grid
        gamma = float(np.clip(0.20 + 0.01 * n_dims, 0.15, 0.35))
        n_ei = int(np.clip(24 * (1 + 2 * frac_cat) * (1 + n_dims / 20), 24, 256))
        prior_weight = 1.0

        # improvement trend: stalled experiments get a stronger prior
        # (more exploration), improving ones sharpen (smaller gamma)
        if n >= 20:
            losses = [float(t["result"]["loss"]) for t in ok]
            best_first = np.minimum.accumulate(losses)
            recent_gain = best_first[-10] - best_first[-1]
            scale = abs(best_first[-1]) + 1e-12
            if recent_gain <= 1e-6 * scale:
                prior_weight = 1.5
            else:
                gamma = max(0.15, gamma - 0.05)

        if self.meta_model is not None:
            try:  # optional learned override (reference-style meta-model)
                gamma, n_ei, prior_weight = self.meta_model(
                    n_dims, frac_cat, n, gamma, n_ei, prior_weight
                )
            except Exception as e:  # pragma: no cover
                logger.warning("meta_model failed, using heuristics: %s", e)

        return {
            "gamma": gamma,
            "n_EI_candidates": n_ei,
            "prior_weight": prior_weight,
        }

    # -- parameter locking --------------------------------------------------
    def locked_values(self, domain, trials, rng):
        """{label: value} of converged hyperparameters to freeze this step."""
        if rng.uniform() > self.lock_fraction:
            return {}
        return self.lock_candidates(domain, trials)

    def lock_candidates(self, domain, trials):
        """The gate-free half of :meth:`locked_values`: which labels have
        converged across the elite set, and to what value.  Invariant for
        a fixed history, so batched suggests compute it once and roll
        only the per-suggestion gate."""
        ok = _ok_trials(trials)
        if len(ok) < 20:
            return {}
        ok.sort(key=lambda t: float(t["result"]["loss"]))
        elite = ok[: self.elite_count]

        helper = _domain_helper(domain)
        locked = {}
        for label, info in helper.hps.items():
            vals = [
                t["misc"]["vals"][label][0]
                for t in elite
                if len(t["misc"]["vals"].get(label, [])) == 1
            ]
            if len(vals) < max(3, len(elite) // 2):
                continue
            if info.dist in ("randint", "categorical", "randint_via_categorical"):
                uniq, counts = np.unique(np.asarray(vals, dtype=int),
                                         return_counts=True)
                if counts.max() / counts.sum() >= 0.8:
                    locked[label] = int(uniq[np.argmax(counts)])
            else:
                arr = np.asarray(vals, dtype=float)
                p = info.params
                if info.dist in ("loguniform", "qloguniform", "lognormal",
                                 "qlognormal"):
                    arr = np.log(np.maximum(arr, 1e-300))
                if "low" in p and isinstance(p.get("low"), (int, float)):
                    width = float(p["high"]) - float(p["low"])
                else:
                    width = 2.0 * float(p.get("sigma", 1.0))
                if width > 0 and arr.std() < 0.05 * width:
                    locked[label] = float(np.median(arr))
                    if info.dist.startswith("q") and isinstance(
                        p.get("q"), (int, float)
                    ):
                        q = float(p["q"])
                        locked[label] = float(np.round(locked[label] / q) * q)
                    if info.dist in ("loguniform", "qloguniform", "lognormal",
                                     "qlognormal"):
                        locked[label] = float(np.exp(locked[label]))
        if locked:
            logger.debug("atpe locking %s", sorted(locked))
        return locked

    # -- one suggestion -----------------------------------------------------
    def suggest_config(self, domain, trials, rng, n_startup_jobs=20):
        helper = _domain_helper(domain)
        ok = _ok_trials(trials)
        if len(ok) < n_startup_jobs:
            return helper.sample_one(rng)

        settings = self.tpe_settings(domain, trials)
        locked = self.locked_values(domain, trials, rng)

        draws = tpe._posterior_draws(
            domain, trials, rng,
            prior_weight=settings["prior_weight"],
            n_EI_candidates=settings["n_EI_candidates"],
            gamma=settings["gamma"],
            LF=tpe._default_linear_forgetting,
        )
        # freeze converged labels BEFORE routing so a locked choice also
        # re-routes its subtree consistently
        draws.update(locked)
        return tpe._route_draws(domain, draws)


def suggest(new_ids, domain, trials, seed, n_startup_jobs=20,
            lock_fraction=0.5, elite_count=8):
    """The algo plugin-boundary entry point: ``algo=atpe.suggest``."""
    rng = ensure_rng(seed)
    opt = getattr(domain, "_atpe_optimizer", None)
    if (opt is None or opt.lock_fraction != lock_fraction
            or opt.elite_count != elite_count):
        opt = ATPEOptimizer(lock_fraction=lock_fraction, elite_count=elite_count)
        domain._atpe_optimizer = opt
    helper = _domain_helper(domain)
    labels = sorted(helper.hps)
    idxs = {label: [] for label in labels}
    vals = {label: [] for label in labels}
    for tid in new_ids:
        config = opt.suggest_config(
            domain, trials, rng, n_startup_jobs=n_startup_jobs
        )
        for label, value in config.items():
            idxs[label].append(tid)
            vals[label].append(value)
    return docs_from_idxs_vals(new_ids, domain, trials, idxs, vals)
