"""Graph analysis helpers over hp-annotated pyll spaces.

Capability parity with the reference's ``hyperopt/pyll_utils.py``
(SURVEY.md SS2): label validation, ``expr_to_config`` (label ->
distribution + activation conditions), ``DuplicateLabel`` detection.

``expr_to_config`` is the single source of truth about a space's structure;
both the numpy TPE (:mod:`hyperopt_tpu.tpe`) and the JAX space compiler
(:mod:`hyperopt_tpu.ops.compile`) are driven by its output.
"""

from __future__ import annotations

from collections import namedtuple

from .exceptions import DuplicateLabel, InvalidAnnotatedParameter
from .pyll.base import Apply, Literal, as_apply

__all__ = ["EQ", "validate_label", "expr_to_config", "ParamInfo", "expr_signature"]


class EQ(namedtuple("EQ", ["name", "val"])):
    """Activation condition: hyperparameter ``name`` drew value ``val``."""

    __slots__ = ()

    def __repr__(self):
        return f"EQ({self.name!r}=={self.val!r})"


def validate_label(label):
    if not isinstance(label, str):
        raise InvalidAnnotatedParameter(
            f"hp label must be a string, got {type(label).__name__}: {label!r}"
        )
    if label == "":
        raise InvalidAnnotatedParameter("hp label must be non-empty")
    return label


def expr_signature(node):
    """Structural signature of a graph (for duplicate-label detection)."""
    if isinstance(node, Literal):
        try:
            hash(node.obj)
            return ("lit", node.obj)
        except TypeError:
            return ("lit-id", id(node))
    return (
        node.name,
        tuple(expr_signature(a) for a in node.pos_args),
        tuple((k, expr_signature(a)) for k, a in node.named_args),
    )


def _const_value(node):
    """Constant-fold a pure subgraph (e.g. a lifted list of floats) to its
    value; symbolic (impure/param-dependent) args stay as Apply nodes."""
    from .pyll.base import dfs, rec_eval, scope

    if isinstance(node, Literal):
        return node.obj
    for n in dfs(node):
        if not (isinstance(n, Literal) or scope.is_pure(n.name)):
            return node
    return rec_eval(node)


class ParamInfo:
    """Everything known about one labeled hyperparameter.

    Attributes:
      label: user-facing name.
      node: the distribution Apply node (e.g. ``uniform(low, high)``).
      conditions: set of condition-tuples; the param is *active* when ANY
        tuple is fully satisfied (each tuple is a conjunction of EQ terms).
        An empty tuple in the set means unconditionally active.
      dist: distribution name (``uniform``, ``randint``, ``categorical``...).
      params: dict of evaluated distribution arguments (floats / arrays),
        when they are literal; symbolic args keep the Apply node.
    """

    def __init__(self, label, node):
        self.label = label
        self.node = node
        self.conditions = set()
        self.dist = node.name
        self.params = {}
        self._extract_params()

    def _extract_params(self):
        names_by_dist = {
            "uniform": ("low", "high"),
            "loguniform": ("low", "high"),
            "quniform": ("low", "high", "q"),
            "qloguniform": ("low", "high", "q"),
            "normal": ("mu", "sigma"),
            "qnormal": ("mu", "sigma", "q"),
            "lognormal": ("mu", "sigma"),
            "qlognormal": ("mu", "sigma", "q"),
            "randint": ("low", "high"),
            "categorical": ("p",),
            "randint_via_categorical": ("p",),
        }
        arg_names = names_by_dist.get(self.dist)
        if arg_names is None:
            raise InvalidAnnotatedParameter(
                f"hp node {self.label!r} wraps unsupported distribution "
                f"{self.dist!r}"
            )
        for i, a in enumerate(self.node.pos_args):
            if i < len(arg_names):
                self.params[arg_names[i]] = _const_value(a)
        for k, a in self.node.named_args:
            if k in ("rng", "size"):
                continue
            self.params[k] = _const_value(a)
        # normalize randint(upper) -> low=0, high=upper
        if self.dist == "randint" and "high" not in self.params:
            self.params["high"] = self.params.pop("low")
            self.params["low"] = 0

    @property
    def unconditional(self):
        return () in self.conditions or not self.conditions

    def __repr__(self):
        return (
            f"ParamInfo({self.label!r}, {self.dist}, {self.params}, "
            f"conditions={sorted(map(repr, self.conditions))})"
        )


def _hp_label_and_dist(hparam_node):
    label_node = hparam_node.pos_args[0]
    if not isinstance(label_node, Literal):
        raise InvalidAnnotatedParameter("hyperopt_param label must be a literal")
    return label_node.obj, hparam_node.pos_args[1]


def expr_to_config(expr, conditions=(), hps=None):
    """Extract {label: ParamInfo} from an hp-annotated space graph.

    Walks the graph tracking ``switch`` branches so each hyperparameter
    records the conjunction of choice outcomes under which it is active.
    Raises :class:`DuplicateLabel` if a label appears twice with different
    distributions (same-structure re-use merges conditions, matching
    reference behavior).
    """
    expr = as_apply(expr)
    if hps is None:
        hps = {}
    _walk(expr, tuple(conditions), hps, set())
    return hps


def _record(hps, label, dist_node, conditions):
    if label in hps:
        prev = hps[label]
        if expr_signature(prev.node) != expr_signature(dist_node):
            raise DuplicateLabel(
                f"label {label!r} used for two different distributions"
            )
        prev.conditions.add(conditions)
    else:
        info = ParamInfo(label, dist_node)
        info.conditions.add(conditions)
        hps[label] = info


def _walk(node, conditions, hps, seen):
    # NOTE: (node, conditions) pairs must be revisited when the same subtree
    # is reachable under different conditions -> key includes conditions.
    key = (id(node), conditions)
    if key in seen:
        return
    seen.add(key)

    if isinstance(node, Literal):
        return

    if node.name == "switch":
        idx_node = node.pos_args[0]
        if idx_node.name == "hyperopt_param":
            label, dist_node = _hp_label_and_dist(idx_node)
            validate_label(label)
            _record(hps, label, dist_node, conditions)
            _walk(dist_node, conditions, hps, seen)
            for i, option in enumerate(node.pos_args[1:]):
                _walk(option, conditions + (EQ(label, i),), hps, seen)
            return
        # unlabeled switch: all branches share current conditions
        for a in node.inputs():
            _walk(a, conditions, hps, seen)
        return

    if node.name == "hyperopt_param":
        label, dist_node = _hp_label_and_dist(node)
        validate_label(label)
        _record(hps, label, dist_node, conditions)
        _walk(dist_node, conditions, hps, seen)
        return

    for a in node.inputs():
        _walk(a, conditions, hps, seen)
