"""Reporters: the text form for humans at a terminal, JSON for tooling
(the bench stamps ``lint_findings_total`` / ``lint_baseline_size`` from
the same structure)."""

from __future__ import annotations

import json

from .rules import RULES

__all__ = [
    "format_text", "format_json", "result_summary",
    "wire_summary", "format_wire_text", "format_wire_json",
]


def result_summary(result):
    return {
        "total": len(result.findings),
        "files": result.n_files,
        "pragma_suppressed": result.n_suppressed,
        "baseline_matched": result.n_baseline_matched,
        "baseline_size": result.baseline_size,
    }


def format_text(result):
    lines = []
    for f in result.findings:
        rule = RULES.get(f.rule)
        name = f" ({rule.name})" if rule else ""
        lines.append(f"{f.path}:{f.line}:{f.col + 1}: {f.rule}{name} {f.message}")
        if f.source_line.strip():
            lines.append(f"    {f.source_line.strip()}")
    s = result_summary(result)
    lines.append(
        f"graftlint: {s['total']} finding(s) in {s['files']} file(s) "
        f"({s['baseline_matched']} baselined, "
        f"{s['pragma_suppressed']} suppressed)"
    )
    return "\n".join(lines)


def format_json(result):
    return json.dumps(
        {
            "summary": result_summary(result),
            "findings": [f.to_dict() for f in result.findings],
        },
        indent=2,
        sort_keys=True,
    )


def ir_summary(result):
    """Summary block of an :class:`~.ir.IRResult` (the bench stamps
    ``ir_programs_checked`` / ``ir_contract_drift`` from this)."""
    return {
        "total": len(result.findings),
        "programs_checked": result.programs_checked,
        "contract_drift": result.contract_drift,
        "contracts": result.contracts_path,
        "updated": result.updated,
    }


def format_ir_text(result):
    lines = []
    for f in result.findings:
        rule = RULES.get(f.rule)
        name = f" ({rule.name})" if rule else ""
        lines.append(f"{f.path}:{f.line}: {f.rule}{name} {f.message}")
    s = ir_summary(result)
    lines.append(
        f"graftir: {s['total']} finding(s) across "
        f"{s['programs_checked']} program(s), "
        f"{s['contract_drift']} with contract drift"
        + (" [contracts updated]" if result.updated else "")
    )
    return "\n".join(lines)


def format_ir_json(result):
    return json.dumps(
        {
            "summary": ir_summary(result),
            "findings": [f.to_dict() for f in result.findings],
        },
        indent=2,
        sort_keys=True,
    )


def wire_summary(result):
    """Summary block of a :class:`~.wire.WireResult` (the bench stamps
    ``wire_ops_checked`` / ``wire_contract_drift`` /
    ``crash_points_armed_frac`` from this)."""
    return {
        "total": len(result.findings),
        "ops_checked": result.ops_checked,
        "contract_drift": result.contract_drift,
        "crash_points_total": result.crash_points_total,
        "crash_points_armed": result.crash_points_armed,
        "errors_checked": result.errors_checked,
        "pragma_suppressed": result.n_suppressed,
        "baseline_matched": result.n_baseline_matched,
        "baseline_size": result.baseline_size,
        "contracts": result.contracts_path,
        "updated": result.updated,
    }


def format_wire_text(result):
    lines = []
    for f in result.findings:
        rule = RULES.get(f.rule)
        name = f" ({rule.name})" if rule else ""
        lines.append(f"{f.path}:{f.line}: {f.rule}{name} {f.message}")
    s = wire_summary(result)
    lines.append(
        f"graftwire: {s['total']} finding(s) across "
        f"{s['ops_checked']} op(s), "
        f"{s['contract_drift']} with contract drift, "
        f"{s['crash_points_armed']}/{s['crash_points_total']} crash "
        f"point(s) armed "
        f"({s['baseline_matched']} baselined, "
        f"{s['pragma_suppressed']} suppressed)"
        + (" [contracts updated]" if result.updated else "")
    )
    return "\n".join(lines)


def format_wire_json(result):
    return json.dumps(
        {
            "summary": wire_summary(result),
            "findings": [f.to_dict() for f in result.findings],
        },
        indent=2,
        sort_keys=True,
    )
