"""graftrace: the GL5xx static concurrency pack (``hyperopt-tpu-lint
--trace``).

The serve/distributed stacks are threaded -- the continuous-batching
scheduler, the TCP front's handler threads, watchdog and heartbeat
workers, the ThreadTrials/SparkTrials dispatchers -- and the two
existing static tiers see none of it: graftlint checks single-threaded
AST invariants, graftir checks traced programs.  This pack proves LOCK
DISCIPLINE with zero test execution, the same posture graftir takes
for program contracts.

The model, per class (single file, stdlib ``ast`` only):

1. **Lock discovery** -- ``self.<attr> = threading.Lock()/RLock()``
   and ``threading.Condition(...)``; a ``Condition(self._lock)`` is an
   ALIAS of its lock (acquiring either acquires the same mutex), so
   held-sets are tracked in canonical lock names.
2. **Held-set analysis** -- every statement's lexically held locks
   (``with self._lock:`` regions), then an inter-procedural fixpoint
   over the class's self-call graph: a private helper called only from
   guarded contexts inherits the intersection of its callers' held
   sets, while PUBLIC methods, dunders, and THREAD-ENTRY TARGETS
   (``threading.Thread(target=self._loop)`` / ``executor.submit`` /
   ``functools.partial(self._method, ...)`` -- resolved by the engine,
   :meth:`~.engine.FileContext._resolve_thread_targets`) are roots
   that enter with nothing held.
3. **Lock-domain inference** -- an attribute is guarded by lock L when
   it is WRITTEN under L somewhere and the strict majority (and at
   least two) of its accesses outside ``__init__`` hold L.

Nested function/lambda bodies are skipped (their execution context is
unknown -- a closure may run on any thread at any time); ``__init__``
is exempt from GL501 (pre-publication writes race nothing; GL506
covers the start-before-assigned hazard).  Heuristic by design, like
every graftlint rule: each checker's true-positive and near-miss
behavior is pinned by a fixture pair in ``tests/lint_fixtures/``, and
the runtime half -- the lockdep sanitizer (:mod:`.lockdep`) armed in
the serve suites -- catches the orders the AST cannot see.

Suppression is the standard pragma (``# graftlint: disable=GL503
reason``), and findings ride the same baseline machinery; the
committed GL5xx baseline is zero.
"""

from __future__ import annotations

import ast

from .engine import JIT_WRAPPERS, dotted_name, terminal_name, walk_scope

__all__ = ["TRACE_CHECKERS"]

TRACE_CHECKERS = []


def register(rule_id):
    def deco(fn):
        TRACE_CHECKERS.append((rule_id, fn))
        return fn

    return deco


_METHOD_NODES = (ast.FunctionDef, ast.AsyncFunctionDef)
_NESTED_NODES = _METHOD_NODES + (ast.Lambda,)

#: factory terminals that make a self attribute a lock
_LOCK_FACTORIES = frozenset({"Lock", "RLock"})

#: container-mutating method names: ``self.x.append(...)`` is a WRITE
#: to the shared attribute for lock-domain inference, even though the
#: attribute node itself is a Load
_MUTATORS = frozenset({
    "append", "appendleft", "extend", "insert", "pop", "popleft",
    "remove", "clear", "add", "discard", "update", "setdefault", "sort",
})

#: blocking-call terminals for GL503 (socket ops, durability barriers)
_SOCKET_BLOCKERS = frozenset({"accept", "connect", "recv", "recv_into",
                              "sendall"})

#: durable-state mutators (the WAL/snapshot protocol surface) -- both
#: GL503 (blocking fsync-class work under a lock) and GL507 (daemon
#: threads tearing them) key off this set
_DURABLE_CALLS = frozenset({
    "durable_pickle", "save_trials", "log_tell", "log_open",
    "log_served", "log_ask", "snapshot", "maybe_snapshot",
})


def _is_self_attr(node):
    return (
        isinstance(node, ast.Attribute)
        and isinstance(node.value, ast.Name)
        and node.value.id == "self"
    )


def _is_mutation(ctx, node):
    """``node`` (an Attribute ``self.X``) is a write: a Store/Del, a
    subscript-store through it, or a mutating method call on it."""
    if isinstance(node.ctx, (ast.Store, ast.Del)):
        return True
    p = ctx.parents.get(node)
    if (
        isinstance(p, ast.Subscript)
        and p.value is node
        and isinstance(p.ctx, (ast.Store, ast.Del))
    ):
        return True
    if isinstance(p, ast.Attribute) and p.value is node and (
        p.attr in _MUTATORS
    ):
        pp = ctx.parents.get(p)
        if isinstance(pp, ast.Call) and pp.func is p:
            return True
    return False


class _MethodScan:
    """One method's concurrency-relevant events, with lexical held-sets
    (canonical lock names) attached to each."""

    __slots__ = ("accesses", "calls", "acquires", "ext_calls", "waits")

    def __init__(self):
        self.accesses = []   # (attr, node, is_write, held)
        self.calls = []      # (method_name, node, held) -- self.m(...)
        self.acquires = []   # (lock_attr, with_node, held_before)
        self.ext_calls = []  # (call_node, held) -- every call
        self.waits = []      # (call_node, cond_attr, held)


class _ClassModel:
    """Lock discovery + held-set analysis for one ClassDef."""

    def __init__(self, ctx, cls):
        self.ctx = ctx
        self.cls = cls
        self.methods = {
            n.name: n for n in cls.body if isinstance(n, _METHOD_NODES)
        }
        self.locks = {}          # attr -> "Lock" | "RLock" | "Condition"
        self.cond_of = {}        # condition attr -> aliased lock attr
        self.dispatch_attrs = set()  # self.X = jit(...)/build_*_fn(...)
        self._collect_attrs()
        self.scans = {}
        self.entry = {}
        if self.locks:
            for name, m in self.methods.items():
                self.scans[name] = self._scan(m)
            self._solve_entry_held()

    # -- discovery ---------------------------------------------------------

    def _collect_attrs(self):
        for m in self.methods.values():
            for node in walk_scope(m):
                if not (isinstance(node, ast.Assign)
                        and isinstance(node.value, ast.Call)):
                    continue
                t = terminal_name(node.value.func)
                for tgt in node.targets:
                    if not _is_self_attr(tgt):
                        continue
                    if t in _LOCK_FACTORIES:
                        self.locks[tgt.attr] = t
                    elif t == "Condition":
                        self.locks[tgt.attr] = "Condition"
                        args = node.value.args
                        if args and _is_self_attr(args[0]):
                            self.cond_of[tgt.attr] = args[0].attr
                    elif t is not None and (
                        t in JIT_WRAPPERS
                        or (t.startswith("build_") and t.endswith("_fn"))
                    ):
                        self.dispatch_attrs.add(tgt.attr)

    def canon(self, attr):
        """Condition attrs alias the lock they were built over."""
        return self.cond_of.get(attr, attr)

    @property
    def lock_names(self):
        return {self.canon(a) for a in self.locks}

    def _lock_attr_of(self, expr):
        if _is_self_attr(expr) and expr.attr in self.locks:
            return self.canon(expr.attr)
        return None

    # -- per-method scan ---------------------------------------------------

    def _scan(self, method):
        sc = _MethodScan()

        def visit(node, held):
            if isinstance(node, _NESTED_NODES):
                return  # nested scope: execution context unknown
            if isinstance(node, ast.With):
                inner = held
                for item in node.items:
                    lock = self._lock_attr_of(item.context_expr)
                    if lock is not None:
                        sc.acquires.append((lock, node, inner))
                        inner = inner | {lock}
                    else:
                        visit(item.context_expr, held)
                    if item.optional_vars is not None:
                        visit(item.optional_vars, held)
                for st in node.body:
                    visit(st, inner)
                return
            if _is_self_attr(node):
                sc.accesses.append((
                    node.attr, node, _is_mutation(self.ctx, node), held,
                ))
            if isinstance(node, ast.Call):
                sc.ext_calls.append((node, held))
                f = node.func
                if (
                    isinstance(f, ast.Attribute)
                    and _is_self_attr(f)
                    and f.attr in self.methods
                ):
                    sc.calls.append((f.attr, node, held))
                if (
                    isinstance(f, ast.Attribute)
                    and f.attr == "wait"
                    and _is_self_attr(f.value)
                    and self.locks.get(f.value.attr) == "Condition"
                ):
                    sc.waits.append((node, f.value.attr, held))
            for child in ast.iter_child_nodes(node):
                visit(child, held)

        for st in method.body:
            visit(st, frozenset())
        return sc

    # -- inter-procedural held-at-entry fixpoint ---------------------------

    def _solve_entry_held(self):
        """entry[m] = locks provably held whenever m runs: the
        intersection over its in-class call sites of (lexical held at
        the site | entry of the caller).  Public methods, dunders, and
        thread-entry targets are roots (entry = nothing held); so are
        private methods with no in-class call site (unknown callers)."""
        called = set()
        for sc in self.scans.values():
            for name, _node, _held in sc.calls:
                called.add(name)
        roots = set()
        for name, m in self.methods.items():
            is_private = name.startswith("_") and not name.startswith("__")
            if not is_private or m in self.ctx.thread_targets or (
                name not in called
            ):
                roots.add(name)
        TOP = frozenset(self.lock_names)
        self.entry = {
            name: (frozenset() if name in roots else TOP)
            for name in self.methods
        }
        for _ in range(len(self.methods) + 2):
            changed = False
            for caller, sc in self.scans.items():
                base = self.entry[caller]
                for callee, _node, held in sc.calls:
                    eff = held | base
                    cur = self.entry[callee]
                    new = cur & eff
                    if callee in roots:
                        new = frozenset()
                    if new != cur:
                        self.entry[callee] = new
                        changed = True
            if not changed:
                break

    def held(self, method_name, lexical):
        return lexical | self.entry[method_name]


def _models(ctx):
    """The file's lock-holding class models (memoized on the ctx)."""
    models = getattr(ctx, "_trace_models", None)
    if models is None:
        models = [
            _ClassModel(ctx, n)
            for n in ast.walk(ctx.tree)
            if isinstance(n, ast.ClassDef)
        ]
        ctx._trace_models = models
    return [m for m in models if m.locks]


# ---------------------------------------------------------------------------
# GL501 -- unguarded shared-attribute access
# ---------------------------------------------------------------------------


@register("GL501")
def check_unguarded_shared_attr(ctx):
    for model in _models(ctx):
        skip = (
            set(model.locks) | set(model.methods) | model.dispatch_attrs
        )
        per_attr = {}
        for name in model.methods:
            if name == "__init__":
                continue
            for attr, node, is_write, held in model.scans[name].accesses:
                if attr in skip:
                    continue
                eff = model.held(name, held)
                per_attr.setdefault(attr, []).append(
                    (name, node, is_write, eff)
                )
        for attr in sorted(per_attr):
            accs = per_attr[attr]
            for lock in sorted(model.lock_names):
                writes_under = any(
                    w and lock in eff for (_n, _nd, w, eff) in accs
                )
                if not writes_under:
                    continue
                n_under = sum(1 for (*_x, eff) in accs if lock in eff)
                n_out = len(accs) - n_under
                if n_under < 2 or n_under <= n_out:
                    continue
                for mname, node, is_write, eff in accs:
                    if lock not in eff:
                        verb = "mutated" if is_write else "read"
                        yield ctx.finding(
                            "GL501", node,
                            f"self.{attr} is guarded by self.{lock} "
                            "(written under it, and the majority of its "
                            f"accesses hold it) but is {verb} lock-free "
                            f"in {model.cls.name}.{mname} -- a data "
                            "race once any thread entry reaches here",
                        )
                break  # one inferred guard per attribute


# ---------------------------------------------------------------------------
# GL502 -- lock-order inversion
# ---------------------------------------------------------------------------


@register("GL502")
def check_lock_order_inversion(ctx):
    for model in _models(ctx):
        if len(model.lock_names) < 2:
            continue
        edges = {}  # (held_lock, acquired_lock) -> (method, with_node)
        for name in model.methods:
            for lock, node, held in model.scans[name].acquires:
                for h in model.held(name, held):
                    if h != lock and (h, lock) not in edges:
                        edges[(h, lock)] = (name, node)
        adj = {}
        for (a, b) in edges:
            adj.setdefault(a, set()).add(b)

        def reaches(src, dst):
            seen, stack = set(), [src]
            while stack:
                cur = stack.pop()
                if cur == dst:
                    return True
                if cur in seen:
                    continue
                seen.add(cur)
                stack.extend(adj.get(cur, ()))
            return False

        flagged = sorted(
            ((name, node, a, b)
             for (a, b), (name, node) in edges.items()
             if reaches(b, a)),
            key=lambda t: (t[1].lineno, t[1].col_offset),
        )
        for name, node, a, b in flagged:
            yield ctx.finding(
                "GL502", node,
                f"{model.cls.name}.{name} acquires self.{b} while "
                f"holding self.{a}, but self.{a} is also acquired "
                f"under self.{b} elsewhere in the class -- a lock-order "
                "cycle (ABBA deadlock once two threads interleave)",
            )


# ---------------------------------------------------------------------------
# GL503 -- blocking call while holding a lock
# ---------------------------------------------------------------------------


def _blocking_label(model, call):
    """A human label when ``call`` is a blocking primitive, else None."""
    func = call.func
    dn = dotted_name(func)
    if dn is not None:
        parts = dn.split(".")
        if parts[-1] == "sleep" and parts[0] in ("time", "_time"):
            return f"{dn}()"
    t = terminal_name(func)
    if t is None:
        return None
    if t in ("result", "join"):
        # thread-join / future-result arg shapes only: no positional
        # args, or a single numeric timeout (str.join / os.path.join
        # always pass non-numeric positionals)
        args_ok = not call.args or (
            len(call.args) == 1
            and isinstance(call.args[0], ast.Constant)
            and isinstance(call.args[0].value, (int, float))
        )
        if args_ok:
            owner = "Future.result" if t == "result" else "Thread.join"
            return f"{owner}()"
        return None
    if t in _SOCKET_BLOCKERS:
        return f"socket .{t}()"
    if t == "fsync":
        return "fsync()"
    if t == "block_until_ready":
        return "block_until_ready()"
    if t in _DURABLE_CALLS:
        return f"durable write {t}()"
    if (
        isinstance(func, ast.Attribute)
        and _is_self_attr(func)
        and func.attr in model.dispatch_attrs
    ):
        return f"jitted dispatch self.{func.attr}()"
    return None


@register("GL503")
def check_blocking_call_under_lock(ctx):
    for model in _models(ctx):
        for name in model.methods:
            for node, held in model.scans[name].ext_calls:
                eff = model.held(name, held)
                if not eff:
                    continue
                label = _blocking_label(model, node)
                if label is None:
                    continue
                locks = ", ".join(f"self.{x}" for x in sorted(eff))
                yield ctx.finding(
                    "GL503", node,
                    f"{label} while holding {locks} "
                    f"({model.cls.name}.{name}): every thread "
                    "contending on the lock stalls for the call's full "
                    "latency -- move it outside the guarded region",
                )


# ---------------------------------------------------------------------------
# GL504 -- Condition.wait without an enclosing predicate while-loop
# ---------------------------------------------------------------------------


@register("GL504")
def check_wait_without_predicate_loop(ctx):
    for model in _models(ctx):
        for name, method in model.methods.items():
            for node, cond_attr, _held in model.scans[name].waits:
                in_while = False
                for anc in ctx.ancestors(node):
                    if isinstance(anc, ast.While):
                        in_while = True
                        break
                    if anc is method:
                        break
                if not in_while:
                    yield ctx.finding(
                        "GL504", node,
                        f"self.{cond_attr}.wait() outside a while loop "
                        f"({model.cls.name}.{name}): spurious wakeups "
                        "and stolen predicates make if-then-wait lose "
                        "the signal -- re-check the predicate in a "
                        "while",
                    )


# ---------------------------------------------------------------------------
# GL505 -- Future resolved while holding a lock
# ---------------------------------------------------------------------------


@register("GL505")
def check_future_resolved_under_lock(ctx):
    for model in _models(ctx):
        for name in model.methods:
            for node, held in model.scans[name].ext_calls:
                eff = model.held(name, held)
                if not eff:
                    continue
                t = terminal_name(node.func)
                if t not in ("set_result", "set_exception"):
                    continue
                locks = ", ".join(f"self.{x}" for x in sorted(eff))
                yield ctx.finding(
                    "GL505", node,
                    f".{t}() while holding {locks} "
                    f"({model.cls.name}.{name}): done-callbacks run "
                    "inline in the resolving thread and can re-enter "
                    "the lock (callback-under-lock deadlock); collect "
                    "futures under the lock, resolve after release",
                )


# ---------------------------------------------------------------------------
# GL506 -- thread started in __init__ before attributes are assigned
# ---------------------------------------------------------------------------


@register("GL506")
def check_thread_started_in_init(ctx):
    for cls in ast.walk(ctx.tree):
        if not isinstance(cls, ast.ClassDef):
            continue
        init = next(
            (n for n in cls.body
             if isinstance(n, _METHOD_NODES) and n.name == "__init__"),
            None,
        )
        if init is None:
            continue
        own = list(walk_scope(init))
        thread_names, thread_attrs = set(), set()
        for n in own:
            if not (isinstance(n, ast.Assign)
                    and isinstance(n.value, ast.Call)
                    and terminal_name(n.value.func) == "Thread"):
                continue
            for tgt in n.targets:
                if isinstance(tgt, ast.Name):
                    thread_names.add(tgt.id)
                elif _is_self_attr(tgt):
                    thread_attrs.add(tgt.attr)
        starts = []
        for n in own:
            if not (isinstance(n, ast.Call)
                    and isinstance(n.func, ast.Attribute)
                    and n.func.attr == "start"):
                continue
            recv = n.func.value
            if (
                (isinstance(recv, ast.Name) and recv.id in thread_names)
                or (_is_self_attr(recv) and recv.attr in thread_attrs)
                or (isinstance(recv, ast.Call)
                    and terminal_name(recv.func) == "Thread")
            ):
                starts.append(n)
        if not starts:
            continue
        attr_assign_lines = [
            n.lineno
            for n in own
            if isinstance(n, (ast.Assign, ast.AugAssign, ast.AnnAssign))
            for tgt in (
                n.targets if isinstance(n, ast.Assign) else [n.target]
            )
            if _is_self_attr(tgt) and tgt.attr not in thread_attrs
        ]
        for node in starts:
            later = [l for l in attr_assign_lines if l > node.lineno]
            if later:
                yield ctx.finding(
                    "GL506", node,
                    f"thread started in {cls.name}.__init__ before the "
                    f"instance attribute assignment(s) at line(s) "
                    f"{sorted(later)}: the target thread can observe a "
                    "partially constructed object -- assign everything "
                    "first, start last (or start() explicitly)",
                )


# ---------------------------------------------------------------------------
# GL507 -- daemon thread mutating WAL/checkpoint durable state
# ---------------------------------------------------------------------------


@register("GL507")
def check_daemon_durable_mutation(ctx):
    seen_nodes = set()
    for fn, info in sorted(
        ctx.thread_targets.items(), key=lambda kv: kv[0].lineno
    ):
        if not info.get("daemon"):
            continue
        # the daemon entry plus its transitive same-class self-callees
        cls = None
        for a in ctx.ancestors(fn):
            if isinstance(a, ast.ClassDef):
                cls = a
                break
        methods = (
            {n.name: n for n in cls.body if isinstance(n, _METHOD_NODES)}
            if cls is not None else {}
        )
        scopes, queue, visited = [], [fn], set()
        while queue:
            cur = queue.pop()
            if cur in visited:
                continue
            visited.add(cur)
            scopes.append(cur)
            for n in walk_scope(cur):
                if (
                    isinstance(n, ast.Call)
                    and isinstance(n.func, ast.Attribute)
                    and _is_self_attr(n.func)
                    and n.func.attr in methods
                ):
                    queue.append(methods[n.func.attr])
        entry = getattr(fn, "name", "<lambda>")
        for scope in scopes:
            for n in walk_scope(scope):
                if not isinstance(n, ast.Call):
                    continue
                t = terminal_name(n.func)
                if t in _DURABLE_CALLS and n not in seen_nodes:
                    seen_nodes.add(n)
                    yield ctx.finding(
                        "GL507", n,
                        f"durable write {t}() is reachable from daemon "
                        f"thread entry {entry!r}: a daemon thread dies "
                        "mid-write at interpreter exit, tearing "
                        "WAL/checkpoint state -- use a joined worker, "
                        "or suppress with the recovery argument",
                    )
