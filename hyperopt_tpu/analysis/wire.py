"""graftwire: static wire-protocol & fault-surface contract checker
(the GL6xx pack, ``hyperopt-tpu-lint --wire``).

PRs 17-18 grew a three-front wire protocol (service + router TCP
fronts, ``RemoteStudy``/``FrameConn`` clients) and a fault surface of
crash-point registries plus a name-keyed typed-error reply mapping.
Those seams are STRING-matched at runtime -- ``op == "tell"``,
``error_type`` names an exception class, ``fs.crashpoint("name")`` --
so nothing in the type system stops an op added to ``_handle_request``
without a client counterpart, a reply-field rename, or a crash point no
test ever arms from drifting silently.  graftwire closes that gap the
way graftir closed the program-shape gap: extract every surface
statically (stdlib ``ast`` only, zero test execution), cross-reference
them, and pin the reply shapes in a committed manifest
(``wire_contracts.json``).

Extracted surfaces
------------------
* **server ops**: every ``op == "x"`` / ``op in (...)`` dispatch arm of
  ``service._handle_request`` (the "service" front) and
  ``RouterServer.handle_request`` (the "router" front), plus the
  ``hello`` proto negotiation in each front's connection handler; per
  op, the union of constant keys over the branch's ``return {...}``
  dict literals (one level of local-helper resolution, ``"*"`` for
  dynamic parts such as ``**service.health()``).
* **client ops**: every ``{"op": <const>}`` dict literal and
  ``call(op="<const>")`` keyword send in ``client.py``
  (``RemoteStudy``), ``router.py`` backend call-sites, ``frames.py``
  (the ``hello`` dial), and ``obs/cli.py``; the same shapes under
  ``tests/`` count as caller evidence.
* **typed errors**: ``exceptions.py`` classes transitively subclassing
  ``ServeError`` vs the client reply seam (``_REPLY_ERRORS`` keys and
  by-name special cases in ``client.py``).
* **crash points**: every ``*_CRASH_POINTS`` registry tuple in
  ``faults.py``/``netfaults.py`` vs arming evidence under ``tests/`` --
  a point armed by string literal, or a registry iterated by name in a
  test file that calls ``arm(``.

Rules
-----
* **GL601** a client-sent op no front handles, a handled op with no
  client/test caller (dead wire surface), or a GLOBAL op one front
  handles that the other refuses untyped (the router forwards
  study-keyed ops generically, so only no-name ops can be asymmetric).
* **GL602** reply-field drift per op against the committed
  ``wire_contracts.json`` -- field-level diffs like GL406, accepted
  only via ``hyperopt-tpu-lint --wire --update-contracts``.  The typed
  error-reply shape (``_serve_error_reply``) is pinned the same way.
* **GL603** a ``ServeError`` subclass unmapped at the client reply
  seam: it crosses the wire as an ``error_type`` name and surfaces as a
  generic ``RuntimeError`` instead of the typed class.
* **GL604** a registered crash point never armed by any test -- dead
  fault surface (the registries exist so chaos suites iterate them).
* **GL605** a durable write seam (``fsync`` / ``rename`` / WAL
  ``append`` under ``serve/`` or ``distributed/``) whose enclosing
  function has no ``crashpoint(`` call in scope: a kill inside that
  window is untestable.  The fault-injection seam itself
  (``faults.py`` / ``netfaults.py``) is exempt -- it IS the
  passthrough.
* **GL606** a ``retry_after``-carrying reply built from a bare numeric
  without the ``RETRY_AFTER_CAP``/jitter path -- a hand-built hint can
  exceed the cap the backoff loops rely on.

Findings ride the standard pragma machinery (``# graftlint:
disable=GL60x reason`` on the line or an enclosing def/class header)
and the committed baseline; everything is cwd-independent (package
files and the default manifest resolve next to the package, like
graftir).
"""

from __future__ import annotations

import ast
import dataclasses
import json
import os

from .engine import (
    FileContext,
    Finding,
    dotted_name,
    parse_pragmas,
    terminal_name,
    walk_scope,
)
from .ir import repo_root

__all__ = [
    "WireResult",
    "analyze",
    "check_wire",
    "default_contracts_path",
    "load_contracts",
    "write_contracts",
    "DEFAULT_CONTRACTS",
]

DEFAULT_CONTRACTS = "wire_contracts.json"
CONTRACTS_VERSION = 1

#: the package files each extraction surface reads (repo-relative,
#: posix).  A role lists FILES, not globs, so a new front must be
#: registered here deliberately -- the fixture corpus drives the same
#: roles with synthetic sources.
SERVER_FILES = (
    "hyperopt_tpu/serve/service.py",
    "hyperopt_tpu/serve/router.py",
)
CLIENT_FILES = (
    "hyperopt_tpu/client.py",
    "hyperopt_tpu/serve/router.py",
    "hyperopt_tpu/serve/frames.py",
    "hyperopt_tpu/obs/cli.py",
)
REPLY_SEAM_FILES = ("hyperopt_tpu/client.py",)
EXCEPTION_FILES = ("hyperopt_tpu/exceptions.py",)
FAULT_FILES = (
    "hyperopt_tpu/distributed/faults.py",
    "hyperopt_tpu/distributed/netfaults.py",
)
#: GL605/GL606 scan scope: the crash-consistency surface.  faults.py /
#: netfaults.py are the injection seam itself (their rename/fsync ARE
#: the passthrough primitives every crashpoint brackets).
DURABLE_DIRS = ("hyperopt_tpu/serve", "hyperopt_tpu/distributed")
DURABLE_EXCLUDE = ("faults.py", "netfaults.py")


@dataclasses.dataclass
class WireResult:
    """What one ``--wire`` run produced (the reporter's input)."""

    findings: list
    ops_checked: int = 0
    contract_drift: int = 0
    crash_points_total: int = 0
    crash_points_armed: int = 0
    errors_checked: int = 0
    n_files: int = 0
    n_suppressed: int = 0
    n_baseline_matched: int = 0
    baseline_size: int = 0
    contracts_path: str = ""
    updated: bool = False

    @property
    def clean(self):
        return not self.findings


def default_contracts_path(root=None):
    return os.path.join(root or repo_root(), DEFAULT_CONTRACTS)


def load_contracts(path):
    with open(path, encoding="utf-8") as f:
        payload = json.load(f)
    if payload.get("version") != CONTRACTS_VERSION:
        raise ValueError(
            f"wire contracts manifest {path!r} has version "
            f"{payload.get('version')!r}; this checker reads version "
            f"{CONTRACTS_VERSION}"
        )
    return payload


def write_contracts(path, fronts, error_reply):
    payload = {
        "version": CONTRACTS_VERSION,
        "fronts": {
            front: {op: sorted(fields) for op, fields in ops.items()}
            for front, ops in fronts.items()
        },
        "error_reply": sorted(error_reply),
    }
    with open(path, "w", encoding="utf-8") as f:
        json.dump(payload, f, indent=2, sort_keys=True)
        f.write("\n")
    return path


# ---------------------------------------------------------------------------
# extraction helpers (pure ast -- shared by the real repo scan and the
# fixture corpus)
# ---------------------------------------------------------------------------


def _const_str(node):
    if isinstance(node, ast.Constant) and isinstance(node.value, str):
        return node.value
    return None


def _is_op_load(node):
    """``op`` (the dispatch local) or ``req.get("op")``."""
    if isinstance(node, ast.Name) and node.id == "op":
        return True
    return (
        isinstance(node, ast.Call)
        and terminal_name(node.func) == "get"
        and node.args
        and _const_str(node.args[0]) == "op"
    )


def _op_compare_values(test):
    """The constant op strings an ``if`` dispatch test matches, or []."""
    if not (isinstance(test, ast.Compare) and len(test.ops) == 1):
        return []
    if not _is_op_load(test.left):
        return []
    cmp = test.comparators[0]
    if isinstance(test.ops[0], ast.Eq):
        s = _const_str(cmp)
        return [s] if s is not None else []
    if isinstance(test.ops[0], ast.In) and isinstance(cmp, (ast.Tuple, ast.List)):
        out = [_const_str(e) for e in cmp.elts]
        return [s for s in out if s is not None]
    return []


def _dict_fields(d):
    fields = set()
    for k in d.keys:
        s = _const_str(k)
        fields.add(s if s is not None else "*")  # None key = ** unpack
    return fields


def _local_helper(ctx, fn, call):
    """Resolve ``return helper(...)`` / ``return self._helper(...)`` to
    the module-level def or same-class method, one level deep."""
    t = terminal_name(call.func)
    if t is None:
        return None
    for node in ctx.tree.body:
        if isinstance(node, ast.FunctionDef) and node.name == t:
            return node
    for anc in ctx.ancestors(fn):
        if isinstance(anc, ast.ClassDef):
            for m in anc.body:
                if isinstance(m, ast.FunctionDef) and m.name == t:
                    return m
    return None


def _return_fields(ctx, fn, scope, depth=0):
    """Union of reply fields over every ``return`` in ``scope``."""
    fields = set()
    for node in walk_scope(scope):
        if not isinstance(node, ast.Return) or node.value is None:
            continue
        v = node.value
        if isinstance(v, ast.Dict):
            fields |= _dict_fields(v)
        elif isinstance(v, ast.Call) and depth == 0:
            helper = _local_helper(ctx, fn, v)
            if helper is not None:
                fields |= _return_fields(ctx, helper, helper, depth=1)
            else:
                fields.add("*")
        else:
            fields.add("*")
    return fields


def _name_gate_line(fn):
    """Line of the ``name = req.get("study"/"name")`` prelude that
    splits GLOBAL ops from study-keyed ops, or None."""
    for node in walk_scope(fn):
        if not (isinstance(node, ast.Assign) and node.targets):
            continue
        vals = [node.value]
        if isinstance(node.value, ast.BoolOp):
            vals = node.value.values
        for v in vals:
            if (
                isinstance(v, ast.Call)
                and terminal_name(v.func) == "get"
                and v.args
                and _const_str(v.args[0]) in ("study", "name")
            ):
                return node.lineno
    return None


def _extract_fronts(ctxs):
    """``{front: {op: {"line", "path", "fields", "global", "ctx",
    "node"}}}`` from every handler function in ``ctxs``.

    A module-level ``_handle_request`` def is the "service" front; a
    ``handle_request`` method is the "router" front.  ``hello`` (proto
    negotiation, handled in the connection loop rather than the
    dispatch function) attaches to whichever front(s) live in the same
    file.
    """
    fronts = {}
    for ctx in ctxs:
        file_fronts = []
        for node in ast.walk(ctx.tree):
            if not isinstance(node, ast.FunctionDef):
                continue
            front = None
            if node.name == "_handle_request":
                front = "service"
            elif node.name == "handle_request" and ctx.enclosing_function(
                node
            ) is None and any(
                isinstance(a, ast.ClassDef) for a in ctx.ancestors(node)
            ):
                front = "router"
            if front is None:
                continue
            file_fronts.append(front)
            ops = fronts.setdefault(front, {})
            gate = _name_gate_line(node)
            for sub in walk_scope(node):
                if not isinstance(sub, ast.If):
                    continue
                fields = _return_fields(ctx, node, sub)
                if not fields:
                    # an op comparison that returns nothing is a retry/
                    # bookkeeping tweak inside a forward loop, not a
                    # dispatch arm
                    continue
                for op in _op_compare_values(sub.test):
                    info = ops.setdefault(op, {
                        "line": sub.lineno,
                        "path": ctx.posix_path,
                        "fields": set(),
                        "global": gate is None or sub.lineno < gate,
                        "ctx": ctx,
                        "node": sub,
                    })
                    info["fields"] |= fields
        if not file_fronts:
            continue
        # hello: `if req.get("op") == "hello":` in the connection loop;
        # reply fields come from dict assigns + subscript stores there
        for node in ast.walk(ctx.tree):
            if not isinstance(node, ast.If):
                continue
            if _op_compare_values(node.test) != ["hello"]:
                continue
            fields = set()
            for sub in ast.walk(node):
                if isinstance(sub, ast.Assign):
                    if isinstance(sub.value, ast.Dict):
                        fields |= _dict_fields(sub.value)
                    for tgt in sub.targets:
                        if isinstance(tgt, ast.Subscript):
                            s = _const_str(tgt.slice)
                            fields.add(s if s is not None else "*")
            for front in file_fronts:
                fronts.setdefault(front, {}).setdefault("hello", {
                    "line": node.lineno,
                    "path": ctx.posix_path,
                    "fields": fields,
                    "global": True,
                    "ctx": ctx,
                    "node": node,
                })
    return fronts


def _sent_ops(ctx):
    """Every constant op this file sends: ``{"op": "x"}`` dict literals
    and ``call(op="x")`` keyword sends (the test-harness idiom).
    Yields ``(op, node, has_name)`` where ``has_name`` records whether
    the send carries a ``name``/``study`` key -- the router forwards
    study-keyed requests generically, so named sends are never
    front-asymmetric."""
    for node in ast.walk(ctx.tree):
        if isinstance(node, ast.Dict):
            op, has_name = None, False
            for k, v in zip(node.keys, node.values):
                ks = _const_str(k)
                if ks == "op":
                    op = _const_str(v)
                elif ks in ("name", "study"):
                    has_name = True
            if op is not None:
                yield op, node, has_name
        elif isinstance(node, ast.Call):
            op, has_name = None, False
            for kw in node.keywords:
                if kw.arg == "op":
                    op = _const_str(kw.value)
                elif kw.arg in ("name", "study"):
                    has_name = True
            if op is not None:
                yield op, node, has_name


def _error_reply_fields(ctxs):
    """The ``_serve_error_reply`` shape: dict-literal keys plus
    ``reply[...] = ...`` stores.  Returns (fields, line, ctx) or
    None."""
    for ctx in ctxs:
        for node in ast.walk(ctx.tree):
            if not (
                isinstance(node, ast.FunctionDef)
                and node.name == "_serve_error_reply"
            ):
                continue
            fields = set()
            for sub in walk_scope(node):
                if isinstance(sub, ast.Dict):
                    fields |= _dict_fields(sub)
                elif isinstance(sub, ast.Assign):
                    for tgt in sub.targets:
                        if isinstance(tgt, ast.Subscript):
                            s = _const_str(tgt.slice)
                            fields.add(s if s is not None else "*")
            return fields, node.lineno, ctx
    return None


def _serve_error_subclasses(ctxs):
    """``{name: (line, ctx)}`` of classes transitively subclassing
    ServeError (the base itself excluded)."""
    bases, sites = {}, {}
    for ctx in ctxs:
        for node in ast.walk(ctx.tree):
            if isinstance(node, ast.ClassDef):
                bases[node.name] = [
                    terminal_name(b) or "" for b in node.bases
                ]
                sites[node.name] = (node.lineno, node, ctx)

    def descends(name, seen):
        if name == "ServeError":
            return True
        if name in seen:
            return False
        return any(
            descends(b, seen | {name}) for b in bases.get(name, ())
        )

    return {
        name: sites[name]
        for name in bases
        if name != "ServeError" and descends(name, set())
    }


def _crash_registries(ctxs):
    """``[(registry_name, [(point, line)], ctx)]`` from module-level
    ``*_CRASH_POINTS = ("...", ...)`` tuples (the concatenated
    ``ALL_CRASH_POINTS`` is not a registry)."""
    out = []
    for ctx in ctxs:
        for node in ctx.tree.body:
            if not (
                isinstance(node, ast.Assign)
                and len(node.targets) == 1
                and isinstance(node.targets[0], ast.Name)
            ):
                continue
            name = node.targets[0].id
            if (
                not name.endswith("CRASH_POINTS")
                or name == "ALL_CRASH_POINTS"
                or not isinstance(node.value, (ast.Tuple, ast.List))
            ):
                continue
            points = []
            for elt in node.value.elts:
                s = _const_str(elt)
                if s is not None:
                    points.append((s, elt.lineno, elt))
            out.append((name, points, ctx))
    return out


def _test_evidence(test_ctxs):
    """(sent_ops, string_constants, iterated_registries) across the
    test corpus.  A registry counts as iterated when its NAME appears
    in a file that also calls ``arm(`` -- the parametrize-over-the-
    tuple idiom the chaos suites use."""
    ops, strings, iterated = set(), set(), set()
    named = set()
    for ctx in test_ctxs:
        for op, _node, has_name in _sent_ops(ctx):
            ops.add(op)
            if has_name:
                named.add(op)
        names, has_arm = set(), False
        for node in ast.walk(ctx.tree):
            if isinstance(node, ast.Constant) and isinstance(node.value, str):
                strings.add(node.value)
            elif isinstance(node, ast.Name):
                names.add(node.id)
            elif isinstance(node, ast.ImportFrom):
                names.update(a.name for a in node.names)
            elif isinstance(node, ast.Call):
                if terminal_name(node.func) == "arm":
                    has_arm = True
        if has_arm:
            iterated.update(
                n for n in names if n.endswith("CRASH_POINTS")
            )
    return ops, named, strings, iterated


def _durable_sites(ctx):
    """``{fn_node: [(line, kind)]}`` of fsync/rename/WAL-append calls
    whose enclosing function lacks a ``crashpoint(`` call."""
    per_fn = {}
    for node in ast.walk(ctx.tree):
        if not isinstance(node, ast.Call):
            continue
        t = terminal_name(node.func)
        kind = None
        if t == "fsync":
            kind = "fsync"
        elif t == "rename":
            kind = "rename"
        elif t == "append":
            recv = dotted_name(node.func) or ""
            recv = recv.rsplit(".", 1)[0] if "." in recv else ""
            if "wal" in recv.lower():
                kind = "WAL append"
        if kind is None:
            continue
        fn = ctx.enclosing_function(node)
        if fn is None:
            continue
        per_fn.setdefault(fn, []).append((node.lineno, kind))
    out = {}
    for fn, sites in per_fn.items():
        bracketed = any(
            isinstance(n, ast.Call)
            and terminal_name(n.func) == "crashpoint"
            for n in walk_scope(fn)
        )
        if not bracketed:
            out[fn] = sorted(sites)
    return out


def _retry_after_values(ctx):
    """Every expression assigned to a reply's ``retry_after`` field."""
    for node in ast.walk(ctx.tree):
        if isinstance(node, ast.Dict):
            for k, v in zip(node.keys, node.values):
                if _const_str(k) == "retry_after":
                    yield v, node
        elif isinstance(node, ast.Assign):
            for tgt in node.targets:
                if (
                    isinstance(tgt, ast.Subscript)
                    and _const_str(tgt.slice) == "retry_after"
                ):
                    yield node.value, node


def _numeric_without_cap(expr):
    has_num = any(
        isinstance(n, ast.Constant)
        and isinstance(n.value, (int, float))
        and not isinstance(n.value, bool)
        for n in ast.walk(expr)
    )
    has_cap = any(
        terminal_name(n) == "RETRY_AFTER_CAP"
        for n in ast.walk(expr)
        if isinstance(n, (ast.Name, ast.Attribute))
    )
    return has_num and not has_cap


# ---------------------------------------------------------------------------
# the pack
# ---------------------------------------------------------------------------


def _parse(path, source, parsed):
    """FileContext for ``path`` (memoized per analyze call); a syntax
    error yields a GL002 finding instead of a crash."""
    if path in parsed:
        return parsed[path]
    try:
        tree = ast.parse(source)
    except SyntaxError as e:
        f = Finding(
            path=path, rule="GL002", line=e.lineno or 1,
            col=(e.offset or 1) - 1,
            message=f"file does not parse: {e.msg}",
            source_line=(e.text or "").rstrip("\n"),
        )
        object.__setattr__(f, "_scope_lines", [])
        parsed[path] = (None, [f])
        return parsed[path]
    parsed[path] = (FileContext(path, source, tree), [])
    return parsed[path]


def analyze(server=None, clients=None, reply_seam=None, exceptions=None,
            faults=None, durable=None, tests=None, contracts=None,
            update=False):
    """Run the GL6xx pack over explicit role -> {path: source} maps.

    This is the fixture-facing core: :func:`check_wire` feeds it the
    real repo files, the fixture corpus feeds it miniature synthetic
    universes, and the mutation kill-checks feed it the real sources
    with one seam textually broken -- all with ZERO test execution.

    Returns ``(findings, stats, fresh_contracts)`` where ``stats`` has
    ``ops_checked`` / ``contract_drift`` / ``crash_points_total`` /
    ``crash_points_armed`` / ``errors_checked`` / ``n_suppressed`` /
    ``n_files``, and ``fresh_contracts`` is the would-be-committed
    manifest payload.
    """
    server = server or {}
    clients = clients or {}
    reply_seam = reply_seam or {}
    exceptions = exceptions or {}
    faults = faults or {}
    durable = durable or {}
    tests = tests or {}

    parsed = {}
    findings = []

    def ctxs_of(role):
        out = []
        for path in sorted(role):
            ctx, errs = _parse(path, role[path], parsed)
            findings.extend(errs)
            if ctx is not None:
                out.append(ctx)
        return out

    server_ctxs = ctxs_of(server)
    client_ctxs = ctxs_of(clients)
    seam_ctxs = ctxs_of(reply_seam)
    exc_ctxs = ctxs_of(exceptions)
    fault_ctxs = ctxs_of(faults)
    durable_ctxs = ctxs_of(durable)
    test_ctxs = ctxs_of(tests)

    fronts = _extract_fronts(server_ctxs)
    test_ops, test_named, test_strings, iterated = _test_evidence(test_ctxs)

    # -- GL601: op-surface symmetry -------------------------------------
    handled = {
        op for ops in fronts.values() for op in ops
    }
    client_sends = []
    for ctx in client_ctxs:
        for op, node, has_name in _sent_ops(ctx):
            client_sends.append((op, node, has_name, ctx))
    for op, node, _has_name, ctx in client_sends:
        if op not in handled:
            findings.append(ctx.finding(
                "GL601", node,
                f"client sends op {op!r} but no front handles it "
                f"(service handles {sorted(fronts.get('service', {}))}, "
                f"router handles {sorted(fronts.get('router', {}))})",
            ))
    called = {op for op, _, _, _ in client_sends} | test_ops
    named_ops = {
        op for op, _, has_name, _ in client_sends if has_name
    } | test_named
    for front, ops in sorted(fronts.items()):
        for op, info in sorted(ops.items()):
            if op not in called:
                findings.append(info["ctx"].finding(
                    "GL601", info["node"],
                    f"op {op!r} on the {front} front has no client or "
                    "test caller -- dead wire surface or missing "
                    "coverage; call it from a client/test or delete "
                    "the handler arm",
                ))
    # front asymmetry: a no-study-name op only one front handles -- the
    # router forwards study-keyed sends generically (``named_ops``:
    # every observed send of the op carries a name), but a global op it
    # does not dispatch gets an untyped refusal
    if "service" in fronts and "router" in fronts:
        for op, info in sorted(fronts["service"].items()):
            if (
                info["global"]
                and op not in fronts["router"]
                and op not in named_ops
            ):
                findings.append(info["ctx"].finding(
                    "GL601", info["node"],
                    f"global op {op!r} is handled by the service front "
                    "but not by the router front: a fleet client gets "
                    "an untyped 'needs a study name' refusal -- handle "
                    "or broadcast it in RouterServer.handle_request",
                ))

    # -- GL602: reply contracts vs the committed manifest ---------------
    fresh_fronts = {
        front: {op: sorted(info["fields"]) for op, info in ops.items()}
        for front, ops in fronts.items()
    }
    err = _error_reply_fields(server_ctxs)
    fresh_error_reply = sorted(err[0]) if err else []
    fresh_contracts = {
        "version": CONTRACTS_VERSION,
        "fronts": fresh_fronts,
        "error_reply": fresh_error_reply,
    }

    drift_ops = set()
    if not update and contracts is not None:
        stored_fronts = contracts.get("fronts", {})
        for front, ops in sorted(fronts.items()):
            stored_ops = stored_fronts.get(front, {})
            for op, info in sorted(ops.items()):
                stored = stored_ops.get(op)
                fresh = sorted(info["fields"])
                if stored is None:
                    drift_ops.add((front, op))
                    findings.append(info["ctx"].finding(
                        "GL602", info["node"],
                        f"no committed reply contract for op {op!r} on "
                        f"the {front} front; pin it with "
                        "`hyperopt-tpu-lint --wire --update-contracts`",
                    ))
                elif sorted(stored) != fresh:
                    added = sorted(set(fresh) - set(stored))
                    removed = sorted(set(stored) - set(fresh))
                    parts = []
                    if removed:
                        parts.append(f"field(s) {removed} removed")
                    if added:
                        parts.append(f"field(s) {added} added")
                    drift_ops.add((front, op))
                    findings.append(info["ctx"].finding(
                        "GL602", info["node"],
                        f"reply contract drift for op {op!r} on the "
                        f"{front} front: {', '.join(parts)} (committed "
                        f"{sorted(stored)} != extracted {fresh}); "
                        "accept deliberate changes with "
                        "`hyperopt-tpu-lint --wire --update-contracts`",
                    ))
            # stale manifest rows: ops the front no longer dispatches
            for op in sorted(set(stored_ops) - set(ops)):
                drift_ops.add((front, op))
                f = Finding(
                    path=DEFAULT_CONTRACTS, rule="GL602", line=1, col=0,
                    message=f"manifest pins a reply contract for op "
                    f"{op!r} on the {front} front, which no longer "
                    "dispatches it; refresh with `hyperopt-tpu-lint "
                    "--wire --update-contracts`",
                )
                object.__setattr__(f, "_scope_lines", [])
                findings.append(f)
        stored_err = contracts.get("error_reply")
        if err is not None and stored_err is not None and (
            sorted(stored_err) != fresh_error_reply
        ):
            fields, line, ctx = err
            drift_ops.add(("service", "_serve_error_reply"))
            findings.append(ctx.finding(
                "GL602",
                ast.Pass(lineno=line, col_offset=0),
                "typed error-reply contract drift: committed "
                f"{sorted(stored_err)} != extracted {fresh_error_reply}"
                "; accept with `hyperopt-tpu-lint --wire "
                "--update-contracts`",
            ))

    # -- GL603: typed-error surface vs the client reply seam ------------
    subclasses = _serve_error_subclasses(exc_ctxs)
    seam_strings = set()
    for ctx in seam_ctxs:
        for node in ast.walk(ctx.tree):
            if isinstance(node, ast.Constant) and isinstance(node.value, str):
                seam_strings.add(node.value)
    for name, (line, node, ctx) in sorted(subclasses.items()):
        if name not in seam_strings:
            findings.append(ctx.finding(
                "GL603", node,
                f"ServeError subclass {name!r} is unmapped at the "
                "client reply seam: it crosses the wire as error_type "
                f"{name!r} and surfaces as a generic RuntimeError -- "
                "add it to _REPLY_ERRORS (or a by-name special case)",
            ))

    # -- GL604: crash points vs test arming -----------------------------
    registries = _crash_registries(fault_ctxs)
    cp_total = cp_armed = 0
    for reg_name, points, ctx in registries:
        for point, line, node in points:
            cp_total += 1
            if point in test_strings or reg_name in iterated:
                cp_armed += 1
            else:
                findings.append(ctx.finding(
                    "GL604", node,
                    f"crash point {point!r} ({reg_name}) is never "
                    "armed by any test -- dead fault surface; arm it "
                    "in a chaos suite or delete it from the registry",
                ))

    # -- GL605: durable write seams without a crash point in scope ------
    for ctx in durable_ctxs:
        for fn, sites in sorted(
            _durable_sites(ctx).items(), key=lambda kv: kv[0].lineno
        ):
            kinds = ", ".join(
                f"{kind} (L{line})" for line, kind in sites
            )
            findings.append(ctx.finding(
                "GL605", fn,
                f"durable write seam in {fn.name!r} ({kinds}) with no "
                "crash point in scope: a kill inside this window is "
                "untestable -- bracket it with fs.crashpoint(...) or "
                "route it through a primitive that does",
            ))

    # -- GL606: hand-built retry_after outside the cap/jitter path ------
    for ctx in server_ctxs:
        for expr, node in _retry_after_values(ctx):
            if _numeric_without_cap(expr):
                findings.append(ctx.finding(
                    "GL606", node,
                    "reply carries a hand-built numeric retry_after "
                    "without the RETRY_AFTER_CAP/jitter path: wrap it "
                    "in min(..., RETRY_AFTER_CAP) or derive it from "
                    "the scheduler's jittered hint",
                ))

    # -- pragma suppression (same engine semantics as lint_source) ------
    pragmas_by_path = {
        path: parse_pragmas(src)
        for role in (server, clients, reply_seam, exceptions, faults,
                     durable, tests)
        for path, src in role.items()
    }
    kept, n_suppressed = [], 0
    for f in findings:
        pragmas = pragmas_by_path.get(f.path, {})
        covering = set(pragmas.get(f.line, ()))
        for scope_line in getattr(f, "_scope_lines", ()):
            covering |= pragmas.get(scope_line, set())
        if f.rule in covering:
            n_suppressed += 1
        else:
            kept.append(f)
    kept.sort(key=lambda f: (f.path, f.line, f.col, f.rule))

    stats = {
        "ops_checked": sum(len(ops) for ops in fronts.values()),
        "contract_drift": len(drift_ops),
        "crash_points_total": cp_total,
        "crash_points_armed": cp_armed,
        "errors_checked": len(subclasses),
        "n_suppressed": n_suppressed,
        "n_files": len(parsed),
    }
    return kept, stats, fresh_contracts


def _load_role(root, paths):
    out = {}
    for rel in paths:
        fp = os.path.join(root, rel)
        with open(fp, encoding="utf-8", errors="replace") as f:
            out[rel] = f.read()
    return out


def _iter_durable_files(root):
    out = []
    for d in DURABLE_DIRS:
        full = os.path.join(root, d)
        if not os.path.isdir(full):
            continue
        for name in sorted(os.listdir(full)):
            if name.endswith(".py") and name not in DURABLE_EXCLUDE:
                out.append(f"{d}/{name}")
    return out


def _iter_test_files(root):
    """Top-level tests/*.py only: the fixture corpus underneath
    (tests/lint_fixtures/) contains synthetic registries and handler
    decoys that must never count as arming/caller evidence."""
    tdir = os.path.join(root, "tests")
    if not os.path.isdir(tdir):
        return []
    return [
        f"tests/{name}" for name in sorted(os.listdir(tdir))
        if name.endswith(".py")
        and os.path.isfile(os.path.join(tdir, name))
    ]


def check_wire(contracts_path=None, update=False, root=None,
               sources=None, baseline=None):
    """Run the GL6xx pack over the real repo surfaces.

    ``contracts_path`` defaults to the committed manifest next to the
    package; ``update=True`` re-pins it instead of diffing (the other
    rules still report).  ``sources`` maps repo-relative paths to
    replacement source text (the mutation kill-checks' seam);
    ``baseline`` is a loaded baseline multiset.  Returns
    :class:`WireResult`.  Cwd-independent: files and the default
    manifest resolve against the package parent.
    """
    from .baseline import apply_baseline

    rootdir = root or repo_root()
    path = contracts_path or default_contracts_path(rootdir)

    roles = {
        "server": _load_role(rootdir, SERVER_FILES),
        "clients": _load_role(rootdir, CLIENT_FILES),
        "reply_seam": _load_role(rootdir, REPLY_SEAM_FILES),
        "exceptions": _load_role(rootdir, EXCEPTION_FILES),
        "faults": _load_role(rootdir, FAULT_FILES),
        "durable": _load_role(rootdir, _iter_durable_files(rootdir)),
        "tests": _load_role(rootdir, _iter_test_files(rootdir)),
    }
    if sources:
        for role in roles.values():
            for rel in role:
                if rel in sources:
                    role[rel] = sources[rel]

    contracts = None
    if not update and os.path.exists(path):
        contracts = load_contracts(path)
    manifest_missing = contracts is None and not update

    findings, stats, fresh = analyze(
        contracts=contracts, update=update, **roles
    )
    if manifest_missing:
        # analyze() treats a None manifest as "skip the diff"; a
        # MISSING committed manifest is itself drift (like graftir)
        f = Finding(
            path=os.path.basename(path), rule="GL602", line=1, col=0,
            message="no committed wire contracts manifest; pin it with "
            "`hyperopt-tpu-lint --wire --update-contracts`",
        )
        object.__setattr__(f, "_scope_lines", [])
        findings = sorted(
            findings + [f],
            key=lambda f: (f.path, f.line, f.col, f.rule),
        )
        stats["contract_drift"] += 1

    if update:
        write_contracts(path, fresh["fronts"], fresh["error_reply"])

    n_matched = 0
    baseline_size = 0
    if baseline is not None:
        baseline_size = sum(baseline.values())
        findings, n_matched = apply_baseline(findings, baseline)

    return WireResult(
        findings=findings,
        ops_checked=stats["ops_checked"],
        contract_drift=stats["contract_drift"],
        crash_points_total=stats["crash_points_total"],
        crash_points_armed=stats["crash_points_armed"],
        errors_checked=stats["errors_checked"],
        n_files=stats["n_files"],
        n_suppressed=stats["n_suppressed"],
        n_baseline_matched=n_matched,
        baseline_size=baseline_size,
        contracts_path=path,
        updated=bool(update),
    )
