"""graftlint: AST-based invariant checker for this codebase's own rules.

PRs 2-4 made the package fast and crash-safe by establishing invariants
that nothing enforced statically: jitted program families must not
host-sync or retrace per ask, donated buffers must never be read after
dispatch, and every durable write must be fsync-before-rename with
transient errors routed through ``with_retries``.  This package turns
those reviewer-memory rules into a lint pass that runs at diff time --
before a bench or a chaos run ever executes.

Rule families (see :mod:`.rules` for the pack, DESIGN.md SS4 for the
table mapping each rule to the PR that motivated it):

* GL0xx -- engine/meta (unknown pragma ID, unparsable file)
* GL1xx -- trace discipline inside jit/shard_map/pallas_call scopes
* GL2xx -- dispatch hygiene (donation, device sync, per-call jit)
* GL3xx -- crash consistency & fault routing
* GL4xx -- graftir: jaxpr/lowering-level program contracts over the
  registered dispatch-critical program families (:mod:`.ir`,
  ``hyperopt-tpu-lint --ir``) -- host callbacks, f64 creep, declined
  donation, oversized baked constants, mid-program transfers, and
  shape/cost drift against the committed ``program_contracts.json``
* GL5xx -- graftrace: static lock-discipline & race analysis over the
  serve/distributed threaded surface (:mod:`.trace`,
  ``hyperopt-tpu-lint --trace``) -- per-class lock-domain inference,
  unguarded shared-attribute access, lock-order cycles, blocking and
  jitted-dispatch calls under a lock, if-then-``Condition.wait``,
  futures resolved under a lock, threads started mid-``__init__``,
  daemon threads tearing durable state; paired with a runtime lockdep
  sanitizer (:mod:`.lockdep`) the serve suites arm at test time
* GL6xx -- graftwire: static wire-protocol & fault-surface contract
  checks over the serve seams (:mod:`.wire`,
  ``hyperopt-tpu-lint --wire``) -- op-surface symmetry between the
  service/router fronts and every client/test call site, per-op
  reply-field drift against the committed ``wire_contracts.json``,
  ServeError subclasses unmapped at the client reply seam, crash
  points no test ever arms, durable write seams outside any crash
  window, and ``retry_after`` replies built without the cap path

Inline suppression::

    risky_line()  # graftlint: disable=GL202 bench-only sync point

on the violating line, or on the ``def``/``class`` header to cover the
whole scope.  Grandfathered findings live in a committed baseline
(``lint_baseline.json``, keyed by (path, rule, content-hash) so entries
survive unrelated line shifts); the tier-1 test fails on any finding
not in it.

CLI: ``hyperopt-tpu-lint hyperopt_tpu/`` (exit 0 clean, 1 findings,
2 usage/internal error).  No third-party dependencies -- stdlib ``ast``
and ``tokenize`` only.
"""

from .baseline import load_baseline, write_baseline
from .engine import Finding, LintResult, lint_paths, lint_source
from .report import (
    format_ir_json,
    format_ir_text,
    format_json,
    format_text,
    format_wire_json,
    format_wire_text,
)
from .rules import RULES

__all__ = [
    "Finding",
    "LintResult",
    "lint_paths",
    "lint_source",
    "RULES",
    "load_baseline",
    "write_baseline",
    "format_text",
    "format_json",
    "format_ir_text",
    "format_ir_json",
    "format_wire_text",
    "format_wire_json",
]

# NOTE: the graftir checker itself (analysis.ir) imports lazily -- it
# needs jax at check time; `from hyperopt_tpu.analysis import ir` keeps
# the package import jax-free for the AST-only paths.
