"""``hyperopt-tpu-lint``: the graftlint/graftir console entry point.

Exit-code contract (pinned by tests/test_lint_suppress.py and
tests/test_graftir.py -- identical for the AST and ``--ir`` paths):

* 0 -- clean (no findings after baseline + pragmas / contracts)
* 1 -- findings
* 2 -- usage error or internal failure (bad path, unreadable baseline
  or contracts manifest, engine exception); argparse's own usage errors
  also exit 2

``lint_baseline.json`` in the current directory is picked up
automatically so ``hyperopt-tpu-lint hyperopt_tpu/`` from the repo root
runs against the committed baseline with no flags.  Finding paths are
anchored at ``--root`` (default: the baseline file's directory when a
baseline is in play, else the cwd), so the CLI reports identical
findings no matter where it is invoked from.

``--ir`` switches to the graftir jaxpr-level pack (GL4xx, see
:mod:`.ir`): it checks the REGISTERED program families, not the path
arguments, against the committed ``program_contracts.json`` (resolved
next to the package by default -- cwd-independent).  Accept deliberate
contract changes with ``--ir --update-contracts``.

``--trace`` switches to the graftrace concurrency pack (GL5xx, see
:mod:`.trace`): lock-domain inference and lock-discipline checks over
the same path arguments, with the identical exit-code contract,
``--format json``, pragma, and baseline workflow as the default pack.

``--wire`` switches to the graftwire wire-protocol pack (GL6xx, see
:mod:`.wire`): it checks the FIXED protocol surfaces (service/router
dispatch, client call sites, typed-error mapping, crash-point
registries), not the path arguments, against the committed
``wire_contracts.json`` (resolved next to the package by default --
cwd-independent).  Accept deliberate reply-shape changes with
``--wire --update-contracts``.
"""

from __future__ import annotations

import argparse
import os
import sys

from . import baseline as baseline_mod
from .engine import lint_paths
from .report import (
    format_ir_json,
    format_ir_text,
    format_json,
    format_text,
    format_wire_json,
    format_wire_text,
)
from .rules import RULES

__all__ = ["main"]

DEFAULT_BASELINE = "lint_baseline.json"


def _build_parser():
    p = argparse.ArgumentParser(
        prog="hyperopt-tpu-lint",
        description="AST-based invariant checker for trace discipline, "
        "dispatch hygiene, and crash consistency (graftlint), plus the "
        "jaxpr-level program contract checker (graftir, --ir).",
    )
    p.add_argument(
        "paths", nargs="*", default=["hyperopt_tpu"],
        help="files or directories to lint (default: hyperopt_tpu; "
        "ignored under --ir, which checks registered programs)",
    )
    p.add_argument(
        "--format", choices=("text", "json"), default="text",
        help="report format (default: text)",
    )
    p.add_argument(
        "--baseline", default=None, metavar="FILE",
        help="findings baseline to grandfather (default: "
        f"./{DEFAULT_BASELINE} when it exists)",
    )
    p.add_argument(
        "--no-baseline", action="store_true",
        help="ignore any baseline, report every finding",
    )
    p.add_argument(
        "--write-baseline", action="store_true",
        help="write the current findings to the baseline file and exit 0",
    )
    p.add_argument(
        "--root", default=None, metavar="DIR",
        help="anchor finding paths at this directory (default: the "
        "baseline file's directory when a baseline is used, else the "
        "cwd) -- makes reports identical regardless of invocation cwd",
    )
    p.add_argument(
        "--ir", action="store_true",
        help="run the graftir jaxpr-level pack (GL4xx) over the "
        "registered dispatch-critical program families",
    )
    p.add_argument(
        "--trace", action="store_true",
        help="run the graftrace concurrency pack (GL5xx: lock-domain "
        "inference, lock-order cycles, blocking/dispatch under lock) "
        "instead of the default AST pack; same exit contract, formats, "
        "and baseline workflow",
    )
    p.add_argument(
        "--wire", action="store_true",
        help="run the graftwire wire-protocol pack (GL6xx: op-surface "
        "symmetry, reply-contract drift, typed-error mapping, crash-"
        "point arming) over the protocol seams; same exit contract, "
        "formats, and baseline workflow",
    )
    p.add_argument(
        "--contracts", default=None, metavar="FILE",
        help="contracts manifest for --ir / --wire (default: the "
        "committed program_contracts.json / wire_contracts.json next "
        "to the package)",
    )
    p.add_argument(
        "--update-contracts", action="store_true",
        help="with --ir or --wire: re-pin the manifest to the current "
        "programs/reply shapes instead of diffing against it",
    )
    p.add_argument(
        "--list-rules", action="store_true",
        help="print the rule pack and exit",
    )
    return p


def _main_ir(args):
    # the graftmesh program contracts trace over a forced multi-device
    # virtual CPU mesh; arm the flag BEFORE anything imports jax (this
    # module and the engine are stdlib-only by design, so a fresh CLI
    # process reaches here with jax uninitialized)
    from ..parallel.mesh import REGISTRY_MESH_DEVICES, force_host_cpu_devices

    force_host_cpu_devices(max(8, REGISTRY_MESH_DEVICES))

    from . import ir as ir_mod

    contracts = args.contracts
    if contracts is None:
        contracts = ir_mod.default_contracts_path(root=args.root)
    try:
        result = ir_mod.check_programs(
            contracts_path=contracts, update=args.update_contracts,
        )
    except (FileNotFoundError, ValueError, OSError) as e:
        print(f"hyperopt-tpu-lint: error: {e}", file=sys.stderr)
        return 2
    except Exception as e:  # internal failure is 2, never a traceback
        print(
            f"hyperopt-tpu-lint: internal error: {type(e).__name__}: {e}",
            file=sys.stderr,
        )
        return 2
    if result.updated:
        print(
            f"pinned {result.programs_checked} program contract(s) to "
            f"{result.contracts_path}",
            file=sys.stderr,
        )
    print(
        format_ir_json(result) if args.format == "json"
        else format_ir_text(result)
    )
    return 0 if result.clean else 1


def _main_wire(args):
    from . import wire as wire_mod

    # the same cwd-independence discipline as the AST path: pick the
    # committed baseline up from the cwd, anchor everything at its home
    baseline_path = args.baseline
    if baseline_path is None and not args.no_baseline:
        if os.path.exists(DEFAULT_BASELINE):
            baseline_path = DEFAULT_BASELINE
    if args.no_baseline:
        baseline_path = None
    root = args.root
    if root is None and baseline_path is not None:
        root = os.path.dirname(os.path.abspath(baseline_path))

    try:
        counter = None
        if baseline_path is not None and not args.write_baseline:
            counter = baseline_mod.load_baseline(baseline_path)
        result = wire_mod.check_wire(
            contracts_path=args.contracts, update=args.update_contracts,
            root=root, baseline=counter,
        )
    except (FileNotFoundError, ValueError, OSError) as e:
        print(f"hyperopt-tpu-lint: error: {e}", file=sys.stderr)
        return 2
    except Exception as e:  # internal failure is 2, never a traceback
        print(
            f"hyperopt-tpu-lint: internal error: {type(e).__name__}: {e}",
            file=sys.stderr,
        )
        return 2

    if args.write_baseline:
        out = baseline_path or DEFAULT_BASELINE
        baseline_mod.write_baseline(out, result.findings)
        print(
            f"wrote {len(result.findings)} finding(s) to {out}",
            file=sys.stderr,
        )
        return 0
    if result.updated:
        print(
            f"pinned {result.ops_checked} op reply contract(s) to "
            f"{result.contracts_path}",
            file=sys.stderr,
        )
    print(
        format_wire_json(result) if args.format == "json"
        else format_wire_text(result)
    )
    return 0 if result.clean else 1


def main(argv=None):
    parser = _build_parser()
    args = parser.parse_args(argv)

    if args.list_rules:
        for rid in sorted(RULES):
            r = RULES[rid]
            print(f"{r.id}  {r.name:28s} {r.summary}")
        return 0

    if args.update_contracts and not (args.ir or args.wire):
        print(
            "hyperopt-tpu-lint: error: --update-contracts requires "
            "--ir or --wire",
            file=sys.stderr,
        )
        return 2
    packs = [f for f, on in (
        ("--ir", args.ir), ("--trace", args.trace), ("--wire", args.wire),
    ) if on]
    if len(packs) > 1:
        print(
            f"hyperopt-tpu-lint: error: {' and '.join(packs)} are "
            "separate packs; run them as separate invocations",
            file=sys.stderr,
        )
        return 2
    if args.ir:
        return _main_ir(args)
    if args.wire:
        return _main_wire(args)

    baseline_path = args.baseline
    if baseline_path is None and not args.no_baseline:
        if os.path.exists(DEFAULT_BASELINE):
            baseline_path = DEFAULT_BASELINE
    if args.no_baseline:
        baseline_path = None

    # cwd-independence: anchor finding paths at the baseline's home (so
    # they keep matching its committed repo-relative keys) unless the
    # caller pins --root explicitly
    root = args.root
    if root is None and baseline_path is not None:
        root = os.path.dirname(os.path.abspath(baseline_path))

    pack = "trace" if args.trace else "ast"
    try:
        counter = None
        if baseline_path is not None and not args.write_baseline:
            counter = baseline_mod.load_baseline(baseline_path)
        result = lint_paths(
            args.paths, baseline=counter, root=root, pack=pack
        )
    except (FileNotFoundError, ValueError, OSError) as e:
        print(f"hyperopt-tpu-lint: error: {e}", file=sys.stderr)
        return 2
    except Exception as e:  # internal failure is 2, never a traceback
        print(
            f"hyperopt-tpu-lint: internal error: {type(e).__name__}: {e}",
            file=sys.stderr,
        )
        return 2

    if args.write_baseline:
        out = baseline_path or DEFAULT_BASELINE
        baseline_mod.write_baseline(out, result.findings)
        print(
            f"wrote {len(result.findings)} finding(s) to {out}",
            file=sys.stderr,
        )
        return 0

    print(format_json(result) if args.format == "json" else format_text(result))
    return 0 if result.clean else 1


if __name__ == "__main__":
    sys.exit(main())
