"""``hyperopt-tpu-lint``: the graftlint console entry point.

Exit-code contract (pinned by tests/test_lint_suppress.py):

* 0 -- clean (no findings after baseline + pragmas)
* 1 -- findings
* 2 -- usage error or internal failure (bad path, unreadable baseline,
  engine exception); argparse's own usage errors also exit 2

``lint_baseline.json`` in the current directory is picked up
automatically so ``hyperopt-tpu-lint hyperopt_tpu/`` from the repo root
runs against the committed baseline with no flags.
"""

from __future__ import annotations

import argparse
import os
import sys

from . import baseline as baseline_mod
from .engine import lint_paths
from .report import format_json, format_text
from .rules import RULES

__all__ = ["main"]

DEFAULT_BASELINE = "lint_baseline.json"


def _build_parser():
    p = argparse.ArgumentParser(
        prog="hyperopt-tpu-lint",
        description="AST-based invariant checker for trace discipline, "
        "dispatch hygiene, and crash consistency (graftlint).",
    )
    p.add_argument(
        "paths", nargs="*", default=["hyperopt_tpu"],
        help="files or directories to lint (default: hyperopt_tpu)",
    )
    p.add_argument(
        "--format", choices=("text", "json"), default="text",
        help="report format (default: text)",
    )
    p.add_argument(
        "--baseline", default=None, metavar="FILE",
        help="findings baseline to grandfather (default: "
        f"./{DEFAULT_BASELINE} when it exists)",
    )
    p.add_argument(
        "--no-baseline", action="store_true",
        help="ignore any baseline, report every finding",
    )
    p.add_argument(
        "--write-baseline", action="store_true",
        help="write the current findings to the baseline file and exit 0",
    )
    p.add_argument(
        "--list-rules", action="store_true",
        help="print the rule pack and exit",
    )
    return p


def main(argv=None):
    parser = _build_parser()
    args = parser.parse_args(argv)

    if args.list_rules:
        for rid in sorted(RULES):
            r = RULES[rid]
            print(f"{r.id}  {r.name:28s} {r.summary}")
        return 0

    baseline_path = args.baseline
    if baseline_path is None and not args.no_baseline:
        if os.path.exists(DEFAULT_BASELINE):
            baseline_path = DEFAULT_BASELINE
    if args.no_baseline:
        baseline_path = None

    try:
        counter = None
        if baseline_path is not None and not args.write_baseline:
            counter = baseline_mod.load_baseline(baseline_path)
        result = lint_paths(args.paths, baseline=counter)
    except (FileNotFoundError, ValueError, OSError) as e:
        print(f"hyperopt-tpu-lint: error: {e}", file=sys.stderr)
        return 2
    except Exception as e:  # internal failure is 2, never a traceback
        print(
            f"hyperopt-tpu-lint: internal error: {type(e).__name__}: {e}",
            file=sys.stderr,
        )
        return 2

    if args.write_baseline:
        out = baseline_path or DEFAULT_BASELINE
        baseline_mod.write_baseline(out, result.findings)
        print(
            f"wrote {len(result.findings)} finding(s) to {out}",
            file=sys.stderr,
        )
        return 0

    print(format_json(result) if args.format == "json" else format_text(result))
    return 0 if result.clean else 1


if __name__ == "__main__":
    sys.exit(main())
