"""graftir: jaxpr-level program contract checker (the GL4xx pack).

graftlint's AST rules (:mod:`.rules`) see source text only; what
actually ends up INSIDE a compiled program is invisible to them -- a
host callback smuggled in via a helper function, a silent f64
promotion, a donation XLA never received, a 10 MB constant baked into
the fused tell+ask jaxpr.  graftir closes that gap: every
dispatch-critical program family registers itself with the program
registry (:mod:`hyperopt_tpu.ops.compile`, ``register_program``) as a
builder over ABSTRACT inputs, and this module traces and lowers each
one on the CPU backend -- ``jax.make_jaxpr``-level work, zero device
execution -- then audits the IR:

* **GL401** host callback (``io_callback``/``pure_callback``/
  ``debug_callback``) inside a dispatch-critical program.  A program
  may DECLARE a deliberate callback via its registration's
  ``allowed_callbacks`` (the chunked device loop's progress
  ``io_callback`` is the canonical case) -- the escape hatch is
  explicit and per-program, never a lint hole: an undeclared callback
  still fails, a stale declaration fails too, and the callback set is
  pinned in the committed manifest (``callbacks`` field, GL406).
* **GL402** f64/complex128 creep: the program is re-traced under
  ``enable_x64`` and any NON-weak wide-float intermediate is flagged --
  weak-typed Python-scalar promotions are exempt, so a finding means an
  un-dtyped array op that silently doubles compute/traffic the moment
  x64 is on.
* **GL403** donation not honored: the registry entry declares the
  program family's donation contract; the lowered module's
  input-output aliasing must match exactly.
* **GL404** oversized baked-in constant: any closed-over array bigger
  than :data:`CONST_BYTES_MAX` re-uploads with every program -- the
  hazard class the resident-history work (PR 4) exists to kill.
* **GL405** mid-program transfer (``device_put`` inside the jaxpr).
* **GL406** contract drift: output shapes/dtypes, the honored donation,
  ``cost_analysis()`` FLOPs/bytes, and total baked-constant bytes are
  pinned in the committed ``program_contracts.json``; any drift fails
  with a field-level diff and is accepted only via
  ``hyperopt-tpu-lint --ir --update-contracts``.

Everything here is cwd-independent: the registry anchors finding paths
at the package parent, and the default manifest path is resolved next
to the package, never the process cwd.
"""

from __future__ import annotations

import contextlib
import dataclasses
import json
import os
import re

from .engine import Finding

__all__ = [
    "IRResult",
    "check_capture",
    "check_programs",
    "default_contracts_path",
    "load_contracts",
    "write_contracts",
    "CONST_BYTES_MAX",
    "DEFAULT_CONTRACTS",
]

DEFAULT_CONTRACTS = "program_contracts.json"
CONTRACTS_VERSION = 1

#: GL404 threshold: a closed-over constant at or past this many bytes is
#: a re-upload hazard (it rides along with EVERY dispatch of the
#: program).  PackedSpace._consts are O(D) -- hundreds of bytes; one MiB
#: means somebody baked a history-sized array into a trace.
CONST_BYTES_MAX = 1 << 20

_CALLBACK_PRIMS = frozenset({
    "io_callback", "pure_callback", "debug_callback",
})
_TRANSFER_PRIMS = frozenset({"device_put"})
_WIDE_DTYPES = frozenset({"float64", "complex128"})

_ARG_RE = re.compile(r"%arg(\d+):")
#: the donation markers jit lowering stamps on main-function arguments:
#: single-device programs alias input to output directly
#: (``tf.aliasing_output``); multi-device (shard_map/GSPMD) programs
#: defer the aliasing decision to XLA and mark the argument a
#: ``jax.buffer_donor`` instead -- both ARE the honored donation
_DONOR_ATTRS = ("tf.aliasing_output", "jax.buffer_donor")


@dataclasses.dataclass
class IRResult:
    """What one ``--ir`` run produced (the reporter's input)."""

    findings: list
    programs_checked: int = 0
    contract_drift: int = 0
    contracts_path: str = ""
    updated: bool = False

    @property
    def clean(self):
        return not self.findings


def repo_root():
    """The package parent -- the anchor for finding paths and the
    default manifest location (cwd-independent by construction)."""
    return os.path.dirname(os.path.dirname(os.path.dirname(
        os.path.abspath(__file__)
    )))


def default_contracts_path(root=None):
    return os.path.join(root or repo_root(), DEFAULT_CONTRACTS)


def _finding(spec, rule, message):
    f = Finding(
        path=spec.path, rule=rule, line=spec.line, col=0,
        message=f"[{spec.name}] {message}",
    )
    object.__setattr__(f, "_scope_lines", [])
    return f


def _walk_eqns(jaxpr, out):
    """Every eqn of ``jaxpr`` and its nested sub-jaxprs (pjit / scan /
    while / cond / shard_map / pallas bodies), depth-first."""
    for eq in jaxpr.eqns:
        out.append(eq)
        for v in eq.params.values():
            items = v if isinstance(v, (tuple, list)) else [v]
            for item in items:
                if hasattr(item, "eqns"):
                    _walk_eqns(item, out)
                else:
                    inner = getattr(item, "jaxpr", None)
                    if hasattr(inner, "eqns"):
                        _walk_eqns(inner, out)
    return out


def _aval_str(aval):
    dt = getattr(aval, "dtype", None)
    shape = getattr(aval, "shape", ())
    name = getattr(dt, "name", str(dt))
    return f"{name}[{','.join(str(int(s)) for s in shape)}]"


def _donated_argnums(lowered_text):
    """Input positions the lowered module donates -- the donations XLA
    actually received (``tf.aliasing_output`` or, on multi-device
    programs, ``jax.buffer_donor`` on the main function's arguments).
    Per-argument attribute dicts may embed commas inside quoted
    sharding strings, so the signature is split on ``%argN:`` markers
    rather than matched with one regex."""
    main = lowered_text
    m = re.search(r"func\.func public @main\((.*?)\)\s*->", main, re.S)
    if m:
        main = m.group(1)
    marks = list(_ARG_RE.finditer(main))
    out = []
    for i, mk in enumerate(marks):
        end = marks[i + 1].start() if i + 1 < len(marks) else len(main)
        chunk = main[mk.end(): end]
        if any(attr in chunk for attr in _DONOR_ATTRS):
            out.append(int(mk.group(1)))
    return tuple(sorted(out))


@contextlib.contextmanager
def _on_cpu():
    """Force tracing/lowering onto the CPU backend: the checker must be
    runnable on a TPU-attached host (bench stamps it every round)
    without dispatching anything over the tunnel, and the committed
    contracts are pinned against CPU lowering."""
    import jax

    try:
        cpu = jax.local_devices(backend="cpu")[0]
    except RuntimeError:
        cpu = None
    if cpu is None:
        yield
    else:
        with jax.default_device(cpu):
            yield


def build_contract(capture):
    """Trace + lower one :class:`~hyperopt_tpu.ops.compile.
    ProgramCapture` on CPU; returns ``(traced, traced_x64, lowered,
    contract)`` where ``contract`` is the committed-manifest row."""
    import jax
    import numpy as np

    with _on_cpu():
        traced = capture.fn.trace(*capture.args, **capture.kwargs)
        lowered = traced.lower()
        traced_x64 = None
        if getattr(capture, "x64_check", True):
            with jax.experimental.enable_x64():
                traced_x64 = capture.fn.trace(
                    *capture.args, **capture.kwargs
                )

    cost = {}
    try:
        cost = lowered.cost_analysis() or {}
    except Exception:  # backend without HLO cost analysis: pin shapes only
        cost = {}

    def _cost_int(key):
        v = cost.get(key)
        return int(round(float(v))) if v is not None else None

    closed = traced.jaxpr
    contract = {
        "outputs": [_aval_str(v) for v in closed.out_avals],
        "donation": list(_donated_argnums(lowered.as_text())),
        # the host-callback primitives the program actually contains:
        # pinned so an allowlisted escape hatch cannot silently grow
        "callbacks": sorted({
            e.primitive.name
            for e in _walk_eqns(closed.jaxpr, [])
            if e.primitive.name in _CALLBACK_PRIMS
        }),
        "flops": _cost_int("flops"),
        "bytes_accessed": _cost_int("bytes accessed"),
        "const_bytes": int(sum(
            np.asarray(c).nbytes for c in closed.consts
        )),
    }
    return traced, traced_x64, lowered, contract


def check_capture(spec, capture, stored=None, const_bytes_max=None):
    """Run the GL4xx pack over one registered program.

    Returns ``(findings, contract)``.  ``stored`` is the committed
    contract row to diff against (GL406); ``None`` skips the drift
    check (the caller handles missing manifests itself).
    """
    limit = CONST_BYTES_MAX if const_bytes_max is None else const_bytes_max
    findings = []
    traced, traced_x64, _lowered, contract = build_contract(capture)

    eqns = _walk_eqns(traced.jaxpr.jaxpr, [])

    # GL401: host callbacks have no place inside a hot program family --
    # unless the registration DECLARES them (allowed_callbacks, the
    # explicit per-program escape hatch; declared set pinned in the
    # manifest's `callbacks` field)
    allowed = frozenset(getattr(capture, "allowed_callbacks", ()) or ())
    unknown_allowed = sorted(allowed - _CALLBACK_PRIMS)
    if unknown_allowed:
        findings.append(_finding(
            spec, "GL401",
            f"allowed_callbacks declares unknown primitive(s) "
            f"{unknown_allowed}: the allowlist names callback "
            f"primitives from {sorted(_CALLBACK_PRIMS)}",
        ))
    cb = sorted({
        e.primitive.name for e in eqns if e.primitive.name in _CALLBACK_PRIMS
    })
    for prim in cb:
        if prim in allowed:
            continue
        findings.append(_finding(
            spec, "GL401",
            f"host callback primitive {prim!r} inside a dispatch-critical "
            "program: every dispatch now blocks on a host round-trip; "
            "hoist it out of the traced scope, or -- if the hop is "
            "deliberate (progress/checkpoint cadence) -- declare it in "
            "the registration's allowed_callbacks",
        ))
    for prim in sorted((allowed & _CALLBACK_PRIMS) - set(cb)):
        findings.append(_finding(
            spec, "GL401",
            f"allowed_callbacks declares {prim!r} but the traced program "
            "contains no such callback: remove the stale declaration "
            "(the allowlist is a contract, not a mute button)",
        ))

    # GL405: a transfer inside the program serializes dispatch.  Only
    # device_put with an EXPLICIT target counts: jnp.array/asarray emit
    # target-less device_put eqns (devices=[None], alias semantics) that
    # move nothing, while jax.device_put(x, some_device_or_sharding)
    # inside a trace pins a real mid-program transfer.
    tr = sorted({
        e.primitive.name
        for e in eqns
        if e.primitive.name in _TRANSFER_PRIMS
        and any(d is not None for d in e.params.get("devices", ()))
    })
    for prim in tr:
        findings.append(_finding(
            spec, "GL405",
            f"mid-program transfer primitive {prim!r} with an explicit "
            "placement target: placement belongs to the caller "
            "(ObsBuffer/device_arrays), not inside the compiled program",
        ))

    # GL402: strong wide-float intermediates under enable_x64
    wide = {}
    for e in ([] if traced_x64 is None
              else _walk_eqns(traced_x64.jaxpr.jaxpr, [])):
        for ov in e.outvars:
            av = ov.aval
            dt = getattr(av, "dtype", None)
            if (
                dt is not None
                and str(dt) in _WIDE_DTYPES
                and not getattr(av, "weak_type", False)
            ):
                wide[e.primitive.name] = wide.get(e.primitive.name, 0) + 1
    for prim, n in sorted(wide.items()):
        findings.append(_finding(
            spec, "GL402",
            f"{n} {prim!r} intermediate(s) promote to a strong 64-bit "
            "float under enable_x64: an un-dtyped op is widening "
            "silently; pin dtype=jnp.float32 at the producing site",
        ))

    # GL404: oversized baked-in constants (the re-upload hazard class)
    import numpy as np

    for c in traced.jaxpr.consts:
        arr = np.asarray(c)
        if arr.nbytes >= limit:
            findings.append(_finding(
                spec, "GL404",
                f"closed-over constant {_aval_str(arr)} ({arr.nbytes} "
                f"bytes >= {limit}) is baked into the jaxpr and rides "
                "along with every dispatch; pass it as an argument "
                "(device-resident) instead",
            ))

    # GL403: the declared donation contract vs what lowering recorded
    declared = tuple(sorted(int(i) for i in capture.donate_argnums))
    honored = tuple(contract["donation"])
    if declared != honored:
        findings.append(_finding(
            spec, "GL403",
            f"donation contract mismatch: registry declares argnums "
            f"{list(declared)} but the lowered program aliases "
            f"{list(honored)} -- a dropped donate_argnums doubles peak "
            "device memory for the state buffers",
        ))

    # GL406: drift against the committed contract
    if stored is not None:
        for line in _diff_contract(stored, contract):
            findings.append(_finding(spec, "GL406", line))

    return findings, contract


def _diff_contract(stored, fresh):
    """Field-level readable diff lines, empty when identical."""
    out = []
    for key in ("outputs", "donation", "callbacks", "flops",
                "bytes_accessed", "const_bytes"):
        a, b = stored.get(key), fresh.get(key)
        if a != b:
            out.append(
                f"contract drift in {key!r}: committed {a!r} != traced "
                f"{b!r} (accept deliberate changes with "
                "`hyperopt-tpu-lint --ir --update-contracts`)"
            )
    return out


def load_contracts(path):
    with open(path, encoding="utf-8") as f:
        payload = json.load(f)
    if payload.get("version") != CONTRACTS_VERSION:
        raise ValueError(
            f"contracts manifest {path!r} has version "
            f"{payload.get('version')!r}; this checker reads version "
            f"{CONTRACTS_VERSION}"
        )
    return payload


def write_contracts(path, programs, params):
    payload = {
        "version": CONTRACTS_VERSION,
        "params": {
            "n_obs": params.n_obs,
            "batch": params.batch,
            "k_spec": params.k_spec,
            "n_studies": params.n_studies,
            "space_dims": params.space.n_dims,
        },
        "programs": programs,
    }
    with open(path, "w", encoding="utf-8") as f:
        json.dump(payload, f, indent=2, sort_keys=True)
        f.write("\n")
    return path


#: per-process memo of the default-parameterization trace results:
#: (name -> (findings-sans-GL406, contract)).  Source cannot change
#: under a live process, and tracing every family costs seconds -- the
#: CLI, the tier-1 gate, and bench all call check_programs repeatedly
#: in one process and only the manifest diff (GL406) varies per call.
_DEFAULT_TRACE_CACHE = {}


def _trace_once(name, spec, params, cache):
    if cache is not None and name in cache:
        fs, contract = cache[name]
        return list(fs), contract
    capture = spec.build(params)
    fs, contract = check_capture(spec, capture)
    if cache is not None:
        cache[name] = (tuple(fs), contract)
    return list(fs), contract


def check_programs(contracts_path=None, update=False, params=None):
    """Run the GL4xx pack over every registered program family.

    ``contracts_path`` defaults to the committed manifest next to the
    package.  ``update=True`` re-pins the manifest instead of diffing
    (GL401-405 still report).  Returns :class:`IRResult`.
    """
    from ..ops.compile import default_program_params, registered_programs

    path = contracts_path or default_contracts_path()
    specs = registered_programs()
    cache = None
    if params is None:
        params = default_program_params()
        cache = _DEFAULT_TRACE_CACHE

    manifest = {}
    manifest_missing = not os.path.exists(path)
    if not manifest_missing and not update:
        manifest = load_contracts(path).get("programs", {})

    findings = []
    fresh = {}
    drift = 0
    for name, spec in specs.items():
        fs, contract = _trace_once(name, spec, params, cache)
        fresh[name] = contract
        stored = None if update else manifest.get(name)
        if stored is not None:
            for line in _diff_contract(stored, contract):
                fs.append(_finding(spec, "GL406", line))
        if not update and stored is None:
            fs.append(_finding(
                spec, "GL406",
                "no committed contract"
                + (" (manifest missing)" if manifest_missing else "")
                + "; pin it with `hyperopt-tpu-lint --ir "
                "--update-contracts`",
            ))
        if any(f.rule == "GL406" for f in fs):
            drift += 1
        findings.extend(fs)

    # stale manifest rows: a program family that no longer registers
    for name in sorted(set(manifest) - set(specs)):
        f = Finding(
            path=os.path.basename(path), rule="GL406", line=1, col=0,
            message=f"[{name}] manifest pins a program no longer in the "
            "registry; refresh with `hyperopt-tpu-lint --ir "
            "--update-contracts`",
        )
        object.__setattr__(f, "_scope_lines", [])
        findings.append(f)
        drift += 1

    if update:
        write_contracts(path, fresh, params)

    findings.sort(key=lambda f: (f.path, f.line, f.col, f.rule))
    return IRResult(
        findings=findings,
        programs_checked=len(specs),
        contract_drift=drift,
        contracts_path=path,
        updated=bool(update),
    )
