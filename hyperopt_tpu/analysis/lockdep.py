"""Runtime lockdep: the observed lock-order sanitizer (graftrace's
dynamic half).

GL502 proves acquisition order statically for what the AST can see;
this wrapper catches the rest at TEST time.  It records the order in
which wrapped locks are acquired, per thread, into a process-wide
order graph, and raises :class:`LockOrderError` at the FIRST
acquisition that inverts an order some thread already established --
no deadlock has to actually happen (the interleaving that would
deadlock is exactly the one the test schedule rarely runs).

Opt-in and test-only by design -- production code never pays the
bookkeeping.  The serve, serve-chaos, and serve-guard suites arm it
via :func:`arm_scheduler_class` (an autouse fixture wraps every
``BatchScheduler``'s lock and rebuilds its condition over the wrapped
lock), and assert zero observed inversions at teardown;
``bench.py bench_trace()`` stamps a live detection probe
(``lockdep_inversions_observed``).

stdlib-only, no jax: importable anywhere the engine is.
"""

from __future__ import annotations

import threading

__all__ = [
    "LockDep",
    "LockOrderError",
    "arm_scheduler_class",
    "instrument_scheduler",
]


class LockOrderError(RuntimeError):
    """Two locks were observed acquired in both orders."""


class LockDep:
    """One acquisition-order graph plus per-thread held stacks."""

    def __init__(self):
        self._mu = threading.Lock()
        self._edges = {}  # (held_name, acquired_name) -> first thread
        self._tls = threading.local()
        self.inversions = 0
        self.errors = []

    def _stack(self):
        st = getattr(self._tls, "stack", None)
        if st is None:
            st = []
            self._tls.stack = st
        return st

    def wrap(self, lock, name):
        """An order-recording proxy over ``lock`` (Lock or RLock)."""
        return _TracedLock(self, lock, name)

    # -- bookkeeping (called by the proxies) -------------------------------

    def note_acquired(self, name, check=True):
        """Record edges held->name for everything this thread holds;
        with ``check`` (the normal acquire path) raise on an observed
        inversion.  ``check=False`` (the Condition.wait re-acquire
        path, where raising would corrupt the Condition's state) still
        counts and records the inversion for the teardown assert."""
        st = self._stack()
        tname = threading.current_thread().name
        with self._mu:
            for held in st:
                if held == name:
                    continue
                self._edges.setdefault((held, name), tname)
                first = self._edges.get((name, held))
                if first is None:
                    continue
                self.inversions += 1
                msg = (
                    f"lock-order inversion: thread {tname!r} acquired "
                    f"{name!r} while holding {held!r}, but thread "
                    f"{first!r} established the opposite order "
                    f"({name!r} before {held!r})"
                )
                self.errors.append(msg)
                if check:
                    raise LockOrderError(msg)
        st.append(name)

    def note_released(self, name):
        st = self._stack()
        for i in range(len(st) - 1, -1, -1):
            if st[i] == name:
                del st[i]
                break


class _TracedLock:
    """Order-recording proxy over a Lock/RLock.

    Duck-types the full protocol ``threading.Condition`` binds off its
    lock (``_release_save`` / ``_acquire_restore`` / ``_is_owned``), so
    ``threading.Condition(dep.wrap(rlock, name))`` keeps the held
    stack exact across ``wait()`` -- the lock leaves the stack while
    the thread sleeps and re-enters it on wakeup."""

    def __init__(self, dep, inner, name):
        self._dep = dep
        self._inner = inner
        self.name = name
        self._depth = threading.local()

    def _get_depth(self):
        return getattr(self._depth, "n", 0)

    def _set_depth(self, n):
        self._depth.n = n

    def acquire(self, blocking=True, timeout=-1):
        ok = self._inner.acquire(blocking, timeout)
        if not ok:
            return ok
        d = self._get_depth()
        if d == 0:
            try:
                self._dep.note_acquired(self.name)
            except BaseException:
                self._inner.release()
                raise
        self._set_depth(d + 1)
        return ok

    def release(self):
        self._inner.release()
        d = self._get_depth() - 1
        self._set_depth(d)
        if d == 0:
            self._dep.note_released(self.name)

    __enter__ = acquire

    def __exit__(self, *exc):
        self.release()

    # -- the Condition lock protocol ---------------------------------------

    def _release_save(self):
        d = self._get_depth()
        self._set_depth(0)
        self._dep.note_released(self.name)
        if hasattr(self._inner, "_release_save"):
            return (d, self._inner._release_save())
        self._inner.release()
        return (d, None)

    def _acquire_restore(self, saved):
        d, state = saved
        if hasattr(self._inner, "_acquire_restore"):
            self._inner._acquire_restore(state)
        else:
            self._inner.acquire()
        self._set_depth(d)
        self._dep.note_acquired(self.name, check=False)

    def _is_owned(self):
        if hasattr(self._inner, "_is_owned"):
            return self._inner._is_owned()
        if self._inner.acquire(False):
            self._inner.release()
            return False
        return True

    def locked(self):
        if hasattr(self._inner, "locked"):
            return self._inner.locked()
        return self._get_depth() > 0


def instrument_scheduler(sched, dep=None):
    """Wrap an already-constructed BatchScheduler's ``_lock`` with a
    traced proxy and rebuild ``_cond`` over it.  Must run before the
    scheduler's threads start (i.e. right after ``__init__``)."""
    if dep is None:
        dep = LockDep()
    traced = dep.wrap(
        sched._lock, f"BatchScheduler._lock@{id(sched):#x}"
    )
    sched._lock = traced
    sched._cond = threading.Condition(traced)
    return dep


def arm_scheduler_class(monkeypatch, dep=None):
    """Arm lockdep for every BatchScheduler a test constructs: patches
    ``BatchScheduler.__init__`` (via the pytest ``monkeypatch``
    fixture, so it unwinds automatically) to instrument each instance
    into the shared ``dep``.  Returns the :class:`LockDep`; assert
    ``dep.inversions == 0`` at teardown."""
    from ..serve.scheduler import BatchScheduler

    if dep is None:
        dep = LockDep()
    orig_init = BatchScheduler.__init__

    def __init__(self, *args, **kwargs):
        orig_init(self, *args, **kwargs)
        instrument_scheduler(self, dep)

    monkeypatch.setattr(BatchScheduler, "__init__", __init__)
    return dep
