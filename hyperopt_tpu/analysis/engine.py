"""graftlint core: file context, jit-scope resolution, suppressions.

The engine is deliberately stdlib-only (``ast`` + ``tokenize``): it must
run in the fast tier on a bare CPU container, lint the whole package in
well under five seconds, and never import jax (linting the trace rules
must not itself build a trace).

Scope model
-----------
A function is a *jitted scope* when it is

* decorated with ``jit`` / ``pmap`` / ``shard_map`` / ``pallas_call``
  (bare, called, or via ``partial(jax.jit, ...)``), or
* passed by name (through one level of plain-name / conditional-name
  aliasing, the ``fn = fn_joint if joint_ei else fn_factorized``
  pattern) or as an inline lambda to a call of one of those wrappers,
* or lexically nested inside a jitted scope (tracing descends into
  closures).

This is lexical, not interprocedural: a helper merely *called from* a
jitted function is not resolved.  That keeps false positives near zero;
the fixture corpus under ``tests/lint_fixtures/`` pins the behavior.

Suppressions
------------
``# graftlint: disable=GL101,GL303 reason`` on the violating line, or on
the ``def``/``class`` header line of any enclosing scope.  A pragma
naming an unknown rule ID is itself a finding (GL001).
"""

from __future__ import annotations

import ast
import dataclasses
import hashlib
import io
import os
import re
import tokenize

__all__ = [
    "Finding",
    "LintResult",
    "FileContext",
    "lint_source",
    "lint_paths",
    "unwrap_partial",
]

# wrapper terminals that open a traced scope
JIT_WRAPPERS = frozenset({"jit", "pmap", "shard_map", "pallas_call"})

_PRAGMA_RE = re.compile(
    r"#\s*graftlint:\s*disable="
    r"([A-Za-z0-9_]+(?:\s*,\s*[A-Za-z0-9_]+)*)"
    r"(?:\s+(?P<reason>\S.*))?$"
)

_FUNC_NODES = (ast.FunctionDef, ast.AsyncFunctionDef, ast.Lambda)
_SCOPE_NODES = _FUNC_NODES + (ast.ClassDef,)


@dataclasses.dataclass(frozen=True)
class Finding:
    path: str
    rule: str
    line: int
    col: int
    message: str
    source_line: str = ""

    def content_hash(self):
        """Identity that survives unrelated line shifts: the rule plus
        the stripped text of the violating line (baseline key)."""
        payload = f"{self.rule}:{self.source_line.strip()}"
        return hashlib.sha1(payload.encode("utf-8", "replace")).hexdigest()

    def to_dict(self):
        return {
            "path": self.path,
            "rule": self.rule,
            "line": self.line,
            "col": self.col,
            "message": self.message,
            "content_hash": self.content_hash(),
        }


@dataclasses.dataclass
class LintResult:
    findings: list
    n_files: int = 0
    n_suppressed: int = 0          # pragma-suppressed
    n_baseline_matched: int = 0    # grandfathered by the baseline
    baseline_size: int = 0

    @property
    def clean(self):
        return not self.findings


def terminal_name(node):
    """``a.b.c`` -> ``"c"``, ``name`` -> ``"name"``, else None."""
    if isinstance(node, ast.Name):
        return node.id
    if isinstance(node, ast.Attribute):
        return node.attr
    return None


def dotted_name(node):
    """Full dotted path of a Name/Attribute chain, or None."""
    parts = []
    while isinstance(node, ast.Attribute):
        parts.append(node.attr)
        node = node.value
    if isinstance(node, ast.Name):
        parts.append(node.id)
        return ".".join(reversed(parts))
    return None


def unwrap_partial(node):
    """``partial(f, ...)`` / ``functools.partial(f, ...)`` -> ``f``;
    anything else passes through.  A partial binds arguments -- it does
    not change which body runs, so scope resolution (jitted scopes AND
    thread-entry targets) must see through it."""
    if (
        isinstance(node, ast.Call)
        and terminal_name(node.func) == "partial"
        and node.args
    ):
        return node.args[0]
    return node


def wrapper_call_name(call):
    """If ``call`` invokes a trace wrapper (directly or via partial),
    return the wrapper terminal, else None."""
    t = terminal_name(call.func)
    if t in JIT_WRAPPERS:
        return t
    if t == "partial":
        for a in call.args:
            at = terminal_name(a)
            if at in JIT_WRAPPERS:
                return at
    return None


def _is_jit_decorator(dec):
    if terminal_name(dec) in JIT_WRAPPERS:
        return True
    return isinstance(dec, ast.Call) and wrapper_call_name(dec) is not None


def walk_scope(node):
    """Yield ``node``'s descendants WITHOUT descending into nested
    function/lambda bodies -- a function's own statements only."""
    stack = list(ast.iter_child_nodes(node))
    while stack:
        child = stack.pop()
        yield child
        if not isinstance(child, _FUNC_NODES):
            stack.extend(ast.iter_child_nodes(child))


class FileContext:
    """Everything a rule checker needs about one parsed file."""

    def __init__(self, path, source, tree):
        self.path = path
        self.posix_path = path.replace(os.sep, "/")
        self.parts = [p for p in self.posix_path.split("/") if p]
        self.source = source
        self.lines = source.splitlines()
        self.tree = tree
        self.parents = {}
        for node in ast.walk(tree):
            for child in ast.iter_child_nodes(node):
                self.parents[child] = node
        self._jitted = self._resolve_jitted_scopes()
        self.functions = [
            n for n in ast.walk(tree) if isinstance(n, _FUNC_NODES)
        ]
        self.thread_targets = self._resolve_thread_targets()

    # -- scope helpers -----------------------------------------------------

    def ancestors(self, node):
        n = self.parents.get(node)
        while n is not None:
            yield n
            n = self.parents.get(n)

    def enclosing_function(self, node):
        for a in self.ancestors(node):
            if isinstance(a, _FUNC_NODES):
                return a
        return None

    def scope_header_lines(self, node):
        """Line numbers of every enclosing def/class header (pragma
        placed there suppresses the whole scope)."""
        out = []
        if isinstance(node, _SCOPE_NODES):
            out.append(node.lineno)
        for a in self.ancestors(node):
            if isinstance(a, _SCOPE_NODES):
                out.append(a.lineno)
        return out

    def in_jitted_scope(self, node):
        if isinstance(node, _FUNC_NODES) and node in self._jitted:
            return True
        return any(
            isinstance(a, _FUNC_NODES) and a in self._jitted
            for a in self.ancestors(node)
        )

    def is_jitted(self, fn_node):
        return fn_node in self._jitted or self.in_jitted_scope(fn_node)

    def source_line(self, lineno):
        if 1 <= lineno <= len(self.lines):
            return self.lines[lineno - 1]
        return ""

    def finding(self, rule, node, message):
        f = Finding(
            path=self.posix_path,
            rule=rule,
            line=getattr(node, "lineno", 1),
            col=getattr(node, "col_offset", 0),
            message=message,
            source_line=self.source_line(getattr(node, "lineno", 1)),
        )
        # scope chain rides along (not part of identity) so the engine
        # can apply def-header pragmas
        object.__setattr__(f, "_scope_lines", self.scope_header_lines(node))
        return f

    # -- jitted-scope resolution -------------------------------------------

    def _resolve_jitted_scopes(self):
        jitted = set()
        defs_by_name = {}
        for node in ast.walk(self.tree):
            if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
                defs_by_name.setdefault(node.name, []).append(node)
                if any(_is_jit_decorator(d) for d in node.decorator_list):
                    jitted.add(node)

        # one level of plain-name aliasing: fn = a / fn = a if c else b /
        # fn = partial(a, ...) -- a partial binds arguments, it does not
        # change which function body traces, so scoped rules must see
        # through it (the fn = functools.partial(f, cfg); jit(fn) gap)
        _unwrap_partial = unwrap_partial

        alias = {}
        for node in ast.walk(self.tree):
            if not (isinstance(node, ast.Assign) and len(node.targets) == 1):
                continue
            tgt = node.targets[0]
            if not isinstance(tgt, ast.Name):
                continue
            names = set()
            v = _unwrap_partial(node.value)
            if isinstance(v, ast.Name):
                names.add(v.id)
            elif isinstance(v, ast.IfExp):
                for leg in (v.body, v.orelse):
                    leg = _unwrap_partial(leg)
                    if isinstance(leg, ast.Name):
                        names.add(leg.id)
            if names:
                alias.setdefault(tgt.id, set()).update(names)

        def resolve(name, depth=0):
            hits = set(defs_by_name.get(name, ()))
            if depth < 4:
                for nxt in alias.get(name, ()):
                    hits |= resolve(nxt, depth + 1)
            return hits

        for node in ast.walk(self.tree):
            if not (isinstance(node, ast.Call) and wrapper_call_name(node)):
                continue
            target = None
            if node.args:
                target = node.args[0]
            else:
                for kw in node.keywords:
                    if kw.arg in ("fun", "f", "fn"):
                        target = kw.value
                        break
            # jit(partial(f, x), ...) / shard_map(functools.partial(f,
            # b), mesh=...): the partial wrapper is transparent -- f's
            # body is what traces
            target = _unwrap_partial(target)
            if isinstance(target, ast.Lambda):
                jitted.add(target)
            elif isinstance(target, ast.Name):
                jitted |= resolve(target.id)
        return jitted

    # -- thread-entry-target resolution ------------------------------------

    def _resolve_thread_targets(self):
        """Map function/method defs that are THREAD ENTRY POINTS to
        ``{"daemon": bool}``.

        Resolves ``threading.Thread(target=...)`` and
        ``executor.submit(fn, ...)`` callables through a ``partial``
        wrapper, covering the three shapes the codebase uses:

        * ``Thread(target=self._loop)`` -- a BOUND METHOD of the
          enclosing class (by-name def lookup alone misses these);
        * ``Thread(target=functools.partial(self._method, arg))``;
        * ``Thread(target=local_fn)`` -- a plain (possibly nested) def.

        Rules treat these as concurrency ROOTS: a thread target enters
        with no lock held, whatever its in-class callers hold."""
        targets = {}
        defs_by_name = {}
        for node in ast.walk(self.tree):
            if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
                defs_by_name.setdefault(node.name, []).append(node)

        def enclosing_class(node):
            for a in self.ancestors(node):
                if isinstance(a, ast.ClassDef):
                    return a
            return None

        for node in ast.walk(self.tree):
            if not isinstance(node, ast.Call):
                continue
            t = terminal_name(node.func)
            target = None
            if t == "Thread":
                for kw in node.keywords:
                    if kw.arg == "target":
                        target = kw.value
            elif t == "submit" and node.args:
                # pool.submit(fn, ...): the executor's worker threads
                target = node.args[0]
            if target is None:
                continue
            target = unwrap_partial(target)
            daemon = any(
                kw.arg == "daemon"
                and isinstance(kw.value, ast.Constant)
                and bool(kw.value.value)
                for kw in node.keywords
            )
            resolved = []
            if (
                isinstance(target, ast.Attribute)
                and isinstance(target.value, ast.Name)
                and target.value.id == "self"
            ):
                cls = enclosing_class(node)
                if cls is not None:
                    for m in cls.body:
                        if (
                            isinstance(m, (ast.FunctionDef,
                                           ast.AsyncFunctionDef))
                            and m.name == target.attr
                        ):
                            resolved.append(m)
            elif isinstance(target, ast.Name):
                resolved.extend(defs_by_name.get(target.id, ()))
            elif isinstance(target, ast.Lambda):
                resolved.append(target)
            for fn in resolved:
                info = targets.setdefault(fn, {"daemon": False})
                info["daemon"] = info["daemon"] or daemon
        return targets


def parse_pragmas(source):
    """Map line -> set of rule IDs disabled there (via tokenize, so
    pragmas inside strings don't count)."""
    pragmas = {}
    try:
        tokens = tokenize.generate_tokens(io.StringIO(source).readline)
        for tok in tokens:
            if tok.type != tokenize.COMMENT:
                continue
            m = _PRAGMA_RE.search(tok.string)
            if m:
                ids = {s.strip() for s in m.group(1).split(",") if s.strip()}
                pragmas.setdefault(tok.start[0], set()).update(ids)
    except (tokenize.TokenError, IndentationError, SyntaxError):
        pass
    return pragmas


def lint_source(source, path="<string>", pack="ast"):
    """Lint one file's source; returns (findings, n_pragma_suppressed).

    ``pack`` selects the checker pack: ``"ast"`` (the default GL1xx-3xx
    invariants) or ``"trace"`` (the GL5xx graftrace concurrency pack,
    ``hyperopt-tpu-lint --trace``).  Both share the engine, the pragma
    machinery, and the baseline format.

    Unparsable source is itself a finding (GL002) rather than an engine
    crash -- a syntax error in a diff must fail the lint test, not
    crash the harness with a traceback.
    """
    from .rules import CHECKERS, RULES

    if pack == "trace":
        from .trace import TRACE_CHECKERS as checkers
    elif pack == "ast":
        checkers = CHECKERS
    else:
        raise ValueError(f"unknown checker pack {pack!r}")

    try:
        tree = ast.parse(source)
    except SyntaxError as e:
        f = Finding(
            path=path.replace(os.sep, "/"),
            rule="GL002",
            line=e.lineno or 1,
            col=(e.offset or 1) - 1,
            message=f"file does not parse: {e.msg}",
            source_line=(e.text or "").rstrip("\n"),
        )
        object.__setattr__(f, "_scope_lines", [])
        return [f], 0

    ctx = FileContext(path, source, tree)
    pragmas = parse_pragmas(source)

    raw = []
    for rule_id, checker in checkers:
        raw.extend(checker(ctx))

    # GL001: a pragma naming a rule NO pack defines is dead weight that
    # silently stops protecting when the real ID differs (ast pack
    # only, so the two packs never double-report the same pragma)
    if pack == "ast":
        for lineno, ids in pragmas.items():
            for rid in sorted(ids):
                if rid not in RULES:
                    f = ctx.finding(
                        "GL001",
                        ast.Pass(lineno=lineno, col_offset=0),
                        f"suppression names unknown rule ID {rid!r}",
                    )
                    raw.append(f)

    kept, n_suppressed = [], 0
    for f in raw:
        covering = set(pragmas.get(f.line, ()))
        for scope_line in getattr(f, "_scope_lines", ()):
            covering |= pragmas.get(scope_line, set())
        if f.rule in covering:
            n_suppressed += 1
        else:
            kept.append(f)
    kept.sort(key=lambda f: (f.path, f.line, f.col, f.rule))
    return kept, n_suppressed


def iter_python_files(paths):
    out = []
    for p in paths:
        if os.path.isdir(p):
            for root, dirs, files in os.walk(p):
                dirs[:] = sorted(
                    d for d in dirs
                    if d != "__pycache__" and not d.startswith(".")
                )
                out.extend(
                    os.path.join(root, f) for f in sorted(files)
                    if f.endswith(".py")
                )
        elif p.endswith(".py") or os.path.isfile(p):
            out.append(p)
        else:
            raise FileNotFoundError(p)
    return out


def lint_paths(paths, baseline=None, root=None, pack="ast"):
    """Lint files/directories; apply ``baseline`` (a loaded baseline
    multiset, see :mod:`.baseline`) to filter grandfathered findings.

    ``root`` anchors finding paths (default: the process cwd) -- pass
    the repo root when calling from elsewhere so paths keep matching
    the committed baseline's repo-relative keys.  ``pack`` selects the
    checker pack (see :func:`lint_source`).
    """
    from .baseline import apply_baseline

    files = iter_python_files(paths)
    findings, n_suppressed = [], 0
    for fp in files:
        try:
            with open(fp, encoding="utf-8", errors="replace") as fh:
                source = fh.read()
        except OSError as e:
            raise FileNotFoundError(f"cannot read {fp}: {e}") from e
        rel = (
            os.path.relpath(fp, start=root)
            if root is not None or os.path.isabs(fp) else fp
        )
        fs, ns = lint_source(source, path=rel, pack=pack)
        findings.extend(fs)
        n_suppressed += ns
    findings.sort(key=lambda f: (f.path, f.line, f.col, f.rule))

    n_matched = 0
    baseline_size = 0
    if baseline is not None:
        baseline_size = sum(baseline.values())
        findings, n_matched = apply_baseline(findings, baseline)
    return LintResult(
        findings=findings,
        n_files=len(files),
        n_suppressed=n_suppressed,
        n_baseline_matched=n_matched,
        baseline_size=baseline_size,
    )
