"""The graftlint rule pack: the invariants PRs 2-4 established, as AST
checks.  DESIGN.md SS4 maps each rule to the PR that motivated it and
the runtime guard it complements.

Every checker is a function ``check(ctx) -> iterable[Finding]`` over a
:class:`~.engine.FileContext`; registration order is reporting order.
Rules are heuristic by design -- lexical, single-file, no type
inference -- and every rule's true-positive and near-miss behavior is
pinned by a fixture pair in ``tests/lint_fixtures/``.
"""

from __future__ import annotations

import ast
import dataclasses

from .engine import (
    JIT_WRAPPERS,
    dotted_name,
    terminal_name,
    walk_scope,
    wrapper_call_name,
)

__all__ = ["RULES", "CHECKERS", "Rule"]


@dataclasses.dataclass(frozen=True)
class Rule:
    id: str
    name: str
    summary: str


RULES = {}
CHECKERS = []


def register(rule_id, name, summary):
    RULES[rule_id] = Rule(rule_id, name, summary)

    def deco(fn):
        CHECKERS.append((rule_id, fn))
        return fn

    return deco


# engine-emitted rules: registered so pragmas naming them validate and
# --list-rules documents them, but no checker walks the tree
RULES["GL001"] = Rule(
    "GL001", "unknown-pragma-rule",
    "a # graftlint: disable= pragma names a rule ID the pack does not define",
)
RULES["GL002"] = Rule(
    "GL002", "parse-error", "file does not parse (syntax error)"
)

# GL4xx -- the graftir IR pack (hyperopt-tpu-lint --ir): checked over
# the TRACED jaxprs/lowerings of registered program families, not the
# AST, so no checker here walks a tree (see analysis/ir.py; the rule
# metadata lives in this table so --list-rules and pragma validation
# cover the whole pack without importing jax)
for _id, _name, _summary in (
    ("GL401", "ir-host-callback",
     "io_callback/pure_callback/debug_callback primitive inside a "
     "dispatch-critical program's jaxpr"),
    ("GL402", "ir-f64-promotion",
     "a non-weak float64/complex128 intermediate appears when the "
     "program is traced under enable_x64 (an un-dtyped op widening "
     "silently)"),
    ("GL403", "ir-donation-not-honored",
     "the registry's declared donate_argnums are absent from (or "
     "exceed) the lowered program's input-output aliasing"),
    ("GL404", "ir-oversized-constant",
     "a closed-over array constant >= the byte threshold is baked into "
     "the jaxpr (re-uploaded with every dispatch)"),
    ("GL405", "ir-mid-program-transfer",
     "a device_put transfer primitive inside the program body"),
    ("GL406", "ir-contract-drift",
     "output shapes/dtypes, donation, or cost_analysis FLOPs/bytes "
     "drifted from the committed program_contracts.json"),
):
    RULES[_id] = Rule(_id, _name, _summary)

# GL5xx -- the graftrace concurrency pack (hyperopt-tpu-lint --trace):
# static lock-discipline and race analysis over the serve/distributed
# threaded surface.  The checkers live in analysis/trace.py (their own
# pack, selected by lint_source(pack="trace")); the metadata lives in
# this table so --list-rules documents them and pragmas naming them
# validate under the default pack (GL001 must not flag a GL5xx
# suppression on a scheduler line as unknown).
for _id, _name, _summary in (
    ("GL501", "unguarded-shared-attribute",
     "a shared instance attribute written under the class's inferred "
     "lock domain (majority `with self._lock:` usage) is read or "
     "mutated lock-free elsewhere -- a data race across methods or "
     "thread-entry targets"),
    ("GL502", "lock-order-inversion",
     "two locks of one class are acquired in both orders across its "
     "methods (inter-procedural acquisition graph over the class and "
     "its self-callees has a cycle) -- the classic ABBA deadlock"),
    ("GL503", "blocking-call-under-lock",
     "a blocking call (socket accept/recv/sendall, fsync/durable "
     "writes, Future.result/Thread.join, time.sleep, or a jitted "
     "dispatch callable) runs while a lock is held -- every contending "
     "thread stalls for the call's full latency"),
    ("GL504", "condition-wait-without-predicate-loop",
     "Condition.wait called outside an enclosing while loop -- spurious "
     "wakeups and stolen predicates make if-then-wait lose signals"),
    ("GL505", "future-resolved-under-lock",
     "Future.set_result/set_exception while holding a lock -- done-"
     "callbacks run inline in the resolving thread and can re-enter "
     "the lock (the callback-under-lock deadlock shape)"),
    ("GL506", "thread-started-before-init-complete",
     "a thread is started inside __init__ before later instance "
     "attributes are assigned -- the target can observe a partially "
     "constructed object"),
    ("GL507", "daemon-thread-durable-mutation",
     "WAL/checkpoint durable state is mutated from a daemon-thread "
     "entry point (directly or via same-class callees) -- daemon "
     "threads die mid-write at interpreter exit, tearing the artifact"),
):
    RULES[_id] = Rule(_id, _name, _summary)

# graftwire (GL6xx) rules run via analysis/wire.py over the wire-
# protocol and fault surfaces (service/router dispatch, client call
# sites, typed-error mapping, crash-point registries), selected by
# `hyperopt-tpu-lint --wire`.  Same registration posture as GL4xx/
# GL5xx: metadata-only rows so --list-rules and GL001 pragma
# validation cover the pack.
for _id, _name, _summary in (
    ("GL601", "wire-op-asymmetry",
     "a client-sent op has no server handler, a handled op has no "
     "client or test caller, or a global op one front handles the "
     "other silently refuses untyped"),
    ("GL602", "wire-contract-drift",
     "an op's extracted reply-field set drifted from the committed "
     "wire_contracts.json (accept deliberate changes with --wire "
     "--update-contracts)"),
    ("GL603", "unmapped-serve-error",
     "a ServeError subclass never appears at the client reply seam "
     "(_REPLY_ERRORS) -- the wire error would surface as a generic "
     "RuntimeError instead of its typed exception"),
    ("GL604", "dead-crash-point",
     "a name registered in a *_CRASH_POINTS tuple is never armed or "
     "iterated by any test -- an untested crash window"),
    ("GL605", "durable-seam-without-crash-point",
     "a durable write seam (fsync/rename/WAL append) in serve// "
     "distributed/ has no crashpoint() in its function scope -- the "
     "torn-state window is uninjectable"),
    ("GL606", "retry-after-without-cap",
     "a retry_after-carrying reply is built from a bare numeric "
     "without the RETRY_AFTER_CAP/jitter path -- clients can be told "
     "to back off unboundedly"),
):
    RULES[_id] = Rule(_id, _name, _summary)


def _is_test_file(ctx):
    base = ctx.parts[-1] if ctx.parts else ""
    return base.startswith("test_") or base == "conftest.py"


def _call_args_all_constant(call):
    return all(isinstance(a, ast.Constant) for a in call.args)


# ---------------------------------------------------------------------------
# GL1xx -- trace discipline (PR 4's resident/fused dispatch contract)
# ---------------------------------------------------------------------------

_HOST_SYNC_METHODS = frozenset({"item", "tolist"})
_HOST_MATERIALIZERS = frozenset({"asarray", "array"})
_NUMPY_MODULES = frozenset({"np", "numpy", "onp"})
_SCALAR_BUILTINS = frozenset({"float", "int", "bool"})


@register(
    "GL101", "tracer-host-sync",
    ".item()/tolist()/float()/int()/bool()/np.asarray on a value inside a "
    "jitted scope -- forces a device sync or a concretization error",
)
def check_tracer_host_sync(ctx):
    for node in ast.walk(ctx.tree):
        if not isinstance(node, ast.Call):
            continue
        if not ctx.in_jitted_scope(node):
            continue
        func = node.func
        # x.item() / x.tolist()
        if (
            isinstance(func, ast.Attribute)
            and func.attr in _HOST_SYNC_METHODS
            and not node.args
        ):
            yield ctx.finding(
                "GL101", node,
                f".{func.attr}() inside a jitted scope host-syncs the "
                "traced value; return it and fetch outside the program",
            )
            continue
        # np.asarray / np.array on a traced value
        if isinstance(func, ast.Attribute):
            dn = dotted_name(func)
            if (
                func.attr in _HOST_MATERIALIZERS
                and dn is not None
                and dn.split(".")[0] in _NUMPY_MODULES
            ):
                yield ctx.finding(
                    "GL101", node,
                    f"{dn}() inside a jitted scope materializes the tracer "
                    "on host; use jnp inside the program",
                )
            continue
        # float(x)/int(x)/bool(x) on non-literal arguments
        if (
            isinstance(func, ast.Name)
            and func.id in _SCALAR_BUILTINS
            and node.args
            and not _call_args_all_constant(node)
        ):
            yield ctx.finding(
                "GL101", node,
                f"{func.id}() on a traced value inside a jitted scope "
                "raises ConcretizationError (or silently host-syncs)",
            )


@register(
    "GL102", "debug-print-in-jit",
    "jax.debug.print/breakpoint inside a jitted scope -- hot program "
    "families must stay debug-callback-free",
)
def check_debug_print(ctx):
    for node in ast.walk(ctx.tree):
        if not isinstance(node, ast.Call):
            continue
        dn = dotted_name(node.func)
        if dn in ("jax.debug.print", "jax.debug.breakpoint") and (
            ctx.in_jitted_scope(node)
        ):
            yield ctx.finding(
                "GL102", node,
                f"{dn} inside a jitted scope inserts a host callback into "
                "the hot program; strip before shipping",
            )


@register(
    "GL103", "loop-var-closure-capture",
    "a jitted function defined inside a loop closes over the loop "
    "variable -- every iteration traces a fresh program",
)
def check_loop_closure_capture(ctx):
    for fn in ctx.functions:
        if not ctx.is_jitted(fn):
            continue
        # names (re)bound by For/While loops that lexically enclose fn
        loop_names = set()
        for anc in ctx.ancestors(fn):
            if isinstance(anc, (ast.For, ast.While)):
                for t in ast.walk(getattr(anc, "target", anc)):
                    if isinstance(t, ast.Name):
                        loop_names.add(t.id)
                for st in walk_scope(anc):
                    if isinstance(st, ast.Name) and isinstance(
                        st.ctx, ast.Store
                    ):
                        loop_names.add(st.id)
        if not loop_names:
            continue
        local = set()
        args = fn.args
        for a in (
            args.args + args.posonlyargs + args.kwonlyargs
            + ([args.vararg] if args.vararg else [])
            + ([args.kwarg] if args.kwarg else [])
        ):
            local.add(a.arg)
        body = fn.body if isinstance(fn.body, list) else [fn.body]
        for st in body:
            for n in ast.walk(st):
                if isinstance(n, ast.Name) and isinstance(n.ctx, ast.Store):
                    local.add(n.id)
        for st in body:
            for n in ast.walk(st):
                if (
                    isinstance(n, ast.Name)
                    and isinstance(n.ctx, ast.Load)
                    and n.id in loop_names
                    and n.id not in local
                ):
                    yield ctx.finding(
                        "GL103", n,
                        f"jitted closure captures loop-carried {n.id!r}: "
                        "each iteration bakes a new constant and retraces; "
                        "pass it as an argument",
                    )


@register(
    "GL104", "jit-constructed-in-loop",
    "jax.jit/pmap called inside a loop -- builds a fresh program family "
    "per iteration; route through ops/compile.py's cache",
)
def check_jit_in_loop(ctx):
    if "compile.py" == (ctx.parts[-1] if ctx.parts else ""):
        return
    for node in ast.walk(ctx.tree):
        if not (isinstance(node, ast.Call) and wrapper_call_name(node)):
            continue
        # the loop must enclose the call within the same function: a
        # def inside a loop re-jitting at ITS top level is regime GL103
        for anc in ctx.ancestors(node):
            if isinstance(anc, (ast.FunctionDef, ast.AsyncFunctionDef,
                                ast.Lambda)):
                break
            if isinstance(anc, (ast.For, ast.While)):
                yield ctx.finding(
                    "GL104", node,
                    "trace wrapper constructed inside a loop: every "
                    "iteration starts a fresh program family (compile "
                    "storm); hoist it or use ops/compile.py's cache",
                )
                break


# ---------------------------------------------------------------------------
# GL2xx -- dispatch hygiene (PR 4's donation + one-dispatch contract)
# ---------------------------------------------------------------------------


def _donated_indices(call):
    """donate_argnums of a jit call, as a tuple of ints, else None."""
    for kw in call.keywords:
        if kw.arg != "donate_argnums":
            continue
        v = kw.value
        if isinstance(v, ast.Constant) and isinstance(v.value, int):
            return (v.value,)
        if isinstance(v, (ast.Tuple, ast.List)):
            idxs = []
            for e in v.elts:
                if isinstance(e, ast.Constant) and isinstance(e.value, int):
                    idxs.append(e.value)
            return tuple(idxs)
    return None


@register(
    "GL201", "read-after-donate",
    "a buffer passed at a donated position is read after the dispatch -- "
    "donated buffers are dead the moment the call is issued",
)
def check_read_after_donate(ctx):
    # names bound to jit(..., donate_argnums=...) anywhere in the file
    donated = {}
    for node in ast.walk(ctx.tree):
        if not (isinstance(node, ast.Assign) and len(node.targets) == 1):
            continue
        tgt = node.targets[0]
        if not (isinstance(tgt, ast.Name) and isinstance(node.value, ast.Call)):
            continue
        if wrapper_call_name(node.value) is None:
            continue
        idxs = _donated_indices(node.value)
        if idxs:
            donated[tgt.id] = idxs
    if not donated:
        return

    def _store_pos(n):
        # a Store takes effect at the END of its statement (the value
        # side of `state = step(state)` runs first), so position the
        # rebind after the donating call it feeds from
        cur = n
        while cur is not None and not isinstance(cur, ast.stmt):
            cur = ctx.parents.get(cur)
        if cur is not None and getattr(cur, "end_lineno", None) is not None:
            return (cur.end_lineno, cur.end_col_offset, 1)
        return (n.lineno, n.col_offset, 1)

    scopes = list(ctx.functions) + [ctx.tree]
    for scope in scopes:
        # own statements only: a nested def is its own dataflow scope
        nodes = [n for n in walk_scope(scope) if hasattr(n, "lineno")]
        events = []
        for n in nodes:
            if isinstance(n, ast.Name) and isinstance(n.ctx, ast.Store):
                events.append((_store_pos(n), n))
            else:
                events.append(((n.lineno, n.col_offset, 0), n))
        # dead[name] = position the buffer dies: the END of the donating
        # call, so argument reads inside the call span stay legal
        dead = {}
        for pos, n in sorted(events, key=lambda e: e[0]):
            if isinstance(n, ast.Call):
                fname = n.func.id if isinstance(n.func, ast.Name) else None
                if fname in donated:
                    end = (
                        (n.end_lineno, n.end_col_offset, 0)
                        if getattr(n, "end_lineno", None) is not None
                        else pos
                    )
                    for i in donated[fname]:
                        if i < len(n.args) and isinstance(n.args[i], ast.Name):
                            dead[n.args[i].id] = end
            elif isinstance(n, ast.Name) and n.id in dead:
                if isinstance(n.ctx, ast.Store):
                    # rebinding revives the name (fresh buffer)
                    if pos > dead[n.id]:
                        del dead[n.id]
                elif isinstance(n.ctx, ast.Load) and pos > dead[n.id]:
                    yield ctx.finding(
                        "GL201", n,
                        f"{n.id!r} was donated to a jitted call above; its "
                        "buffer is dead -- use the program's outputs",
                    )
                    del dead[n.id]  # one finding per donation site


@register(
    "GL202", "sync-outside-bench",
    "block_until_ready outside bench/profiling modules -- product paths "
    "must stay dispatch-async (the RTT floor is the contract)",
)
def check_block_until_ready(ctx):
    name = ctx.parts[-1] if ctx.parts else ""
    if "bench" in name or "profiling" in name or _is_test_file(ctx):
        return
    for node in ast.walk(ctx.tree):
        if not isinstance(node, ast.Call):
            continue
        if terminal_name(node.func) == "block_until_ready":
            yield ctx.finding(
                "GL202", node,
                "block_until_ready in a product path serializes dispatch "
                "on device completion; only bench/profiling may sync",
            )


@register(
    "GL203", "per-call-jit",
    "jax.jit(f)(args) -- wrapping per call defeats the program cache "
    "(a fresh callable each time)",
)
def check_per_call_jit(ctx):
    for node in ast.walk(ctx.tree):
        if not isinstance(node, ast.Call):
            continue
        inner = node.func
        if isinstance(inner, ast.Call) and wrapper_call_name(inner) in (
            "jit", "pmap"
        ):
            yield ctx.finding(
                "GL203", node,
                "jit-wrap-then-call in one expression builds a fresh "
                "callable per invocation; bind the jitted function once",
            )


# ---------------------------------------------------------------------------
# GL3xx -- crash consistency & fault routing (PR 3's durability contract)
# ---------------------------------------------------------------------------

_WRITE_MODES = "wax+"


def _is_write_open(call):
    if terminal_name(call.func) != "open":
        return False
    mode = None
    if len(call.args) >= 2 and isinstance(call.args[1], ast.Constant):
        mode = call.args[1].value
    for kw in call.keywords:
        if kw.arg == "mode" and isinstance(kw.value, ast.Constant):
            mode = kw.value.value
    return isinstance(mode, str) and any(c in mode for c in _WRITE_MODES)


@register(
    "GL301", "rename-without-fsync",
    "os.rename/os.replace publishes a file written in the same function "
    "with no fsync -- a crash can publish an empty or truncated file",
)
def check_rename_without_fsync(ctx):
    for fn in ctx.functions:
        if isinstance(fn, ast.Lambda):
            continue
        own = list(walk_scope(fn))
        wrote = any(isinstance(n, ast.Call) and _is_write_open(n) for n in own)
        if not wrote:
            continue
        synced = any(
            isinstance(n, ast.Call) and terminal_name(n.func) == "fsync"
            for n in own
        )
        if synced:
            continue
        for n in own:
            if isinstance(n, ast.Call) and terminal_name(n.func) in (
                "rename", "replace"
            ):
                yield ctx.finding(
                    "GL301", n,
                    "rename publishes a file this function wrote without "
                    "fsync: the rename's metadata can reach disk before "
                    "the data does (fsync-before-rename, PR 3)",
                )


# broad = a net wide enough to catch OSError/TransientBackendError by
# accident; a typed `except OSError` is a deliberate protocol catch
_BROAD_EXCEPTS = frozenset({"Exception", "BaseException"})


def _handler_is_broad(handler):
    if handler.type is None:
        return True
    types = (
        handler.type.elts
        if isinstance(handler.type, ast.Tuple)
        else [handler.type]
    )
    return any(terminal_name(t) in _BROAD_EXCEPTS for t in types)


@register(
    "GL302", "swallowed-broad-except",
    "broad except in the fault domain (distributed/, checkpoint) that "
    "neither re-raises nor consults is_transient -- can eat "
    "TransientBackendError/OSError meant for with_retries",
)
def check_swallowed_broad_except(ctx):
    in_domain = "distributed" in ctx.parts or (
        ctx.parts and ctx.parts[-1] == "checkpoint.py"
    )
    if not in_domain or _is_test_file(ctx):
        return
    for node in ast.walk(ctx.tree):
        if not isinstance(node, ast.ExceptHandler):
            continue
        if not _handler_is_broad(node):
            continue
        consults = False
        for n in [x for st in node.body for x in ast.walk(st)]:
            if isinstance(n, ast.Raise):
                consults = True
                break
            if isinstance(n, ast.Call) and terminal_name(n.func) in (
                "is_transient", "classify",
            ):
                consults = True
                break
        if not consults:
            yield ctx.finding(
                "GL302", node,
                "broad except swallows the error class with_retries "
                "routes on; catch typed, re-raise, or consult "
                "is_transient (suppress with a reason if deliberate)",
            )


@register(
    "GL303", "sleep-in-retry-loop",
    "time.sleep inside an except handler inside a loop -- a hand-rolled "
    "retry loop; route through _common.with_retries",
)
def check_sleep_in_retry_loop(ctx):
    for node in ast.walk(ctx.tree):
        if not isinstance(node, ast.Call):
            continue
        if dotted_name(node.func) != "time.sleep":
            continue
        in_handler = in_loop = False
        for anc in ctx.ancestors(node):
            if isinstance(anc, ast.ExceptHandler):
                in_handler = True
            if isinstance(anc, (ast.For, ast.While)):
                in_loop = True
            if isinstance(anc, (ast.FunctionDef, ast.AsyncFunctionDef,
                                ast.Lambda)):
                break
        if in_handler and in_loop:
            yield ctx.finding(
                "GL303", node,
                "sleep-on-error inside a loop is a hand-rolled retry: "
                "use _common.with_retries (bounded, classified backoff)",
            )


#: state-serialization entry points whose output, written straight to a
#: file, is a checkpoint in the making
_STATE_DUMPERS = frozenset({"dump", "savez", "savez_compressed"})


def _is_state_dump(call):
    """pickle.dump / np.savez / np.savez_compressed with arguments."""
    dn = dotted_name(call.func)
    if dn is None or not call.args:
        return False
    parts = dn.split(".")
    if len(parts) != 2 or parts[1] not in _STATE_DUMPERS:
        return False
    return parts[0] == "pickle" or parts[0] in _NUMPY_MODULES


@register(
    "GL305", "state-dump-bypasses-durable-saver",
    "pickle.dump/np.savez writes state to a file with no fsync in the "
    "same function -- a crash publishes a truncated checkpoint; route "
    "through utils/checkpoint's durable savers (tmp+fsync+rename)",
)
def check_state_dump_bypasses_durable_saver(ctx):
    # the gap GL301 cannot see: a checkpoint written IN PLACE (no
    # rename at all, so GL301 never fires) is still torn by a crash
    # mid-dump -- the exact fmin.py:285 latent bug this rule pins
    if _is_test_file(ctx):
        return
    for scope in list(ctx.functions) + [ctx.tree]:
        if isinstance(scope, ast.Lambda):
            continue
        own = list(walk_scope(scope))
        dumps = [
            n for n in own if isinstance(n, ast.Call) and _is_state_dump(n)
        ]
        if not dumps:
            continue
        names = {
            terminal_name(n.func)
            for n in own
            if isinstance(n, ast.Call)
        }
        if "fsync" in names:
            continue  # durable-saver shape; rename ordering is GL301's job
        if "BytesIO" in names:
            continue  # in-memory serialization: nothing to make durable
        for n in dumps:
            yield ctx.finding(
                "GL305", n,
                f"{dotted_name(n.func)}() writes state with no fsync "
                "in scope: a crash mid-write (or before writeback) "
                "publishes a truncated checkpoint; use the durable "
                "savers in utils/checkpoint.py",
            )


#: method names that mark a class as a LONG-LIVED service object (it
#: runs/serves/pumps for the process lifetime, so per-event growth is a
#: leak, not a working buffer)
_SERVICE_METHODS = frozenset({
    "start", "stop", "step", "serve_forever", "pump", "shutdown",
    "drain", "_loop", "loop", "run_forever",
})

#: calls on the attribute that bound its growth
_BOUNDING_CALLS = frozenset({"pop", "popleft", "clear", "remove"})


def _self_attr(node, attrs):
    """``node`` is ``self.<attr>`` for an attr in ``attrs``?"""
    return (
        isinstance(node, ast.Attribute)
        and isinstance(node.value, ast.Name)
        and node.value.id == "self"
        and node.attr in attrs
    )


@register(
    "GL306", "unbounded-append-on-service-object",
    "a plain-list attribute of a long-lived service class grows by "
    "append with no bounding operation anywhere in the class -- a slow "
    "per-event leak; use a maxlen deque or trim it",
)
def check_unbounded_service_append(ctx):
    # the PR-8 review leak class: BatchScheduler.ask_latencies grew one
    # entry per ask forever until it became a maxlen ring buffer.  A
    # heuristic single-class dataflow: list attrs born in __init__,
    # appended to by the service's methods, never popped/cleared/
    # trimmed/rebound anywhere in the class.
    if _is_test_file(ctx):
        return
    for cls in ast.walk(ctx.tree):
        if not isinstance(cls, ast.ClassDef):
            continue
        methods = {
            n.name: n for n in cls.body
            if isinstance(n, (ast.FunctionDef, ast.AsyncFunctionDef))
        }
        if not (_SERVICE_METHODS & set(methods)):
            continue
        init = methods.get("__init__")
        if init is None:
            continue
        list_attrs = {
            t.attr
            for node in ast.walk(init)
            if isinstance(node, ast.Assign)
            and isinstance(node.value, ast.List)
            for t in node.targets
            if isinstance(t, ast.Attribute)
            and isinstance(t.value, ast.Name)
            and t.value.id == "self"
        }
        if not list_attrs:
            continue
        appends, bounded = {}, set()
        for name, fn in methods.items():
            for node in ast.walk(fn):
                if (
                    isinstance(node, ast.Call)
                    and isinstance(node.func, ast.Attribute)
                    and _self_attr(node.func.value, list_attrs)
                ):
                    attr = node.func.value.attr
                    if node.func.attr == "append" and name != "__init__":
                        appends.setdefault(attr, []).append(node)
                    elif node.func.attr in _BOUNDING_CALLS:
                        bounded.add(attr)
                elif isinstance(node, ast.Delete):
                    for t in node.targets:
                        tv = getattr(t, "value", None)
                        if isinstance(t, ast.Subscript) and _self_attr(
                            tv, list_attrs
                        ):
                            bounded.add(tv.attr)
                elif isinstance(node, ast.Assign) and name != "__init__":
                    for t in node.targets:
                        if _self_attr(t, list_attrs):
                            bounded.add(t.attr)  # rebound (swap/reset)
                        tv = getattr(t, "value", None)
                        if isinstance(t, ast.Subscript) and _self_attr(
                            tv, list_attrs
                        ):
                            bounded.add(tv.attr)  # slice trim
        for attr, nodes in appends.items():
            if attr in bounded:
                continue
            for node in nodes:
                yield ctx.finding(
                    "GL306", node,
                    f"self.{attr} grows by append on long-lived service "
                    f"class {cls.name} with no pop/clear/trim/rebind in "
                    "the class: a per-event leak on a process that "
                    "serves forever -- use collections.deque(maxlen=...)"
                    " or trim it",
                )


# the call names that mark a timing delta as ALREADY landing on a
# graftscope sink (Histogram.observe / ring append / Recorder.record /
# the *_since helpers): the delta is computed en route to the registry,
# which is the sanctioned place for it
_METRIC_SINKS = frozenset({
    "observe", "observe_since", "append", "record", "event",
    "set_duration_ms",
})

_TIME_SOURCES = frozenset({"time.time", "time.perf_counter"})

#: graftscope's own internals: the one place timing math and raw
#: accumulator attributes are the implementation, not ad-hoc state
_OBS_INTERNALS = frozenset({"registry.py", "flightrec.py"})

#: class-body descriptor factories that register an attribute on the
#: graftscope registry -- an attr declared this way is the MIGRATED
#: idiom GL307 exists to steer toward
_REGISTRY_DESCRIPTORS = frozenset({
    "CounterAttr", "GaugeAttr", "HistogramAttr",
})


def _feeds_metric_sink(ctx, node):
    """Is this expression an argument of a ``.observe(...)``-style
    call (directly or via an enclosing expression)?"""
    for anc in ctx.ancestors(node):
        if isinstance(anc, ast.stmt):
            return False
        if (
            isinstance(anc, ast.Call)
            and isinstance(anc.func, ast.Attribute)
            and anc.func.attr in _METRIC_SINKS
        ):
            return True
    return False


@register(
    "GL307", "ad-hoc-metric-state",
    "timing deltas (time.time()/perf_counter() subtraction) or public "
    "counter attributes accumulated outside the graftscope registry in "
    "serve//obs//distributed/ library code -- operational signals must "
    "live on the typed, bounded, scrapeable registry",
)
def check_adhoc_metric_state(ctx):
    in_domain = any(
        p in ("serve", "obs", "distributed") for p in ctx.parts[:-1]
    )
    if not in_domain or _is_test_file(ctx):
        return
    base = ctx.parts[-1] if ctx.parts else ""
    if "obs" in ctx.parts[:-1] and base in _OBS_INTERNALS:
        return
    # (a) inline timing deltas: a minus with a direct time.time()/
    # perf_counter() operand that is NOT en route to a registry sink
    for node in ast.walk(ctx.tree):
        if not (isinstance(node, ast.BinOp) and isinstance(node.op, ast.Sub)):
            continue
        for side in (node.left, node.right):
            if (
                isinstance(side, ast.Call)
                and dotted_name(side.func) in _TIME_SOURCES
                and not _feeds_metric_sink(ctx, node)
            ):
                yield ctx.finding(
                    "GL307", node,
                    f"ad-hoc {dotted_name(side.func)}() delta in library "
                    "code: land it on the graftscope registry "
                    "(Histogram.observe_since / Gauge.set_duration_ms) "
                    "so it is bounded, typed, and scrapeable",
                )
                break
    # (b) public numeric counter attrs (born as a literal in __init__)
    # accumulated by +=/-= in methods, with no registry descriptor of
    # that name on the class -- the pre-graftscope counter idiom
    for cls in ast.walk(ctx.tree):
        if not isinstance(cls, ast.ClassDef):
            continue
        descriptor_attrs = {
            t.id
            for node in cls.body
            if isinstance(node, ast.Assign)
            and isinstance(node.value, ast.Call)
            and terminal_name(node.value.func) in _REGISTRY_DESCRIPTORS
            for t in node.targets
            if isinstance(t, ast.Name)
        }
        methods = {
            n.name: n for n in cls.body
            if isinstance(n, (ast.FunctionDef, ast.AsyncFunctionDef))
        }
        init = methods.get("__init__")
        if init is None:
            continue
        counter_attrs = {
            t.attr
            for node in ast.walk(init)
            if isinstance(node, ast.Assign)
            and isinstance(node.value, ast.Constant)
            and isinstance(node.value.value, (int, float))
            and not isinstance(node.value.value, bool)
            for t in node.targets
            if isinstance(t, ast.Attribute)
            and isinstance(t.value, ast.Name)
            and t.value.id == "self"
            and not t.attr.startswith("_")
        } - descriptor_attrs
        if not counter_attrs:
            continue
        for name, fn in methods.items():
            if name == "__init__":
                continue
            for node in ast.walk(fn):
                if (
                    isinstance(node, ast.AugAssign)
                    and isinstance(node.op, (ast.Add, ast.Sub))
                    and _self_attr(node.target, counter_attrs)
                ):
                    yield ctx.finding(
                        "GL307", node,
                        f"self.{node.target.attr} is a hand-rolled "
                        f"counter on {cls.name}: declare it as a "
                        "graftscope CounterAttr/GaugeAttr so the "
                        "total is typed, labeled, and scrapeable",
                    )


#: the durability calls whose per-item cost group-commit amortizes: a
#: raw fsync and the durable-pickle saver (tmp+fsync+rename) -- one of
#: these per loop iteration is one storage barrier per item
_SYNC_CALLS = frozenset({"fsync", "durable_pickle"})


@register(
    "GL308", "fsync-in-hot-loop",
    "fsync/durable_pickle issued inside a for-loop in serve//"
    "distributed/ library code -- one storage barrier per item is the "
    "latency class group-commit retired (PR-6 flush-then-barrier, "
    "graftburst round barriers); flush per item, fsync ONCE after the "
    "loop (barrier helpers are exempt by name)",
)
def check_fsync_in_hot_loop(ctx):
    # the graftburst rule: a tell/round/batch loop that fsyncs every
    # iteration serializes the whole batch behind N storage barriers.
    # The sanctioned shape is flush-in-loop + one barrier after -- so
    # functions whose name carries "barrier" (TellWAL.barrier, the
    # scheduler's _barrier_round) are the fix, not the bug, and are
    # exempt wherever the sync call lands inside them.
    in_domain = any(
        p in ("serve", "distributed") for p in ctx.parts[:-1]
    )
    if not in_domain or _is_test_file(ctx):
        return
    for node in ast.walk(ctx.tree):
        if not (
            isinstance(node, ast.Call)
            and terminal_name(node.func) in _SYNC_CALLS
        ):
            continue
        in_loop = exempt = False
        for anc in ctx.ancestors(node):
            if isinstance(
                anc, (ast.FunctionDef, ast.AsyncFunctionDef)
            ) and "barrier" in anc.name:
                exempt = True
                break
            if isinstance(anc, ast.For) and ctx.enclosing_function(
                node
            ) is ctx.enclosing_function(anc):
                # same function scope: the sync runs once PER ITERATION
                # (a closure merely defined inside the loop does not)
                in_loop = True
                break
        if in_loop and not exempt:
            # keep climbing for a barrier-named enclosing helper
            exempt = any(
                isinstance(a, (ast.FunctionDef, ast.AsyncFunctionDef))
                and "barrier" in a.name
                for a in ctx.ancestors(node)
            )
        if in_loop and not exempt:
            yield ctx.finding(
                "GL308", node,
                f"{terminal_name(node.func)}() inside a for-loop: one "
                "storage barrier per item serializes the batch; flush "
                "in the loop and issue ONE barrier fsync after it "
                "(TellWAL.barrier / the group-commit round shape)",
            )


#: socket calls that block forever unless a deadline is in force: the
#: handle-makers (``makefile`` inherits the socket's timeout -- or its
#: absence) and the raw blocking reads/accepts
_SOCKET_DEADLINE_OPS = frozenset({"makefile", "recv", "recv_into", "accept"})

#: deadline evidence inside one function scope: an explicit
#: ``settimeout``, or the blessed :func:`~..serve.frames.dial` seam
#: (which carries both deadlines by construction)
_SOCKET_DEADLINE_EVIDENCE = frozenset({"settimeout", "dial"})


def _create_connection_has_timeout(call):
    if len(call.args) >= 2:
        return True
    return any(kw.arg == "timeout" for kw in call.keywords)


@register(
    "GL309", "socket-op-without-deadline",
    "create_connection/makefile/recv/accept in serve//distributed//"
    "client.py with no timeout in scope -- a silent peer blocks the "
    "thread forever; dial() (or settimeout before the op) is the "
    "graftstorm contract",
)
def check_socket_op_without_deadline(ctx):
    # the graftstorm rule: every socket op in the serve stack must run
    # under a deadline.  Heuristic, scope-local: a function that calls
    # settimeout, dial(), or create_connection(..., timeout=...) has
    # deadline evidence; a makefile/recv/accept (or a timeout-less
    # create_connection) in a scope WITHOUT evidence is the hung-read
    # shape the storm suite exposes.
    in_domain = any(
        p in ("serve", "distributed") for p in ctx.parts[:-1]
    ) or (ctx.parts and ctx.parts[-1] == "client.py")
    if not in_domain or _is_test_file(ctx):
        return
    for fn in ctx.functions:
        if isinstance(fn, ast.Lambda):
            continue
        own = list(walk_scope(fn))
        calls = [n for n in own if isinstance(n, ast.Call)]
        evidence = any(
            terminal_name(c.func) in _SOCKET_DEADLINE_EVIDENCE
            or (
                terminal_name(c.func) == "create_connection"
                and _create_connection_has_timeout(c)
            )
            for c in calls
        )
        for c in calls:
            name = terminal_name(c.func)
            if (
                name == "create_connection"
                and not _create_connection_has_timeout(c)
            ):
                yield ctx.finding(
                    "GL309", c,
                    "create_connection without a timeout: the connect "
                    "blocks for the OS default (minutes) and the socket "
                    "inherits NO read deadline -- use frames.dial() or "
                    "pass timeout=",
                )
            elif name in _SOCKET_DEADLINE_OPS and not evidence:
                yield ctx.finding(
                    "GL309", c,
                    f"{name}() with no deadline in scope: a silent or "
                    "half-open peer blocks this thread forever -- "
                    "settimeout first (or route through frames.dial)",
                )


_NP_GLOBAL_STATE = frozenset({
    "seed", "rand", "randn", "randint", "random", "uniform", "normal",
    "choice", "shuffle", "permutation", "standard_normal", "beta",
    "binomial", "get_state", "set_state", "sample", "random_sample",
    "exponential", "poisson", "lognormal", "multivariate_normal",
})


@register(
    "GL304", "np-random-global-state",
    "np.random global-state use outside tests -- seeded streams are the "
    "reproducibility contract (rstate/default_rng only)",
)
def check_np_random_global(ctx):
    if _is_test_file(ctx):
        return
    for node in ast.walk(ctx.tree):
        if not isinstance(node, ast.Call):
            continue
        dn = dotted_name(node.func)
        if dn is None:
            continue
        parts = dn.split(".")
        if (
            len(parts) == 3
            and parts[0] in _NUMPY_MODULES
            and parts[1] == "random"
            and parts[2] in _NP_GLOBAL_STATE
        ):
            yield ctx.finding(
                "GL304", node,
                f"{dn} mutates/reads numpy's process-global RNG: every "
                "draw must come from an explicit Generator (default_rng)",
            )
