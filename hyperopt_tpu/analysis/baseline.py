"""Findings baseline: grandfathered findings, committed next to the code.

Entries are keyed by ``(path, rule, content-hash)`` where the hash
covers the rule ID plus the *stripped text of the violating line* --
NOT the line number -- so unrelated edits above a grandfathered finding
do not invalidate the baseline, while any edit to the violating line
itself surfaces the finding again (the edit is the moment to fix it).

The baseline is a multiset: two identical violating lines in one file
need two entries, and fixing one of them shrinks the count.  The goal
is a file that is small and shrinking; ``--write-baseline`` regenerates
it, and the tier-1 test pins its size so it cannot silently grow.
"""

from __future__ import annotations

import collections
import json

__all__ = ["load_baseline", "write_baseline", "apply_baseline", "to_entries"]

BASELINE_VERSION = 1


def to_entries(findings):
    """Serializable baseline entries for ``findings`` (sorted, stable)."""
    counter = collections.Counter(
        (f.path, f.rule, f.content_hash(), f.source_line.strip())
        for f in findings
    )
    return [
        {
            "path": path,
            "rule": rule,
            "content_hash": h,
            "line": text,       # for humans reviewing the baseline diff
            "count": n,
        }
        for (path, rule, h, text), n in sorted(counter.items())
    ]


def write_baseline(path, findings):
    payload = {
        "version": BASELINE_VERSION,
        "entries": to_entries(findings),
    }
    with open(path, "w", encoding="utf-8") as f:
        json.dump(payload, f, indent=2, sort_keys=True)
        f.write("\n")
    return path


def load_baseline(path):
    """Load a baseline into a Counter keyed by (path, rule, hash)."""
    with open(path, encoding="utf-8") as f:
        payload = json.load(f)
    if payload.get("version") != BASELINE_VERSION:
        raise ValueError(
            f"baseline {path!r} has version {payload.get('version')!r}; "
            f"this engine reads version {BASELINE_VERSION}"
        )
    counter = collections.Counter()
    for e in payload.get("entries", []):
        counter[(e["path"], e["rule"], e["content_hash"])] += int(
            e.get("count", 1)
        )
    return counter


def apply_baseline(findings, counter):
    """Filter findings through the baseline multiset; each entry absorbs
    up to ``count`` occurrences.  Returns (new_findings, n_matched)."""
    remaining = collections.Counter(counter)
    kept, matched = [], 0
    for f in findings:
        key = (f.path, f.rule, f.content_hash())
        if remaining.get(key, 0) > 0:
            remaining[key] -= 1
            matched += 1
        else:
            kept.append(f)
    return kept, matched
