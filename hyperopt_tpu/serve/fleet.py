"""graftfleet: the horizontal serve fleet -- sharded replicas, claim/
epoch study ownership, WAL-backed migration, and failover.

The ROADMAP's "millions of users" tier (the Vizier-style service
architecture) composed from primitives that already exist:

* a study is a portable WAL+bundle pair (PR 6/8) rooted in a SHARED
  directory, so "migrating" a study is a snapshot + a claim handoff --
  nothing is copied, the new owner restores in place with tid-dedup
  exactly-once replay;
* each replica is an ordinary :class:`~hyperopt_tpu.serve.service.
  SuggestService` with a fleet identity (``owner=``): a per-study
  :class:`StudyClaim` -- the ``distributed/`` claim-token idiom at the
  study granularity, plus a monotone EPOCH -- fences every ask/tell,
  so a partitioned or zombie replica gets
  :class:`~hyperopt_tpu.exceptions.OwnershipLost` instead of
  double-serving a study that failed over;
* the :class:`Fleet` is the control plane: a consistent-hash ring
  (:class:`~hyperopt_tpu.serve.router.HashRing` salted with the study-
  family guard fingerprint) places studies on replicas, ``failover``
  re-materializes a dead replica's studies on ring survivors from
  their WAL+bundle pairs, and ``drain_replica`` runs the planned
  rolling-restart path (PR-9 drain protocol: typed
  ``Overloaded(reason="draining", retry_after=...)`` to clients,
  snapshot -> hand off -> new owner restores -> router repoints).

Determinism: placement is a pure function of (guard fingerprint,
study name, alive replicas); suggestion streams are pure functions of
(study seed, tell history) with submit-time seeds and WAL-logged
cursors, so a failed-over stream continues bitwise -- the fleet chaos
suite (``tests/test_fleet_chaos.py``) pins surviving streams against
the same-seed no-fault run.

Fencing caveat (documented, not hidden): the claim check and the WAL
append it guards are two filesystem operations, so a takeover landing
in the instruction window between them can still interleave one
record; production deployments put the claim on a lease (the file's
mtime) and fence at the storage layer.  The chaos suite exercises the
protocol-visible windows deterministically.
"""

from __future__ import annotations

import json
import logging
import os
import threading
import time
import uuid

from ..distributed import _common
from ..distributed.faults import REAL_FS
from ..exceptions import OwnershipLost, ReplicaDead
from ..obs.expo import tag_rows
from ..obs.registry import GaugeAttr, MetricsRegistry
from .router import HashRing
from .service import SuggestService, _study_guard

logger = logging.getLogger(__name__)

__all__ = ["StudyClaim", "Replica", "Fleet", "fleet_salt"]


def fleet_salt(algo, space):
    """The ring salt: the study-family guard fingerprint, so placement
    is deterministic across routers, processes, and runs."""
    return json.dumps(_study_guard(algo, space), sort_keys=True)


class StudyClaim:
    """Per-study ownership token at ``<root>/<name>.claim``.

    The file holds ``{"replica", "token", "epoch", "released"}``,
    published atomically (tmp + fsync + rename).  ``token`` is the
    uniqueness check (the filequeue claim idiom: a holder proves
    liveness by reading its own token back); ``epoch`` is the fencing
    counter -- every acquire and release bumps it, so any observer can
    totally order ownership changes and a zombie's stale epoch can
    never win an argument with the current owner.  ``release`` writes
    a tombstone (keeping the epoch monotone) rather than unlinking.
    """

    SUFFIX = ".claim"

    def __init__(self, path, replica, token, epoch, fs=REAL_FS):
        self.path = path
        self.replica = replica
        self.token = token
        self.epoch = int(epoch)
        self.fs = fs

    # -- reading -----------------------------------------------------------
    @staticmethod
    def path_for(root, name):
        return os.path.join(str(root), name + StudyClaim.SUFFIX)

    @classmethod
    def read(cls, root, name, fs=REAL_FS):
        """The current claim doc, or None when never claimed."""
        path = cls.path_for(root, name)

        def _read():
            if not fs.exists(path):
                return None
            with fs.open(path, "r") as f:
                return json.load(f)

        return _common.with_retries(_read, label="claim read")

    # -- acquiring ---------------------------------------------------------
    @classmethod
    def acquire(cls, root, name, replica, fs=REAL_FS, takeover=False):
        """Claim the study for ``replica``; returns the live claim.

        A study live-owned by ANOTHER replica is refused with
        :class:`OwnershipLost` unless ``takeover=True`` -- the router/
        fleet failover path, which is the only authority entitled to
        declare an owner dead.  The publish is last-writer-wins
        (atomic rename) followed by a read-back: losing the race to a
        concurrent claimant surfaces as :class:`OwnershipLost`, never
        as two winners."""
        fs.makedirs(str(root), exist_ok=True)
        cur = cls.read(root, name, fs=fs)
        if (
            cur is not None
            and not cur.get("released")
            and cur.get("replica") not in (None, replica)
            and not takeover
        ):
            raise OwnershipLost(
                f"study {name!r} is owned by replica "
                f"{cur['replica']!r} (epoch {cur.get('epoch')}); only "
                "the failover/migration path may take it over"
            )
        epoch = (int(cur.get("epoch", -1)) + 1) if cur is not None else 0
        claim = cls(
            cls.path_for(root, name), str(replica), uuid.uuid4().hex,
            epoch, fs=fs,
        )
        claim._publish({
            "replica": claim.replica, "token": claim.token,
            "epoch": claim.epoch, "released": False,
        })
        back = cls.read(root, name, fs=fs)
        if back is None or back.get("token") != claim.token:
            raise OwnershipLost(
                f"lost the claim race for study {name!r} to "
                f"{(back or {}).get('replica')!r}"
            )
        return claim

    def _publish(self, doc):
        def _write():
            tmp = f"{self.path}.tmp.{os.getpid()}"
            with self.fs.open(tmp, "w") as f:
                f.write(json.dumps(doc, sort_keys=True))
                self.fs.fsync(f)
            self.fs.crashpoint("fleet_claim_tmp_before_rename")
            self.fs.rename(tmp, self.path)

        _common.with_retries(_write, label="claim publish")

    # -- fencing -----------------------------------------------------------
    def is_live(self):
        """Whether this replica still owns the study: the claim file
        carries OUR token.  False after any takeover or release."""
        def _read():
            if not self.fs.exists(self.path):
                return None
            with self.fs.open(self.path, "r") as f:
                return json.load(f)

        cur = _common.with_retries(_read, label="claim check")
        return (
            cur is not None
            and not cur.get("released")
            and cur.get("token") == self.token
        )

    def ensure_live(self):
        if not self.is_live():
            raise OwnershipLost(
                f"replica {self.replica!r} no longer holds the claim "
                f"for {os.path.basename(self.path)!r} (taken over or "
                "released); dropping the operation instead of "
                "double-serving"
            )

    def release(self, handoff=False):
        """Tombstone the claim (epoch bumped, monotone) -- the planned
        handoff half of migration.  A crashed owner never releases;
        its successor takes over with ``acquire(takeover=True)``.

        ``handoff=True`` marks the tombstone as the SOURCE half of a
        migration: the releasing replica expects a new owner to adopt
        next.  The next ``acquire`` (adoption) overwrites the marker;
        a marker still on disk is therefore a study stranded between
        handoff and restore -- the ``study_half_migrated`` artifact
        ``hyperopt-tpu-fsck --serve`` reports on cross-host audits."""
        if not self.is_live():
            return  # taken over already; nothing of ours to release
        self.epoch += 1
        doc = {
            "replica": None, "token": None,
            "epoch": self.epoch, "released": True,
        }
        if handoff:
            doc["handoff"] = True
        self._publish(doc)


class Replica:
    """One fleet member: a fleet-identified ``SuggestService`` plus
    the liveness flags the in-process harness needs (``dead`` -- the
    process is gone; ``partitioned`` -- alive but unreachable from the
    router, the zombie case the claim epochs exist for)."""

    def __init__(self, rid, service):
        self.rid = str(rid)
        self.service = service
        self.dead = False
        self.partitioned = False

    def _check(self):
        if self.dead:
            raise ReplicaDead(f"replica {self.rid!r} is dead")

    def _handle(self, name):
        svc = self.service
        with svc._lock:
            handle = svc._handles.get(name)
        if handle is None and svc.root is not None:
            # lazy adoption: the router routed this study here (ring
            # owner), so any artifacts in the shared root are ours to
            # restore -- the failover / aborted-migration heal path
            handle = svc.create_study(name, takeover=True)
        if handle is None:
            raise ValueError(f"study {name!r} unknown on {self.rid!r}")
        return handle

    # -- the ops the router forwards ---------------------------------------
    def open_study(self, name, seed=0, takeover=False):
        self._check()
        return self.service.create_study(name, seed=seed, takeover=takeover)

    def ask(self, name, timeout=60.0, recover=False):
        self._check()
        return self._handle(name).ask(timeout=timeout, recover=recover)

    def ask_async(self, name):
        self._check()
        return self._handle(name).ask_async()

    def tell(self, name, tid, loss, vals=None):
        self._check()
        return self._handle(name).tell(tid, loss, vals=vals)

    def best(self, name):
        self._check()
        return self._handle(name).best()

    def close_study(self, name):
        self._check()
        self.service.close_study(name)

    def pump_until(self, futures, timeout=60.0):
        """Deterministic-mode gather: pump coalesced rounds until every
        future resolves (crashes propagate to the caller -- the router
        is the failure detector)."""
        self._check()
        deadline = time.perf_counter() + float(timeout)
        while not all(f.done() for f in futures):
            if self.service.pump() == 0:
                if time.perf_counter() > deadline:
                    raise TimeoutError(
                        f"replica {self.rid!r}: batch not served within "
                        f"{timeout}s"
                    )
                time.sleep(0.001)

    # -- liveness ----------------------------------------------------------
    def die(self):
        """Crash semantics: the process is gone.  No snapshots, no
        claim releases -- just drop the file handles a dead process
        would drop, and refuse every future op."""
        if self.dead:
            return
        self.dead = True
        for st in list(self.service.scheduler._studies.values()):
            if st.persist is not None:
                st.persist.wal.close()


class Fleet:
    """The control plane: replicas + ring + registry + failover.

    ``plans`` maps replica id -> :class:`~hyperopt_tpu.distributed.
    faults.FaultPlan` (arm crash points / storms per replica); ``fs``
    is the FLEET MANAGER's own seam, carrying the migration crash
    point between handoff and restore.  ``service_kw`` passes through
    to every replica's ``SuggestService`` (batch sizes, algo params --
    keep them identical across replicas or streams stop being
    placement-independent)."""

    #: last failover's re-materialization time (ms) -- a graftscope
    #: gauge behind the historic attribute name (None until the first
    #: failover, exactly as before)
    recovery_ms = GaugeAttr(
        "fleet_recovery_ms",
        "last failover's study re-materialization time",
    )

    def __init__(self, space, root, n_replicas=3, algo="tpe",
                 replica_ids=None, plans=None, fs=REAL_FS, vnodes=64,
                 **service_kw):
        self.metrics = MetricsRegistry("fleet")
        self.space = space
        self.root = str(root)
        self.algo = str(algo)
        self.fs = fs
        self.service_kw = dict(service_kw)
        self.salt = fleet_salt(algo, space)
        self.ring = HashRing(salt=self.salt, vnodes=vnodes)
        self.replicas = {}
        self.registry = set()  # studies created through the router
        self._moved = {}  # name -> rid: migration repoints ahead of ring
        # membership lock: scale-out, scale-in, and failover all move
        # claims, and two of them interleaving (the autoscaler racing
        # the router's failure handling) could double-adopt a study or
        # strand it between owners.  Every membership mutation runs
        # under this single RLock, so racing paths serialize and each
        # sees the other's completed placement -- the claim-epoch fence
        # below stays the cross-process guarantee, this lock is the
        # in-process one.
        self._mlock = threading.RLock()
        plans = plans or {}
        for rid in replica_ids or [f"r{i}" for i in range(n_replicas)]:
            plan = plans.get(rid)
            self.add_replica(
                rid, fs=None if plan is None else plan.fs(), migrate=False
            )

    # -- membership --------------------------------------------------------
    def add_replica(self, rid, fs=None, migrate=True):
        """Join a replica.  With ``migrate=True`` (scale-out / rolling
        replacement), the registered studies whose ring owner becomes
        the new replica are handed over via the drain-migrate protocol
        BEFORE the ring flips -- adding a node moves ~1/N of the keys
        and nothing else.

        Crash window (``pilot_mid_scale_out``, armed on the FLEET
        plan): the coordinator dies after the first remapped study
        moved -- the ring already includes the new replica, the rest
        of the remapped keys have not.  Recovery is the ordinary lazy-
        adoption path: the new ring owner adopts each stranded study
        with ``create_study(takeover=True)`` on its first routed
        request; re-running ``add_replica`` is NOT the heal (the rid is
        already a member and is refused)."""
        with self._mlock:
            rid = str(rid)
            if rid in self.replicas:
                raise ValueError(f"replica {rid!r} already in the fleet")
            service = SuggestService(
                self.space, algo=self.algo, root=self.root,
                fs=fs if fs is not None else REAL_FS, owner=rid,
                background=False, **self.service_kw,
            )
            replica = Replica(rid, service)
            before = (
                self.ring.placement(self.registry)
                if migrate and self.registry else {}
            )
            self.replicas[rid] = replica
            self.ring.add(rid)
            if before:
                after = self.ring.placement(self.registry)
                moved = 0
                for name in sorted(self.registry):
                    if after[name] == rid and before[name] != rid:
                        self.migrate_study(name, rid, src_rid=before[name])
                        moved += 1
                        if moved == 1:
                            self.fs.crashpoint("pilot_mid_scale_out")
            return replica

    def register(self, name):
        with self._mlock:
            self.registry.add(name)

    def unregister(self, name):
        with self._mlock:
            self.registry.discard(name)
            self._moved.pop(name, None)

    def route(self, name):
        """The replica currently serving ``name``: a migration
        override when one is pending, else the ring owner."""
        with self._mlock:
            rid = self._moved.get(name)
            if rid is not None and rid in self.ring.nodes:
                return rid
            return self.ring.owner(name)

    # -- failure handling --------------------------------------------------
    def mark_dead(self, rid):
        """The router observed ``rid`` fail.  A partitioned replica is
        left running (the zombie the claim epochs fence); anything
        else gets crash semantics."""
        replica = self.replicas.get(rid)
        if replica is None or replica.partitioned:
            return
        replica.die()

    def kill_replica(self, rid):
        """Simulate external replica death (the chaos harness's kill
        -9): crash semantics now, failover when the router notices."""
        self.replicas[rid].die()

    def partition(self, rid):
        """Partition a replica away from the router: the router fails
        its studies over, while the replica itself keeps running as a
        zombie whose fenced ops must all raise ``OwnershipLost``."""
        self.replicas[rid].partitioned = True

    def heal(self, rid):
        """The partition lifts (graftstorm): the replica was alive the
        whole time and rejoins the ring.  Its resident study handles
        still carry pre-partition claims, so its first routed op per
        study raises ``OwnershipLost`` -- the router's adoption path
        re-claims with ``create_study(takeover=True)`` (epoch bumped,
        WAL-restored from the shared root) and the rejoin is client-
        invisible.  Idempotent; a no-op for dead or unknown rids."""
        with self._mlock:
            replica = self.replicas.get(rid)
            if replica is None or replica.dead:
                return
            replica.partitioned = False
            self.ring.add(rid)  # failover removed it; re-placement is
            # the same ~1/N key move as any membership change

    def failover(self, rid):
        """Re-materialize a dead replica's studies on ring survivors
        from their WAL+bundle pairs (tid-dedup exactly-once replay,
        claim epochs bumped).  Idempotent; returns the moved names."""
        with self._mlock:
            if rid not in self.ring.nodes:
                return []
            t0 = time.perf_counter()
            owned = [
                n for n in sorted(self.registry) if self.route(n) == rid
            ]
            self.ring.remove(rid)
            self._moved = {
                n: r for n, r in self._moved.items() if r != rid
            }
            for name in owned:
                new_rid = self.ring.owner(name)
                self.replicas[new_rid].open_study(name, takeover=True)
                logger.info(
                    "failover: study %r re-materialized on %r (was %r)",
                    name, new_rid, rid,
                )
            self.metrics.gauge(
                "fleet_recovery_ms",
                "last failover's study re-materialization time",
            ).set_duration_ms(t0)
            self.metrics.counter(
                "fleet_failovers_total", "replica failovers executed"
            ).inc()
            return owned

    # -- planned migration (the drain protocol) ----------------------------
    def migrate_study(self, name, dst_rid, src_rid=None):
        """Snapshot -> hand off -> new owner restores -> repoint.

        Idempotent across coordinator crashes: a re-run skips the
        handoff when the source already released the study (the
        ``after_handoff_before_restore`` window) and the restore when
        the target already adopted it."""
        with self._mlock:
            src_rid = src_rid if src_rid is not None else self.route(name)
            if src_rid == dst_rid:
                return
            src = self.replicas[src_rid]
            if not src.dead and name in src.service.studies():
                src.service.handoff_study(name)
            self.fs.crashpoint("fleet_migrate_after_handoff_before_restore")
            self.replicas[dst_rid].open_study(name, takeover=True)
            self._moved[name] = dst_rid

    def begin_drain(self, rid, timeout=30.0):
        """Mark the replica draining: new asks are refused with
        ``Overloaded(reason="draining", retry_after=<time left until
        the drain deadline>)`` while migration proceeds."""
        self.replicas[rid].service.drain(timeout=timeout, block=False)

    def complete_drain(self, rid):
        """Migrate every owned study to its ring successor, flip the
        ring, shut the replica down.  Returns the migrated names."""
        with self._mlock:
            replica = self.replicas[rid]
            owned = [
                n for n in sorted(self.registry) if self.route(n) == rid
            ]
            for name in owned:
                dst = self.ring.owner(name, exclude={rid})
                self.migrate_study(name, dst, src_rid=rid)
            self.ring.remove(rid)
            self._moved = {
                n: r for n, r in self._moved.items()
                if n in self.registry and self.ring.owner(n) != r
            }
            replica.service.shutdown()
            replica.dead = True
            del self.replicas[rid]
            return owned

    def drain_replica(self, rid, timeout=30.0):
        """The full rolling-restart step for one replica."""
        self.begin_drain(rid, timeout=timeout)
        return self.complete_drain(rid)

    # -- observability -----------------------------------------------------
    def health(self):
        return {
            rid: (
                {"status": "dead"} if r.dead
                else {"partitioned": True, **r.service.health()}
                if r.partitioned else r.service.health()
            )
            for rid, r in sorted(self.replicas.items())
        }

    def metrics_rows(self):
        """graftscope exposition for the whole (in-process) fleet: the
        control plane's own series plus every live replica's, each
        tagged with its replica id."""
        rows = list(self.metrics.collect())
        for rid, r in sorted(self.replicas.items()):
            if not r.dead:
                rows.extend(tag_rows(r.service.metrics_rows(), replica=rid))
        return rows

    def counters(self):
        """Fleet-aggregate deterministic counters (summed)."""
        total = {}
        for r in self.replicas.values():
            if r.dead:
                continue
            for k, v in r.service.counters.items():
                total[k] = total.get(k, 0) + v
        total["replicas_alive"] = sum(
            1 for r in self.replicas.values() if not r.dead
        )
        return total

    def wal_fsyncs_per_tell(self):
        """Fleet-wide fsync amortization: WAL fsyncs issued per tell
        absorbed.  Per-tell fsync pins this at >= 1.0; group-commit
        (graftburst) drops it toward 1/round-size -- the bench stamps
        it as ``wal_fsyncs_per_tell``."""
        c = self.counters()
        tells = c.get("wal_tells", 0)
        return (c.get("wal_fsyncs", 0) / tells) if tells else 0.0

    def shutdown(self):
        for r in self.replicas.values():
            if not r.dead:
                r.service.shutdown()
