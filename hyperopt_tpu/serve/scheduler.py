"""The continuous-batching scheduler: coalesce many studies' asks into
one device dispatch.

The LLM-serving idiom applied to the ask/tell plugin boundary: incoming
asks queue up; a dispatch round picks at most one ask per study, fills
a SLOTTED batch (fixed pow2 slot capacities + an active-slot mask, so
studies join and leave without retracing -- :func:`~hyperopt_tpu.serve.
batched.slot_capacity`), rides every slot's staged O(D) tell delta
along, and runs ONE :func:`~hyperopt_tpu.serve.batched.
build_batched_step_fn` program for the whole round.  A background
thread drives rounds under a latency/occupancy budget (``max_wait``
deadline after the oldest queued ask, early dispatch once every joined
study has an ask queued); tests and the chaos suite drive :meth:`
BatchScheduler.step` synchronously instead, so simulated crashes
propagate to the caller.

Determinism: each study draws its per-ask seed from its OWN
``np.random.Generator`` stream at SUBMIT time, so the suggestion
sequence of a study is a pure function of its seed and its own
tell history -- independent of batching order, sibling churn, or slot
placement (the 64-study bitwise pin in ``tests/test_serve.py``).

Tells are absorbed synchronously: WAL append (durability first), host
``ObsBuffer.add``, then an O(D) delta staged for the slot -- exactly
the PR-4 resident-mirror protocol, per slot.  A backlog past one delta
drains through the batched masked-delta program; out-of-order (late)
tells and bucket growth re-materialize the stacked state from host
truth, the same log schedule as the solo resident mirror.
"""

from __future__ import annotations

import collections
import logging
import threading
import time
from concurrent.futures import Future

import numpy as np

from ..distributed.faults import REAL_FS
from ..jax_trials import MAX_PENDING_DELTAS, MIN_CAPACITY, ObsBuffer
from .batched import (
    StudyBatchState,
    _dummy_delta,
    build_batched_delta_fn,
    build_batched_step_fn,
    slot_capacity,
    stack_states,
)

logger = logging.getLogger(__name__)

__all__ = ["BatchScheduler", "ServeStudy", "dense_to_vals"]

#: ring-buffer length for the timing metrics (``ask_latencies`` /
#: ``occupancy``): plenty for any bench window, bounded for a
#: long-running service
METRICS_WINDOW = 65536


def dense_to_vals(ps, col_v, col_a):
    """One dense suggestion column -> the {label: value} config dict at
    API types (ints for categorical-family dims, inactive conditional
    dims omitted) -- the serve twin of ``tpe_jax._cast_vals``."""
    cat = {int(d) for d in ps.cat_idx}
    vals = {}
    for d, label in enumerate(ps.labels):
        if col_a[d]:
            v = float(col_v[d])
            vals[label] = int(round(v)) if d in cat else v
    return vals


class ServeStudy:
    """One tenant: host-truth history + seed stream + slot bookkeeping.

    The host :class:`~hyperopt_tpu.jax_trials.ObsBuffer` is
    authoritative (exactly as in the solo resident path); the device
    only ever holds a slot-wise mirror of it.
    """

    def __init__(self, name, seed, ps):
        self.name = name
        self.seed = int(seed)
        self.rstate = np.random.default_rng(self.seed)
        self.buf = ObsBuffer(ps)
        self.slot = None
        self.pending = collections.deque()  # staged (vcol, acol, loss, idx)
        self.dirty = True  # device slot needs re-materialization
        self.closed = False
        self.next_tid = 0
        self.n_asks = 0
        self.n_tells = 0
        self.outstanding = {}  # tid -> served vals (awaiting their tell)
        self.persist = None  # durability hooks (service wires them)

    def best(self):
        """(loss, vals) of the best finite completed trial, or None --
        recomputed from the buffer, so it survives restore for free."""
        buf = self.buf
        ok = buf.valid[: buf.count] & np.isfinite(buf.losses[: buf.count])
        if not ok.any():
            return None
        i = int(np.argmin(np.where(ok, buf.losses[: buf.count], np.inf)))
        return float(buf.losses[i]), dense_to_vals(
            buf.space, buf.values[:, i], buf.active[:, i]
        )


class _AskRequest:
    __slots__ = ("study", "tid", "seed", "future", "t_submit")

    def __init__(self, study, tid, seed):
        self.study = study
        self.tid = tid
        self.seed = seed
        self.future = Future()
        self.t_submit = time.perf_counter()


class BatchScheduler:
    """The slotted continuous-batching engine for one space template.

    ``max_batch`` caps the slot capacity (and so the number of
    concurrently open studies); ``max_wait`` is the latency budget a
    queued ask may wait for co-batching before the background loop
    dispatches anyway.  ``algo`` is ``"tpe"`` or ``"anneal"``;
    ``algo_kw`` passes through to :func:`~hyperopt_tpu.serve.batched.
    build_batched_step_fn`.  ``fs`` is the PR-3 fault-injection seam --
    the serve chaos points fire through it.

    Deterministic counters (never timing): ``dispatch_count`` (batched
    step programs run), ``delta_drain_dispatches`` (backlog-drain
    programs, included in ``dispatch_count``), ``upload_events`` /
    ``upload_bytes`` (stacked re-materializations), ``joins``,
    ``rebuckets``.  ``ask_latencies`` / ``occupancy`` feed the bench.
    """

    def __init__(self, ps, algo="tpe", max_batch=64, max_wait=0.002,
                 n_startup_jobs=20, fs=REAL_FS, **algo_kw):
        self.ps = ps
        self.algo = str(algo)
        self.max_batch = int(max_batch)
        self.max_wait = float(max_wait)
        self.n_startup_jobs = int(n_startup_jobs)
        self.fs = fs
        self.algo_kw = dict(algo_kw)
        if self.algo == "tpe":
            from ..tpe_jax import _resolve_above_cap

            self._pow2_cap = _resolve_above_cap(
                self.algo_kw.get("above_cap")
            )
        else:
            self._pow2_cap = None
        self._step_fn = build_batched_step_fn(
            ps, algo=self.algo, **self.algo_kw
        )
        self._delta_fn = build_batched_delta_fn()

        self._lock = threading.RLock()
        self._cond = threading.Condition(self._lock)
        self._asks = collections.deque()
        self._studies = {}
        self._slots = {}  # slot index -> ServeStudy
        self._free = []
        self._state = None  # StudyBatchState (device)
        self._slot_cap = 0
        self._bucket = MIN_CAPACITY
        self._materialize = True
        self._thread = None
        self._stopping = False

        # deterministic accounting
        self.dispatch_count = 0
        self.delta_drain_dispatches = 0
        self.upload_events = 0
        self.upload_bytes = 0
        self.joins = 0
        self.rebuckets = 0
        # bounded: bench metrics on a long-running service must not
        # grow one entry per ask forever (slow leak at scale)
        self.ask_latencies = collections.deque(maxlen=METRICS_WINDOW)
        self.occupancy = collections.deque(maxlen=METRICS_WINDOW)

    # -- tenancy -----------------------------------------------------------
    def open_study(self, name, seed=0, study=None):
        """Join a (new or restored) study to the slotted batch."""
        with self._lock:
            if name in self._studies:
                raise ValueError(f"study {name!r} already open")
            if len(self._studies) >= self.max_batch:
                raise ValueError(
                    f"batch capacity {self.max_batch} studies reached; "
                    "close a study or raise max_batch"
                )
            st = study if study is not None else ServeStudy(
                name, seed, self.ps
            )
            if self._free:
                st.slot = self._free.pop()
            else:
                st.slot = len(self._studies)
            st.dirty = True
            self._studies[name] = st
            self._slots[st.slot] = st
            self.joins += 1
            self._materialize = True
            return st

    def close_study(self, name):
        """Leave: free the slot (device data becomes garbage behind the
        active-slot mask -- siblings are untouched, no re-upload)."""
        with self._lock:
            st = self._studies.pop(name)
            st.closed = True
            self._slots.pop(st.slot, None)
            self._free.append(st.slot)
            self._free.sort(reverse=True)  # reuse lowest slots first
            st.slot = None
            return st

    def study(self, name):
        with self._lock:
            return self._studies[name]

    # -- tell --------------------------------------------------------------
    def tell(self, study, tid, vals, loss):
        """Absorb one completed trial: WAL first, host buffer second,
        device delta staged third.  Synchronous -- the durability
        barrier is the WAL append, and the host add is O(D).

        Idempotent by tid: a client re-telling work whose ack a
        crashed service lost (the tell may already have been WAL-
        replayed on restore) is absorbed exactly once."""
        with self._lock:
            buf = study.buf
            if (buf.tids[: buf.count] == int(tid)).any():
                study.outstanding.pop(tid, None)
                return
            if study.persist is not None:
                study.persist.log_tell(tid, vals, loss)
            self.fs.crashpoint("serve_after_wal_before_dispatch")
            self._apply_tell(study, tid, vals, loss)
            study.outstanding.pop(tid, None)

    def _apply_tell(self, study, tid, vals, loss):
        """Host-side tell application (shared with WAL replay, which
        must skip the durability hooks it is replaying from)."""
        buf = study.buf
        n = buf.count
        in_order = n == 0 or tid > int(buf.tids[n - 1])
        buf.add(dict(vals), float(loss), tid=int(tid))
        study.n_tells += 1
        study.next_tid = max(study.next_tid, int(tid) + 1)
        if (
            in_order
            and not study.dirty
            and len(study.pending) < MAX_PENDING_DELTAS
        ):
            study.pending.append((
                n,
                buf.values[:, n].copy(),
                buf.active[:, n].copy(),
                float(loss),
            ))
        else:
            # late completion shifted the tail (or the backlog is past
            # the crossover): slot re-materializes from host truth
            study.dirty = True
            study.pending.clear()

    # -- ask ---------------------------------------------------------------
    def submit_ask(self, study):
        """Queue one ask; returns ``(tid, Future)``.  The per-ask seed
        is drawn HERE, from the study's own stream -- the batching
        order downstream can no longer affect the suggestion."""
        with self._lock:
            if self._stopping:
                raise RuntimeError("suggestion service shutting down")
            if study.closed:
                raise ValueError(f"study {study.name!r} is closed")
            seed = int(study.rstate.integers(2**31 - 1))
            tid = study.next_tid
            study.next_tid = tid + 1
            study.n_asks += 1
            if study.persist is not None:
                study.persist.log_ask(tid, seed, study.rstate)
            req = _AskRequest(study, tid, seed)
            self._asks.append(req)
            self._cond.notify_all()
            return tid, req.future

    # -- the dispatch round ------------------------------------------------
    def _compute_bucket(self):
        b = MIN_CAPACITY
        for st in self._slots.values():
            b = max(b, st.buf._device_bucket(self._pow2_cap))
        return b

    def _rematerialize(self, slot_cap, bucket):
        buffers = {st.slot: st.buf for st in self._slots.values()}
        if not buffers:
            self._state = None
            return
        self._state, nbytes = stack_states(buffers, slot_cap, bucket)
        self.upload_events += 1
        self.upload_bytes += nbytes
        for st in self._slots.values():
            st.dirty = False
            st.pending.clear()  # host truth already includes them

    def _maintain(self):
        """Bring the stacked state up to date with tenancy/host truth:
        slot-capacity growth, obs-bucket growth, joins, dirty slots --
        all absorbed by ONE re-materialization; then drain any
        remaining multi-delta backlog down to one staged tell per slot
        (the fused dispatch absorbs the last one)."""
        # size from the HIGHEST occupied slot, not the study count:
        # churn can leave survivors on slots >= len(self._studies)
        # (closed studies free their low slots, survivors keep high
        # ones), and stack_states must cover every occupied index
        top_slot = max(self._slots, default=-1)
        slot_cap = max(
            slot_capacity(top_slot + 1, self.max_batch),
            self._slot_cap,  # capacities never shrink mid-flight
        )
        bucket = self._compute_bucket()
        if slot_cap != self._slot_cap or bucket != self._bucket:
            if self._state is not None:
                self.rebuckets += 1
            self._materialize = True
        if any(st.dirty for st in self._slots.values()):
            self._materialize = True
        if self._materialize:
            self._slot_cap, self._bucket = slot_cap, bucket
            self._rematerialize(slot_cap, bucket)
            self._materialize = False
            return
        # backlog drain: one masked delta per slot per dispatch, FIFO
        while any(len(st.pending) > 1 for st in self._slots.values()):
            vcol, acol, dloss, didx, dapply = _dummy_delta(
                self.ps, self._slot_cap
            )
            for st in self._slots.values():
                if len(st.pending) > 1:
                    n, vc, ac, lo = st.pending.popleft()
                    vcol[st.slot] = vc
                    acol[st.slot] = ac
                    dloss[st.slot] = lo
                    didx[st.slot] = n
                    dapply[st.slot] = True
            out = self._delta_fn(
                *self._state, vcol, acol, dloss, didx, dapply
            )
            self._state = StudyBatchState(*out)
            self.dispatch_count += 1
            self.delta_drain_dispatches += 1

    def _pick_round(self):
        """At most one queued ask per study this round, FIFO."""
        picked, leftover, seen = [], collections.deque(), set()
        while self._asks:
            req = self._asks.popleft()
            if req.study.closed:
                req.future.set_exception(
                    ValueError(f"study {req.study.name!r} closed")
                )
                continue
            if id(req.study) in seen or len(picked) >= self.max_batch:
                leftover.append(req)
                continue
            seen.add(id(req.study))
            picked.append(req)
        self._asks = leftover
        return picked

    def step(self):
        """One dispatch round: returns the number of asks served.
        Synchronous entry point -- the background loop calls this, and
        tests/chaos harnesses call it directly so crashes propagate."""
        with self._lock:
            picked = self._pick_round()
            if not picked:
                # tells without asks stay staged (or dirty) until the
                # next ask round -- a tell-only window never dispatches
                return 0
            try:
                return self._dispatch_round(picked)
            except BaseException as e:
                # _pick_round already popped these off the queue: a
                # failed dispatch must fail their futures too, or
                # clients blocked in ask() hang out their full timeout
                for req in picked:
                    if not req.future.done():
                        req.future.set_exception(e)
                raise

    def _dispatch_round(self, picked):
        """Serve one picked round (lock held): maintain the stacked
        state, run the batched program, ack every pick."""
        import jax
        import jax.numpy as jnp

        from ..jax_trials import host_key

        self._maintain()
        s = self._slot_cap
        dummy = host_key(0)
        keys = [dummy] * s
        warm = np.zeros(s, dtype=bool)
        vcol, acol, dloss, didx, dapply = _dummy_delta(self.ps, s)
        for st in self._slots.values():
            if st.pending:  # at most one left after _maintain
                n, vc, ac, lo = st.pending.popleft()
                vcol[st.slot] = vc
                acol[st.slot] = ac
                dloss[st.slot] = lo
                didx[st.slot] = n
                dapply[st.slot] = True
            warm[st.slot] = (
                st.buf.count > 0
                if self.algo == "anneal"
                else st.buf.count >= self.n_startup_jobs
            )
        for req in picked:
            keys[req.study.slot] = host_key(req.seed % (2**31 - 1))
        self.fs.crashpoint("serve_mid_batch")
        out = self._step_fn(
            jnp.stack(keys), *self._state, vcol, acol, dloss, didx,
            dapply, warm, batch=1,
        )
        self._state = StudyBatchState(*out[:4])
        self.dispatch_count += 1
        new_v, new_a = jax.device_get((out[4], out[5]))
        new_v = np.asarray(new_v)
        new_a = np.asarray(new_a)
        self.fs.crashpoint("serve_after_dispatch_before_ack")
        now = time.perf_counter()
        self.occupancy.append(len(picked) / s)
        results = []
        for req in picked:
            st = req.study
            vals = dense_to_vals(
                self.ps, new_v[st.slot, :, 0], new_a[st.slot, :, 0]
            )
            if st.persist is not None:
                st.persist.log_served(req.tid, vals)
            st.outstanding[req.tid] = vals
            self.ask_latencies.append(now - req.t_submit)
            results.append((req, vals))
        # acks last: a crash above leaves every pick un-acked and
        # replayable, never half-acked
        for req, vals in results:
            req.future.set_result((req.tid, vals))
        return len(picked)

    # -- background loop ---------------------------------------------------
    def start(self):
        """Run the continuous-batching loop on a daemon thread."""
        with self._lock:
            if self._thread is not None:
                return
            self._stopping = False
            self._thread = threading.Thread(
                target=self._loop, name="graftserve-batcher", daemon=True
            )
            self._thread.start()

    def stop(self):
        with self._lock:
            self._stopping = True
            self._cond.notify_all()
            t = self._thread
            self._thread = None
            # a stopping batcher must not strand blocked clients:
            # drain the queue and fail every pending ask promptly
            # instead of letting ask() hang out its full timeout
            while self._asks:
                req = self._asks.popleft()
                if not req.future.done():
                    req.future.set_exception(
                        RuntimeError("suggestion service shutting down")
                    )
        if t is not None:
            t.join(timeout=5.0)

    def _ready(self):
        """Dispatch early once every open study has an ask queued (or
        the queue already fills the batch)."""
        distinct = {id(r.study) for r in self._asks}
        return len(distinct) >= min(
            max(len(self._studies), 1), self.max_batch
        )

    def _loop(self):
        while True:
            with self._cond:
                while not self._asks and not self._stopping:
                    self._cond.wait(timeout=0.05)
                if self._stopping:
                    return
                deadline = self._asks[0].t_submit + self.max_wait
                while (
                    not self._stopping
                    and not self._ready()
                    and (remaining := deadline - time.perf_counter()) > 0
                ):
                    self._cond.wait(timeout=min(remaining, 0.05))
                if self._stopping:
                    return
            try:
                self.step()
            except BaseException:
                # a dying batcher must not strand blocked clients
                with self._lock:
                    while self._asks:
                        req = self._asks.popleft()
                        req.future.set_exception(
                            RuntimeError("serve batcher died")
                        )
                raise
