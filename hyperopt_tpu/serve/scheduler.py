"""The continuous-batching scheduler: coalesce many studies' asks into
one device dispatch.

The LLM-serving idiom applied to the ask/tell plugin boundary: incoming
asks queue up; a dispatch round picks at most one ask per study, fills
a SLOTTED batch (fixed pow2 slot capacities + an active-slot mask, so
studies join and leave without retracing -- :func:`~hyperopt_tpu.serve.
batched.slot_capacity`), rides every slot's staged O(D) tell delta
along, and runs ONE :func:`~hyperopt_tpu.serve.batched.
build_batched_step_fn` program for the whole round.  A background
thread drives rounds under a latency/occupancy budget (``max_wait``
deadline after the oldest queued ask, early dispatch once every joined
study has an ask queued); tests and the chaos suite drive :meth:`
BatchScheduler.step` synchronously instead, so simulated crashes
propagate to the caller.

Determinism: each study draws its per-ask seed from its OWN
``np.random.Generator`` stream at SUBMIT time, so the suggestion
sequence of a study is a pure function of its seed and its own
tell history -- independent of batching order, sibling churn, or slot
placement (the 64-study bitwise pin in ``tests/test_serve.py``).

Tells are absorbed synchronously: WAL append (durability first), host
``ObsBuffer.add``, then an O(D) delta staged for the slot -- exactly
the PR-4 resident-mirror protocol, per slot.  A backlog past one delta
drains through the batched masked-delta program; out-of-order (late)
tells and bucket growth re-materialize the stacked state from host
truth, the same log schedule as the solo resident mirror.
"""

from __future__ import annotations

import collections
import logging
import math
import random
import threading
import time
from concurrent.futures import Future
from concurrent.futures import TimeoutError as FutureTimeout

import numpy as np

from ..distributed._common import is_transient
from ..distributed.faults import REAL_FS
from ..exceptions import (
    DeadlineExpired,
    DispatchTimeout,
    Overloaded,
    StudyPoisoned,
    StudyQuarantined,
)
from ..jax_trials import MAX_PENDING_DELTAS, MIN_CAPACITY, ObsBuffer
from ..obs.flightrec import NULL_RECORDER
from ..obs.registry import (
    LATENCY_BUCKETS_MS,
    RATIO_BUCKETS,
    CounterAttr,
    HistogramAttr,
    MetricsRegistry,
)
from .batched import (
    StudyBatchState,
    _dummy_delta,
    build_batched_delta_fn,
    build_batched_step_fn,
    build_finite_check_fn,
    restack_shards,
    slot_capacity,
    stack_states,
)

logger = logging.getLogger(__name__)

__all__ = ["BatchScheduler", "ServeStudy", "dense_to_vals"]

#: ring-buffer length for the timing metrics (``ask_latencies`` /
#: ``occupancy``): plenty for any bench window, bounded for a
#: long-running service
METRICS_WINDOW = 65536

#: consecutive finite-check trips before a poisoned study is EVICTED
#: from the slotted batch (its host truth itself is bad -- e.g. a told
#: NaN loss survives re-materialization, so retrying cannot heal it)
QUARANTINE_TRIPS = 3

#: consecutive failed dispatch rounds (after their retry) before the
#: batcher circuit-breaks into reject-with-Overloaded mode
CIRCUIT_THRESHOLD = 3


def _cache_interlock():
    """Refuse a known-poisoned configuration: jaxlib 0.4.36's CPU
    runtime intermittently corrupts the heap when it DESERIALIZES
    persistently-cached executables of the vmapped serve program
    family -- warm-cache processes die later with SIGSEGV / glibc
    abort inside unrelated traces or allocations, while cold-cache
    runs are clean (reproduced bitwise-at-seed; FAILURES.md "Known
    test debt").  A scheduler on the CPU backend therefore disables
    the persistent compilation cache process-wide, loudly, before its
    first program builds; accelerator backends keep the cache (the
    fault is in the CPU executable deserializer, and compile seconds
    actually matter there)."""
    import jax

    if jax.default_backend() != "cpu":
        return
    if not getattr(jax.config, "jax_enable_compilation_cache", False):
        return
    if not getattr(jax.config, "jax_compilation_cache_dir", None):
        return
    logger.warning(
        "graftserve: disabling the persistent XLA compilation cache "
        "on the CPU backend -- jaxlib 0.4.36 heap-corrupts when "
        "deserializing cached serve-family executables (see "
        "FAILURES.md); programs will compile fresh in this process"
    )
    jax.config.update("jax_enable_compilation_cache", False)


def draw_seed(rstate):
    """One per-ask seed off ``rstate``'s stream -- the submit-time draw.
    Works for both ``np.random.Generator`` and the legacy
    ``RandomState`` (the two stream types ``fmin`` accepts), so a
    client study wired to the driver's own rstate draws exactly the
    seeds the solo driver's ``_take_seed`` would."""
    if hasattr(rstate, "integers"):
        return int(rstate.integers(2**31 - 1))
    return int(rstate.randint(2**31 - 1))


def _cat_set(ps):
    """The categorical-dim index set, cached on the PackedSpace: the
    dispatch hot loop calls :func:`dense_to_vals` once per served ask,
    and rebuilding this set each time was measurable at burst rates."""
    cat = getattr(ps, "_serve_cat_set", None)
    if cat is None:
        cat = frozenset(int(d) for d in ps.cat_idx)
        try:
            ps._serve_cat_set = cat
        except AttributeError:
            pass  # immutable space container: rebuild per call
    return cat


def dense_to_vals(ps, col_v, col_a):
    """One dense suggestion column -> the {label: value} config dict at
    API types (ints for categorical-family dims, inactive conditional
    dims omitted) -- the serve twin of ``tpe_jax._cast_vals``."""
    cat = _cat_set(ps)
    labels = ps.labels
    vals = {}
    for d in np.nonzero(np.asarray(col_a))[0]:
        d = int(d)
        v = float(col_v[d])
        vals[labels[d]] = int(round(v)) if d in cat else v
    return vals


class ServeStudy:
    """One tenant: host-truth history + seed stream + slot bookkeeping.

    The host :class:`~hyperopt_tpu.jax_trials.ObsBuffer` is
    authoritative (exactly as in the solo resident path); the device
    only ever holds a slot-wise mirror of it.
    """

    def __init__(self, name, seed, ps):
        self.name = name
        self.seed = int(seed)
        self.rstate = np.random.default_rng(self.seed)
        self.buf = ObsBuffer(ps)
        self.slot = None
        self.pending = collections.deque()  # staged (vcol, acol, loss, idx)
        self.dirty = True  # device slot needs re-materialization
        self.closed = False
        self.quarantined = False  # evicted by the finite-check guard
        self.poison_trips = 0  # CONSECUTIVE finite-check trips
        self.next_tid = 0
        self.n_asks = 0
        self.n_tells = 0
        self.outstanding = {}  # tid -> served vals (awaiting their tell)
        self.pending_asks = {}  # tid -> seed: WAL-logged, never served
        self.persist = None  # durability hooks (service wires them)
        self.claim = None  # fleet ownership token (service wires it)
        # graftclient (the fmin-as-client path):
        # host_algo: per-study host-adaptive dispatch hook
        #   ``hook(seed) -> (values [D, 1], active [D, 1])`` -- serves
        #   this study's picks instead of the shared vmapped program
        #   (atpe's host decision layer cannot vmap across studies);
        #   the study then never occupies a batch slot.
        # fresh_window: depth-k outstanding-ask gate -- with it set, a
        #   queued ask is only picked while fewer than this many served
        #   suggestions await their tell, so an ask-ahead client's
        #   every dispatch sees the full posterior (the bitwise-at-any-
        #   depth construction; None = no gate, the multi-tenant
        #   default).
        # client_state_fn / client_blob / restore_records: the client's
        #   snapshot seam -- extra durable state rides the study bundle
        #   and comes back (with the replayed WAL suffix) on restore.
        self.host_algo = None
        self.fresh_window = None
        self.client_state_fn = None
        self.client_blob = None
        self.restore_records = None

    def best(self):
        """(loss, vals) of the best finite completed trial, or None --
        recomputed from the buffer, so it survives restore for free."""
        buf = self.buf
        ok = buf.valid[: buf.count] & np.isfinite(buf.losses[: buf.count])
        if not ok.any():
            return None
        i = int(np.argmin(np.where(ok, buf.losses[: buf.count], np.inf)))
        return float(buf.losses[i]), dense_to_vals(
            buf.space, buf.values[:, i], buf.active[:, i]
        )


class _AskRequest:
    __slots__ = ("study", "tid", "seed", "future", "t_submit", "deadline")

    def __init__(self, study, tid, seed, deadline=None):
        self.study = study
        self.tid = tid
        self.seed = seed
        self.future = Future()
        self.t_submit = time.perf_counter()
        self.deadline = deadline  # absolute perf_counter() instant


class BatchScheduler:
    """The slotted continuous-batching engine for one space template.

    ``max_batch`` caps the slot capacity (and so the number of
    concurrently open studies); ``max_wait`` is the latency budget a
    queued ask may wait for co-batching before the background loop
    dispatches anyway.  ``algo`` is ``"tpe"`` or ``"anneal"``;
    ``algo_kw`` passes through to :func:`~hyperopt_tpu.serve.batched.
    build_batched_step_fn`.  ``fs`` is the PR-3 fault-injection seam --
    the serve chaos points fire through it.

    Deterministic counters (never timing): ``dispatch_count`` (batched
    step programs run), ``delta_drain_dispatches`` (backlog-drain
    programs, included in ``dispatch_count``), ``upload_events`` /
    ``upload_bytes`` (stacked re-materializations), ``joins``,
    ``rebuckets``.  ``ask_latencies`` / ``occupancy`` feed the bench.

    graftguard (the runtime-protection layer):

    * **Admission control** -- the ask queue is bounded at ``max_queue``
      (default ``4 * max_batch``) with a per-study fairness cap
      (``study_queue_cap``); a submit past either is refused with a
      typed :class:`~hyperopt_tpu.exceptions.Overloaded` carrying a
      retry-after hint derived from queue occupancy and the p50 ask
      latency.  An ask whose client deadline already passed is shed
      (:class:`~hyperopt_tpu.exceptions.DeadlineExpired`) before it
      wastes a dispatch slot; admission happens BEFORE the per-study
      seed draw, so a shed submit never perturbs the study's stream.
    * **Poisoned-tenant isolation** -- after every batched step a fused
      finite-check (:func:`~hyperopt_tpu.serve.batched.
      build_finite_check_fn`) scans the stacked state and the round's
      suggestions; a tripping slot fails only ITS client
      (:class:`~hyperopt_tpu.exceptions.StudyPoisoned`), re-materializes
      from host truth, and is evicted after :data:`QUARANTINE_TRIPS`
      consecutive trips (:class:`~hyperopt_tpu.exceptions.
      StudyQuarantined`); sibling slots stay bitwise undisturbed.
    * **Dispatch watchdog** -- with ``dispatch_timeout`` set, every
      device dispatch runs under a deadline; a timeout or transiently
      raising dispatch retries ONCE against a freshly re-materialized
      stacked state (deterministic program bugs -- not
      ``is_transient`` -- skip the pointless retry), and
      :data:`CIRCUIT_THRESHOLD` consecutive failed rounds circuit-break
      the batcher into reject-with-Overloaded mode instead of
      crash-looping.
    * **Device-fault injection** -- a :class:`~hyperopt_tpu.distributed.
      faults.DeviceFaultPlan` riding the ``fs=`` seam (``fs.plan.
      device``) injects NaN outputs, dispatch hangs, and dispatch
      raises deterministically; the guard chaos suite
      (``tests/test_serve_guard.py``) drives all of the above with it.
    """

    # graftscope: every deterministic counter and timing window lives
    # on the scheduler's MetricsRegistry, exposed BEHIND its historic
    # attribute name (CounterAttr/HistogramAttr descriptors), so bench,
    # tests, and the counters dict read exactly what they always did
    # while the metrics op / router scrape get typed, bounded series
    dispatch_count = CounterAttr(
        "serve_dispatch_total", "batched step programs run")
    delta_drain_dispatches = CounterAttr(
        "serve_delta_drain_dispatches_total",
        "backlog-drain delta programs (included in serve_dispatch_total)")
    upload_events = CounterAttr(
        "serve_upload_events_total", "stacked re-materializations")
    upload_bytes = CounterAttr(
        "serve_upload_bytes_total", "bytes re-uploaded to device")
    joins = CounterAttr("serve_joins_total", "studies joined")
    rebuckets = CounterAttr(
        "serve_rebuckets_total", "slot/obs geometry growth events")
    shard_restacks = CounterAttr(
        "serve_shard_restacks_total",
        "graftmesh shard-local re-materializations")
    admitted_count = CounterAttr(
        "serve_admitted_total", "asks admitted past admission control")
    shed_count = CounterAttr(
        "serve_shed_total", "Overloaded + DeadlineExpired refusals")
    guard_checks = CounterAttr(
        "serve_guard_checks_total", "finite-check programs run")
    quarantine_count = CounterAttr(
        "serve_quarantine_trips_total",
        "finite-check trips (per slot-round)")
    evictions = CounterAttr(
        "serve_evictions_total", "studies evicted after K trips")
    watchdog_timeouts = CounterAttr(
        "serve_watchdog_timeouts_total", "dispatch watchdog deadline hits")
    watchdog_retries = CounterAttr(
        "serve_watchdog_retries_total", "watchdog retry rounds")
    watchdog_recoveries = CounterAttr(
        "serve_watchdog_recoveries_total", "watchdog retries that healed")
    device_metric_dispatches = CounterAttr(
        "serve_device_metric_dispatches_total",
        "obs.device_metrics twin dispatches (cadence-gated; NOT part "
        "of serve_dispatch_total)")
    host_algo_served = CounterAttr(
        "serve_host_algo_served_total",
        "asks served by a per-study host_algo hook (graftclient atpe; "
        "NOT part of serve_dispatch_total -- the hook's own device "
        "dispatches are counted on its ObsBuffer)")
    group_commit_barriers = CounterAttr(
        "serve_group_commit_barriers_total",
        "round fsync barriers issued (graftburst group commit: one "
        "covers every tell flushed since the previous round)")
    ask_latencies = HistogramAttr(
        "serve_ask_latency_seconds", "submit-to-ack ask latency",
        window=METRICS_WINDOW)
    occupancy = HistogramAttr(
        "serve_batch_occupancy", "filled-slot fraction per round",
        buckets=RATIO_BUCKETS, window=METRICS_WINDOW)
    watchdog_recovery_ms = HistogramAttr(
        "serve_watchdog_recovery_ms", "watchdog retry-to-heal latency",
        buckets=LATENCY_BUCKETS_MS, window=METRICS_WINDOW)

    def __init__(self, ps, algo="tpe", max_batch=64, max_wait=0.002,
                 n_startup_jobs=20, fs=REAL_FS, max_queue=None,
                 study_queue_cap=None, dispatch_timeout=None,
                 finite_check=True, quarantine_trips=QUARANTINE_TRIPS,
                 circuit_threshold=CIRCUIT_THRESHOLD, mesh=None,
                 recorder=None, device_metrics_every=0,
                 retry_jitter=0.25, retry_jitter_seed=0,
                 group_commit=True, **algo_kw):
        # graftscope wiring first: the descriptors above resolve
        # through this registry from the first counter touch on
        self.metrics = MetricsRegistry("serve")
        self.recorder = recorder if recorder is not None else NULL_RECORDER
        self.span_ids = {}  # correlation ids stamped on every span
        self.device_metrics_every = int(device_metrics_every)
        self._device_metrics_fn = None  # built lazily iff cadence on
        self.ps = ps
        self.algo = str(algo)
        self.max_batch = int(max_batch)
        self.max_wait = float(max_wait)
        self.n_startup_jobs = int(n_startup_jobs)
        self.fs = fs
        # graftmesh: a 1-D study mesh shards the slot axis with
        # shard_map -- slot capacity multiplies with device count, and
        # re-materialization/quarantine stay shard-local
        self.mesh = mesh
        if mesh is not None:
            axes = list(mesh.shape)
            if len(axes) != 1:
                raise ValueError(
                    f"BatchScheduler mesh must be 1-D (the study axis); "
                    f"got axes {axes}"
                )
            self._mesh_axis = axes[0]
            self._n_shards = int(mesh.shape[self._mesh_axis])
        else:
            self._mesh_axis = None
            self._n_shards = 1
        self.max_queue = (
            4 * self.max_batch if max_queue is None else int(max_queue)
        )
        # fairness: one tenant may hold at most this many queued asks,
        # so a storm from one study cannot starve the others out of the
        # bounded queue (default: an even share, floored at 2)
        self.study_queue_cap = (
            max(2, self.max_queue // self.max_batch)
            if study_queue_cap is None else int(study_queue_cap)
        )
        self.dispatch_timeout = (
            None if dispatch_timeout is None else float(dispatch_timeout)
        )
        # graftpilot satellite: a deterministic retry_after makes every
        # shed client retry on the same tick (a thundering herd against
        # the recovering replica), so queue-based refusals jitter the
        # hint from a SEEDED scheduler-private rng -- bounded, and
        # drawn only after admission already refused, so suggestion
        # streams can never observe it
        self.retry_jitter = float(retry_jitter)
        self._retry_rng = random.Random(int(retry_jitter_seed))
        self.finite_check = bool(finite_check)
        self.quarantine_trips = int(quarantine_trips)
        self.circuit_threshold = int(circuit_threshold)
        # graftburst group commit: tells append flush-only (process-
        # crash safe immediately) and ONE fsync barrier per round --
        # issued before the dispatch, covering every WAL touched since
        # the previous round -- establishes the machine-crash
        # durability point N per-tell fsyncs used to
        self.group_commit = bool(group_commit)
        self._pending_barrier = set()  # TellWALs flushed, not barriered
        # the device-fault seam: a DeviceFaultPlan riding the fs plan
        # (REAL_FS has no plan -> None -> zero overhead in production)
        self._device_faults = getattr(
            getattr(fs, "plan", None), "device", None
        )
        self.algo_kw = dict(algo_kw)
        _cache_interlock()  # before any serve program builds/loads
        # "atpe" studies are served by their per-study host_algo hook
        # (graftclient), never by the shared vmapped program -- the
        # engine program family stays the TPE body (jit is lazy, so an
        # all-hook service never compiles it)
        self._engine_algo = "tpe" if self.algo == "atpe" else self.algo
        if self._engine_algo == "tpe":
            from ..tpe_jax import _resolve_above_cap

            self._pow2_cap = _resolve_above_cap(
                self.algo_kw.get("above_cap")
            )
        else:
            self._pow2_cap = None
        engine_kw = {
            k: v for k, v in self.algo_kw.items()
            if self.algo != "atpe"
        }
        self._step_fn = build_batched_step_fn(
            ps, algo=self._engine_algo, mesh=self.mesh,
            mesh_axis=self._mesh_axis, **engine_kw
        )
        self._delta_fn = build_batched_delta_fn(
            mesh=self.mesh, mesh_axis=self._mesh_axis
        )
        self._finite_fn = build_finite_check_fn(
            mesh=self.mesh, mesh_axis=self._mesh_axis
        )

        self._lock = threading.RLock()
        self._cond = threading.Condition(self._lock)
        self._asks = collections.deque()
        self._studies = {}
        self._slots = {}  # slot index -> ServeStudy
        self._free = []
        self._state = None  # StudyBatchState (device)
        self._slot_cap = 0
        self._bucket = MIN_CAPACITY
        self._materialize = True
        self._thread = None
        self._stopping = False

        # graftguard state
        self.draining = False
        self.drain_deadline = None  # absolute perf_counter() instant
        self.circuit_open = False
        self._round_failures = 0  # CONSECUTIVE failed dispatch rounds
        self._queued_per_study = collections.Counter()

        # deterministic accounting + bounded timing windows: all
        # graftscope registry series now (see the descriptor block at
        # the top of the class); touching each one here materializes
        # the full series set so a scrape of an idle scheduler is
        # already schema-complete
        for attr in (
            "dispatch_count", "delta_drain_dispatches", "upload_events",
            "upload_bytes", "joins", "rebuckets", "shard_restacks",
            "admitted_count", "shed_count", "guard_checks",
            "quarantine_count", "evictions", "watchdog_timeouts",
            "watchdog_retries", "watchdog_recoveries",
            "device_metric_dispatches", "host_algo_served",
            "group_commit_barriers",
            "ask_latencies", "occupancy", "watchdog_recovery_ms",
        ):
            getattr(self, attr)
        # graftburst dispatch-path caches (vectorized round
        # bookkeeping): the per-round delta template and the dummy
        # PRNG key are reused instead of rebuilt per round
        self._delta_cache = None
        self._dummy_key = None

    # -- tenancy -----------------------------------------------------------
    def _alloc_slot(self):
        """Pick the next study's slot (lock held).  Unsharded: reuse
        the lowest freed slot, else append.  Sharded (graftmesh):
        stripe across shards -- the unoccupied slot whose shard holds
        the fewest studies (lowest index on ties), so tenants spread
        over the mesh instead of piling onto shard 0 and every shard's
        re-materializations stay small."""
        if self._n_shards == 1:
            if self._free:
                return self._free.pop()
            return len(self._studies)
        cap = max(
            self._slot_cap,
            slot_capacity(
                len(self._studies) + 1, self.max_batch,
                shards=self._n_shards,
            ),
        )
        blk = max(1, cap // self._n_shards)
        occ = collections.Counter(s // blk for s in self._slots)
        slot = min(
            (s for s in range(cap) if s not in self._slots),
            key=lambda s: (occ.get(s // blk, 0), s),
        )
        if slot in self._free:
            self._free.remove(slot)
        return slot

    def open_study(self, name, seed=0, study=None):
        """Join a (new or restored) study to the slotted batch."""
        with self._lock:
            if name in self._studies:
                raise ValueError(f"study {name!r} already open")
            if len(self._studies) >= self.max_batch:
                raise ValueError(
                    f"batch capacity {self.max_batch} studies reached; "
                    "close a study or raise max_batch"
                )
            st = study if study is not None else ServeStudy(
                name, seed, self.ps
            )
            if st.host_algo is None:
                st.slot = self._alloc_slot()
                st.dirty = True  # _maintain re-materializes its shard
                self._slots[st.slot] = st
            else:
                # host-hook studies (graftclient atpe) are served
                # outside the slotted batch: no slot, no stacked state
                st.slot = None
            self._studies[name] = st
            self.joins += 1
            return st

    def close_study(self, name):
        """Leave: free the slot (device data becomes garbage behind the
        active-slot mask -- siblings are untouched, no re-upload).  An
        evicted (quarantined) study has no slot to free."""
        with self._lock:
            st = self._studies.pop(name)
            st.closed = True
            if st.slot is not None:
                self._slots.pop(st.slot, None)
                self._free.append(st.slot)
                self._free.sort(reverse=True)  # reuse lowest slots first
                st.slot = None
            self._queued_per_study.pop(name, None)
            return st

    def study(self, name):
        with self._lock:
            return self._studies[name]

    # -- tell --------------------------------------------------------------
    def tell(self, study, tid, vals, loss, result=None):  # graftlint: disable=GL503 the WAL append IS the tell's durability barrier and must be ordered inside the study's tell linearization (write-ahead-then-apply, PR-6/PR-8); moving it outside the lock reorders tells against dedup and delta staging
        """Absorb one completed trial: WAL first, host buffer second,
        device delta staged third.  Synchronous -- the durability
        barrier is the WAL append, and the host add is O(D).

        Idempotent by tid: a client re-telling work whose ack a
        crashed service lost (the tell may already have been WAL-
        replayed on restore) is absorbed exactly once."""
        rec = self.recorder
        with self._lock:
            if study.quarantined:
                raise StudyQuarantined(
                    f"study {study.name!r} was evicted by the finite-"
                    "check guard; close it and open a fresh study"
                )
            buf = study.buf
            if (buf.tids[: buf.count] == int(tid)).any():
                study.outstanding.pop(tid, None)
                return
            t0 = time.perf_counter() if rec.enabled else 0.0
            if study.persist is not None:
                # group commit: flush-only append (kernel-visible at
                # once -- process death loses nothing) and register the
                # WAL for the next round's single fsync barrier
                study.persist.log_tell(
                    tid, vals, loss, result=result,
                    sync=not self.group_commit,
                )
                if self.group_commit:
                    self._pending_barrier.add(study.persist.wal)
            if rec.enabled:
                t1 = time.perf_counter()
                rec.record(
                    "tell.wal_append", t0, t1, study=study.name,
                    tid=int(tid), **self.span_ids,
                )
            self.fs.crashpoint("serve_after_wal_before_dispatch")
            self._apply_tell(study, tid, vals, loss)
            study.outstanding.pop(tid, None)
            study.pending_asks.pop(int(tid), None)
            if rec.enabled:
                t2 = time.perf_counter()
                rec.record(
                    "tell.applied", t1, t2, study=study.name,
                    tid=int(tid), **self.span_ids,
                )
                rec.record(
                    "tell", t0, t2, study=study.name, tid=int(tid),
                    loss=float(loss), **self.span_ids,
                )
            # a tell can open a study's fresh_window gate: wake the
            # background loop so the unblocked ask dispatches now
            self._cond.notify_all()

    def tell_failure(self, study, tid, doc=None):
        """Absorb one FAILED trial (graftclient): the evaluation ended
        in STATUS_FAIL / JOB_STATE_ERROR, so nothing enters the
        posterior -- exactly the solo driver's behavior, where failed
        docs never pass ``posterior_state`` -- but the outcome is made
        durable (WAL ``fail`` record) BEFORE the outstanding ask is
        retired, so a resumed client never re-runs a known-bad trial
        and never re-serves its suggestion."""
        with self._lock:
            buf = study.buf
            if (buf.tids[: buf.count] == int(tid)).any():
                return  # already told ok earlier: nothing to fail
            if study.persist is not None:
                study.persist.log_fail(tid, doc=doc)
            study.next_tid = max(study.next_tid, int(tid) + 1)
            study.outstanding.pop(int(tid), None)
            study.pending_asks.pop(int(tid), None)
            self._cond.notify_all()

    def _apply_tell(self, study, tid, vals, loss):
        """Host-side tell application (shared with WAL replay, which
        must skip the durability hooks it is replaying from)."""
        buf = study.buf
        n = buf.count
        in_order = n == 0 or tid > int(buf.tids[n - 1])
        buf.add(dict(vals), float(loss), tid=int(tid))
        study.n_tells += 1
        study.next_tid = max(study.next_tid, int(tid) + 1)
        if (
            in_order
            and not study.dirty
            and len(study.pending) < MAX_PENDING_DELTAS
        ):
            study.pending.append((
                n,
                buf.values[:, n].copy(),
                buf.active[:, n].copy(),
                float(loss),
            ))
        else:
            # late completion shifted the tail (or the backlog is past
            # the crossover): slot re-materializes from host truth
            study.dirty = True
            study.pending.clear()

    # -- ask ---------------------------------------------------------------
    def retry_after(self):
        """The back-off hint an :class:`Overloaded` refusal carries:
        how long until the queue has likely drained one slot -- rounds
        pending at current occupancy x the p50 ask latency (a fresh
        service with no latency history hints 10 ms)."""
        with self._lock:
            rounds = max(1, math.ceil(
                (len(self._asks) + 1) / max(1, self.max_batch)
            ))
            lats = sorted(self.ask_latencies)
        p50 = lats[len(lats) // 2] if lats else 0.010
        return round(rounds * p50, 6)

    def _jittered(self, base):
        """Seeded, bounded jitter on a queue-based ``retry_after`` hint
        (the reply seam): the hint lands in ``[base, base * (1 +
        retry_jitter)]``, spreading the retry herd instead of stamping
        every shed client with the same tick.  Draining refusals stay
        EXACT -- their hint is the published drain deadline, monotone
        by contract, not a congestion estimate."""
        if self.retry_jitter <= 0.0:
            return base
        frac = self.retry_jitter * self._retry_rng.random()
        return round(base * (1.0 + frac), 6)

    def drain_retry_after(self):
        """The CONCRETE back-off hint a ``draining`` refusal carries:
        time left until the drain deadline (when migration/handoff will
        have finished and the router has repointed), floored at one
        queue-drain estimate so a client never hot-loops a replica
        whose deadline just passed."""
        floor = self.retry_after()
        if self.drain_deadline is None:
            return floor
        left = self.drain_deadline - time.perf_counter()  # graftlint: disable=GL307 deadline arithmetic (time left until the published drain deadline), not an ad-hoc latency metric
        return round(max(left, floor, 0.001), 6)

    def _dec_queue(self, req):
        """A request left the queue for good (picked, shed, dropped,
        or drained): release its per-study fairness budget."""
        c = self._queued_per_study
        name = req.study.name
        if c.get(name, 0) <= 1:
            c.pop(name, None)
        else:
            c[name] -= 1

    def submit_ask(self, study, deadline=None, replay=None):  # graftlint: disable=GL503 the flush-only (no-fsync) ask record must stay ordered with the seed draw and tid allocation it snapshots -- the restored-cursor bitwise contract; the next tell's fsync is its barrier
        """Queue one ask; returns the queued request (``.tid`` /
        ``.future``).  The per-ask seed is drawn HERE, from the study's
        own stream -- the batching order downstream can no longer
        affect the suggestion.

        ``replay=(tid, seed)`` re-queues a restored in-flight ask (a
        WAL ``ask`` record with no ``tell`` -- the crashed owner logged
        it but never served or never acked it): the logged seed is used
        verbatim and nothing is drawn or re-logged, so the re-served
        suggestion is bitwise what the crashed replica would have
        served.  Admission control still applies.

        Admission control runs BEFORE the seed draw: a refused submit
        (:class:`Overloaded` / :class:`DeadlineExpired` /
        :class:`StudyQuarantined`) consumes nothing from the study's
        seed stream or tid space, so shedding never perturbs the
        suggestion stream of the asks that are admitted.

        ``deadline`` is an absolute ``time.perf_counter()`` instant;
        an already-expired deadline is shed here, an expiry while
        queued is shed at pick time (:meth:`_pick_round`) -- either
        way the request never consumes a dispatch slot."""
        with self._lock:
            if self._stopping:
                raise RuntimeError("suggestion service shutting down")
            if study.closed:
                raise ValueError(f"study {study.name!r} is closed")
            if study.quarantined:
                raise StudyQuarantined(
                    f"study {study.name!r} was evicted after "
                    f"{self.quarantine_trips} consecutive finite-check "
                    "trips (its history contains non-finite values); "
                    "close it and open a fresh study"
                )
            if self.draining:
                self.shed_count += 1
                raise Overloaded(
                    "service is draining for shutdown; retry against "
                    "another replica",
                    retry_after=self.drain_retry_after(), reason="draining",
                )
            if self.circuit_open:
                self.shed_count += 1
                raise Overloaded(
                    "batcher circuit breaker is open after "
                    f"{self.circuit_threshold} consecutive failed "
                    "dispatch rounds; the service needs operator "
                    "attention (reset_circuit)",
                    retry_after=self._jittered(self.retry_after()),
                    reason="circuit_open",
                )
            if deadline is not None and time.perf_counter() >= deadline:
                self.shed_count += 1
                raise DeadlineExpired(
                    f"ask for study {study.name!r} submitted with an "
                    "already-expired deadline; shed before queueing"
                )
            if len(self._asks) >= self.max_queue:
                self.shed_count += 1
                raise Overloaded(
                    f"ask queue at high-water mark ({self.max_queue}); "
                    "back off and resubmit",
                    retry_after=self._jittered(self.retry_after()),
                    reason="queue_full",
                )
            if self._queued_per_study.get(study.name, 0) >= \
                    self.study_queue_cap:
                self.shed_count += 1
                raise Overloaded(
                    f"study {study.name!r} already holds "
                    f"{self.study_queue_cap} queued asks (per-study "
                    "fairness cap); tell or await results first",
                    retry_after=self._jittered(self.retry_after()),
                    reason="study_queue_cap",
                )
            if replay is not None:
                # a restored in-flight ask: seed/tid come from its WAL
                # record (already durable -- nothing to draw or re-log)
                tid, seed = int(replay[0]), int(replay[1])
                study.next_tid = max(study.next_tid, tid + 1)
                self.admitted_count += 1
            else:
                seed = draw_seed(study.rstate)
                tid = study.next_tid
                study.next_tid = tid + 1
                study.n_asks += 1
                self.admitted_count += 1
                if study.persist is not None:
                    study.persist.log_ask(tid, seed, study.rstate)
                # the live twin of the WAL ask record: queued-but-
                # unserved asks survive a snapshot that compacts their
                # records away (the bundle carries pending_asks), so a
                # restored service re-serves a crashed client's ask
                # window bitwise no matter where the cadence fell
                study.pending_asks[int(tid)] = int(seed)
            req = _AskRequest(study, tid, seed, deadline=deadline)
            self._asks.append(req)
            self._queued_per_study[study.name] += 1
            if self.recorder.enabled:
                self.recorder.event(
                    "ask.submit", study=study.name, tid=tid,
                    queue_depth=len(self._asks), **self.span_ids,
                )
            self._cond.notify_all()
            return req

    def drop_request(self, req):
        """Drop a still-queued request (the slow-client path: its
        ``ask(timeout=...)`` gave up).  Returns True when the request
        was still queued -- its future is failed with
        :class:`DeadlineExpired` and it will never consume a dispatch
        slot; False when it was already picked (the in-flight dispatch
        will resolve it)."""
        with self._lock:
            try:
                self._asks.remove(req)
            except ValueError:
                return False
            self._dec_queue(req)
            self.shed_count += 1
        if not req.future.done():
            req.future.set_exception(DeadlineExpired(
                f"ask tid={req.tid} for study {req.study.name!r} "
                "dropped from the queue: its client stopped waiting"
            ))
        return True

    # -- the dispatch round ------------------------------------------------
    def _compute_bucket(self):
        b = MIN_CAPACITY
        for st in self._slots.values():
            b = max(b, st.buf._device_bucket(self._pow2_cap))
        return b

    def _rematerialize(self, slot_cap, bucket):
        buffers = {st.slot: st.buf for st in self._slots.values()}
        if not buffers:
            self._state = None
            return
        self._state, nbytes = stack_states(
            buffers, slot_cap, bucket, mesh=self.mesh,
            axis=self._mesh_axis,
        )
        self.upload_events += 1
        self.upload_bytes += nbytes
        for st in self._slots.values():
            st.dirty = False
            st.pending.clear()  # host truth already includes them

    def _restack_dirty_shards(self):
        """graftmesh shard-local re-materialization (lock held,
        geometry unchanged): rebuild only the shards holding dirty
        slots from host truth; every other shard's device buffers are
        reused untouched -- siblings there are pinned bitwise because
        their bytes never move.  Pending deltas of the rebuilt shards
        clear (host truth already includes them); other shards keep
        their staged backlogs."""
        blk = self._slot_cap // self._n_shards
        dirty_shards = {
            st.slot // blk for st in self._slots.values() if st.dirty
        }
        buffers = {st.slot: st.buf for st in self._slots.values()}
        self._state, nbytes = restack_shards(
            self._state, buffers, self._slot_cap, self._bucket,
            self.ps.n_dims, self.mesh, self._mesh_axis, dirty_shards,
        )
        self.upload_events += 1
        self.upload_bytes += nbytes
        self.shard_restacks += 1
        for st in self._slots.values():
            if st.slot // blk in dirty_shards:
                st.dirty = False
                st.pending.clear()

    def _maintain(self):
        """Bring the stacked state up to date with tenancy/host truth:
        slot-capacity growth, obs-bucket growth, joins, dirty slots --
        all absorbed by ONE re-materialization (shard-local on a mesh
        when geometry is unchanged); then drain any remaining
        multi-delta backlog down to one staged tell per slot (the
        fused dispatch absorbs the last one)."""
        # size from the HIGHEST occupied slot, not the study count:
        # churn can leave survivors on slots >= len(self._studies)
        # (closed studies free their low slots, survivors keep high
        # ones), and stack_states must cover every occupied index
        top_slot = max(self._slots, default=-1)
        slot_cap = max(
            slot_capacity(
                top_slot + 1, self.max_batch, shards=self._n_shards
            ),
            self._slot_cap,  # capacities never shrink mid-flight
        )
        bucket = self._compute_bucket()
        if slot_cap != self._slot_cap or bucket != self._bucket:
            if self._state is not None:
                self.rebuckets += 1
            self._materialize = True
        dirty = any(st.dirty for st in self._slots.values())
        if (
            dirty
            and not self._materialize
            and self._n_shards > 1
            and self._state is not None
        ):
            self._restack_dirty_shards()
        elif dirty:
            self._materialize = True
        if self._materialize:
            self._slot_cap, self._bucket = slot_cap, bucket
            self._rematerialize(slot_cap, bucket)
            self._materialize = False
            return
        # backlog drain: one masked delta per slot per dispatch, FIFO
        while any(len(st.pending) > 1 for st in self._slots.values()):
            vcol, acol, dloss, didx, dapply = self._delta_template(
                self._slot_cap
            )
            for st in self._slots.values():
                if len(st.pending) > 1:
                    n, vc, ac, lo = st.pending.popleft()
                    vcol[st.slot] = vc
                    acol[st.slot] = ac
                    dloss[st.slot] = lo
                    didx[st.slot] = n
                    dapply[st.slot] = True
            out = self._run_dispatch(lambda: self._delta_fn(
                *self._state, vcol, acol, dloss, didx, dapply
            ))
            self._state = StudyBatchState(*out)
            self.dispatch_count += 1
            self.delta_drain_dispatches += 1

    def _delta_template(self, s):
        """The round's delta columns, zeroed (graftburst: one cached
        allocation reused per round instead of five fresh arrays --
        safe because the jitted callee copies its np inputs to device
        synchronously at call time, so by the next round nothing
        aliases these buffers)."""
        tmpl = self._delta_cache
        if tmpl is None or tmpl[2].shape[0] != s:
            tmpl = _dummy_delta(self.ps, s)
            self._delta_cache = tmpl
        else:
            for arr in tmpl:
                arr.fill(0)
        return tmpl

    def _pick_round(self):  # graftlint: disable=GL505 shed futures resolve under the round lock by design: the service API attaches no done-callbacks to ask futures (clients block in Future.result, which waits on the future's own condition, never this lock)
        """At most one queued ask per study this round, FIFO.  Expired
        deadlines and closed/quarantined studies are shed here -- a
        request nobody is waiting for must not consume a dispatch
        slot."""
        now = time.perf_counter()
        n = len(self._asks)
        if n == 0:
            return []
        reqs = list(self._asks)
        studies = [r.study for r in reqs]
        # graftburst: ONE vectorized verdict pass over the queue
        # instead of a 6-branch python loop per request -- at 10^3-
        # client queue depths the per-request attribute churn was the
        # profile's top pick cost.  Semantics are the FIFO originals:
        # shed closed/quarantined/expired; hold fresh_window-gated asks
        # (depth-k ask-ahead: the submit-time seed is already fixed,
        # the later dispatch sees the full posterior); pick the FIRST
        # eligible ask per study, capped at max_batch.
        closed = np.fromiter((s.closed for s in studies), bool, n)
        quar = np.fromiter((s.quarantined for s in studies), bool, n)
        expired = np.fromiter(
            ((r.deadline is not None and now >= r.deadline)
             for r in reqs), bool, n,
        )
        gated = np.fromiter(
            ((s.fresh_window is not None
              and len(s.outstanding) >= s.fresh_window)
             for s in studies), bool, n,
        )
        shed = closed | quar | expired
        eligible = np.nonzero(~(shed | gated))[0]
        # first occurrence per study id in FIFO order (np.unique
        # returns the first index of each value), capped at max_batch
        ids = np.fromiter(
            (id(studies[i]) for i in eligible), np.int64, len(eligible)
        )
        _uniq, first = np.unique(ids, return_index=True)
        chosen = set(np.sort(eligible[first])[: self.max_batch].tolist())
        picked, leftover = [], collections.deque()
        for i, req in enumerate(reqs):
            if shed[i]:
                self._dec_queue(req)
                if closed[i]:
                    req.future.set_exception(
                        ValueError(f"study {req.study.name!r} closed")
                    )
                elif quar[i]:
                    req.future.set_exception(StudyQuarantined(
                        f"study {req.study.name!r} was evicted by the "
                        "finite-check guard while this ask was queued"
                    ))
                else:
                    self.shed_count += 1
                    req.future.set_exception(DeadlineExpired(
                        f"ask tid={req.tid} for study "
                        f"{req.study.name!r} expired while queued; "
                        "shed before dispatch"
                    ))
            elif i in chosen:
                self._dec_queue(req)
                picked.append(req)
            else:
                leftover.append(req)
        self._asks = leftover
        if self.recorder.enabled:
            rec, now2 = self.recorder, time.perf_counter()
            for req in picked:
                rec.record(
                    "ask.queued", req.t_submit, now2,
                    study=req.study.name, tid=req.tid, **self.span_ids,
                )
        return picked

    def step(self):  # graftlint: disable=GL505 the BaseException path fails picked futures before re-raising a simulated/real process death -- reordering outside the lock would let a racing submit observe a dying batcher; no done-callbacks exist (see _pick_round)
        """One dispatch round: returns the number of asks served.
        Synchronous entry point -- the background loop calls this, and
        tests/chaos harnesses call it directly so crashes propagate.

        The watchdog contract: a dispatch that times out or raises a
        TRANSIENT fault retries once against a freshly re-materialized
        stacked state; a failure that survives the retry (or a
        deterministic program bug, which skips the pointless retry)
        fails ONLY the picked requests with the typed error and counts
        toward the circuit breaker -- the batcher itself stays alive.
        Simulated crashes (:class:`SimulatedCrash` is a BaseException)
        keep propagating: a dead process serves nobody."""
        with self._lock:
            picked = self._pick_round()
            try:
                # group-commit fsync point: every WAL flushed since the
                # previous round barriers HERE, before the dispatch --
                # so a round's device work never outruns the durability
                # of the tells it was conditioned on
                self._barrier_round()
                if not picked:
                    # tells without asks stay staged (or dirty) until
                    # the next ask round -- a tell-only window never
                    # dispatches (its barrier just ran above)
                    return 0
                served = self._dispatch_round(picked)
                self._round_failures = 0
                return served
            except Exception as e:
                if not picked:
                    # a barrier failure with no picks has no futures to
                    # contain it in: surface the fs truth to the caller
                    raise
                return self._recover_round(picked, e)
            except BaseException as e:
                # simulated process death (and real interpreter exits):
                # _pick_round already popped these off the queue, so a
                # dying dispatch must fail their futures too, or
                # clients blocked in ask() hang out their full timeout
                for req in picked:
                    if not req.future.done():
                        req.future.set_exception(e)
                raise

    def _barrier_round(self, fire_crashpoint=True):
        """Issue the round's group-commit barriers (lock held): one
        fsync per WAL touched by a flush-only tell since the last
        round.  The ``serve_group_commit_after_flush_before_barrier``
        crash window sits between the flushed records and their
        barrier: a kill here loses nothing a process crash could lose
        (the records are kernel-visible), and replay restores exactly
        the flushed prefix with zero duplicates.  A WAL whose barrier
        fails stays registered, so the next round (or :meth:`stop`)
        retries it; its records remain flushed in the meantime."""
        if not self._pending_barrier:
            return
        if fire_crashpoint:
            self.fs.crashpoint(
                "serve_group_commit_after_flush_before_barrier"
            )
        pend = list(self._pending_barrier)
        self._pending_barrier.clear()
        for i, wal in enumerate(pend):
            try:
                if wal.barrier():
                    self.group_commit_barriers += 1
            except BaseException:
                self._pending_barrier.update(pend[i:])
                raise

    def _force_rematerialize(self):
        """Host truth is authoritative: after any failed dispatch the
        stacked device state (possibly donated away, possibly half-
        updated) is rebuilt from the per-study buffers on next use."""
        self._materialize = True
        for st in self._slots.values():
            st.dirty = True

    def _recover_round(self, picked, exc):  # graftlint: disable=GL505 failure futures resolve under the round lock: the retry/circuit decision and the picked set must stay atomic wrt racing submits; no done-callbacks exist (see _pick_round)
        """The watchdog's failure path (lock held): retry once on
        transient faults, contain the failure to the picked requests
        otherwise, trip the circuit breaker on repeated failures."""
        transient = isinstance(exc, DispatchTimeout) or is_transient(exc)
        self._force_rematerialize()
        if transient:
            self.watchdog_retries += 1
            t0 = time.perf_counter()
            try:
                served = self._dispatch_round(picked)
            except Exception as retry_exc:
                self._force_rematerialize()
                exc = retry_exc
            else:
                self._round_failures = 0
                self.watchdog_recoveries += 1
                self.watchdog_recovery_ms.append(
                    1000.0 * (time.perf_counter() - t0)
                )
                return served
        logger.warning(
            "serve dispatch round failed (%s: %s); failing %d picked "
            "ask(s)", type(exc).__name__, exc, len(picked),
        )
        for req in picked:
            if not req.future.done():
                req.future.set_exception(exc)
        self._round_failures += 1
        if self._round_failures >= self.circuit_threshold:
            if not self.circuit_open:
                logger.error(
                    "serve batcher circuit breaker OPEN after %d "
                    "consecutive failed rounds; rejecting submits with "
                    "Overloaded until reset_circuit()",
                    self._round_failures,
                )
            self.circuit_open = True
        return 0

    def reset_circuit(self):
        """Operator action: close the circuit breaker and accept
        submits again (the next failed rounds re-open it)."""
        with self._lock:
            self.circuit_open = False
            self._round_failures = 0

    def _run_dispatch(self, fn):  # graftlint: disable=GL503 serializing dispatch rounds under the scheduler lock IS the continuous-batching design (one round in flight, ever); the watchdog deadline bounds the blocking result() wait
        """Run one device dispatch under the watchdog deadline.  With
        no ``dispatch_timeout`` the call is inline (zero overhead); with
        one, the dispatch runs on a disposable worker thread and a
        deadline overrun raises :class:`DispatchTimeout` -- the wedged
        thread is abandoned (its result, computed over donated buffers
        the retry no longer uses, is discarded)."""
        if self.dispatch_timeout is None:
            return fn()
        box = Future()

        def work():
            try:
                box.set_result(fn())
            except BaseException as e:  # ferried across the thread
                box.set_exception(e)   # boundary, re-raised at result()

        t = threading.Thread(
            target=work, name="graftserve-dispatch", daemon=True
        )
        t.start()
        try:
            return box.result(timeout=self.dispatch_timeout)
        except FutureTimeout:
            self.watchdog_timeouts += 1
            raise DispatchTimeout(
                f"device dispatch exceeded the {self.dispatch_timeout}s "
                "watchdog deadline"
            ) from None

    def _dispatch_round(self, picked):  # graftlint: disable=GL503,GL505,GL507 the round (flush-only served record, acks) is atomic under the lock by design -- acks-last keeps crashes replayable, no done-callbacks exist (see _pick_round), and a daemon-torn served record is flush-only: replay re-derives it from the ask cursor (PR-6/PR-8 recovery contract)
        """Serve one picked round (lock held): the batched program for
        slot-resident studies, the per-study ``host_algo`` hook for
        host-adaptive ones (graftclient atpe), then ack every pick --
        acks last, so a crash anywhere above leaves the round fully
        replayable, never half-acked."""
        host_picked = [r for r in picked if r.study.host_algo is not None]
        eng_picked = [r for r in picked if r.study.host_algo is None]
        if eng_picked:
            results = self._dispatch_engine(eng_picked)
            results.extend(self._serve_host_picks(host_picked, False))
        else:
            # host-only round: same crash windows as an engine round
            # (mid-batch before the draw, after-dispatch before the
            # served record), so the client chaos suite exercises
            # identical seams on the hook path
            self.fs.crashpoint("serve_mid_batch")
            results = self._serve_host_picks(host_picked, True)
        served = 0
        rec = self.recorder
        s = max(self._slot_cap, 1)
        blk = max(1, s // self._n_shards)
        now = time.perf_counter()
        for req, vals in results:
            if isinstance(vals, Exception):
                req.future.set_exception(vals)
            else:
                req.future.set_result((req.tid, vals))
                served += 1
                if rec.enabled:
                    slot = req.study.slot
                    rec.record(
                        "ask.delivered", req.t_submit, now,
                        study=req.study.name, tid=req.tid, slot=slot,
                        shard=(slot // blk if slot is not None else None),
                        **self.span_ids,
                    )
        return served

    def _serve_host_picks(self, host_picked, fire_crashpoint):  # graftlint: disable=GL503,GL507 same contract as _dispatch_round: the flush-only served record is part of the atomic round under the lock, and a daemon-torn record is re-derived on replay from the ask cursor (PR-6/PR-8 recovery contract)
        """Serve the host-hook picks of one round (lock held): each
        study's ``host_algo(seed)`` draws its suggestion -- the hook
        is the solo host-adaptive dispatch verbatim, so the stream is
        bitwise the solo driver's.  A raising hook fails only ITS
        client (the typed error rides the ack), exactly like a
        poisoned slot; ``SimulatedCrash`` (a BaseException) keeps
        propagating."""
        draws = []
        for req in host_picked:
            try:
                draws.append((req, req.study.host_algo(req.seed)))
            except Exception as e:
                draws.append((req, e))
        if fire_crashpoint:
            self.fs.crashpoint("serve_after_dispatch_before_ack")
        results = []
        now = time.perf_counter()
        for req, out in draws:
            if isinstance(out, Exception):
                results.append((req, out))
                continue
            st = req.study
            v, a = out
            vals = dense_to_vals(
                self.ps, np.asarray(v)[:, 0], np.asarray(a)[:, 0]
            )
            if st.persist is not None:
                st.persist.log_served(req.tid, vals)
            st.outstanding[req.tid] = vals
            st.pending_asks.pop(req.tid, None)
            self.ask_latencies.append(now - req.t_submit)
            self.host_algo_served += 1
            results.append((req, vals))
        return results

    def _dispatch_engine(self, picked):  # graftlint: disable=GL503,GL505,GL507 see _dispatch_round -- this is its engine half, same round-atomicity contract
        """The engine half of one round (lock held): maintain the
        stacked state, run the batched program, build (req, vals)
        results for the ack phase."""
        import jax
        import jax.numpy as jnp

        from ..jax_trials import host_key

        self._maintain()
        s = self._slot_cap
        if self._dummy_key is None:
            self._dummy_key = host_key(0)
        keys = [self._dummy_key] * s
        warm = np.zeros(s, dtype=bool)
        vcol, acol, dloss, didx, dapply = self._delta_template(s)
        # vectorized warm mask over the slot table (graftburst): one
        # fancy-index assignment instead of a per-slot python branch
        n_slots = len(self._slots)
        if n_slots:
            slot_arr = np.fromiter(
                self._slots.keys(), np.int64, n_slots
            )
            counts = np.fromiter(
                (st.buf.count for st in self._slots.values()),
                np.int64, n_slots,
            )
            warm[slot_arr] = (
                counts > 0 if self._engine_algo == "anneal"
                else counts >= self.n_startup_jobs
            )
        for st in self._slots.values():
            if st.pending:  # at most one left after _maintain
                n, vc, ac, lo = st.pending.popleft()
                vcol[st.slot] = vc
                acol[st.slot] = ac
                dloss[st.slot] = lo
                didx[st.slot] = n
                dapply[st.slot] = True
        for req in picked:
            keys[req.study.slot] = host_key(req.seed % (2**31 - 1))
        self.fs.crashpoint("serve_mid_batch")
        slot_of = {st.name: st.slot for st in self._slots.values()}
        device = self._device_faults
        stacked_keys = jnp.stack(keys)
        state = self._state

        def run():
            # everything the watchdog deadline must cover: the injected
            # device faults, the batched step, and the blocking fetch
            if device is not None:
                device.on_dispatch()
            out = self._step_fn(
                stacked_keys, *state, vcol, acol, dloss, didx,
                dapply, warm, batch=1,
            )
            new_state = StudyBatchState(*out[:4])
            new_v, new_a = jax.device_get((out[4], out[5]))
            # OWNED copies, not device_get's zero-copy views: the view
            # aliases a device buffer that later rounds DONATE away
            # (and the injector needs a writable buffer anyway) --
            # feeding an aliased view back into the finite-check while
            # its backing buffer gets recycled corrupts the heap
            new_v = np.array(new_v)
            new_a = np.array(new_a)
            if device is not None:  # NaN scribbled into the outputs
                device.corrupt_outputs(new_v, slot_of)
            poisoned = None
            if self.finite_check:
                poisoned = np.array(jax.device_get(
                    self._finite_fn(*new_state, new_v)
                ))
            return new_state, new_v, new_a, poisoned

        t_disp = time.perf_counter() if self.recorder.enabled else 0.0
        new_state, new_v, new_a, poisoned = self._run_dispatch(run)
        self._state = new_state
        self.dispatch_count += 1
        if self.finite_check:
            self.guard_checks += 1
        if self.recorder.enabled:
            self.recorder.record(
                "serve.dispatch", t_disp, time.perf_counter(),
                n_picked=len(picked), slots=s, shards=self._n_shards,
                **self.span_ids,
            )
        self._dispatch_device_metrics(new_state)
        bad_slots = self._quarantine(poisoned)
        self.fs.crashpoint("serve_after_dispatch_before_ack")
        now = time.perf_counter()
        self.occupancy.append(len(picked) / s)
        results = []
        for req in picked:
            st = req.study
            if st.slot is None or st.slot in bad_slots:
                # the poisoned slot's failure is ITS OWN: the typed
                # error rides this future, siblings ack normally
                results.append((req, StudyQuarantined(
                    f"study {st.name!r} was evicted by the finite-check "
                    "guard (non-finite history); close it and open a "
                    "fresh study"
                ) if st.quarantined else StudyPoisoned(
                    f"study {st.name!r} tripped the finite-check guard "
                    f"({st.poison_trips}/{self.quarantine_trips} "
                    "consecutive trips): non-finite values in its slot "
                    "state or this round's suggestion; the slot is "
                    "re-materializing from host truth"
                )))
                continue
            vals = dense_to_vals(
                self.ps, new_v[st.slot, :, 0], new_a[st.slot, :, 0]
            )
            if st.persist is not None:
                st.persist.log_served(req.tid, vals)
            st.outstanding[req.tid] = vals
            st.pending_asks.pop(req.tid, None)  # replayed ask served
            self.ask_latencies.append(now - req.t_submit)
            results.append((req, vals))
        # acks happen in _dispatch_round, last: a crash above leaves
        # every pick un-acked and replayable, never half-acked
        return results

    def _dispatch_device_metrics(self, state):  # graftlint: disable=GL503 the metrics twin runs inside the round serialization point by design (one dispatch in flight, ever -- see _run_dispatch); its cost is cadence-bounded
        """The graftscope device twin (lock held): on cadence, run the
        read-only ``obs.device_metrics`` program over the fresh stacked
        state -- one declared io_callback row lands per-round
        occupancy / trials-done / best-loss on the registry.  Cadence
        off (the default) never builds the program: exactly zero extra
        dispatches (the test_obs pin)."""
        every = self.device_metrics_every
        if every <= 0 or self.dispatch_count % every:
            return
        if self._device_metrics_fn is None:
            from ..obs.device import build_device_metrics_fn

            m = self.metrics
            best = m.gauge(
                "serve_device_best_loss",
                "best finite loss across the stacked batch (device twin)",
            )
            done = m.gauge(
                "serve_device_trials_done",
                "valid observations across the stacked batch (device twin)",
            )
            active_g = m.gauge(
                "serve_device_active_slots",
                "occupied slots this round (device twin)",
            )
            events = m.counter(
                "obs_device_events_total",
                "device->host metric rows received via declared "
                "io_callback",
            )
            rec = self.recorder

            def sink(row):
                best.set(row["best_loss"])
                done.set(row["trials_done"])
                active_g.set(row["active_slots"])
                events.inc()
                if rec.enabled:
                    rec.event("device.metrics", **row)

            self._device_metrics_fn = build_device_metrics_fn(sink)
        active = np.zeros(self._slot_cap, dtype=bool)
        for slot in self._slots:
            active[slot] = True
        self._device_metrics_fn(state.losses, state.valid, active)
        self.device_metric_dispatches += 1

    def _quarantine(self, poisoned):
        """Apply one round's finite-check verdicts (lock held): trip
        counters, dirty-slot re-materialization, and K-trip eviction.
        Returns the set of slots that tripped this round."""
        if poisoned is None:
            return frozenset()
        bad = {int(i) for i in np.nonzero(poisoned)[0]}
        tripped = set()
        for st in list(self._slots.values()):
            if st.slot in bad:
                tripped.add(st.slot)
                st.poison_trips += 1
                st.dirty = True  # re-materialize from host truth
                self.quarantine_count += 1
                logger.warning(
                    "finite-check trip %d/%d for study %r (slot %d)",
                    st.poison_trips, self.quarantine_trips, st.name,
                    st.slot,
                )
                if st.poison_trips >= self.quarantine_trips:
                    self._evict(st)
            else:
                st.poison_trips = 0  # trips must be CONSECUTIVE
        return tripped

    def _evict(self, st):
        """Evict a poisoned study from the batch: its slot is freed
        (garbage behind the mask, exactly like close), the study is
        marked quarantined so asks/tells are refused, and every
        sibling's device state is left untouched."""
        logger.error(
            "evicting study %r after %d consecutive finite-check "
            "trips; siblings are unaffected", st.name, st.poison_trips,
        )
        st.quarantined = True
        self._slots.pop(st.slot, None)
        self._free.append(st.slot)
        self._free.sort(reverse=True)
        st.slot = None
        self.evictions += 1

    # -- background loop ---------------------------------------------------
    def start(self):
        """Run the continuous-batching loop on a daemon thread."""
        with self._lock:
            if self._thread is not None:
                return
            self._stopping = False
            self._thread = threading.Thread(
                target=self._loop, name="graftserve-batcher", daemon=True
            )
            self._thread.start()

    def drain(self, timeout=None):
        """Enter draining mode (rolling-restart protocol): new submits
        are refused with ``Overloaded(reason="draining")`` while the
        already-queued asks keep being served; call :meth:`stop` once
        the queue is empty.  ``timeout`` (seconds) publishes a drain
        DEADLINE: every draining refusal then carries the time left
        until it as a concrete ``retry_after``, so routers and clients
        back off for exactly the handoff window instead of hot-looping
        the draining replica."""
        with self._lock:
            self.draining = True
            if timeout is not None:
                self.drain_deadline = time.perf_counter() + float(timeout)
            self._cond.notify_all()

    def stop(self):
        with self._lock:
            self._stopping = True
            self._cond.notify_all()
            t = self._thread
            self._thread = None
            # group-commit epilogue: no further rounds will run, so the
            # last window's flushed tells barrier here (not a round --
            # the crash window does not apply; durable studies that
            # snapshot on close have already absorbed theirs)
            try:
                self._barrier_round(fire_crashpoint=False)
            except OSError:
                # shutdown must not hang on a dead mount: the records
                # are flushed (process-crash safe) and fsck's torn-tail
                # rule covers the machine-crash window
                logger.warning(
                    "group-commit barrier failed during stop; flushed "
                    "tells remain kernel-visible", exc_info=True,
                )
            # a stopping batcher must not strand blocked clients:
            # drain the queue promptly instead of letting ask() hang
            # out its full timeout -- but resolve the futures AFTER
            # release (GL505: a done-callback re-entering the
            # scheduler would deadlock on the held lock)
            stranded = []
            while self._asks:
                req = self._asks.popleft()
                self._dec_queue(req)
                stranded.append(req)
        for req in stranded:
            if not req.future.done():
                req.future.set_exception(
                    RuntimeError("suggestion service shutting down")
                )
        if t is not None:
            t.join(timeout=5.0)

    def _ready(self):
        """Dispatch early once every open study has an ask queued (or
        the queue already fills the batch).  Quarantined studies never
        ask again, so they do not count toward 'every'."""
        active = sum(
            1 for st in self._studies.values() if not st.quarantined
        )
        distinct = {id(r.study) for r in self._asks}
        return len(distinct) >= min(max(active, 1), self.max_batch)

    def _loop(self):
        while True:
            with self._cond:
                while not self._asks and not self._stopping:
                    self._cond.wait(timeout=0.05)
                if self._stopping:
                    return
                deadline = self._asks[0].t_submit + self.max_wait
                while (
                    not self._stopping
                    and not self._ready()
                    and (remaining := deadline - time.perf_counter()) > 0  # graftlint: disable=GL307 max_wait budget arithmetic (how long to keep coalescing), not a metric
                ):
                    self._cond.wait(timeout=min(remaining, 0.05))
                if self._stopping:
                    return
            try:
                served = self.step()
                if served == 0:
                    # every queued ask is gated (a fresh_window study
                    # still owes tells): park until a tell notifies
                    # instead of spinning the round loop dry
                    with self._cond:
                        if self._asks and not self._stopping:
                            self._cond.wait(timeout=0.005)
            except BaseException:
                # a dying batcher must not strand blocked clients
                # (contained dispatch failures no longer land here --
                # step() fails only the picked futures and survives;
                # this is the SimulatedCrash / interpreter-exit path).
                # Queue drained under the lock, futures failed after
                # release (GL505)
                with self._lock:
                    stranded = []
                    while self._asks:
                        req = self._asks.popleft()
                        self._dec_queue(req)
                        stranded.append(req)
                for req in stranded:
                    if not req.future.done():
                        req.future.set_exception(
                            RuntimeError("serve batcher died")
                        )
                raise
