"""graftpilot: the metric-driven fleet autoscaler.

The control loop that closes ROADMAP item 2's "self-scaling" gap: a
human no longer calls :meth:`~hyperopt_tpu.serve.fleet.Fleet.
add_replica` / ``drain_replica`` -- a :class:`FleetPilot` does, and its
ONLY input is the graftscope series the router already scrapes (ask
latency histograms, queue-depth gauges, shed/admit counters, batch
occupancy, ``router_backend_up``).  There is no private channel into
fleet state: what an operator can see on ``/metrics`` is exactly what
the controller can act on, so every decision is reproducible from the
scrape that caused it.

Control discipline (the boring parts that make autoscalers safe):

* **hysteresis** -- a pressure signal must breach for
  ``breach_ticks`` consecutive ticks before scale-out, and the fleet
  must be quiet for ``clear_ticks`` before scale-in, so one noisy
  scrape never moves capacity;
* **cooldown** -- after any actuation the controller holds for
  ``cooldown_ticks`` ticks: a migration's own latency spike must not
  trigger the next decision;
* **bounds** -- ``min_replicas``/``max_replicas`` clamp everything;
* **asymmetric caution** -- a backend the router reports down
  (``router_backend_up == 0``) vetoes scale-in (capacity is already
  reduced; draining a survivor mid-failover compounds the outage) but
  never vetoes scale-out.

Actuation reuses the proven membership primitives: scale-out is
``Fleet.add_replica(migrate=True)`` (moves ~1/N of the keys), scale-in
is ``begin_drain`` + ``complete_drain`` (the victim refuses new asks
with a typed ``Overloaded(reason="draining")`` while its studies
migrate).  The controller is itself observable -- every tick and every
decision is a flight-recorder span plus typed ``pilot_*`` metrics --
and itself crashable: ``PILOT_CRASH_POINTS`` covers the window between
decision and actuation (a restarted pilot just re-scrapes and
re-decides; decisions are stateless functions of the metrics) and the
mid-migration window inside a scale-out (the ring already flipped;
stranded studies heal through the ordinary lazy-adoption path).
"""

from __future__ import annotations

import logging
import threading
import time

from ..distributed.faults import REAL_FS
from ..obs.flightrec import NULL_RECORDER
from ..obs.registry import MetricsRegistry

logger = logging.getLogger(__name__)

__all__ = ["PilotConfig", "PilotSample", "PilotDecision", "FleetPilot",
           "summarize_rows"]


class PilotConfig:
    """The autoscaler's thresholds and discipline knobs.

    Pressure (any one sustained for ``breach_ticks`` ticks scales
    out): summed queue depth >= ``queue_high``; estimated ask p99 >=
    ``p99_high_s`` (None disables); refusals observed since the last
    tick >= ``shed_high`` (0 disables).  Quiet (ALL sustained for
    ``clear_ticks`` ticks scales in): queue depth <= ``queue_low``, no
    refusals, and per-tick mean batch occupancy <= ``occupancy_low``
    (idle ticks with no dispatches count as quiet)."""

    def __init__(self, min_replicas=1, max_replicas=8,
                 queue_high=16.0, queue_low=1.0, p99_high_s=None,
                 shed_high=1.0, occupancy_low=0.25,
                 breach_ticks=2, clear_ticks=3, cooldown_ticks=3,
                 drain_timeout=30.0):
        self.min_replicas = int(min_replicas)
        self.max_replicas = int(max_replicas)
        if not 1 <= self.min_replicas <= self.max_replicas:
            raise ValueError(
                f"need 1 <= min_replicas <= max_replicas, got "
                f"{self.min_replicas}..{self.max_replicas}"
            )
        self.queue_high = float(queue_high)
        self.queue_low = float(queue_low)
        self.p99_high_s = None if p99_high_s is None else float(p99_high_s)
        self.shed_high = float(shed_high)
        self.occupancy_low = float(occupancy_low)
        self.breach_ticks = max(1, int(breach_ticks))
        self.clear_ticks = max(1, int(clear_ticks))
        self.cooldown_ticks = max(0, int(cooldown_ticks))
        self.drain_timeout = float(drain_timeout)


class PilotSample:
    """One tick's view of the fleet, distilled from scraped rows: a
    plain value object so ``decide`` is a function of data, never of
    fleet internals."""

    def __init__(self, replicas, queue_depth, ask_p99_s, shed_total,
                 admitted_total, occupancy_sum, occupancy_count,
                 backends_down):
        self.replicas = tuple(sorted(replicas))
        self.queue_depth = float(queue_depth)
        self.ask_p99_s = float(ask_p99_s)
        self.shed_total = float(shed_total)
        self.admitted_total = float(admitted_total)
        self.occupancy_sum = float(occupancy_sum)
        self.occupancy_count = float(occupancy_count)
        self.backends_down = int(backends_down)

    @property
    def n_replicas(self):
        return len(self.replicas)


class PilotDecision:
    """What one tick concluded: ``action`` in ``{"hold", "scale_out",
    "scale_in"}``, the replica id it targets (None for hold), and the
    human-readable trigger."""

    def __init__(self, action, rid=None, reason=""):
        self.action = action
        self.rid = rid
        self.reason = reason

    def __repr__(self):
        return f"PilotDecision({self.action}, {self.rid!r}, {self.reason!r})"


def _bucket_p99(merged_buckets, total):
    """Upper-bound p99 estimate from per-bucket counts merged across
    replicas ({le: count}); 0.0 with no observations."""
    if total <= 0:
        return 0.0
    target = 0.99 * total
    seen = 0
    for le in sorted(merged_buckets):
        seen += merged_buckets[le]
        if seen >= target:
            return le if le != float("inf") else sorted(merged_buckets)[-2]
    return 0.0


def summarize_rows(rows):
    """Distill one scrape (a list of registry rows, e.g.
    ``Fleet.metrics_rows()`` or the router's aggregated scrape) into a
    :class:`PilotSample`.  Pure: rows in, value object out."""
    replicas = set()
    queue_depth = 0.0
    shed = 0.0
    admitted = 0.0
    occ_sum = 0.0
    occ_count = 0.0
    lat_buckets = {}
    lat_total = 0
    backends_down = 0
    for row in rows:
        name = row.get("name")
        labels = row.get("labels", {})
        rid = labels.get("replica")
        if rid is not None:
            replicas.add(rid)
        if name == "serve_queue_depth" and row.get("value") is not None:
            queue_depth += float(row["value"])
        elif name == "serve_shed_total":
            shed += float(row.get("value") or 0)
        elif name == "serve_admitted_total":
            admitted += float(row.get("value") or 0)
        elif name == "serve_batch_occupancy":
            occ_sum += float(row.get("sum") or 0.0)
            occ_count += float(row.get("count") or 0)
        elif name == "serve_ask_latency_seconds":
            for b in row.get("buckets", ()):
                le = float(b["le"])
                lat_buckets[le] = lat_buckets.get(le, 0) + int(b["count"])
            lat_total += int(row.get("count") or 0)
        elif name == "router_backend_up" and row.get("value") == 0:
            backends_down += 1
    return PilotSample(
        replicas=replicas,
        queue_depth=queue_depth,
        ask_p99_s=_bucket_p99(lat_buckets, lat_total),
        shed_total=shed,
        admitted_total=admitted,
        occupancy_sum=occ_sum,
        occupancy_count=occ_count,
        backends_down=backends_down,
    )


class FleetPilot:
    """The autoscaler: scrape -> summarize -> decide -> actuate.

    ``scrape`` is any zero-arg callable returning registry rows
    (default: the fleet's own in-process exposition -- production
    points it at the router's ``/metrics`` aggregation); ``fleet`` is
    only touched by :meth:`actuate`, through the public membership
    primitives.  Tests drive :meth:`tick` directly; :meth:`run` is the
    production background loop."""

    def __init__(self, fleet, config=None, scrape=None, fs=REAL_FS,
                 recorder=None):
        self.fleet = fleet
        self.config = config if config is not None else PilotConfig()
        self.scrape = scrape if scrape is not None else fleet.metrics_rows
        self.fs = fs
        self.recorder = recorder if recorder is not None else NULL_RECORDER
        self.metrics = MetricsRegistry("pilot")
        self._decisions = self.metrics.counter(
            "pilot_decisions_total", "autoscaler decisions taken",
            labels=("action",),
        )
        self._scale_outs = self.metrics.counter(
            "pilot_scale_outs_total", "replicas added by the autoscaler",
        )
        self._scale_ins = self.metrics.counter(
            "pilot_scale_ins_total", "replicas drained by the autoscaler",
        )
        self._actuation_errors = self.metrics.counter(
            "pilot_actuation_errors_total",
            "actuations refused by the fleet (decision re-derived next "
            "tick)",
        )
        self._out_ms = self.metrics.gauge(
            "pilot_scale_out_ms", "last scale-out wall-clock (add + "
            "1/N-key migration)",
        )
        self._in_ms = self.metrics.gauge(
            "pilot_scale_in_ms", "last scale-in wall-clock (drain + "
            "migrate + retire)",
        )
        self._obs_replicas = self.metrics.gauge(
            "pilot_replicas_observed", "replicas present in the last "
            "scrape",
        )
        self._obs_queue = self.metrics.gauge(
            "pilot_queue_depth_observed", "summed queue depth in the "
            "last scrape",
        )
        # controller state: streaks, cooldown, the previous sample's
        # counter values (per-tick deltas), and the next replica name
        self._breach = 0
        self._clear = 0
        self._cooldown = 0
        self._prev = None
        self._next_rid = 0
        self._thread = None
        self._running = False

    # -- the loop ----------------------------------------------------------
    def tick(self):
        """One control-loop iteration; returns the
        :class:`PilotDecision` it took (after actuating it)."""
        sample = summarize_rows(self.scrape())
        decision = self.decide(sample)
        self._record_decision(sample, decision)
        self.fs.crashpoint("pilot_after_decision_before_actuate")
        if decision.action != "hold":
            self.actuate(decision)
        return decision

    def decide(self, sample):
        """The policy: hysteresis + cooldown + bounds over one
        sample.  Mutates only controller-local streak state."""
        cfg = self.config
        prev = self._prev
        self._prev = sample
        shed_delta = (
            sample.shed_total - prev.shed_total if prev is not None
            else sample.shed_total
        )
        occ_delta_n = (
            sample.occupancy_count - prev.occupancy_count
            if prev is not None else sample.occupancy_count
        )
        occ_delta_sum = (
            sample.occupancy_sum - prev.occupancy_sum
            if prev is not None else sample.occupancy_sum
        )
        occ_mean = occ_delta_sum / occ_delta_n if occ_delta_n > 0 else 0.0
        pressure = []
        if sample.queue_depth >= cfg.queue_high:
            pressure.append(f"queue_depth {sample.queue_depth:.0f} >= "
                            f"{cfg.queue_high:.0f}")
        if cfg.p99_high_s is not None and sample.ask_p99_s >= cfg.p99_high_s:
            pressure.append(f"ask_p99 {sample.ask_p99_s:.3f}s >= "
                            f"{cfg.p99_high_s:.3f}s")
        if cfg.shed_high > 0 and shed_delta >= cfg.shed_high:
            pressure.append(f"shed {shed_delta:.0f} >= "
                            f"{cfg.shed_high:.0f} this tick")
        quiet = (
            sample.queue_depth <= cfg.queue_low
            and shed_delta <= 0
            and occ_mean <= cfg.occupancy_low
        )
        if pressure:
            self._breach += 1
            self._clear = 0
        elif quiet:
            self._clear += 1
            self._breach = 0
        else:
            self._breach = 0
            self._clear = 0
        if self._cooldown > 0:
            self._cooldown -= 1
            return PilotDecision("hold", reason="cooldown")
        if (
            pressure
            and self._breach >= cfg.breach_ticks
            and sample.n_replicas < cfg.max_replicas
        ):
            rid = self._fresh_rid(sample)
            return PilotDecision("scale_out", rid=rid,
                                 reason="; ".join(pressure))
        if (
            quiet
            and self._clear >= cfg.clear_ticks
            and sample.n_replicas > cfg.min_replicas
            and sample.backends_down == 0
        ):
            # deterministic victim: the lexicographically last replica
            # the scrape observed -- pure function of the sample
            return PilotDecision(
                "scale_in", rid=max(sample.replicas),
                reason=f"quiet x{self._clear} (queue "
                f"{sample.queue_depth:.0f}, occupancy {occ_mean:.2f})",
            )
        return PilotDecision("hold", reason="within bounds")

    def _fresh_rid(self, sample):
        """The next pilot-spawned replica name not present in the
        scrape (controller-local counter; a collision with a dead,
        unscraped member surfaces as an actuation error and the
        counter moves past it)."""
        while f"p{self._next_rid}" in sample.replicas:
            self._next_rid += 1
        return f"p{self._next_rid}"

    def _record_decision(self, sample, decision):
        self._obs_replicas.set(sample.n_replicas)
        self._obs_queue.set(sample.queue_depth)
        self._decisions.labels(action=decision.action).inc()
        if self.recorder.enabled:
            self.recorder.event(
                "pilot.tick", action=decision.action,
                rid=decision.rid, reason=decision.reason,
                replicas=sample.n_replicas,
                queue_depth=sample.queue_depth,
                ask_p99_s=sample.ask_p99_s,
                backends_down=sample.backends_down,
            )

    def actuate(self, decision):
        """Execute one non-hold decision through the fleet's public
        membership primitives, timing it into the ``pilot_*`` gauges.
        A fleet refusal (e.g. the rid joined or left by another path
        since the scrape) is counted and absorbed: the next tick
        re-scrapes and re-decides."""
        cfg = self.config
        if decision.action not in ("scale_out", "scale_in"):
            return
        t0 = time.perf_counter()
        rec = self.recorder
        try:
            if decision.action == "scale_out":
                self.fleet.add_replica(decision.rid, migrate=True)
                self._next_rid += 1
                self._out_ms.set_duration_ms(t0)
                self._scale_outs.inc()
            else:
                self.fleet.begin_drain(
                    decision.rid, timeout=cfg.drain_timeout
                )
                self.fleet.complete_drain(decision.rid)
                self._in_ms.set_duration_ms(t0)
                self._scale_ins.inc()
        except (ValueError, KeyError) as e:
            self._actuation_errors.inc()
            self._next_rid += 1  # never retry the same contested name
            logger.warning(
                "pilot: %s %r refused by the fleet (%s); will "
                "re-decide from the next scrape",
                decision.action, decision.rid, e,
            )
            return
        finally:
            self._cooldown = cfg.cooldown_ticks
            self._breach = 0
            self._clear = 0
        if rec.enabled:
            rec.record(
                "pilot.decision", t0, time.perf_counter(),
                action=decision.action, rid=decision.rid,
                reason=decision.reason,
            )
        logger.info(
            "pilot: %s %r (%s)", decision.action, decision.rid,
            decision.reason,
        )

    # -- background loop (production posture) ------------------------------
    def run(self, interval=1.0):
        """Tick on a daemon thread every ``interval`` seconds (tests
        call :meth:`tick` directly for determinism)."""
        if self._thread is not None:
            return
        self._running = True
        interval = float(interval)

        def _loop():
            while self._running:
                try:
                    self.tick()
                except Exception:  # graftlint: disable=GL302 the control loop must outlive any one bad scrape/actuation; the failure is logged and the next tick re-derives from fresh metrics
                    logger.exception("pilot: tick failed; continuing")
                time.sleep(interval)

        self._thread = threading.Thread(
            target=_loop, name="graftpilot", daemon=True
        )
        self._thread.start()

    def stop(self):
        self._running = False
        t = self._thread
        self._thread = None
        if t is not None:
            t.join(timeout=5.0)

    def metrics_rows(self):
        return self.metrics.collect()
