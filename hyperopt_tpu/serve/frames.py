"""graftburst wire layer: negotiated binary framing + pipelining.

The serve/router TCP seam started as JSON-lines -- one JSON object per
request line, one reply line each, in lockstep.  That costs a JSON
encode/decode per message and a full round trip per request.  This
module closes both gaps without breaking a single deployed peer:

* **Version negotiation** rides the JSON-line protocol itself.  A new
  client's first line is ``{"op": "hello", "proto": 2}``.  A new server
  replies ``{"ok": true, "proto": 2}`` and both sides switch to binary
  frames for the rest of the connection.  An old server answers the
  unknown op with ``ok: false`` -- the client stays on JSON-lines.  An
  old client never says hello -- the server stays on JSON-lines for
  that connection.  Nobody needs a flag day.

* **Binary frames** are a 4-byte big-endian length prefix followed by a
  msgpack-style payload (single-byte type tags + fixed-width struct
  packs; the tag values match msgpack's wide forms, the subset is what
  the serve protocol actually ships: None/bool/int/float/str/bytes/
  list/dict).  No third-party codec -- the whole thing is ~100 lines of
  ``struct``.

* **Pipelining** replaces lockstep with rid correlation: every request
  carries a monotone ``rid``, every reply echoes it, and
  :class:`FrameConn` keeps N requests in flight per connection,
  resolving each reply onto the right future whatever order it lands
  in.  Old JSON-line servers reply strictly in order and may not echo
  rids; an rid-less reply therefore resolves the oldest pending future
  (FIFO), which is exactly correct for an in-order peer.

Failure discipline: a frame whose declared length exceeds
:data:`MAX_FRAME` (or is garbage) and a payload that does not decode
are **typed errors** (:class:`FrameError`) -- the server replies with
``error_type: "FrameError"`` and closes, never hangs.  A short read is
EOF mid-frame: the connection is over (:class:`FrameError` on the
reader so callers distinguish it from a clean close).
"""

from __future__ import annotations

import json
import socket
import struct

from ..exceptions import HyperoptTpuError, NetworkTimeout, PeerUnreachable

__all__ = [
    "PROTO_V1",
    "PROTO_V2",
    "MAX_FRAME",
    "DEFAULT_CONNECT_TIMEOUT",
    "DEFAULT_READ_TIMEOUT",
    "FrameError",
    "DeadlineFile",
    "dial",
    "pack",
    "unpack",
    "read_frame",
    "write_frame",
    "FrameConn",
]

PROTO_V1 = 1  # JSON-lines, lockstep (the original seam)
PROTO_V2 = 2  # length-prefixed binary frames, pipelined

#: graftstorm defaults: every outbound socket gets BOTH deadlines --
#: nothing in the serve stack is allowed to block forever on a silent
#: peer (the GL309 contract).
DEFAULT_CONNECT_TIMEOUT = 5.0
DEFAULT_READ_TIMEOUT = 30.0

#: refuse to allocate for a frame longer than this (a malformed or
#: hostile length prefix must be a typed error, not an OOM)
MAX_FRAME = 64 * 1024 * 1024

# msgpack's wide-form type tags (the subset the serve protocol ships)
_T_NIL = 0xC0
_T_FALSE = 0xC2
_T_TRUE = 0xC3
_T_BIN = 0xC6    # + u32 length + bytes
_T_FLOAT = 0xCB  # + f64 big-endian
_T_INT = 0xD3    # + i64 big-endian
_T_STR = 0xDB    # + u32 length + utf-8 bytes
_T_LIST = 0xDD   # + u32 count + items
_T_MAP = 0xDF    # + u32 count + key/value pairs

_U32 = struct.Struct(">I")
_F64 = struct.Struct(">d")
_I64 = struct.Struct(">q")


class FrameError(HyperoptTpuError):
    """A binary frame could not be read or decoded: oversized or
    garbled length prefix, truncated payload (EOF mid-frame), unknown
    type tag, or an undecodable body.  The transport converts this
    into a typed error reply (``error_type: "FrameError"``) and closes
    the connection -- past a framing error the stream offset is
    meaningless, so resynchronization is not attempted."""


# ---------------------------------------------------------------------------
# dialing: deadlines on every outbound socket
# ---------------------------------------------------------------------------


class DeadlineFile:
    """File-object proxy that converts a missed socket deadline into
    the typed :class:`~..exceptions.NetworkTimeout`.

    ``socket.create_connection(timeout=...)`` leaves the timeout set on
    the socket, so every ``makefile`` read/write inherits it -- but a
    miss surfaces as ``socket.timeout``, which callers would have to
    distinguish from real ``OSError`` transport failures by hand.  This
    proxy does the conversion once, at the transport seam, so the
    failover/retry machinery matches on the typed hierarchy."""

    def __init__(self, f, peer=None):
        self._f = f
        self._peer = peer

    def _timeout(self, op, e):
        raise NetworkTimeout(
            f"socket {op} missed its deadline"
            + (f" (peer {self._peer})" if self._peer else "")
        ) from e

    def read(self, n=-1):
        try:
            return self._f.read(n)
        except socket.timeout as e:
            self._timeout("read", e)

    def readline(self, limit=-1):
        try:
            return self._f.readline(limit)
        except socket.timeout as e:
            self._timeout("read", e)

    def write(self, b):
        try:
            return self._f.write(b)
        except socket.timeout as e:
            self._timeout("write", e)

    def flush(self):
        try:
            self._f.flush()
        except socket.timeout as e:
            self._timeout("write", e)

    def close(self):
        self._f.close()

    @property
    def closed(self):
        return self._f.closed

    def __getattr__(self, name):
        return getattr(self._f, name)


def dial(host, port, connect_timeout=DEFAULT_CONNECT_TIMEOUT,
         read_timeout=DEFAULT_READ_TIMEOUT, net_plan=None, key=None):
    """Open one deadline-armed transport to ``(host, port)``.

    The single connection-creation seam for the whole serve stack
    (client transport, router backend conns, probes, obs CLI): connect
    failures surface typed :class:`~..exceptions.PeerUnreachable`, the
    connect deadline stays on the socket as the read/write deadline
    (missed reads surface typed :class:`~..exceptions.NetworkTimeout`
    via :class:`DeadlineFile`), and an optional
    :class:`~..distributed.netfaults.NetFaultPlan` wraps the handle so
    chaos suites inject wire faults at exactly the production seam.

    Returns ``(sock, f)`` -- the socket (for callers that need
    ``close``/peer info) and the wrapped ``rwb`` file handle ready for
    :class:`FrameConn`."""
    try:
        sock = socket.create_connection((host, port), timeout=connect_timeout)
    except socket.timeout as e:
        raise PeerUnreachable(
            f"connect to {host}:{port} missed its {connect_timeout}s deadline"
        ) from e
    except OSError as e:
        raise PeerUnreachable(f"connect to {host}:{port} failed: {e}") from e
    sock.settimeout(read_timeout)
    f = sock.makefile("rwb")
    if net_plan is not None:
        f = net_plan.wrap(f, sock=sock, key=key)
    return sock, DeadlineFile(f, peer=f"{host}:{port}")


# ---------------------------------------------------------------------------
# codec
# ---------------------------------------------------------------------------


def _pack_into(obj, out):
    if obj is None:
        out.append(bytes([_T_NIL]))
    elif obj is True:
        out.append(bytes([_T_TRUE]))
    elif obj is False:
        out.append(bytes([_T_FALSE]))
    elif isinstance(obj, int):
        out.append(bytes([_T_INT]) + _I64.pack(obj))
    elif isinstance(obj, float):
        out.append(bytes([_T_FLOAT]) + _F64.pack(obj))
    elif isinstance(obj, str):
        b = obj.encode("utf-8")
        out.append(bytes([_T_STR]) + _U32.pack(len(b)) + b)
    elif isinstance(obj, (bytes, bytearray)):
        out.append(bytes([_T_BIN]) + _U32.pack(len(obj)) + bytes(obj))
    elif isinstance(obj, (list, tuple)):
        out.append(bytes([_T_LIST]) + _U32.pack(len(obj)))
        for item in obj:
            _pack_into(item, out)
    elif isinstance(obj, dict):
        out.append(bytes([_T_MAP]) + _U32.pack(len(obj)))
        for k, v in obj.items():
            _pack_into(k, out)
            _pack_into(v, out)
    else:
        raise TypeError(
            f"frame codec cannot encode {type(obj).__name__!r} "
            "(the wire protocol ships JSON-able values only)"
        )


def pack(obj):
    """Encode one protocol value to bytes."""
    out = []
    _pack_into(obj, out)
    return b"".join(out)


def _unpack_from(buf, pos):
    try:
        tag = buf[pos]
    except IndexError:
        raise FrameError("truncated frame: type tag past end of payload")
    pos += 1
    try:
        if tag == _T_NIL:
            return None, pos
        if tag == _T_TRUE:
            return True, pos
        if tag == _T_FALSE:
            return False, pos
        if tag == _T_INT:
            return _I64.unpack_from(buf, pos)[0], pos + 8
        if tag == _T_FLOAT:
            return _F64.unpack_from(buf, pos)[0], pos + 8
        if tag in (_T_STR, _T_BIN):
            n = _U32.unpack_from(buf, pos)[0]
            pos += 4
            raw = buf[pos:pos + n]
            if len(raw) != n:
                raise FrameError("truncated frame: short str/bin body")
            return (
                raw.decode("utf-8") if tag == _T_STR else bytes(raw),
                pos + n,
            )
        if tag == _T_LIST:
            n = _U32.unpack_from(buf, pos)[0]
            pos += 4
            items = []
            for _ in range(n):
                item, pos = _unpack_from(buf, pos)
                items.append(item)
            return items, pos
        if tag == _T_MAP:
            n = _U32.unpack_from(buf, pos)[0]
            pos += 4
            d = {}
            for _ in range(n):
                k, pos = _unpack_from(buf, pos)
                v, pos = _unpack_from(buf, pos)
                d[k] = v
            return d, pos
    except struct.error as e:
        raise FrameError(f"truncated frame: {e}") from e
    except UnicodeDecodeError as e:
        raise FrameError(f"undecodable frame string: {e}") from e
    raise FrameError(f"unknown frame type tag 0x{tag:02x}")


def unpack(buf):
    """Decode one protocol value; the payload must be exactly one."""
    obj, pos = _unpack_from(buf, 0)
    if pos != len(buf):
        raise FrameError(
            f"frame payload has {len(buf) - pos} trailing byte(s)"
        )
    return obj


# ---------------------------------------------------------------------------
# framing
# ---------------------------------------------------------------------------


def _read_exact(rfile, n):
    """n bytes or None at a clean EOF boundary; FrameError mid-read."""
    chunks, got = [], 0
    while got < n:
        chunk = rfile.read(n - got)
        if not chunk:
            if got == 0:
                return None
            raise FrameError(
                f"truncated frame: EOF after {got}/{n} byte(s)"
            )
        chunks.append(chunk)
        got += len(chunk)
    return b"".join(chunks)


def read_frame(rfile):
    """One decoded frame, or None at clean EOF (connection closed
    between frames).  Raises :class:`FrameError` for anything torn."""
    head = _read_exact(rfile, 4)
    if head is None:
        return None
    n = _U32.unpack(head)[0]
    if n == 0 or n > MAX_FRAME:
        raise FrameError(
            f"frame length {n} out of range (1..{MAX_FRAME}) -- "
            "malformed prefix or a non-frame peer"
        )
    payload = _read_exact(rfile, n)
    if payload is None:
        raise FrameError("truncated frame: EOF before payload")
    return unpack(payload)


def write_frame(wfile, obj):
    payload = pack(obj)
    wfile.write(_U32.pack(len(payload)) + payload)


# ---------------------------------------------------------------------------
# the pipelined client connection
# ---------------------------------------------------------------------------


class FrameConn:
    """One negotiated client connection with request pipelining.

    ``submit(req)`` writes the request (stamped with a fresh ``rid``)
    and returns a Future immediately; any number may be in flight.
    ``call(req)`` is submit + drain until that reply lands.  Replies
    resolve by rid match; an rid-less reply (old JSON-line server,
    which answers strictly in order) resolves the oldest pending
    future.  NOT thread-safe -- the router gives each handler thread
    its own connection map, which is the intended shape.
    """

    def __init__(self, f, negotiate=True):
        self.f = f
        self.binary = False
        self._next_rid = 0
        self._pending = {}  # rid -> Future
        self._order = []    # FIFO of rids for rid-less (v1) replies
        if negotiate:
            self._hello()

    def _hello(self):
        """One JSON line each way; switch to binary iff the server
        speaks proto >= 2 (an old server's unknown-op error leaves the
        connection in JSON-line mode -- that IS the fallback)."""
        self.f.write(
            (json.dumps({"op": "hello", "proto": PROTO_V2}) + "\n")
            .encode("utf-8")
        )
        self.f.flush()
        line = self.f.readline()
        if not line:
            raise ConnectionError("backend closed during hello")
        try:
            reply = json.loads(line)
        except ValueError as e:
            raise ConnectionError(f"garbled hello reply: {e}") from e
        if not reply.get("ok") and reply.get("error_type") == "Overloaded":
            # the server front's connection-cap refusal (graftstorm):
            # a typed, retryable rejection sent pre-negotiation -- NOT
            # an old server's unknown-op error, which must stay the
            # silent JSON-line fallback
            from ..exceptions import Overloaded

            raise Overloaded(
                reply.get("error") or "connection refused at the cap",
                retry_after=reply.get("retry_after"),
                reason=reply.get("reason") or "max_connections",
            )
        self.binary = bool(
            reply.get("ok") and int(reply.get("proto", PROTO_V1)) >= PROTO_V2
        )

    def submit(self, req):
        from concurrent.futures import Future

        rid = self._next_rid
        self._next_rid += 1
        fut = Future()
        self._pending[rid] = fut
        self._order.append(rid)
        wire = dict(req, rid=rid)
        if self.binary:
            write_frame(self.f, wire)
        else:
            self.f.write((json.dumps(wire) + "\n").encode("utf-8"))
        self.f.flush()
        return fut

    def _read_one(self):
        """Pull the next reply off the wire and resolve its future."""
        if self.binary:
            reply = read_frame(self.f)
            if reply is None:
                raise ConnectionError("backend closed the connection")
        else:
            line = self.f.readline()
            if not line:
                raise ConnectionError("backend closed the connection")
            reply = json.loads(line)
        rid = reply.get("rid") if isinstance(reply, dict) else None
        if rid is None and self._order:
            rid = self._order[0]
        fut = self._pending.pop(rid, None)
        if rid in self._order:
            self._order.remove(rid)
        if fut is not None:
            fut.set_result(reply)
        return reply

    def drain(self, fut):
        """Read replies until ``fut`` resolves; returns its reply."""
        while not fut.done():
            self._read_one()
        return fut.result()

    def call(self, req):
        return self.drain(self.submit(req))

    def close(self):
        for fut in self._pending.values():
            if not fut.done():
                fut.set_exception(
                    ConnectionError("connection closed with the "
                                    "request still in flight")
                )
        self._pending.clear()
        self._order.clear()
        try:
            self.f.close()
        except OSError:
            pass
