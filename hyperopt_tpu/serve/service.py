"""The service front: study handles, WAL durability, socket transport.

Three layers on top of the :mod:`~hyperopt_tpu.serve.scheduler`:

* :class:`SuggestService` / :class:`StudyHandle` -- the in-process API
  (``create_study / ask / tell / best``), the multi-tenant twin of the
  paper's ask/tell plugin boundary;
* :class:`StudyPersistence` -- per-study durability riding the PR-6
  machinery: every tell is appended to a :class:`~hyperopt_tpu.utils.
  wal.TellWAL` (fsync-durable, checksummed, guard-fingerprinted)
  BEFORE it is applied, ask records carry the post-draw rstate cursor
  (flush-only -- the next tell's fsync covers them), and cadence-driven
  snapshot bundles (``durable_pickle`` of the dense history npz + the
  cursor) compact the log.  A service killed mid-batch restores every
  study with zero lost / zero duplicated tells and a suggestion stream
  that continues exactly where it stopped;
* a stdlib JSON-line TCP transport (:func:`serve_forever`) behind the
  ``hyperopt-tpu-serve`` console script, so external clients drive the
  same API over a socket -- one JSON object per line, one reply line
  per request.
"""

from __future__ import annotations

import json
import logging
import os
import re
import threading

import numpy as np

from ..distributed.faults import REAL_FS
from ..exceptions import DeadlineExpired, Overloaded, ServeError
from ..ops.compile import compile_space
from ..utils.wal import TellWAL
from .scheduler import BatchScheduler, ServeStudy

logger = logging.getLogger(__name__)

__all__ = [
    "StudyHandle",
    "StudyPersistence",
    "SuggestService",
    "serve_forever",
    "main",
]

_NAME_RE = re.compile(r"[A-Za-z0-9._-]{1,120}")

#: ceiling on any server-provided ``retry_after`` hint a backoff loop
#: will honor (seconds): the hint is advisory pacing, and a pathological
#: or drain-length value must never turn one retry sleep into the whole
#: client deadline.  Shared by :meth:`StudyHandle.ask`, the fmin
#: client's submit loop, and the router's drain-absorbing retry.
RETRY_AFTER_CAP = 5.0

#: compiled spaces keyed by structural fingerprint: a RESTARTED service
#: over the same space (the crash-recovery loop, and every test
#: harness) reuses the PackedSpace -- and with it the program cache the
#: batched builders hang off it -- instead of recompiling from scratch.
_PS_CACHE = {}


def _compile_space_cached(space):
    from ..hyperband import _space_fingerprint
    from ..pyll.base import as_apply

    fp = _space_fingerprint(as_apply(space))
    ps = _PS_CACHE.get(fp)
    if ps is None:
        ps = compile_space(space)
        _PS_CACHE[fp] = ps
    return ps


def _study_guard(algo, space):
    """The study-family fingerprint stamped into every WAL/snapshot
    (PR-3/6 guard discipline): restoring a study dir written by a
    different space or algo silently changes the experiment and must
    be refused instead."""
    from ..hyperband import _space_fingerprint
    from ..pyll.base import as_apply

    return ["graftserve", 1, str(algo), _space_fingerprint(as_apply(space))]


class StudyPersistence:  # graftlint: disable=GL605 the serve crash windows fire at the scheduler batching layer (serve_after_wal_before_dispatch / group-commit barriers bracket these appends from above), and the TellWAL primitive itself rides the driver windows
    """Per-study WAL + snapshot bundle rooted at ``<root>/<name>``.

    Artifacts: ``<name>.wal`` (the :class:`TellWAL`: ``open`` / ``ask``
    / ``served`` / ``tell`` records) and ``<name>.snap`` (the durable
    snapshot bundle the WAL compacts into every ``cadence`` tells).
    Write-ahead ordering is the crash-recovery contract: a tell is on
    disk before the host buffer mutates, so replay after a crash is
    exactly-once (dedup by tid)."""

    def __init__(self, root, name, guard, fs=REAL_FS, cadence=256):
        self.root = str(root)
        self.name = name
        self.fs = fs
        self.cadence = max(1, int(cadence))
        self.fs.makedirs(self.root, exist_ok=True)
        base = os.path.join(self.root, name)
        self.snap_path = base + ".snap"
        self.wal = TellWAL(base + ".wal", fs=fs, guard=guard)
        self._tells_since_snap = 0

    def _snap_exists(self):
        from ..distributed import _common

        return _common.with_retries(
            lambda: self.fs.exists(self.snap_path), label="snap exists"
        )

    def exists(self):
        return self.wal.exists() or self._snap_exists()

    # -- write-ahead records ----------------------------------------------
    def log_open(self, seed):
        self.wal.append("open", {"seed": int(seed)})

    def log_ask(self, tid, seed, rstate):
        from ..utils.checkpoint import encode_rstate

        # flush-only: a lost ask re-derives bitwise from the restored
        # cursor; the tell's fsync barrier covers it (PR-6 semantics)
        self.wal.append("ask", {
            "tid": int(tid),
            "seed": int(seed),
            "rstate": encode_rstate(rstate),
        }, sync=False)

    def log_served(self, tid, vals):
        self.wal.append(
            "served", {"tid": int(tid), "vals": dict(vals)}, sync=False
        )

    def log_tell(self, tid, vals, loss, result=None, sync=True):
        """``sync=False`` is the group-commit half of the PR-6 idiom:
        the tell is flushed (kernel-visible, process-crash safe) and
        the scheduler's per-round :meth:`TellWAL.barrier` establishes
        the machine-crash durability point for the whole round at one
        fsync instead of one per tell."""
        body = {"tid": int(tid), "vals": dict(vals), "loss": float(loss)}
        if result is not None:
            # graftclient: the full SONified result dict rides the tell
            # record, so a resumed fmin client rebuilds its Trials docs
            # (arbitrary objective-returned keys included) from the one
            # unified WAL instead of a driver-WAL twin
            body["result"] = result
        self.wal.append("tell", body, sync=sync)
        self._tells_since_snap += 1

    def log_fail(self, tid, doc=None):
        """One FAILED evaluation, durable before the doc finalizes
        (graftclient): nothing enters the posterior, but the outcome --
        including the client's error/traceback payload -- survives a
        crash, so a resumed run never re-runs a known-bad trial."""
        body = {"tid": int(tid)}
        if doc is not None:
            body["doc"] = doc
        self.wal.append("fail", body)
        self._tells_since_snap += 1

    # -- snapshot bundles --------------------------------------------------
    def maybe_snapshot(self, study, force=False):
        if not force and self._tells_since_snap < self.cadence:
            return False
        self.snapshot(study)
        return True

    def snapshot(self, study):
        """Publish the durable bundle, then compact the WAL (the PR-6
        checkpoint protocol: every crash window between the two is
        covered by tid-dedup replay of the old log)."""
        from ..distributed import _common
        from ..utils.checkpoint import (
            durable_pickle,
            encode_rstate,
            obs_buffer_npz_bytes,
        )

        bundle = {
            "format": 1,
            "guard": self.wal.guard,
            "seed": study.seed,
            "obs_npz": obs_buffer_npz_bytes(study.buf),
            "rstate": encode_rstate(study.rstate),
            "next_tid": int(study.next_tid),
            "n_asks": int(study.n_asks),
            "n_tells": int(study.n_tells),
            "total_tells": int(self.wal.total_tells),
            "outstanding": {
                int(t): dict(v) for t, v in study.outstanding.items()
            },
            # restored-but-not-yet-re-served in-flight asks survive a
            # snapshot that compacts their WAL records away
            "pending_asks": {
                int(t): int(s) for t, s in study.pending_asks.items()
            },
        }
        if study.client_state_fn is not None:
            # graftclient: the fmin client's durable state (its SONified
            # Trials docs) rides the SAME bundle -- one snapshot, one
            # WAL, one durability story for engine and driver state
            bundle["client"] = study.client_state_fn()
        _common.with_retries(
            lambda: durable_pickle(bundle, self.snap_path, fs=self.fs),
            label="serve snapshot",
        )
        _common.with_retries(self.wal.reset, label="serve wal reset")
        self._tells_since_snap = 0

    # -- restore -----------------------------------------------------------
    def restore(self, ps):
        """Rebuild the study from snapshot + WAL-suffix replay, or
        return None when no artifact exists.  Tells replay exactly
        once (dedup by tid); the rstate cursor of the last logged ask
        supersedes the snapshot's, so the seed stream continues
        bitwise where the crashed service stopped."""
        from ..exceptions import CheckpointError
        from ..utils.checkpoint import (
            decode_rstate,
            load_obs_buffer_bytes,
            load_pickle_guarded,
        )

        if not self.exists():
            return None
        bundle = None
        if self._snap_exists():
            bundle = load_pickle_guarded(
                self.snap_path, fs=self.fs, what="study snapshot"
            )
            if (
                self.wal.guard is not None
                and bundle.get("guard") is not None
                and list(bundle["guard"]) != list(self.wal.guard)
            ):
                raise CheckpointError(
                    f"study snapshot {self.snap_path!r} was written by "
                    f"a different study family (guard {bundle['guard']!r}"
                    f" != {self.wal.guard!r}); refusing to restore"
                )
        seed = int(bundle["seed"]) if bundle else 0
        study = ServeStudy(self.name, seed, ps)
        if bundle is not None:
            study.buf = load_obs_buffer_bytes(ps, bundle["obs_npz"])
            study.rstate = decode_rstate(bundle["rstate"])
            study.next_tid = int(bundle["next_tid"])
            study.n_asks = int(bundle["n_asks"])
            study.n_tells = int(bundle["n_tells"])
            study.outstanding = {
                int(t): dict(v)
                for t, v in bundle.get("outstanding", {}).items()
            }
            study.pending_asks = {
                int(t): int(s)
                for t, s in bundle.get("pending_asks", {}).items()
            }
        records = self.wal.replay() if self.wal.exists() else []
        last_cursor = None
        for rec in records:
            kind = rec.get("kind")
            if kind == "open":
                study.seed = int(rec["seed"])
                if bundle is None:
                    study.rstate = np.random.default_rng(study.seed)
            elif kind == "ask":
                tid = int(rec["tid"])
                study.next_tid = max(study.next_tid, tid + 1)
                last_cursor = rec["rstate"]
                # in-flight until a served/tell record supersedes it:
                # the logged seed lets the new owner re-serve the ask
                # bitwise (suggestion = f(seed, history))
                study.pending_asks[tid] = int(rec["seed"])
            elif kind == "served":
                tid = int(rec["tid"])
                study.outstanding[tid] = dict(rec["vals"])
                study.pending_asks.pop(tid, None)
            elif kind == "tell":
                tid = int(rec["tid"])
                buf = study.buf
                if not (buf.tids[: buf.count] == tid).any():
                    buf.add(dict(rec["vals"]), float(rec["loss"]), tid=tid)
                    study.n_tells += 1
                study.next_tid = max(study.next_tid, tid + 1)
                study.outstanding.pop(tid, None)
                study.pending_asks.pop(tid, None)
            elif kind == "fail":
                # a durably-failed evaluation: nothing entered the
                # posterior, but the ask is settled -- never re-served,
                # never re-run (graftclient exactly-once contract)
                tid = int(rec["tid"])
                study.next_tid = max(study.next_tid, tid + 1)
                study.outstanding.pop(tid, None)
                study.pending_asks.pop(tid, None)
        if last_cursor is not None:
            study.rstate = decode_rstate(last_cursor)
        # the client's restore seam: its bundle blob plus the replayed
        # WAL suffix (doc rebuild needs the served/tell/fail payloads)
        study.client_blob = bundle.get("client") if bundle else None
        study.restore_records = records
        study.dirty = True
        return study

    def close(self):
        self.wal.close()


class StudyHandle:
    """One tenant's view of the service: the ask/tell plugin boundary
    as an object.  ``ask`` returns ``(tid, vals)``; evaluate, then
    ``tell(tid, loss)`` -- the service remembers what it suggested for
    every outstanding tid (durably, when a root is configured), so the
    caller never round-trips the config back."""

    def __init__(self, service, study):
        self._service = service
        self._study = study

    @property
    def name(self):
        return self._study.name

    def ask_async(self):
        """Queue one ask; returns a Future of ``(tid, vals)``.  Raises
        :class:`~hyperopt_tpu.exceptions.Overloaded` (with a
        ``retry_after`` hint) when admission control refuses the
        submit."""
        return self._service._ask_async(self._study)

    def ask(self, timeout=60.0, recover=False, backoff=False):
        """One suggestion, blocking until its batch is served.

        ``timeout`` doubles as the CLIENT DEADLINE the scheduler
        sheds against: an ask still queued when it passes is dropped
        from the queue (it will never consume a dispatch slot) and
        raises :class:`~hyperopt_tpu.exceptions.DeadlineExpired`; one
        already picked into an in-flight dispatch is awaited a short
        grace period instead.

        ``backoff=True`` turns an admission refusal
        (:class:`~hyperopt_tpu.exceptions.Overloaded`) into bounded
        retry-with-backoff UNDER THE SAME DEADLINE: the client sleeps
        the refusal's ``retry_after`` hint (never past the deadline)
        and resubmits; when the deadline cannot fit another retry the
        typed escalation is :class:`~hyperopt_tpu.exceptions.
        DeadlineExpired`, never a silent full-timeout hang.  This is
        what a waiting ``fmin`` client uses -- backpressure is a pace
        signal, not a failure.

        ``recover=True`` is the retrying client's declaration that its
        PREVIOUS ask's reply was lost (replica failover, router crash
        between forward and ack): the smallest undelivered suggestion
        is re-served instead of drawing a fresh one -- a restored
        in-flight ask re-dispatches with its WAL-logged seed (bitwise
        what the crashed owner would have served), a served-but-unacked
        one returns its recorded vals directly.  With one logical
        client per study this gives exactly-once delivery; concurrent
        clients of one study should not pass it casually."""
        import time as _time

        if recover:
            got = self._service._recover_ask(self._study, timeout)
            if got is not None:
                return got
        deadline = _time.perf_counter() + float(timeout)
        while True:
            remaining = deadline - _time.perf_counter()
            try:
                req = self._service._submit(
                    self._study, timeout=max(remaining, 0.0)
                )
            except Overloaded as e:
                if not backoff:
                    raise
                # honor the server's jittered retry_after hint, capped:
                # the hint paces the herd, the cap bounds one sleep
                wait = min(
                    e.retry_after if e.retry_after else 0.05,
                    RETRY_AFTER_CAP,
                )
                if _time.perf_counter() + wait >= deadline:
                    raise DeadlineExpired(
                        f"study {self._study.name!r}: the service stayed "
                        f"overloaded ({e.reason}) past the client "
                        f"deadline ({timeout}s); last retry_after hint "
                        f"was {wait}s"
                    ) from e
                _time.sleep(wait)
                continue
            return self._service._await(req, max(remaining, 0.0))

    def tell(self, tid, loss, vals=None, result=None):
        """Report one evaluation.  ``vals`` defaults to what the
        service served for ``tid``; pass it explicitly when re-telling
        work whose ack a crashed service lost.  ``result`` (optional,
        JSON-able) is stored on the durable tell record -- the fmin
        client rides its full result dict along so resume can rebuild
        Trials docs from the one WAL."""
        self._service._tell(self._study, tid, loss, vals, result=result)

    def fail(self, tid, doc=None):
        """Report one FAILED evaluation: the suggestion for ``tid`` is
        retired (never re-served, nothing enters the posterior) and
        the failure -- with the optional JSON-able ``doc`` payload
        (error, traceback) -- is WAL-durable first, so a resumed
        client never re-runs a known-bad trial."""
        self._service._fail(self._study, tid, doc)

    def best(self):
        """``{"loss", "vals"}`` of the best completed trial, or None."""
        out = self._study.best()
        if out is None:
            return None
        loss, vals = out
        return {"loss": loss, "vals": vals}

    @property
    def n_tells(self):
        return self._study.n_tells

    def close(self):
        self._service.close_study(self.name)


class SuggestService:
    """The multi-tenant suggestion service over one space template.

    ``background=True`` (default) runs the continuous-batching loop on
    a daemon thread: concurrent ``ask()`` calls from many studies
    coalesce into shared device dispatches under the ``max_wait_ms``
    latency budget.  ``background=False`` is the deterministic mode the
    tests and chaos harness drive: submit with ``ask_async`` and pump
    rounds explicitly with :meth:`pump` (blocking ``ask`` still works
    -- it pumps inline).

    ``root`` enables per-study WAL durability (:class:`
    StudyPersistence`); ``create_study`` then restores any study the
    root already holds.  ``fs`` is the PR-3 fault seam shared by the
    scheduler and every WAL/snapshot write.
    """

    def __init__(self, space, algo="tpe", root=None, max_batch=64,
                 max_wait_ms=2.0, n_startup_jobs=20, background=True,
                 fs=REAL_FS, snapshot_cadence=256, max_queue=None,
                 study_queue_cap=None, dispatch_timeout=None,
                 finite_check=True, mesh=None, owner=None, recorder=None,
                 device_metrics_every=0, retry_jitter=0.25,
                 retry_jitter_seed=0, group_commit=True, **algo_kw):
        self.space = space
        self.ps = _compile_space_cached(space)
        self.root = None if root is None else str(root)
        self.fs = fs
        self.snapshot_cadence = int(snapshot_cadence)
        self._guard = _study_guard(algo, space)
        self._background = bool(background)
        # fleet identity: with an owner id AND a (shared) root, every
        # study is fenced by a per-study claim/epoch token -- a replica
        # that lost its claim (failover, migration) gets OwnershipLost
        # instead of double-serving (graftfleet; the distributed/
        # claim-token idiom at the study granularity)
        self.owner = None if owner is None else str(owner)
        self._lock = threading.RLock()
        self._handles = {}
        self.scheduler = BatchScheduler(
            self.ps, algo=algo, max_batch=max_batch,
            max_wait=float(max_wait_ms) / 1000.0,
            n_startup_jobs=n_startup_jobs, fs=fs, max_queue=max_queue,
            study_queue_cap=study_queue_cap,
            dispatch_timeout=dispatch_timeout,
            finite_check=finite_check, mesh=mesh, recorder=recorder,
            device_metrics_every=device_metrics_every,
            retry_jitter=retry_jitter,
            retry_jitter_seed=retry_jitter_seed,
            group_commit=group_commit, **algo_kw,
        )
        # graftscope identity: every series and span a fleet replica
        # emits carries its owner id, so the router-side merge can
        # tell replicas apart without re-tagging
        self.recorder = self.scheduler.recorder
        if self.owner is not None:
            self.scheduler.metrics.const_labels["replica"] = self.owner
            self.scheduler.span_ids["replica"] = self.owner
        if self._background:
            self.scheduler.start()

    # -- tenancy -----------------------------------------------------------
    def create_study(self, name, seed=0, takeover=False, host_algo=None):  # graftlint: disable=GL503 the durable open record must be atomic with the registry insert -- two racing creates of one name must serialize restore-or-create, and an unrecorded-but-registered study would lose its seed on crash
        """Open (or re-attach to, or restore) a study by name.

        With a fleet identity (``owner=``) the study's claim token is
        acquired first: a study live-owned by another replica is
        refused with :class:`~hyperopt_tpu.exceptions.OwnershipLost`
        unless ``takeover=True`` (the failover/migration path, which
        bumps the claim epoch and fences the previous owner out).

        ``host_algo`` (in-process clients only -- graftclient) attaches
        a per-study host-adaptive dispatch hook ``hook(seed) ->
        (values [D, 1], active [D, 1])``: the study is served by the
        hook instead of the shared vmapped program (atpe's host
        decision layer cannot vmap across studies) and never occupies
        a batch slot.  Not expressible over the socket transport."""
        if not _NAME_RE.fullmatch(name):
            raise ValueError(
                f"study name {name!r} must match {_NAME_RE.pattern}"
            )
        with self._lock:
            if name in self._handles:
                handle = self._handles[name]
                stale = handle._study.claim
                if not (
                    takeover and stale is not None and not stale.is_live()
                ):
                    return handle
                # probe-recovered rejoin (graftscope): this replica
                # held the study, lost its claim while it was marked
                # dead (a survivor took it over), and the router is now
                # re-adopting it here.  Every local mutation since the
                # takeover was fenced off (OwnershipLost), so the
                # shared root is the truth: discard the stale resident
                # state and fall through to a fresh claim + restore --
                # the client never sees an error
                self._handles.pop(name, None)
                self.scheduler.close_study(name)
                if handle._study.persist is not None:
                    handle._study.persist.close()
            claim = None
            if self.owner is not None and self.root is not None:
                from .fleet import StudyClaim

                claim = StudyClaim.acquire(
                    self.root, name, self.owner, fs=self.fs,
                    takeover=takeover,
                )
            persist = None
            study = None
            if self.root is not None:
                persist = StudyPersistence(
                    self.root, name, self._guard, fs=self.fs,
                    cadence=self.snapshot_cadence,
                )
                study = persist.restore(self.ps)
            if study is None:
                study = ServeStudy(name, seed, self.ps)
                if persist is not None:
                    persist.log_open(seed)
            study.persist = persist
            study.claim = claim
            study.host_algo = host_algo  # before open: decides slotting
            self.scheduler.open_study(name, seed, study=study)
            if self.recorder.enabled:
                # the replayable-workload contract (serve/replay.py):
                # the open span carries the study's EFFECTIVE seed
                # (restored or fresh), so a recorded span log is
                # self-contained load -- replaying it re-creates the
                # study with the same seed and the suggestion stream
                # re-derives bitwise
                self.recorder.event(
                    "study.open", study=name, seed=int(study.seed),
                    **self.scheduler.span_ids,
                )
            handle = StudyHandle(self, study)
            self._handles[name] = handle
            return handle

    def close_study(self, name):
        with self._lock:
            handle = self._handles.pop(name, None)
            if handle is None:
                return
            study = self.scheduler.close_study(name)
        # the durable close runs OUTSIDE the registry lock (GL503: the
        # snapshot fsyncs, and unrelated create/close calls must not
        # stall behind it); the study is already unregistered, and the
        # WAL it compacts holds every tell, so a racing re-create of
        # the same name restores losslessly either way
        if study.persist is not None:
            study.persist.maybe_snapshot(study, force=True)
            study.persist.close()
        if study.claim is not None:
            study.claim.release()

    def handoff_study(self, name):
        """The migration SOURCE half of the drain protocol (graftfleet):
        publish a final snapshot while still owning the study, then --
        past the ``fleet_migrate_after_snapshot_before_handoff`` crash
        window, where an aborted migration leaves this replica owning
        and serving -- unregister, close the WAL, and release the
        claim so the target can adopt with a clean epoch bump.  The
        study's artifacts (WAL + bundle + released claim) ARE the
        handoff: nothing is copied, the target restores in place."""
        with self._lock:
            handle = self._handles.get(name)
            if handle is None:
                raise ValueError(f"study {name!r} is not open here")
            study = handle._study
        if study.persist is not None:
            study.persist.maybe_snapshot(study, force=True)
        self.fs.crashpoint("fleet_migrate_after_snapshot_before_handoff")
        with self._lock:
            self._handles.pop(name, None)
            self.scheduler.close_study(name)
        if study.persist is not None:
            study.persist.close()
        if study.claim is not None:
            # the handoff-marked tombstone: adoption overwrites it, so
            # a marker still on disk is a study stranded between
            # handoff and restore (fsck --serve: study_half_migrated)
            study.claim.release(handoff=True)
        return study

    def studies(self):
        with self._lock:
            return sorted(self._handles)

    # -- the handle's plumbing ---------------------------------------------
    def _fence(self, study):
        """Ownership fence (fleet): refuse to act on a study whose
        claim this replica no longer holds.  A no-op without claims."""
        if study.claim is not None:
            study.claim.ensure_live()

    def _ask_async(self, study):
        self._fence(study)
        return self.scheduler.submit_ask(study).future

    def _submit(self, study, timeout=None, replay=None):
        import time as _time

        self._fence(study)
        deadline = (
            None if timeout is None
            else _time.perf_counter() + float(timeout)
        )
        return self.scheduler.submit_ask(
            study, deadline=deadline, replay=replay
        )

    def _recover_ask(self, study, timeout):
        """Re-serve the smallest undelivered suggestion for a retrying
        client, or None when nothing is recoverable (fresh ask)."""
        self._fence(study)
        cand = sorted(set(study.pending_asks) | set(study.outstanding))
        if not cand:
            return None
        tid = cand[0]
        if tid in study.outstanding:
            self._fence(study)
            return tid, dict(study.outstanding[tid])
        req = self._submit(
            study, timeout=timeout, replay=(tid, study.pending_asks[tid])
        )
        return self._await(req, timeout)

    def _await(self, req, timeout):
        """Block on one admitted ask under its client deadline: pump
        inline in deterministic mode, wait in background mode; on
        expiry, drop the request from the queue (the slow-client
        shed) or grace-wait an already-picked dispatch."""
        import time as _time
        from concurrent.futures import TimeoutError as _FutTimeout

        fut = req.future
        if not self._background:
            # deterministic mode: serve rounds inline until this future
            # resolves (each pump is one coalesced dispatch)
            while not fut.done():
                if self.scheduler.step() == 0 and not fut.done():
                    if (req.deadline is not None
                            and _time.perf_counter() > req.deadline):
                        break
                    _time.sleep(0.001)
            if fut.done():
                out = fut.result(timeout=0)
                self._fence(req.study)  # a zombie must not deliver
                return out
        else:
            try:
                out = fut.result(timeout=timeout)
                self._fence(req.study)
                return out
            except _FutTimeout:
                pass
        if self.scheduler.drop_request(req):
            return fut.result(timeout=0)  # raises DeadlineExpired
        # already picked into an in-flight dispatch: give the round a
        # short grace window to resolve it (served or typed failure)
        grace = self.scheduler.dispatch_timeout or 5.0
        return fut.result(timeout=2.0 * grace + 1.0)

    def _tell(self, study, tid, loss, vals=None, result=None):
        if vals is None:
            vals = study.outstanding.get(tid)
        if vals is None:
            raise ValueError(
                f"study {study.name!r} has no outstanding suggestion "
                f"for tid {tid}; pass vals= explicitly (e.g. when "
                "re-telling work a crashed service never acked)"
            )
        # the ownership fence sits BEFORE the WAL append: a replica
        # whose claim was taken over must not write to a log the new
        # owner is appending to (the double-serve hazard)
        self._fence(study)
        self.scheduler.tell(study, tid, vals, loss, result=result)
        if study.persist is not None and study.client_state_fn is None:
            # client studies snapshot at TRIAL boundaries instead (the
            # blob must never capture a doc mid-finalize; the client
            # drives the cadence after each doc settles)
            study.persist.maybe_snapshot(study)

    def _fail(self, study, tid, doc=None):
        self._fence(study)
        self.scheduler.tell_failure(study, tid, doc=doc)
        if study.persist is not None and study.client_state_fn is None:
            study.persist.maybe_snapshot(study)

    # -- service-level controls --------------------------------------------
    def pump(self):
        """Serve one coalesced round inline (deterministic mode)."""
        return self.scheduler.step()

    @property
    def counters(self):
        s = self.scheduler
        return {
            "dispatch_count": s.dispatch_count,
            "delta_drain_dispatches": s.delta_drain_dispatches,
            "upload_events": s.upload_events,
            "upload_bytes": s.upload_bytes,
            "joins": s.joins,
            "rebuckets": s.rebuckets,
            # graftmesh accounting
            "shard_restacks": s.shard_restacks,
            "mesh_shards": s._n_shards,
            # graftguard accounting
            "admitted_count": s.admitted_count,
            "shed_count": s.shed_count,
            "guard_checks": s.guard_checks,
            "quarantine_count": s.quarantine_count,
            "evictions": s.evictions,
            "watchdog_timeouts": s.watchdog_timeouts,
            "watchdog_retries": s.watchdog_retries,
            "watchdog_recoveries": s.watchdog_recoveries,
            # graftclient accounting
            "host_algo_served": s.host_algo_served,
            # graftburst accounting: round fsync barriers issued, and
            # the raw fsync/tell tallies across the open studies'
            # WALs -- wal_fsyncs / wal_tells is the bench's
            # ``wal_fsyncs_per_tell`` (1.0 per-tell-fsync regime,
            # ~1/round-size under group commit)
            "group_commit_barriers": s.group_commit_barriers,
            "wal_fsyncs": self._wal_stat("fsyncs"),
            "wal_tells": self._wal_stat("total_tells"),
        }

    def _wal_stat(self, attr):
        with self._lock:
            studies = [h._study for h in self._handles.values()]
        return sum(
            int(getattr(st.persist.wal, attr))
            for st in studies if st.persist is not None
        )

    def metrics_rows(self):
        """graftscope exposition: refresh the point-in-time gauges,
        then one snapshot-consistent collect of the scheduler registry
        (every series already carries ``replica=<owner>`` on a fleet
        member)."""
        s = self.scheduler
        m = s.metrics
        with self._lock:
            n_studies = len(self._handles)
        m.gauge("serve_studies", "open studies").set(n_studies)
        m.gauge("serve_queue_depth", "asks queued").set(len(s._asks))
        m.gauge(
            "serve_ready", "1 = accepting asks (health/ready protocol)"
        ).set(1 if self.ready() else 0)
        return m.collect()

    def metrics_text(self):
        from ..obs import render_prometheus

        return render_prometheus(self.metrics_rows())

    def trace_tail(self, n=None):
        """The most recent flight-recorder spans (empty when no
        recorder is armed)."""
        return self.recorder.tail(n)

    def ready(self):
        """Readiness for traffic: False while draining, circuit-broken,
        or stopped -- the load balancer's drain signal."""
        s = self.scheduler
        return not (s.draining or s.circuit_open or s._stopping)

    def health(self):
        """The health endpoint's structured snapshot: status, tenancy,
        queue occupancy, and the full counter set."""
        s = self.scheduler
        if s._stopping:
            status = "stopped"
        elif s.circuit_open:
            status = "circuit_open"
        elif s.draining:
            status = "draining"
        else:
            status = "ok"
        with self._lock:
            n_studies = len(self._handles)
        return {
            "status": status,
            "ready": self.ready(),
            "owner": self.owner,
            "studies": n_studies,
            "queue_depth": len(s._asks),
            "max_queue": s.max_queue,
            "max_batch": s.max_batch,
            "counters": self.counters,
        }

    def drain(self, timeout=30.0, block=True):
        """Rolling-restart protocol: refuse new asks with
        ``Overloaded(reason="draining", retry_after=<time left until
        the drain deadline>)``, serve what is already queued, then shut
        down (snapshotting every durable study).  ``block=False`` only
        ENTERS draining mode (publishing the deadline) and returns --
        the fleet's drain-migrate protocol serves the queue, hands the
        studies off, and shuts the replica down itself."""
        import time as _time

        self.scheduler.drain(timeout=timeout)
        if not block:
            return
        deadline = _time.perf_counter() + float(timeout)
        while self.scheduler._asks and _time.perf_counter() < deadline:
            if not self._background:
                self.scheduler.step()
            else:
                _time.sleep(0.01)
        self.shutdown()

    def shutdown(self):
        self.scheduler.stop()
        with self._lock:
            for name in list(self._handles):
                self.close_study(name)
        self.recorder.flush()  # orderly exit: span export durable
        self.recorder.close()


# ---------------------------------------------------------------------------
# JSON-line socket transport + console script
# ---------------------------------------------------------------------------


def _serve_error_reply(e):
    """The structured refusal a typed :class:`ServeError` maps to on
    the wire: ``error_type`` names the exception class (``Overloaded``
    / ``DeadlineExpired`` / ``StudyPoisoned`` / ``StudyQuarantined`` /
    ``DispatchTimeout``), and Overloaded's backpressure fields ride
    along so a client can back off exactly as the in-process API
    would."""
    reply = {
        "ok": False,
        "error": str(e),
        "error_type": type(e).__name__,
    }
    if isinstance(e, Overloaded):
        ra = e.retry_after
        if ra is None:
            # the wire contract is a CONCRETE back-off: a router that
            # sees null would hot-loop a draining replica (the
            # scheduler derives the real value from its drain
            # deadline; this floor only covers hand-built Overloadeds)
            ra = 0.05
        reply["retry_after"] = ra
        reply["reason"] = e.reason
    return reply


def _ask_batch(service, req):
    """Coalesced multi-study ask: every admitted ask is submitted
    BEFORE any round is pumped, so one vmapped dispatch serves the
    whole group -- the router forwards one ``ask_batch`` frame per
    backend, preserving coalescing through the pipelined transport
    (in lockstep per-connection request/response, the server would
    only ever see one ask at a time)."""
    import time as _time

    names = list(req.get("studies") or req.get("names") or ())
    timeout = float(req.get("timeout", 60.0))
    results, reqs = {}, {}
    for name in names:
        with service._lock:
            handle = service._handles.get(name)
        if handle is None:
            results[name] = {
                "ok": False, "error": f"unknown study {name!r}",
                "error_type": "UnknownStudy",
            }
            continue
        try:
            reqs[name] = service._submit(handle._study, timeout=timeout)
        except ServeError as e:
            results[name] = _serve_error_reply(e)
    deadline = _time.perf_counter() + timeout
    pending = dict(reqs)
    while pending:
        stepped = (
            service.scheduler.step() if not service._background else 0
        )
        for name in [n for n, r in pending.items() if r.future.done()]:
            pending.pop(name)
        if not pending or _time.perf_counter() >= deadline:
            break
        if stepped == 0:
            _time.sleep(0.001)
    for name, r in reqs.items():
        if not r.future.done():
            # past the deadline the pick loop sheds it; force the drop
            # so a late round cannot strand the suggestion in flight
            service.scheduler.drop_request(r)
        try:
            tid, vals = r.future.result(timeout=0)
            results[name] = {"ok": True, "tid": tid, "vals": vals}
        except ServeError as e:
            results[name] = _serve_error_reply(e)
        except Exception as e:
            results[name] = {
                "ok": False, "error": f"{type(e).__name__}: {e}",
                "error_type": type(e).__name__,
            }
    return {"ok": True, "results": results}


def _handle_request(service, req):
    op = req.get("op")
    try:
        if op == "ping":
            return {"ok": True, "pong": True}
        if op == "health":
            return {"ok": True, **service.health()}
        if op == "ready":
            return {"ok": True, "ready": service.ready()}
        if op == "metrics":
            rows = service.metrics_rows()
            from ..obs import render_prometheus

            return {
                "ok": True, "metrics": rows,
                "text": render_prometheus(rows),
            }
        if op == "trace":
            tail = req.get("tail")
            return {
                "ok": True,
                "spans": service.trace_tail(
                    None if tail is None else int(tail)
                ),
            }
        if op == "create_study":
            h = service.create_study(
                req["name"], seed=int(req.get("seed", 0)),
                takeover=bool(req.get("takeover", False)),
            )
            return {"ok": True, "study": h.name, "n_tells": h.n_tells}
        if op == "studies":
            return {"ok": True, "studies": service.studies()}
        if op == "ask_batch":
            return _ask_batch(service, req)
        if op == "drain":
            service.drain(
                timeout=float(req.get("timeout", 30.0)), block=False
            )
            return {
                "ok": True, "draining": True,
                "retry_after": service.scheduler.drain_retry_after(),
            }
        name = req.get("study")
        with service._lock:
            handle = service._handles.get(name)
        if handle is None:
            return {
                "ok": False, "error": f"unknown study {name!r}",
                "error_type": "UnknownStudy",
            }
        if op == "ask":
            tid, vals = handle.ask(
                timeout=float(req.get("timeout", 60.0)),
                recover=bool(req.get("recover", False)),
            )
            return {"ok": True, "tid": tid, "vals": vals}
        if op == "tell":
            handle.tell(
                int(req["tid"]), float(req["loss"]), vals=req.get("vals")
            )
            return {"ok": True}
        if op == "best":
            return {"ok": True, "best": handle.best()}
        if op == "close_study":
            handle.close()
            return {"ok": True}
        if op == "handoff_study":
            service.handoff_study(name)
            return {"ok": True, "handed_off": name}
        return {"ok": False, "error": f"unknown op {op!r}"}
    except ServeError as e:
        return _serve_error_reply(e)


def serve_forever(service, host="127.0.0.1", port=0,
                  idle_timeout=300.0, max_conns=256, net_plan=None):
    """Bind the TCP front; returns the (not yet serving)
    ``ThreadingTCPServer`` -- call ``.serve_forever()`` (the console
    script does) or drive it from a thread (the tests do).

    Protocol: JSON-lines by default (one JSON object per request line,
    one JSON reply line each; every reply carries ``ok`` plus either
    the result fields or ``error``).  A client whose first request is
    ``{"op": "hello", "proto": 2}`` negotiates the connection up to
    graftburst binary frames (:mod:`~hyperopt_tpu.serve.frames`);
    replies echo the request's ``rid`` when it carries one, so a
    pipelining client can keep many requests in flight.  A framing
    error gets a typed ``FrameError`` reply and the connection closes
    -- never a hang.

    graftstorm hygiene: ``idle_timeout`` is each accepted socket's
    read deadline (an idle or half-open peer is reaped, never a
    stranded handler thread); at most ``max_conns`` connections are
    served at once -- one past the cap gets a typed ``Overloaded``
    refusal (``reason: "max_connections"``) and a close, the GL306
    queue-cap shape applied at the socket layer.  ``net_plan`` (a
    :class:`~hyperopt_tpu.distributed.netfaults.NetFaultPlan`) wraps
    every accepted connection so chaos suites storm the real server
    seam."""
    import socket as _socket
    import socketserver

    from .frames import PROTO_V2, FrameError, read_frame, write_frame

    idle = idle_timeout
    plan = net_plan
    slots = threading.BoundedSemaphore(int(max_conns))

    class Handler(socketserver.StreamRequestHandler):
        timeout = idle  # StreamRequestHandler: settimeout in setup()

        def setup(self):
            super().setup()
            if plan is not None:
                self.rfile, self.wfile = plan.wrap_pair(
                    self.rfile, self.wfile, sock=self.connection,
                    key="serve-front",
                )

        def _send(self, reply, binary):
            if binary:
                write_frame(self.wfile, reply)
            else:
                self.wfile.write(
                    (json.dumps(reply) + "\n").encode("utf-8")
                )
            self.wfile.flush()

        def handle(self):
            if not slots.acquire(blocking=False):
                try:
                    self._send({
                        "ok": False,
                        "error": "server connection cap reached",
                        "error_type": "Overloaded",
                        "reason": "max_connections",
                        "retry_after": min(0.05, RETRY_AFTER_CAP),
                    }, False)
                except OSError:
                    pass
                return
            try:
                self._handle_conn()
            except _socket.timeout:
                # idle deadline: a silent or half-open client is
                # reaped -- close quietly, no stranded thread
                return
            except ConnectionError:
                # the peer reset or vanished mid-request (storm
                # weather, not a server bug): close quietly
                return
            finally:
                slots.release()

        def _handle_conn(self):
            binary = False
            while True:
                if binary:
                    try:
                        req = read_frame(self.rfile)
                    except FrameError as e:
                        # typed reply, then hang up: past a framing
                        # error the stream offset is meaningless
                        self._send({
                            "ok": False, "error": str(e),
                            "error_type": "FrameError",
                        }, binary)
                        return
                    if req is None:
                        return
                    if not isinstance(req, dict):
                        self._send({
                            "ok": False,
                            "error": "frame payload must be a map",
                            "error_type": "FrameError",
                        }, binary)
                        return
                else:
                    raw = self.rfile.readline()
                    if not raw:
                        return
                    line = raw.strip()
                    if not line:
                        continue
                    try:
                        req = json.loads(line)
                    except ValueError as e:
                        self._send({
                            "ok": False,
                            "error": f"malformed request line: {e}",
                            "error_type": "FrameError",
                        }, binary)
                        continue
                    if not isinstance(req, dict):
                        self._send({
                            "ok": False,
                            "error": "request must be a JSON object",
                            "error_type": "FrameError",
                        }, binary)
                        continue
                if req.get("op") == "hello":
                    proto = min(int(req.get("proto", 1)), PROTO_V2)
                    reply = {"ok": True, "proto": proto}
                    if "rid" in req:
                        reply["rid"] = req["rid"]
                    # the ack goes out in the OLD mode; both sides
                    # switch after it
                    self._send(reply, binary)
                    binary = proto >= PROTO_V2
                    continue
                try:
                    reply = _handle_request(service, req)
                except Exception as e:  # one bad request must not
                    # kill the connection; the error rides the reply
                    reply = {
                        "ok": False,
                        "error": f"{type(e).__name__}: {e}",
                    }
                if "rid" in req:
                    reply = dict(reply, rid=req["rid"])
                self._send(reply, binary)

    class Server(socketserver.ThreadingTCPServer):
        allow_reuse_address = True
        daemon_threads = True

    return Server((host, int(port)), Handler)


def _load_space(spec):
    """``module:attr`` -> the space object (called if it's a factory)."""
    import importlib

    mod_name, _, attr = spec.partition(":")
    if not attr:
        raise SystemExit(
            f"--space must be module:attr, got {spec!r}"
        )
    obj = getattr(importlib.import_module(mod_name), attr)
    return obj() if callable(obj) else obj


def main(argv=None):
    """``hyperopt-tpu-serve``: the service as a process."""
    import argparse

    parser = argparse.ArgumentParser(
        prog="hyperopt-tpu-serve",
        description="multi-tenant suggestion service: study-batched "
        "fused tell+ask with continuous batching over a JSON-line "
        "TCP transport",
    )
    parser.add_argument(
        "--space", required=True,
        help="module:attr of the search space (or a zero-arg factory)",
    )
    parser.add_argument("--algo", default="tpe", choices=("tpe", "anneal"))
    parser.add_argument("--host", default="127.0.0.1")
    parser.add_argument("--port", type=int, default=7077)
    parser.add_argument(
        "--root", default=None,
        help="directory for per-study WAL/snapshot durability",
    )
    parser.add_argument("--max-batch", type=int, default=64)
    parser.add_argument("--max-wait-ms", type=float, default=2.0)
    parser.add_argument("--n-startup-jobs", type=int, default=20)
    parser.add_argument(
        "--max-queue", type=int, default=None,
        help="ask-queue high-water mark (default 4 * max-batch); "
        "submits past it get a typed Overloaded with retry-after",
    )
    parser.add_argument(
        "--dispatch-timeout", type=float, default=30.0,
        help="watchdog deadline (seconds) per device dispatch; "
        "0 disables the watchdog",
    )
    parser.add_argument(
        "--mesh-devices", type=int, default=0,
        help="shard the study slot axis over this many devices "
        "(graftmesh; 0 = single-device engine, -1 = every visible "
        "device)",
    )
    parser.add_argument(
        "--owner", default=None,
        help="fleet replica identity: with --root on a SHARED "
        "directory, per-study claim/epoch tokens fence this replica "
        "against double-serving a study another replica took over "
        "(graftfleet; front replicas with hyperopt-tpu-router)",
    )
    parser.add_argument(
        "--flight-log", default=None, metavar="PATH",
        help="arm the graftscope flight recorder with a WAL-style "
        "durable span export at PATH (scrape live with "
        "hyperopt-tpu-scope trace, post-mortem with "
        "hyperopt-tpu-scope flight PATH)",
    )
    parser.add_argument(
        "--trace-cadence", type=int, default=1,
        help="flight-recorder sampling cadence (1 = record every "
        "span; k keeps every k-th); only meaningful with --flight-log",
    )
    parser.add_argument(
        "--device-metrics-every", type=int, default=0,
        help="dispatch the obs.device_metrics io_callback twin every "
        "N rounds (0 = off: exactly zero extra dispatches)",
    )
    parser.add_argument(
        "--idle-timeout", type=float, default=300.0,
        help="per-connection idle deadline (seconds): an idle or "
        "half-open client is reaped instead of stranding a handler "
        "thread (graftstorm)",
    )
    parser.add_argument(
        "--max-conns", type=int, default=256,
        help="bound on concurrently served connections; one past the "
        "cap gets a typed Overloaded refusal (reason max_connections)",
    )
    args = parser.parse_args(argv)

    mesh = None
    if args.mesh_devices:
        from ..parallel.mesh import study_mesh

        mesh = study_mesh(
            None if args.mesh_devices < 0 else args.mesh_devices
        )
    recorder = None
    if args.flight_log:
        from ..obs import FlightRecorder

        recorder = FlightRecorder(
            path=args.flight_log, cadence=args.trace_cadence
        )
    service = SuggestService(
        _load_space(args.space), algo=args.algo, root=args.root,
        max_batch=args.max_batch, max_wait_ms=args.max_wait_ms,
        n_startup_jobs=args.n_startup_jobs, max_queue=args.max_queue,
        dispatch_timeout=args.dispatch_timeout or None, mesh=mesh,
        owner=args.owner, recorder=recorder,
        device_metrics_every=args.device_metrics_every,
    )
    server = serve_forever(
        service, host=args.host, port=args.port,
        idle_timeout=args.idle_timeout, max_conns=args.max_conns,
    )
    host, port = server.server_address[:2]
    print(f"hyperopt-tpu-serve listening on {host}:{port}", flush=True)
    try:
        server.serve_forever()
    except KeyboardInterrupt:
        pass
    finally:
        server.server_close()
        service.shutdown()
    return 0


if __name__ == "__main__":
    raise SystemExit(main())
