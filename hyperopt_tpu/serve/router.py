"""graftfleet's routing front: the consistent-hash ring and the thin
router that speaks the existing JSON-line protocol.

Two routers share one :class:`HashRing`:

* :class:`FleetRouter` -- the in-process front over a
  :class:`~hyperopt_tpu.serve.fleet.Fleet`: routes
  ``create/ask/tell/best/close`` by study name, converts an observed
  replica death (:class:`~hyperopt_tpu.exceptions.ReplicaDead`, or a
  :class:`~hyperopt_tpu.distributed.faults.SimulatedCrash` escaping a
  replica's batching loop) into fleet failover and retries the op
  against the new owner with ``recover=True`` -- the exactly-once
  delivery path -- and propagates typed
  :class:`~hyperopt_tpu.exceptions.Overloaded` backpressure (honoring
  ``retry_after``) to the client untouched;
* :class:`RouterServer` -- the same policy over TCP: clients speak the
  ordinary JSON-line protocol to the router, which forwards each
  request to the backend replica that owns the study.  Backends are
  plain ``hyperopt-tpu-serve`` processes sharing a ``--root``
  directory (and fenced by ``--owner`` claim tokens); when one stops
  answering, the router reroutes its studies to ring survivors, which
  restore them from their WAL+bundle pairs via ``create_study``.

Placement is a pure function of (guard fingerprint, study name, the
alive replica set): deterministic across processes, runs, and
PYTHONHASHSEED -- the ring hashes with blake2b, never ``hash()``.
"""

from __future__ import annotations

import bisect
import hashlib
import json
import logging
import socket
import threading
import time

from ..distributed.faults import REAL_FS, SimulatedCrash
from ..exceptions import (
    NetworkTimeout, OwnershipLost, PeerUnreachable, ReplicaDead,
)
from ..obs.expo import merge_rows, render_prometheus, tag_rows
from ..obs.registry import LATENCY_BUCKETS_S, MetricsRegistry
from .frames import (
    DEFAULT_READ_TIMEOUT, FrameConn, FrameError, dial,
)

logger = logging.getLogger(__name__)

__all__ = ["HashRing", "FleetRouter", "RouterServer", "main"]

#: everything a forward can die of at the transport: raw socket
#: failures, torn frames, garbled JSON-line replies, and the typed
#: graftstorm deadline/reachability pair (ServeError subclasses, so
#: they are NOT under OSError and must be named here) -- one tuple so
#: every catch site routes the same set into failover.
_NET_ERRORS = (
    OSError, ConnectionError, FrameError, json.JSONDecodeError,
    NetworkTimeout, PeerUnreachable,
)


def _h64(s):
    """Stable 64-bit point on the ring (process-independent)."""
    return int.from_bytes(
        hashlib.blake2b(s.encode("utf-8"), digest_size=8).digest(), "big"
    )


class HashRing:
    """Consistent hashing of study names over replica ids.

    ``salt`` is the study-family guard fingerprint: two fleets serving
    different spaces place the same study names differently, and the
    placement of one fleet is reproducible anywhere the fingerprint
    is.  ``vnodes`` virtual points per replica keep the load within a
    small factor of even; adding or removing one replica moves only
    the keys whose arcs it owned -- ~1/N of them -- and no key whose
    owner survives ever moves (the stability contract
    ``tests/test_fleet.py`` pins).
    """

    def __init__(self, nodes=(), salt="", vnodes=64):
        self.salt = str(salt)
        self.vnodes = int(vnodes)
        self._points = []  # sorted [(hash, node), ...]
        self.nodes = set()
        for node in nodes:
            self.add(node)

    def add(self, node):
        node = str(node)
        if node in self.nodes:
            return
        self.nodes.add(node)
        for v in range(self.vnodes):
            point = (_h64(f"{self.salt}|node|{node}|{v}"), node)
            bisect.insort(self._points, point)

    def remove(self, node):
        node = str(node)
        if node not in self.nodes:
            return
        self.nodes.discard(node)
        self._points = [p for p in self._points if p[1] != node]

    def owner(self, key, exclude=()):
        """The replica owning ``key`` -- the first ring point at or
        after the key's hash (wrapping), skipping ``exclude``."""
        exclude = set(exclude)
        alive = self.nodes - exclude
        if not alive:
            raise ReplicaDead(
                f"no live replica on the ring for key {key!r}"
            )
        h = _h64(f"{self.salt}|key|{key}")
        i = bisect.bisect_left(self._points, (h, ""))
        n = len(self._points)
        for step in range(n):
            node = self._points[(i + step) % n][1]
            if node in alive:
                return node
        raise ReplicaDead(f"ring exhausted for key {key!r}")  # unreachable

    def placement(self, keys, exclude=()):
        """{key: owner} for a batch of keys (the stability tests and
        the drain planner both want the full map)."""
        return {k: self.owner(k, exclude=exclude) for k in keys}


class FleetRouter:
    """The in-process routing front over a Fleet.

    One router instance is one "router process": its ``fs`` seam
    carries the router-side crash point
    (``fleet_router_after_forward_before_ack`` -- the replica executed
    the op, the client never saw the ack); a crashed router is
    "restarted" by constructing a new one over the same fleet, and the
    client retries idempotently (tells dedup by tid, asks re-deliver
    with ``recover=True``).

    Failure policy: an op that finds its replica dead (or watches it
    die -- ``SimulatedCrash`` out of the replica's own batching loop)
    triggers :meth:`~hyperopt_tpu.serve.fleet.Fleet.failover` and ONE
    retry against the new owner; asks retry with ``recover=True`` so a
    suggestion the dead replica logged or served is re-delivered
    bitwise instead of burning a fresh seed.  Typed ``Overloaded``
    (draining / queue-full / circuit-open) passes through to the
    client -- backpressure is the client's signal, not the router's to
    swallow.
    """

    def __init__(self, fleet, fs=REAL_FS):
        self.fleet = fleet
        self.fs = fs

    # -- routing -----------------------------------------------------------
    def _forward(self, name, op, recover_op=None):
        """Run ``op(replica)`` on the study's owner; on replica death
        (observed before the call -- the failure detector -- or DURING
        it, a ``SimulatedCrash`` escaping the batching loop) fail the
        replica over and retry once on the new owner (``recover_op``
        when given)."""
        rid = self.fleet.route(name)
        replica = self.fleet.replicas[rid]
        try:
            if replica.dead or replica.partitioned:
                raise ReplicaDead(f"replica {rid!r} is unreachable")
            return op(replica)
        except (ReplicaDead, SimulatedCrash):
            self.fleet.mark_dead(rid)
            self.fleet.failover(rid)
            retry = recover_op or op
            return retry(self.fleet.replicas[self.fleet.route(name)])
        except OwnershipLost:
            # a healed rejoiner (graftstorm): the partition lifted and
            # the ring routed the study back, but the replica's
            # resident handle still carries its pre-partition claim.
            # Re-claim from the shared root (takeover bumps the epoch,
            # WAL restore is tid-dedup exactly-once) and retry -- the
            # rejoin is client-invisible
            replica = self.fleet.replicas[self.fleet.route(name)]
            replica.open_study(name, takeover=True)
            retry = recover_op or op
            return retry(replica)

    def _ack(self):
        self.fs.crashpoint("fleet_router_after_forward_before_ack")

    # -- the client API ----------------------------------------------------
    def create_study(self, name, seed=0):
        self.fleet.register(name)
        out = self._forward(
            name, lambda r: r.open_study(name, seed=seed).name
        )
        self._ack()
        return out

    def ask(self, name, timeout=60.0, recover=False):
        out = self._forward(
            name,
            lambda r: r.ask(name, timeout=timeout, recover=recover),
            recover_op=lambda r: r.ask(name, timeout=timeout, recover=True),
        )
        self._ack()
        return out

    def tell(self, name, tid, loss, vals=None):
        self._forward(name, lambda r: r.tell(name, tid, loss, vals=vals))
        self._ack()

    def best(self, name):
        out = self._forward(name, lambda r: r.best(name))
        self._ack()
        return out

    def close_study(self, name):
        self._forward(name, lambda r: r.close_study(name))
        self.fleet.unregister(name)
        self._ack()

    def ask_batch(self, names, timeout=60.0):
        """Fleet-throughput path: group asks by owning replica, submit
        each group async (ONE coalesced dispatch per replica per
        round), then gather.  Returns {name: (tid, vals)}; any name
        whose replica died mid-round is retried through the failover
        path with ``recover=True``."""
        by_replica = {}
        for name in names:
            by_replica.setdefault(self.fleet.route(name), []).append(name)
        out, retry = {}, []
        for rid, group in by_replica.items():
            replica = self.fleet.replicas[rid]
            if replica.dead or replica.partitioned:
                retry.extend(group)
                continue
            try:
                futs = [(n, replica.ask_async(n)) for n in group]
                replica.pump_until(
                    [f for _, f in futs], timeout=timeout
                )
                for n, f in futs:
                    out[n] = f.result(timeout=0)
            except (ReplicaDead, SimulatedCrash, OwnershipLost):
                self.fleet.mark_dead(rid)
                self.fleet.failover(rid)
                retry.extend(n for n in group if n not in out)
        for n in retry:
            out[n] = self.ask(n, timeout=timeout, recover=True)
        self._ack()
        return out


# ---------------------------------------------------------------------------
# the TCP router: same policy, JSON-line protocol on both sides
# ---------------------------------------------------------------------------


class _Backend:
    """One replica endpoint.  Connections are opened per handler
    thread (stored on the caller), so the backend object itself holds
    only the address and its liveness flag."""

    def __init__(self, rid, host, port):
        self.rid = rid
        self.host = host
        self.port = int(port)

    def connect(self, timeout=10.0, read_timeout=DEFAULT_READ_TIMEOUT,
                net_plan=None):
        """Deadline-armed transport to this replica via
        :func:`~.frames.dial`: connect failures are typed
        :class:`PeerUnreachable`, hung reads typed
        :class:`NetworkTimeout` -- a silent backend can no longer
        strand a router handler thread."""
        _sock, f = dial(
            self.host, self.port, connect_timeout=timeout,
            read_timeout=read_timeout, net_plan=net_plan, key=self.rid,
        )
        return f


class RouterServer:
    """The TCP routing front: JSON-line requests in, forwarded to the
    owning backend, JSON-line replies out.

    Every client connection gets its own handler thread with its OWN
    backend connections (no shared sockets, no lock around I/O); the
    only shared mutable state is the dead-backend set, mutated under a
    small lock with nothing blocking inside.  A backend that fails a
    forward is marked dead, the ring excludes it, and the request is
    retried on the new owner -- ``create_study(takeover=True)`` first
    when the study is not yet resident there (the shared ``--root``
    restores it), then the original op with ``recover`` set for asks.
    """

    def __init__(self, backends, salt="", vnodes=64,
                 probe_timeout=5.0, probe_backoff_cap=8,
                 read_timeout=DEFAULT_READ_TIMEOUT, net_plan=None,
                 idle_timeout=300.0, max_conns=256):
        self.backends = {b.rid: b for b in backends}
        # graftstorm socket hygiene: per-op read deadline on every
        # backend conn, idle deadline + bounded conn count on the
        # client front, and an optional NetFaultPlan injected at the
        # backend dial seam (chaos suites storm the real sockets)
        self.read_timeout = float(read_timeout)
        self.net_plan = net_plan
        self.idle_timeout = idle_timeout
        self.max_conns = int(max_conns)
        self.ring = HashRing(self.backends, salt=salt, vnodes=vnodes)
        self._lock = threading.Lock()
        self._dead = set()
        # graftscope: the router's own series (probe health/latency,
        # failovers observed) -- merged into the fleet-wide scrape
        self.metrics = MetricsRegistry("router")
        self._up_gauge = self.metrics.gauge(
            "router_backend_up",
            "1 = the last health probe (or forward) succeeded",
            labels=("backend",),
        )
        self._probe_hist = self.metrics.histogram(
            "router_probe_seconds", "health-probe round-trip time",
            buckets=LATENCY_BUCKETS_S,
        )
        self._probe_failures = self.metrics.counter(
            "router_probe_failures_total", "failed health probes",
        )
        self._rejoins = self.metrics.counter(
            "router_backend_rejoins_total",
            "dead backends revived by a succeeding probe",
        )
        self._probes_total = self.metrics.counter(
            "router_probes_total",
            "health probes attempted (skips under backoff excluded)",
        )
        self.probe_timeout = float(probe_timeout)
        # exponential probe backoff (graftpilot satellite): after the
        # f-th consecutive failure the next min(2**(f-1), cap) sweeps
        # skip the backend, so it is re-probed on sweeps 0, 2, 5, 10,
        # 19, 28, ... -- a long-dead host is not hammered every
        # interval; any success resets the schedule
        self.probe_backoff_cap = int(probe_backoff_cap)
        self._probe_fails = {}  # rid -> consecutive probe failures
        self._probe_wait = {}  # rid -> sweeps left before the next try
        self._probe_conns = {}  # the probe loop's OWN connection cache
        self._probe_thread = None
        self._probing = False

    def _mark_dead(self, rid):
        with self._lock:
            self._dead.add(rid)
        self._up_gauge.labels(backend=rid).set(0)

    def _alive_excluded(self):
        with self._lock:
            return frozenset(self._dead)

    def _conn(self, conns, rid, timeout=30.0):
        """This thread's negotiated :class:`FrameConn` to ``rid``
        (opened + hello'd on first use): binary frames against a
        graftburst backend, JSON-lines against an old one -- the
        fallback is the negotiation's, not ours."""
        c = conns.get(rid)
        if c is None:
            c = conns[rid] = FrameConn(
                self.backends[rid].connect(
                    timeout=timeout,
                    read_timeout=min(self.read_timeout, float(timeout)),
                    net_plan=self.net_plan,
                )
            )
        return c

    def _drop_conn(self, conns, rid):
        c = conns.pop(rid, None)
        if c is not None:
            c.close()

    def _rpc(self, conns, rid, req, timeout=30.0):
        return self._conn(conns, rid, timeout=timeout).call(req)

    def handle_request(self, req, conns):
        """Route one request; ``conns`` is the calling thread's
        backend-connection cache ({rid: file}).  Fleet-level ops
        (health/ready/studies) aggregate over live backends."""
        op = req.get("op")
        if op == "ping":
            return {"ok": True, "pong": True, "router": True}
        if op in ("health", "ready", "studies"):
            return self._aggregate(op, conns)
        if op == "metrics":
            return self._aggregate_metrics(conns)
        if op == "trace":
            return self._aggregate_trace(conns, req.get("tail"))
        if op == "ask_batch":
            return self._ask_batch(req, conns)
        if op == "drain":
            return self._drain_all(req, conns)
        name = req.get("name") or req.get("study")
        if not name:
            return {"ok": False, "error": f"op {op!r} needs a study name"}
        last_exc = None
        draining_reply = None
        for _attempt in range(1 + len(self.backends)):
            try:
                rid = self.ring.owner(name, exclude=self._alive_excluded())
            except ReplicaDead as e:
                return {"ok": False, "error": str(e),
                        "error_type": "ReplicaDead"}
            try:
                reply = self._rpc(conns, rid, req)
                if (
                    not reply.get("ok")
                    and reply.get("error_type") in (
                        "UnknownStudy", "OwnershipLost"
                    )
                    and op != "create_study"
                ):
                    # failover adoption: the ring owner has not loaded
                    # this study yet (UnknownStudy), or it is a
                    # probe-recovered rejoiner still holding a stale
                    # claim (OwnershipLost) -- restore/re-claim it from
                    # the shared root, then retry the op on the same
                    # backend
                    adopt = self._rpc(conns, rid, {
                        "op": "create_study", "name": name,
                        "takeover": True,
                    })
                    if adopt.get("ok"):
                        if op == "ask":
                            req = dict(req, recover=True)
                        reply = self._rpc(conns, rid, req)
                if (
                    not reply.get("ok")
                    and reply.get("error_type") == "Overloaded"
                    and reply.get("reason") == "draining"
                    and reply.get("retry_after") is not None
                ):
                    # a draining backend names its own comeback time
                    # (jittered server-side, PR 16): honor it, capped,
                    # and retry -- bounded by this attempt loop, so a
                    # backend that drains forever still ends in a typed
                    # refusal, never a hang
                    from .service import RETRY_AFTER_CAP

                    draining_reply = reply
                    time.sleep(min(  # graftlint: disable=GL303 the sleep IS the server's typed retry_after hint, capped and bounded by the attempt budget
                        float(reply["retry_after"]), RETRY_AFTER_CAP
                    ))
                    continue
                return reply
            except _NET_ERRORS as e:
                last_exc = e
                self._drop_conn(conns, rid)
                self._mark_dead(rid)
                logger.warning(
                    "router: backend %s unreachable (%s); failing over",
                    rid, e,
                )
                if op == "ask":
                    req = dict(req, recover=True)
                continue
        if draining_reply is not None:
            # the backend outlasted the retry budget still draining:
            # hand the TYPED backpressure to the client, whose own
            # backoff loop owns the longer wait -- never ReplicaDead
            return draining_reply
        return {
            "ok": False, "error_type": "ReplicaDead",
            "error": f"no backend could serve {name!r}: {last_exc}",
        }

    def _ask_batch(self, req, conns):
        """The coalesced fleet ask over TCP: group names by ring owner,
        SUBMIT one ``ask_batch`` frame per backend (all in flight at
        once -- the pipelining half of graftburst), then drain.  Names
        whose backend died, isn't loaded (UnknownStudy -> adoption), or
        predates ``ask_batch`` fall back to the per-name
        :meth:`handle_request` path with its full failover policy."""
        names = [str(n) for n in (req.get("names") or ())]
        timeout = float(req.get("timeout") or 60.0)
        results, retry, flights = {}, [], []
        by_rid = {}
        for name in names:
            try:
                rid = self.ring.owner(
                    name, exclude=self._alive_excluded()
                )
            except ReplicaDead as e:
                results[name] = {"ok": False, "error": str(e),
                                 "error_type": "ReplicaDead"}
                continue
            by_rid.setdefault(rid, []).append(name)
        for rid, group in by_rid.items():
            try:
                c = self._conn(conns, rid)
                flights.append((rid, group, c, c.submit({
                    "op": "ask_batch", "names": group,
                    "timeout": timeout,
                })))
            except _NET_ERRORS:
                self._drop_conn(conns, rid)
                self._mark_dead(rid)
                retry.extend(group)
        for rid, group, c, fut in flights:
            try:
                reply = c.drain(fut)
            except _NET_ERRORS:
                self._drop_conn(conns, rid)
                self._mark_dead(rid)
                retry.extend(group)
                continue
            if not reply.get("ok"):
                retry.extend(group)  # pre-graftburst backend
                continue
            sub = reply.get("results") or {}
            for name in group:
                r = sub.get(name)
                if r is None or (
                    not r.get("ok")
                    and r.get("error_type") in (
                        "UnknownStudy", "OwnershipLost"
                    )
                ):
                    retry.append(name)  # adoption via the per-name path
                else:
                    results[name] = r
        for name in retry:
            results[name] = self.handle_request(
                {"op": "ask", "study": name, "timeout": timeout}, conns
            )
        return {"ok": True, "results": results}

    def _drain_all(self, req, conns):
        """Fleet-wide drain broadcast: forward ``drain`` to every live
        backend so the whole fleet stops admitting new asks at once;
        the reply's ``retry_after`` is the slowest backend's comeback
        hint (each already jittered server-side), capped."""
        from .service import RETRY_AFTER_CAP

        fwd = {"op": "drain"}
        if req.get("timeout") is not None:
            fwd["timeout"] = req["timeout"]
        replicas, hints = {}, []
        for rid in sorted(self.backends):
            if rid in self._alive_excluded():
                continue
            try:
                reply = self._rpc(conns, rid, fwd)
            except _NET_ERRORS:
                self._drop_conn(conns, rid)
                self._mark_dead(rid)
                replicas[rid] = False
                continue
            replicas[rid] = bool(reply.get("draining"))
            if reply.get("retry_after") is not None:
                hints.append(float(reply["retry_after"]))
        return {
            "ok": True, "draining": True, "replicas": replicas,
            "retry_after": min(max(hints, default=0.0), RETRY_AFTER_CAP),
        }

    def _aggregate(self, op, conns):
        replies = {}
        for rid in self.backends:
            if rid in self._alive_excluded():
                continue
            try:
                replies[rid] = self._rpc(conns, rid, {"op": op})
            except _NET_ERRORS as e:
                self._drop_conn(conns, rid)
                replies[rid] = {"ok": False, "error": str(e)}
        if op == "ready":
            return {
                "ok": True,
                "ready": any(
                    r.get("ready") for r in replies.values()
                ),
                "replicas": {
                    rid: bool(r.get("ready")) for rid, r in replies.items()
                },
            }
        if op == "studies":
            studies = sorted({
                s for r in replies.values() for s in r.get("studies", [])
            })
            return {"ok": True, "studies": studies}
        return {"ok": True, "replicas": replies}

    def _aggregate_metrics(self, conns):
        """The fleet-wide scrape: every live replica's collected rows
        (tagged with its replica id) plus the router's own, rendered
        as ONE Prometheus text document -- one call scrapes the
        fleet."""
        row_lists = [tag_rows(self.metrics.collect(), component="router")]
        scraped = []
        for rid in sorted(self.backends):
            if rid in self._alive_excluded():
                continue
            try:
                reply = self._rpc(conns, rid, {"op": "metrics"})
            except _NET_ERRORS:
                self._drop_conn(conns, rid)
                continue
            if reply.get("ok"):
                row_lists.append(
                    tag_rows(reply.get("metrics", []), replica=rid)
                )
                scraped.append(rid)
        rows = merge_rows(*row_lists)
        return {
            "ok": True, "metrics": rows,
            "text": render_prometheus(rows), "replicas": scraped,
        }

    def _aggregate_trace(self, conns, tail=None):
        """Fleet-wide span tail: every live replica's recent spans
        (each already stamped with its replica id at record time),
        time-ordered."""
        spans = []
        for rid in sorted(self.backends):
            if rid in self._alive_excluded():
                continue
            try:
                reply = self._rpc(
                    conns, rid, {"op": "trace", "tail": tail}
                )
            except _NET_ERRORS:
                self._drop_conn(conns, rid)
                continue
            if reply.get("ok"):
                for s in reply.get("spans", []):
                    s.setdefault("replica", rid)
                    spans.append(s)
        spans.sort(key=lambda s: s.get("ts", 0))
        if tail is not None:
            spans = spans[-int(tail):]
        return {"ok": True, "spans": spans}

    # -- health probing (graftscope satellite) -----------------------------
    def probe_backends(self):
        """One probe sweep over every backend, on the probe loop's OWN
        reused connections: a failing backend is marked dead BEFORE any
        client ask eats its connection failure; a dead backend whose
        probe succeeds again rejoins the ring (its studies were adopted
        elsewhere -- the lazy-adoption path hands them back request by
        request, with no client-visible error either way).

        Persistently-down backends back off exponentially: after the
        f-th consecutive failed probe the next ``min(2**(f-1),
        probe_backoff_cap)`` sweeps skip the backend entirely, and any
        successful probe resets its schedule -- so rejoin latency
        stays bounded at ``cap`` intervals while a long-dead host
        costs one connection attempt per cap window instead of one
        per sweep."""
        for rid in sorted(self.backends):
            wait = self._probe_wait.get(rid, 0)
            if wait > 0:
                self._probe_wait[rid] = wait - 1
                continue
            self._probes_total.inc()
            t0 = time.perf_counter()
            try:
                reply = self._rpc(
                    self._probe_conns, rid, {"op": "health"},
                    timeout=self.probe_timeout,
                )
                ok = bool(reply.get("ok"))
            except _NET_ERRORS:
                self._drop_conn(self._probe_conns, rid)
                ok = False
            self._probe_hist.observe_since(t0)
            if ok:
                self._probe_fails.pop(rid, None)
                self._probe_wait.pop(rid, None)
                with self._lock:
                    rejoined = rid in self._dead
                    self._dead.discard(rid)
                if rejoined:
                    self._rejoins.inc()
                    logger.info(
                        "router: backend %s probe-recovered; rejoining "
                        "the ring", rid,
                    )
                self._up_gauge.labels(backend=rid).set(1)
            else:
                fails = self._probe_fails.get(rid, 0) + 1
                self._probe_fails[rid] = fails
                self._probe_wait[rid] = min(
                    2 ** (fails - 1), self.probe_backoff_cap
                )
                self._probe_failures.inc()
                already = rid in self._alive_excluded()
                self._mark_dead(rid)
                if not already:
                    logger.warning(
                        "router: backend %s failed its health probe; "
                        "marked suspect before any client ask hit it",
                        rid,
                    )

    def start_probes(self, interval=1.0):
        """Run :meth:`probe_backends` on a background thread every
        ``interval`` seconds (the production liveness loop; tests call
        ``probe_backends`` directly for determinism)."""
        if self._probe_thread is not None:
            return
        self._probing = True
        interval = float(interval)

        def _probe_loop():
            while self._probing:
                self.probe_backends()
                time.sleep(interval)

        self._probe_thread = threading.Thread(
            target=_probe_loop, name="graftscope-router-probe", daemon=True
        )
        self._probe_thread.start()

    def stop_probes(self):
        self._probing = False
        t = self._probe_thread
        self._probe_thread = None
        if t is not None:
            t.join(timeout=5.0)
        for f in self._probe_conns.values():
            try:
                f.close()
            except OSError:
                pass
        self._probe_conns.clear()

    def serve_forever(self, host="127.0.0.1", port=0):
        """Bind the client front; returns the (not yet serving)
        ``ThreadingTCPServer`` exactly like ``service.serve_forever``
        -- including the graftburst hello negotiation, so a binary
        pipelining client gets frames end to end through the router.

        graftstorm hygiene: every accepted connection carries the
        router's ``idle_timeout`` as its socket deadline (an idle or
        half-open peer is reaped, never a stranded handler thread),
        and at most ``max_conns`` connections are served at once --
        one past the cap gets a typed ``Overloaded`` refusal
        (``reason: "max_connections"``) and a close, the GL306 shape
        applied at the socket layer."""
        import socketserver

        from .frames import PROTO_V2, read_frame, write_frame
        from .service import RETRY_AFTER_CAP

        router = self
        idle = self.idle_timeout
        plan = self.net_plan
        slots = threading.BoundedSemaphore(self.max_conns)

        class Handler(socketserver.StreamRequestHandler):
            timeout = idle  # StreamRequestHandler: settimeout in setup()

            def setup(self):
                super().setup()
                if plan is not None:
                    self.rfile, self.wfile = plan.wrap_pair(
                        self.rfile, self.wfile, sock=self.connection,
                        key="router-front",
                    )

            def _send(self, reply, binary):
                if binary:
                    write_frame(self.wfile, reply)
                else:
                    self.wfile.write(
                        (json.dumps(reply) + "\n").encode("utf-8")
                    )
                self.wfile.flush()

            def handle(self):
                if not slots.acquire(blocking=False):
                    try:
                        self._send({
                            "ok": False,
                            "error": "router connection cap reached",
                            "error_type": "Overloaded",
                            "reason": "max_connections",
                            "retry_after": min(0.05, RETRY_AFTER_CAP),
                        }, False)
                    except OSError:
                        pass
                    return
                try:
                    self._handle_conn()
                except ConnectionError:
                    # the peer reset or vanished mid-request (storm
                    # weather, not a router bug): close quietly
                    return
                finally:
                    slots.release()

            def _handle_conn(self):
                conns = {}  # this thread's backend connections
                binary = False
                try:
                    while True:
                        if binary:
                            try:
                                req = read_frame(self.rfile)
                            except FrameError as e:
                                self._send({
                                    "ok": False, "error": str(e),
                                    "error_type": "FrameError",
                                }, binary)
                                return
                            if req is None:
                                return
                            if not isinstance(req, dict):
                                self._send({
                                    "ok": False,
                                    "error": "frame payload must be a map",
                                    "error_type": "FrameError",
                                }, binary)
                                return
                        else:
                            raw = self.rfile.readline()
                            if not raw:
                                return
                            line = raw.strip()
                            if not line:
                                continue
                            try:
                                req = json.loads(line)
                            except ValueError as e:
                                self._send({
                                    "ok": False,
                                    "error": f"malformed request line: {e}",
                                    "error_type": "FrameError",
                                }, binary)
                                continue
                            if not isinstance(req, dict):
                                self._send({
                                    "ok": False,
                                    "error": "request must be a JSON object",
                                    "error_type": "FrameError",
                                }, binary)
                                continue
                        if req.get("op") == "hello":
                            proto = min(int(req.get("proto", 1)), PROTO_V2)
                            reply = {"ok": True, "proto": proto}
                            if "rid" in req:
                                reply["rid"] = req["rid"]
                            self._send(reply, binary)
                            binary = proto >= PROTO_V2
                            continue
                        try:
                            reply = router.handle_request(req, conns)
                        except Exception as e:  # one bad request must
                            # not kill the connection
                            reply = {
                                "ok": False,
                                "error": f"{type(e).__name__}: {e}",
                            }
                        if "rid" in req:
                            reply = dict(reply, rid=req["rid"])
                        self._send(reply, binary)
                except socket.timeout:
                    # idle deadline: a silent or half-open client is
                    # reaped -- close quietly, no stranded thread
                    return
                finally:
                    for c in conns.values():
                        c.close()

        class Server(socketserver.ThreadingTCPServer):
            allow_reuse_address = True
            daemon_threads = True

        return Server((host, int(port)), Handler)


def main(argv=None):
    """``hyperopt-tpu-router``: the fleet's routing front as a process.

    Example (two replicas sharing a durability root)::

        hyperopt-tpu-serve --space my.mod:space --root /shared/studies \\
            --owner r0 --port 7070 &
        hyperopt-tpu-serve --space my.mod:space --root /shared/studies \\
            --owner r1 --port 7071 &
        hyperopt-tpu-router --salt my-space \\
            --backend r0=127.0.0.1:7070 --backend r1=127.0.0.1:7071
    """
    import argparse

    parser = argparse.ArgumentParser(
        prog="hyperopt-tpu-router",
        description="consistent-hash router for a hyperopt-tpu serve "
        "fleet: speaks the JSON-line protocol, routes by study name, "
        "fails studies over to ring survivors (which restore from the "
        "shared --root) when a replica dies",
    )
    parser.add_argument(
        "--backend", action="append", required=True, metavar="ID=HOST:PORT",
        help="one replica endpoint (repeatable)",
    )
    parser.add_argument(
        "--salt", default="",
        help="ring salt -- use the fleet's space/guard fingerprint so "
        "placement matches any other router over the same fleet",
    )
    parser.add_argument("--vnodes", type=int, default=64)
    parser.add_argument("--host", default="127.0.0.1")
    parser.add_argument("--port", type=int, default=7076)
    parser.add_argument(
        "--probe-interval", type=float, default=1.0,
        help="seconds between background health-probe sweeps "
        "(graftscope: per-backend connection reuse, suspect marking "
        "before client asks fail, probe-recovered backends rejoin the "
        "ring); persistently-down backends back off exponentially "
        "inside the sweep (see --probe-backoff-cap); 0 disables "
        "probing",
    )
    parser.add_argument(
        "--read-timeout", type=float, default=DEFAULT_READ_TIMEOUT,
        help="per-op read deadline on every backend connection "
        "(graftstorm: a hung backend surfaces typed NetworkTimeout "
        "and takes the failover path instead of stranding a handler)",
    )
    parser.add_argument(
        "--idle-timeout", type=float, default=300.0,
        help="per-connection idle deadline on the client front: an "
        "idle or half-open client is reaped after this many seconds",
    )
    parser.add_argument(
        "--max-conns", type=int, default=256,
        help="bound on concurrently served client connections; one "
        "past the cap gets a typed Overloaded refusal "
        "(reason max_connections) instead of an unbounded accept loop",
    )
    parser.add_argument(
        "--probe-backoff-cap", type=int, default=8,
        help="max sweeps skipped between probes of a persistently-"
        "down backend (exponential backoff 1, 2, 4, ... capped here; "
        "any successful probe resets it) -- bounds both the load on a "
        "long-dead host and its rejoin latency",
    )
    args = parser.parse_args(argv)

    backends = []
    for spec in args.backend:
        rid, _, addr = spec.partition("=")
        host, _, port = addr.rpartition(":")
        if not (rid and host and port):
            raise SystemExit(f"--backend must be ID=HOST:PORT, got {spec!r}")
        backends.append(_Backend(rid, host, int(port)))
    router = RouterServer(
        backends, salt=args.salt, vnodes=args.vnodes,
        probe_backoff_cap=args.probe_backoff_cap,
        read_timeout=args.read_timeout,
        idle_timeout=args.idle_timeout, max_conns=args.max_conns,
    )
    server = router.serve_forever(host=args.host, port=args.port)
    if args.probe_interval > 0:
        router.start_probes(interval=args.probe_interval)
    host, port = server.server_address[:2]
    print(
        f"hyperopt-tpu-router listening on {host}:{port} "
        f"({len(backends)} backend(s))", flush=True,
    )
    try:
        server.serve_forever()
    except KeyboardInterrupt:
        pass
    finally:
        router.stop_probes()
        server.server_close()
    return 0


if __name__ == "__main__":
    raise SystemExit(main())
