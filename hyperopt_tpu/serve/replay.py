"""graftreplay: flight-recorder span logs as replayable traffic.

Capacity planning needs real traffic shapes, and chaos proofs need a
way to show an entire recorded scenario re-derives bitwise.  This
module turns the graftscope flight recorder's span log -- which the
serve stack already writes -- into both:

* the ``study.open`` span carries the study's EFFECTIVE seed and the
  ``tell`` span carries the reported loss (two observation-only fields
  added for this contract), so a span log is a **self-contained
  workload**: which studies opened with which seeds, every ask in
  arrival order, every tell with its loss;
* because a suggestion is a pure function of (seed, tell history) --
  the determinism contract the whole repo is built on -- replaying
  that workload against a fresh service or fleet reproduces every
  suggestion stream bitwise, no matter how the original run was
  batched, sharded, failed over, or autoscaled mid-flight;
* a FAULTED run's log replays to the CLEAN streams: recovery
  re-submissions and re-served asks appear as duplicate (study, tid)
  spans, and extraction keeps only the first occurrence of each.

``record once, replay bitwise``: arm a ``FlightRecorder(path=...)`` on
the service, run traffic, then::

    ops = load_workload(path)
    streams = replay_workload(ops, ServiceTarget(fresh_service))
    assert stream_hash(streams) == stream_hash(recorded_streams)

``replay_fidelity(a, b)`` is the scalar the bench stamps: 1.0 on hash
match, 0.0 otherwise.
"""

from __future__ import annotations

import hashlib
import json
import logging

from ..distributed.faults import REAL_FS
from ..obs.flightrec import read_flight_log

logger = logging.getLogger(__name__)

__all__ = [
    "extract_workload", "load_workload", "replay_workload",
    "ServiceTarget", "stream_hash", "replay_fidelity",
    "replay_flight_log",
]


def extract_workload(spans):
    """Distill spans into an ordered op list: ``("open", study, seed)``
    / ``("ask", study, tid)`` / ``("tell", study, tid, loss)``.

    Ordering: exported spans carry the recorder's monotone ``seq``
    (used when present); in-memory ``tail()`` spans replay in list
    order.  Asks anchor on ``ask.delivered`` -- the DISPATCH-side
    span -- because a suggestion is a function of the study's history
    at dispatch time, not at submit time: the per-study interleave of
    delivered asks and applied tells in span order IS the history
    each suggestion saw (per-study delivery is FIFO in tid order, and
    cross-study order cannot matter -- histories are per-study).
    Dedup: only the FIRST span per (study, tid) counts for asks and
    for tells -- a faulted run's recovery re-serves and replayed
    tells collapse onto the clean order.

    Record with ``FlightRecorder(cadence=1)`` (the default): a
    sampled log is missing ops and replays loudly wrong (the tid
    check in :func:`replay_workload`), never silently wrong.
    """
    ordered = sorted(
        enumerate(spans),
        key=lambda pair: (pair[1].get("seq", pair[0]), pair[0]),
    )
    ops = []
    opened = {}
    seen_asks = set()
    seen_tells = set()
    for _i, span in ordered:
        name = span.get("name")
        study = span.get("study")
        if name == "study.open" and study is not None:
            if study not in opened:
                seed = int(span.get("seed", 0))
                opened[study] = seed
                ops.append(("open", study, seed))
        elif name == "ask.delivered" and study is not None:
            key = (study, int(span["tid"]))
            if key not in seen_asks:
                seen_asks.add(key)
                ops.append(("ask", study, key[1]))
        elif name == "tell" and study is not None:
            key = (study, int(span["tid"]))
            if key in seen_tells:
                continue
            seen_tells.add(key)
            if "loss" not in span:
                raise ValueError(
                    f"tell span for {study!r} tid {key[1]} carries no "
                    "loss -- the log predates the replayable-workload "
                    "contract and cannot be replayed"
                )
            ops.append(("tell", study, key[1], float(span["loss"])))
    return ops


def load_workload(path, fs=REAL_FS):
    """The op list of a flight log on disk (torn tail ignored)."""
    return extract_workload(read_flight_log(path, fs=fs))


class ServiceTarget:
    """Adapts a solo :class:`~hyperopt_tpu.serve.service.
    SuggestService` to the replay target protocol (``open`` / ``ask``
    / ``tell`` by study name).  A fleet's in-process
    :class:`~hyperopt_tpu.serve.router.FleetRouter` already speaks it
    natively (``create_study`` / ``ask`` / ``tell``)."""

    def __init__(self, service, timeout=60.0):
        self.service = service
        self.timeout = float(timeout)
        self._handles = {}

    def create_study(self, name, seed=0):
        self._handles[name] = self.service.create_study(name, seed=seed)

    def ask(self, name, timeout=None):
        return self._handles[name].ask(
            timeout=self.timeout if timeout is None else timeout
        )

    def tell(self, name, tid, loss):
        self._handles[name].tell(tid, loss)


def replay_workload(ops, target, timeout=60.0):
    """Drive the recorded ops against ``target`` (a
    :class:`ServiceTarget` or an in-process ``FleetRouter``) in
    arrival order; returns ``{study: [(tid, vals), ...]}`` -- the
    replayed suggestion streams.

    The replayed tids must match the recorded ones (same submit order
    per study => same tid sequence); a mismatch means the log and the
    target disagree about history and is raised, not papered over."""
    streams = {}
    for op in ops:
        kind, study = op[0], op[1]
        if kind == "open":
            target.create_study(study, seed=op[2])
            streams.setdefault(study, [])
        elif kind == "ask":
            tid, vals = target.ask(study, timeout=timeout)
            if int(tid) != int(op[2]):
                raise ValueError(
                    f"replay diverged: study {study!r} served tid "
                    f"{tid}, the recording expected {op[2]}"
                )
            streams.setdefault(study, []).append((int(tid), dict(vals)))
        elif kind == "tell":
            target.tell(study, op[2], op[3])
    return streams


def stream_hash(streams):
    """Canonical digest of suggestion streams ({study: [(tid, vals)]}):
    sorted-key JSON (floats via repr round-trip exactly) -> blake2b.
    Two runs are bitwise-identical iff their hashes match."""
    canon = {
        str(study): [
            [int(tid), {k: float(v) for k, v in sorted(vals.items())}]
            for tid, vals in pairs
        ]
        for study, pairs in sorted(streams.items())
    }
    data = json.dumps(canon, sort_keys=True, separators=(",", ":"))
    return hashlib.blake2b(data.encode("utf-8"), digest_size=16).hexdigest()


def replay_fidelity(recorded_streams, replayed_streams):
    """The bench scalar: 1.0 when the replayed streams hash-match the
    recorded ones, else 0.0."""
    return (
        1.0 if stream_hash(recorded_streams) == stream_hash(replayed_streams)
        else 0.0
    )


def replay_flight_log(path, target, fs=REAL_FS, timeout=60.0):
    """Convenience: load the span log at ``path`` and replay it
    against ``target``; returns the replayed streams."""
    return replay_workload(load_workload(path, fs=fs), target,
                           timeout=timeout)
