"""graftserve: the multi-tenant suggestion service.

The "millions of users" scenario (ROADMAP open item 1) is not one giant
study -- it is thousands of small concurrent ones arriving as traffic.
This package batches the paper's ask/tell plugin boundary ACROSS studies
the way LLM serving batches requests (continuous batching):

* :mod:`.batched` -- the device engine: N independent studies' resident
  :class:`~hyperopt_tpu.ops.kernels.HistoryState`\\ s stacked along a
  leading study axis (:class:`~.batched.StudyBatchState`) and the fused
  tell+ask program ``vmap``-ed over it, so ONE dispatch serves every
  active study's ask;
* :mod:`.scheduler` -- the continuous-batching scheduler: a slotted
  batch (fixed pow2 capacities + an active-slot mask, so studies join
  and leave without retracing) that coalesces incoming asks under a
  max-wait / max-batch budget, with per-study rstate streams keeping
  every suggestion sequence deterministic regardless of batching order;
* :mod:`.service` -- the front: an in-process ``StudyHandle`` API
  (``create_study / ask / tell / best``), per-study WAL-backed
  durability (PR-6 :class:`~hyperopt_tpu.utils.wal.TellWAL` machinery,
  exactly-once tells across a service crash), and a stdlib JSON-line
  socket transport behind the ``hyperopt-tpu-serve`` console script.

Since round 20 this engine is ALSO the sequential driver: a solo
``fmin(engine=True / ask_ahead=k)`` is a batch-of-one tenant driven
through :mod:`hyperopt_tpu.client` (graftclient) -- there is no
separate solo dispatch regime anymore (DESIGN.md §3b/§3g).
"""

__all__ = [
    "StudyHandle", "SuggestService",
    # graftfleet: the horizontal tier above one service
    "Fleet", "FleetRouter", "HashRing", "StudyClaim",
    # graftpilot: the metric-driven autoscaler + traffic replay
    "FleetPilot", "PilotConfig",
    "extract_workload", "replay_flight_log", "stream_hash",
]

_HOMES = {
    "StudyHandle": "service",
    "SuggestService": "service",
    "Fleet": "fleet",
    "StudyClaim": "fleet",
    "FleetRouter": "router",
    "HashRing": "router",
    "FleetPilot": "pilot",
    "PilotConfig": "pilot",
    "extract_workload": "replay",
    "replay_flight_log": "replay",
    "stream_hash": "replay",
}


def __getattr__(name):
    # lazy: the graftir registry imports ``serve.batched`` on every
    # lint/bench run; pulling the scheduler/service front along would
    # be dead weight there
    home = _HOMES.get(name)
    if home is not None:
        import importlib

        return getattr(importlib.import_module(f".{home}", __name__), name)
    raise AttributeError(name)
