"""The study-batched device engine: one dispatch serves N studies.

PR 4 made a single study's tell+ask one device dispatch over a resident
:class:`~hyperopt_tpu.ops.kernels.HistoryState`.  This module stacks N
independent studies' states along a leading study axis
(:class:`StudyBatchState`) and ``vmap``\\ s the very same per-study
suggest closure over it, so one compiled program applies every slot's
staged O(D) tell delta AND draws every slot's next suggestion -- the
fused tell+ask of the sequential driver, amortized across tenants.

Parity contract: the per-slot body is the UNJITTED closure the solo
builders jit (``build_suggest_fn(..., raw=True)`` /
``build_anneal_fn(..., raw=True)``), the delta write is
:func:`~hyperopt_tpu.ops.kernels.apply_delta_masked` (bitwise
:func:`~hyperopt_tpu.ops.kernels.apply_delta` where the mask applies),
and regime selection is an elementwise ``where`` between the warm
suggestion and the prior draw computed from the same per-study key --
so slot ``i`` of a batched dispatch is bitwise-identical to the solo
fused path run on study ``i``'s state alone (pinned per-study against
the unbatched programs in ``tests/test_serve.py``).

Shape discipline: all studies share one space template, one obs-bucket
width (the max of the per-study pow2 buckets) and one pow2 SLOT
capacity, so the program family retraces only on bucket/capacity
growth -- studies joining and leaving a slotted batch reuse the same
trace, exactly like history growth in the solo path.

graftmesh (PR 12): every builder takes ``mesh=`` -- a 1-D ``study``
mesh (:func:`hyperopt_tpu.parallel.mesh.study_mesh`) over which the
slot axis shards with ``shard_map``.  The per-shard body IS the same
vmapped closure run over that shard's slot block, so a 1-device mesh
is bitwise the unsharded engine and an n-device mesh multiplies slot
capacity by n with zero cross-shard collectives (slots never interact;
the only mesh-wide work is the input scatter/output gather at the jit
boundary).  Slot capacities round up to a multiple of the study-axis
size (:func:`slot_capacity` ``shards=``) so the stacked state always
shards evenly -- dead pad slots hide behind the active mask like any
freed slot.
"""

from __future__ import annotations

from typing import NamedTuple

import numpy as np

__all__ = [
    "StudyBatchState",
    "build_batched_step_fn",
    "build_batched_delta_fn",
    "build_finite_check_fn",
    "stack_states",
    "restack_shards",
    "slot_capacity",
    "MIN_SLOTS",
]

#: smallest slot capacity a batch is allocated at; capacities grow by
#: pow2 doubling up to the scheduler's ``max_batch`` (same bounded-
#: recompile argument as ObsBuffer's history buckets).
MIN_SLOTS = 4


class StudyBatchState(NamedTuple):
    """N stacked :class:`~hyperopt_tpu.ops.kernels.HistoryState`\\ s.

    The four dense history arrays with a leading study axis -- the
    device-resident state of one slotted batch.  Slot ``i`` IS study
    ``i``'s ``HistoryState`` (``jax.tree.map(lambda a: a[i], state)``),
    so every per-study invariant of the solo resident mirror carries
    over slot-wise; freed slots hold garbage behind the scheduler's
    active-slot mask and are never read back.
    """

    values: object  # [S, D, cap] natural-space draws
    active: object  # [S, D, cap] per-dim activity mask
    losses: object  # [S, cap]
    valid: object   # [S, cap] slot occupancy (per-study prefix mask)


def slot_capacity(n_studies, max_batch, shards=1):
    """The slot capacity a batch of ``n_studies`` runs at: pow2
    doubling from :data:`MIN_SLOTS`, clamped to ``max_batch`` (the
    scheduler's configured ceiling), then rounded UP to a multiple of
    ``shards`` (the mesh study-axis size) so the stacked state always
    shards evenly -- the rounding pads dead slots behind the active
    mask, it never truncates live ones."""
    cap = MIN_SLOTS
    while cap < n_studies and cap < max_batch:
        cap <<= 1
    cap = min(cap, max_batch)
    m = max(1, int(shards))
    return -(-cap // m) * m


def _host_stack(buffers, slot_cap, bucket, n_dims):
    """The four stacked host arrays for ``slot_cap`` slots (relative
    slot indices) at ``bucket`` width -- shared by the full
    materialization and the per-shard block rebuild."""
    s = int(slot_cap)
    b = int(bucket)
    d = int(n_dims)
    values = np.zeros((s, d, b), dtype=np.float32)
    active = np.zeros((s, d, b), dtype=bool)
    losses = np.zeros((s, b), dtype=np.float32)
    valid = np.zeros((s, b), dtype=bool)
    for i, buf in buffers.items():
        # a sibling's host capacity may trail the batch bucket (the
        # bucket tracks the LARGEST study); its tail stays zero/invalid
        w = min(buf.values.shape[1], b)
        values[i, :, :w] = buf.values[:, :w]
        active[i, :, :w] = buf.active[:, :w]
        losses[i, :w] = buf.losses[:w]
        valid[i, :w] = buf.valid[:w]
    return values, active, losses, valid


def _study_sharding(mesh, axis):
    from jax.sharding import NamedSharding, PartitionSpec as P

    return NamedSharding(mesh, P(axis))


def stack_states(buffers, slot_cap, bucket, mesh=None, axis=None):
    """Stack per-study host buffers into a device StudyBatchState.

    ``buffers`` maps slot index -> ObsBuffer (missing slots are zero
    history -- freed or never-joined, masked out by the scheduler).
    One ``device_put`` of the stacked arrays; the upload that happens
    on joins, bucket growth, and out-of-order re-materializations (the
    log schedule of the solo resident mirror, batch-wide).  With
    ``mesh=`` the arrays are placed sharded over the study axis, so
    the batched step's ``shard_map`` never reshards its state.
    Returns ``(state, nbytes)``.
    """
    import jax

    d = None
    for buf in buffers.values():
        d = buf.space.n_dims
        break
    if d is None:
        raise ValueError("stack_states needs at least one study buffer")
    arrays = _host_stack(buffers, slot_cap, bucket, d)
    nbytes = sum(a.nbytes for a in arrays)
    if mesh is None:
        return StudyBatchState(*(jax.device_put(a) for a in arrays)), nbytes
    from ..parallel.mesh import STUDY_AXIS

    sharding = _study_sharding(mesh, axis or STUDY_AXIS)
    return StudyBatchState(
        *(jax.device_put(a, sharding) for a in arrays)
    ), nbytes


def restack_shards(state, buffers, slot_cap, bucket, n_dims, mesh, axis,
                   dirty_shards):
    """Shard-local re-materialization: rebuild ONLY the dirty shards'
    slot blocks from host truth, reusing every clean shard's device
    buffers untouched -- siblings on other shards are pinned bitwise
    by construction (their bytes never move).  Returns
    ``(state, nbytes_uploaded)``.

    ``buffers`` maps GLOBAL slot index -> ObsBuffer; ``dirty_shards``
    is the set of shard ordinals (mesh device order) to rebuild.
    """
    import jax

    n_shards = int(mesh.shape[axis])
    s = int(slot_cap)
    blk = s // n_shards
    devices = list(mesh.devices.flat)
    sharding = _study_sharding(mesh, axis)
    host = {}
    for k in sorted(dirty_shards):
        lo = k * blk
        sub = {
            i - lo: buf for i, buf in buffers.items() if lo <= i < lo + blk
        }
        host[k] = _host_stack(sub, blk, bucket, n_dims)
    nbytes = sum(a.nbytes for blks in host.values() for a in blks)
    out = []
    for field, prev in enumerate(state):
        by_dev = {sh.device: sh.data for sh in prev.addressable_shards}
        datas = []
        for k, dev in enumerate(devices):
            if k in host:
                datas.append(jax.device_put(host[k][field], dev))
            else:
                datas.append(by_dev[dev])
        out.append(jax.make_array_from_single_device_arrays(
            prev.shape, sharding, datas
        ))
    return StudyBatchState(*out), nbytes


def _dummy_delta(ps, slot_cap):
    """Host-side no-op delta rows for slots with nothing staged (the
    ``apply=False`` mask makes them pure pass-through on device)."""
    d = ps.n_dims
    s = int(slot_cap)
    return (
        np.zeros((s, d), dtype=np.float32),
        np.zeros((s, d), dtype=bool),
        np.zeros((s,), dtype=np.float32),
        np.zeros((s,), dtype=np.int32),
        np.zeros((s,), dtype=bool),
    )


def build_batched_step_fn(ps, algo="tpe", n_cand=16, gamma=0.25, lf=25.0,
                          prior_weight=1.0, n_cand_cat=None,
                          above_cap=None, avg_best_idx=2.0,
                          shrink_coef=0.1, mesh=None, mesh_axis=None):
    """Compile (once per parameterization) the batched fused tell+ask
    step for a PackedSpace.

    Returns jitted ``fn(keys, values, active, losses, valid, vcol,
    acol, loss, idx, apply, warm, batch) -> (values', active', losses',
    valid', new_values [S, D, B], new_active [S, D, B])`` with
    ``batch`` static and the four state buffers DONATED -- the stacked
    twin of ``build_suggest_fn(state_io=True)``.

    Per slot: the staged delta applies where ``apply`` is set
    (:func:`~hyperopt_tpu.ops.kernels.apply_delta_masked`), then the
    suggestion is drawn from the updated slot state -- through the
    solo algo closure where ``warm`` is set, through the prior program
    otherwise (the startup regime), both from the SAME per-slot key, so
    each slot's output is bitwise the solo path's for that regime.
    Slots without a pending ask receive a placeholder key and their
    suggestion columns are simply never read back.

    ``algo`` selects the per-study suggest body: ``"tpe"``
    (:func:`hyperopt_tpu.tpe_jax.build_suggest_fn`) or ``"anneal"``
    (:func:`hyperopt_tpu.anneal_jax.build_anneal_fn`).

    ``mesh=`` (graftmesh) shards the slot axis over a 1-D study mesh
    with ``shard_map``: each device runs the IDENTICAL vmapped per-slot
    body over its slot block, so a 1-device mesh is bitwise this
    function's unsharded program and slot capacity scales with device
    count.  The slot axis length must divide by the mesh size
    (:func:`slot_capacity` ``shards=`` guarantees it).

    The jitted program is cached ON the PackedSpace (the
    ``cached_suggest_fn`` pattern): a restarted service over the same
    compiled space -- the crash-recovery loop -- reuses the program and
    its traces instead of recompiling.
    """
    import jax
    import jax.numpy as jnp

    from ..ops import kernels as K

    cache_key = (
        str(algo), int(n_cand), float(gamma), float(lf),
        float(prior_weight),
        None if n_cand_cat is None else int(n_cand_cat),
        None if above_cap is None else int(above_cap),
        float(avg_best_idx), float(shrink_coef),
        None if mesh is None else (mesh, mesh_axis),
    )
    cache = getattr(ps, "_serve_step_cache", None)
    if cache is None:
        cache = {}
        ps._serve_step_cache = cache
    cached = cache.get(cache_key)
    if cached is not None:
        return cached

    if algo == "tpe":
        from ..tpe_jax import _resolve_above_cap, build_suggest_fn

        core = build_suggest_fn(
            ps, int(n_cand), float(gamma), float(lf), float(prior_weight),
            n_cand_cat=n_cand_cat,
            above_cap=0 if _resolve_above_cap(above_cap) is None
            else _resolve_above_cap(above_cap),
            raw=True,
        )
    elif algo == "anneal":
        from ..anneal_jax import build_anneal_fn

        core = build_anneal_fn(
            ps, float(avg_best_idx), float(shrink_coef), raw=True
        )
    else:
        raise ValueError(f"unknown serve algo {algo!r}")
    _ = ps._consts  # materialize constants outside the trace

    def step(keys, values, active, losses, valid, vcol, acol, loss, idx,
             apply, warm, batch):
        def one(key, v, a, l, vd, vc, ac, lo, ix, ap, wm):
            st = K.apply_delta_masked(v, a, l, vd, vc, ac, lo, ix, ap)
            warm_v, warm_a = core(key, *st, batch)
            pri_v, pri_a = ps.sample_prior_fn(key, batch)
            nv = jnp.where(wm, warm_v, pri_v)
            na = jnp.where(wm, warm_a, pri_a)
            return tuple(st) + (nv, na)

        body = jax.vmap(one)
        if mesh is not None:
            # graftmesh: the SAME vmapped closure per shard -- slots
            # never interact, so there is no collective in the body
            # and each slot's math is bitwise the unsharded program's
            from jax.sharding import PartitionSpec as P

            from ..parallel.mesh import STUDY_AXIS
            from ..parallel.sharded import _shard_map

            ax = mesh_axis or STUDY_AXIS
            body = _shard_map()(
                body, mesh=mesh, in_specs=(P(ax),) * 11,
                out_specs=P(ax), check_vma=False,
            )
        return body(
            keys, values, active, losses, valid, vcol, acol, loss, idx,
            apply, warm,
        )

    fn = jax.jit(
        step, static_argnames=("batch",), donate_argnums=(1, 2, 3, 4)
    )
    cache[cache_key] = fn
    return fn


_FINITE_CHECK_FN = None  # lazily-built; shared by every scheduler
_FINITE_CHECK_FN_MESH = {}  # (mesh, axis) -> jitted sharded twin


def build_finite_check_fn(mesh=None, mesh_axis=None):
    """The graftguard poisoned-slot detector: ``fn(values, active,
    losses, valid, new_v) -> poisoned [S] bool``.

    One cheap fused reduction over the stacked state and the round's
    suggestion columns: a slot is POISONED when any active history
    value, any valid loss, or any of this round's suggestion columns
    is non-finite -- the signature of a tenant telling NaN/Inf losses,
    a corrupted resident slot, or a device fault scribbling NaN into
    the batched step output.  Masked positions (inactive dims, empty
    history slots) are exempt: a freed or short slot's garbage tail
    must never trip a healthy tenant.

    Read-only by design (NO donation): it runs between the batched
    step and the acks, and the state it inspects is the state the next
    round dispatches from.  Built once per process -- like the delta
    drain, it has no space dependence.  ``mesh=`` builds the
    shard_map twin (per-shard reduction over its slot block -- the
    guard stays shard-local, one cached program per mesh)."""
    import jax
    import jax.numpy as jnp

    def finite_check(values, active, losses, valid, new_v):
        v_ok = jnp.all(
            jnp.isfinite(jnp.where(active, values, 0.0)), axis=(1, 2)
        )
        l_ok = jnp.all(
            jnp.isfinite(jnp.where(valid, losses, 0.0)), axis=1
        )
        s_ok = jnp.all(jnp.isfinite(new_v), axis=(1, 2))
        return ~(v_ok & l_ok & s_ok)

    if mesh is not None:
        from jax.sharding import PartitionSpec as P

        from ..parallel.mesh import STUDY_AXIS
        from ..parallel.sharded import _shard_map

        ax = mesh_axis or STUDY_AXIS
        key = (mesh, ax)
        fn = _FINITE_CHECK_FN_MESH.get(key)
        if fn is None:
            fn = jax.jit(_shard_map()(
                finite_check, mesh=mesh, in_specs=(P(ax),) * 5,
                out_specs=P(ax), check_vma=False,
            ))
            _FINITE_CHECK_FN_MESH[key] = fn
        return fn
    global _FINITE_CHECK_FN
    if _FINITE_CHECK_FN is None:
        _FINITE_CHECK_FN = jax.jit(finite_check)
    return _FINITE_CHECK_FN


_BATCHED_DELTA_FN = None  # lazily-built; shared by every scheduler
_BATCHED_DELTA_FN_MESH = {}  # (mesh, axis) -> jitted sharded twin


def build_batched_delta_fn(mesh=None, mesh_axis=None):
    """The stacked twin of the standalone O(D) delta-tell program:
    ``fn(values, active, losses, valid, vcol, acol, loss, idx, apply)``
    -- one dispatch applies (at most) one staged delta per slot, the
    backlog-drain path when a study told more than once between asks.
    Donated state, like the solo ``_apply_delta_fn`` (and like it,
    built once per process -- it has no space dependence).  ``mesh=``
    builds the shard_map twin over the study axis (one cached program
    per mesh; the per-slot write is bitwise the unsharded one)."""
    import jax

    from ..ops.kernels import apply_delta_masked

    if mesh is not None:
        from jax.sharding import PartitionSpec as P

        from ..parallel.mesh import STUDY_AXIS
        from ..parallel.sharded import _shard_map

        ax = mesh_axis or STUDY_AXIS
        key = (mesh, ax)
        fn = _BATCHED_DELTA_FN_MESH.get(key)
        if fn is None:
            fn = jax.jit(
                _shard_map()(
                    jax.vmap(apply_delta_masked), mesh=mesh,
                    in_specs=(P(ax),) * 9, out_specs=P(ax),
                    check_vma=False,
                ),
                donate_argnums=(0, 1, 2, 3),
            )
            _BATCHED_DELTA_FN_MESH[key] = fn
        return fn
    global _BATCHED_DELTA_FN
    if _BATCHED_DELTA_FN is None:
        _BATCHED_DELTA_FN = jax.jit(
            jax.vmap(apply_delta_masked), donate_argnums=(0, 1, 2, 3)
        )
    return _BATCHED_DELTA_FN


# ---------------------------------------------------------------------------
# graftir registrations (hyperopt-tpu-lint --ir): the batched families
# ---------------------------------------------------------------------------

from ..ops.compile import ProgramCapture, register_program  # noqa: E402


@register_program(
    "serve.batched_step",
    families=("hyperopt_tpu.serve.batched:build_batched_step_fn",),
)
def _registry_serve_step(p):
    """The service's one-dispatch-per-round program: every slot's
    staged tell applied and every slot's ask drawn, vmapped over the
    study axis (donated stacked state)."""
    fn = build_batched_step_fn(p.space, algo="tpe", n_cand=16)
    return ProgramCapture(
        fn=fn,
        args=(p.keys_spec(),) + p.study_history_specs()
        + p.study_delta_specs() + (p.study_mask_spec(),),
        kwargs={"batch": 1},
        donate_argnums=(1, 2, 3, 4),
        # vmap of closures whose GL402 promotion behavior is already
        # pinned by their solo registrations (tpe_jax.suggest,
        # compile.sample_prior, jax_trials.apply_delta) -- skip the
        # duplicate re-trace, same precedent as speculative_redraw
        x64_check=False,
    )


@register_program(
    "serve.batched_anneal_step",
    families=("hyperopt_tpu.serve.batched:build_batched_step_fn",),
)
def _registry_serve_anneal_step(p):
    """The annealing twin of ``serve.batched_step`` (same stacked
    state contract, anneal per-study body)."""
    fn = build_batched_step_fn(p.space, algo="anneal")
    return ProgramCapture(
        fn=fn,
        args=(p.keys_spec(),) + p.study_history_specs()
        + p.study_delta_specs() + (p.study_mask_spec(),),
        kwargs={"batch": 1},
        donate_argnums=(1, 2, 3, 4),
        # constituent closures x64-pinned by anneal_jax.suggest /
        # compile.sample_prior / jax_trials.apply_delta
        x64_check=False,
    )


@register_program(
    "serve.batched_apply_delta",
    families=("hyperopt_tpu.ops.kernels:apply_delta_masked",),
)
def _registry_serve_delta(p):
    """The backlog-drain program: one masked O(D) delta per slot,
    donated stacked state (the batched ``jax_trials.apply_delta``)."""
    fn = build_batched_delta_fn()
    return ProgramCapture(
        fn=fn,
        args=p.study_history_specs() + p.study_delta_specs(),
        donate_argnums=(0, 1, 2, 3),
    )


def _mesh_specs(specs, mesh, axis):
    """Re-pin abstract specs with the study-axis sharding attached, so
    the traced/lowered mesh program sees the layout production runs at
    (and GL403 reads the multi-device donation attributes)."""
    import jax

    sharding = _study_sharding(mesh, axis)
    return tuple(
        jax.ShapeDtypeStruct(s.shape, s.dtype, sharding=sharding)
        for s in specs
    )


@register_program(
    "serve.batched_step_mesh",
    families=("hyperopt_tpu.serve.batched:build_batched_step_fn",),
)
def _registry_serve_step_mesh(p):
    """The graftmesh twin of ``serve.batched_step``: the same vmapped
    per-slot body shard_mapped over a forced 4-virtual-CPU-device
    study mesh (donated stacked state, verified under shard_map by
    GL403 via the multi-device ``jax.buffer_donor`` attributes)."""
    from ..parallel.mesh import STUDY_AXIS, registry_cpu_mesh

    mesh = registry_cpu_mesh()
    fn = build_batched_step_fn(
        p.space, algo="tpe", n_cand=16, mesh=mesh, mesh_axis=STUDY_AXIS,
    )
    specs = (
        (p.keys_spec(),) + p.study_history_specs()
        + p.study_delta_specs() + (p.study_mask_spec(),)
    )
    return ProgramCapture(
        fn=fn,
        args=_mesh_specs(specs, mesh, STUDY_AXIS),
        kwargs={"batch": 1},
        donate_argnums=(1, 2, 3, 4),
        # per-slot closures x64-pinned by the solo registrations (same
        # precedent as serve.batched_step)
        x64_check=False,
    )


@register_program(
    "serve.batched_delta_mesh",
    families=("hyperopt_tpu.ops.kernels:apply_delta_masked",),
)
def _registry_serve_delta_mesh(p):
    """The graftmesh backlog-drain twin: one masked O(D) delta per
    slot, shard_mapped over the forced study mesh (donated stacked
    state, GL403-verified under shard_map)."""
    from ..parallel.mesh import STUDY_AXIS, registry_cpu_mesh

    mesh = registry_cpu_mesh()
    fn = build_batched_delta_fn(mesh=mesh, mesh_axis=STUDY_AXIS)
    specs = p.study_history_specs() + p.study_delta_specs()
    return ProgramCapture(
        fn=fn,
        args=_mesh_specs(specs, mesh, STUDY_AXIS),
        donate_argnums=(0, 1, 2, 3),
    )


@register_program(
    "serve.guard_finite_check",
    families=(
        "hyperopt_tpu.serve.batched:build_finite_check_fn",
    ),
)
def _registry_guard_finite_check(p):
    """graftguard's poisoned-slot detector: one fused masked
    isfinite-reduction over the stacked state and the round's
    suggestion columns, [S] bool out, NO donation (it inspects the
    state the next round dispatches from)."""
    import jax
    import jax.numpy as jnp

    fn = build_finite_check_fn()
    s, d = p.n_studies, p.space.n_dims
    return ProgramCapture(
        fn=fn,
        args=p.study_history_specs() + (
            jax.ShapeDtypeStruct((s, d, 1), jnp.float32),
        ),
        donate_argnums=(),
    )
