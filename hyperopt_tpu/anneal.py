"""Simulated-annealing-flavored suggest algorithm.

Capability parity with the reference's ``hyperopt/anneal.py`` (SURVEY.md
SS2): propose new configs near previously good ones, with neighborhoods
that shrink as observations accumulate.  Cheap, embarrassingly local --
useful as a baseline between random search and TPE.
"""

from __future__ import annotations

import numpy as np

from .base import JOB_STATE_DONE, STATUS_OK, miscs_to_idxs_vals
from .pyll.base import rec_eval
from .pyll.stochastic import ensure_rng
from .rand import docs_from_idxs_vals
from .vectorize import VectorizeHelper

__all__ = ["suggest", "AnnealingAlgo"]


def _qround(x, q):
    return np.round(x / q) * q


class AnnealingAlgo:
    """One annealing step over a Domain's search space.

    avg_best_idx: mean rank of the anchor trial drawn from the sorted-by-
      loss history (2.0 -> usually one of the few best).
    shrink_coef: neighborhood shrink rate; fraction of the prior range used
      at n observations is ``1 / (1 + n * shrink_coef)``.
    """

    def __init__(self, domain, trials, seed, avg_best_idx=2.0, shrink_coef=0.1):
        self.domain = domain
        self.trials = trials
        self.rng = ensure_rng(seed)
        self.avg_best_idx = avg_best_idx
        self.shrink_coef = shrink_coef
        helper = getattr(domain, "_vectorize_helper", None)
        if helper is None:
            helper = VectorizeHelper(domain.expr)
            domain._vectorize_helper = helper
        self.helper = helper
        self.hps = helper.hps

    # -- history -----------------------------------------------------------
    def _ok_trials(self):
        return [
            t
            for t in self.trials.trials
            if t["state"] == JOB_STATE_DONE
            and t["result"].get("status") == STATUS_OK
            and t["result"].get("loss") is not None
        ]

    def _anchor_config(self, ok_trials):
        """Pick a good past trial (geometric over loss rank) -> its config."""
        losses = np.array([float(t["result"]["loss"]) for t in ok_trials])
        order = np.argsort(losses)
        rank = int(self.rng.geometric(1.0 / self.avg_best_idx) - 1)
        rank = min(rank, len(order) - 1)
        anchor = ok_trials[order[rank]]
        return {
            k: v[0]
            for k, v in anchor["misc"]["vals"].items()
            if len(v) == 1
        }

    def _n_obs(self, label, ok_trials):
        return sum(1 for t in ok_trials if len(t["misc"]["vals"].get(label, [])) == 1)

    def shrink_frac(self, n_obs):
        return 1.0 / (1.0 + n_obs * self.shrink_coef)

    # -- per-distribution draws -------------------------------------------
    def prior_draw(self, info):
        rng = self.rng
        p = info.params
        d = info.dist
        if d == "uniform":
            return rng.uniform(p["low"], p["high"])
        if d == "quniform":
            return _qround(rng.uniform(p["low"], p["high"]), p["q"])
        if d == "loguniform":
            return np.exp(rng.uniform(p["low"], p["high"]))
        if d == "qloguniform":
            return _qround(np.exp(rng.uniform(p["low"], p["high"])), p["q"])
        if d == "normal":
            return rng.normal(p["mu"], p["sigma"])
        if d == "qnormal":
            return _qround(rng.normal(p["mu"], p["sigma"]), p["q"])
        if d == "lognormal":
            return np.exp(rng.normal(p["mu"], p["sigma"]))
        if d == "qlognormal":
            return _qround(np.exp(rng.normal(p["mu"], p["sigma"])), p["q"])
        if d == "randint":
            return int(rng.integers(p["low"], p["high"]))
        if d in ("categorical", "randint_via_categorical"):
            probs = np.asarray(p["p"], dtype=float)
            return int(rng.choice(len(probs), p=probs / probs.sum()))
        raise NotImplementedError(d)

    def neighborhood_draw(self, info, anchor_val, n_obs):
        """Draw near ``anchor_val`` with a neighborhood shrunk by history."""
        rng = self.rng
        p = info.params
        d = info.dist
        frac = self.shrink_frac(n_obs)

        def trunc_uniform(center, low, high):
            width = (high - low) * frac
            lo = max(low, center - width / 2)
            hi = min(high, center + width / 2)
            if hi <= lo:
                return center
            return rng.uniform(lo, hi)

        if d == "uniform":
            return trunc_uniform(anchor_val, p["low"], p["high"])
        if d == "quniform":
            return _qround(trunc_uniform(anchor_val, p["low"], p["high"]), p["q"])
        if d == "loguniform":
            return np.exp(trunc_uniform(np.log(anchor_val), p["low"], p["high"]))
        if d == "qloguniform":
            v = max(anchor_val, np.exp(p["low"]))
            return _qround(
                np.exp(trunc_uniform(np.log(v), p["low"], p["high"])), p["q"]
            )
        if d == "normal":
            return rng.normal(anchor_val, p["sigma"] * frac)
        if d == "qnormal":
            return _qround(rng.normal(anchor_val, p["sigma"] * frac), p["q"])
        if d == "lognormal":
            return np.exp(rng.normal(np.log(max(anchor_val, 1e-12)), p["sigma"] * frac))
        if d == "qlognormal":
            return _qround(
                np.exp(rng.normal(np.log(max(anchor_val, 1e-12)), p["sigma"] * frac)),
                p["q"],
            )
        if d == "randint":
            if rng.uniform() < frac:
                return int(rng.integers(p["low"], p["high"]))
            return int(anchor_val)
        if d in ("categorical", "randint_via_categorical"):
            if rng.uniform() < frac:
                probs = np.asarray(p["p"], dtype=float)
                return int(rng.choice(len(probs), p=probs / probs.sum()))
            return int(anchor_val)
        raise NotImplementedError(d)

    # -- one batch ---------------------------------------------------------
    def sample_batch(self, new_ids):
        ok_trials = self._ok_trials()
        idxs = {label: [] for label in self.hps}
        vals = {label: [] for label in self.hps}
        n_obs = {label: self._n_obs(label, ok_trials) for label in self.hps}

        for tid in new_ids:
            if ok_trials:
                anchor = self._anchor_config(ok_trials)
            else:
                anchor = {}
            draws = {}
            for label, info in self.hps.items():
                if label in anchor:
                    draws[label] = self.neighborhood_draw(
                        info, anchor[label], n_obs[label]
                    )
                else:
                    draws[label] = self.prior_draw(info)
            # route through the space graph: only active labels recorded
            memo = {info.node: draws[label] for label, info in self.hps.items()}
            active = {}

            def observer(node, value):
                if node.name == "hyperopt_param":
                    active[node.pos_args[0].obj] = value

            rec_eval(self.domain.expr, memo=memo, observer=observer)
            for label, value in active.items():
                idxs[label].append(tid)
                vals[label].append(value)
        return idxs, vals

    def __call__(self, new_ids):
        idxs, vals = self.sample_batch(new_ids)
        return docs_from_idxs_vals(new_ids, self.domain, self.trials, idxs, vals)


def suggest(new_ids, domain, trials, seed, avg_best_idx=2.0, shrink_coef=0.1):
    algo = AnnealingAlgo(
        domain, trials, seed, avg_best_idx=avg_best_idx, shrink_coef=shrink_coef
    )
    return algo(new_ids)
