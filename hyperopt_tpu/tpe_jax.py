"""TPE as one jitted XLA program -- the TPU-native suggest path.

The north-star deliverable (BASELINE.json): ``algo=tpe_jax.suggest`` is a
drop-in replacement for ``tpe.suggest`` at the same plugin boundary, but
the entire suggest step -- good/bad split, adaptive-Parzen fits for every
hyperparameter, thousands of truncated-GMM candidate draws, EI
log-likelihood-ratio scoring, factorized argmax, and conditional activity
-- is a single compiled program over dense masked buffers
(:mod:`hyperopt_tpu.ops.kernels`).  ``vmap`` runs all dimensions and all
requested trials in parallel; there is no per-hyperparameter Python loop
(contrast SURVEY.md SS3.2's interpreted ``rec_eval`` walk).

Defaults match the parity path except the candidate counts, which are
per-FAMILY (measured, BASELINE.md 24-vs-128 study): continuous dims rise
from the reference's 24 to ``n_EI_candidates=128`` (the vectorized sweep
is free on an accelerator and the continuous llr landscape rewards more
draws -- hartmann6/branin improve), while categorical dims keep
``n_EI_candidates_cat=24`` (their EI argmax saturates once draws cover
every option, so large counts are pure argmax exploitation; the
reference's 24 preserves draw-randomness exploration and wins on every
categorical-bearing config).  ``n_EI_candidates=24`` alone is therefore
reference-exact behavior for every dim family.
"""

from __future__ import annotations

import functools
import logging

from .rand import docs_from_idxs_vals
from .jax_trials import cached_suggest_fn, host_key, obs_buffer_for, packed_space_for
from .vectorize import dense_to_idxs_vals

logger = logging.getLogger(__name__)

__all__ = ["suggest", "suggest_batch", "suggest_dense", "build_suggest_fn"]

_default_prior_weight = 1.0
_default_n_EI_candidates = 128
# categorical dims keep the reference's 24: their EI argmax saturates once
# draws cover every option, so large counts are pure exploitation while 24
# preserves draw-randomness exploration (measured -- BASELINE.md NAS table
# and the 24-vs-128 study rows; continuous dims DO improve at 128)
_default_n_EI_candidates_cat = 24
_default_gamma = 0.25
_default_n_startup_jobs = 20
_default_linear_forgetting = 25


def _resolve_above_cap(above_cap):
    """Resolve the above-model compaction knob shared by every suggest
    builder: ``None`` -> the framework default
    (:data:`hyperopt_tpu.ops.kernels.DEFAULT_ABOVE_CAP`), ``0`` (or any
    non-positive value) -> disabled (full-width scoring), an int -> that
    cap.  Returns the host int handed to ``fit_all_dims`` (None when
    disabled)."""
    if above_cap is None:
        from .ops import kernels as K

        return int(K.DEFAULT_ABOVE_CAP)
    cap = int(above_cap)
    return cap if cap > 0 else None


def build_suggest_fn(ps, n_cand, gamma, lf, prior_weight, joint_ei=False,
                     n_cand_cat=None, above_cap=None, state_io=False,
                     raw=False):
    """Compile the full TPE suggest step for a PackedSpace.

    Returns jitted ``fn(key, values, active, losses, valid, batch) ->
    (new_values [D, B], new_active [D, B])`` with ``batch`` static.
    Buffer capacity is baked into the trace via the array shapes
    (power-of-2 bucketed by ObsBuffer -> bounded recompiles).

    ``raw=True`` returns the UNJITTED closure instead (same signature,
    ``batch`` an ordinary positional) -- the seam the study-batched
    service engine (:mod:`hyperopt_tpu.serve.batched`) uses to ``vmap``
    the very same per-study suggest body over a leading study axis:
    wrapping the identical closure is what makes the batched per-study
    math bitwise-equal to this builder's solo programs.

    ``state_io=True`` returns the FUSED tell+ask variant instead:
    ``fn(key, values, active, losses, valid, vcol, acol, loss, idx,
    batch) -> (values', active', losses', valid', new_values,
    new_active)`` -- one dispatch applies a staged O(D) observation
    delta (:func:`ops.kernels.apply_delta`) to the DONATED state
    buffers AND draws the next suggestion from the updated posterior,
    halving the sequential driver's round trips.  The suggest body is
    the same closure either way, so at equal state the two variants'
    suggestion streams are bitwise identical (the delta write is pure
    data movement); see :func:`_state_dispatch` for the driver that
    pairs this with :meth:`ObsBuffer.take_fusable_delta`.

    ``n_cand_cat`` sets a separate candidate count for categorical-family
    dims (None = same as ``n_cand``).  Rationale (measured, BASELINE.md
    NAS table): the categorical EI argmax saturates once draws cover all
    K options, so large counts are pure exploitation there while the
    reference's 24 preserves draw-randomness exploration; continuous
    dims, whose llr landscape is continuous, do benefit from more.
    Ignored under ``joint_ei`` (joint scoring needs one S across dims).

    ``above_cap`` (None = :data:`ops.kernels.DEFAULT_ABOVE_CAP`, 0 =
    disabled) caps the ABOVE Parzen model at a fixed component width
    (:func:`ops.kernels.compact_gmm`): the above model is the only fit
    whose width tracks the observation count, so full-width scoring is
    the linear term that collapsed suggest throughput ~28x between 500
    and 10k observations (BASELINE.md 10k-soak row).  Below the cap the
    compaction is the identity and the suggestion stream is bitwise
    unchanged; above it, merged near-duplicate components approximate
    the same density at O(above_cap) scoring cost.

    ``joint_ei=False`` (default) keeps the reference's factorized
    posterior: each hyperparameter's EI argmax is taken independently
    (SURVEY.md SS3.2e -- parity behavior).  ``joint_ei=True`` scores
    whole candidate *configurations* instead: candidate s of a trial is
    the s-th draw of every dimension together; its score is the sum of
    per-dim log-likelihood ratios over the dims *active* in that
    configuration (conditional branches contribute only when taken), and
    the trial takes the argmax configuration column.  Affordable only
    because the accelerator path draws hundreds of candidates per dim
    (SURVEY.md SS7 'hard parts': joint variant behind a flag).

    VERDICT on when to enable joint_ei (measured, round-2 battery, 5
    seeds -- see BASELINE.md): never for quality.  Candidates are drawn
    from the same factorized marginals either way and the acquisition is
    additive, so the factorized per-dim argmax dominates the single-
    column joint argmax by construction; measured medians agree
    (corr_sum ~tie; rosenbrock2/gauss_wave2 factorized wins).  The flag
    stays for its structural property -- the returned configuration is a
    single coherent draw (one column), which some analyses of
    conditional spaces want -- not as an optimizer upgrade.  Default
    OFF, matching reference parity.
    """
    import jax
    import jax.numpy as jnp

    from .ops import kernels as K

    K.check_prior_weight(prior_weight)
    c = ps._consts
    D = ps.n_dims
    Dc = len(ps.cont_idx)
    Dk = len(ps.cat_idx)
    gamma = float(gamma)
    lf_f = float(lf)
    pw = float(prior_weight)
    n_cat = int(n_cand) if n_cand_cat is None else max(1, int(n_cand_cat))
    a_cap = _resolve_above_cap(above_cap)

    def fn_factorized(key, values, active, losses, valid, batch):
        fits = K.fit_all_dims(c, values, active, losses, valid, gamma, lf_f,
                              pw, above_cap=a_cap)
        new_values = jnp.zeros((D, batch), dtype=jnp.float32)

        n_keys = batch * (Dc + Dk)
        keys = jax.random.split(key, max(n_keys, 1))

        if fits["cont"] is not None:
            cont_keys = keys[: batch * Dc].reshape(batch, Dc)
            cont_vals, _ = K.ei_sweep_cont(
                ps.q, c, cont_keys, fits["cont"], n_cand
            )  # scores unused here; XLA dead-code-eliminates them
            new_values = new_values.at[c["cont_idx"]].set(cont_vals.T)

        if fits["cat"] is not None:
            pb, pa = fits["cat"]
            cat_keys = keys[batch * Dc: batch * (Dc + Dk)].reshape(batch, Dk)
            cat_vals, _ = K.ei_sweep_cat(cat_keys, pb, pa, n_cat)
            new_values = new_values.at[c["cat_idx"]].set(
                cat_vals.T + c["int_low"][:, None]
            )

        return new_values, ps.active_fn(new_values)

    def fn_joint(key, values, active, losses, valid, batch):
        fits = K.fit_all_dims(c, values, active, losses, valid, gamma, lf_f,
                              pw, above_cap=a_cap)
        n_keys = batch * (Dc + Dk)
        keys = jax.random.split(key, max(n_keys, 1))

        cand_values = jnp.zeros((batch, D, n_cand), dtype=jnp.float32)
        llrs = jnp.zeros((batch, D, n_cand), dtype=jnp.float32)
        if fits["cont"] is not None:
            cont_keys = keys[: batch * Dc].reshape(batch, Dc)
            v, l = K.ei_sweep_cont_scores(
                ps.q, c, cont_keys, fits["cont"], n_cand
            )
            cand_values = cand_values.at[:, c["cont_idx"]].set(v)
            llrs = llrs.at[:, c["cont_idx"]].set(l)
        if fits["cat"] is not None:
            pb, pa = fits["cat"]
            cat_keys = keys[batch * Dc: batch * (Dc + Dk)].reshape(batch, Dk)
            v, l = K.ei_sweep_cat_scores(cat_keys, pb, pa, n_cand)
            cand_values = cand_values.at[:, c["cat_idx"]].set(
                v + c["int_low"][None, :, None]
            )
            llrs = llrs.at[:, c["cat_idx"]].set(l)

        # configuration s = column s of every dim; only dims active in
        # that configuration contribute to its joint score
        flat = jnp.moveaxis(cand_values, 0, 1).reshape(D, batch * n_cand)
        cand_active = ps.active_fn(flat).reshape(D, batch, n_cand)
        cand_active = jnp.moveaxis(cand_active, 0, 1)  # [B, D, S]
        joint = jnp.sum(jnp.where(cand_active, llrs, 0.0), axis=1)  # [B, S]
        best = jnp.argmax(joint, axis=1)  # [B]
        new_values = jnp.take_along_axis(
            cand_values, best[:, None, None], axis=2
        )[..., 0].T  # [D, B]
        return new_values, ps.active_fn(new_values)

    fn = fn_joint if joint_ei else fn_factorized
    if not state_io:
        if raw:
            return fn
        return jax.jit(fn, static_argnames=("batch",))

    def fused(key, values, active, losses, valid, vcol, acol, loss, idx,
              batch):
        state = K.apply_delta(
            values, active, losses, valid, vcol, acol, loss, idx
        )
        new_values, new_active = fn(key, *state, batch)
        return tuple(state) + (new_values, new_active)

    if raw:
        return fused
    return jax.jit(
        fused, static_argnames=("batch",), donate_argnums=(1, 2, 3, 4)
    )


def _cast_vals(ps, idxs, vals):
    """Dense float draws -> API types (ints for categorical-family dims)."""
    cat_labels = {ps.labels[d] for d in ps.cat_idx}
    for label in vals:
        if label in cat_labels:
            vals[label] = [int(round(v)) for v in vals[label]]
        else:
            vals[label] = [float(v) for v in vals[label]]
    return idxs, vals


def _state_dispatch(buf, key, batch, pow2_cap, plain_fn, fused_fn):
    """Serve one dense draw over ``buf`` in ONE device dispatch whenever
    the state allows it -- the shared engine of every resident suggest
    path (TPE here, :mod:`hyperopt_tpu.anneal_jax`, and the speculative
    k-wide redraws, which all route their warm draws through it).

    With a resident buffer holding exactly one staged tell at an
    unchanged bucket, the fused ``state_io`` program applies the delta
    and draws the suggestion in a single dispatch (the buffer's mirror
    is swapped for the program's state outputs -- the old buffers were
    donated).  Otherwise -- non-resident buffer, cold mirror, bucket
    growth, or a multi-tell backlog -- the staged deltas (or a full
    upload, on the log schedule) flow through :meth:`ObsBuffer.
    device_arrays` and the plain program draws from the settled state.
    Both legs run the same suggest closure on bitwise-equal state, so
    the suggestion stream does not depend on which leg served an ask.

    Returns DEVICE (values, active) -- no host fetch, so callers that
    pre-dispatch (the ask-ahead hook) stay non-blocking.
    """
    if fused_fn is not None:
        fusable = buf.take_fusable_delta(pow2_cap)
        if fusable is not None:
            state, delta = fusable
            out = fused_fn(key, *state, *delta, batch=batch)
            buf.commit_resident(out[:4])
            buf.dispatch_count += 1
            return out[4], out[5]
    arrays = buf.device_arrays(pow2_cap=pow2_cap)
    buf.dispatch_count += 1
    return plain_fn(key, *arrays, batch=batch)


def _tpe_builder(ps_, nc, g, lf, pw, je, ncc, ac, sio):
    return build_suggest_fn(
        ps_, nc, g, lf, pw, joint_ei=je, n_cand_cat=ncc,
        above_cap=0 if ac is None else ac, state_io=sio,
    )


def _dense_dispatch(
    domain,
    trials,
    seed,
    batch,
    prior_weight=_default_prior_weight,
    n_startup_jobs=_default_n_startup_jobs,
    n_EI_candidates=_default_n_EI_candidates,
    gamma=_default_gamma,
    linear_forgetting=_default_linear_forgetting,
    joint_ei=False,
    n_EI_candidates_cat=_default_n_EI_candidates_cat,
    above_cap=None,
):
    """Device half of :func:`suggest_dense`: returns DEVICE (values,
    active) without blocking on the result -- the ask-ahead hook calls
    this to enqueue the next dispatch behind the objective evaluation."""
    ps = packed_space_for(domain)
    buf = obs_buffer_for(domain, trials)
    key = host_key(int(seed) % (2**31 - 1))

    if buf.count < n_startup_jobs:
        buf.dispatch_count += 1
        return ps.sample_prior(key, batch)

    n_cat = (
        None if n_EI_candidates_cat is None else int(n_EI_candidates_cat)
    )
    a_cap = _resolve_above_cap(above_cap)
    params = (
        int(n_EI_candidates), float(gamma), float(linear_forgetting),
        float(prior_weight), bool(joint_ei), n_cat, a_cap,
    )
    fn = cached_suggest_fn(
        domain, "_tpe_jax_cache", params + (False,), _tpe_builder
    )
    fused = (
        cached_suggest_fn(
            domain, "_tpe_jax_cache", params + (True,), _tpe_builder
        )
        if buf.resident
        else None
    )
    # with compaction active the scoring width is static, so the
    # device view stops pow2 re-bucketing past the cap (fewer
    # retraces; only the cheap fit pays the coarser padding)
    return _state_dispatch(buf, key, batch, a_cap, fn, fused)


def suggest_dense(
    domain,
    trials,
    seed,
    batch,
    prior_weight=_default_prior_weight,
    n_startup_jobs=_default_n_startup_jobs,
    n_EI_candidates=_default_n_EI_candidates,
    gamma=_default_gamma,
    linear_forgetting=_default_linear_forgetting,
    joint_ei=False,
    n_EI_candidates_cat=_default_n_EI_candidates_cat,
    above_cap=None,
):
    """Dense draws for a batch: (values [D, batch], active [D, batch]) as
    host numpy -- one device program (prior during startup, TPE after).
    The shared engine under :func:`suggest_batch` and adaptive variants
    (:mod:`hyperopt_tpu.atpe_jax`).  Over a resident buffer
    (``ObsBuffer.resident`` / ``JaxTrials(resident=True)``) the warm
    draw is the state-in/state-out path of :func:`_state_dispatch`:
    staged tells ride along as O(D) deltas -- fused into the very same
    dispatch when exactly one is pending -- instead of re-uploading the
    bucketed history."""
    import jax

    return jax.device_get(_dense_dispatch(
        domain, trials, seed, batch,
        prior_weight=prior_weight,
        n_startup_jobs=n_startup_jobs,
        n_EI_candidates=n_EI_candidates,
        gamma=gamma,
        linear_forgetting=linear_forgetting,
        joint_ei=joint_ei,
        n_EI_candidates_cat=n_EI_candidates_cat,
        above_cap=above_cap,
    ))


def suggest_batch(
    new_ids,
    domain,
    trials,
    seed,
    prior_weight=_default_prior_weight,
    n_startup_jobs=_default_n_startup_jobs,
    n_EI_candidates=_default_n_EI_candidates,
    gamma=_default_gamma,
    linear_forgetting=_default_linear_forgetting,
    joint_ei=False,
    n_EI_candidates_cat=_default_n_EI_candidates_cat,
    above_cap=None,
):
    """Sparse (idxs, vals) for a batch of ids -- one device program for the
    whole batch (B trials x D dims x n_EI_candidates candidates)."""
    ps = packed_space_for(domain)
    values, active = suggest_dense(
        domain, trials, seed, len(new_ids),
        prior_weight=prior_weight,
        n_startup_jobs=n_startup_jobs,
        n_EI_candidates=n_EI_candidates,
        gamma=gamma,
        linear_forgetting=linear_forgetting,
        joint_ei=joint_ei,
        n_EI_candidates_cat=n_EI_candidates_cat,
        above_cap=above_cap,
    )
    idxs, vals = dense_to_idxs_vals(new_ids, ps.labels, values, active)
    return _cast_vals(ps, idxs, vals)


def _saturated_categorical(ps, n_cat_total):
    """True when the k columns of a speculative draw would be near-
    duplicates: every dim is categorical-family AND the candidate draw
    covers every option (n >= k_max), so the per-dim EI argmax is
    deterministic given one posterior (measured -- BASELINE.md NAS
    speculative row: median 8.11 vs 6.28 without).  Machine-detectable
    at build time; callers auto-degrade to ``speculative=0`` with a
    warning instead of relying on users reading docstrings."""
    return len(ps.cont_idx) == 0 and int(n_cat_total) >= int(ps.k_max)


def _warn_saturated(domain, k, advice=None):
    import warnings

    if getattr(domain, "_spec_saturation_warned", False):
        return
    domain._spec_saturation_warned = True
    if advice is None:
        advice = (
            "to keep speculation here, lower the categorical candidate "
            "count below the largest option count (draw randomness is "
            "the exploration mechanism on saturated categorical spaces)."
        )
    warnings.warn(
        f"speculative={k} disabled: every dimension of this space is "
        "categorical and the candidate draw covers every option, so the "
        "EI argmax is deterministic and the k speculative columns would "
        "be near-duplicate suggestions evaluated k times (measured "
        "quality loss -- see BASELINE.md NAS speculative row). Falling "
        "back to one dispatch per ask; " + advice,
        stacklevel=3,
    )


def _speculative_cols(domain, trials, seed, k, max_stale, params,
                      n_startup_jobs, draw_fn):
    """Serve one [D, 1] column from a k-wide speculative draw.

    One device dispatch (``draw_fn(seed, k) -> (values, active)`` host
    numpy) draws ``k`` suggestion columns; follow-up calls pop cached
    columns for free until either the cache drains or the posterior has
    moved by more than ``max_stale`` completed-ok observations since the
    draw (then a fresh k-wide dispatch).  With ``max_stale = k - 1``
    this is exactly the posterior-staleness profile of the reference's
    ``fmin(max_queue_len=k)`` batching -- the accepted ask-k-ahead trade
    -- served through the per-trial API.  Staleness is measured in
    posterior-relevant observations (``ObsBuffer.count``), so failed/NaN
    trials, which never enter the posterior, do not burn the cache.
    Shared by :func:`suggest` and the mesh-sharded
    :func:`hyperopt_tpu.parallel.sharded.sharded_suggest`.
    """
    import weakref

    if max_stale is None:
        max_stale = int(k) - 1
    if max_stale < 2**61:
        buf_count = obs_buffer_for(domain, trials).count  # syncs trials
        warm = buf_count >= n_startup_jobs  # regime decided HERE, once
    else:
        # prior-only callers (rand_jax) pass an effectively infinite
        # staleness budget: their draws never depend on observations,
        # so skip the per-ask posterior-mirror maintenance entirely
        buf_count = 0
        warm = True
    cache = getattr(domain, "_tpe_spec_draws", None)
    if cache is None:
        cache = {}
        domain._tpe_spec_draws = cache
    entry = cache.get(params)
    if entry is not None:
        stale = buf_count - entry["count_at_draw"]
        if (
            entry["trials_ref"]() is trials  # id() may alias after GC
            and 0 <= stale <= max_stale
            and entry["warm"] == warm  # startup<->TPE regime flip invalidates
            and entry["next"] < entry["values"].shape[1]
        ):
            i = entry["next"]
            entry["next"] = i + 1
            return entry["values"][:, i: i + 1], entry["active"][:, i: i + 1]
    values, active = draw_fn(seed, k)
    cache[params] = {
        "trials_ref": weakref.ref(trials),
        "count_at_draw": buf_count,
        "warm": warm,
        "next": 1,
        "values": values,
        "active": active,
    }
    return values[:, :1], active[:, :1]


def _kw_key(kw):
    """Hashable identity of a suggest-kwarg dict (ask-ahead matching)."""
    return tuple(sorted((k, v) for k, v in kw.items()))


def _ask_ahead_state(domain):
    st = getattr(domain, "_ask_ahead_state", None)
    if st is None:
        st = {"pending": None, "hook_key": None}
        domain._ask_ahead_state = st
    return st


def _install_ask_ahead(domain, kw):
    """Register the sequential driver's result hook (idempotent per kw).

    The hook is the ask-ahead half of the fused driver: the driver
    (``FMinIter.serial_evaluate``) calls it right after recording a
    loss, passing the seed it will hand the NEXT ask (pre-drawn from
    the same rstate stream, so seed order -- and therefore the
    suggestion stream -- is identical to the un-hooked driver).  The
    hook enqueues the fused tell+ask dispatch WITHOUT fetching, so the
    device round trip overlaps the driver's host-side bookkeeping (and,
    with a queue, the remaining objective evaluations); the next
    ``suggest(fused=True)`` call recognizes the pending draw and only
    then blocks on it.
    """
    st = _ask_ahead_state(domain)
    key = _kw_key(kw)
    if st["hook_key"] == key and getattr(domain, "_ask_ahead_hook", None):
        return
    import weakref

    def hook(trials, seed):
        out = _dense_dispatch(domain, trials, int(seed), 1, **kw)
        st["pending"] = {
            "seed": int(seed),
            "trials_ref": weakref.ref(trials),
            "count": obs_buffer_for(domain, trials).count,
            "kw_key": key,
            "out": out,
        }

    domain._ask_ahead_hook = hook
    st["hook_key"] = key


def _fused_ask(domain, trials, seed, kw, ask_ahead):
    """One sequential ask through the fused driver: consume a matching
    pre-dispatched suggestion if the ask-ahead hook staged one, else
    dispatch now (fused with the pending tell when possible)."""
    import jax

    if ask_ahead:
        _install_ask_ahead(domain, kw)
    st = _ask_ahead_state(domain)
    pending, st["pending"] = st["pending"], None
    buf = obs_buffer_for(domain, trials, resident=True)
    if (
        pending is not None
        and pending["seed"] == int(seed)
        and pending["trials_ref"]() is trials
        and pending["kw_key"] == _kw_key(kw)
        and pending["count"] == buf.count
    ):
        # the tell inside the pre-dispatch is already committed; only
        # the suggestion is fetched here (blocking at last possible
        # moment -- the dispatch has been in flight since the result
        # was recorded)
        return jax.device_get(pending["out"])
    # no (matching) pre-dispatch: the staleness guards above dropped a
    # draw whose posterior or key no longer applies -- its tell stays
    # committed, only the ask re-runs
    return jax.device_get(_dense_dispatch(domain, trials, seed, 1, **kw))


def suggest(
    new_ids,
    domain,
    trials,
    seed,
    prior_weight=_default_prior_weight,
    n_startup_jobs=_default_n_startup_jobs,
    n_EI_candidates=_default_n_EI_candidates,
    gamma=_default_gamma,
    linear_forgetting=_default_linear_forgetting,
    joint_ei=False,
    n_EI_candidates_cat=_default_n_EI_candidates_cat,
    speculative=0,
    max_stale=None,
    above_cap=None,
    fused=False,
    resident=None,
    ask_ahead=None,
):
    """The TPU plugin-boundary entry point: ``algo=tpe_jax.suggest``.

    ``partial(tpe_jax.suggest, joint_ei=True)`` switches from the
    reference's factorized per-dimension EI argmax to whole-configuration
    scoring (see :func:`build_suggest_fn`).

    ``partial(tpe_jax.suggest, speculative=k)`` amortizes the per-trial
    device dispatch for sequential (one-ask-at-a-time) drivers: each
    dispatch draws ``k`` suggestions and serves the next ``k-1`` asks
    from cache while the posterior is at most ``max_stale`` (default
    ``k-1``) observations stale -- the quality profile of the reference's
    ``max_queue_len=k`` with the latency profile of one dispatch per
    ``k`` trials.  ``speculative=0`` (default) keeps exact one-dispatch-
    per-ask parity behavior.

    Guard (measured, BASELINE.md): on SMALL pure-categorical spaces the
    per-dim EI argmax saturates once the candidate draw covers every
    option, so the k columns of a speculative draw are near-duplicates
    evaluated k times (NAS-Bench median 8.11 vs 6.28 without).  The
    regime is detected at build time (every dim categorical-family and
    the categorical candidate count >= the largest option count) and
    speculation AUTO-DEGRADES to one dispatch per ask with a one-time
    warning -- the trap cannot be hit silently.  To keep speculation on
    such a space, lower the categorical candidate count below the
    option count (draw randomness is the exploration mechanism there).

    ``resident=True`` makes the observation mirror device-resident
    (O(D) delta tells instead of O(n_obs*D) re-uploads -- see
    :class:`~hyperopt_tpu.jax_trials.ObsBuffer`); the suggestion stream
    is bitwise identical to the re-upload path.  ``fused=True``
    (implies ``resident``) additionally serves sequential asks through
    the fused tell+ask program -- ONE dispatch per trial, with fresh
    (zero-staleness) posteriors, unlike ``speculative=k`` -- and, under
    ``fmin``'s sequential driver, pre-dispatches each ask the moment
    the previous result is recorded (``ask_ahead``, default on with
    ``fused``), hiding the device round trip behind the driver's host
    work.  ``speculative=k`` composes with ``resident`` (the k-wide
    redraw rides the same delta/fused state engine) and keeps its own
    staleness semantics; the auto-degrade guard above is build-time
    space logic and behaves identically on resident state.

    COMPATIBILITY STATUS (round 20, graftclient): the solo fused /
    speculative dispatch modes above are maintained as the parity
    reference, not the production path -- ``fmin(engine=True)`` /
    ``fmin(ask_ahead=k)`` routes this same suggest body through the
    serve engine (one fused dispatch per trial at batch 1, bitwise
    this driver's stream at any depth, plus WAL durability, admission
    control, and tracing).  The ``state_io`` builder stays load-
    bearing either way: it IS the per-slot closure the serve engine
    vmaps (DESIGN.md §3b).
    """
    kw = dict(
        prior_weight=prior_weight,
        n_startup_jobs=n_startup_jobs,
        n_EI_candidates=n_EI_candidates,
        gamma=gamma,
        linear_forgetting=linear_forgetting,
        joint_ei=joint_ei,
        n_EI_candidates_cat=n_EI_candidates_cat,
        above_cap=above_cap,
    )
    if fused and resident is None:
        resident = True
    if resident is not None:
        obs_buffer_for(domain, trials, resident=bool(resident))
    if fused and not speculative and len(new_ids) == 1:
        ps = packed_space_for(domain)
        values, active = _fused_ask(
            domain, trials, seed, kw,
            ask_ahead=True if ask_ahead is None else bool(ask_ahead),
        )
        idxs, vals = dense_to_idxs_vals(new_ids, ps.labels, values, active)
        idxs, vals = _cast_vals(ps, idxs, vals)
        return docs_from_idxs_vals(new_ids, domain, trials, idxs, vals)
    if speculative and len(new_ids) == 1:
        ps = packed_space_for(domain)
        n_cat_eff = (
            n_EI_candidates
            if n_EI_candidates_cat is None
            else n_EI_candidates_cat
        )
        if _saturated_categorical(ps, n_cat_eff):
            _warn_saturated(domain, speculative)
            return docs_from_idxs_vals(
                new_ids, domain, trials,
                *suggest_batch(new_ids, domain, trials, seed, **kw),
            )
        # key includes every regime-determining knob plus the trials-store
        # identity: one Domain shared across stores or differently-
        # configured partials must never serve each other's columns
        params = (
            int(n_EI_candidates), float(gamma), float(linear_forgetting),
            float(prior_weight), bool(joint_ei), int(speculative),
            int(n_startup_jobs), id(trials),
            None if n_EI_candidates_cat is None else int(n_EI_candidates_cat),
            # the RESOLVED staleness budget: partials differing only in
            # max_stale must not pop each other's cached columns
            int(speculative) - 1 if max_stale is None else int(max_stale),
            # resolved compaction cap: different caps trace different
            # programs, so their columns must never be served across
            _resolve_above_cap(above_cap),
        )
        values, active = _speculative_cols(
            domain, trials, seed, int(speculative), max_stale, params,
            n_startup_jobs,
            lambda s, k: suggest_dense(domain, trials, s, k, **kw),
        )
        idxs, vals = dense_to_idxs_vals(new_ids, ps.labels, values, active)
        idxs, vals = _cast_vals(ps, idxs, vals)
    else:
        idxs, vals = suggest_batch(new_ids, domain, trials, seed, **kw)
    return docs_from_idxs_vals(new_ids, domain, trials, idxs, vals)


# ---------------------------------------------------------------------------
# graftir registrations (hyperopt-tpu-lint --ir): TPE's program families
# ---------------------------------------------------------------------------

from .ops.compile import ProgramCapture, register_program  # noqa: E402

_TPE_FAMILIES = ("hyperopt_tpu.tpe_jax:build_suggest_fn",)


def _registry_build(ps, n_cand, state_io=False):
    _ = ps._consts
    return build_suggest_fn(
        ps, n_cand, _default_gamma, _default_linear_forgetting,
        _default_prior_weight, n_cand_cat=_default_n_EI_candidates_cat,
        state_io=state_io,
    )


@register_program("tpe_jax.suggest", families=_TPE_FAMILIES)
def _registry_tpe_suggest(p):
    """The plain batched ask: one dispatch draws ``batch`` suggestions
    from the settled history (``suggest_batch`` / ``suggest_dense``)."""
    fn = _registry_build(p.space, _default_n_EI_candidates)
    return ProgramCapture(
        fn=fn, args=(p.key_spec(),) + p.history_specs(),
        kwargs={"batch": p.batch},
    )


@register_program("tpe_jax.fused_tell_ask", families=_TPE_FAMILIES)
def _registry_tpe_fused(p):
    """The ``state_io=True`` fused tell+ask program of the sequential
    driver (one dispatch per trial, donated state buffers -- PR 4's
    whole perf story rides on what is, and is not, inside this one)."""
    fn = _registry_build(p.space, _default_n_EI_candidates, state_io=True)
    return ProgramCapture(
        fn=fn,
        args=(p.key_spec(),) + p.history_specs() + p.delta_specs(),
        kwargs={"batch": 1},
        donate_argnums=(1, 2, 3, 4),
    )


@register_program("tpe_jax.speculative_redraw", families=_TPE_FAMILIES)
def _registry_tpe_speculative(p):
    """The k-wide speculative draw (``suggest(speculative=k)``): the same
    suggest family at ``batch=k`` -- its own contract because its output
    shapes ARE the speculation cache layout ``_speculative_cols`` pops."""
    fn = _registry_build(p.space, _default_n_EI_candidates)
    return ProgramCapture(
        fn=fn, args=(p.key_spec(),) + p.history_specs(),
        kwargs={"batch": p.k_spec},
        # same closure as tpe_jax.suggest at a different static batch:
        # the family's GL402 promotion behavior is pinned there already
        x64_check=False,
    )
