"""TPE as one jitted XLA program -- the TPU-native suggest path.

The north-star deliverable (BASELINE.json): ``algo=tpe_jax.suggest`` is a
drop-in replacement for ``tpe.suggest`` at the same plugin boundary, but
the entire suggest step -- good/bad split, adaptive-Parzen fits for every
hyperparameter, thousands of truncated-GMM candidate draws, EI
log-likelihood-ratio scoring, factorized argmax, and conditional activity
-- is a single compiled program over dense masked buffers
(:mod:`hyperopt_tpu.ops.kernels`).  ``vmap`` runs all dimensions and all
requested trials in parallel; there is no per-hyperparameter Python loop
(contrast SURVEY.md SS3.2's interpreted ``rec_eval`` walk).

Defaults match the parity path except ``n_EI_candidates``: with the
candidate sweep vectorized on an accelerator, the default rises from the
reference's 24 to 128 (SURVEY.md SS7 stance #2 -- 'thousands of EI
candidates per step' are affordable; pass ``n_EI_candidates=24`` for
reference-exact behavior).
"""

from __future__ import annotations

import functools
import logging

import numpy as np

from .rand import docs_from_idxs_vals
from .jax_trials import cached_suggest_fn, obs_buffer_for, packed_space_for
from .vectorize import dense_to_idxs_vals

logger = logging.getLogger(__name__)

__all__ = ["suggest", "suggest_batch", "build_suggest_fn"]

_default_prior_weight = 1.0
_default_n_EI_candidates = 128
_default_gamma = 0.25
_default_n_startup_jobs = 20
_default_linear_forgetting = 25


def build_suggest_fn(ps, n_cand, gamma, lf, prior_weight):
    """Compile the full TPE suggest step for a PackedSpace.

    Returns jitted ``fn(key, values, active, losses, valid, batch) ->
    (new_values [D, B], new_active [D, B])`` with ``batch`` static.
    Buffer capacity is baked into the trace via the array shapes
    (power-of-2 bucketed by ObsBuffer -> bounded recompiles).
    """
    import jax
    import jax.numpy as jnp

    from .ops import kernels as K

    c = ps._consts
    D = ps.n_dims
    Dc = len(ps.cont_idx)
    Dk = len(ps.cat_idx)
    gamma = float(gamma)
    lf_f = float(lf)
    pw = float(prior_weight)

    def fn(key, values, active, losses, valid, batch):
        fits = K.fit_all_dims(c, values, active, losses, valid, gamma, lf_f, pw)
        new_values = jnp.zeros((D, batch), dtype=jnp.float32)

        n_keys = batch * (Dc + Dk)
        keys = jax.random.split(key, max(n_keys, 1))

        if fits["cont"] is not None:
            cont_keys = keys[: batch * Dc].reshape(batch, Dc)
            cont_vals, _ = K.ei_sweep_cont(
                ps.q, c, cont_keys, fits["cont"], n_cand
            )  # scores unused here; XLA dead-code-eliminates them
            new_values = new_values.at[c["cont_idx"]].set(cont_vals.T)

        if fits["cat"] is not None:
            pb, pa = fits["cat"]
            cat_keys = keys[batch * Dc: batch * (Dc + Dk)].reshape(batch, Dk)
            cat_vals, _ = K.ei_sweep_cat(cat_keys, pb, pa, n_cand)
            new_values = new_values.at[c["cat_idx"]].set(
                cat_vals.T + c["int_low"][:, None]
            )

        return new_values, ps.active_fn(new_values)

    return jax.jit(fn, static_argnames=("batch",))


def _cast_vals(ps, idxs, vals):
    """Dense float draws -> API types (ints for categorical-family dims)."""
    cat_labels = {ps.labels[d] for d in ps.cat_idx}
    for label in vals:
        if label in cat_labels:
            vals[label] = [int(round(v)) for v in vals[label]]
        else:
            vals[label] = [float(v) for v in vals[label]]
    return idxs, vals


def suggest_batch(
    new_ids,
    domain,
    trials,
    seed,
    prior_weight=_default_prior_weight,
    n_startup_jobs=_default_n_startup_jobs,
    n_EI_candidates=_default_n_EI_candidates,
    gamma=_default_gamma,
    linear_forgetting=_default_linear_forgetting,
):
    """Sparse (idxs, vals) for a batch of ids -- one device program for the
    whole batch (B trials x D dims x n_EI_candidates candidates)."""
    import jax

    ps = packed_space_for(domain)
    buf = obs_buffer_for(domain, trials)
    B = len(new_ids)
    key = jax.random.key(int(seed) % (2**31 - 1))

    if buf.count < n_startup_jobs:
        values, active = ps.sample_prior(key, B)
    else:
        fn = cached_suggest_fn(
            domain, "_tpe_jax_cache",
            (int(n_EI_candidates), float(gamma), float(linear_forgetting),
             float(prior_weight)),
            build_suggest_fn,
        )
        values, active = fn(key, *buf.device_arrays(), batch=B)

    idxs, vals = dense_to_idxs_vals(
        new_ids, ps.labels, np.asarray(values), np.asarray(active)
    )
    return _cast_vals(ps, idxs, vals)


def suggest(
    new_ids,
    domain,
    trials,
    seed,
    prior_weight=_default_prior_weight,
    n_startup_jobs=_default_n_startup_jobs,
    n_EI_candidates=_default_n_EI_candidates,
    gamma=_default_gamma,
    linear_forgetting=_default_linear_forgetting,
):
    """The TPU plugin-boundary entry point: ``algo=tpe_jax.suggest``."""
    idxs, vals = suggest_batch(
        new_ids, domain, trials, seed,
        prior_weight=prior_weight,
        n_startup_jobs=n_startup_jobs,
        n_EI_candidates=n_EI_candidates,
        gamma=gamma,
        linear_forgetting=linear_forgetting,
    )
    return docs_from_idxs_vals(new_ids, domain, trials, idxs, vals)
