"""Mesh construction helpers.

One place decides how devices are arranged; everything else takes a Mesh.
Axis conventions:
  ``cand``  -- candidate-batch sharding (the throughput axis; rides ICI)
  ``trial`` -- trial-batch sharding for population evaluation (data-ish)
  ``study`` -- study-slot sharding for the serve engine (graftmesh):
              the stacked :class:`~hyperopt_tpu.serve.batched.
              StudyBatchState` splits its slot axis over this axis, so
              slot capacity multiplies with device count
"""

from __future__ import annotations

import os
import sys

import numpy as np

__all__ = [
    "default_mesh",
    "device_count",
    "force_host_cpu_devices",
    "mesh_from_spec",
    "registry_cpu_mesh",
    "rung_submesh",
    "study_mesh",
    "subprocess_env_with_devices",
    "CAND_AXIS",
    "STUDY_AXIS",
    "TRIAL_AXIS",
]

CAND_AXIS = "cand"
TRIAL_AXIS = "trial"
STUDY_AXIS = "study"

#: study-axis width the graftir mesh-sharded program contracts are
#: pinned at (and the device count every repo entry point -- conftest,
#: bench, the lint CLI, the multichip dryrun -- forces on the virtual
#: CPU platform, so the contracts trace identically everywhere)
REGISTRY_MESH_DEVICES = 4


def device_count():
    import jax

    return jax.device_count()


def default_mesh(axis_name=CAND_AXIS, devices=None):
    """1-D mesh over all (or given) devices -- the workhorse for candidate
    sharding; a v4-8 slice becomes ``Mesh([8], ('cand',))``."""
    import jax
    from jax.sharding import Mesh

    if devices is None:
        devices = jax.devices()
    return Mesh(np.asarray(devices), (axis_name,))


def mesh_from_spec(shape, axis_names, devices=None):
    """N-D mesh, e.g. ``mesh_from_spec((2, 4), ('trial', 'cand'))`` to split
    a slice between trial-batch and candidate-batch parallelism."""
    import jax
    from jax.sharding import Mesh

    if devices is None:
        devices = jax.devices()
    n = int(np.prod(shape))
    if n > len(devices):
        raise ValueError(f"mesh shape {shape} needs {n} devices, have {len(devices)}")
    arr = np.asarray(devices[:n]).reshape(shape)
    return Mesh(arr, tuple(axis_names))


def study_mesh(n_devices=None, devices=None, axis=STUDY_AXIS):
    """1-D ``study`` mesh over the first ``n_devices`` devices -- the
    serve engine's slot-axis mesh (graftmesh).  ``n_devices=None``
    takes every visible device (the pod-scale default)."""
    import jax
    from jax.sharding import Mesh

    if devices is None:
        devices = jax.devices()
    if n_devices is not None:
        n = int(n_devices)
        if n > len(devices):
            raise ValueError(
                f"study_mesh needs {n} devices, have {len(devices)}"
            )
        devices = devices[:n]
    return Mesh(np.asarray(devices), (axis,))


def registry_cpu_mesh(n_devices=REGISTRY_MESH_DEVICES, axis=STUDY_AXIS):
    """The forced multi-device CPU mesh the graftir mesh-sharded
    program contracts are pinned over.

    Every repo entry point that traces the registry (tests/conftest.py,
    ``hyperopt-tpu-lint --ir``, bench.py, the multichip dryrun) forces
    at least :data:`REGISTRY_MESH_DEVICES` virtual CPU devices via
    :func:`force_host_cpu_devices` BEFORE jax initializes; a process
    that skipped that step gets a loud error here, never a silently
    drifted single-device contract."""
    import jax
    from jax.sharding import Mesh

    devices = jax.local_devices(backend="cpu")
    if len(devices) < int(n_devices):
        raise RuntimeError(
            f"graftir's mesh-sharded contracts trace over "
            f"{int(n_devices)} virtual CPU devices but this process has "
            f"{len(devices)}; call hyperopt_tpu.parallel.mesh."
            "force_host_cpu_devices() before jax initializes (set "
            f"XLA_FLAGS=--xla_force_host_platform_device_count="
            f"{int(n_devices)})"
        )
    return Mesh(np.asarray(devices[: int(n_devices)]), (axis,))


def rung_submesh(mesh, axis, members):
    """The gcd-sized per-rung sub-mesh of the SHA/ASHA shard_map seam.

    A rung's (shrinking) member count rarely stays divisible by the
    full mesh width, so the rung shards over the first
    ``gcd(members, mesh.shape[axis])`` devices instead -- late tiny
    rungs shrink their sub-mesh rather than breaking divisibility, and
    a 1-device sub-mesh degenerates to the unsharded program (the
    bitwise-parity anchor).  ONE definition shared by
    :func:`hyperopt_tpu.hyperband.compile_sha`'s per-rung programs and
    the compiled-ASHA device loop (:func:`hyperopt_tpu.device_loop.
    compile_fmin` with ``asha=``).  Returns ``(sub_mesh, n_devices)``.
    """
    import math

    from jax.sharding import Mesh

    k = math.gcd(int(members), int(mesh.shape[axis]))
    sub = Mesh(np.asarray(list(mesh.devices.flat)[:k]), (axis,))
    return sub, k


def force_host_cpu_devices(n=8):
    """Force >= ``n`` virtual CPU devices, BEFORE jax backend init.

    The shared harness behind every multi-device CPU entry point (the
    test fixture, the lint CLI's ``--ir`` path, bench.py): mutates
    ``XLA_FLAGS`` with ``--xla_force_host_platform_device_count=n`` so
    mesh parity tests and the mesh-sharded contract traces run without
    real multi-chip hardware.  A no-op once jax's backends are live --
    callers that may run late check the returned effective count."""
    if "jax" in sys.modules:
        # a LIVE backend latches the flag; probe without creating one
        # (jax.devices() would itself initialize under current flags)
        initialized = False
        try:
            from jax._src import xla_bridge as xb

            initialized = bool(xb._backends)
        except Exception:
            initialized = False
        if initialized:
            import jax

            try:
                return len(jax.local_devices(backend="cpu"))
            except RuntimeError:
                return 0
    flags = [
        f
        for f in os.environ.get("XLA_FLAGS", "").split()
        if "xla_force_host_platform_device_count" not in f
    ]
    flags.append(f"--xla_force_host_platform_device_count={int(n)}")
    os.environ["XLA_FLAGS"] = " ".join(flags)
    return int(n)


def subprocess_env_with_devices(n, env=None):
    """An environment dict for a subprocess pinned to the virtual CPU
    platform with exactly ``n`` devices -- the subprocess half of the
    multi-device harness (tests spawn parity checks under device
    counts the parent process does not run at)."""
    env = dict(os.environ if env is None else env)
    flags = [
        f
        for f in env.get("XLA_FLAGS", "").split()
        if "xla_force_host_platform_device_count" not in f
    ]
    flags.append(f"--xla_force_host_platform_device_count={int(n)}")
    env["XLA_FLAGS"] = " ".join(flags)
    env["JAX_PLATFORMS"] = "cpu"
    return env
