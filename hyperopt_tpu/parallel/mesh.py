"""Mesh construction helpers.

One place decides how devices are arranged; everything else takes a Mesh.
Axis conventions:
  ``cand``  -- candidate-batch sharding (the throughput axis; rides ICI)
  ``trial`` -- trial-batch sharding for population evaluation (data-ish)
"""

from __future__ import annotations

import numpy as np

__all__ = ["default_mesh", "device_count", "mesh_from_spec", "CAND_AXIS", "TRIAL_AXIS"]

CAND_AXIS = "cand"
TRIAL_AXIS = "trial"


def device_count():
    import jax

    return jax.device_count()


def default_mesh(axis_name=CAND_AXIS, devices=None):
    """1-D mesh over all (or given) devices -- the workhorse for candidate
    sharding; a v4-8 slice becomes ``Mesh([8], ('cand',))``."""
    import jax
    from jax.sharding import Mesh

    if devices is None:
        devices = jax.devices()
    return Mesh(np.asarray(devices), (axis_name,))


def mesh_from_spec(shape, axis_names, devices=None):
    """N-D mesh, e.g. ``mesh_from_spec((2, 4), ('trial', 'cand'))`` to split
    a slice between trial-batch and candidate-batch parallelism."""
    import jax
    from jax.sharding import Mesh

    if devices is None:
        devices = jax.devices()
    n = int(np.prod(shape))
    if n > len(devices):
        raise ValueError(f"mesh shape {shape} needs {n} devices, have {len(devices)}")
    arr = np.asarray(devices[:n]).reshape(shape)
    return Mesh(arr, tuple(axis_names))
