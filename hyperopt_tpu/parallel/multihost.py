"""Multi-host (DCN) coordination.

TPU-native counterpart of the reference's multi-machine execution
(SURVEY.md SS5 'distributed communication backend'): instead of a MongoDB
queue between processes, all hosts join one ``jax.distributed`` runtime;
the sharded suggest program spans every host's devices (collectives ride
ICI within a slice and DCN across slices), and suggested configs are
replicated to every host with a one-to-all broadcast so each host
evaluates its share of trials.

Single-process degenerates gracefully: ``initialize()`` is a no-op,
``broadcast_configs`` is identity, ``process_index() == 0``.
"""

from __future__ import annotations

import logging

logger = logging.getLogger(__name__)

__all__ = [
    "initialize",
    "is_multihost",
    "process_index",
    "process_count",
    "broadcast_configs",
    "fetch_global",
    "shard_ids_for_host",
]


def initialize(coordinator_address=None, num_processes=None, process_id=None):
    """Join the jax.distributed runtime (no-op when single-process or
    already initialized)."""
    import jax

    if num_processes is None or num_processes <= 1:
        return False
    try:
        jax.distributed.initialize(
            coordinator_address=coordinator_address,
            num_processes=num_processes,
            process_id=process_id,
        )
        return True
    except RuntimeError as e:  # already initialized
        logger.warning("jax.distributed.initialize: %s", e)
        return False


def is_multihost():
    import jax

    return jax.process_count() > 1


def process_index():
    import jax

    return jax.process_index()


def process_count():
    import jax

    return jax.process_count()


def broadcast_configs(values, active):
    """Replicate a suggested dense batch from process 0 to all hosts.

    Ensures every host materializes identical trial docs without a
    host-side queue (the Mongo role for config distribution).
    """
    import jax

    if jax.process_count() == 1:
        return values, active
    from jax.experimental import multihost_utils

    values = multihost_utils.broadcast_one_to_all(values)
    active = multihost_utils.broadcast_one_to_all(active)
    return values, active


def fetch_global(tree):
    """Host-fetch a pytree whose leaves may be sharded across PROCESSES.

    ``np.asarray``/``jax.device_get`` refuse arrays spanning
    non-addressable devices (a population axis sharded over a
    multi-host mesh); such leaves are assembled with
    ``multihost_utils.process_allgather`` -- every process receives the
    identical GLOBAL numpy array, so replicated host-side bookkeeping
    (best-member selection, result dicts) stays consistent across
    hosts.  Fully-addressable leaves (the single-process common case)
    take the plain ``np.asarray`` path untouched.
    """
    import jax
    import numpy as np

    def fetch(x):
        if isinstance(x, jax.Array) and not x.is_fully_addressable:
            from jax.experimental import multihost_utils

            return multihost_utils.process_allgather(x, tiled=True)
        return np.asarray(x)

    return jax.tree.map(fetch, tree)


def shard_ids_for_host(new_ids, index=None, count=None):
    """Round-robin split of a trial-id batch across hosts: each host
    evaluates ``new_ids[process_index::process_count]`` (trial-level
    farming across slices for expensive objectives)."""
    if index is None:
        index = process_index()
    if count is None:
        count = process_count()
    return list(new_ids)[index::count]
