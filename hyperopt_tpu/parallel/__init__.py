"""Device-mesh parallelism for suggest steps.

The TPU-native replacement for the reference's distributed story
(SURVEY.md SS2 'parallelism-strategy checklist' and SS5): candidate
batches shard across a ``jax.sharding.Mesh`` with ``shard_map``; the EI
argmax reduces over ICI collectives (``pmax``-style all-gather + argmax);
multi-host runs ride ``jax.distributed`` over DCN
(:mod:`hyperopt_tpu.parallel.multihost`).  Trial-level task farming (the
MongoDB role) lives in :mod:`hyperopt_tpu.distributed`.
"""

from . import multihost
from .mesh import CAND_AXIS, TRIAL_AXIS, default_mesh, device_count, mesh_from_spec
from .sharded import build_sharded_suggest_fn, sharded_suggest, suggest

__all__ = [
    "CAND_AXIS",
    "TRIAL_AXIS",
    "default_mesh",
    "device_count",
    "mesh_from_spec",
    "build_sharded_suggest_fn",
    "sharded_suggest",
    "suggest",
    "multihost",
]
