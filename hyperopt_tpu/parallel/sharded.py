"""Mesh-sharded TPE suggest: candidate batches split across devices.

The scale story of the TPU design (SURVEY.md SS5/SS7 stance #4 and the
BASELINE.json north star): Parzen fits are tiny and replicated; the
expensive part -- drawing and scoring ``n_EI_candidates`` per
hyperparameter -- shards over the ``cand`` mesh axis with ``shard_map``.
Each device draws an independent candidate slab (key folded by
``lax.axis_index``), scores it locally, and emits its local argmax; the
global EI winner is reduced over the gathered per-device bests (an
argmax-allgather over ICI).  Total candidates per dim =
``n_cand_per_device * mesh.size``.

On a single device this degenerates to exactly :mod:`hyperopt_tpu.tpe_jax`
semantics with one shard.  Multi-host: build the mesh over
``jax.devices()`` after ``jax.distributed.initialize`` (see
:mod:`hyperopt_tpu.parallel.multihost`) and the same program spans DCN.
"""

from __future__ import annotations

import functools

from ..jax_trials import cached_suggest_fn, host_key, obs_buffer_for, packed_space_for
from ..rand import docs_from_idxs_vals
from ..vectorize import dense_to_idxs_vals
from .mesh import CAND_AXIS, default_mesh

__all__ = [
    "build_sharded_suggest_fn",
    "build_sharded_sweep",
    "per_device_count",
    "sharded_suggest",
    "suggest",
]


def per_device_count(total, n_dev):
    """Per-device slab width for a TOTAL sweep width: round up, floor at
    1 -- the executed total may exceed the request by < n_dev.  THE
    single definition of the total->per-device contract, shared by every
    sharded-sweep entry point (plain, adaptive, device-loop)."""
    return max(1, -(-int(total) // int(n_dev)))


def _shard_map():
    import functools
    import inspect

    import jax

    if hasattr(jax, "shard_map"):
        sm = jax.shard_map
    else:  # pragma: no cover
        from jax.experimental.shard_map import shard_map as sm
    if "check_vma" in inspect.signature(sm).parameters:
        return sm

    # older jax spells the replication-check knob ``check_rep``
    @functools.wraps(sm)
    def compat(*args, check_vma=None, **kwargs):
        if check_vma is not None:
            kwargs["check_rep"] = check_vma
        return sm(*args, **kwargs)

    return compat


def build_sharded_sweep(ps, mesh, n_cand_per_device, axis=CAND_AXIS,
                        n_cand_cat_per_device=None):
    """The mesh-sharded EI candidate sweep, taking precomputed fits.

    Returns ``sweep(key, fits, batch) -> (new_values [D, B], active)``
    where ``fits`` is :func:`hyperopt_tpu.ops.kernels.fit_all_dims`
    output.  Factored out so builders that compute their fits with
    TRACED per-step settings (the adaptive on-device path,
    :func:`hyperopt_tpu.atpe_jax.build_atpe_device_fn`) share the exact
    per-device slab draw + argmax-allgather with the static-settings
    :func:`build_sharded_suggest_fn`.
    """
    import jax
    import jax.numpy as jnp
    from jax.sharding import PartitionSpec as P

    from ..ops import kernels as K

    c = ps._consts
    D = ps.n_dims
    Dc = len(ps.cont_idx)
    Dk = len(ps.cat_idx)
    n_cat = (
        int(n_cand_per_device)
        if n_cand_cat_per_device is None
        else max(1, int(n_cand_cat_per_device))
    )
    smap = _shard_map()

    # Per-shard program: every input replicated; each device draws its own
    # candidate slab, and the cross-shard winner is reduced INSIDE the
    # shard with ONE coalesced all_gather per step.
    def _local_ei(key, wb, mb, sb, wa, ma, sa, pb, pa, batch):
        di = jax.lax.axis_index(axis)
        dev_key = jax.random.fold_in(key, di)
        keys = jax.random.split(dev_key, max(batch * (Dc + Dk), 1))

        out_vals = []
        out_scores = []
        if Dc:
            cont_keys = keys[: batch * Dc].reshape(batch, Dc)
            v, s = K.ei_sweep_cont(
                ps.q, c, cont_keys, (wb, mb, sb, wa, ma, sa),
                n_cand_per_device,
            )  # [B, Dc] each
            out_vals.append(v)
            out_scores.append(s)
        if Dk:
            cat_keys = keys[batch * Dc: batch * (Dc + Dk)].reshape(batch, Dk)
            v, s = K.ei_sweep_cat(cat_keys, pb, pa, n_cat)  # [B, Dk]
            out_vals.append(v)
            out_scores.append(s)
        vals = jnp.concatenate(out_vals, axis=1)  # [B, Dc+Dk]
        scores = jnp.concatenate(out_scores, axis=1)
        # ONE collective for the whole step: every dim's (value, score)
        # pair crosses the mesh in a single all_gather, and the argmax
        # runs locally on the replicated result.  The previous design
        # returned axis-sharded outputs and left the cross-shard argmax
        # + winner gather to GSPMD outside the shard_map, which lowered
        # to per-(trial, dim)-class collectives and dominated wall-clock
        # at small per-device slabs (VERDICT r4 weak #2: 2.5-3.1x at 16
        # cand/device -- the flagship 128-total config on 8 chips).
        # Device order in the gather matches the old leading-axis order,
        # so ties still resolve to the first device: bitwise-identical
        # suggestion streams.
        packed = jnp.stack([vals, scores], axis=-1)  # [B, Dc+Dk, 2]
        allv = jax.lax.all_gather(packed, axis)  # [n_dev, B, Dc+Dk, 2]
        win = jnp.argmax(allv[..., 1], axis=0)  # [B, Dc+Dk]
        best = jnp.take_along_axis(allv[..., 0], win[None], axis=0)[0]
        return best  # [B, Dc+Dk], replicated over the axis

    def sweep(key, fits, batch):
        zc = jnp.zeros((0,), jnp.float32)
        wb, mb, sb, wa, ma, sa = fits["cont"] or (zc,) * 6
        pb, pa = fits["cat"] or (zc, zc)

        local = smap(
            functools.partial(_local_ei, batch=batch),
            mesh=mesh,
            in_specs=(P(),) * 9,
            out_specs=P(),
            check_vma=False,
        )
        best = local(key, wb, mb, sb, wa, ma, sa, pb, pa)  # [B, Dc+Dk]

        new_values = jnp.zeros((D, batch), dtype=jnp.float32)
        if Dc:
            new_values = new_values.at[c["cont_idx"]].set(best[:, :Dc].T)
        if Dk:
            new_values = new_values.at[c["cat_idx"]].set(
                best[:, Dc:].T + c["int_low"][:, None]
            )
        return new_values, ps.active_fn(new_values)

    return sweep


def build_sharded_suggest_fn(
    ps, mesh, n_cand_per_device, gamma, lf, prior_weight, axis=CAND_AXIS,
    n_cand_cat_per_device=None, above_cap=None,
):
    """Compile the mesh-sharded TPE step for a PackedSpace.

    Returns jitted ``fn(key, values, active, losses, valid, batch)`` like
    :func:`hyperopt_tpu.tpe_jax.build_suggest_fn`, with the candidate sweep
    sharded over ``axis`` of ``mesh``.

    ``n_cand_cat_per_device`` (None = follow ``n_cand_per_device``) caps
    the per-device categorical draw: the union of per-device draws is
    statistically one (n_per_device x n_devices)-draw sweep, and the
    categorical EI argmax saturates into pure exploitation once that
    total covers every option (measured -- BASELINE.md NAS table), so
    callers keep the TOTAL categorical draw near the reference's 24.

    ``above_cap`` follows :func:`tpe_jax.build_suggest_fn`'s knob (None
    = framework default, 0 = full width): the fits are replicated but
    every device's slab scores against them, so compaction shrinks the
    per-device sweep the same way it shrinks the unsharded one.
    """
    import jax

    from ..ops import kernels as K
    from ..tpe_jax import _resolve_above_cap

    K.check_prior_weight(prior_weight)
    c = ps._consts
    gamma = float(gamma)
    lf_f = float(lf)
    pw = float(prior_weight)
    a_cap = _resolve_above_cap(above_cap)
    sweep = build_sharded_sweep(
        ps, mesh, n_cand_per_device, axis=axis,
        n_cand_cat_per_device=n_cand_cat_per_device,
    )

    def fn(key, values, active, losses, valid, batch):
        fits = K.fit_all_dims(c, values, active, losses, valid, gamma, lf_f,
                              pw, above_cap=a_cap)
        return sweep(key, fits, batch)

    return jax.jit(fn, static_argnames=("batch",))


def sharded_draw(domain, buf, mesh, cache_attr, n_per_dev, gamma, lf,
                 prior_weight, cat_per_dev, key, batch, above_cap=None):
    """One warm-path mesh-sharded draw: the cache-keyed builder +
    history placement + device fetch sequence, shared by
    :func:`sharded_suggest` and the adaptive path
    (:func:`hyperopt_tpu.atpe_jax._sharded_dense`) so the cache-key and
    multi-process placement contracts live in one place."""
    import jax

    from ..tpe_jax import _resolve_above_cap

    a_cap = _resolve_above_cap(above_cap)
    fn = cached_suggest_fn(
        domain, cache_attr,
        (id(mesh), int(n_per_dev), float(gamma), float(lf),
         float(prior_weight), cat_per_dev, a_cap),
        lambda ps_, _mid, n_pd, g, lf_, pw_, cpd, ac: (
            build_sharded_suggest_fn(
                ps_, mesh, n_pd, g, lf_, pw_, n_cand_cat_per_device=cpd,
                above_cap=0 if ac is None else ac,
            )
        ),
    )
    return jax.device_get(
        fn(key, *_history_inputs(buf, pow2_cap=a_cap), batch=batch)
    )


def _history_inputs(buf, pow2_cap=None):
    """History buffers placed for the current process span.

    Single-process (the common case): the ObsBuffer's cached default-
    device upload is reused untouched.  Multi-process (a
    ``jax.distributed`` mesh spanning hosts -- the DCN path): inputs
    committed to one local device cannot feed a computation laid out
    over the global mesh, so the buffers are handed to jit as host
    numpy instead -- uncommitted inputs are placed by jit itself as
    fully-replicated over the global mesh (each process uploads its
    identical copy; an explicit device_put is impossible here, the
    global sharding is not process-addressable).
    """
    import jax

    if jax.process_count() == 1:
        return buf.device_arrays(pow2_cap=pow2_cap)
    import numpy as np

    b = buf._device_bucket(pow2_cap)
    return tuple(np.ascontiguousarray(a[..., :b]) for a in buf.arrays())


# ---------------------------------------------------------------------------
# drop-in suggest using a default all-devices mesh
# ---------------------------------------------------------------------------

_default_n_EI_per_device = 64
# TOTAL categorical draw across the mesh; the union of per-device draws is
# statistically one (per_device x n_devices)-draw sweep, and the
# categorical EI argmax saturates into pure exploitation once that total
# covers every option (measured -- BASELINE.md NAS table), so the default
# keeps the reference's 24 regardless of mesh size
_default_n_EI_cat_total = 24
_default_gamma = 0.25
_default_n_startup_jobs = 20
_default_linear_forgetting = 25
_default_prior_weight = 1.0


def sharded_suggest(
    new_ids,
    domain,
    trials,
    seed,
    mesh=None,
    n_EI_per_device=_default_n_EI_per_device,
    n_EI_cat_total=_default_n_EI_cat_total,
    prior_weight=_default_prior_weight,
    n_startup_jobs=_default_n_startup_jobs,
    gamma=_default_gamma,
    linear_forgetting=_default_linear_forgetting,
    speculative=0,
    max_stale=None,
    above_cap=None,
):
    """``algo=parallel.sharded_suggest``: TPE with the candidate sweep
    sharded over every visible device.  ``n_EI_cat_total`` caps the
    TOTAL categorical draw (split across devices); None follows
    ``n_EI_per_device`` on every device.  ``speculative=k`` serves k
    sequential asks from one mesh-wide dispatch (same cache semantics
    as :func:`hyperopt_tpu.tpe_jax.suggest`).  ``above_cap`` follows
    :func:`hyperopt_tpu.tpe_jax.suggest`'s above-model compaction knob."""
    import jax

    ps = packed_space_for(domain)
    buf = obs_buffer_for(domain, trials)
    B = len(new_ids)

    if mesh is None:
        mesh = getattr(domain, "_tpe_mesh", None)
        if mesh is None:
            mesh = default_mesh()
            domain._tpe_mesh = mesh
    n_dev = int(mesh.shape[CAND_AXIS])
    cat_per_dev = (
        None if n_EI_cat_total is None
        else per_device_count(n_EI_cat_total, n_dev)
    )

    def draw(seed_, batch):
        key = host_key(int(seed_) % (2**31 - 1))
        if buf.count < n_startup_jobs:
            return jax.device_get(ps.sample_prior(key, batch))
        return sharded_draw(
            domain, buf, mesh, "_sharded_tpe_cache", n_EI_per_device,
            gamma, linear_forgetting, prior_weight, cat_per_dev, key, batch,
            above_cap=above_cap,
        )

    if speculative and B == 1:
        from ..tpe_jax import _saturated_categorical, _warn_saturated

        # the ACTUAL total categorical draw across the mesh decides
        # saturation: per-device counts round up, so the executed total
        # (cat_per_dev * n_dev) can exceed the requested n_EI_cat_total
        n_cat_total = (
            int(n_EI_per_device) if cat_per_dev is None else cat_per_dev
        ) * n_dev
        if _saturated_categorical(ps, n_cat_total):
            _warn_saturated(domain, speculative)
            speculative = 0

    if speculative and B == 1:
        from ..tpe_jax import _resolve_above_cap, _speculative_cols

        params = (
            "sharded", id(mesh), int(n_EI_per_device), cat_per_dev,
            float(gamma), float(linear_forgetting), float(prior_weight),
            int(n_startup_jobs), id(trials), int(speculative),
            # resolved staleness budget (see tpe_jax.suggest's key)
            int(speculative) - 1 if max_stale is None else int(max_stale),
            _resolve_above_cap(above_cap),
        )
        values, active = _speculative_cols(
            domain, trials, seed, int(speculative), max_stale, params,
            n_startup_jobs, draw,
        )
    else:
        values, active = draw(seed, B)

    from ..tpe_jax import _cast_vals

    idxs, vals = dense_to_idxs_vals(new_ids, ps.labels, values, active)
    idxs, vals = _cast_vals(ps, idxs, vals)
    return docs_from_idxs_vals(new_ids, domain, trials, idxs, vals)


suggest = sharded_suggest


# ---------------------------------------------------------------------------
# graftir registration (hyperopt-tpu-lint --ir)
# ---------------------------------------------------------------------------

from ..ops.compile import ProgramCapture, register_program  # noqa: E402


@register_program(
    "sharded.suggest",
    families=("hyperopt_tpu.parallel.sharded:build_sharded_suggest_fn",),
)
def _registry_sharded_suggest(p):
    """The mesh-sharded candidate sweep, traced over a one-CPU-device
    mesh: the shard_map slab draw + argmax-allgather structure is
    device-count-independent, so the single-shard IR pins the same
    program family the multi-chip mesh runs."""
    import jax

    _ = p.space._consts
    mesh = default_mesh(devices=jax.local_devices(backend="cpu")[:1])
    fn = build_sharded_suggest_fn(
        p.space, mesh, _default_n_EI_per_device, _default_gamma,
        _default_linear_forgetting, _default_prior_weight,
        n_cand_cat_per_device=_default_n_EI_cat_total,
    )
    return ProgramCapture(
        fn=fn, args=(p.key_spec(),) + p.history_specs(),
        kwargs={"batch": 1},
    )
